"""Live session migration: freeze an in-flight stream, resume it
elsewhere token-exact.

The failover path (gateway/core.py) already survives replica DEATH
token-exact by re-running from the prompt — correct, but every
*planned* event (drain, scale-down, rebalance) would pay the same
re-prefill and finish-everything latency. This module is the planned
path: at a dispatch boundary the source engine freezes a live decode
slot into a ``SessionSnapshot`` — everything the decode loop's
exactness invariant says the slot IS:

- ``n_tokens`` positions of token-exact KV (prompt + generated), as
  either shared-pool page ids (local owner swap, zero bytes moved) or
  gathered page content (remote, over the agent wire);
- the sampler state: per-slot PRNG key at its CURRENT chain position
  (advanced only by sampled draws, so resuming from it continues the
  exact random sequence a never-migrated slot would have drawn),
  temperature/top-k, and the speculation acceptance EMA;
- the absolute emitted prefix (``generated``) and the ORIGINAL budget
  — remaining budget is derived, and the gateway's absolute-offset
  emit dedup makes the client stream continue gap/dup-free.

The target engine adopts the snapshot without any prefill or sampling
dispatch: the first token of every future step was already drawn, so
the slot is armed directly (``SlotCache.admit`` with the carried rng)
and the next decode round continues as if the slot had lived there
all along. Byte-identical streams under greedy AND seeded sampling,
speculation live, is the acceptance bar (tests/test_migrate.py).

Failure model: migration is MOVE semantics with a copy-then-delete
ordering on the remote path — the source frees its half only after
the target's adopt returns. A SIGKILL of either end mid-migration
leaves at most one live copy plus the gateway's ticket, and the
ordinary failover path re-runs the request from the prompt,
token-exact. Nothing here weakens the crash story; it only makes the
planned story cheap.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import numpy as np

from tony_tpu.serve.prefix import summary_match_len
from tony_tpu.serve.tier import decode_array, encode_array, \
    encode_payload, trim_payload


class StaleDelta(ValueError):
    """A delta (suffix-only) snapshot arrived but the adopter no
    longer holds the prefix its trim assumed — the radix summary the
    sender diffed against was stale (heartbeat lag, or the entry was
    evicted in between). Raised at SUBMIT time, before any slot or
    page is touched; the sender's contract is to fall back to the
    full-page payload (gateway/remote.py does, counting the
    fallback)."""


@dataclass
class SessionSnapshot:
    """One frozen in-flight session, captured at a dispatch boundary.

    The engine's decode invariant after any dispatch: ``n_tokens``
    (= slot length) equals ``len(prompt) + len(generated) - 1`` — the
    final sampled token was never fed back, so its K/V is not in the
    pages; ``generated[-1]`` is the token the next step feeds. Both
    facts are what make adopt a pure arm-the-slot, no dispatch.
    """

    prompt: list
    generated: list        # absolute emitted tokens, first to last
    max_new_tokens: int    # ORIGINAL budget; remaining is derived
    temperature: float
    top_k: int
    seed: int
    rng: Any               # np.uint32[2] PRNG key, current chain pos
    spec_ema: float        # speculation acceptance EMA (k autotune)
    n_tokens: int          # KV positions held = len(prompt)+len(generated)-1
    pages: Any             # local: [page_id] (share()d, transferable);
    # remote: gathered page content (device tree or wire dict)
    local: bool            # True = pages are ids in a shared pool
    t_freeze: float        # wall clock at freeze (freeze->resume ms)
    pool: Any = None       # the shared PagePool ids live in (local
    # only) — adopt refuses a snapshot from a different pool
    page_size: int = 0     # tokens per page at the SOURCE (wire only;
    # what delta_trim_doc converts summary tokens into page counts
    # with — 0 means unknown, delta trimming declines)

    @property
    def remaining(self) -> int:
        """Token budget left at resume time."""
        return max(0, int(self.max_new_tokens) - len(self.generated))


def gather_local(pool, pages) -> Any:
    """Materialize the CONTENT of shared-pool ``pages`` as a
    standalone device tree and release the transfer ref they carried —
    the bridge from an owner-swap payload (page ids, zero-copy while
    the session stays on this host) to a wire-shippable one, taken
    when routing sends the session to a REMOTE replica after all.

    Ordering matters: the gather is forced (``block_until_ready``)
    BEFORE the unref, so the pages cannot be reallocated and
    overwritten while their content is still being read. The caller
    must replace the id payload with the returned tree IN PLACE
    (ticket and request share the payload object) — the transfer ref
    is consumed exactly once, and any retry/requeue ships the gathered
    copy instead of dangling ids."""
    import jax
    import jax.numpy as jnp

    from tony_tpu.serve.engine import _padded_pages
    from tony_tpu.serve.slots import _gather_pages

    ids = [int(p) for p in pages]
    with pool.lock:
        idx = jnp.asarray(_padded_pages(ids), jnp.int32)
        payload = _gather_pages(pool.cache, idx)
        jax.block_until_ready(payload)
        pool.unref(ids)
    return payload


def snapshot_to_doc(snap: SessionSnapshot) -> dict:
    """Wire form (JSON-safe) of a REMOTE snapshot — rides the agent
    ``POST /v1/migrate_in`` op on the mux channel, pages through the
    same base64 leaf codec as ``/v1/handoff``."""
    if snap.local:
        raise ValueError(
            "a local (owner-swap) snapshot holds page ids, not page "
            "content — extract with wire pages to cross hosts")
    return {
        "prompt": [int(t) for t in snap.prompt],
        "generated": [int(t) for t in snap.generated],
        "max_new_tokens": int(snap.max_new_tokens),
        "temperature": float(snap.temperature),
        "top_k": int(snap.top_k),
        "seed": int(snap.seed),
        "rng": encode_array(np.asarray(snap.rng, np.uint32)),
        "spec_ema": float(snap.spec_ema),
        "n_tokens": int(snap.n_tokens),
        "pages": encode_payload(snap.pages),
        "t_freeze": float(snap.t_freeze),
        "page_size": int(snap.page_size),
    }


def snapshot_from_doc(doc: dict) -> SessionSnapshot:
    """Inverse of ``snapshot_to_doc``. ``pages`` stays in wire form —
    the adopting engine decodes it against its OWN cache treedef
    (mismatched model configs fail loudly there, same contract as the
    handoff path)."""
    return SessionSnapshot(
        prompt=[int(t) for t in doc["prompt"]],
        generated=[int(t) for t in doc["generated"]],
        max_new_tokens=int(doc["max_new_tokens"]),
        temperature=float(doc["temperature"]),
        top_k=int(doc["top_k"]),
        seed=int(doc["seed"]),
        rng=np.asarray(decode_array(doc["rng"]), np.uint32).reshape(2),
        spec_ema=float(doc["spec_ema"]),
        n_tokens=int(doc["n_tokens"]),
        pages=doc["pages"],
        local=False,
        t_freeze=float(doc["t_freeze"]),
        page_size=int(doc.get("page_size", 0)),
    )


# ----------------------------------------------------- delta migration


def delta_trim_doc(doc: dict, summary) -> dict | None:
    """Prefix-delta trim of a wire snapshot doc against the TARGET's
    radix summary (the ``[[n_tokens, crc32], ...]`` pairs riding its
    agent heartbeat since ISSUE-18). When the target already holds a
    prefix of this session's context, ship only the uncovered SUFFIX
    pages: the returned doc carries ``delta.prefix_tokens`` (always a
    page multiple) and a page payload trimmed to ``[k, n)``; the
    adopter reconstructs pages ``[0, k)`` by refcount-sharing its own
    store pages — the same alias accounting local adoptions use.

    Returns None when trimming buys nothing (no summary overlap, page
    size unknown, or the session spans a single page). The diff is
    advisory: a stale summary makes the ADOPTER raise ``StaleDelta``
    and the sender re-ships the full doc — correctness never rests on
    summary freshness.

    At least one page always ships (``k <= n - 1``): the final page is
    partial in general, and the adopter's boundary arithmetic stays
    uniform when the suffix is never empty."""
    ps = int(doc.get("page_size", 0))
    if ps <= 0 or not summary:
        return None
    n_tok = int(doc["n_tokens"])
    n = -(-n_tok // ps)
    # the context whose KV the pages hold: prompt + generated minus
    # the never-fed-back final token (the snapshot invariant)
    ctx = [int(t) for t in doc["prompt"]]
    ctx += [int(t) for t in doc["generated"]][:-1]
    covered = summary_match_len(summary, ctx)
    k = min(covered // ps, n - 1)
    if k <= 0:
        return None
    out = dict(doc)
    out["pages"] = trim_payload(doc["pages"], k, n)
    out["delta"] = {"prefix_tokens": k * ps}
    return out
