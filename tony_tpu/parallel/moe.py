"""Mixture-of-Experts with expert parallelism.

Absent from the reference (SURVEY.md section 2.4: EP "NO"). Implementation
is the pjit idiom: expert weights carry a leading expert dim annotated with
the ``expert`` mesh axis; dispatch/combine are einsums against a capacity-
limited one-hot dispatch tensor, so under pjit XLA lowers the token
exchange to all-to-all over ICI — no hand-written comms.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass
class MoEConfig:
    num_experts: int = 8
    capacity_factor: float = 1.25
    top_k: int = 2
    d_model: int = 512
    d_ff: int = 2048


def init_moe_params(key, cfg: MoEConfig, dtype=jnp.float32) -> dict:
    k1, k2, k3 = jax.random.split(key, 3)
    scale_in = cfg.d_model ** -0.5
    return {
        "router": jax.random.normal(k1, (cfg.d_model, cfg.num_experts),
                                    dtype) * scale_in,
        # leading expert dim -> sharded on the "expert" mesh axis
        "wi": jax.random.normal(k2, (cfg.num_experts, cfg.d_model, cfg.d_ff),
                                dtype) * scale_in,
        "wo": jax.random.normal(k3, (cfg.num_experts, cfg.d_ff, cfg.d_model),
                                dtype) * (cfg.d_ff ** -0.5),
    }


def moe_logical_axes() -> dict:
    """Logical sharding annotations (see parallel.sharding RULES['ep'])."""
    return {
        "router": (None, None),
        "wi": ("expert", None, "mlp"),
        "wo": ("expert", "mlp", None),
    }


def top_k_gating(logits: jnp.ndarray, k: int, capacity: int):
    """Top-k token->expert routing with per-expert capacity.

    logits: [T, E]. Returns (dispatch [T, E, C] one-hot, combine [T, E, C]
    weights, aux_loss scalar).
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, gate_idx = jax.lax.top_k(probs, k)  # [T, k]
    # load-balancing auxiliary loss (Switch/GShard style)
    me = jnp.mean(probs, axis=0)
    ce = jnp.mean(
        jnp.sum(jax.nn.one_hot(gate_idx, e), axis=1), axis=0)
    aux_loss = e * jnp.sum(me * ce) / k

    dispatch = jnp.zeros((t, e, capacity), dtype=logits.dtype)
    combine = jnp.zeros((t, e, capacity), dtype=logits.dtype)
    # position of each token within its expert's buffer, per top-k choice
    taken = jnp.zeros((e,), dtype=jnp.int32)
    for choice in range(k):
        idx = gate_idx[:, choice]  # [T]
        one_hot = jax.nn.one_hot(idx, e, dtype=jnp.int32)  # [T, E]
        pos_within = jnp.cumsum(one_hot, axis=0) - 1 + taken[None, :]
        taken = taken + jnp.sum(one_hot, axis=0)
        pos = jnp.sum(pos_within * one_hot, axis=1)  # [T]
        keep = pos < capacity
        w = gate_vals[:, choice] * keep
        dispatch = dispatch + (
            jax.nn.one_hot(idx, e, dtype=logits.dtype)[:, :, None]
            * jax.nn.one_hot(jnp.where(keep, pos, 0), capacity,
                             dtype=logits.dtype)[:, None, :]
            * keep[:, None, None]
        )
        combine = combine + (
            jax.nn.one_hot(idx, e, dtype=logits.dtype)[:, :, None]
            * jax.nn.one_hot(jnp.where(keep, pos, 0), capacity,
                             dtype=logits.dtype)[:, None, :]
            * w[:, None, None]
        )
    return dispatch, combine, aux_loss


def moe_layer(params: dict, x: jnp.ndarray, cfg: MoEConfig):
    """x: [B, L, D] -> ([B, L, D], aux_loss).

    Token exchange happens in the two einsums against dispatch/combine;
    with wi/wo sharded on the expert axis XLA emits all-to-all.
    """
    b, l, d = x.shape
    tokens = x.reshape(b * l, d)
    logits = tokens @ params["router"]
    capacity = max(1, int(cfg.capacity_factor * (b * l) / cfg.num_experts))
    dispatch, combine, aux = top_k_gating(logits, cfg.top_k, capacity)
    # [E, C, D]: gather each expert's tokens (all-to-all under pjit)
    expert_in = jnp.einsum("td,tec->ecd", tokens, dispatch)
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", expert_in, params["wi"]))
    expert_out = jnp.einsum("ecf,efd->ecd", h, params["wo"])
    out = jnp.einsum("ecd,tec->td", expert_out, combine)
    return out.reshape(b, l, d), aux
