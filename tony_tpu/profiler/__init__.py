from tony_tpu.profiler.profiler import (
    StepProfiler,
    maybe_start_server,
    trace,
    trigger_path,
    write_trigger,
)

__all__ = [
    "StepProfiler",
    "maybe_start_server",
    "trace",
    "trigger_path",
    "write_trigger",
]
