"""int8 weight-only serving (models/quantize.py + QuantDense).

Correctness anchor: the quantized model must match a full-precision
forward over the SAME dequantized weights (the kernel adds no error
beyond quantization itself), across architecture families and the
KV-cache decode path.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models import (
    Transformer,
    TransformerConfig,
    generate,
    quantize_for_serving,
)
from tony_tpu.ops.quant import dequantize_q8


def _dequant_params(params, reference):
    """Quantized tree -> fp tree shaped like ``reference``."""

    def walk(node, ref):
        if isinstance(node, dict) and "kernel_q8" in node:
            w = np.asarray(dequantize_q8(node["kernel_q8"], node["scale"]))
            out = {"kernel": jnp.asarray(
                w.reshape(np.asarray(ref["kernel"]).shape), jnp.float32)}
            if "bias" in node:
                out["bias"] = node["bias"]
            return out
        if isinstance(node, dict):
            return {k: walk(v, ref[k]) for k, v in node.items()}
        return node

    return walk(params, reference)


CONFIGS = {
    "llama_gqa": dict(norm="rms", positional="rope", use_bias=False,
                      gated_mlp=True, n_kv_heads=2),
    "gpt2": dict(norm="layer", positional="learned", use_bias=True,
                 activation="gelu_tanh"),
    "neox": dict(norm="layer", positional="rope", use_bias=True,
                 parallel_residual=True, rotary_dims=4),
    "phi": dict(norm="layer", positional="rope", use_bias=True,
                parallel_residual=True, rotary_dims=4,
                tied_embeddings=False, lm_head_bias=True),
}


@pytest.mark.parametrize("family", sorted(CONFIGS))
def test_quantized_forward_matches_dequant_reference(family):
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_layers=2, d_ff=64, max_seq_len=16,
                            dtype=jnp.float32,
                            attention_backend="reference",
                            **CONFIGS[family])
    model = Transformer(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 7), 0, 64)
    params = model.init(jax.random.PRNGKey(0), tokens)
    qmodel, qparams = quantize_for_serving(model, params)
    assert qmodel.cfg.quantized
    got = np.asarray(qmodel.apply(qparams, tokens))
    ref = np.asarray(model.apply(_dequant_params(qparams, params), tokens))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)
    # and close to the ORIGINAL fp model (int8 error only)
    fp = np.asarray(model.apply(params, tokens))
    assert np.abs(got - fp).mean() / (np.abs(fp).mean() + 1e-9) < 0.05


def test_quantized_decode_matches_quantized_forward():
    """KV-cache decode through QuantDense == the quantized full forward
    (the serving path generate() drives)."""
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_layers=2, d_ff=64, max_seq_len=16,
                            dtype=jnp.float32,
                            attention_backend="reference", gated_mlp=True)
    model = Transformer(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 6), 0, 64)
    params = model.init(jax.random.PRNGKey(0), tokens)
    qmodel, qparams = quantize_for_serving(model, params)
    full = np.asarray(qmodel.apply(qparams, tokens))
    cache = qmodel.init(jax.random.PRNGKey(0), tokens, decode=True)["cache"]
    steps = []
    variables = {"params": qparams["params"], "cache": cache}
    for i in range(tokens.shape[1]):
        logits, mut = qmodel.apply(variables, tokens[:, i:i + 1],
                                   decode=True, mutable=["cache"])
        variables = {"params": qparams["params"], "cache": mut["cache"]}
        steps.append(np.asarray(logits[:, 0]))
    np.testing.assert_allclose(np.stack(steps, axis=1), full,
                               rtol=2e-4, atol=2e-4)


def test_quantized_generate_runs_greedy():
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_layers=2, d_ff=64, max_seq_len=16,
                            dtype=jnp.float32,
                            attention_backend="reference")
    model = Transformer(cfg)
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)
    qmodel, qparams = quantize_for_serving(model, params)
    out = generate(qmodel, qparams["params"], prompt, max_new_tokens=4)
    assert out.shape == (1, 4)
    assert bool(jnp.all((out >= 0) & (out < 64)))


def test_quantize_rejects_unsupported_configs():
    base = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
                max_seq_len=16, dtype=jnp.float32)
    moe = Transformer(TransformerConfig(**base, moe_every=1))
    with pytest.raises(ValueError, match="MoE"):
        quantize_for_serving(moe, {})
    scan = Transformer(TransformerConfig(**base, scan_layers=True))
    with pytest.raises(ValueError, match="scan_layers"):
        quantize_for_serving(scan, {})


def test_quantized_params_are_half_the_bytes():
    cfg = TransformerConfig(vocab_size=64, d_model=64, n_heads=4,
                            n_layers=2, d_ff=128, max_seq_len=16,
                            dtype=jnp.float32,
                            attention_backend="reference", gated_mlp=True,
                            tied_embeddings=True)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    _, qparams = quantize_for_serving(model, params)

    def nbytes(tree):
        return sum(np.asarray(x).nbytes for x in jax.tree.leaves(tree))

    # dense kernels went fp32 -> int8 (+ tiny scales); embeddings/norms
    # stay fp32, so the total shrinks by well over 2x for kernel-heavy
    # trees and the kernels themselves by ~4x
    assert nbytes(qparams) < 0.5 * nbytes(params)


def test_q8_matmul_prime_rows_pads_not_degenerates():
    """A prime activation row count (batch*prompt_len) must pad to block
    multiples, not collapse to 1-row blocks."""
    from tony_tpu.ops import dequantize_q8, q8_matmul, quantize_q8

    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.standard_normal((257, 64)), jnp.float32)  # prime m
    w = jnp.asarray(rng.standard_normal((64, 128)), jnp.float32)
    w_q, scale = quantize_q8(w)
    got = np.asarray(q8_matmul(x, w_q, scale, block_m=128))
    want = np.asarray(x) @ np.asarray(dequantize_q8(w_q, scale))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_quantized_params_replicated_logical_axes():
    """Quantized leaves get all-None logical axes (replicated) — the fp
    head/kv rules would shard the flattened kernels wrongly."""
    from tony_tpu.models.transformer import logical_axis_rules_tree

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_layers=1, d_ff=64, max_seq_len=16,
                            dtype=jnp.float32,
                            attention_backend="reference")
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    _, qparams = quantize_for_serving(model, params)
    axes = logical_axis_rules_tree(qparams)
    blk = axes["params"]["block_0"]["attn"]["q"]
    assert blk["kernel_q8"] == (None, None)
    assert blk["scale"] == (None,)
    # fp leaves (embedding) keep their rules
    assert axes["params"]["embedding"] == ("vocab", "embed")
