"""Test env: force an 8-device virtual CPU mesh before jax initializes.

Multi-chip sharding tests run on xla_force_host_platform_device_count=8
per the build contract (real multi-chip hardware is unavailable; the driver
separately dry-runs __graft_entry__.dryrun_multichip).
"""

import os
import sys

# Hard-set, not setdefault: the session env carries JAX_PLATFORMS=axon (the
# TPU tunnel) and a sitecustomize hook that re-registers it via
# jax.config.update("jax_platforms", "axon,cpu") at interpreter startup —
# the env var alone cannot win. Tests must never dial the TPU relay:
# (1) fix the config in this process, (2) drop the sitecustomize trigger
# env so subprocesses (agents, payload scripts) skip registration entirely.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: the suite is compile-dominated (tiny
# models, many distinct program shapes), and identical programs recompile
# on every pytest invocation. Caching them across runs keeps the tier-1
# wall clock well inside its budget on a warm box and costs a cold run
# only the cache writes (measured ~2.5x faster warm on this suite's
# serving tests). Keys include jax/XLA versions and compile options, so a
# toolchain bump simply misses. JAX_COMPILATION_CACHE_DIR, when set,
# wins — jax reads it natively before this config is consulted. The
# path is per-user: a fixed world-shared /tmp name would be silently
# unwritable for the second user on a shared box (and a cache-
# poisoning surface — entries deserialize as compiled executables).
if not os.environ.get("JAX_COMPILATION_CACHE_DIR"):
    import getpass
    import tempfile

    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(tempfile.gettempdir(),
                     f"tony-tpu-jax-cache-{getpass.getuser()}"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavyweight tests excluded from the tier-1 budget "
        "(ROADMAP.md runs -m 'not slow'); run explicitly with -m slow")
