"""Payload assertion: the horovod runtime's worker env contract
(ref: tony-core test script check_horovod_env.py — exits non-zero if the
injected HOROVOD_* rendezvous env is missing or inconsistent)."""

import os
import sys


def main() -> int:
    required = [
        "HOROVOD_CONTROLLER", "HOROVOD_CPU_OPERATIONS",
        "HOROVOD_GLOO_RENDEZVOUS_ADDR", "HOROVOD_GLOO_RENDEZVOUS_PORT",
        "HOROVOD_RANK", "HOROVOD_SIZE", "HOROVOD_LOCAL_RANK",
        "HOROVOD_LOCAL_SIZE", "HOROVOD_CROSS_RANK", "HOROVOD_CROSS_SIZE",
        "HOROVOD_HOSTNAME",
    ]
    missing = [k for k in required if k not in os.environ]
    if missing:
        print(f"missing env: {missing}", file=sys.stderr)
        return 1
    if os.environ["HOROVOD_CONTROLLER"] != "gloo":
        return 2
    rank = int(os.environ["HOROVOD_RANK"])
    size = int(os.environ["HOROVOD_SIZE"])
    if not (0 <= rank < size):
        print(f"bad rank {rank} of {size}", file=sys.stderr)
        return 3
    if int(os.environ["HOROVOD_GLOO_RENDEZVOUS_PORT"]) <= 0:
        return 4
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
