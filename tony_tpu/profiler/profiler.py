"""Tracing/profiling subsystem.

The reference has none (SURVEY.md §5.1: "Rebuild note: TPU equivalent
should add jax.profiler/xplane trace capture — greenfield"). Design:

- Every task can host a ``jax.profiler`` server (``TONY_PROFILER_PORT``
  env, set from ``tony.task.profiler-port``) so TensorBoard's profile
  plugin can capture remotely.
- On-demand capture without TensorBoard: the coordinator queues a
  ``profile`` command for a task (RPC verb ``request_profile``), the
  agent's heartbeat response delivers it, and the agent drops a trigger
  file in the task workdir. The user process — any loop that calls
  ``StepProfiler.poll()`` once per step, which ``tony_tpu.train.Trainer``
  users get for free — picks the trigger up and writes an xplane trace
  for the next N steps into the job dir, where the portal/logs page can
  link it.

Both paths degrade to no-ops off-TPU or when jax is absent; the trigger
file protocol is plain JSON so non-JAX runtimes can honor it too.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import threading
import time

from tony_tpu.utils.controlfile import (
    control_file_path,
    current_task_id,
    write_control_file,
)

log = logging.getLogger(__name__)

TRIGGER_FILENAME = ".tony_profile_request"
PROFILER_PORT_ENV = "TONY_PROFILER_PORT"
PROFILE_DIR_ENV = "TONY_PROFILE_DIR"


def trigger_path(workdir: str, task_id: str = "") -> str:
    """Per-task trigger file (tasks can share a job dir on one host)."""
    return control_file_path(workdir, TRIGGER_FILENAME, task_id)


def write_trigger(workdir: str, num_steps: int = 5,
                  logdir: str | None = None, task_id: str = "") -> str:
    """Agent side: request a trace from the user process in ``workdir``."""
    return write_control_file(
        trigger_path(workdir, task_id),
        {"num_steps": int(num_steps), "logdir": logdir})


def maybe_start_server() -> int:
    """Start jax's profiler server when TONY_PROFILER_PORT is set (called
    from tony_tpu.distributed.initialize). Returns the port or 0."""
    port = int(os.environ.get(PROFILER_PORT_ENV, "0") or "0")
    if port <= 0:
        return 0
    try:
        import jax

        jax.profiler.start_server(port)
        log.info("jax profiler server on :%d", port)
        return port
    except Exception:
        log.exception("could not start jax profiler server")
        return 0


@contextlib.contextmanager
def trace(logdir: str):
    """Programmatic xplane trace of a code region."""
    import jax

    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


class StepProfiler:
    """Poll-per-step on-demand tracing for training loops.

    ``poll()`` is one ``os.path.exists`` when idle — cheap enough to call
    every step. When a trigger file appears, the next ``num_steps`` steps
    are traced to the trigger's logdir (default: ``$TONY_PROFILE_DIR`` or
    ``<workdir>/profiles``).
    """

    def __init__(self, workdir: str | None = None,
                 default_logdir: str | None = None,
                 task_id: str | None = None):
        self.workdir = workdir or os.getcwd()
        self.task_id = current_task_id() if task_id is None else task_id
        self.default_logdir = (default_logdir
                               or os.environ.get(PROFILE_DIR_ENV)
                               or os.path.join(self.workdir, "profiles"))
        self.active_steps_left = 0
        self.captures = 0
        self._logdir = ""

    def poll(self) -> bool:
        """Call once per training step. Returns True while tracing."""
        if self.active_steps_left > 0:
            self.active_steps_left -= 1
            if self.active_steps_left == 0:
                self._stop()
            return self.active_steps_left > 0
        path = trigger_path(self.workdir, self.task_id)
        if not os.path.exists(path):
            return False
        try:
            with open(path) as f:
                req = json.load(f)
        except (OSError, json.JSONDecodeError):
            req = {}
        finally:
            with contextlib.suppress(OSError):
                os.remove(path)  # consume: one trigger, one capture
        self._start(req.get("logdir") or self.default_logdir,
                    int(req.get("num_steps", 5)))
        return True

    def _start(self, logdir: str, num_steps: int) -> None:
        try:
            import jax

            os.makedirs(logdir, exist_ok=True)
            jax.profiler.start_trace(logdir)
        except Exception:
            log.exception("profile trigger ignored: start_trace failed")
            return
        self._logdir = logdir
        self.active_steps_left = max(num_steps, 1)
        log.info("profiling next %d steps -> %s", self.active_steps_left, logdir)

    def _stop(self) -> None:
        try:
            import jax

            jax.profiler.stop_trace()
            self.captures += 1
            log.info("profile capture #%d written to %s", self.captures,
                     self._logdir)
        except Exception:
            log.exception("stop_trace failed")

    def close(self) -> None:
        if self.active_steps_left > 0:
            self.active_steps_left = 0
            self._stop()


class ServeProfiler:
    """On-demand xplane capture for SERVING loops — the request/poll
    protocol of ``StepProfiler`` without the trigger file, safe under
    many scheduler threads.

    The gateway's ``POST /debug/profile?steps=N`` calls ``request()``;
    every replica scheduler thread calls ``poll()`` once per WORKING
    iteration (idle waits don't count — profiling an idle fleet would
    capture nothing and never finish; the capture simply waits for
    traffic). The first poll after arming starts ``jax.profiler``'s
    trace; each subsequent poll burns one step; the Nth stops it and
    stamps ``last_logdir``. Steps are counted FLEET-WIDE (the trace is
    process-global anyway — jax has one profiler session), so with R
    busy replicas ``steps=N`` spans ~N/R iterations of each.

    ``poll()``'s idle path is a single attribute read (no lock): the
    arming thread publishes ``_armed`` last, and a replica that misses
    the flag by a race picks it up on its next iteration — fine for a
    debug trigger, free for the hot loop.

    FOOTGUN (measured): the FIRST ``jax.profiler.start_trace`` of a
    process can block its caller >10 s while the profiler plugin spins
    up — and it runs on a replica scheduler thread, which stops
    heartbeating for the duration. Keep the gateway's
    ``--stall-timeout`` above that (the default 30 s is) or arming a
    capture will get the capturing replica declared stalled and its
    requests failed over. Same class of footgun as first-compile vs
    stall-timeout, documented in docs/OBSERVABILITY.md.
    """

    def __init__(self, default_logdir: str | None = None):
        self.default_logdir = (default_logdir
                               or os.environ.get(PROFILE_DIR_ENV)
                               or os.path.join(os.getcwd(), "profiles"))
        self._lock = threading.Lock()
        self._armed = False        # lock-free fast-path flag
        self._pending: tuple[int, str] | None = None
        self._starting = False     # a poller is inside start_trace
        self._closed = False       # terminal (gateway drained)
        self._steps_left = 0
        self._active_logdir = ""
        self.captures = 0
        self.last_logdir = ""
        self.last_error = ""

    @property
    def busy(self) -> bool:
        return self._armed

    def request(self, num_steps: int, logdir: str | None = None) -> str:
        """Arm a capture of the next ``num_steps`` scheduler iterations.
        Returns the logdir the xplane files will land in. Raises
        ``RuntimeError`` while a capture is pending/active (jax has one
        global profiler session — queueing would silently serialize
        debug sessions against each other)."""
        num_steps = int(num_steps)
        if num_steps < 1:
            raise ValueError("steps must be >= 1")
        with self._lock:
            if self._closed:
                raise RuntimeError("profiler closed (gateway drained)")
            if self._armed:
                raise RuntimeError(
                    "a profile capture is already pending or active")
            logdir = logdir or os.path.join(
                self.default_logdir,
                f"profile-{int(time.time() * 1000)}")
            self._pending = (num_steps, logdir)
            self.last_error = ""
            self._armed = True  # published LAST: poll()'s lock-free
            #                     read must never see armed without the
            #                     pending tuple in place
        log.info("serving profile armed: next %d scheduler steps -> %s",
                 num_steps, logdir)
        return logdir

    def poll(self) -> None:
        """One working scheduler iteration. Near-free when idle."""
        if not self._armed:
            return
        finish = False
        with self._lock:
            pending, self._pending = self._pending, None
            if pending is not None:
                self._starting = True  # other pollers skip counting
                # until the trace is actually running
            elif self._starting:
                return  # another poller is mid start/stop transition
            elif self._steps_left > 0:
                self._steps_left -= 1
                if self._steps_left == 0:
                    self._starting = True  # hold pollers off the stop
                    finish = True
            if pending is None and not finish:
                return
        if finish:
            self._stop_outside_lock()
            return
        num_steps, logdir = pending
        # start_trace OUTSIDE the lock: its first call can block >10 s
        # (plugin spin-up), and every OTHER replica's poll() would pile
        # up on the lock and stop heartbeating — one slow replica is
        # the documented footgun, a fleet-wide stall is not. Same
        # discipline for stop_trace (_stop_outside_lock), whose capture
        # write-out scales with trace size.
        try:
            import jax

            os.makedirs(logdir, exist_ok=True)
            jax.profiler.start_trace(logdir)
        except Exception as e:  # noqa: BLE001 — a broken
            # profiler must not take the serving loop with it
            log.exception("profile request ignored: start_trace failed")
            with self._lock:
                self.last_error = f"{type(e).__name__}: {e}"
                self._starting = False
                self._armed = False
            return
        abandoned = False
        with self._lock:
            self._starting = False
            self._active_logdir = logdir
            if self._closed or not self._armed:
                # close() raced the spin-up (gateway drain): finalize
                # right away so the global session is not left running
                abandoned = True
                self._starting = True
            else:
                self._steps_left = num_steps
        if abandoned:
            self._stop_outside_lock()

    def _stop_outside_lock(self) -> None:
        """Finish the capture with the LOCK RELEASED (the caller set
        ``_starting`` so concurrent pollers skip, not block): the
        write-out scales with capture size and must stall at most the
        one thread driving it."""
        err = ""
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001 — see poll()
            log.exception("stop_trace failed")
            err = f"{type(e).__name__}: {e}"
        with self._lock:
            if err:
                self.last_error = err
            else:
                self.captures += 1
                self.last_logdir = self._active_logdir
                log.info("serving profile capture #%d written to %s",
                         self.captures, self.last_logdir)
            self._active_logdir = ""
            self._starting = False
            self._armed = False

    def status(self) -> dict:
        """The ``GET /debug/profile`` payload."""
        with self._lock:
            return {
                "active": self._armed,
                "starting": self._starting,
                "steps_left": (self._pending[0] if self._pending
                               else self._steps_left),
                "captures": self.captures,
                "last_logdir": self.last_logdir,
                "last_error": self.last_error,
            }

    def close(self) -> None:
        """Terminal stop (gateway shutdown): finalize a capture left
        running and refuse all future ``request()``s. A capture still
        inside start_trace on another thread finalizes itself when the
        spin-up returns and finds ``_closed`` set."""
        with self._lock:
            self._closed = True  # terminal: request() refuses from
            # here on, so nothing can arm a capture that would collide
            # with an in-flight start/stop (one global jax session)
            self._pending = None
            stop = self._steps_left > 0
            self._steps_left = 0
            if stop:
                # hold pollers off the stop; _armed stays True until
                # _stop_outside_lock completes
                self._starting = True
            elif not self._starting:
                # a start/stop still in flight on a poller thread keeps
                # _armed until ITS completion path (which sees _closed)
                # finalizes; clearing it here would only widen races
                self._armed = False
        if stop:
            self._stop_outside_lock()
