"""Model + trainer smoke tests on CPU (tiny shapes)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from tony_tpu.models import ResNet18, Transformer, TransformerConfig
from tony_tpu.parallel import MeshSpec, data_parallel_mesh, make_mesh
from tony_tpu.parallel.sharding import batch_sharding
from tony_tpu.train import Trainer, cross_entropy_loss


def tiny_cfg(**kw):
    defaults = dict(vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
                    max_seq_len=64, dtype=jnp.float32,
                    attention_backend="blockwise", attention_block_size=16)
    defaults.update(kw)
    return TransformerConfig(**defaults)


def test_transformer_forward_shapes():
    cfg = tiny_cfg()
    model = Transformer(cfg)
    tokens = jnp.zeros((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    logits = model.apply(params, tokens)
    assert logits.shape == (2, 16, 64)
    assert jnp.all(jnp.isfinite(logits))


def test_transformer_backends_agree():
    cfg_ref = tiny_cfg(attention_backend="reference")
    cfg_blk = tiny_cfg(attention_backend="blockwise")
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)
    model_ref = Transformer(cfg_ref)
    params = model_ref.init(jax.random.PRNGKey(0), tokens)
    out_ref = model_ref.apply(params, tokens)
    out_blk = Transformer(cfg_blk).apply(params, tokens)
    np.testing.assert_allclose(np.asarray(out_ref), np.asarray(out_blk),
                               atol=1e-4, rtol=1e-4)


def test_transformer_ring_backend_on_mesh():
    mesh = make_mesh(MeshSpec(data=-1, seq=4))
    cfg_ring = tiny_cfg(attention_backend="ring", mesh=mesh)
    cfg_ref = tiny_cfg(attention_backend="reference")
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 32), 0, 64)
    model = Transformer(cfg_ref)
    params = model.init(jax.random.PRNGKey(0), tokens)
    out_ref = model.apply(params, tokens)
    out_ring = Transformer(cfg_ring).apply(params, tokens)
    np.testing.assert_allclose(np.asarray(out_ref), np.asarray(out_ring),
                               atol=1e-4, rtol=1e-4)


def test_resnet_forward():
    model = ResNet18(num_classes=10, num_filters=8, dtype=jnp.float32)
    x = jnp.ones((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)


def test_trainer_loss_decreases():
    mesh = data_parallel_mesh()
    cfg = tiny_cfg()
    model = Transformer(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (8, 16), 0, 64)
    params = model.init(jax.random.PRNGKey(0), tokens)

    def apply_fn(p, batch):
        logits = model.apply(p, batch["tokens"])
        return cross_entropy_loss(logits[:, :-1], batch["tokens"][:, 1:])

    trainer = Trainer(mesh=mesh, apply_fn=apply_fn,
                      optimizer=optax.adam(1e-2), donate=False)
    state = trainer.init_state(params)
    step_fn, placed = trainer.build_step(state)
    batch = {"tokens": jax.device_put(tokens, batch_sharding(mesh))}
    losses = []
    for _ in range(5):
        placed, metrics = step_fn(placed, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses
    assert int(placed.step) == 5


def test_trainer_fsdp_sharding():
    mesh = make_mesh(MeshSpec(data=2, fsdp=4))
    cfg = tiny_cfg(d_model=32, d_ff=64)
    model = Transformer(cfg)
    tokens = jnp.zeros((8, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)

    def apply_fn(p, batch):
        logits = model.apply(p, batch["tokens"])
        return cross_entropy_loss(logits[:, :-1], batch["tokens"][:, 1:])

    trainer = Trainer(mesh=mesh, apply_fn=apply_fn,
                      optimizer=optax.adam(1e-2), fsdp=True, donate=False)
    state = trainer.init_state(params)
    step_fn, placed = trainer.build_step(state)
    batch = {"tokens": jax.device_put(tokens, batch_sharding(mesh))}
    placed, metrics = step_fn(placed, batch)
    assert jnp.isfinite(metrics["loss"])


def test_checkpoint_roundtrip(tmp_path):
    from tony_tpu.train import CheckpointManager

    state = {"params": {"w": jnp.arange(4.0)}, "step": jnp.array(3)}
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    assert mgr.save(3, state, force=True)
    mgr.wait()
    template = jax.tree.map(jnp.zeros_like, state)
    restored = mgr.restore(template)
    np.testing.assert_array_equal(np.asarray(restored["params"]["w"]),
                                  np.arange(4.0))
    mgr.close()
