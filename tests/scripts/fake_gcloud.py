#!/usr/bin/env python3
"""Fake gcloud for provisioner tests (the MiniYARNCluster analog of the RM
conversation: tests drive create/describe/delete without GCP).

State lives under $FAKE_GCLOUD_DIR: ``<name>.node.json`` for TPU nodes,
``<name>.qr.json`` for queued resources (separate namespaces, as in real
gcloud where a queued resource and its node share a name). Every
invocation is appended to calls.log. Knobs (env):

  FAKE_GCLOUD_READY_AFTER  node describes before READY (default 2)
  FAKE_GCLOUD_HOSTS        comma ipAddress list when READY (default 2 IPs)
  FAKE_GCLOUD_FAIL_CREATE  non-empty -> create exits 1 (quota denial)
  FAKE_GCLOUD_DOOM         non-empty -> node lands PREEMPTED, not READY
"""

import json
import os
import sys

VALUE_FLAGS = {"--zone", "--project", "--format", "--accelerator-type",
               "--version", "--runtime-version", "--node-id", "--network",
               "--labels", "--node-count", "--node-prefix"}


def state_path(key):
    return os.path.join(os.environ["FAKE_GCLOUD_DIR"], key + ".json")


def load(key):
    try:
        with open(state_path(key)) as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None


def save(key, st):
    with open(state_path(key), "w") as f:
        json.dump(st, f)


def main():
    argv = sys.argv[1:]
    with open(os.path.join(os.environ["FAKE_GCLOUD_DIR"], "calls.log"),
              "a") as f:
        f.write(" ".join(argv) + "\n")
    pos, flags = [], {}
    i = 0
    while i < len(argv):
        a = argv[i]
        if a in VALUE_FLAGS:
            flags[a] = argv[i + 1]
            i += 2
        elif a.startswith("--"):
            flags[a] = True
            i += 1
        else:
            pos.append(a)
            i += 1
    if pos[:2] != ["compute", "tpus"] or len(pos) < 5:
        print("fake gcloud: unsupported invocation", file=sys.stderr)
        return 64
    kind, verb, name = pos[2], pos[3], pos[4]
    ready_after = int(os.environ.get("FAKE_GCLOUD_READY_AFTER", "2"))
    key = f"{name}.qr" if kind == "queued-resources" else f"{name}.node"

    if verb == "create":
        if os.environ.get("FAKE_GCLOUD_FAIL_CREATE"):
            print("ERROR: quota exceeded for TPU cores", file=sys.stderr)
            return 1
        n_nodes = int(flags.get("--node-count", "0") or 0)
        if kind == "queued-resources" and n_nodes > 1:
            # multislice shape: one queued resource, N nodes <prefix>-i;
            # each node gets its own 10.0.<i>.x endpoints when READY
            prefix = flags.get("--node-prefix", name)
            names = [f"{prefix}-{i}" for i in range(n_nodes)]
            save(key, {"name": name, "kind": "qr", "describes": 0,
                       "deleted": False, "nodes": names})
            for i, node_name in enumerate(names):
                save(f"{node_name}.node",
                     {"name": node_name, "state": "CREATING", "describes": 0,
                      "accel": flags.get("--accelerator-type", ""),
                      "deleted": False, "node_index": i})
            return 0
        node = {"name": name, "state": "CREATING", "describes": 0,
                "accel": flags.get("--accelerator-type", ""),
                "deleted": False}
        if kind == "queued-resources":
            save(key, {"name": name, "kind": "qr", "describes": 0,
                       "deleted": False, "nodes": [name]})
        save(f"{name}.node", node)
        return 0

    st = load(key)
    if verb == "describe":
        if st is None or st.get("deleted"):
            print(f"ERROR: NOT_FOUND: {name}", file=sys.stderr)
            return 1
        st["describes"] += 1
        save(key, st)
        if kind == "queued-resources":
            qstate = "ACTIVE" if st["describes"] >= 1 else \
                "WAITING_FOR_RESOURCES"
            print(json.dumps({"name": name, "state": {"state": qstate}}))
            return 0
        if st["describes"] >= ready_after:
            st["state"] = "PREEMPTED" if os.environ.get("FAKE_GCLOUD_DOOM") \
                else "READY"
            save(key, st)
        out = {"name": name, "state": st["state"]}
        if st["state"] == "READY":
            if "node_index" in st:  # one node of a multi-node resource
                idx = st["node_index"]
                hosts = [f"10.0.{idx}.1", f"10.0.{idx}.2"]
            else:
                hosts = os.environ.get("FAKE_GCLOUD_HOSTS",
                                       "10.0.0.1,10.0.0.2").split(",")
            out["networkEndpoints"] = [{"ipAddress": h} for h in hosts
                                       if h.strip()]
        print(json.dumps(out))
        return 0

    if verb == "delete":
        if st is None:
            return 1
        st["deleted"] = True
        save(key, st)
        if kind == "queued-resources":
            for node_name in st.get("nodes", [name]):
                node = load(f"{node_name}.node")
                if node is not None:
                    node["deleted"] = True
                    save(f"{node_name}.node", node)
        return 0
    print(f"fake gcloud: unknown verb {verb}", file=sys.stderr)
    return 64


if __name__ == "__main__":
    raise SystemExit(main())
