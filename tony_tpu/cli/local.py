"""``tony-tpu local`` — LocalSubmitter equivalent.

Reference: tony-cli LocalSubmitter.java: boots a MiniCluster, runs a job
against it with security off, tears down. Here: isolated temp staging +
fast timings + CPU jax, then a normal submission.
"""

from __future__ import annotations

import logging

from tony_tpu import constants as C
from tony_tpu.cli.submit import build_parser, conf_from_args
from tony_tpu.client import TonyClient
from tony_tpu.mini import MiniTonyCluster


def main(argv: list[str] | None = None) -> int:
    logging.basicConfig(level=logging.INFO)
    parser = build_parser()
    parser.prog = "tony-tpu local"
    args = parser.parse_args(argv)
    with MiniTonyCluster() as mini:
        conf = mini.adopt(conf_from_args(args))
        conf.set("tony.application.security.enabled", False)
        ok = TonyClient(conf).run()
    return C.EXIT_SUCCESS if ok else C.EXIT_FAIL


if __name__ == "__main__":
    raise SystemExit(main())
