from tony_tpu.rpc.client import RpcClient, RpcError
from tony_tpu.rpc.server import RpcServer

__all__ = ["RpcClient", "RpcError", "RpcServer"]
