"""TCP proxy — tony-proxy equivalent.

Reference: tony-proxy ProxyServer.java:21-91: a threaded byte-pump proxying
a local gateway port to a host inside the cluster, used by the notebook
submitter to tunnel Jupyter. A native C++ implementation (native/proxy.cc)
is used when built (``make -C native``); this module is the fallback and
the control wrapper.
"""

from __future__ import annotations

import logging
import os
import shutil
import socket
import subprocess
import threading

log = logging.getLogger(__name__)

_NATIVE_BIN = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))), "native", "build", "tony_proxy")


class ProxyServer:
    def __init__(self, remote_host: str, remote_port: int, local_port: int = 0,
                 prefer_native: bool = True):
        self.remote_host = remote_host
        self.remote_port = remote_port
        self._native_proc: subprocess.Popen | None = None
        self._server: socket.socket | None = None
        self._stop = threading.Event()
        self.local_port = local_port
        self.prefer_native = prefer_native and os.path.exists(_NATIVE_BIN) and \
            shutil.which(_NATIVE_BIN) is not None

    def start(self) -> "ProxyServer":
        if self.prefer_native:
            return self._start_native()
        return self._start_python()

    def _start_native(self) -> "ProxyServer":
        # native binary prints "LISTENING <port>" then serves until killed
        self._native_proc = subprocess.Popen(
            [_NATIVE_BIN, str(self.local_port), self.remote_host,
             str(self.remote_port)],
            stdout=subprocess.PIPE, text=True)
        line = self._native_proc.stdout.readline().strip()
        if line.startswith("LISTENING"):
            self.local_port = int(line.split()[1])
            log.info("native proxy :%d -> %s:%d", self.local_port,
                     self.remote_host, self.remote_port)
            return self
        log.warning("native proxy failed to start (%r); falling back", line)
        self._native_proc.kill()
        self._native_proc = None
        return self._start_python()

    def _start_python(self) -> "ProxyServer":
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind(("", self.local_port))
        self._server.listen(16)
        self.local_port = self._server.getsockname()[1]
        threading.Thread(target=self._accept_loop, name="proxy-accept",
                         daemon=True).start()
        log.info("proxy :%d -> %s:%d", self.local_port, self.remote_host,
                 self.remote_port)
        return self

    def _accept_loop(self) -> None:
        assert self._server is not None
        while not self._stop.is_set():
            try:
                client, _ = self._server.accept()
            except OSError:
                return
            try:
                upstream = socket.create_connection(
                    (self.remote_host, self.remote_port), timeout=10)
            except OSError:
                log.warning("proxy: upstream %s:%d unreachable",
                            self.remote_host, self.remote_port)
                client.close()
                continue
            for a, b in ((client, upstream), (upstream, client)):
                threading.Thread(target=self._pump, args=(a, b),
                                 daemon=True).start()

    @staticmethod
    def _pump(src: socket.socket, dst: socket.socket) -> None:
        """Ref: ProxyServer's per-direction copy threads."""
        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                dst.sendall(data)
        except OSError:
            pass
        finally:
            for s in (src, dst):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                s.close()

    def stop(self) -> None:
        self._stop.set()
        if self._native_proc is not None:
            self._native_proc.kill()
        if self._server is not None:
            self._server.close()
