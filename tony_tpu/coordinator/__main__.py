from tony_tpu.coordinator.coordinator import main

raise SystemExit(main())
