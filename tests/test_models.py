"""Model + trainer smoke tests on CPU (tiny shapes)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tony_tpu.models import ResNet18, Transformer, TransformerConfig
from tony_tpu.parallel import MeshSpec, data_parallel_mesh, make_mesh
from tony_tpu.parallel.sharding import batch_sharding
from tony_tpu.train import Trainer, cross_entropy_loss


def tiny_cfg(**kw):
    defaults = dict(vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
                    max_seq_len=64, dtype=jnp.float32,
                    attention_backend="blockwise", attention_block_size=16)
    defaults.update(kw)
    return TransformerConfig(**defaults)


def test_transformer_forward_shapes():
    cfg = tiny_cfg()
    model = Transformer(cfg)
    tokens = jnp.zeros((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    logits = model.apply(params, tokens)
    assert logits.shape == (2, 16, 64)
    assert jnp.all(jnp.isfinite(logits))


def test_transformer_backends_agree():
    cfg_ref = tiny_cfg(attention_backend="reference")
    cfg_blk = tiny_cfg(attention_backend="blockwise")
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)
    model_ref = Transformer(cfg_ref)
    params = model_ref.init(jax.random.PRNGKey(0), tokens)
    out_ref = model_ref.apply(params, tokens)
    out_blk = Transformer(cfg_blk).apply(params, tokens)
    np.testing.assert_allclose(np.asarray(out_ref), np.asarray(out_blk),
                               atol=1e-4, rtol=1e-4)


def test_transformer_ring_backend_on_mesh():
    mesh = make_mesh(MeshSpec(data=-1, seq=4))
    cfg_ring = tiny_cfg(attention_backend="ring", mesh=mesh)
    cfg_ref = tiny_cfg(attention_backend="reference")
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 32), 0, 64)
    model = Transformer(cfg_ref)
    params = model.init(jax.random.PRNGKey(0), tokens)
    out_ref = model.apply(params, tokens)
    out_ring = Transformer(cfg_ring).apply(params, tokens)
    np.testing.assert_allclose(np.asarray(out_ref), np.asarray(out_ring),
                               atol=1e-4, rtol=1e-4)


def test_transformer_gqa_forward_and_decode():
    """GQA (n_kv_heads < n_heads): forward finite, decode cache holds only
    kv_heads, and incremental decode agrees with the full forward pass."""
    cfg = tiny_cfg(n_heads=4, n_kv_heads=2, attention_backend="reference")
    model = Transformer(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(5), (2, 8), 0, 64)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    full = model.apply(variables, tokens)
    assert full.shape == (2, 8, 64)

    cache = model.init(jax.random.PRNGKey(0), tokens, decode=True)["cache"]
    ck = cache["block_0"]["attn"]["cached_key"]
    assert ck.shape == (2, cfg.max_seq_len, 2, cfg.head_dim)  # kv_heads=2
    step_logits = []
    for i in range(tokens.shape[1]):
        logits, mut = model.apply(
            {"params": variables["params"], "cache": cache},
            tokens[:, i:i + 1], decode=True, mutable=["cache"])
        cache = mut["cache"]
        step_logits.append(logits[:, 0])
    decoded = jnp.stack(step_logits, axis=1)
    np.testing.assert_allclose(np.asarray(full), np.asarray(decoded),
                               atol=1e-3, rtol=1e-3)


def test_gqa_tensor_parallel_sharding():
    """GQA K/V kernels (n_kv_heads < tensor axis) must be replicated on the
    head dim under tp presets, while full-MHA q stays tensor-sharded."""
    from jax.sharding import NamedSharding
    from tony_tpu.models.transformer import logical_axis_rules_tree
    from tony_tpu.parallel.sharding import tree_shardings

    mesh = make_mesh(MeshSpec(data=2, tensor=4))
    cfg = tiny_cfg(n_heads=4, n_kv_heads=2)
    model = Transformer(cfg)
    tokens = jnp.zeros((2, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    axes = logical_axis_rules_tree(params["params"])
    sh = tree_shardings(mesh, axes, "tp")
    blk = sh["block_0"]["attn"]
    assert blk["q"]["kernel"].spec[1] == "tensor"
    assert blk["k"]["kernel"].spec[1] is None  # kv_heads: replicated
    # placement must succeed (this raised pre-fix: 2 not divisible by 4)
    placed = jax.device_put(params["params"], sh)
    assert isinstance(jax.tree_util.tree_leaves(placed)[0].sharding,
                      NamedSharding)


def test_transformer_moe_blocks():
    """moe_every=2 replaces every 2nd MLP with expert-parallel MoE; aux
    load-balance loss is sown into the `losses` collection."""
    from tony_tpu.models import moe_aux_loss

    cfg = tiny_cfg(moe_every=2, moe_num_experts=4, moe_top_k=2)
    model = Transformer(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(6), (2, 16), 0, 64)
    params = model.init(jax.random.PRNGKey(0), tokens)
    assert "moe" in params["params"]["block_1"]  # 2nd block is MoE
    assert "mlp" in params["params"]["block_0"]  # 1st stays dense
    wi = params["params"]["block_1"]["moe"]["wi"]
    assert wi.shape == (4, cfg.d_model, cfg.d_ff)
    # init must NOT leak a "losses" collection (it would be trained as a
    # free parameter and double-counted when apply seeds the collection)
    assert set(params) == {"params"}
    logits, mut = model.apply(params, tokens, mutable=["losses"])
    assert logits.shape == (2, 16, 64)
    assert jnp.all(jnp.isfinite(logits))
    aux_leaves = jax.tree_util.tree_leaves(mut["losses"])
    assert len(aux_leaves) == 1  # exactly one sown value for the one MoE block
    aux = moe_aux_loss(mut["losses"])
    assert float(aux) > 0.0
    # plain apply (no mutable) still works — sow no-ops
    logits2 = model.apply(params, tokens)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(logits2))


def test_moe_param_tree_logical_axes_and_ep_sharding():
    """logical_axis_rules_tree must handle MoE trees (regression: it used
    moe_logical_axes without importing it) and place them on an ep mesh."""
    from tony_tpu.models.transformer import logical_axis_rules_tree
    from tony_tpu.parallel.sharding import tree_shardings

    mesh = make_mesh(MeshSpec(data=-1, expert=2))
    cfg = tiny_cfg(moe_every=1, moe_num_experts=2, moe_top_k=1)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((2, 8), jnp.int32))["params"]
    axes = logical_axis_rules_tree(params)
    assert axes["block_0"]["moe"]["wi"] == ("expert", None, "mlp")
    assert axes["block_0"]["moe"]["router"] == (None, None)
    sh = tree_shardings(mesh, axes, "ep")
    assert sh["block_0"]["moe"]["wi"].spec[0] == "expert"
    jax.device_put(params, sh)  # placement must succeed


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_transformer_moe_trains_on_expert_mesh():
    from tony_tpu.models import moe_aux_loss

    mesh = make_mesh(MeshSpec(data=-1, expert=2))
    cfg = tiny_cfg(moe_every=1, moe_num_experts=2, moe_top_k=1)
    model = Transformer(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(7), (8, 16), 0, 64)
    params = model.init(jax.random.PRNGKey(0), tokens)

    def apply_fn(p, batch):
        logits, mut = model.apply(p, batch["tokens"], mutable=["losses"])
        ce = cross_entropy_loss(logits[:, :-1], batch["tokens"][:, 1:])
        return ce + moe_aux_loss(mut["losses"])

    trainer = Trainer(mesh=mesh, apply_fn=apply_fn,
                      optimizer=optax.adam(1e-2), donate=False)
    state = trainer.init_state(params)
    step_fn, placed = trainer.build_step(state)
    batch = {"tokens": jax.device_put(tokens, batch_sharding(mesh))}
    losses = []
    for _ in range(5):
        placed, metrics = step_fn(placed, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses


def test_scan_layers_forward_decode_and_sharding():
    """scan_layers: stacked params (leading n_layers dim tagged "layers"),
    forward finite, incremental decode agrees with full forward, and the
    pp preset shards the stacked dim over the pipe axis."""
    from tony_tpu.models.transformer import logical_axis_rules_tree
    from tony_tpu.parallel.sharding import tree_shardings

    cfg = tiny_cfg(n_layers=4, n_heads=4, n_kv_heads=2, scan_layers=True,
                   attention_backend="reference")
    model = Transformer(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(8), (2, 8), 0, 64)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    qk = variables["params"]["layers"]["block"]["attn"]["q"]["kernel"]
    assert qk.shape == (4, cfg.d_model, 4, cfg.head_dim)  # stacked
    full = model.apply(variables, tokens)
    assert full.shape == (2, 8, 64) and jnp.all(jnp.isfinite(full))

    cache = model.init(jax.random.PRNGKey(0), tokens, decode=True)["cache"]
    ck = cache["layers"]["block"]["attn"]["cached_key"]
    assert ck.shape == (4, 2, cfg.max_seq_len, 2, cfg.head_dim)
    step_logits = []
    for i in range(tokens.shape[1]):
        logits, mut = model.apply(
            {"params": variables["params"], "cache": cache},
            tokens[:, i:i + 1], decode=True, mutable=["cache"])
        cache = mut["cache"]
        step_logits.append(logits[:, 0])
    decoded = jnp.stack(step_logits, axis=1)
    np.testing.assert_allclose(np.asarray(full), np.asarray(decoded),
                               atol=1e-3, rtol=1e-3)

    axes = logical_axis_rules_tree(variables["params"])
    assert axes["layers"]["block"]["attn"]["q"]["kernel"] == \
        ("layers", "embed", "heads", "kv")
    assert axes["layers"]["block"]["attn"]["k"]["kernel"] == \
        ("layers", "embed", "kv_heads", "kv")
    mesh = make_mesh(MeshSpec(data=-1, pipe=4))
    sh = tree_shardings(mesh, axes, "pp")
    assert sh["layers"]["block"]["mlp"]["wi"]["kernel"].spec[0] == "pipe"
    jax.device_put(variables["params"], sh)


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_remat_policy_dots_matches_nothing():
    """remat_policy='dots' (keep matmul outputs, skip the 2N recompute)
    is a scheduling choice only: grads must match full remat exactly."""
    cfg = tiny_cfg(n_layers=2, scan_layers=True, remat=True)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 16), 0, 64)
    params = Transformer(cfg).init(jax.random.PRNGKey(0), tokens)

    def loss(c):
        def f(p):
            logits = Transformer(c).apply(p, tokens)
            return jnp.mean(logits.astype(jnp.float32) ** 2)
        return jax.grad(f)(params)

    g_nothing = loss(cfg)
    for policy in ("dots", "attn_saved"):
        g_p = loss(dataclasses.replace(cfg, remat_policy=policy))
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
            g_nothing, g_p)
    with pytest.raises(ValueError, match="remat_policy"):
        Transformer(dataclasses.replace(cfg, remat_policy="bogus")).apply(
            params, tokens)


def test_scan_layers_trains_and_remat():
    cfg = tiny_cfg(n_layers=3, scan_layers=True, remat=True)
    model = Transformer(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(9), (8, 16), 0, 64)
    params = model.init(jax.random.PRNGKey(0), tokens)

    def apply_fn(p, batch):
        logits = model.apply(p, batch["tokens"])
        return cross_entropy_loss(logits[:, :-1], batch["tokens"][:, 1:])

    mesh = data_parallel_mesh()
    trainer = Trainer(mesh=mesh, apply_fn=apply_fn,
                      optimizer=optax.adam(1e-2), donate=False)
    state = trainer.init_state(params)
    step_fn, placed = trainer.build_step(state)
    batch = {"tokens": jax.device_put(tokens, batch_sharding(mesh))}
    losses = []
    for _ in range(5):
        placed, metrics = step_fn(placed, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses


def test_scan_layers_rejects_moe():
    with np.testing.assert_raises(ValueError):
        tiny_cfg(scan_layers=True, moe_every=1)  # rejected at construction


def test_resnet_forward():
    model = ResNet18(num_classes=10, num_filters=8, dtype=jnp.float32)
    x = jnp.ones((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)


def test_trainer_loss_decreases():
    mesh = data_parallel_mesh()
    cfg = tiny_cfg()
    model = Transformer(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (8, 16), 0, 64)
    params = model.init(jax.random.PRNGKey(0), tokens)

    def apply_fn(p, batch):
        logits = model.apply(p, batch["tokens"])
        return cross_entropy_loss(logits[:, :-1], batch["tokens"][:, 1:])

    trainer = Trainer(mesh=mesh, apply_fn=apply_fn,
                      optimizer=optax.adam(1e-2), donate=False)
    state = trainer.init_state(params)
    step_fn, placed = trainer.build_step(state)
    batch = {"tokens": jax.device_put(tokens, batch_sharding(mesh))}
    losses = []
    for _ in range(5):
        placed, metrics = step_fn(placed, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0], losses
    assert int(placed.step) == 5


def test_trainer_fsdp_sharding():
    mesh = make_mesh(MeshSpec(data=2, fsdp=4))
    cfg = tiny_cfg(d_model=32, d_ff=64)
    model = Transformer(cfg)
    tokens = jnp.zeros((8, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)

    def apply_fn(p, batch):
        logits = model.apply(p, batch["tokens"])
        return cross_entropy_loss(logits[:, :-1], batch["tokens"][:, 1:])

    trainer = Trainer(mesh=mesh, apply_fn=apply_fn,
                      optimizer=optax.adam(1e-2), fsdp=True, donate=False)
    state = trainer.init_state(params)
    step_fn, placed = trainer.build_step(state)
    batch = {"tokens": jax.device_put(tokens, batch_sharding(mesh))}
    placed, metrics = step_fn(placed, batch)
    assert jnp.isfinite(metrics["loss"])


def test_checkpoint_roundtrip(tmp_path):
    from tony_tpu.train import CheckpointManager

    state = {"params": {"w": jnp.arange(4.0)}, "step": jnp.array(3)}
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    assert mgr.save(3, state, force=True)
    mgr.wait()
    template = jax.tree.map(jnp.zeros_like, state)
    restored = mgr.restore(template)
    np.testing.assert_array_equal(np.asarray(restored["params"]["w"]),
                                  np.arange(4.0))
    mgr.close()


def test_gated_mlp_rejected_with_moe():
    """MoE experts don't implement the SwiGLU gate; the combo must raise
    at config construction instead of silently training an architecturally
    inconsistent model."""
    import jax.numpy as jnp
    import pytest
    from tony_tpu.models import Transformer, TransformerConfig

    with pytest.raises(ValueError, match="gated_mlp"):
        TransformerConfig(
            vocab_size=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
            max_seq_len=32, dtype=jnp.float32, attention_backend="reference",
            gated_mlp=True, moe_every=2)


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_pipelined_forward_matches_plain_apply():
    """PP on the flagship model: identical logits to model.apply with the
    same scan_layers params, GPipe and interleaved schedules."""
    from tony_tpu.models import Transformer, TransformerConfig, pipelined_forward
    from tony_tpu.parallel import MeshSpec, make_mesh

    mesh = make_mesh(MeshSpec(data=2, pipe=4))
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=8,
                            d_ff=64, max_seq_len=32, dtype=jnp.float32,
                            attention_backend="reference", scan_layers=True)
    model = Transformer(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (8, 16), 0, 64)
    variables = model.init(jax.random.PRNGKey(1), tokens)
    ref = np.asarray(model.apply(variables, tokens))

    # 8 layers on 4 pipe devices: GPipe needs 4 stages -> use R=2 circular;
    # also exercise plain GPipe with a 4-layer config
    out = pipelined_forward(model, variables, tokens, mesh=mesh,
                            n_microbatches=4, circular_repeats=2)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-4, rtol=1e-4)

    cfg4 = TransformerConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=4,
                             d_ff=64, max_seq_len=32, dtype=jnp.float32,
                             attention_backend="reference", scan_layers=True)
    m4 = Transformer(cfg4)
    v4 = m4.init(jax.random.PRNGKey(2), tokens)
    ref4 = np.asarray(m4.apply(v4, tokens))
    out4 = pipelined_forward(m4, v4, tokens, mesh=mesh, n_microbatches=4)
    np.testing.assert_allclose(np.asarray(out4), ref4, atol=1e-4, rtol=1e-4)


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_pipelined_forward_trains():
    """Loss + grads through the pipelined model decrease under adam."""
    from tony_tpu.models import Transformer, TransformerConfig, pipelined_forward
    from tony_tpu.parallel import MeshSpec, make_mesh
    from tony_tpu.train import cross_entropy_loss

    mesh = make_mesh(MeshSpec(data=2, pipe=4))
    cfg = TransformerConfig(vocab_size=32, d_model=16, n_heads=2, n_layers=4,
                            d_ff=32, max_seq_len=16, dtype=jnp.float32,
                            attention_backend="reference", scan_layers=True)
    model = Transformer(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (8, 12), 0, 32)
    variables = model.init(jax.random.PRNGKey(4), tokens)

    def loss(v):
        logits = pipelined_forward(model, v, tokens, mesh=mesh,
                                   n_microbatches=4, remat=True)
        return cross_entropy_loss(logits[:, :-1], tokens[:, 1:])

    tx = optax.adam(1e-2)
    opt = tx.init(variables)

    @jax.jit
    def step(v, o):
        g = jax.grad(loss)(v)
        updates, o = tx.update(g, o, v)
        return optax.apply_updates(v, updates), o

    l0 = float(loss(variables))
    for _ in range(10):
        variables, opt = step(variables, opt)
    assert float(loss(variables)) < l0


def test_pipelined_forward_validates():
    from tony_tpu.models import Transformer, TransformerConfig, pipelined_forward
    from tony_tpu.parallel import MeshSpec, make_mesh

    mesh = make_mesh(MeshSpec(data=2, pipe=4))
    cfg = TransformerConfig(vocab_size=32, d_model=16, n_heads=2, n_layers=6,
                            d_ff=32, max_seq_len=16, dtype=jnp.float32,
                            attention_backend="reference", scan_layers=True)
    model = Transformer(cfg)
    tokens = jnp.zeros((4, 8), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens)
    with pytest.raises(ValueError, match="n_layers"):
        pipelined_forward(model, variables, tokens, mesh=mesh,
                          n_microbatches=4)
    cfg_ns = TransformerConfig(vocab_size=32, d_model=16, n_heads=2,
                               n_layers=4, d_ff=32, max_seq_len=16,
                               dtype=jnp.float32,
                               attention_backend="reference")
    m = Transformer(cfg_ns)
    v = m.init(jax.random.PRNGKey(0), tokens)
    with pytest.raises(ValueError, match="scan_layers"):
        pipelined_forward(m, v, tokens, mesh=mesh, n_microbatches=4)


def test_segment_ids_isolate_packed_documents():
    """Packing two documents with segment_ids must reproduce each
    document's standalone logits exactly (no cross-document leakage)."""
    for backend in ("reference", "blockwise"):
        cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                                n_layers=2, d_ff=64, max_seq_len=32,
                                dtype=jnp.float32, attention_backend=backend,
                                attention_block_size=8)
        model = Transformer(cfg)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 8), jnp.int32))
        doc_a = jax.random.randint(jax.random.PRNGKey(1), (1, 6), 0, 64)
        doc_b = jax.random.randint(jax.random.PRNGKey(2), (1, 10), 0, 64)
        packed = jnp.concatenate([doc_a, doc_b], axis=1)
        segs = jnp.asarray([[0] * 6 + [1] * 10], jnp.int32)
        out = np.asarray(model.apply(params, packed, segment_ids=segs))
        ref_a = np.asarray(model.apply(params, doc_a))
        # doc B standalone: positions restart at 0 only for learned
        # positions; RoPE is relative so same-segment attention with
        # shifted absolute positions still matches standalone
        ref_b = np.asarray(model.apply(params, doc_b))
        np.testing.assert_allclose(out[:, :6], ref_a, atol=1e-4, rtol=1e-4,
                                   err_msg=backend)
        np.testing.assert_allclose(out[:, 6:], ref_b, atol=1e-4, rtol=1e-4,
                                   err_msg=backend)


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_segment_ids_scan_layers_and_rejections():
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=2,
                            d_ff=64, max_seq_len=32, dtype=jnp.float32,
                            attention_backend="reference", scan_layers=True)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    tokens = jax.random.randint(jax.random.PRNGKey(3), (2, 12), 0, 64)
    segs = jnp.where(jnp.arange(12)[None] < 5, 0, 1)
    segs = jnp.broadcast_to(segs, (2, 12))
    out = model.apply(params, tokens, segment_ids=segs)
    assert out.shape == (2, 12, 64)
    # changing the other segment's tokens must not change this segment
    tokens2 = tokens.at[:, 6:].set((tokens[:, 6:] + 1) % 64)
    out2 = model.apply(params, tokens2, segment_ids=segs)
    np.testing.assert_allclose(np.asarray(out[:, :5]),
                               np.asarray(out2[:, :5]), atol=1e-5, rtol=1e-5)
    with pytest.raises(ValueError, match="decode"):
        model.apply(params, tokens, decode=True, segment_ids=segs,
                    mutable=["cache"])
    # sp backends accept segment_ids since r4 (VERDICT r3 weak #3): the
    # ulysses logits must match the reference backend on packed docs
    mesh_sp = make_mesh(MeshSpec(data=2, seq=4))
    base_sp = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=1,
                   d_ff=64, max_seq_len=32, dtype=jnp.float32)
    cfg_u = TransformerConfig(**base_sp, attention_backend="ulysses",
                              attention_block_size=4, mesh=mesh_sp)
    cfg_r = TransformerConfig(**base_sp, attention_backend="reference")
    m_u, m_r = Transformer(cfg_u), Transformer(cfg_r)
    p_u = m_r.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))
    out_u = m_u.apply(p_u, tokens, segment_ids=segs)
    out_r = m_r.apply(p_u, tokens, segment_ids=segs)
    np.testing.assert_allclose(np.asarray(out_u), np.asarray(out_r),
                               atol=1e-5, rtol=1e-5)


def test_segment_ids_pallas_backend_matches_reference():
    cfg_p = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                              n_layers=2, d_ff=64, max_seq_len=32,
                              dtype=jnp.float32, attention_backend="pallas",
                              attention_block_size=8)
    cfg_r = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                              n_layers=2, d_ff=64, max_seq_len=32,
                              dtype=jnp.float32,
                              attention_backend="reference")
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 24), 0, 64)
    segs = jnp.asarray([[0] * 9 + [1] * 15, [0] * 24], jnp.int32)
    model_r = Transformer(cfg_r)
    params = model_r.init(jax.random.PRNGKey(1), tokens)
    ref = model_r.apply(params, tokens, segment_ids=segs)
    out = Transformer(cfg_p).apply(params, tokens, segment_ids=segs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)
