"""SLO-aware admission: weighted fair queuing + per-tenant quotas.

The gateway's original admission queue was one FIFO deque per replica
— correct for a single cooperative client, hopeless for the
multi-tenant, priority-skewed traffic ROADMAP item 3 targets: one
tenant's batch flood parks every interactive request behind it, and
the only defense (the global ``max_queue`` bound) punishes everyone
equally. This module replaces it with the Borg/YARN-shaped answer:

- ``WFQueue``: weighted fair queuing over PRIORITY TIERS
  (``interactive`` / ``standard`` / ``batch`` by default). Each tier
  accumulates *virtual work* — token cost divided by its weight — and
  the queue always pops the non-empty tier with the least virtual
  work. A saturating ``batch`` flood therefore costs ``interactive``
  at most one request's service time per ``weight_i / weight_b``
  admissions (bounded wait, never starvation), while an otherwise-idle
  queue gives any single tier the full admission rate (the scheduler
  is work-conserving: weights shape CONTENTION, they never reserve
  idle capacity). Within a tier, tickets pop deadline-first
  (``ttl_s``-anchored; no deadline sorts last in arrival order), so a
  request about to expire is not wasted behind patient ones.
- ``TenantQuotas``: a token bucket per tenant over ESTIMATED token
  cost (prompt + max_new_tokens — the same estimate routing uses).
  A tenant past its rate gets an immediate, honest 429 with a
  ``Retry-After`` derived from its bucket's refill time: quota
  breaches are priced, not queued, so one tenant's overrun can never
  occupy queue slots other tenants need (the "never starvation"
  half of the quota contract).

Both are pure host-side data structures with no locking of their own:
the gateway serializes ``WFQueue`` access under each replica's
condition variable, and ``TenantQuotas`` carries one small lock for
the cross-thread ``submit()`` path.
"""

from __future__ import annotations

import heapq
import math
import threading
import time

# the default tier ladder: weights shape how admission interleaves
# UNDER CONTENTION (8:4:1 — interactive pops ~8x as often as batch per
# unit token cost when both queues are non-empty); an idle queue gives
# any tier its full throughput. Order is the tie-break rank.
DEFAULT_TIER_WEIGHTS: dict[str, float] = {
    "interactive": 8.0,
    "standard": 4.0,
    "batch": 1.0,
}

DEFAULT_TIER = "standard"


def parse_tier_weights(spec: str) -> dict[str, float]:
    """Parse a CLI tier spec (``"interactive=8,standard=4,batch=1"``).
    Empty spec -> the defaults. Raises ``ValueError`` on malformed
    entries or non-positive weights (a zero weight would starve the
    tier — the exact failure mode WFQ exists to rule out)."""
    if not spec.strip():
        return dict(DEFAULT_TIER_WEIGHTS)
    out: dict[str, float] = {}
    for part in spec.split(","):
        name, sep, val = part.partition("=")
        name = name.strip()
        if not sep or not name:
            raise ValueError(f"bad tier weight {part!r} "
                             f"(want name=weight,name=weight,...)")
        try:
            w = float(val)
        except ValueError:
            raise ValueError(f"bad tier weight {part!r}: {val!r} is not "
                             f"a number") from None
        if not w > 0:
            raise ValueError(f"tier {name!r} weight must be > 0 "
                             f"(got {w}); a zero-weight tier would starve")
        out[name] = w
    return out


class WFQueue:
    """Weighted fair queue of gateway tickets over priority tiers.

    Self-clocked fair queuing over per-tier virtual work: popping a
    ticket charges its tier ``cost / weight``; ``pop()`` serves the
    non-empty tier with the least accumulated virtual work (ties break
    by tier rank — the order of the weights dict). A tier going idle
    keeps its counter, and a tier waking from idle is CAUGHT UP to the
    busiest floor (the min virtual work among non-empty tiers), so a
    long-idle tier gets priority for one scheduling round but can
    never cash in unbounded credit.

    Within a tier, order is (deadline, arrival): a ticket's deadline
    is anchored to its ORIGINAL submit time (``Ticket.deadline`` is
    derived from ``t_submit + ttl_s``), so a failover re-enqueue
    re-sorts the ticket by the deadline it always had — never a
    refreshed one.

    NOT thread-safe by design: the owning replica serializes access
    under its condition variable, same as the deque it replaces.
    """

    def __init__(self, weights: dict[str, float] | None = None):
        self.weights = dict(weights or DEFAULT_TIER_WEIGHTS)
        if not self.weights:
            raise ValueError("WFQueue needs at least one tier")
        for tier, w in self.weights.items():
            if not w > 0:
                raise ValueError(f"tier {tier!r} weight must be > 0")
        self._rank = {t: i for i, t in enumerate(self.weights)}
        self._heaps: dict[str, list] = {t: [] for t in self.weights}
        self._vwork: dict[str, float] = {t: 0.0 for t in self.weights}
        self._seq = 0
        self._n = 0

    # ------------------------------------------------------------ sizing

    def __len__(self) -> int:
        return self._n

    def __bool__(self) -> bool:
        return self._n > 0

    def depth_by_tier(self) -> dict[str, int]:
        return {t: len(h) for t, h in self._heaps.items() if h}

    def oldest_t_queued(self) -> float | None:
        """Earliest ``t_queued`` among waiting tickets (the queue's
        oldest-wait sensor — an autoscaler's most direct pressure
        signal). O(n) scan; admission queues are small by design."""
        oldest = None
        for heap in self._heaps.values():
            for _, _, ticket in heap:
                if oldest is None or ticket.t_queued < oldest:
                    oldest = ticket.t_queued
        return oldest

    # ------------------------------------------------------------ queue

    def _key(self, ticket) -> tuple:
        deadline = ticket.deadline
        return (math.inf if deadline is None else deadline, self._seq)

    def push(self, ticket) -> int:
        """Enqueue; returns the ticket's queue position (tickets ahead
        of it across all tiers — the ``queue_pos`` metrics record).
        Unknown tiers raise ``KeyError``: the gateway validates
        priority names at submit, so a miss here is a programming
        error, not a client error."""
        heap = self._heaps[ticket.tier]
        if not heap:
            # catch-up rule: a tier waking from idle starts at the
            # busiest floor — priority for one round, no banked credit
            floor = min((self._vwork[t] for t, h in self._heaps.items()
                         if h), default=None)
            if floor is not None:
                self._vwork[ticket.tier] = max(self._vwork[ticket.tier],
                                               floor)
        key = self._key(ticket)
        self._seq += 1
        ticket._wfq_key = key
        heapq.heappush(heap, (*key, ticket))
        self._n += 1
        return self._n - 1

    def unpop(self, ticket) -> None:
        """Put a just-popped ticket back at its old position and refund
        its tier's virtual-work charge (the engine-QueueFull putback
        path: the pop never resulted in service)."""
        heapq.heappush(self._heaps[ticket.tier], (*ticket._wfq_key, ticket))
        self._vwork[ticket.tier] -= ticket.cost / self.weights[ticket.tier]
        self._n += 1

    def pop(self):
        """The WFQ decision: least virtual work among non-empty tiers
        (rank breaks ties), deadline-first within the tier. Returns
        ``None`` when empty."""
        best = None
        for tier, heap in self._heaps.items():
            if not heap:
                continue
            cand = (self._vwork[tier], self._rank[tier])
            if best is None or cand < best[0]:
                best = (cand, tier)
        if best is None:
            return None
        tier = best[1]
        ticket = heapq.heappop(self._heaps[tier])[2]
        self._vwork[tier] += ticket.cost / self.weights[tier]
        self._n -= 1
        return ticket

    def steal_all(self) -> list:
        """Remove and return every ticket in WFQ service order (the
        failover steal): tickets keep their tier, so re-enqueueing them
        on a survivor re-applies the same fairness there."""
        out = []
        while True:
            ticket = self.pop()
            if ticket is None:
                return out
            out.append(ticket)


class TenantQuotas:
    """Per-tenant token-rate quotas: one token bucket per tenant over
    estimated request cost (prompt + budget tokens).

    ``rate_tokens_per_s <= 0`` disables quotas entirely (the default:
    a single-tenant deployment should pay zero bookkeeping).
    ``burst_tokens`` is the bucket depth (default ``4 * rate``): a
    tenant may burst that many tokens instantly, then sustain
    ``rate`` tokens/s. ``admit()`` returns ``None`` to admit or the
    seconds until the bucket could cover the request — the HTTP
    layer's ``Retry-After``. A request costing more than the whole
    burst charges exactly one full burst (documented in
    docs/SERVING.md): huge requests stay admittable but empty the
    tenant's bucket.

    Buckets are created on first sight and never expire; a tenant's
    entry is ~3 floats — millions of tenants fit in memory long before
    they fit in a fleet.

    A charge whose request is then refused downstream (the admission
    bound raced full, no healthy replica) must be ``refund()``ed: the
    tenant got zero service, its bucket must not pay. Rejection
    counting lives with the gateway's other shed accounting
    (``_Stats``), not here — one authoritative counter.
    """

    def __init__(self, rate_tokens_per_s: float = 0.0,
                 burst_tokens: float = 0.0):
        self.rate = float(rate_tokens_per_s)
        self.burst = float(burst_tokens) if burst_tokens > 0 \
            else 4.0 * max(self.rate, 0.0)
        self._lock = threading.Lock()
        self._buckets: dict[str, tuple[float, float]] = {}  # level, t

    @property
    def enabled(self) -> bool:
        return self.rate > 0

    def admit(self, tenant: str | None, cost: float,
              now: float | None = None) -> float | None:
        """Charge ``cost`` to ``tenant``'s bucket. Returns ``None`` on
        admit, else the retry-after seconds. Tenant ``None`` shares
        one anonymous bucket — with quotas on, unattributed traffic is
        a tenant too, not a bypass."""
        if not self.enabled:
            return None
        key = tenant or ""
        cost = min(float(cost), self.burst)  # a request bigger than
        # the burst charges the whole burst (else it could never pass)
        if now is None:
            now = time.monotonic()
        with self._lock:
            level, last = self._buckets.get(key, (self.burst, now))
            level = min(self.burst, level + (now - last) * self.rate)
            if level >= cost:
                self._buckets[key] = (level - cost, now)
                return None
            self._buckets[key] = (level, now)
            return (cost - level) / self.rate

    def refund(self, tenant: str | None, cost: float) -> None:
        """Re-credit a charge whose request was refused downstream of
        the quota gate (queue bound, no healthy replica): zero service
        delivered means zero tokens spent. Clamped the same way the
        charge was; the bucket's refill timestamp is untouched."""
        if not self.enabled:
            return
        key = tenant or ""
        cost = min(float(cost), self.burst)
        with self._lock:
            level, last = self._buckets.get(key,
                                            (self.burst - cost,
                                             time.monotonic()))
            self._buckets[key] = (min(self.burst, level + cost), last)

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "rate_tokens_per_s": self.rate,
                "burst_tokens": self.burst,
                "tenants": len(self._buckets),
            }
