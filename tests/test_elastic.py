"""Elastic training tests — checkpoint-aware gang restart (the reference
stubs elasticity: horovod_driver.py:28-29 elastic_driver_fn = pass)."""

import glob
import json
import os
import threading
import time


from tony_tpu import elastic
from tony_tpu.mini import MiniTonyCluster, script_conf
import pytest

SCRIPTS = os.path.join(os.path.dirname(__file__), "scripts")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_control_file_roundtrip(tmp_path):
    assert not elastic.save_and_exit_requested(str(tmp_path), "worker:0")
    elastic.write_save_and_exit(str(tmp_path), task_id="worker:0")
    assert elastic.save_and_exit_requested(str(tmp_path), "worker:0")
    assert not elastic.save_and_exit_requested(str(tmp_path), "worker:1")


def test_resize_validation():
    import tempfile

    from tony_tpu.config import TonyConf
    from tony_tpu.coordinator.coordinator import Coordinator

    conf = TonyConf()
    conf.set("tony.worker.instances", 2)
    conf.set("tony.application.security.enabled", False)
    with tempfile.TemporaryDirectory() as tmp:
        conf.set("tony.staging-dir", tmp)
        conf.set("tony.history.location", os.path.join(tmp, "hist"))
        coord = Coordinator(conf, "application_rsz", os.path.join(tmp, "job"))
        try:
            assert coord.request_resize("worker", 4) is True
            assert coord.request_resize("worker", 0) is False
            assert coord.request_resize("ghost", 2) is False
            assert coord._take_pending_resize() == {"worker": 4}
            assert coord._take_pending_resize() == {}
        finally:
            coord.rpc.stop()
            coord.metrics_rpc.stop()


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_elastic_resize_e2e():
    """Submit 2 elastic workers, grow to 3 mid-run: job must SUCCEED, the
    new epoch must see TASK_NUM=3, progress must resume (not restart), and
    the history must record SESSION_RESIZED."""
    with MiniTonyCluster() as c:
        conf = script_conf(c, os.path.join(SCRIPTS, "elastic_worker.py"),
                           {"worker": 2})
        conf.set("tony.elastic.grace-ms", 5000)
        conf.set("tony.application.shell-env", f"TONY_REPO_ROOT={REPO}")
        hist = str(conf.get("tony.history.location"))
        client = c.make_client(conf)

        def resize_soon():
            for _ in range(200):
                if client.rpc is not None:
                    try:
                        infos = client.rpc.call("get_task_infos")
                        if infos and all(i["status"] in ("RUNNING", "READY")
                                         for i in infos):
                            ok = client.rpc.call("resize_role", role="worker",
                                                 instances=3)
                            print("resize ->", ok)
                            return
                    except Exception:
                        pass
                time.sleep(0.1)

        t = threading.Thread(target=resize_soon, daemon=True)
        t.start()
        ok = client.run()
        assert ok, client.final_status
        job_dir = client.job_dir

        # every worker of the final gang saw TASK_NUM=3 in epoch 1
        sizes = {}
        for path in glob.glob(os.path.join(job_dir, "sizes-worker-*.txt")):
            idx = path.rsplit("-", 1)[1].split(".")[0]
            with open(path) as f:
                sizes[idx] = f.read().strip().splitlines()
        assert "2" in sizes, sizes  # the grown worker existed
        assert any(line == "1:3" for line in sizes["2"]), sizes
        # worker 0 lived in both epochs: 0:2 then 1:3
        assert sizes["0"][0] == "0:2" and "1:3" in sizes["0"], sizes

        # progress resumed: worker-0's file shows a resume line in its log
        log0 = os.path.join(job_dir, "logs", "worker-0-user.log")
        with open(log0) as f:
            content = f.read()
        assert "resumed at step" in content, content

        # history has the resize event
        events = []
        for path in glob.glob(os.path.join(hist, "**", "*.jhist.jsonl"),
                              recursive=True):
            with open(path) as f:
                events += [json.loads(line) for line in f if line.strip()]
        assert any(e["type"] == "SESSION_RESIZED" for e in events), \
            [e["type"] for e in events]


def test_double_resize_last_wins_and_merges_roles():
    """Two queued resizes before the monitor drains them: same-role
    requests coalesce to the newest; distinct roles merge into one
    atomic resize batch."""
    import tempfile

    from tony_tpu.config import TonyConf
    from tony_tpu.coordinator.coordinator import Coordinator

    conf = TonyConf()
    conf.set("tony.worker.instances", 2)
    conf.set("tony.ps.instances", 1)
    conf.set("tony.application.security.enabled", False)
    with tempfile.TemporaryDirectory() as tmp:
        conf.set("tony.staging-dir", tmp)
        conf.set("tony.history.location", os.path.join(tmp, "hist"))
        coord = Coordinator(conf, "application_rsz2", os.path.join(tmp, "job"))
        try:
            assert coord.request_resize("worker", 4)
            assert coord.request_resize("worker", 6)  # supersedes 4
            assert coord.request_resize("ps", 2)
            assert coord._take_pending_resize() == {"worker": 6, "ps": 2}
            # queue drained atomically: a second take sees nothing
            assert coord._take_pending_resize() == {}
            # a resize queued AFTER a drain survives for the next cycle
            # (e.g. requested while a retry epoch is being rebuilt)
            assert coord.request_resize("worker", 3)
            assert coord._take_pending_resize() == {"worker": 3}
        finally:
            coord.rpc.stop()
            coord.metrics_rpc.stop()


def _request_resize_when_running(client, role, n):
    """Poll the client's coordinator RPC until the gang is up, then queue
    a resize; returns the thread."""
    def run():
        for _ in range(300):
            if client.rpc is not None:
                try:
                    infos = client.rpc.call("get_task_infos")
                    if infos and all(i["status"] in ("RUNNING", "READY")
                                     for i in infos):
                        client.rpc.call("resize_role", role=role,
                                        instances=n)
                        return
                except Exception:
                    pass
            time.sleep(0.1)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_elastic_shrink_e2e():
    """Shrink 3 -> 1: the new epoch runs a single worker, the removed
    indices never reappear, progress resumes (ref semantics:
    ApplicationMaster.java:612-628 session reset at new sizes)."""
    with MiniTonyCluster() as c:
        conf = script_conf(c, os.path.join(SCRIPTS, "elastic_worker.py"),
                           {"worker": 3})
        conf.set("tony.elastic.grace-ms", 5000)
        conf.set("tony.application.shell-env", f"TONY_REPO_ROOT={REPO}")
        client = c.make_client(conf)
        _request_resize_when_running(client, "worker", 1)
        ok = client.run()
        assert ok, client.final_status
        job_dir = client.job_dir

        sizes = {}
        for path in glob.glob(os.path.join(job_dir, "sizes-worker-*.txt")):
            idx = path.rsplit("-", 1)[1].split(".")[0]
            with open(path) as f:
                sizes[idx] = f.read().strip().splitlines()
        # worker 0 lived in both epochs: 3-wide then 1-wide, with resume
        assert sizes["0"][0] == "0:3", sizes
        assert "1:1" in sizes["0"], sizes
        # removed indices never joined epoch 1
        for idx in ("1", "2"):
            assert all(line.startswith("0:") for line in sizes.get(idx, [])), \
                sizes
        log0 = os.path.join(job_dir, "logs", "worker-0-user.log")
        assert "resumed at step" in open(log0).read()


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_resize_while_task_failing_with_retry_e2e():
    """Resize racing a task failure (+ the resulting retry epoch): in
    every interleaving the job must converge — the pending resize
    survives a session reset, the resized gang passes, and no epoch
    hangs. Payload: worker:1 exits 1 only in session epoch 0."""
    with MiniTonyCluster() as c:
        conf = c.base_conf()
        conf.set("tony.worker.instances", 2)
        conf.set(
            "tony.worker.command",
            "python -c \"import os,sys,time; time.sleep(0.5); "
            "sys.exit(1 if os.environ['TONY_SESSION_ID']=='0' and "
            "os.environ['TONY_TASK_INDEX']=='1' else 0)\"")
        conf.set("tony.coordinator.retry-count", 2)
        conf.set("tony.elastic.grace-ms", 3000)
        hist = str(conf.get("tony.history.location"))
        client = c.make_client(conf)
        _request_resize_when_running(client, "worker", 3)
        ok = client.run()
        assert ok, client.final_status
        # the job ended in a later session epoch (resize and/or retry
        # both bump it; the resize must not have been lost)
        assert client.final_status["session_id"] >= 1, client.final_status
        # the resize itself happened in SOME epoch — a pending resize
        # must survive a session reset, not vanish with the failed epoch
        events = []
        for path in glob.glob(os.path.join(hist, "**", "*.jhist.jsonl"),
                              recursive=True):
            with open(path) as f:
                events += [json.loads(line) for line in f if line.strip()]
        assert any(e["type"] == "SESSION_RESIZED" for e in events), \
            sorted({e["type"] for e in events})


def _mini_coord(tmp, **conf_kv):
    from tony_tpu.config import TonyConf
    from tony_tpu.coordinator.coordinator import Coordinator

    conf = TonyConf()
    conf.set("tony.worker.instances", 2)
    conf.set("tony.application.security.enabled", False)
    for k, v in conf_kv.items():
        conf.set(k, v)
    conf.set("tony.staging-dir", tmp)
    conf.set("tony.history.location", os.path.join(tmp, "hist"))
    return Coordinator(conf, "application_eu", os.path.join(tmp, "job"))


def test_exit_resize_inside_window_is_clean_outside_is_policy(tmp_path):
    """EXIT_RESIZE (75) during the resize grace window is a cooperative
    clean exit; the same code OUTSIDE the window goes through the normal
    exit-status policy (here: fail-on-worker-failure)."""
    from tony_tpu.elastic import EXIT_RESIZE
    from tony_tpu.session import SessionStatus

    coord = _mini_coord(
        str(tmp_path), **{"tony.application.fail-on-worker-failure-enabled":
                          True})
    try:
        for i in (0, 1):
            coord.session.init_task("worker", i)
        coord._resizing = True
        coord._complete_task("worker:0", EXIT_RESIZE)
        assert coord.session.get_task_by_id("worker:0").exit_code == 0
        assert coord.session.status == SessionStatus.RUNNING

        coord._resizing = False
        coord._complete_task("worker:1", EXIT_RESIZE)
        assert coord.session.status == SessionStatus.FAILED
    finally:
        coord.rpc.stop()
        coord.metrics_rpc.stop()


def test_pending_resize_survives_session_reset(tmp_path):
    """The property the resize-vs-failure race rests on: a queued resize
    outlives _reset_session (the retry epoch performs it), while stale
    pending COMMANDS do not leak across epochs."""
    coord = _mini_coord(str(tmp_path))
    try:
        coord.session.init_task("worker", 0)
        assert coord.request_resize("worker", 5)
        coord._pending_commands["worker:0"] = [{"type": "save_and_exit"}]
        coord._reset_session()
        assert coord.session.session_id == 1
        assert coord._pending_commands == {}
        assert coord._take_pending_resize() == {"worker": 5}
    finally:
        coord.rpc.stop()
        coord.metrics_rpc.stop()
