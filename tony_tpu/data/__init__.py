from tony_tpu.data.loader import DataLoader, device_prefetch
from tony_tpu.data.sources import (
    ArraySource,
    JsonlSource,
    PackedTokenSource,
    SyntheticImageSource,
    SyntheticTokenSource,
)

__all__ = [
    "ArraySource",
    "DataLoader",
    "device_prefetch",
    "JsonlSource",
    "PackedTokenSource",
    "SyntheticImageSource",
    "SyntheticTokenSource",
]
