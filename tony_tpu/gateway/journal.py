"""Durable ticket journal: the gateway's write-ahead log (ISSUE-20).

TonY's control plane survives a resource-manager restart because the
job-history record outlives the ApplicationMaster process — a restarted
coordinator replays it and re-adopts its running containers instead of
killing the job. This module is that record for the gateway: one
NDJSON file under the history job_dir, appended on the same paths that
already build ``requests.jsonl`` rows, recording per request id

  {"ev": "admit", "rid", "t", "prompt": [ids], "max_new_tokens",
   "temperature", "top_k", "seed", "stream"}     admission accepted
  {"ev": "route", "rid", "replica": i, "host": "h:p"|null}
                                                 placed on a replica
                                                 (null host = local,
                                                 in-process engine)
  {"ev": "emit", "rid", "off": N}                N tokens delivered to
                                                 the client so far
                                                 (absolute offset)
  {"ev": "done", "rid"} / {"ev": "shed", "rid", "status": 503}
                                                 terminal

On boot with ``--recover`` the gateway replays the newest journal it
can find and learns exactly which requests were in flight, where they
were running, and how many tokens each client already received — the
three facts restart recovery needs (gateway/core.py adopts the parked
remote sessions and re-runs the local ones from the prompt; the
journaled offset seeds the absolute-offset emit dedup so resumed
client streams carry exactly the missing suffix).

Durability knob (``--journal-fsync``): "always" fsyncs every append
(each admitted request survives a power cut, at a syscall per token
batch), "batch" (default) fsyncs terminals and admits but lets emit
offsets ride the OS page cache (a crash forgets at most the last few
offsets — recovery then re-emits a suffix the client's own resume
offset dedups), "off" never fsyncs (throughput benches).

The journal COMPACTS on clean drain: every request that reached a
terminal is dropped and the file is rewritten atomically (tmp +
rename), so a cleanly-drained gateway leaves an empty journal and
``--recover`` on the next boot finds nothing to do. A torn final line
(the append a crash cut mid-write) is tolerated on replay: NDJSON's
framing makes every complete line independently decodable, and the
torn tail by construction holds the least information in the file.
"""

from __future__ import annotations

import json
import logging
import os
import threading

log = logging.getLogger(__name__)

FSYNC_POLICIES = ("always", "batch", "off")


class JournalEntry:
    """One request's replayed state: everything recovery needs."""

    __slots__ = ("rid", "request", "replica", "host", "offset",
                 "terminal", "t_admit")

    def __init__(self, rid):
        self.rid = rid
        self.request: dict | None = None   # the admit row's params
        self.replica: int | None = None    # replica index at crash
        self.host: str | None = None       # "h:p" for remote, None local
        self.offset = 0                    # tokens the client received
        self.terminal: str | None = None   # "done" / "shed" / None=live
        self.t_admit = 0.0

    @property
    def live(self) -> bool:
        return self.terminal is None and self.request is not None


class TicketJournal:
    """Append-side of the WAL. Thread-safe: admits land from handler
    threads, emit offsets from replica loops, terminals from both."""

    def __init__(self, path: str, fsync: str = "batch"):
        if fsync not in FSYNC_POLICIES:
            raise ValueError(
                f"fsync policy {fsync!r} not in {FSYNC_POLICIES}")
        self.path = path
        self.fsync = fsync
        self._lock = threading.Lock()
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # append mode: a recovered gateway keeps journaling into the
        # journal it replayed — the live entries it re-admitted get
        # fresh route/emit rows under their original rids
        self._f = open(path, "a", encoding="utf-8")
        self._closed = False

    # ------------------------------------------------------- appends

    def _append(self, doc: dict, *, sync: bool) -> None:
        line = json.dumps(doc, separators=(",", ":")) + "\n"
        with self._lock:
            if self._closed:
                return
            self._f.write(line)
            self._f.flush()
            if self.fsync == "always" or (sync and self.fsync == "batch"):
                os.fsync(self._f.fileno())

    def admit(self, rid, request_doc: dict, t_wall: float) -> None:
        """The moment admission accepted the request — before any
        token exists. ``request_doc`` must carry enough to re-run from
        the prompt (prompt/max_new_tokens/temperature/top_k/seed)."""
        self._append({"ev": "admit", "rid": rid, "t": t_wall,
                      **request_doc}, sync=True)

    def route(self, rid, replica: int, host: str | None) -> None:
        self._append({"ev": "route", "rid": rid, "replica": replica,
                      "host": host}, sync=False)

    def emit(self, rid, offset: int) -> None:
        """Absolute client-delivered offset — the high-rate row; under
        the "batch" policy it rides the page cache (see module doc)."""
        self._append({"ev": "emit", "rid": rid, "off": int(offset)},
                     sync=False)

    def done(self, rid) -> None:
        self._append({"ev": "done", "rid": rid}, sync=True)

    def shed(self, rid, status: int) -> None:
        self._append({"ev": "shed", "rid": rid, "status": int(status)},
                     sync=True)

    # ---------------------------------------------------- compaction

    def compact(self) -> int:
        """Drop every terminated request; atomic rewrite. Returns the
        number of LIVE entries kept (0 after a clean drain)."""
        with self._lock:
            if not self._closed:
                self._f.flush()
            entries = _replay_lines(self.path)
            live = [e for e in entries.values() if e.live]
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                for e in live:
                    f.write(json.dumps(
                        {"ev": "admit", "rid": e.rid, "t": e.t_admit,
                         **(e.request or {})},
                        separators=(",", ":")) + "\n")
                    if e.replica is not None:
                        f.write(json.dumps(
                            {"ev": "route", "rid": e.rid,
                             "replica": e.replica, "host": e.host},
                            separators=(",", ":")) + "\n")
                    if e.offset:
                        f.write(json.dumps(
                            {"ev": "emit", "rid": e.rid,
                             "off": e.offset},
                            separators=(",", ":")) + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.path)
            if not self._closed:
                self._f.close()
                self._f = open(self.path, "a", encoding="utf-8")
            return len(live)

    def close(self, *, compact: bool = False) -> None:
        if compact:
            self.compact()
        with self._lock:
            if not self._closed:
                self._closed = True
                self._f.flush()
                if self.fsync != "off":
                    os.fsync(self._f.fileno())
                self._f.close()

    # -------------------------------------------------------- replay


def _replay_lines(path: str) -> dict:
    entries: dict = {}
    try:
        f = open(path, "r", encoding="utf-8")
    except FileNotFoundError:
        return entries
    with f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                doc = json.loads(line)
            except ValueError:
                # the torn tail a crash cut mid-append — every complete
                # line before it already decoded, and a torn line can
                # only be the file's LAST append, so skipping it loses
                # at most one emit offset (recovery over-resends a
                # suffix the client-side offset dedup drops)
                log.warning("journal %s: skipping torn line %d",
                            path, i + 1)
                continue
            rid = doc.get("rid")
            if rid is None:
                continue
            e = entries.get(rid)
            if e is None:
                e = entries[rid] = JournalEntry(rid)
            ev = doc.get("ev")
            if ev == "admit":
                e.t_admit = float(doc.get("t", 0.0))
                e.request = {k: v for k, v in doc.items()
                             if k not in ("ev", "rid", "t")}
            elif ev == "route":
                e.replica = doc.get("replica")
                e.host = doc.get("host")
            elif ev == "emit":
                e.offset = max(e.offset, int(doc.get("off", 0)))
            elif ev in ("done", "shed"):
                e.terminal = ev
    return entries


def replay(path: str) -> dict:
    """Replay a journal into ``{rid: JournalEntry}``. Idempotent (a
    second replay of the same file returns the same map) and tolerant
    of a torn final line. Missing file -> empty map: ``--recover`` on
    a fresh deployment is a no-op, not an error."""
    return _replay_lines(path)


def find_latest(history_root: str) -> str | None:
    """The newest ``journal.ndjson`` under ``<root>/intermediate/*/``
    — a restarted gateway gets a NEW timestamped job_dir, so recovery
    must look at the previous boots' dirs, not its own."""
    inter = os.path.join(history_root, "intermediate")
    best: tuple[float, str] | None = None
    try:
        apps = os.listdir(inter)
    except OSError:
        return None
    for app in apps:
        p = os.path.join(inter, app, "journal.ndjson")
        try:
            mt = os.path.getmtime(p)
        except OSError:
            continue
        if best is None or mt > best[0]:
            best = (mt, p)
    return best[1] if best else None
