"""Ulysses-style sequence parallelism: all-to-all head redistribution.

Absent from the reference (SURVEY.md section 5.7). Complements ring
attention as the second SP backend: instead of rotating K/V blocks around
a ring, one ``all_to_all`` swaps the sequence sharding for a head sharding
— each device then holds the FULL sequence for H/n heads and runs plain
(blockwise) attention locally, followed by the inverse all_to_all.

Trade-offs vs ring (public DeepSpeed-Ulysses pattern, re-implemented for
shard_map/TPU):
- comm volume: 2 all-to-alls over activations, independent of #steps —
  cheaper than a ring when heads >= devices and ICI all-to-all is fast;
- constraint: n_heads must be divisible by the seq-axis size (ring has no
  such constraint);
- memory: holds L (full) x H/n activations per device vs ring's L/n x H.
"""

from __future__ import annotations

import functools

from jax import lax
from tony_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from tony_tpu.parallel.mesh import SEQ
from tony_tpu.parallel.ring_attention import blockwise_attention


def _ulysses_local(q, k, v, segments, *, axis_name: str, causal: bool,
                   block_size: int, window: int):
    """Per-shard body. Local shapes in: [B, L/n, H, D]; segments
    [B, L/n] int or None (packed-document ids, all-gathered to the full
    sequence so the local full-seq attention can mask exactly)."""
    # seq-shard -> head-shard: split heads (axis 2) n ways, gather seq (1)
    q = lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1, tiled=True)
    k = lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1, tiled=True)
    v = lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1, tiled=True)
    if segments is not None:
        segments = lax.all_gather(segments, axis_name, axis=1, tiled=True)
    # full-sequence attention over this device's head group
    out = blockwise_attention(q, k, v, block_size=block_size,
                              causal=causal, window=window,
                              segment_ids=segments)
    # head-shard -> seq-shard
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)


def ulysses_attention(q, k, v, mesh: Mesh, *, axis_name: str = SEQ,
                      causal: bool = True, block_size: int = 512,
                      batch_spec: P | None = None, window: int = 0,
                      segment_ids=None):
    """Sequence-parallel attention via all-to-all head redistribution.

    q/k/v: [B, L, H, D] globally, sharded along L over ``axis_name``.
    Requires H % mesh.shape[axis_name] == 0. Returns the same sharding.
    ``window`` adds sliding-window masking and ``segment_ids`` [B, L]
    packed-document masking (each device holds the full sequence
    post-all-to-all, so both cuts are local; segment ids need one cheap
    int all-gather along the seq axis).
    """
    import jax.numpy as jnp

    n = mesh.shape.get(axis_name, 1)
    heads = q.shape[2]
    if heads % n != 0:
        raise ValueError(
            f"ulysses needs n_heads ({heads}) divisible by the {axis_name!r} "
            f"axis size ({n}); use ring attention otherwise")
    qspec = P(batch_spec, axis_name, None, None) if batch_spec else \
        P(None, axis_name, None, None)
    sspec = P(batch_spec, axis_name) if batch_spec else P(None, axis_name)
    local = functools.partial(_ulysses_local, axis_name=axis_name,
                              causal=causal, block_size=block_size,
                              window=window)
    if segment_ids is None:
        fn = shard_map(lambda q, k, v: local(q, k, v, None), mesh=mesh,
                       in_specs=(qspec, qspec, qspec), out_specs=qspec,
                       check_vma=False)
        return fn(q, k, v)
    fn = shard_map(local, mesh=mesh, in_specs=(qspec, qspec, qspec, sspec),
                   out_specs=qspec, check_vma=False)
    return fn(q, k, v, segment_ids.astype(jnp.int32))
