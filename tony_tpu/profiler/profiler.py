"""Tracing/profiling subsystem.

The reference has none (SURVEY.md §5.1: "Rebuild note: TPU equivalent
should add jax.profiler/xplane trace capture — greenfield"). Design:

- Every task can host a ``jax.profiler`` server (``TONY_PROFILER_PORT``
  env, set from ``tony.task.profiler-port``) so TensorBoard's profile
  plugin can capture remotely.
- On-demand capture without TensorBoard: the coordinator queues a
  ``profile`` command for a task (RPC verb ``request_profile``), the
  agent's heartbeat response delivers it, and the agent drops a trigger
  file in the task workdir. The user process — any loop that calls
  ``StepProfiler.poll()`` once per step, which ``tony_tpu.train.Trainer``
  users get for free — picks the trigger up and writes an xplane trace
  for the next N steps into the job dir, where the portal/logs page can
  link it.

Both paths degrade to no-ops off-TPU or when jax is absent; the trigger
file protocol is plain JSON so non-JAX runtimes can honor it too.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os

from tony_tpu.utils.controlfile import (
    control_file_path,
    current_task_id,
    write_control_file,
)

log = logging.getLogger(__name__)

TRIGGER_FILENAME = ".tony_profile_request"
PROFILER_PORT_ENV = "TONY_PROFILER_PORT"
PROFILE_DIR_ENV = "TONY_PROFILE_DIR"


def trigger_path(workdir: str, task_id: str = "") -> str:
    """Per-task trigger file (tasks can share a job dir on one host)."""
    return control_file_path(workdir, TRIGGER_FILENAME, task_id)


def write_trigger(workdir: str, num_steps: int = 5,
                  logdir: str | None = None, task_id: str = "") -> str:
    """Agent side: request a trace from the user process in ``workdir``."""
    return write_control_file(
        trigger_path(workdir, task_id),
        {"num_steps": int(num_steps), "logdir": logdir})


def maybe_start_server() -> int:
    """Start jax's profiler server when TONY_PROFILER_PORT is set (called
    from tony_tpu.distributed.initialize). Returns the port or 0."""
    port = int(os.environ.get(PROFILER_PORT_ENV, "0") or "0")
    if port <= 0:
        return 0
    try:
        import jax

        jax.profiler.start_server(port)
        log.info("jax profiler server on :%d", port)
        return port
    except Exception:
        log.exception("could not start jax profiler server")
        return 0


@contextlib.contextmanager
def trace(logdir: str):
    """Programmatic xplane trace of a code region."""
    import jax

    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


class StepProfiler:
    """Poll-per-step on-demand tracing for training loops.

    ``poll()`` is one ``os.path.exists`` when idle — cheap enough to call
    every step. When a trigger file appears, the next ``num_steps`` steps
    are traced to the trigger's logdir (default: ``$TONY_PROFILE_DIR`` or
    ``<workdir>/profiles``).
    """

    def __init__(self, workdir: str | None = None,
                 default_logdir: str | None = None,
                 task_id: str | None = None):
        self.workdir = workdir or os.getcwd()
        self.task_id = current_task_id() if task_id is None else task_id
        self.default_logdir = (default_logdir
                               or os.environ.get(PROFILE_DIR_ENV)
                               or os.path.join(self.workdir, "profiles"))
        self.active_steps_left = 0
        self.captures = 0
        self._logdir = ""

    def poll(self) -> bool:
        """Call once per training step. Returns True while tracing."""
        if self.active_steps_left > 0:
            self.active_steps_left -= 1
            if self.active_steps_left == 0:
                self._stop()
            return self.active_steps_left > 0
        path = trigger_path(self.workdir, self.task_id)
        if not os.path.exists(path):
            return False
        try:
            with open(path) as f:
                req = json.load(f)
        except (OSError, json.JSONDecodeError):
            req = {}
        finally:
            with contextlib.suppress(OSError):
                os.remove(path)  # consume: one trigger, one capture
        self._start(req.get("logdir") or self.default_logdir,
                    int(req.get("num_steps", 5)))
        return True

    def _start(self, logdir: str, num_steps: int) -> None:
        try:
            import jax

            os.makedirs(logdir, exist_ok=True)
            jax.profiler.start_trace(logdir)
        except Exception:
            log.exception("profile trigger ignored: start_trace failed")
            return
        self._logdir = logdir
        self.active_steps_left = max(num_steps, 1)
        log.info("profiling next %d steps -> %s", self.active_steps_left, logdir)

    def _stop(self) -> None:
        try:
            import jax

            jax.profiler.stop_trace()
            self.captures += 1
            log.info("profile capture #%d written to %s", self.captures,
                     self._logdir)
        except Exception:
            log.exception("stop_trace failed")

    def close(self) -> None:
        if self.active_steps_left > 0:
            self.active_steps_left = 0
            self._stop()
