"""Goodput attribution: an analytic roofline ledger for the serving path.

ROADMAP item 4 says serving-scale decode sits at ~33% of HBM bandwidth
and that "the gap is dispatch overhead and fixed-shape slot waste" —
but until now nothing in the system could say where the other 67%
GOES: the dispatch timeline records wall-ms and tokens, counters like
``wasted_steps`` and ``compile_ms`` exist, and an operator had to
correlate them by hand. This module closes that loop with two pieces:

- ``CostModel``: bytes-moved and FLOPs estimates for every dispatch
  kind (``prefill`` / ``hit_admit`` / ``cow_admit`` / ``decode`` /
  ``verify``) computed from the model's dimensions, the measured
  KV-cache byte layout, and the LIVE shape knobs each dispatch ran
  with (chunk depth, occupancy, paged view extent). Stamped onto each
  ``DispatchRecord`` as ``est_bytes`` / ``est_flops``; with a
  peak-HBM-GB/s reference available (chip table or ``--hbm-gbps``)
  each record also gets a per-dispatch HBM-BW% and MFU estimate. CPU
  runs report bytes with ``utilization: null`` — an estimate against
  an unknown roofline would be a lie.
- the goodput LEDGER (``ledger()``): decompose a replica's wall clock
  into named buckets that sum to <= 1.0 — steady useful work per
  dispatch kind, compile time, bucket/view padding waste (the pow2
  program shape minus what was actually fed), ``wasted_steps``
  overshoot past a finish, rejected speculative-draft positions, and
  the idle/queue gap that is everything the engine never dispatched.
  The decomposition is EXACT against the timeline by construction:
  every steady record's duration is split by its own
  ``tokens``/``fed``/``work`` position counts (useful + padding +
  overshoot + rejected == steady ms per kind), and
  ``sum(fed - tokens)`` over decode+verify reproduces the engine's
  ``wasted_steps`` counter — the reconciliation tests pin both.

Estimates are deliberately simple upper-bound program models (the
compiled program's static read/write set, causal attention averaged),
documented per method — good enough to rank waste buckets and track a
regression, not a substitute for an xplane capture. Everything here is
numpy/stdlib only; jax is touched only inside ``detect_*`` (guarded)
so the module stays importable anywhere.
"""

from __future__ import annotations

import math
import os


def _floor6(v: float) -> float:
    """Fraction rounding that PRESERVES the sums-to-<=1 invariant:
    floor at 1e-6 — round-half-up could push a bucket sum a few 1e-7
    past 1.0 and turn the ledger's structural guarantee into a flake."""
    return math.floor(max(0.0, v) * 1e6) / 1e6


# chip tables shared with bench.py (single source): peak bf16 FLOP/s
# and HBM bandwidth per chip, keyed by substring of the accelerator
# name (TPU_ACCELERATOR_TYPE or jax device_kind, lowercased)
PEAK_BF16_TABLE = (
    ("v6e", 918e12), ("trillium", 918e12), ("v5p", 459e12),
    ("v5litepod", 197e12), ("v5 lite", 197e12), ("v5e", 197e12),
    ("v4", 275e12), ("v3", 123e12), ("v2", 45e12),
)

HBM_BW_TABLE = (
    ("v6e", 1638e9), ("trillium", 1638e9), ("v5p", 2765e9),
    ("v5litepod", 819e9), ("v5 lite", 819e9), ("v5e", 819e9),
    ("v4", 1228e9), ("v3", 900e9), ("v2", 700e9),
)

# ledger bucket names; "useful.<kind>" buckets ride alongside these.
# host_tier is the ISSUE-12 migration bucket: device<->host page moves
# (spills, page-ins) and handoff scatters — real work, but not token
# work, so it must neither inflate useful_fraction nor hide in idle
WASTE_BUCKETS = ("compile", "padding", "overshoot", "spec_rejected",
                 "host_tier", "idle")

# dispatch kinds whose steady time lands in the host_tier bucket:
# tier spills/restores AND the role-split handoff's gather/scatter —
# all pure page migration; none of them land tokens a request keeps
_HOST_TIER_KINDS = ("host_spill", "host_page_in", "handoff_out",
                    "handoff_admit")


_DISCOVERED_NAMES: list | None = None


def _discovered_chip_names() -> list:
    """The EXPENSIVE half of chip resolution (``TpuDiscoverer``'s
    info-command subprocess, ``jax.devices()``), memoized per process:
    the chip does not change under a running process, and every
    ``Server`` construction — including the autoscaler's scale-up
    path — resolves the roofline reference twice."""
    global _DISCOVERED_NAMES
    if _DISCOVERED_NAMES is None:
        names = []
        try:
            from tony_tpu.utils.tpu_info import TpuDiscoverer

            names.append(TpuDiscoverer().get_device_information()
                         .accelerator_type)
        except Exception:  # noqa: BLE001 — discovery trouble: miss
            pass
        try:
            import jax

            names.append(jax.devices()[0].device_kind)
        except Exception:  # noqa: BLE001 — no jax / devices: miss
            pass
        _DISCOVERED_NAMES = names
    return _DISCOVERED_NAMES


def chip_lookup(table) -> float:
    """Resolve a per-chip constant from the accelerator name
    (``TPU_ACCELERATOR_TYPE`` env — read fresh, it is the cheap
    override — then ``TpuDiscoverer``'s accelerator type and the jax
    device kind, both memoized per process). 0.0 when unknown — CPU
    boxes and exotic chips must degrade to "no utilization estimate",
    never to a wrong one."""
    names = [os.environ.get("TPU_ACCELERATOR_TYPE", "")]
    names.extend(_discovered_chip_names())
    for name in names:
        low = str(name).lower()
        for key, val in table:
            if key in low:
                return val
    return 0.0


def detect_hbm_gbps() -> float:
    """Peak HBM bandwidth reference in GB/s (0.0 = unknown).
    ``TONY_HBM_GBPS`` overrides the chip table — the hook for hardware
    the table does not know."""
    env = os.environ.get("TONY_HBM_GBPS", "")
    if env:
        try:
            return max(0.0, float(env))
        except ValueError:
            pass
    return chip_lookup(HBM_BW_TABLE) / 1e9


def detect_peak_flops() -> float:
    """Peak bf16 FLOP/s reference (0.0 = unknown)."""
    return chip_lookup(PEAK_BF16_TABLE)


class CostModel:
    """Analytic bytes/FLOPs per dispatch, from numbers the engine
    already has: real parameter bytes/count from the param tree, the
    MEASURED per-token KV byte cost (cache row bytes / max_seq_len, or
    page bytes / page size — so int8-KV, GQA, and scan_layers layouts
    are priced from truth, not re-derived), and the attention
    dimensions from the config. All estimates model the COMPILED
    program's static read/write set: a fixed-shape decode step reads
    the whole ``[batch, view]`` cache buffer whether slots are live or
    not — which is exactly why the ledger's padding bucket exists.

    PER-CHIP contract (ISSUE-14): on a sharded replica the caller
    constructs this model with PER-CHIP quantities — ``param_bytes``/
    ``param_count`` summed from the actual shardings (replicated
    leaves whole), ``kv_token_bytes`` divided by the pool's kv-head
    shard count, ``n_heads`` the per-chip head count — while
    ``hbm_gbps``/``peak_flops`` stay the SINGLE-chip roofline. Pricing
    total mesh bytes against one chip's roofline would push HBM-BW%
    past 100% and permanently mask a goodput collapse;
    ``serve.Server.__init__`` owns the division (it has the
    shardings), this class stays pure arithmetic."""

    def __init__(self, *, param_bytes: int, param_count: int,
                 kv_token_bytes: float, n_heads: int, head_dim: int,
                 vocab_size: int, hbm_gbps: float = 0.0,
                 peak_flops: float = 0.0):
        self.param_bytes = int(param_bytes)
        self.param_count = int(param_count)
        self.kv_token_bytes = float(kv_token_bytes)
        self.n_heads = int(n_heads)
        self.head_dim = int(head_dim)
        self.vocab_size = int(vocab_size)
        self.hbm_gbps = float(hbm_gbps)
        self.peak_flops = float(peak_flops)

    # attention FLOPs for one query position against a ctx-token
    # window: QK^T + PV, 2 FLOPs per MAC each
    def _attn_flops(self, ctx: float) -> float:
        return 4.0 * self.n_heads * self.head_dim * max(0.0, ctx)

    def decode(self, depth: int, batch: int, view_tokens: int) -> tuple:
        """A chunked decode dispatch: ``depth`` micro-steps over the
        resident ``[batch]`` slots, each re-reading every parameter
        byte and the ``[batch, view_tokens]`` cache span (the paged
        view's live extent, or max_seq_len unpaged), writing one K/V
        token per slot per step. Returns ``(bytes, flops)``."""
        kv_read = batch * view_tokens * self.kv_token_bytes
        kv_write = batch * self.kv_token_bytes
        n_bytes = depth * (self.param_bytes + kv_read + kv_write)
        flops = depth * batch * (2.0 * self.param_count
                                 + self._attn_flops(view_tokens))
        return n_bytes, flops

    def verify(self, window: int, batch: int, view_tokens: int) -> tuple:
        """A speculative verify dispatch: ONE multi-token pass scores
        ``window`` positions for every slot — parameters are read once
        (the whole point of verification vs ``window`` micro-steps),
        attention spans the view per position."""
        kv_read = batch * view_tokens * self.kv_token_bytes
        kv_write = batch * window * self.kv_token_bytes
        n_bytes = self.param_bytes + kv_read + kv_write
        flops = batch * window * (2.0 * self.param_count
                                  + self._attn_flops(view_tokens))
        return n_bytes, flops

    def prefill(self, window: int, offset: int = 0,
                view_tokens: int = 0) -> tuple:
        """A (suffix) prefill admit: one batch-1 pass over a
        ``window``-token bucket at position ``offset``, causal
        attention averaged over the window (each position sees
        ``offset + i`` context tokens)."""
        ctx = view_tokens if view_tokens else offset + window
        n_bytes = (self.param_bytes
                   + ctx * self.kv_token_bytes          # row/view read
                   + window * self.kv_token_bytes       # K/V written
                   + 4.0 * self.vocab_size)             # logits out
        flops = window * (2.0 * self.param_count
                          + self._attn_flops(offset + window / 2.0))
        return n_bytes, flops

    def hit_admit(self, row_bytes: int) -> tuple:
        """Unpaged exact-prefix hit: the stored cache row is COPIED
        into the slot, then one ``[1, V]`` sample from stored logits —
        read + write of the row dominates."""
        n_bytes = 2.0 * row_bytes + 4.0 * self.vocab_size
        return n_bytes, 2.0 * self.vocab_size

    def cow_admit(self, fork_bytes: int = 0) -> tuple:
        """Paged exact hit: pages alias host-side; device work is the
        optional boundary-page CoW fork plus the ``[1, V]`` sample —
        the 14.8x-fewer-bytes admission extras.paged measured."""
        n_bytes = 2.0 * fork_bytes + 4.0 * self.vocab_size
        return n_bytes, 2.0 * self.vocab_size

    def host_move(self, n_bytes: float) -> tuple:
        """A page-content migration (host-tier spill/page-in, handoff
        gather/scatter): a pure copy — ``n_bytes`` moved, zero
        FLOPs."""
        return float(n_bytes), 0.0

    def utilization(self, n_bytes: float, flops: float,
                    dur_ms: float) -> tuple:
        """(hbm_bw_pct, mfu_pct) for a dispatch that moved ``n_bytes``
        and computed ``flops`` in ``dur_ms`` — ``None`` where no
        roofline reference is known (CPU runs report bytes with
        utilization null rather than a made-up percentage)."""
        if dur_ms <= 0:
            return None, None
        secs = dur_ms / 1e3
        bw = round(100.0 * n_bytes / (secs * self.hbm_gbps * 1e9), 2) \
            if self.hbm_gbps > 0 else None
        mfu = round(100.0 * flops / (secs * self.peak_flops), 2) \
            if self.peak_flops > 0 else None
        return bw, mfu


def ledger(summary: dict, wall_ms: float, *, hbm_gbps: float = 0.0,
           peak_flops: float = 0.0) -> dict:
    """The goodput ledger: fold an (extended) timeline summary — the
    per-kind aggregates ``DispatchTimeline.summary()`` returns, with
    the ``useful_ms``/``padding_ms``/``overshoot_ms``/``rejected_ms``
    splits — plus the replica's wall clock into named bucket FRACTIONS
    that sum to <= 1.0:

    - ``useful.<kind>`` — steady dispatch time weighted by the
      positions that landed tokens a request kept;
    - ``compile`` — first-call (compile / cache-load) dispatch time;
    - ``padding`` — pow2 bucket/view/batch-shape positions the program
      computed but nobody fed (empty slots, prefill bucket tails,
      verify window padding, and — under in-dispatch EOS — a finished
      slot's FROZEN re-emit positions, which write no KV and feed
      nothing): the fixed-shape-waste bucket;
    - ``overshoot`` — positions fed real work whose output was trimmed
      (chunk overshoot past EOS/budget, verify bonus past a finish):
      the ``wasted_steps`` counter, as time. Structurally 0 with
      in-dispatch EOS on (ISSUE-13) — nonzero overshoot on a frozen
      engine means an accounting bug, which the reconciliation tests
      would catch;
    - ``spec_rejected`` — rejected speculative-draft positions;
    - ``idle`` — wall clock the engine never dispatched in (queue
      gaps, host scheduling, admission lulls).

    The denominator is ``max(wall_ms, total dispatch ms)`` so the sum
    is <= 1.0 STRUCTURALLY even under clock jitter. Per-kind HBM-BW%
    and MFU ride along when a roofline reference is known (None
    otherwise — the CPU contract)."""
    wall_ms = max(0.0, float(wall_ms))
    ms: dict[str, float] = {"compile": 0.0, "padding": 0.0,
                            "overshoot": 0.0, "spec_rejected": 0.0,
                            "host_tier": 0.0}
    kinds: dict[str, dict] = {}
    total_dispatch = 0.0
    for kind, agg in summary.items():
        total_dispatch += agg["ms"]
        if kind in _HOST_TIER_KINDS:
            # migration time is its own bucket: page moves keep the
            # engine busy without landing tokens, and filing them
            # under useful.<kind> would let tier churn masquerade as
            # goodput (compile time still goes to compile)
            ms["host_tier"] += agg["ms"] - agg.get("compile_ms", 0.0)
            ms["compile"] += agg.get("compile_ms", 0.0)
        else:
            ms[f"useful.{kind}"] = agg.get("useful_ms", 0.0)
            ms["compile"] += agg.get("compile_ms", 0.0)
            ms["padding"] += agg.get("padding_ms", 0.0)
            ms["overshoot"] += agg.get("overshoot_ms", 0.0)
            ms["spec_rejected"] += agg.get("rejected_ms", 0.0)
        # utilization pairs STEADY cost with STEADY time: a compile
        # record's bytes over a steady denominator would inflate the
        # estimate (or read past 100% on a short run)
        steady_ms = agg["ms"] - agg.get("compile_ms", 0.0)
        bw = mfu = None
        if steady_ms > 0:
            secs = steady_ms / 1e3
            if hbm_gbps > 0:
                bw = round(100.0 * agg.get("est_bytes_steady", 0.0)
                           / (secs * hbm_gbps * 1e9), 2)
            if peak_flops > 0:
                mfu = round(100.0 * agg.get("est_flops_steady", 0.0)
                            / (secs * peak_flops), 2)
        kinds[kind] = {
            "est_bytes": agg.get("est_bytes", 0.0),
            "est_flops": agg.get("est_flops", 0.0),
            "hbm_bw_pct": bw,
            "mfu_pct": mfu,
        }
    ms["idle"] = max(0.0, wall_ms - total_dispatch)
    # the bucket sum itself joins the denominator (mirroring
    # merge_ledgers): the summary's per-kind splits arrive ROUNDED to
    # 3 decimals, and their rounding excess — up to ~0.5 us per split
    # key — can push sum(ms) a hair past the wall clock on a short,
    # warm-cache run; the sums-<=1 invariant must hold structurally,
    # not up to rounding luck
    denom = max(wall_ms, total_dispatch, sum(ms.values()), 1e-9)
    buckets = {k: _floor6(v / denom) for k, v in ms.items()}
    waste = {k: buckets.get(k, 0.0) for k in WASTE_BUCKETS}
    largest = max(waste, key=waste.get) if waste else None
    return {
        "wall_ms": round(wall_ms, 3),
        "dispatch_ms": round(total_dispatch, 3),
        "buckets": buckets,
        "ms": {k: round(v, 3) for k, v in ms.items()},
        "largest_waste": largest,
        "useful_fraction": round(sum(
            v for k, v in buckets.items()
            if k.startswith("useful.")), 6),
        "utilization": kinds,
        "hbm_gbps": hbm_gbps if hbm_gbps > 0 else None,
    }


def merge_ledgers(ledgers: list[dict]) -> dict:
    """Fleet rollup: sum bucket milliseconds and wall clocks across
    replicas, recompute fractions — a replica that has been up twice
    as long weighs twice as much, which is what a fleet-level "where
    does the time go" means. Utilization blocks are dropped (they are
    per-replica rates; the fleet /debug/goodput report carries each
    replica's own)."""
    ledgers = [g for g in ledgers if g]
    if not ledgers:
        return {}
    wall = sum(g["wall_ms"] for g in ledgers)
    dispatch = sum(g["dispatch_ms"] for g in ledgers)
    ms: dict[str, float] = {}
    for g in ledgers:
        for k, v in g["ms"].items():
            ms[k] = ms.get(k, 0.0) + v
    # the bucket sum itself joins the denominator: per-replica ledgers
    # export ms ROUNDED to 3 decimals, and summed rounding drift can
    # push sum(ms) a few 1e-6 past max(wall, dispatch) — the sums-<=1
    # invariant must hold structurally, not up to rounding luck
    denom = max(wall, dispatch, sum(ms.values()), 1e-9)
    buckets = {k: _floor6(v / denom) for k, v in ms.items()}
    waste = {k: buckets.get(k, 0.0) for k in WASTE_BUCKETS}
    return {
        "wall_ms": round(wall, 3),
        "dispatch_ms": round(dispatch, 3),
        "buckets": buckets,
        "ms": {k: round(v, 3) for k, v in ms.items()},
        "largest_waste": max(waste, key=waste.get) if waste else None,
        "useful_fraction": round(sum(
            v for k, v in buckets.items()
            if k.startswith("useful.")), 6),
    }
