"""Trace-bucket analysis of the flagship train step (tuning aid).

Runs N steps of bench.bench_transformer's exact step under an xplane
trace and prints device-busy time grouped into buckets (dense fusions,
pallas kernels, optimizer-ish fusions, copies, the rest) plus the
top-K individual ops. This is the tool behind docs/PERF.md's
"where the time goes" tables.

Usage (TPU):  python tools/trace_buckets.py [steps]
Honors the TONY_BENCH_LM_* env knobs bench.py uses.
"""

from __future__ import annotations

import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def classify(name: str) -> str:
    from tony_tpu.profiler.xplane import hlo_op_kind

    kind = hlo_op_kind(name).lower()
    if "custom-call" in kind or "custom_call" in kind:
        return "pallas (attention/decode kernels)"
    if kind.startswith(("copy", "bitcast", "transpose", "reshape")):
        return "copies/layout"
    if "dynamic-update-slice" in kind or "dynamic-slice" in kind:
        return "dynamic slices"
    if kind.startswith(("all-reduce", "all-gather", "reduce-scatter",
                        "collective")):
        return "collectives"
    if kind == "fusion":
        return "fusions (dense + elementwise)"
    if kind.startswith(("convolution", "dot")):
        return "bare matmul/conv"
    return f"other ({kind})"


def main() -> None:
    steps = int(sys.argv[1]) if len(sys.argv) > 1 else 3
    import jax
    import jax.numpy as jnp

    import bench
    from tony_tpu.parallel.sharding import batch_sharding
    from tony_tpu.profiler import op_totals_ms
    from tony_tpu.utils import compilecache

    compilecache.enable(os.path.join(bench.REPO_DIR, ".jax_compile_cache"))
    # the EXACT benchmarked step: config/trainer/env knobs live in
    # bench.flagship_lm_setup, shared with bench_transformer
    model, trainer, batch, accum, seq, _ = bench.flagship_lm_setup(True)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0,
                                model.cfg.vocab_size, jnp.int32)
    params = jax.device_get(model.init(jax.random.PRNGKey(0),
                                       jnp.zeros((1, seq), jnp.int32)))
    state = trainer.init_state(params)
    step_fn, placed = trainer.build_step(state)
    train_batch = {"tokens": jax.device_put(
        tokens, batch_sharding(trainer.mesh))}

    def fw(carry):
        new_state, metrics = step_fn(carry, train_batch)
        return new_state, metrics["loss"]

    _, placed = bench.timed_round(fw, placed, 2)  # compile + prime

    import tempfile

    logdir = tempfile.mkdtemp(prefix="tony_buckets_")
    jax.profiler.start_trace(logdir)
    out = None
    for _ in range(steps):
        placed, out = fw(placed)
    float(jnp.asarray(out).reshape(-1)[0])
    jax.profiler.stop_trace()

    totals = op_totals_ms(logdir)
    if not totals:
        print("no device plane in trace (CPU backend?)")
        return
    buckets: dict[str, float] = {}
    for name, ms in totals.items():
        buckets[classify(name)] = buckets.get(classify(name), 0.0) + ms
    total = sum(totals.values())
    print(f"\n== device-busy {total/steps:.1f} ms/step over {steps} steps "
          f"(batch {batch}, accum {accum}) ==")
    for b, ms in sorted(buckets.items(), key=lambda kv: -kv[1]):
        print(f"  {ms/steps:8.2f} ms  {100*ms/total:5.1f}%  {b}")
    print("\n== top 20 ops ==")
    for name, ms in sorted(totals.items(), key=lambda kv: -kv[1])[:20]:
        short = re.sub(r"[%.\d]+$", "", name)[:84]
        print(f"  {ms/steps:8.2f} ms  {short}")


if __name__ == "__main__":
    main()
