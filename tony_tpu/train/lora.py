"""LoRA fine-tuning: low-rank adapters over the flagship models.

No reference analog (TonY has no model stack). TPU-first design: LoRA
is implemented FUNCTIONALLY over the params pytree — no module changes,
no flax surgery. ``lora_init`` builds a small adapter tree mirroring the
targeted kernels; ``merge_lora`` produces ``W + (alpha/r)·A@B`` inside
the jitted step, where XLA fuses the rank-r matmul + add into the
epilogue of the consumer (the adapters are a few MB; the merge costs
``in·out·r`` FLOPs per target — noise next to the forward pass). The
frozen base params enter the jitted step as CLOSURE CONSTANTS, which
keep whatever placement they already have: on a multi-device mesh,
``jax.device_put`` the base tree to its serving shardings (replicated
or fsdp) BEFORE wrapping — jit preserves committed shardings of
constants — and HBM then holds one (sharded) copy of the model plus
optimizer state only for the adapters, the reason LoRA fits where full
fine-tuning does not.

Typical wiring (see tests/test_lora.py)::

    lora = lora_init(jax.random.PRNGKey(0), params, rank=8)
    def apply_fn(lp, batch):                  # TRAINED tree = adapters
        merged = merge_lora(params, lp, alpha=16.0)
        return loss_of(model.apply(merged, batch["tokens"]), batch)
    trainer = Trainer(mesh=mesh, apply_fn=apply_fn, optimizer=optax.adamw(...))
    ...fit(trainer, lora, loader)             # optimizer state is LoRA-sized
    serving = materialize_lora(params, trained_lora, alpha=16.0)  # bake in
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp

# default targets: attention q/v projections — the classic LoRA recipe
DEFAULT_TARGETS = ("q", "v")


def _is_target(path: tuple, targets: Sequence[str]) -> bool:
    """A leaf is adapted when it is a 2-D+ 'kernel' whose parent module
    name matches a target (e.g. .../attn/q/kernel)."""
    names = [getattr(p, "key", None) for p in path]
    return len(names) >= 2 and names[-1] == "kernel" \
        and names[-2] in targets


def _ab_shapes(shape: tuple, rank: int) -> tuple[tuple, tuple]:
    """A: [in, r]; B: [r, *out]. DenseGeneral kernels may have multi-dim
    outputs ([d, heads, dh]) — B carries the full output shape so the
    merge contracts only the rank axis."""
    return (shape[0], rank), (rank,) + tuple(shape[1:])


def lora_init(rng, params: Any, rank: int = 8,
              targets: Sequence[str] = DEFAULT_TARGETS) -> Any:
    """Adapter tree mirroring ``params``: targeted kernels get
    ``{"a": N(0, 1/r) [in, r], "b": zeros [r, *out]}`` (zero-init B makes
    step 0 EXACTLY the base model); everything else is absent. Raises if
    nothing matches — a silent no-op adapter is a footgun."""
    flat = {}
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    keys = jax.random.split(rng, max(len(leaves), 1))
    for key, (path, leaf) in zip(keys, leaves):
        if not _is_target(path, targets) or leaf.ndim < 2:
            continue
        a_shape, b_shape = _ab_shapes(leaf.shape, rank)
        flat[path] = {
            "a": jax.random.normal(key, a_shape, jnp.float32) / rank,
            "b": jnp.zeros(b_shape, jnp.float32),
        }
    if not flat:
        raise ValueError(f"no kernels matched LoRA targets {targets!r}")
    out: dict = {}
    for path, ab in flat.items():
        node = out
        names = [p.key for p in path]
        for name in names[:-1]:
            node = node.setdefault(name, {})
        node[names[-1]] = ab
    return out


def _delta(ab: dict, dtype) -> jnp.ndarray:
    """(A@B) contracted over the rank axis, shaped like the kernel."""
    return jnp.tensordot(ab["a"].astype(dtype), ab["b"].astype(dtype),
                         axes=([1], [0]))


def merge_lora(params: Any, lora: Any, alpha: float = 16.0) -> Any:
    """``W + (alpha/r)·A@B`` for every adapted kernel (r is read off the
    adapter itself); all other leaves pass through untouched. Safe under
    jit (pure pytree math)."""

    def walk(p_node, l_node):
        if isinstance(l_node, dict) and set(l_node) == {"a", "b"} \
                and not isinstance(p_node, dict):
            scale = alpha / l_node["a"].shape[-1]
            return (p_node + scale * _delta(l_node, p_node.dtype)) \
                .astype(p_node.dtype)
        if isinstance(l_node, dict):
            return {k: walk(p_node[k], l_node[k]) if k in l_node else
                    p_node[k] for k in p_node}
        return p_node

    return walk(params, lora)


def materialize_lora(params: Any, lora: Any, alpha: float = 16.0) -> Any:
    """One-time bake for serving: identical math to merge_lora, returned
    as a standalone params tree (feed to generate()/checkpointing)."""
    return merge_lora(params, lora, alpha=alpha)


def lora_param_count(lora: Any) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(lora))


def wrap_apply_fn(base_apply: Callable[[Any, Any], Any], params: Any,
                  alpha: float = 16.0,
                  compute_dtype: Any = None) -> Callable[[Any, Any], Any]:
    """Convenience: lift apply_fn(params, batch) into
    apply_fn(lora, batch) with the base params frozen inside.

    Mixed precision goes HERE, not through ``Trainer.compute_dtype``:
    the trainer's cast covers only the TRAINED tree (the adapters), so
    an fp32 base would promote every downstream op back to fp32.
    ``compute_dtype=jnp.bfloat16`` casts the frozen base's floating
    leaves once, and the merge then runs in that dtype end to end."""
    if compute_dtype is not None:
        params = jax.tree.map(
            lambda x: x.astype(compute_dtype)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, params)

    def apply_fn(lora, batch):
        return base_apply(merge_lora(params, lora, alpha=alpha), batch)

    return apply_fn
