"""Wire format for the control-plane RPC: length-prefixed JSON frames with
per-job HMAC auth.

Reference: Hadoop IPC + protobuf 2.5 service (rpc/ApplicationRpcServer.java,
tensorflow_cluster_service_protos.proto). The rebuild keeps the shape — a
small authenticated request/response service — with a dependency-free codec:
4-byte big-endian length prefix + UTF-8 JSON body. Auth mirrors the
ClientToAM token secret manager (ApplicationMaster.java:484-504): each
request carries an HMAC-SHA256 of its canonical body under the per-job
secret; the server verifies in constant time.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import socket
import struct
from typing import Any

MAX_FRAME = 64 * 1024 * 1024  # sanity cap on a control-plane message
_LEN = struct.Struct(">I")


class WireError(ConnectionError):
    pass


def send_frame(sock: socket.socket, obj: dict) -> None:
    body = json.dumps(obj, separators=(",", ":")).encode()
    if len(body) > MAX_FRAME:
        raise WireError(f"frame too large: {len(body)}")
    sock.sendall(_LEN.pack(len(body)) + body)


def recv_frame(sock: socket.socket) -> dict | None:
    header = _recv_exact(sock, _LEN.size)
    if header is None:
        return None
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise WireError(f"frame too large: {length}")
    body = _recv_exact(sock, length)
    if body is None:
        raise WireError("connection closed mid-frame")
    return json.loads(body)


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None if not buf else None
        buf.extend(chunk)
    return bytes(buf)


def sign(secret: str, method: str, params: dict) -> str:
    msg = json.dumps([method, params], sort_keys=True, separators=(",", ":"))
    return hmac.new(secret.encode(), msg.encode(), hashlib.sha256).hexdigest()


def verify(secret: str, method: str, params: dict, signature: str) -> bool:
    return hmac.compare_digest(sign(secret, method, params), str(signature))


def make_request(req_id: int, method: str, params: dict, secret: str | None) -> dict:
    req: dict[str, Any] = {"id": req_id, "method": method, "params": params}
    if secret:
        req["sig"] = sign(secret, method, params)
    return req


def make_response(req_id: int, result: Any = None, error: str | None = None) -> dict:
    if error is not None:
        return {"id": req_id, "error": error}
    return {"id": req_id, "result": result}
