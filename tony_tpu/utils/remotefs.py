"""Remote-scheme (``gs://``) inputs for confs, resources, and checkpoints.

Reference: TonyClient accepts remote-scheme ``--conf_file`` and resource
paths and round-trips them through HDFS (TonyClient.java:657-691;
LocalizableResource.java:30-114 remote branch downloads into staging).
TPU-native, the remote store is GCS:

- client-side FETCHES (conf files, ``tony.<role>.resources``, venv zips,
  src dirs) shell out to ``gsutil`` / ``gcloud storage`` — present on
  every TPU-VM image — so no GCS SDK dependency enters the tree;
- checkpoint WRITES need no copier at all: orbax/tensorstore speak
  ``gs://`` natively, the framework only has to pass such paths through
  untouched (no ``os.makedirs``, no step scans).

Tests point ``TONY_GSUTIL`` at a fake that serves a local directory.
"""

from __future__ import annotations

import logging
import os
import shlex
import shutil
import subprocess

log = logging.getLogger(__name__)

REMOTE_SCHEMES = ("gs://",)


def is_remote(path: str) -> bool:
    return str(path).startswith(REMOTE_SCHEMES)


def _copier() -> list[str]:
    override = os.environ.get("TONY_GSUTIL", "")
    if override:
        return shlex.split(override)
    if shutil.which("gsutil"):
        return ["gsutil"]
    if shutil.which("gcloud"):
        return ["gcloud", "storage"]
    raise RuntimeError(
        "gs:// input given but neither gsutil nor gcloud is on PATH "
        "(set TONY_GSUTIL to an equivalent copier)")


def fetch(remote: str, dest: str, recursive: bool = False) -> str:
    """Copy ``remote`` (gs://...) to local path ``dest``. ``dest`` is the
    target file/dir itself, not its parent. Raises on copier failure."""
    os.makedirs(os.path.dirname(os.path.abspath(dest)), exist_ok=True)
    argv = [*_copier(), "cp", *(["-r"] if recursive else []), remote, dest]
    proc = subprocess.run(argv, capture_output=True, text=True,
                          timeout=float(os.environ.get(
                              "TONY_GSUTIL_TIMEOUT_S", "600")))
    if proc.returncode != 0:
        raise RuntimeError(
            f"fetch {remote} failed (rc {proc.returncode}): "
            f"{proc.stderr.strip()[-500:]}")
    log.info("fetched %s -> %s", remote, dest)
    return dest


def fetch_to_dir(remote: str, dest_dir: str, recursive: bool = False) -> str:
    """Copy ``remote`` into ``dest_dir`` keeping its basename."""
    return fetch(remote,
                 os.path.join(dest_dir, os.path.basename(remote.rstrip("/"))),
                 recursive=recursive)
