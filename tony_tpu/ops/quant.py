"""Weight-only int8 quantization + pallas dequant-matmul kernel.

Decode roofline (docs/PERF.md): generation is HBM-bound — every token
re-reads the weights — so storing kernels as int8 with per-output-channel
scales HALVES the bytes per decode step vs bf16. The pallas kernel
dequantizes tiles in VMEM right at the MXU: HBM traffic stays int8, the
matmul runs at full precision, and the scale multiply fuses into the
output epilogue. A plain ``int8.astype(bf16) * scale`` in jax would be
hoisted out of the decode scan as a loop invariant and materialize full
bf16 weights — exactly the traffic the format exists to avoid.

Quantization is symmetric per OUTPUT channel (absmax / 127), the
standard weight-only recipe: activations stay bf16/fp32, so there is no
calibration step and no accuracy cliff for serving-sized models.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from tony_tpu.ops.platform import interpret_mode as _interp


def quantize_q8(w: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """w: [in, out] float -> (w_q int8 [in, out], scale fp32 [out]).
    Symmetric absmax per output channel; dequant is ``w_q * scale``."""
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=0)
    scale = jnp.maximum(absmax, 1e-8) / 127.0
    w_q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127) \
        .astype(jnp.int8)
    return w_q, scale


def dequantize_q8(w_q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    return w_q.astype(jnp.float32) * scale[None, :]


def _q8_matmul_kernel(x_ref, w_ref, s_ref, o_ref):
    x = x_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)  # int8 tile dequant happens IN VMEM
    acc = jnp.dot(x, w, preferred_element_type=jnp.float32)
    # s_ref is deliberately [1, bn] (2-D): Mosaic rejects 1-D blocks
    # whose lane count disagrees with XLA's vector tiling (seen on-chip:
    # f32[4096] laid out T(1024) vs a (256,) block); [1, bn] broadcasts
    # over the [bm, bn] accumulator as-is
    o_ref[:] = (acc * s_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_m", "block_n",
                                             "out_dtype"))
def q8_matmul(x, w_q, scale, *, block_m: int = 128, block_n: int = 256,
              out_dtype=None):
    """x: [m, k] float @ int8 weights [k, n] (+ scale [n]) -> [m, k]·W.

    Grid tiles (m, n); each block reads an int8 [k, bn] weight tile from
    HBM and dequantizes in VMEM. K is kept whole per block (serving dims
    k<=8192 fit comfortably: bm·k fp32 + k·bn int8 < VMEM)."""
    m, k = x.shape
    k2, n = w_q.shape
    if k != k2 or scale.shape != (n,):
        raise ValueError(f"shape mismatch: x{x.shape} w{w_q.shape} "
                         f"scale{scale.shape}")
    out_dtype = out_dtype or x.dtype
    # n (a WEIGHT dim): largest divisor <= block_n — padding weights per
    # call would re-copy k*n bytes and forfeit the bandwidth win. Dense
    # dims are MXU-sized in practice; if only a tiny divisor exists the
    # kernel would degenerate (per-column dispatches), so fall back to
    # the XLA dequant matmul — correct, merely without the int8 traffic
    # saving for that pathological shape.
    bn = min(block_n, n)
    while n % bn:
        bn -= 1
    if bn < 64 and n > 64:
        return (jnp.dot(x.astype(jnp.float32), dequantize_q8(w_q, scale))
                ).astype(out_dtype)
    # m (the ACTIVATION dim): pad rows up to a block multiple and slice —
    # cheap (activations are small), and it avoids the prime-length
    # cliff where a divisor search would collapse to 1-row blocks that
    # each re-read the whole weight tile.
    bm = min(block_m, m)
    m_pad = -(-m // bm) * bm
    x_in = x if m_pad == m else jnp.pad(x, ((0, m_pad - m), (0, 0)))
    out = pl.pallas_call(
        _q8_matmul_kernel,
        out_shape=jax.ShapeDtypeStruct((m_pad, n), out_dtype),
        grid=(m_pad // bm, n // bn),
        in_specs=[
            pl.BlockSpec((bm, k), lambda i, j: (i, 0)),
            pl.BlockSpec((k, bn), lambda i, j: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        interpret=_interp(),
    )(x_in, w_q, scale.reshape(1, n))
    return out if m_pad == m else out[:m]

# Tensor parallelism note: GSPMD cannot see inside a pallas_call (an
# opaque custom call), so a tensor-sharded int8 kernel fed to q8_matmul
# under bare pjit would be silently ALL-GATHERED before the kernel ran —
# the opposite of the bandwidth win. The serving path therefore runs the
# kernel under shard_map with explicit column/row-parallel specs: see
# models.transformer.QuantDense (a custom_partitioning route was tried
# and dropped — jax 0.9's Shardy glue hands the callbacks sub-axis
# shardings it cannot convert mid-model).
