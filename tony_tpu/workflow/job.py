"""Workflow-scheduler jobtype — the tony-azkaban equivalent (layer L7).

Reference: tony-azkaban/TonyJob.java:27-121+ — an Azkaban job that (1)
collects every ``tony.*`` prop into a generated config file placed on the
job classpath, (2) injects flow lineage tags (exec id, flow id, project,
web host) as ``tony.application.tags``, (3) maps standard props
(TonyJobArg.java: src_dir, executes, task_params, python_venv,
python_binary_path, shell_env) to client CLI args, and (4) points the
launcher at TonyClient.

``WorkflowJob`` is that contract with the scheduler abstracted away: any
engine that can hand over a flat prop map (Azkaban Props, Airflow params,
Luigi config) and call ``run()`` gets a fully-formed tony-tpu submission.
"""

from __future__ import annotations

import logging
import os
import uuid
from dataclasses import dataclass, field

from tony_tpu.config import TonyConf, build_conf

log = logging.getLogger(__name__)

TONY_PREFIX = "tony."
WORKER_ENV_PREFIX = "worker_env."
# prop name -> conf key (ref: TonyJobArg.java's az-prop -> CLI-arg map)
STANDARD_ARGS = {
    "src_dir": "tony.application.src-dir",
    "executes": "tony.application.executes",
    "task_params": "tony.application.task-params",
    "python_venv": "tony.application.python-venv",
    "python_binary_path": "tony.application.python-command",
    "shell_env": "tony.application.shell-env",
}


@dataclass
class FlowContext:
    """Workflow lineage injected as tags (ref: CommonJobProperties.EXEC_ID /
    FLOW_ID / PROJECT_NAME + azkaban.webserverhost -> constructHadoopTags)."""

    execution_id: str = ""
    flow_id: str = ""
    project_name: str = ""
    scheduler_host: str = ""

    def tags(self) -> str:
        parts = [
            f"execution_id:{self.execution_id}" if self.execution_id else "",
            f"flow_id:{self.flow_id}" if self.flow_id else "",
            f"project_name:{self.project_name}" if self.project_name else "",
            f"scheduler_host:{self.scheduler_host}" if self.scheduler_host else "",
        ]
        return ",".join(p for p in parts if p)


@dataclass
class WorkflowJob:
    """One scheduler job that submits a tony-tpu application.

    ``props`` is the engine's flat prop map for this job; ``working_dir``
    is the job's scratch dir (the generated conf lands there, mirroring
    the reference's ``_tony-conf-<jobid>-<uuid>/tony.xml``).
    """

    job_id: str
    props: dict[str, str]
    working_dir: str
    flow: FlowContext = field(default_factory=FlowContext)
    conf_path: str = ""

    def build_conf(self) -> TonyConf:
        """Collect tony.* props + standard args + flow tags into a job conf
        (ref: TonyJob.getJobConfiguration)."""
        conf_file = self.props.get("conf_file", "")
        conf = build_conf(conf_file or None)
        for key, value in self.props.items():
            if key.startswith(TONY_PREFIX):
                conf.set(key, value)
        for prop, conf_key in STANDARD_ARGS.items():
            if self.props.get(prop):
                conf.set(conf_key, self.props[prop])
        worker_env = [
            f"{key[len(WORKER_ENV_PREFIX):]}={value}"
            for key, value in self.props.items()
            if key.startswith(WORKER_ENV_PREFIX)
        ]
        if worker_env:
            existing = str(conf.get("tony.application.shell-env", ""))
            joined = ",".join(worker_env)
            conf.set("tony.application.shell-env",
                     f"{existing},{joined}" if existing else joined)
        tags = self.flow.tags()
        if tags:
            conf.set("tony.application.tags", tags)
        if not conf.get("tony.application.name") or \
                str(conf.get("tony.application.name")) == "tony-tpu":
            conf.set("tony.application.name", self.flow.flow_id or self.job_id)
        return conf

    def write_generated_conf(self, conf: TonyConf) -> str:
        """Persist the merged conf where the launcher (or a human) can see
        exactly what was submitted (ref: setupJobConfigurationFile)."""
        gen_dir = os.path.join(self.working_dir,
                               f"_tony-conf-{self.job_id}-{uuid.uuid4().hex[:8]}")
        os.makedirs(gen_dir, exist_ok=True)
        self.conf_path = os.path.join(gen_dir, "tony.json")
        conf.write_final(self.conf_path)
        return self.conf_path

    def run(self) -> bool:
        """Build conf, write it, submit, block until terminal status
        (ref: TonyJob.run -> main class TonyClient)."""
        from tony_tpu.client import TonyClient

        conf = self.build_conf()
        self.write_generated_conf(conf)
        log.info("workflow job %s submitting (conf: %s, tags: %s)",
                 self.job_id, self.conf_path,
                 conf.get("tony.application.tags"))
        return TonyClient(conf).run()
