"""GPT-style decoder-only transformer — the long-context flagship.

No reference analog (TonY has no model code); built TPU-first:

- logical-axis param annotations ("embed", "heads", "mlp", "vocab") so the
  parallel.sharding presets (dp/fsdp/tp/fsdp_tp) apply unchanged
- attention backend selectable: "reference" (O(L^2)), "blockwise"
  (chunked online-softmax), "ring" (sequence-parallel over the seq mesh
  axis), or "pallas" (fused TPU kernel, tony_tpu.ops.attention)
- bfloat16 activations / float32 params + optimizer, MXU-sized dims
- optional remat (jax.checkpoint) per block to trade FLOPs for HBM
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from flax.linen import partitioning as nn_partitioning

from tony_tpu.parallel.ring_attention import (
    blockwise_attention,
    reference_attention,
    ring_attention,
)

param_with_axes = nn_partitioning.param_with_axes


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32_000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 6
    d_ff: int = 2048
    max_seq_len: int = 2048
    dtype: Any = jnp.bfloat16
    attention_backend: str = "blockwise"  # reference|blockwise|ring|ulysses|pallas
    attention_block_size: int = 512
    remat: bool = False
    mesh: Any = None  # required for the ring backend

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def _attention(cfg: TransformerConfig, q, k, v):
    if cfg.attention_backend == "reference":
        return reference_attention(q, k, v, causal=True)
    if cfg.attention_backend == "blockwise":
        return blockwise_attention(q, k, v, block_size=cfg.attention_block_size,
                                   causal=True)
    if cfg.attention_backend == "ring":
        if cfg.mesh is None:
            raise ValueError("ring attention needs cfg.mesh")
        return ring_attention(q, k, v, cfg.mesh, causal=True)
    if cfg.attention_backend == "ulysses":
        if cfg.mesh is None:
            raise ValueError("ulysses attention needs cfg.mesh")
        from tony_tpu.parallel.ulysses import ulysses_attention

        return ulysses_attention(q, k, v, cfg.mesh, causal=True,
                                 block_size=cfg.attention_block_size)
    if cfg.attention_backend == "pallas":
        from tony_tpu.ops.attention import flash_attention

        return flash_attention(q, k, v, causal=True)
    raise ValueError(f"unknown attention backend {cfg.attention_backend}")


class RMSNorm(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones_init(), (x.shape[-1],),
                           jnp.float32)
        x32 = x.astype(jnp.float32)
        norm = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True)
                                   + 1e-6)
        return (norm * scale).astype(self.dtype)


def rotary_embedding(x, positions):
    """RoPE over head_dim (TPU-friendly: pure elementwise, fuses away)."""
    d = x.shape[-1]
    half = d // 2
    freq = 1.0 / (10_000 ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions[:, None].astype(jnp.float32) * freq[None, :]  # [L, half]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1).astype(x.dtype)


class Attention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, decode: bool = False):
        cfg = self.cfg
        b, l, _ = x.shape
        dense = lambda name, feats, axes: nn.DenseGeneral(  # noqa: E731
            feats, axis=-1, use_bias=False, dtype=cfg.dtype,
            param_dtype=jnp.float32, name=name,
            kernel_init=nn.initializers.normal(0.02))
        q = dense("q", (cfg.n_heads, cfg.head_dim), ("embed", "heads", "kv"))(x)
        k = dense("k", (cfg.n_heads, cfg.head_dim), ("embed", "heads", "kv"))(x)
        v = dense("v", (cfg.n_heads, cfg.head_dim), ("embed", "heads", "kv"))(x)
        if decode:
            out = self._decode_attention(q, k, v)
        else:
            positions = jnp.arange(l)
            q = rotary_embedding(q, positions)
            k = rotary_embedding(k, positions)
            out = _attention(cfg, q, k, v)
        out = nn.DenseGeneral(
            cfg.d_model, axis=(-2, -1), use_bias=False, dtype=cfg.dtype,
            param_dtype=jnp.float32, name="o",
            kernel_init=nn.initializers.normal(0.02))(out)
        return out

    def _decode_attention(self, q, k, v):
        """Incremental attention over a fixed-size KV cache.

        Flax "cache" collection, the standard jittable decode shape: the
        cache is a static [b, max_seq_len, h, dh] buffer updated with
        lax.dynamic_update_slice at the current index, so every decode
        step compiles to the same static-shape program (no growing
        tensors, no recompiles — the XLA-friendly way to autoregress).
        """
        cfg = self.cfg
        b, l, h, dh = q.shape
        max_len = cfg.max_seq_len
        is_init = self.has_variable("cache", "cached_key")
        cached_k = self.variable("cache", "cached_key", jnp.zeros,
                                 (b, max_len, h, dh), k.dtype)
        cached_v = self.variable("cache", "cached_value", jnp.zeros,
                                 (b, max_len, h, dh), v.dtype)
        cache_index = self.variable("cache", "cache_index",
                                    lambda: jnp.array(0, jnp.int32))
        if not is_init:  # shape-only init pass
            return jnp.zeros((b, l, h, dh), q.dtype)
        cur = cache_index.value
        positions = cur + jnp.arange(l)
        q = rotary_embedding(q, positions)
        k = rotary_embedding(k, positions)
        keys = jax.lax.dynamic_update_slice(cached_k.value, k, (0, cur, 0, 0))
        values = jax.lax.dynamic_update_slice(cached_v.value, v, (0, cur, 0, 0))
        cached_k.value = keys
        cached_v.value = values
        cache_index.value = cur + l
        s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                       keys.astype(jnp.float32)) / jnp.sqrt(dh)
        kv_pos = jnp.arange(max_len)
        visible = kv_pos[None, :] <= (cur + jnp.arange(l))[:, None]  # [l, max]
        s = jnp.where(visible[None, None, :, :], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", p, values.astype(jnp.float32))
        return out.astype(q.dtype)


class MLP(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        h = nn.Dense(cfg.d_ff, use_bias=False, dtype=cfg.dtype,
                     param_dtype=jnp.float32, name="wi",
                     kernel_init=nn.initializers.normal(0.02))(x)
        h = nn.gelu(h)
        return nn.Dense(cfg.d_model, use_bias=False, dtype=cfg.dtype,
                        param_dtype=jnp.float32, name="wo",
                        kernel_init=nn.initializers.normal(0.02))(h)


class Block(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, decode: bool = False):
        x = x + Attention(self.cfg, name="attn")(
            RMSNorm(self.cfg.dtype, name="ln1")(x), decode=decode)
        x = x + MLP(self.cfg, name="mlp")(RMSNorm(self.cfg.dtype,
                                                  name="ln2")(x))
        return x


class Transformer(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, decode: bool = False):
        cfg = self.cfg
        embed = self.param("embedding", nn.initializers.normal(0.02),
                           (cfg.vocab_size, cfg.d_model), jnp.float32)
        x = embed[tokens].astype(cfg.dtype)
        block = Block
        if cfg.remat and not decode:
            block = nn.remat(Block, static_argnums=(2,))
        for i in range(cfg.n_layers):
            x = block(cfg, name=f"block_{i}")(x, decode)
        x = RMSNorm(cfg.dtype, name="ln_f")(x)
        logits = jnp.einsum("bld,vd->blv", x.astype(jnp.float32), embed)
        return logits


def logical_axis_rules_tree(params: Any) -> Any:
    """Best-effort logical axes for the transformer param tree, consumed by
    parallel.sharding.tree_shardings. Derived from param path names."""

    def axes_for(path: tuple, x) -> tuple:
        names = [getattr(p, "key", str(p)) for p in path]
        leaf_dims = x.ndim
        joined = "/".join(names)
        if "embedding" in joined:
            return ("vocab", "embed")
        if any(s in joined for s in ("/q/", "/k/", "/v/")) or \
                joined.endswith(("q/kernel", "k/kernel", "v/kernel")):
            return ("embed", "heads", "kv")[:leaf_dims]
        if "/o/" in joined or joined.endswith("o/kernel"):
            return ("heads", "kv", "embed")[:leaf_dims]
        if "wi" in joined:
            return ("embed", "mlp")
        if "wo" in joined:
            return ("mlp", "embed")
        return tuple([None] * leaf_dims)

    return jax.tree_util.tree_map_with_path(axes_for, params)
