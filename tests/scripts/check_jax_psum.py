"""Real multi-process jax.distributed rendezvous: initialize from the
injected env, allgather a per-process value across the gang, assert the
global reduction. This pins the rendezvous CONTRACT itself (coordinator
address serves, processes join, collectives flow), not just the env
spelling that check_jax_env.py covers."""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# one local device per process: the test pins the multi-PROCESS contract;
# inheriting the suite's 8-virtual-device XLA_FLAGS would put 16 virtual
# devices' collective rendezvous on a loaded 1-core box — the gang-flake
# source VERDICT r3 #8 asks to pin
os.environ["XLA_FLAGS"] = " ".join(
    [f for f in os.environ.get("XLA_FLAGS", "").split()
     if "xla_force_host_platform_device_count" not in f]
    + ["--xla_force_host_platform_device_count=1"])

from tony_tpu import distributed  # noqa: E402

spec = distributed.initialize(timeout_s=120)
if spec is None:
    print("not in a gang")
    sys.exit(5)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.experimental import multihost_utils  # noqa: E402

if jax.process_count() != spec["num_processes"]:
    print("bad process_count", jax.process_count(), spec["num_processes"])
    sys.exit(6)

val = jnp.asarray([float(spec["process_id"] + 1)])
total = float(multihost_utils.process_allgather(val).sum())
n = spec["num_processes"]
expect = n * (n + 1) / 2
if abs(total - expect) > 1e-6:
    print("bad global sum", total, expect)
    sys.exit(7)
print("global sum ok:", total)
sys.exit(0)
