"""Paged KV cache (serve/slots.PagePool + the paged engine paths).

Two layers of pinning: PagePool/SlotCache property tests (alloc/free
round-trips never leak, refcounts pin shared pages, reservations keep
the no-preemption invariant, a copy-on-write fork preserves the
parent page bit-for-bit) and the serving exactness anchor — paged
greedy outputs byte-identical to the unpaged fixed-shape path and to
solo ``generate()`` across the rope/learned x scan_layers x int8-KV
matrix, under prefix sharing, speculation, pool pressure, and
eviction. CPU-only; the paged attention gathers the same values to
the same logical positions as the unpaged buffer, so parity is exact,
not approximate.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models import Transformer, TransformerConfig, generate
from tony_tpu.serve import PagePool, PoolExhausted, Request, Server


@pytest.fixture(scope="module")
def tiny():
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_seq_len=32,
                            dtype=jnp.float32,
                            attention_backend="reference")
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _solo(model, params, prompt, n):
    out = generate(model, params, jnp.asarray([prompt], jnp.int32),
                   max_new_tokens=n)
    return np.asarray(out)[0].tolist()


# ------------------------------------------------------------ PagePool


def test_pool_alloc_free_roundtrip_never_leaks(tiny):
    """Randomized alloc/share/unref churn holds the conservation
    invariant (free + used == total, refcounts never negative) and
    returns every page once the last holder lets go."""
    model, params = tiny
    pool = PagePool(model, params, n_pages=7, page_size=8)
    rng = np.random.default_rng(0)
    held: list[int] = []
    for _ in range(200):
        op = rng.integers(0, 3)
        if op == 0 and pool.available() > 0:
            held.extend(pool.alloc(1))
        elif op == 1 and held:
            page = held[rng.integers(len(held))]
            pool.share([page])
            held.append(page)
        elif held:
            page = held.pop(rng.integers(len(held)))
            pool.unref([page])
        assert pool.n_free + pool.n_used == pool.n_pages
        assert (pool.refcount >= 0).all()
        # every held reference is to a live page
        for page in held:
            assert pool.refcount[page] > 0
    for page in held:
        pool.unref([page])
    assert pool.n_used == 0 and pool.n_free == pool.n_pages
    assert (pool.refcount == 0).all()
    assert pool.allocs == pool.frees


def test_pool_refcount_pins_shared_pages(tiny):
    model, params = tiny
    pool = PagePool(model, params, n_pages=4, page_size=8)
    (page,) = pool.alloc(1)
    pool.share([page])           # second holder
    assert pool.cow_shared() == 1
    pool.unref([page])           # first holder gone
    assert pool.n_used == 1      # still pinned
    assert pool.cow_shared() == 0
    pool.unref([page])
    assert pool.n_used == 0
    with pytest.raises(ValueError, match="free page"):
        pool.unref([page])
    with pytest.raises(ValueError, match="free page"):
        pool.share([page])


def test_pool_reservation_invariant(tiny):
    """free >= reserved always: a granted reservation can always be
    allocated (the no-preemption guarantee), over-asks are refused,
    and alloc past the reservation is an engine bug that raises."""
    model, params = tiny
    pool = PagePool(model, params, n_pages=4, page_size=8)
    assert pool.reserve(3)
    assert not pool.reserve(2)          # only 1 unreserved left
    assert pool.available() == 1
    got = pool.alloc(2, from_reservation=True)
    assert pool.reserved == 1 and len(got) == 2
    with pytest.raises(RuntimeError, match="reservation"):
        pool.alloc(2, from_reservation=True)
    with pytest.raises(RuntimeError, match="available"):
        pool.alloc(2)                   # 2 free, 1 reserved -> 1 available
    pool.cancel(1)
    assert pool.reserved == 0
    with pytest.raises(ValueError, match="cancel"):
        pool.cancel(1)
    pool.unref(got)
    assert pool.available() == 4


def test_pool_concurrent_churn_reconciles(tiny):
    """The two-lock allocator under real thread contention: several
    threads churn reserve/alloc/share/unref/cancel against ONE shared
    pool, and the ledger reconciles exactly — no page is ever issued
    to two owners (the final free list holds each page id exactly
    once), ``free >= reserved`` holds at every sampled instant, and
    once every thread drops its references the pool is empty with
    ``allocs == frees``."""
    model, params = tiny
    pool = PagePool(model, params, n_pages=32, page_size=8, shared=True)
    n_threads, iters = 6, 250
    barrier = threading.Barrier(n_threads)
    errors: list[BaseException] = []

    def churn(seed):
        rng = np.random.default_rng(seed)
        held: list[int] = []   # pages this thread holds one ref to
        barrier.wait()
        try:
            for _ in range(iters):
                op = int(rng.integers(0, 5))
                if op == 0:                      # reserve -> alloc
                    n = int(rng.integers(1, 3))
                    if pool.reserve(n):
                        got = pool.alloc(n, from_reservation=True)
                        # freshly allocated pages belong to this
                        # thread alone: refcount is exactly 1
                        assert all(pool.refcount[p] == 1 for p in got)
                        held.extend(got)
                elif op == 1:                    # reserve -> cancel
                    n = int(rng.integers(1, 3))
                    if pool.reserve(n):
                        pool.cancel(n)
                elif op == 2 and held:           # cow fork: extra ref
                    page = held[int(rng.integers(len(held)))]
                    pool.share([page])
                    held.append(page)
                elif held:                       # drop one ref
                    page = held.pop(int(rng.integers(len(held))))
                    pool.unref([page])
                st = pool.stats()                # one _mu snapshot
                assert st["free"] + st["used"] == st["total"]
                assert st["free"] >= st["reserved"] >= 0
        except BaseException as e:               # pragma: no cover
            errors.append(e)
        finally:
            for page in held:
                pool.unref([page])

    threads = [threading.Thread(target=churn, args=(i,), daemon=True)
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert not errors, errors
    assert pool.n_used == 0 and pool.reserved == 0
    assert (pool.refcount == 0).all()
    assert pool.allocs == pool.frees
    # a double-issued page would appear twice here (or be missing)
    assert sorted(pool._free) == list(range(pool.n_pages))


def test_cow_fork_preserves_parent(tiny):
    """seed_pages forking a mid-page boundary copies the page: the
    fresh page starts bit-identical, and writes to it never touch the
    shared parent (the copy-on-write contract prefix consumers rely
    on)."""
    from tony_tpu.serve import SlotCache, cache_batch_axis

    model, params = tiny
    pool = PagePool(model, params, n_pages=6, page_size=8)
    slots = SlotCache(model, params, 2, pool=pool)
    (parent,) = pool.alloc(1)

    def paged_leaves(cache):
        return [leaf for path, leaf
                in jax.tree_util.tree_flatten_with_path(cache)[0]
                if cache_batch_axis(path, leaf) is not None]

    # stamp recognizable content into the parent page (every pool leaf)
    slots.cache = jax.tree_util.tree_map_with_path(
        lambda p, l: l.at[parent].set(7.0)
        if cache_batch_axis(p, l) is not None else l, slots.cache)
    before = [np.asarray(leaf[parent]) for leaf in paged_leaves(slots.cache)]
    assert pool.reserve(3)
    forked = slots.seed_pages(0, [parent], seed_len=5, reserve=3)
    assert forked and pool.forks == 1
    fresh = int(slots.page_table[0, 0])
    assert fresh != parent
    for leaf, want in zip(paged_leaves(slots.cache), before):
        assert np.array_equal(np.asarray(leaf[fresh]), want)  # exact copy
    # mutate the fork; the parent must not move
    slots.cache = jax.tree_util.tree_map_with_path(
        lambda p, l: l.at[fresh].set(-1.0)
        if cache_batch_axis(p, l) is not None else l, slots.cache)
    for leaf, want in zip(paged_leaves(slots.cache), before):
        assert np.array_equal(np.asarray(leaf[parent]), want)
    # parent still pinned by its original holder only
    assert pool.refcount[parent] == 1


# ------------------------------------------------ serving exactness


@pytest.mark.parametrize("positional,scan_layers,kv_int8", [
    ("rope", False, False),
    ("rope", False, True),
    ("rope", True, False),
    ("rope", True, True),
    ("learned", False, False),
    ("learned", True, True),
])
def test_paged_unpaged_greedy_parity_matrix(positional, scan_layers,
                                            kv_int8):
    """The tentpole anchor, mirroring test_serve's slot-row matrix:
    paged and unpaged servers produce byte-identical outputs (greedy
    AND seeded sampling) across positional encoding x scan_layers x
    int8-KV — the paged gather feeds the same values at the same
    logical positions into the same reduction."""
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_seq_len=32,
                            dtype=jnp.float32,
                            attention_backend="reference",
                            positional=positional,
                            norm="layer" if positional == "learned"
                            else "rms",
                            scan_layers=scan_layers,
                            kv_cache_quant=kv_int8)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    reqs = [Request([1, 2, 3], 6, id="a"),
            Request([17, 46, 10, 20, 62], 5, id="b"),
            Request([5, 9], 6, temperature=0.9, top_k=8, seed=3, id="c"),
            Request([7, 7, 2, 1], 4, id="d")]
    import copy

    out = {}
    for paged in (False, True):
        srv = Server(model, params, batch_size=2, eos_id=-1, min_bucket=8,
                     paged=paged, kv_page_size=8)
        out[paged] = {r.id: (r.tokens, r.finish_reason)
                      for r in srv.run(copy.deepcopy(reqs))}
    assert out[True] == out[False]


def test_paged_matches_solo_and_page_boundaries(tiny):
    """Sequences long enough to cross several page boundaries match
    solo generate() token for token (page extension mid-decode is
    invisible), and the pool drains back to empty."""
    model, params = tiny
    srv = Server(model, params, batch_size=2, eos_id=-1, min_bucket=8,
                 paged=True, kv_page_size=4)  # tiny pages: many crossings
    prompts = [[1, 2, 3], [17, 46, 10, 20, 62, 26, 3]]
    res = {r.id: r for r in srv.run(
        Request(p, max_new_tokens=12) for p in prompts)}
    for i, p in enumerate(prompts):
        assert res[i].tokens == _solo(model, params, p, 12), p
    assert srv.slots.pool.n_used == 0
    assert srv.slots.pool.reserved == 0


def test_exact_hit_is_cow_admit_not_prefill(tiny):
    """Satellite: a paged exact-prefix hit is its own dispatch kind.
    The second identical prompt must cost zero prefill dispatches and
    land as one ``cow_admit`` timeline record (bucket 0), so
    tokens_per_dispatch for prefill is not diluted by aliasing
    admits."""
    model, params = tiny
    srv = Server(model, params, batch_size=1, eos_id=-1, min_bucket=8,
                 paged=True, kv_page_size=8, prefix_cache_mb=4.0)
    p = [17, 46, 10, 20, 62, 26]
    first = {r.id: r for r in srv.run([Request(p, 4, id="one")])}
    prefills_after_first = srv.prefills
    second = {r.id: r for r in srv.run([Request(p, 4, id="two")])}
    assert second["two"].tokens == first["one"].tokens
    assert srv.prefills == prefills_after_first  # no new prefill
    kinds = srv.timeline.summary()
    assert kinds["cow_admit"]["count"] == 1
    assert kinds["prefill"]["count"] == prefills_after_first
    assert second["two"].prefix_hit_tokens == len(p)
    rec = [r for r in srv.timeline.recent() if r.kind == "cow_admit"][0]
    assert rec.bucket == 0 and rec.request_id == "two"


def test_partial_hit_unaligned_forks_and_matches(tiny):
    """A prompt extending a stored prefix whose boundary falls mid-page
    forks exactly one page (parent preserved for the store) and stays
    token-exact vs solo."""
    model, params = tiny
    srv = Server(model, params, batch_size=1, eos_id=-1, min_bucket=8,
                 paged=True, kv_page_size=8, prefix_cache_mb=4.0)
    base = [17, 46, 10, 20, 62]          # 5 tokens: mid-page boundary
    ext = base + [26, 3, 9]
    list(srv.run([Request(base, 4, id="seed")]))
    forks_before = srv.slots.pool.forks
    res = {r.id: r for r in srv.run([Request(ext, 5, id="ext")])}
    assert res["ext"].tokens == _solo(model, params, ext, 5)
    assert res["ext"].prefix_hit_tokens > 0
    assert srv.slots.pool.forks > forks_before
    assert srv.counters()["kv_cow_forks"] == srv.slots.pool.forks


def test_tight_pool_backpressure_serializes_without_loss(tiny):
    """A pool holding ~one request's worst case at a time: admissions
    queue behind the reservation gate (no preemption, no crash, no
    drop) and every output stays token-exact."""
    model, params = tiny
    srv = Server(model, params, batch_size=4, eos_id=-1, min_bucket=8,
                 paged=True, kv_page_size=8, kv_pages=4)
    prompts = [[1, 2, 3], [5, 9], [17, 46, 10, 20, 62, 26], [7, 7, 7, 2]]
    res = {r.id: r for r in srv.run(
        Request(p, max_new_tokens=6) for p in prompts)}
    assert len(res) == len(prompts)
    for i, p in enumerate(prompts):
        assert res[i].tokens == _solo(model, params, p, 6), p
    assert srv.slots.pool.n_used == 0


def test_pool_exhaustion_sheds_typed_not_crash(tiny):
    """A request bigger than the whole pool sheds with the typed
    PoolExhausted (-> 503 at the gateway), and the engine keeps
    serving admissible requests afterwards."""
    model, params = tiny
    srv = Server(model, params, batch_size=2, eos_id=-1, min_bucket=8,
                 paged=True, kv_page_size=8, kv_pages=2)
    with pytest.raises(PoolExhausted, match="KV pages"):
        srv.submit(Request([1] * 20, max_new_tokens=10))
    res = {r.id: r for r in srv.run([Request([1, 2, 3], 4, id="ok")])}
    assert res["ok"].tokens == _solo(model, params, [1, 2, 3], 4)


def test_pool_exhaustion_gateway_sheds_503(tiny):
    """The gateway maps PoolExhausted to a 503 shed — capacity, not a
    400 malformation — and counts it in /stats."""
    from tony_tpu.gateway import Gateway, GenRequest, Shed

    model, params = tiny
    srv = Server(model, params, batch_size=2, eos_id=-1, min_bucket=8,
                 paged=True, kv_page_size=8, kv_pages=2)
    gw = Gateway([srv]).start()
    try:
        with pytest.raises(Shed, match="KV pages") as exc:
            gw.submit(GenRequest([1] * 20, max_new_tokens=10,
                                 id="big")).result(timeout=60)
        assert exc.value.http_status == 503
        res = gw.submit(GenRequest([1, 2, 3], max_new_tokens=4,
                                   id="ok")).result(timeout=120)
        assert res.tokens == _solo(model, params, [1, 2, 3], 4)
        assert gw.snapshot()["shed"].get(503, 0) >= 1
    finally:
        assert gw.drain(timeout=60)


def test_store_squeeze_under_pool_pressure(tiny):
    """Prefix-store pages yield to admissions: with the pool sized so
    retained store entries would block the next request, admission
    evicts LRU store entries (freeing their pages) instead of
    stalling; outputs stay exact and the engine reports evictions."""
    model, params = tiny
    srv = Server(model, params, batch_size=1, eos_id=-1, min_bucket=8,
                 paged=True, kv_page_size=8, kv_pages=4,
                 prefix_cache_mb=4.0)
    prompts = [[i + 1, i + 2, i + 3, i + 4, i + 5] for i in range(5)]
    res = {r.id: r for r in srv.run(
        Request(p, max_new_tokens=6) for p in prompts)}
    for i, p in enumerate(prompts):
        assert res[i].tokens == _solo(model, params, p, 6), p
    assert srv.prefix.stats()["evictions"] > 0
    # the store keeps whatever still fits; pool accounting stays sane
    pool = srv.slots.pool
    assert pool.n_used + pool.n_free == pool.n_pages
    assert pool.reserved == 0


def test_donation_is_refcount_bump_pages_survive_evict(tiny):
    """EOS donation pins the slot's own pages into the store — after
    the slot is evicted the pages stay resident under the store's
    refcount (no read_slot_row dispatch, no copy), and the next turn
    seeds from them token-exactly."""
    model, params = tiny
    srv = Server(model, params, batch_size=1, eos_id=-1, min_bucket=8,
                 paged=True, kv_page_size=8, prefix_cache_mb=4.0)
    t1 = [11, 12, 13]
    r1 = {r.id: r for r in srv.run([Request(t1, 4, id="t1")])}
    pool = srv.slots.pool
    assert pool.n_used > 0          # store-held pages outlive the slot
    turn2 = t1 + r1["t1"].tokens[:-1] + [14]
    r2 = {r.id: r for r in srv.run([Request(turn2, 4, id="t2")])}
    assert r2["t2"].tokens == _solo(model, params, turn2, 4)
    assert r2["t2"].prefix_hit_tokens > 0


def test_paged_speculation_parity(tiny):
    """Speculative decoding over the paged cache: greedy outputs
    unchanged, drafts accepted, verify windows write through page
    tables."""
    model, params = tiny
    rep = [3, 4, 3, 4, 3, 4]
    import copy

    reqs = [Request(rep, 8, id="r"), Request([1, 2], 8, id="s")]
    out = {}
    for paged in (False, True):
        srv = Server(model, params, batch_size=2, eos_id=-1, min_bucket=8,
                     paged=paged, kv_page_size=8, speculate_k=4,
                     chunk_steps=1)
        out[paged] = ({r.id: r.tokens for r in srv.run(
            copy.deepcopy(reqs))}, srv.spec_accepted)
    assert out[True][0] == out[False][0]
    assert out[True][0]["r"] == _solo(model, params, rep, 8)
    assert out[True][1] > 0  # drafts actually flowed through verify


def test_paged_flash_decode_backend():
    """The pallas flash-decode kernel consumes the gathered paged
    buffers unchanged (contiguous [b, span] views) — parity vs the
    einsum path's solo generate."""
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_seq_len=32,
                            dtype=jnp.float32,
                            attention_backend="reference",
                            decode_attention="flash")
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    srv = Server(model, params, batch_size=2, eos_id=-1, min_bucket=8,
                 paged=True, kv_page_size=8)
    prompts = [[1, 2, 3], [17, 46, 10, 20, 62]]
    res = {r.id: r for r in srv.run(
        Request(p, max_new_tokens=6) for p in prompts)}
    for i, p in enumerate(prompts):
        assert res[i].tokens == _solo(model, params, p, 6), p


def test_paged_refuses_sliding_window_explicitly(tiny):
    """Same precedent as the prefix store: parity over sliding-window
    models is unpinned — explicit paged=True fails loud, the default
    downgrades to unpaged."""
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_seq_len=32,
                            dtype=jnp.float32, sliding_window=8,
                            attention_backend="reference")
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    with pytest.raises(NotImplementedError, match="sliding-window"):
        Server(model, params, batch_size=1, paged=True)
    srv = Server(model, params, batch_size=1)  # default: auto-downgrade
    assert not srv.paged


def test_kv_counters_block(tiny):
    """counters() carries the kv_pages observability block with sane
    arithmetic mid-flight and after drain."""
    model, params = tiny
    srv = Server(model, params, batch_size=2, eos_id=-1, min_bucket=8,
                 paged=True, kv_page_size=8, prefix_cache_mb=4.0)
    srv.submit(Request([1, 2, 3, 4, 5], 6, id="x"))
    srv.step()
    c = srv.counters()
    assert c["kv_pages_total"] == srv.slots.pool.n_pages
    assert c["kv_pages_used"] + c["kv_pages_free"] == c["kv_pages_total"]
    assert c["kv_bytes_resident"] == \
        c["kv_pages_used"] * srv.slots.pool.page_nbytes
    assert c["kv_tokens_resident"] > 0
    assert c["kv_page_size"] == 8
    list(srv.run(()))  # drain
    c = srv.counters()
    # store retains the donated pages; live-slot tokens are gone
    assert c["kv_pages_reserved"] == 0
