#!/bin/sh
# serve-smoke: boot a tiny-model gateway, fire concurrent curl clients
# (unary + streaming), assert 200s and a well-formed NDJSON stream, run
# a shared-prefix round (same preamble, different tails) and assert the
# prefix KV cache registered hits on /stats, run a speculation round
# (repetitive prompt; /stats engine.spec must show accepted drafts and
# the output must match a --speculate-k 0 control gateway), a PAGED
# round (a fresh gateway with a deliberately small KV page pool under
# shared-prefix traffic: zero 5xx, /stats engine.kv_pages shows CoW
# page sharing, outputs identical to a --no-paged-kv control), exercise
# the SIGTERM graceful drain, then a CHAOS round: a fresh 2-replica
# gateway armed through TONY_SERVE_FAULTS has replica 0's dispatches
# killed mid-run — every request must still answer 200 (failover, not
# 5xx), /stats must show the supervision counters, and the dead
# replica must rejoin via its breaker probe. Every phase is bounded by
# `timeout`, so a hang exits nonzero instead of wedging CI.
#
# Then an AUTOSCALE round: a min=1/max=3 elastic gateway under
# burst load must scale up (the new replica probe-admitted into
# routing), serve the whole burst with zero 5xx, and drain back to
# the one-replica floor once idle.
#
# A GOODPUT/ALERTS round (ISSUE-10): a deliberately tiny KV
# page pool under concurrent load fires a kv_pages_pressure alert
# (/stats alerts + history alerts.jsonl + the portal's metrics page),
# resolves after load stops, and /debug/goodput names the largest
# waste bucket on the live subprocess gateway. The whole script also
# starts with the `make check` lint gate so smoke fails fast on drift.
#
# Finally a REMOTE round (ISSUE-11): two real `python -m
# tony_tpu.cli.replica` agent subprocesses behind a --agents gateway;
# concurrent traffic, `kill -9` one agent mid-run -> zero 5xx, every
# output token-exact vs a local-replica control gateway, the corpse
# quarantined, the survivor agent SIGTERM-drained clean. ISSUE-15
# extended the round: the survivor's dispatch counts and a non-null
# merged goodput block must land on /stats, tony_goodput_fraction +
# tony_transport_clock_offset_ms on /metrics, and one POST
# /debug/profile must fan a real capture out to the survivor agent.
# Plus a BUNDLE round (ISSUE-15): a synthetic alert on a live
# subprocess gateway must dump a self-contained debug bundle into the
# history job dir, validated as JSON (`make bundle-smoke`).
# Plus a STORM round (ISSUE-16): tools/storm.py drives 2000+
# concurrent NDJSON streams (after parking 500 idle keep-alive
# connections) into the event-driven edge — zero unintentional 5xx,
# token-exact spot checks vs unary controls, the edge block on
# /stats + tony_edge_* on /metrics, then a clean SIGTERM drain
# (`make storm-smoke`).
# Plus a MIGRATE round (ISSUE-18): two replicas on ONE shared
# PagePool; remove_replica freezes a throttled in-flight stream and
# the survivor adopts it by owner swap — token-exact vs a
# no-migration control, zero 5xx, zero pages copied, and the
# retiring drain returns in freeze-time instead of decoding the
# remaining budget to completion (`make migrate-smoke`).
# Plus a RECOVERY round (ISSUE-20): a --journal gateway over two real
# agent subprocesses is kill -9'd mid-stream; the agents park the
# orphaned sessions after --gateway-grace, a fresh `--recover` boot
# replays the WAL and adopts them (zero re-prefill), and every
# request's stream is re-fetched via GET /v1/stream/<id>?offset=0
# byte-identical to a never-crashed control — zero 5xx after restart
# (`make recovery-smoke`).
#
# Usage: tools/serve_smoke.sh       (repo root; `make serve-smoke`)
#        SERVE_SMOKE_ROUNDS=chaos tools/serve_smoke.sh
#                                   (chaos round only; `make chaos-smoke`)
#        SERVE_SMOKE_ROUNDS=autoscale tools/serve_smoke.sh
#                                   (autoscale round only; `make autoscale-smoke`)
#        SERVE_SMOKE_ROUNDS=goodput tools/serve_smoke.sh
#                                   (goodput/alerts round only; `make goodput-smoke`)
#        SERVE_SMOKE_ROUNDS=remote tools/serve_smoke.sh
#                                   (remote round only; `make remote-smoke`)
#        SERVE_SMOKE_ROUNDS=bundle tools/serve_smoke.sh
#                                   (flight-recorder round only; `make bundle-smoke`)
#        SERVE_SMOKE_ROUNDS=shard tools/serve_smoke.sh
#                                   (sharded-replica round only; `make shard-smoke`)
#        SERVE_SMOKE_ROUNDS=storm tools/serve_smoke.sh
#                                   (connection-storm round only; `make storm-smoke`)
#        SERVE_SMOKE_ROUNDS=migrate tools/serve_smoke.sh
#                                   (live-migration round only; `make migrate-smoke`)
#        SERVE_SMOKE_ROUNDS=recovery tools/serve_smoke.sh
#                                   (crash-recovery round only; `make recovery-smoke`)
set -u

PY=${PY:-python}
BOUND=${SERVE_SMOKE_TIMEOUT:-300}   # whole-run ceiling, seconds
WORK=$(mktemp -d /tmp/serve_smoke.XXXXXX)
GW_PID=''
CTRL_PID=''
CHAOS_PID=''
PAGED_PID=''
SCALE_PID=''
GP_PID=''
PORTAL_PID=''
AGENT0_PID=''
AGENT1_PID=''
RGW_PID=''
RCTRL_PID=''
DGW_PID=''
DCTRL_PID=''
AT_PID=''
ATCTRL_PID=''
SHGW_PID=''
SHCTRL_PID=''
BGW_PID=''
STGW_PID=''
KGW_PID=''
KGW2_PID=''
KCTRL_PID=''
KAGENT0_PID=''
KAGENT1_PID=''
trap 'kill $GW_PID $CTRL_PID $CHAOS_PID $PAGED_PID $SCALE_PID $GP_PID $PORTAL_PID $RGW_PID $RCTRL_PID $DGW_PID $DCTRL_PID $AT_PID $ATCTRL_PID $SHGW_PID $SHCTRL_PID $BGW_PID $STGW_PID $KGW_PID $KGW2_PID $KCTRL_PID 2>/dev/null; kill -9 $AGENT0_PID $AGENT1_PID $KAGENT0_PID $KAGENT1_PID 2>/dev/null; rm -rf "$WORK"' EXIT INT TERM

fail() { echo "serve-smoke: FAIL: $1" >&2; exit 1; }

# ---- lint gate (fail fast, before booting anything) ------------------
# exactly `make lint` (ruff when the box has it AND the in-tree AST
# checker, one source of truth for paths and policy) — a smoke run on
# a lint-drifted tree stops here, not after minutes of gateway rounds
make lint PY="$PY" || fail "lint findings (run: make lint)"
echo "serve-smoke: lint clean"

# ---- chaos round (also standalone: SERVE_SMOKE_ROUNDS=chaos) ---------
# the serving half of the TonY story: kill a replica's work, keep
# serving. TONY_SERVE_FAULTS (serve/faults.py) deterministically fails
# replica 0's 4th dispatch; with 6 concurrent requests in flight its
# tickets must fail over token-exactly to replica 1 (zero 5xx), the
# supervision counters must register the failure, and the breaker
# probe must rejoin replica 0 (/healthz back to "ok").
chaos_round() {
    TONY_SERVE_FAULTS='{"op": "fail", "dispatch": 4, "replica": 0}' \
    JAX_PLATFORMS=cpu $PY -m tony_tpu.cli.gateway --demo-model \
        --replicas 2 --port 0 --compile-cache '' \
        --breaker-base 0.1 --breaker-max 1 \
        >"$WORK/chaos_boot.log" 2>"$WORK/chaos_stderr.log" &
    CHAOS_PID=$!
    CHAOS_URL=''
    i=0
    while [ $i -lt $BOUND ]; do
        CHAOS_URL=$(sed -n 's/.*gateway at \(http:[^ ]*\).*/\1/p' "$WORK/chaos_boot.log")
        [ -n "$CHAOS_URL" ] && break
        kill -0 $CHAOS_PID 2>/dev/null || fail "chaos gateway died at boot: $(cat "$WORK/chaos_stderr.log")"
        sleep 1; i=$((i + 1))
    done
    [ -n "$CHAOS_URL" ] || fail "chaos gateway did not print its URL within ${BOUND}s"
    echo "serve-smoke: chaos gateway at $CHAOS_URL (replica 0 armed to die)"

    CHAOS_PIDS=''
    n=0
    while [ $n -lt 6 ]; do
        curl_s "$WORK/chaos_$n" "$CHAOS_URL/v1/generate" \
            "{\"token_ids\": [$((1 + n)), 2, 3], \"max_new_tokens\": 8, \"id\": $n}" \
            >"$WORK/chaos_${n}.code" &
        CHAOS_PIDS="$CHAOS_PIDS $!"
        n=$((n + 1))
    done
    wait $CHAOS_PIDS
    n=0
    while [ $n -lt 6 ]; do
        # the whole point: a replica kill is failover, never a 5xx
        [ "$(cat "$WORK/chaos_${n}.code")" = 200 ] || fail "chaos request $n -> $(cat "$WORK/chaos_${n}.code") (replica kill must fail over, not 5xx)"
        grep -q '"finish_reason"' "$WORK/chaos_$n" || fail "chaos request $n: no finish_reason"
        n=$((n + 1))
    done

    code=$(curl_s "$WORK/chaos_stats" "$CHAOS_URL/stats") || fail "chaos stats curl"
    [ "$code" = 200 ] || fail "chaos stats -> $code"
    $PY - "$WORK/chaos_stats" <<'EOF' || fail "chaos stats: supervision counters wrong"
import json, sys
stats = json.load(open(sys.argv[1]))
assert stats["completed"] == 6, stats["completed"]
assert stats["shed"] == {}, stats["shed"]  # zero 5xx
sup = stats["supervision"]
assert sup["replica_failures"] >= 1, sup
assert sup["failovers"] >= 1 and sup["retries"] >= 1, sup
EOF

    # the dead replica must rejoin: /healthz back to "ok" (breaker
    # probe succeeded; the injected fault was single-shot)
    i=0
    while [ $i -lt $BOUND ]; do
        curl_s "$WORK/chaos_health" "$CHAOS_URL/healthz" >/dev/null 2>&1
        grep -q '"status": "ok"' "$WORK/chaos_health" && break
        sleep 1; i=$((i + 1))
    done
    grep -q '"status": "ok"' "$WORK/chaos_health" || fail "replica 0 never rejoined: $(cat "$WORK/chaos_health")"

    # and serves real traffic again, then drains clean
    code=$(curl_s "$WORK/chaos_after" "$CHAOS_URL/v1/generate" \
        '{"token_ids": [7, 7], "max_new_tokens": 3}') || fail "post-rejoin curl"
    [ "$code" = 200 ] || fail "post-rejoin request -> $code"
    kill -TERM $CHAOS_PID
    i=0
    while kill -0 $CHAOS_PID 2>/dev/null; do
        [ $i -ge $BOUND ] && fail "chaos gateway did not drain within ${BOUND}s of SIGTERM"
        sleep 1; i=$((i + 1))
    done
    wait $CHAOS_PID
    rc=$?
    [ $rc = 0 ] || fail "chaos gateway exited $rc after SIGTERM"
    CHAOS_PID=''
    echo "serve-smoke: chaos OK (replica kill -> failover, zero 5xx, rejoin, clean drain)"
}

curl_s() { timeout -k 5 "$BOUND" curl -sS -o "$1" -w '%{http_code}' "$2" ${3:+-d "$3"}; }

# ---- remote round (also standalone: SERVE_SMOKE_ROUNDS=remote) -------
# ISSUE-11: serve ON the provisioned hosts. Two real replica-agent
# subprocesses (`python -m tony_tpu.cli.replica`) behind an --agents
# gateway; `kill -9` one agent mid-run. Every request must still
# answer 200 with outputs token-exact vs a LOCAL-replica control
# gateway, the corpse must be quarantined, and the survivor agent
# must SIGTERM-drain clean (the deregister-by-draining story).
remote_round() {
    JAX_PLATFORMS=cpu $PY -m tony_tpu.cli.replica --demo-model \
        --serve-batch 2 --port 0 --port-file "$WORK/agent0.port" \
        --replica-index 0 --compile-cache '' \
        >"$WORK/agent0.log" 2>&1 &
    AGENT0_PID=$!
    JAX_PLATFORMS=cpu $PY -m tony_tpu.cli.replica --demo-model \
        --serve-batch 2 --port 0 --port-file "$WORK/agent1.port" \
        --replica-index 1 --compile-cache '' \
        >"$WORK/agent1.log" 2>&1 &
    AGENT1_PID=$!
    i=0
    while [ $i -lt $BOUND ]; do
        [ -f "$WORK/agent0.port" ] && [ -f "$WORK/agent1.port" ] && break
        kill -0 $AGENT0_PID 2>/dev/null || fail "agent 0 died at boot: $(cat "$WORK/agent0.log")"
        kill -0 $AGENT1_PID 2>/dev/null || fail "agent 1 died at boot: $(cat "$WORK/agent1.log")"
        sleep 1; i=$((i + 1))
    done
    [ -f "$WORK/agent0.port" ] && [ -f "$WORK/agent1.port" ] || fail "agents did not bind within ${BOUND}s"
    A0=$(awk '{print $1 ":" $2}' "$WORK/agent0.port")
    A1=$(awk '{print $1 ":" $2}' "$WORK/agent1.port")
    echo "serve-smoke: replica agents at $A0 and $A1"

    # the remote gateway (a pure router: no model in this process) and
    # the local-replica CONTROL gateway outputs are compared against
    JAX_PLATFORMS=cpu $PY -m tony_tpu.cli.gateway --agents "$A0,$A1" \
        --serve-batch 2 --port 0 --compile-cache '' \
        --agent-heartbeat 0.2 --agent-lease-misses 3 \
        --breaker-base 0.2 --breaker-max 1 --quarantine-after 3 \
        >"$WORK/remote_boot.log" 2>"$WORK/remote_stderr.log" &
    RGW_PID=$!
    JAX_PLATFORMS=cpu $PY -m tony_tpu.cli.gateway --demo-model \
        --replicas 1 --serve-batch 2 --port 0 --compile-cache '' \
        >"$WORK/rctrl_boot.log" 2>&1 &
    RCTRL_PID=$!
    RURL=''; RCTRL_URL=''
    i=0
    while [ $i -lt $BOUND ]; do
        RURL=$(sed -n 's/.*gateway at \(http:[^ ]*\).*/\1/p' "$WORK/remote_boot.log")
        RCTRL_URL=$(sed -n 's/.*gateway at \(http:[^ ]*\).*/\1/p' "$WORK/rctrl_boot.log")
        [ -n "$RURL" ] && [ -n "$RCTRL_URL" ] && break
        kill -0 $RGW_PID 2>/dev/null || fail "remote gateway died at boot: $(cat "$WORK/remote_stderr.log")"
        sleep 1; i=$((i + 1))
    done
    [ -n "$RURL" ] && [ -n "$RCTRL_URL" ] || fail "remote/control gateways did not print URLs within ${BOUND}s"
    echo "serve-smoke: remote gateway at $RURL (control at $RCTRL_URL)"

    # warm both fleets so the kill lands mid-decode, not mid-compile
    code=$(curl_s "$WORK/rwarm" "$RURL/v1/generate" '{"token_ids": [9, 9], "max_new_tokens": 2}') || fail "remote warm curl"
    [ "$code" = 200 ] || fail "remote warm -> $code"
    curl_s "$WORK/rcwarm" "$RCTRL_URL/v1/generate" '{"token_ids": [9, 9], "max_new_tokens": 2}' >/dev/null || fail "control warm curl"

    REMOTE_PIDS=''
    n=0
    while [ $n -lt 8 ]; do
        curl_s "$WORK/remote_$n" "$RURL/v1/generate" \
            "{\"token_ids\": [$((1 + n)), 2, 3], \"max_new_tokens\": 48, \"id\": $n}" \
            >"$WORK/remote_${n}.code" &
        REMOTE_PIDS="$REMOTE_PIDS $!"
        n=$((n + 1))
    done
    # the headline move: SIGKILL agent 0 while the burst is in flight
    kill -9 $AGENT0_PID
    echo "serve-smoke: kill -9 agent 0 ($A0) mid-run"
    wait $REMOTE_PIDS
    n=0
    while [ $n -lt 8 ]; do
        curl_s "$WORK/rctrl_$n" "$RCTRL_URL/v1/generate" \
            "{\"token_ids\": [$((1 + n)), 2, 3], \"max_new_tokens\": 48, \"id\": $n}" \
            >/dev/null || fail "control request $n curl"
        n=$((n + 1))
    done
    n=0
    while [ $n -lt 8 ]; do
        # a dead HOST is failover, never a 5xx
        [ "$(cat "$WORK/remote_${n}.code")" = 200 ] || fail "remote request $n -> $(cat "$WORK/remote_${n}.code") (host kill must fail over, not 5xx)"
        $PY - "$WORK/remote_$n" "$WORK/rctrl_$n" <<'EOF' || fail "remote request $n: output differs from local-replica control"
import json, sys
a = json.load(open(sys.argv[1]))
b = json.load(open(sys.argv[2]))
assert a["token_ids"] == b["token_ids"], (a["token_ids"], b["token_ids"])
EOF
        n=$((n + 1))
    done

    # the corpse is quarantined (probes against a dead host keep
    # failing; --quarantine-after 3) and the stats name the machine
    i=0
    while [ $i -lt $BOUND ]; do
        curl_s "$WORK/remote_stats" "$RURL/stats" >/dev/null 2>&1
        grep -q '"state": "quarantined"' "$WORK/remote_stats" && break
        sleep 1; i=$((i + 1))
    done
    $PY - "$WORK/remote_stats" "$A0" "$A1" <<'EOF' || fail "remote stats: supervision/transport wrong ($(cat "$WORK/remote_stats"))"
import json, sys
stats = json.load(open(sys.argv[1]))
assert stats["shed"] == {}, stats["shed"]       # zero 5xx, whole round
assert stats["completed"] >= 9, stats["completed"]
sup = stats["supervision"]
assert sup["replica_failures"] >= 1, sup
rows = {r["replica"]: r for r in stats["replicas"]}
assert rows[0]["state"] == "quarantined", rows[0]["state"]
assert rows[0]["transport"]["address"] == sys.argv[2]
assert rows[1]["state"] == "healthy", rows[1]["state"]
assert rows[1]["completed"] >= 1, rows[1]["completed"]
# ISSUE-15: the survivor is OBSERVED, not a black hole — its pulled
# dispatch timeline and goodput ledger land in the gateway surfaces
r1 = rows[1]
assert r1["obs"]["pulls"] >= 1, r1.get("obs")
assert r1["dispatch"]["decode"]["count"] >= 1, r1.get("dispatch")
assert r1["goodput"] is not None
assert sum(r1["goodput"]["buckets"].values()) <= 1 + 1e-6
eng = stats["engine"]
assert eng["dispatch"]["decode"]["count"] >= 1, eng.get("dispatch")
assert eng["goodput"] and eng["goodput"]["buckets"], eng.get("goodput")
EOF
    curl_s "$WORK/remote_metrics" "$RURL/metrics" >/dev/null 2>&1
    grep -q 'tony_transport_rtt_seconds' "$WORK/remote_metrics" || fail "no transport metrics on /metrics"
    # ISSUE-15: goodput fractions + the clock-offset model exported
    # with the remote replica present
    grep -q 'tony_goodput_fraction{' "$WORK/remote_metrics" || fail "no goodput fractions on /metrics with a remote replica"
    grep -q 'tony_transport_clock_offset_ms{' "$WORK/remote_metrics" || fail "no clock-offset series on /metrics"
    grep -q 'tony_transport_obs_pulls_total{' "$WORK/remote_metrics" || fail "no obs-pull series on /metrics"

    # ISSUE-15: one POST /debug/profile fans the capture out to the
    # surviving agent host (the dead one reports its error, never
    # blocks the fan-out)
    code=$(curl_s "$WORK/remote_prof" "$RURL/debug/profile?steps=2" '{}') || fail "profile fanout curl"
    [ "$code" = 200 ] || fail "profile fanout -> $code"
    $PY - "$WORK/remote_prof" "$A1" <<'EOF' || fail "profile fanout did not arm the survivor agent ($(cat "$WORK/remote_prof"))"
import json, sys
doc = json.load(open(sys.argv[1]))
assert doc["armed"] is True
assert doc["remote"][sys.argv[2]]["armed"] is True, doc["remote"]
EOF
    # drive traffic until the agent-side capture lands (the first
    # start_trace of a process can block ~10 s on plugin spin-up)
    i=0
    while [ $i -lt $BOUND ]; do
        curl_s "$WORK/remote_drive" "$RURL/v1/generate" '{"token_ids": [5, 5], "max_new_tokens": 4}' >/dev/null 2>&1
        curl_s "$WORK/remote_prof_status" "$RURL/debug/profile" >/dev/null 2>&1
        if $PY - "$WORK/remote_prof_status" "$A1" <<'EOF'
import json, sys
doc = json.load(open(sys.argv[1]))
sys.exit(0 if doc.get("remote", {}).get(sys.argv[2], {})
         .get("captures", 0) >= 1 else 1)
EOF
        then break; fi
        sleep 1; i=$((i + 1))
    done
    [ $i -lt $BOUND ] || fail "survivor agent capture never completed: $(cat "$WORK/remote_prof_status")"

    # gateway SIGTERM drain (attached agents are left running), then
    # the survivor agent deregisters by DRAINING on its own SIGTERM
    kill -TERM $RGW_PID
    i=0
    while kill -0 $RGW_PID 2>/dev/null; do
        [ $i -ge $BOUND ] && fail "remote gateway did not drain within ${BOUND}s"
        sleep 1; i=$((i + 1))
    done
    wait $RGW_PID; rc=$?
    [ $rc = 0 ] || fail "remote gateway exited $rc after SIGTERM"
    RGW_PID=''
    kill -TERM $AGENT1_PID
    i=0
    while kill -0 $AGENT1_PID 2>/dev/null; do
        [ $i -ge $BOUND ] && fail "survivor agent did not drain within ${BOUND}s"
        sleep 1; i=$((i + 1))
    done
    wait $AGENT1_PID; rc=$?
    [ $rc = 0 ] || fail "survivor agent exited $rc after SIGTERM"
    grep -q "agent drained clean" "$WORK/agent1.log" || fail "survivor agent did not report a clean drain"
    AGENT1_PID=''
    wait $AGENT0_PID 2>/dev/null
    AGENT0_PID=''
    kill -TERM $RCTRL_PID
    wait $RCTRL_PID 2>/dev/null
    RCTRL_PID=''
    echo "serve-smoke: remote OK (kill -9 one of 2 agents -> zero 5xx, token-exact vs local control, corpse quarantined, survivor drained clean)"
}

# ---- recovery round (also standalone: SERVE_SMOKE_ROUNDS=recovery) ---
# ISSUE-20 crash-safe control plane: a --journal gateway routing to two
# real agent subprocesses is kill -9'd MID-STREAM. The orphaned agents
# park the in-flight sessions once --gateway-grace expires (or buffer
# results that finish into the void), a fresh boot with --recover
# replays the WAL and re-attaches the parked KV token-exact (zero
# re-prefill); every crashed request's stream is then fetched from the
# NEW gateway via GET /v1/stream/<id>?offset=0 and compared
# byte-for-byte against a never-crashed local control gateway. Zero
# 5xx after restart, and a clean SIGTERM drain compacts the journal
# back to empty.
recovery_round() {
    # engine wedge (~0.05s/token, timing-only — never alters tokens)
    # so the SIGKILL and the parking grace both land mid-stream
    KFAULTS='[{"op": "wedge", "dispatch": 1, "seconds": 0.05, "times": -1}]'
    TONY_SERVE_FAULTS="$KFAULTS" JAX_PLATFORMS=cpu $PY -m tony_tpu.cli.replica --demo-model \
        --serve-batch 4 --port 0 --port-file "$WORK/kagent0.port" \
        --replica-index 0 --compile-cache '' \
        --gateway-grace 0.5 --park-ttl 120 \
        >"$WORK/kagent0.log" 2>&1 &
    KAGENT0_PID=$!
    TONY_SERVE_FAULTS="$KFAULTS" JAX_PLATFORMS=cpu $PY -m tony_tpu.cli.replica --demo-model \
        --serve-batch 4 --port 0 --port-file "$WORK/kagent1.port" \
        --replica-index 1 --compile-cache '' \
        --gateway-grace 0.5 --park-ttl 120 \
        >"$WORK/kagent1.log" 2>&1 &
    KAGENT1_PID=$!
    i=0
    while [ $i -lt $BOUND ]; do
        [ -f "$WORK/kagent0.port" ] && [ -f "$WORK/kagent1.port" ] && break
        kill -0 $KAGENT0_PID 2>/dev/null || fail "recovery agent 0 died at boot: $(cat "$WORK/kagent0.log")"
        kill -0 $KAGENT1_PID 2>/dev/null || fail "recovery agent 1 died at boot: $(cat "$WORK/kagent1.log")"
        sleep 1; i=$((i + 1))
    done
    [ -f "$WORK/kagent0.port" ] && [ -f "$WORK/kagent1.port" ] || fail "recovery agents did not bind within ${BOUND}s"
    KA0=$(awk '{print $1 ":" $2}' "$WORK/kagent0.port")
    KA1=$(awk '{print $1 ":" $2}' "$WORK/kagent1.port")
    echo "serve-smoke: recovery agents at $KA0 and $KA1"

    # the journaling gateway (the crash victim) and the never-crashed
    # local-replica CONTROL its outputs are compared against
    JAX_PLATFORMS=cpu $PY -m tony_tpu.cli.gateway --agents "$KA0,$KA1" \
        --serve-batch 4 --port 0 --compile-cache '' \
        --agent-heartbeat 0.2 --agent-lease-misses 3 \
        --journal --history "$WORK/khist" \
        >"$WORK/kgw_boot.log" 2>"$WORK/kgw_stderr.log" &
    KGW_PID=$!
    JAX_PLATFORMS=cpu $PY -m tony_tpu.cli.gateway --demo-model \
        --replicas 1 --serve-batch 4 --port 0 --compile-cache '' \
        >"$WORK/kctrl_boot.log" 2>&1 &
    KCTRL_PID=$!
    KURL=''; KCTRL_URL=''
    i=0
    while [ $i -lt $BOUND ]; do
        KURL=$(sed -n 's/.*gateway at \(http:[^ ]*\).*/\1/p' "$WORK/kgw_boot.log")
        KCTRL_URL=$(sed -n 's/.*gateway at \(http:[^ ]*\).*/\1/p' "$WORK/kctrl_boot.log")
        [ -n "$KURL" ] && [ -n "$KCTRL_URL" ] && break
        kill -0 $KGW_PID 2>/dev/null || fail "recovery gateway died at boot: $(cat "$WORK/kgw_stderr.log")"
        sleep 1; i=$((i + 1))
    done
    [ -n "$KURL" ] && [ -n "$KCTRL_URL" ] || fail "recovery/control gateways did not print URLs within ${BOUND}s"
    echo "serve-smoke: recovery gateway at $KURL (journal under $WORK/khist)"

    # warm both fleets so the SIGKILL lands mid-decode, not mid-compile
    code=$(curl_s "$WORK/kwarm" "$KURL/v1/generate" '{"token_ids": [9, 9], "max_new_tokens": 2}') || fail "recovery warm curl"
    [ "$code" = 200 ] || fail "recovery warm -> $code"
    curl_s "$WORK/kcwarm" "$KCTRL_URL/v1/generate" '{"token_ids": [9, 9], "max_new_tokens": 2}' >/dev/null || fail "recovery control warm curl"

    # 6 in-flight requests (STRING ids — the resume URL carries the id
    # verbatim), then the headline move: SIGKILL the whole gateway
    KPIDS=''
    n=0
    while [ $n -lt 6 ]; do
        curl_s "$WORK/krec_$n" "$KURL/v1/generate" \
            "{\"token_ids\": [$((1 + n)), 2, 3], \"max_new_tokens\": 48, \"id\": \"r$n\"}" \
            >"$WORK/krec_${n}.code" 2>/dev/null &
        KPIDS="$KPIDS $!"
        n=$((n + 1))
    done
    sleep 1
    kill -9 $KGW_PID
    echo "serve-smoke: kill -9 the gateway mid-stream (6 requests in flight)"
    wait $KPIDS 2>/dev/null   # the clients die with the socket — fine
    wait $KGW_PID 2>/dev/null
    KGW_PID=''
    # gateway-liveness grace (0.5s) expires -> the agents park the
    # orphaned sessions; give the watchdog a couple of beats
    sleep 2

    # restart against the SAME history root: --recover replays the WAL
    # left exactly as the crash abandoned it
    JAX_PLATFORMS=cpu $PY -m tony_tpu.cli.gateway --agents "$KA0,$KA1" \
        --serve-batch 4 --port 0 --compile-cache '' \
        --agent-heartbeat 0.2 --agent-lease-misses 3 \
        --journal --history "$WORK/khist" --recover \
        >"$WORK/kgw2_boot.log" 2>"$WORK/kgw2_stderr.log" &
    KGW2_PID=$!
    KURL2=''
    i=0
    while [ $i -lt $BOUND ]; do
        KURL2=$(sed -n 's/.*gateway at \(http:[^ ]*\).*/\1/p' "$WORK/kgw2_boot.log")
        [ -n "$KURL2" ] && break
        kill -0 $KGW2_PID 2>/dev/null || fail "recovered gateway died at boot: $(cat "$WORK/kgw2_stderr.log")"
        sleep 1; i=$((i + 1))
    done
    [ -n "$KURL2" ] || fail "recovered gateway did not come up within ${BOUND}s: $(cat "$WORK/kgw2_stderr.log")"
    grep -q 'recovery: replayed' "$WORK/kgw2_stderr.log" || fail "no WAL replay line on the --recover boot: $(cat "$WORK/kgw2_stderr.log")"
    # the recovery report: all 6 accounted for, at least one session
    # adopted mid-stream (parked KV re-attached, zero re-prefill),
    # none shed
    $PY - "$WORK/kgw2_stderr.log" <<'EOF' || fail "recovery report wrong: $(cat "$WORK/kgw2_stderr.log")"
import re, sys
text = open(sys.argv[1]).read()
m = re.search(r"recovery: (\d+) adopted mid-stream, (\d+) re-run from "
              r"prompt, (\d+) finished results, (\d+) shed", text)
assert m, text
adopted, rerun, finished, shed = map(int, m.groups())
assert adopted >= 1, \
    f"nothing adopted mid-stream ({adopted=} {rerun=} {finished=})"
assert adopted + rerun + finished == 6, (adopted, rerun, finished)
assert shed == 0, f"{shed} journaled request(s) shed during recovery"
EOF
    echo "serve-smoke: $(grep 'adopted mid-stream' "$WORK/kgw2_stderr.log")"

    # every crashed stream resumes on the NEW gateway from offset 0,
    # byte-identical to the gateway that never crashed
    n=0
    while [ $n -lt 6 ]; do
        curl_s "$WORK/kctrl_$n" "$KCTRL_URL/v1/generate" \
            "{\"token_ids\": [$((1 + n)), 2, 3], \"max_new_tokens\": 48, \"id\": \"c$n\"}" \
            >/dev/null || fail "recovery control request $n curl"
        code=$(curl_s "$WORK/kres_$n" "$KURL2/v1/stream/r$n?offset=0") || fail "resume r$n curl"
        [ "$code" = 200 ] || fail "resume r$n -> $code (every journaled request must be resumable)"
        $PY - "$WORK/kres_$n" "$WORK/kctrl_$n" <<'EOF' || fail "resumed stream r$n differs from the never-crashed control"
import json, sys
toks, done = [], None
for line in open(sys.argv[1]):
    line = line.strip()
    if not line:
        continue
    doc = json.loads(line)
    if doc.get("keepalive"):
        continue
    if doc.get("done"):
        done = doc
        break
    assert "error" not in doc, doc
    assert doc["offset"] == len(toks), (doc["offset"], len(toks))
    toks.extend(doc["token_ids"])
assert done is not None, "resume stream ended without a done line"
ctrl = json.load(open(sys.argv[2]))
assert toks == ctrl["token_ids"][3:], (toks, ctrl["token_ids"])
EOF
        n=$((n + 1))
    done

    # zero 5xx after restart + the recovery ledger on /stats
    curl_s "$WORK/kgw2_stats" "$KURL2/stats" >/dev/null || fail "recovered gateway stats curl"
    $PY - "$WORK/kgw2_stats" <<'EOF' || fail "recovered gateway stats wrong: $(cat "$WORK/kgw2_stats")"
import json, sys
stats = json.load(open(sys.argv[1]))
assert stats["shed"] == {}, stats["shed"]    # zero 5xx, whole restart
rec = stats["recovery"]
assert rec["journal"] is True, rec
assert rec["recoveries"] == 1, rec
assert rec["sessions_adopted"] >= 1, rec
assert rec["sessions_adopted"] + rec["sessions_rerun"] \
    + rec["recovered_finished"] == 6, rec
EOF

    # clean drain: gateway exit 0 and the journal compacts to empty
    # (nothing for a NEXT --recover boot to replay), agents drain clean
    kill -TERM $KGW2_PID
    i=0
    while kill -0 $KGW2_PID 2>/dev/null; do
        [ $i -ge $BOUND ] && fail "recovered gateway did not drain within ${BOUND}s"
        sleep 1; i=$((i + 1))
    done
    wait $KGW2_PID; rc=$?
    [ $rc = 0 ] || fail "recovered gateway exited $rc after SIGTERM"
    KGW2_PID=''
    $PY - "$WORK/khist" <<'EOF' || fail "journal did not compact on clean drain"
import sys
from tony_tpu.gateway import journal
path = journal.find_latest(sys.argv[1])
assert path is not None, "no journal left under the history root"
entries = journal.replay(path)
assert entries == {}, \
    f"{len(entries)} entr(ies) survived a clean drain: {sorted(entries)}"
EOF
    kill -TERM $KAGENT0_PID $KAGENT1_PID
    for pid in $KAGENT0_PID $KAGENT1_PID; do
        i=0
        while kill -0 $pid 2>/dev/null; do
            [ $i -ge $BOUND ] && fail "recovery agent did not drain within ${BOUND}s"
            sleep 1; i=$((i + 1))
        done
        wait $pid; rc=$?
        [ $rc = 0 ] || fail "recovery agent exited $rc after SIGTERM"
    done
    KAGENT0_PID=''; KAGENT1_PID=''
    grep -q "agent drained clean" "$WORK/kagent0.log" || fail "recovery agent 0 did not report a clean drain"
    grep -q "agent drained clean" "$WORK/kagent1.log" || fail "recovery agent 1 did not report a clean drain"
    kill -TERM $KCTRL_PID
    wait $KCTRL_PID 2>/dev/null
    KCTRL_PID=''
    echo "serve-smoke: recovery OK (kill -9 the gateway mid-stream -> WAL replayed, parked sessions adopted token-exact, zero 5xx after restart, clean drain compacts the journal)"
}

# ---- bundle round (also standalone: SERVE_SMOKE_ROUNDS=bundle) -------
# ISSUE-15 flight recorder: a live subprocess gateway with --history
# and a synthetic alert (queue_aging threshold 0.05 s against a
# 1-slot replica under a 6-request burst) must dump ONE self-contained
# debug bundle into <job dir>/bundles/ at the firing transition, and
# GET /debug/bundle must serve the same document shape on demand.
bundle_round() {
    JAX_PLATFORMS=cpu $PY -m tony_tpu.cli.gateway --demo-model \
        --replicas 1 --serve-batch 1 --port 0 --compile-cache '' \
        --history "$WORK/bhistory" --alert-queue-wait 0.05 \
        --alert-interval 0.1 \
        >"$WORK/bundle_boot.log" 2>"$WORK/bundle_stderr.log" &
    BGW_PID=$!
    BURL=''
    i=0
    while [ $i -lt $BOUND ]; do
        BURL=$(sed -n 's/.*gateway at \(http:[^ ]*\).*/\1/p' "$WORK/bundle_boot.log")
        [ -n "$BURL" ] && break
        kill -0 $BGW_PID 2>/dev/null || fail "bundle gateway died at boot: $(cat "$WORK/bundle_stderr.log")"
        sleep 1; i=$((i + 1))
    done
    [ -n "$BURL" ] || fail "bundle gateway did not print its URL within ${BOUND}s"
    echo "serve-smoke: bundle gateway at $BURL (queue_aging armed at 0.05s)"

    # 6 concurrent requests at ONE slot: the queue ages past the
    # synthetic threshold while the first request pays its compiles
    BUNDLE_PIDS=''
    n=0
    while [ $n -lt 6 ]; do
        curl_s "$WORK/bundle_$n" "$BURL/v1/generate" \
            "{\"token_ids\": [$((1 + n)), 3], \"max_new_tokens\": 16, \"id\": $n}" \
            >"$WORK/bundle_${n}.code" &
        BUNDLE_PIDS="$BUNDLE_PIDS $!"
        n=$((n + 1))
    done
    wait $BUNDLE_PIDS
    n=0
    while [ $n -lt 6 ]; do
        [ "$(cat "$WORK/bundle_${n}.code")" = 200 ] || fail "bundle round request $n -> $(cat "$WORK/bundle_${n}.code")"
        n=$((n + 1))
    done

    # the firing alert dumped a bundle into the history job dir
    i=0
    while [ $i -lt $BOUND ]; do
        BUNDLE=$(ls "$WORK"/bhistory/intermediate/*/bundles/bundle-*.json 2>/dev/null | head -1)
        [ -n "$BUNDLE" ] && break
        sleep 1; i=$((i + 1))
    done
    [ -n "$BUNDLE" ] || fail "no alert-triggered bundle written under $WORK/bhistory"
    $PY - "$BUNDLE" <<'EOF' || fail "dumped bundle JSON malformed ($BUNDLE)"
import json, sys
doc = json.load(open(sys.argv[1]))
assert doc["reason"] == "alert" and doc["trigger"], doc.get("trigger")
al = doc["alerts"]
assert al["enabled"] and al["fired"].get("queue_aging", 0) >= 1, al
assert doc["replicas"] and "dispatch" in doc["replicas"][0]
assert doc["goodput"]["fleet"], doc["goodput"]
assert "signals" in doc and "supervision" in doc
assert isinstance(doc["traces"]["summaries"], list)
EOF
    echo "serve-smoke: alert-triggered bundle at $BUNDLE"

    # GET /debug/bundle serves the same document shape on demand, and
    # its recorder trail names the dumped file
    code=$(curl_s "$WORK/bundle_live" "$BURL/debug/bundle") || fail "live bundle curl"
    [ "$code" = 200 ] || fail "live bundle -> $code"
    $PY - "$WORK/bundle_live" <<'EOF' || fail "live /debug/bundle malformed"
import json, sys
doc = json.load(open(sys.argv[1]))
assert doc["reason"] == "manual"
assert doc["alerts"]["enabled"] and doc["replicas"]
assert doc["bundles"]["written"] >= 1 and doc["bundles"]["last_path"]
EOF

    kill -TERM $BGW_PID
    i=0
    while kill -0 $BGW_PID 2>/dev/null; do
        [ $i -ge $BOUND ] && fail "bundle gateway did not drain within ${BOUND}s"
        sleep 1; i=$((i + 1))
    done
    wait $BGW_PID; rc=$?
    [ $rc = 0 ] || fail "bundle gateway exited $rc after SIGTERM"
    BGW_PID=''
    echo "serve-smoke: bundle OK (synthetic alert -> one browsable bundle in the job dir, live /debug/bundle consistent)"
}

# ---- autoscale round (also standalone: SERVE_SMOKE_ROUNDS=autoscale) --
# the elastic loop end-to-end on a real subprocess gateway: burst 16
# concurrent requests at a min=1/max=3 fleet with aggressive scaler
# knobs -> every request 200 (zero 5xx), /stats scaler shows >=1
# scale-up with the newcomer PROBE-admitted (supervision.probes/
# rejoins), and once traffic stops the fleet drains back to 1 live
# replica (scale-down rides the zero-loss drain).
autoscale_round() {
    JAX_PLATFORMS=cpu $PY -m tony_tpu.cli.gateway --demo-model \
        --replicas 1 --port 0 --compile-cache '' \
        --autoscale-max 3 --autoscale-min 1 --autoscale-interval 0.2 \
        --autoscale-up-queue 1.5 --autoscale-up-wait 0.5 \
        --autoscale-cooldown-up 0.5 --autoscale-cooldown-down 1 \
        --breaker-base 0.1 --breaker-max 1 \
        >"$WORK/scale_boot.log" 2>"$WORK/scale_stderr.log" &
    SCALE_PID=$!
    SCALE_URL=''
    i=0
    while [ $i -lt $BOUND ]; do
        SCALE_URL=$(sed -n 's/.*gateway at \(http:[^ ]*\).*/\1/p' "$WORK/scale_boot.log")
        [ -n "$SCALE_URL" ] && break
        kill -0 $SCALE_PID 2>/dev/null || fail "autoscale gateway died at boot: $(cat "$WORK/scale_stderr.log")"
        sleep 1; i=$((i + 1))
    done
    [ -n "$SCALE_URL" ] || fail "autoscale gateway did not print its URL within ${BOUND}s"
    echo "serve-smoke: autoscale gateway at $SCALE_URL (min 1 / max 3)"

    SCALE_PIDS=''
    n=0
    while [ $n -lt 16 ]; do
        curl_s "$WORK/scale_$n" "$SCALE_URL/v1/generate" \
            "{\"token_ids\": [$((1 + n % 5)), 2, 3], \"max_new_tokens\": 12, \"id\": $n}" \
            >"$WORK/scale_${n}.code" &
        SCALE_PIDS="$SCALE_PIDS $!"
        n=$((n + 1))
    done
    wait $SCALE_PIDS
    n=0
    while [ $n -lt 16 ]; do
        # the whole point: burst pressure scales, it never 5xxes
        [ "$(cat "$WORK/scale_${n}.code")" = 200 ] || fail "autoscale request $n -> $(cat "$WORK/scale_${n}.code") (burst must scale, not shed)"
        grep -q '"finish_reason"' "$WORK/scale_$n" || fail "autoscale request $n: no finish_reason"
        n=$((n + 1))
    done

    # scale-up must have happened (probe-admitted), then the fleet
    # must drain back to the floor; poll /stats for both
    i=0
    while [ $i -lt $BOUND ]; do
        curl_s "$WORK/scale_stats" "$SCALE_URL/stats" >/dev/null 2>&1
        $PY - "$WORK/scale_stats" <<'EOF' 2>/dev/null && break
import json, sys
s = json.load(open(sys.argv[1]))
sc = s["scaler"]
assert sc["scale_ups"] >= 1
assert s["supervision"]["probes"] >= 1 and s["supervision"]["rejoins"] >= 1
assert sc["replicas_live"] == 1  # drained back to the floor
assert sc["scale_downs"] >= 1
EOF
        sleep 1; i=$((i + 1))
    done
    $PY - "$WORK/scale_stats" <<'EOF' || fail "autoscale stats never converged: $(cat "$WORK/scale_stats")"
import json, sys
s = json.load(open(sys.argv[1]))
sc = s["scaler"]
assert sc["scale_ups"] >= 1, sc
assert s["supervision"]["probes"] >= 1 and s["supervision"]["rejoins"] >= 1, \
    s["supervision"]
assert sc["replicas_live"] == 1, sc   # back at the floor
assert sc["scale_downs"] >= 1, sc
assert s["completed"] == 16, s["completed"]
assert s["shed"] == {}, s["shed"]     # zero 5xx across the whole round
EOF

    kill -TERM $SCALE_PID
    i=0
    while kill -0 $SCALE_PID 2>/dev/null; do
        [ $i -ge $BOUND ] && fail "autoscale gateway did not drain within ${BOUND}s of SIGTERM"
        sleep 1; i=$((i + 1))
    done
    wait $SCALE_PID
    rc=$?
    [ $rc = 0 ] || fail "autoscale gateway exited $rc after SIGTERM"
    SCALE_PID=''
    echo "serve-smoke: autoscale OK (burst -> scale-up probe-admitted, zero 5xx, drained to floor)"
}

# ---- goodput/alerts round (also standalone: SERVE_SMOKE_ROUNDS=goodput)
# ISSUE-10 acceptance: a deliberately tiny KV page pool (6 pages x 8
# tokens vs 4 slots wanting 40+ token lifetimes) under concurrent load
# must fire a kv_pages_pressure alert — visible in /stats alerts, in
# history metrics/alerts.jsonl, and on the portal's metrics page —
# then RESOLVE once load stops; /debug/goodput must name a largest
# waste bucket on the live subprocess gateway.
goodput_round() {
    GHIST="$WORK/ghistory"
    JAX_PLATFORMS=cpu $PY -m tony_tpu.cli.gateway --demo-model \
        --replicas 1 --port 0 --compile-cache '' \
        --kv-page-size 8 --kv-pages 6 --prefix-cache-mb 0 \
        --history "$GHIST" --alert-interval 0.2 \
        >"$WORK/gp_boot.log" 2>"$WORK/gp_stderr.log" &
    GP_PID=$!
    GP_URL=''
    i=0
    while [ $i -lt $BOUND ]; do
        GP_URL=$(sed -n 's/.*gateway at \(http:[^ ]*\).*/\1/p' "$WORK/gp_boot.log")
        [ -n "$GP_URL" ] && break
        kill -0 $GP_PID 2>/dev/null || fail "goodput gateway died at boot: $(cat "$WORK/gp_stderr.log")"
        sleep 1; i=$((i + 1))
    done
    [ -n "$GP_URL" ] || fail "goodput gateway did not print its URL within ${BOUND}s"
    echo "serve-smoke: goodput gateway at $GP_URL (6x8-token KV pool)"

    # 6 concurrent 40-token requests: the pool holds ~one lifetime at
    # a time, so reservation pressure is sustained while the rest wait
    GP_PIDS=''
    n=0
    while [ $n -lt 6 ]; do
        curl_s "$WORK/gp_$n" "$GP_URL/v1/generate" \
            "{\"token_ids\": [$((1 + n)), 2, 3], \"max_new_tokens\": 40, \"id\": $n}" \
            >"$WORK/gp_${n}.code" &
        GP_PIDS="$GP_PIDS $!"
        n=$((n + 1))
    done
    # poll /stats WHILE the load is in flight: the alert must show up
    # live, not post-hoc
    FIRED=''
    i=0
    while [ $i -lt $BOUND ]; do
        curl_s "$WORK/gp_stats" "$GP_URL/stats" >/dev/null 2>&1
        $PY - "$WORK/gp_stats" <<'EOF' 2>/dev/null && { FIRED=1; break; }
import json, sys
s = json.load(open(sys.argv[1]))
assert any(a["alert"] == "kv_pages_pressure"
           for a in s["alerts"]["active"])
EOF
        sleep 1; i=$((i + 1))
    done
    wait $GP_PIDS
    [ -n "$FIRED" ] || fail "kv_pages_pressure never fired in /stats alerts: $(cat "$WORK/gp_stats")"
    n=0
    while [ $n -lt 6 ]; do
        [ "$(cat "$WORK/gp_${n}.code")" = 200 ] || fail "goodput request $n -> $(cat "$WORK/gp_${n}.code") (pool pressure must queue, not 5xx)"
        n=$((n + 1))
    done

    # load stopped -> the alert must RESOLVE (active empties)
    i=0
    while [ $i -lt $BOUND ]; do
        curl_s "$WORK/gp_stats2" "$GP_URL/stats" >/dev/null 2>&1
        $PY - "$WORK/gp_stats2" <<'EOF' 2>/dev/null && break
import json, sys
s = json.load(open(sys.argv[1]))
assert not s["alerts"]["active"]
assert s["alerts"]["resolved"].get("kv_pages_pressure", 0) >= 1
EOF
        sleep 1; i=$((i + 1))
    done
    $PY - "$WORK/gp_stats2" <<'EOF' || fail "kv_pages_pressure never resolved: $(cat "$WORK/gp_stats2")"
import json, sys
s = json.load(open(sys.argv[1]))
assert not s["alerts"]["active"], s["alerts"]["active"]
assert s["alerts"]["resolved"].get("kv_pages_pressure", 0) >= 1, \
    s["alerts"]["resolved"]
EOF

    # /debug/goodput on the live gateway: ledger sums <= 1 and a
    # largest waste bucket is NAMED
    code=$(curl_s "$WORK/gp_goodput" "$GP_URL/debug/goodput") || fail "goodput curl"
    [ "$code" = 200 ] || fail "debug/goodput -> $code"
    $PY - "$WORK/gp_goodput" <<'EOF' || fail "/debug/goodput report wrong: $(cat "$WORK/gp_goodput")"
import json, sys
doc = json.load(open(sys.argv[1]))
assert doc["enabled"], doc
assert doc["largest_waste"] in ("compile", "padding", "overshoot",
                                "spec_rejected", "idle"), doc
total = sum(doc["fleet"]["buckets"].values())
assert total <= 1.0 + 1e-6, total
assert doc["fleet"]["buckets"].get("useful.decode", 0) > 0, doc["fleet"]
EOF

    # drain; the history job closes with alerts.jsonl on disk
    kill -TERM $GP_PID
    i=0
    while kill -0 $GP_PID 2>/dev/null; do
        [ $i -ge $BOUND ] && fail "goodput gateway did not drain within ${BOUND}s of SIGTERM"
        sleep 1; i=$((i + 1))
    done
    wait $GP_PID
    rc=$?
    [ $rc = 0 ] || fail "goodput gateway exited $rc after SIGTERM"
    GP_PID=''

    ALERTS_JSONL=$(ls "$GHIST"/intermediate/*/metrics/alerts.jsonl 2>/dev/null | head -1)
    [ -n "$ALERTS_JSONL" ] || fail "no metrics/alerts.jsonl written under $GHIST"
    $PY - "$ALERTS_JSONL" <<'EOF' || fail "alerts.jsonl rows wrong: $(cat "$ALERTS_JSONL")"
import json, sys
rows = [json.loads(ln) for ln in open(sys.argv[1]) if ln.strip()]
states = {(r["alert"], r["state"]) for r in rows}
assert ("kv_pages_pressure", "firing") in states, states
assert ("kv_pages_pressure", "resolved") in states, states
EOF

    # the portal renders alerts.jsonl next to requests.jsonl: boot it
    # on the history dir and fetch the job's metrics page
    APP_ID=$(ls "$GHIST/intermediate" | head -1)
    [ -n "$APP_ID" ] || fail "no history job dir under $GHIST"
    $PY -m tony_tpu.portal --history "$GHIST" --port 0 \
        >"$WORK/portal_boot.log" 2>&1 &
    PORTAL_PID=$!
    PORTAL_URL=''
    i=0
    while [ $i -lt $BOUND ]; do
        # head -1: the URL prints twice (log.info on stderr + the
        # stdout banner), and sed would hand curl both lines
        PORTAL_URL=$(sed -n 's/.*portal at \(http[s]*:[^ ]*\).*/\1/p' "$WORK/portal_boot.log" | head -1)
        [ -n "$PORTAL_URL" ] && break
        kill -0 $PORTAL_PID 2>/dev/null || fail "portal died at boot: $(cat "$WORK/portal_boot.log")"
        sleep 1; i=$((i + 1))
    done
    [ -n "$PORTAL_URL" ] || fail "portal did not print its URL within ${BOUND}s"
    code=$(curl_s "$WORK/portal_metrics" "$PORTAL_URL/job/$APP_ID/metrics") || fail "portal metrics curl"
    [ "$code" = 200 ] || fail "portal metrics page -> $code"
    grep -q 'alerts' "$WORK/portal_metrics" || fail "portal metrics page has no alerts section"
    grep -q 'kv_pages_pressure' "$WORK/portal_metrics" || fail "portal metrics page does not show the alert rows"
    kill $PORTAL_PID 2>/dev/null
    wait $PORTAL_PID 2>/dev/null
    PORTAL_PID=''
    echo "serve-smoke: goodput OK (kv_pages_pressure fired + resolved, alerts.jsonl + portal render, /debug/goodput names largest waste)"
}

# ---- disagg round (also standalone: SERVE_SMOKE_ROUNDS=disagg) -------
# ISSUE-12: disaggregated prefill/decode end-to-end on a real
# subprocess gateway. --roles prefill=1,decode=1 with chunked prefill
# (16-token budget vs a 40-token prompt -> 3 chunks), a deliberately
# tiny per-replica prefix store (~2 entries) so distinct prompts evict
# each other into the --kv-host-mb host tier, and exact repeats page
# back in. Mixed long-prompt/short-chat traffic: zero 5xx, every
# output token-exact vs a single-pool control gateway, /stats shows
# kv_host.page_ins > 0 and at least one multi-chunk prefill.
disagg_round() {
    JAX_PLATFORMS=cpu $PY -m tony_tpu.cli.gateway --demo-model \
        --roles prefill=1,decode=1 --prefill-chunk-tokens 16 \
        --kv-page-size 8 --prefix-cache-mb 0.03 --kv-host-mb 4 \
        --port 0 --compile-cache '' \
        >"$WORK/disagg_boot.log" 2>"$WORK/disagg_stderr.log" &
    DGW_PID=$!
    JAX_PLATFORMS=cpu $PY -m tony_tpu.cli.gateway --demo-model \
        --replicas 1 --port 0 --compile-cache '' --kv-page-size 8 \
        >"$WORK/dctrl_boot.log" 2>"$WORK/dctrl_stderr.log" &
    DCTRL_PID=$!
    DURL=''; DCTRL_URL=''
    i=0
    while [ $i -lt $BOUND ]; do
        DURL=$(sed -n 's/.*gateway at \(http:[^ ]*\).*/\1/p' "$WORK/disagg_boot.log")
        DCTRL_URL=$(sed -n 's/.*gateway at \(http:[^ ]*\).*/\1/p' "$WORK/dctrl_boot.log")
        [ -n "$DURL" ] && [ -n "$DCTRL_URL" ] && break
        kill -0 $DGW_PID 2>/dev/null || fail "disagg gateway died at boot: $(cat "$WORK/disagg_stderr.log")"
        kill -0 $DCTRL_PID 2>/dev/null || fail "disagg control died at boot: $(cat "$WORK/dctrl_stderr.log")"
        sleep 1; i=$((i + 1))
    done
    [ -n "$DURL" ] && [ -n "$DCTRL_URL" ] || fail "disagg gateways did not print URLs within ${BOUND}s"
    echo "serve-smoke: disagg gateway at $DURL (prefill=1,decode=1, chunk 16, host tier 4 MB; control at $DCTRL_URL)"

    # mixed traffic, CONCURRENT against the disagg gateway: one long
    # prompt (3 chunks), short chats riding between its chunks, three
    # distinct shared-shape prompts that churn the tiny store into the
    # host tier, then exact repeats that page back in
    LONG='1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16, 17, 18, 19, 20, 21, 22, 23, 24, 25, 26, 27, 28, 29, 30, 31, 32, 33, 34, 35, 36, 37, 38, 39, 40'
    P1='41, 42, 43, 44, 45, 46, 47, 48, 49, 50, 51, 52, 53, 54, 55, 56, 41, 42, 43, 44, 45, 46, 47, 48'
    P2='2, 4, 6, 8, 10, 12, 14, 16, 18, 20, 22, 24, 26, 28, 30, 32, 34, 36, 38, 40, 42, 44, 46, 48'
    P3='3, 5, 7, 9, 11, 13, 15, 17, 19, 21, 23, 25, 27, 29, 31, 33, 35, 37, 39, 41, 43, 45, 47, 49'
    SHORT1='61, 62, 63'
    SHORT2='9, 8, 7'
    n=0
    DISAGG_PIDS=''
    for BODY in "$LONG" "$SHORT1" "$SHORT2"; do
        curl_s "$WORK/disagg_$n" "$DURL/v1/generate" \
            "{\"token_ids\": [$BODY], \"max_new_tokens\": 6, \"id\": $n}" \
            >"$WORK/disagg_${n}.code" &
        DISAGG_PIDS="$DISAGG_PIDS $!"
        n=$((n + 1))
    done
    wait $DISAGG_PIDS
    # store-churn phase, sequential (deterministic spill/page-in)
    for BODY in "$P1" "$P2" "$P3" "$P1" "$P2"; do
        code=$(curl_s "$WORK/disagg_$n" "$DURL/v1/generate" \
            "{\"token_ids\": [$BODY], \"max_new_tokens\": 6, \"id\": $n}") \
            || fail "disagg request $n curl"
        [ "$code" = 200 ] || fail "disagg request $n -> $code"
        n=$((n + 1))
    done
    N_REQ=$n
    n=0
    for BODY in "$LONG" "$SHORT1" "$SHORT2" "$P1" "$P2" "$P3" "$P1" "$P2"; do
        [ -f "$WORK/disagg_${n}.code" ] && \
            { [ "$(cat "$WORK/disagg_${n}.code")" = 200 ] || fail "disagg request $n -> $(cat "$WORK/disagg_${n}.code")"; }
        code=$(curl_s "$WORK/dctrl_$n" "$DCTRL_URL/v1/generate" \
            "{\"token_ids\": [$BODY], \"max_new_tokens\": 6, \"id\": $n}") \
            || fail "disagg control $n curl"
        [ "$code" = 200 ] || fail "disagg control $n -> $code"
        $PY - "$WORK/disagg_$n" "$WORK/dctrl_$n" <<'EOF' || fail "disagg request $n: output differs from single-pool control"
import json, sys
a = json.load(open(sys.argv[1]))
b = json.load(open(sys.argv[2]))
assert a["token_ids"] == b["token_ids"], (a["token_ids"], b["token_ids"])
EOF
        n=$((n + 1))
    done

    code=$(curl_s "$WORK/disagg_stats" "$DURL/stats") || fail "disagg stats curl"
    [ "$code" = 200 ] || fail "disagg stats -> $code"
    $PY - "$WORK/disagg_stats" "$N_REQ" <<'EOF' || fail "disagg stats wrong: $(cat "$WORK/disagg_stats")"
import json, sys
stats = json.load(open(sys.argv[1]))
n = int(sys.argv[2])
assert stats["completed"] == n, stats["completed"]
assert stats["shed"] == {}, stats["shed"]          # zero 5xx
routing = stats["routing"]
assert routing["handoffs"] == n, routing           # every request crossed pools
assert routing["roles"] == {"0": "prefill", "1": "decode"}, routing
eng = stats["engine"]
assert eng["prefill_chunks"]["enabled"], eng["prefill_chunks"]
assert eng["prefill_chunks"]["requests"] >= 1, eng["prefill_chunks"]
assert eng["prefill_chunks"]["dispatches"] >= 2, eng["prefill_chunks"]
kvh = eng["kv_host"]
assert kvh["enabled"], kvh
assert kvh["spills"] > 0, kvh                      # store churned into the tier
assert kvh["page_ins"] > 0, kvh                    # repeats paged back in
rows = {r["replica"]: r for r in stats["replicas"]}
assert rows[1]["prefills"] == 0, rows[1]           # decode pool never prefills
assert rows[0]["handoffs_out"] == n and rows[1]["handoffs_in"] == n, rows
assert "prefix" in rows[0] and rows[0]["prefix"]["nodes"] >= 1, rows[0]
EOF
    curl_s "$WORK/disagg_metrics" "$DURL/metrics" >/dev/null 2>&1
    grep -q 'tony_kv_host_page_ins_total' "$WORK/disagg_metrics" || fail "no tony_kv_host_* on /metrics"
    grep -q 'tony_handoffs_total' "$WORK/disagg_metrics" || fail "no tony_handoffs_total on /metrics"

    kill -TERM $DGW_PID $DCTRL_PID
    for P in $DGW_PID $DCTRL_PID; do
        i=0
        while kill -0 $P 2>/dev/null; do
            [ $i -ge $BOUND ] && fail "disagg gateway did not drain within ${BOUND}s of SIGTERM"
            sleep 1; i=$((i + 1))
        done
    done
    wait $DGW_PID; rc=$?
    [ $rc = 0 ] || fail "disagg gateway exited $rc after SIGTERM"
    DGW_PID=''
    DCTRL_PID=''
    echo "serve-smoke: disagg OK (role split + chunked prefill + host tier, zero 5xx, token-exact vs single-pool control)"
}

# ---- autotune round (also standalone: SERVE_SMOKE_ROUNDS=autotune) ---
# ISSUE-13: the ledger-driven adaptive shape controller on a real
# subprocess gateway. Boots with chunk-steps 1 (the streaming floor)
# and --autotune at a fast tick; mixed traffic gives the controller a
# clean-overshoot ledger, so it must grow chunk_steps (>= 1
# actuation), with zero 5xx, every output token-exact vs a static
# control gateway, the decision visible in /stats engine.autotune and
# history metrics/autotune.jsonl, and the controller CONVERGED by the
# time traffic stops.
autotune_round() {
    JAX_PLATFORMS=cpu $PY -m tony_tpu.cli.gateway --demo-model \
        --replicas 1 --chunk-steps 1 --autotune \
        --autotune-interval 0.1 --autotune-hold 1 \
        --autotune-cooldown 0 --autotune-chunk-max 16 \
        --history "$WORK/at_history" \
        --port 0 --compile-cache '' \
        >"$WORK/at_boot.log" 2>"$WORK/at_stderr.log" &
    AT_PID=$!
    JAX_PLATFORMS=cpu $PY -m tony_tpu.cli.gateway --demo-model \
        --replicas 1 --chunk-steps 1 --port 0 --compile-cache '' \
        >"$WORK/atctrl_boot.log" 2>"$WORK/atctrl_stderr.log" &
    ATCTRL_PID=$!
    ATURL=''; ATCTRL_URL=''
    i=0
    while [ $i -lt $BOUND ]; do
        ATURL=$(sed -n 's/.*gateway at \(http:[^ ]*\).*/\1/p' "$WORK/at_boot.log")
        ATCTRL_URL=$(sed -n 's/.*gateway at \(http:[^ ]*\).*/\1/p' "$WORK/atctrl_boot.log")
        [ -n "$ATURL" ] && [ -n "$ATCTRL_URL" ] && break
        kill -0 $AT_PID 2>/dev/null || fail "autotune gateway died at boot: $(cat "$WORK/at_stderr.log")"
        kill -0 $ATCTRL_PID 2>/dev/null || fail "autotune control died at boot: $(cat "$WORK/atctrl_stderr.log")"
        sleep 1; i=$((i + 1))
    done
    [ -n "$ATURL" ] && [ -n "$ATCTRL_URL" ] || fail "autotune gateways did not print URLs within ${BOUND}s"
    echo "serve-smoke: autotune gateway at $ATURL (chunk-steps 1, controller armed; control at $ATCTRL_URL)"

    # mixed greedy traffic in waves: enough steady decode rounds for
    # the controller to judge and actuate between waves
    n=0
    wave=0
    while [ $wave -lt 6 ]; do
        for BODY in "1, 2, 3, $wave" "5, 9, $wave" "17, 46, 10, 20, $wave"; do
            code=$(curl_s "$WORK/at_$n" "$ATURL/v1/generate" \
                "{\"token_ids\": [$BODY], \"max_new_tokens\": 24, \"id\": $n}") \
                || fail "autotune request $n curl"
            [ "$code" = 200 ] || fail "autotune request $n -> $code"
            n=$((n + 1))
        done
        wave=$((wave + 1))
    done
    N_REQ=$n
    # token-exactness vs the static control: an actuation must never
    # change a single output token
    n=0
    wave=0
    while [ $wave -lt 6 ]; do
        for BODY in "1, 2, 3, $wave" "5, 9, $wave" "17, 46, 10, 20, $wave"; do
            code=$(curl_s "$WORK/atctrl_$n" "$ATCTRL_URL/v1/generate" \
                "{\"token_ids\": [$BODY], \"max_new_tokens\": 24, \"id\": $n}") \
                || fail "autotune control $n curl"
            [ "$code" = 200 ] || fail "autotune control $n -> $code"
            $PY - "$WORK/at_$n" "$WORK/atctrl_$n" <<'EOF' || fail "autotune request $n: output differs from static control"
import json, sys
a = json.load(open(sys.argv[1]))
b = json.load(open(sys.argv[2]))
assert a["token_ids"] == b["token_ids"], (a["token_ids"], b["token_ids"])
EOF
            n=$((n + 1))
        done
        wave=$((wave + 1))
    done

    # give the controller a few idle ticks to settle, then assert
    sleep 2
    code=$(curl_s "$WORK/at_stats" "$ATURL/stats") || fail "autotune stats curl"
    [ "$code" = 200 ] || fail "autotune stats -> $code"
    $PY - "$WORK/at_stats" "$N_REQ" <<'EOF' || fail "autotune stats wrong: $(cat "$WORK/at_stats")"
import json, sys
stats = json.load(open(sys.argv[1]))
n = int(sys.argv[2])
assert stats["completed"] == n, stats["completed"]
assert stats["shed"] == {}, stats["shed"]          # zero 5xx
auto = stats["engine"]["autotune"]
assert auto["enabled"], auto
assert auto["actuations_total"] >= 1, auto         # the controller acted
assert auto["actuations"].get("chunk_steps", 0) >= 1, auto
assert auto["replicas"]["0"]["chunk_steps"] > 1, auto
assert auto["converged"], auto                     # and went quiet
row = auto["recent"][-1]
assert {"knob", "from", "to", "reason", "new_compile"} <= set(row), row
EOF
    curl_s "$WORK/at_metrics" "$ATURL/metrics" >/dev/null 2>&1
    grep -q 'tony_autotune_enabled 1' "$WORK/at_metrics" || fail "no tony_autotune_enabled on /metrics"
    grep -q 'tony_autotune_actuations_total{knob="chunk_steps"}' "$WORK/at_metrics" || fail "no tony_autotune_actuations_total on /metrics"
    AT_JSONL=$(find "$WORK/at_history" -name autotune.jsonl | head -1)
    [ -n "$AT_JSONL" ] || fail "no metrics/autotune.jsonl in the history dir"
    $PY - "$AT_JSONL" <<'EOF' || fail "autotune.jsonl malformed: $(cat "$AT_JSONL")"
import json, sys
rows = [json.loads(ln) for ln in open(sys.argv[1]) if ln.strip()]
assert rows, "no actuation rows"
assert rows[0]["knob"] == "chunk_steps", rows[0]
assert {"from", "to", "reason", "signals", "new_compile"} <= set(rows[0])
EOF

    kill -TERM $AT_PID $ATCTRL_PID
    for P in $AT_PID $ATCTRL_PID; do
        i=0
        while kill -0 $P 2>/dev/null; do
            [ $i -ge $BOUND ] && fail "autotune gateway did not drain within ${BOUND}s of SIGTERM"
            sleep 1; i=$((i + 1))
        done
    done
    wait $AT_PID; rc=$?
    [ $rc = 0 ] || fail "autotune gateway exited $rc after SIGTERM"
    AT_PID=''
    ATCTRL_PID=''
    echo "serve-smoke: autotune OK (>=1 actuation, converged, zero 5xx, token-exact vs static control)"
}

# ---- shard round (also standalone: SERVE_SMOKE_ROUNDS=shard) ---------
# ISSUE-14: tensor-sharded replicas. A --mesh 4 gateway on 4 virtual
# CPU devices (demo model: 4 heads -> params shard on output dims, KV
# page pools shard 4-way on the kv-head axis) under mixed greedy /
# sampled / prefix-repeat / streaming traffic must produce
# byte-identical outputs to a single-device control gateway, report
# the mesh topology + per-chip pricing on /stats engine.mesh, and
# export tony_mesh_* on /metrics.
shard_round() {
    XLA_FLAGS="--xla_force_host_platform_device_count=4" \
    JAX_PLATFORMS=cpu $PY -m tony_tpu.cli.gateway --demo-model \
        --replicas 1 --mesh 4 --speculate-k 4 --prefix-cache-mb 1 \
        --port 0 --compile-cache '' \
        >"$WORK/shard_boot.log" 2>"$WORK/shard_stderr.log" &
    SHGW_PID=$!
    JAX_PLATFORMS=cpu $PY -m tony_tpu.cli.gateway --demo-model \
        --replicas 1 --speculate-k 4 --prefix-cache-mb 1 \
        --port 0 --compile-cache '' \
        >"$WORK/shctrl_boot.log" 2>"$WORK/shctrl_stderr.log" &
    SHCTRL_PID=$!
    SHURL=''; SHCTRL_URL=''
    i=0
    while [ $i -lt $BOUND ]; do
        SHURL=$(sed -n 's/.*gateway at \(http:[^ ]*\).*/\1/p' "$WORK/shard_boot.log")
        SHCTRL_URL=$(sed -n 's/.*gateway at \(http:[^ ]*\).*/\1/p' "$WORK/shctrl_boot.log")
        [ -n "$SHURL" ] && [ -n "$SHCTRL_URL" ] && break
        kill -0 $SHGW_PID 2>/dev/null || fail "shard gateway died at boot: $(cat "$WORK/shard_stderr.log")"
        kill -0 $SHCTRL_PID 2>/dev/null || fail "shard control died at boot: $(cat "$WORK/shctrl_stderr.log")"
        sleep 1; i=$((i + 1))
    done
    [ -n "$SHURL" ] && [ -n "$SHCTRL_URL" ] || fail "shard gateways did not print URLs within ${BOUND}s"
    echo "serve-smoke: shard gateway at $SHURL (mesh 4 over virtual devices; control at $SHCTRL_URL)"

    # mixed traffic against BOTH gateways: greedy, seeded sampling, a
    # repeat that must hit the prefix store, a repetitive prompt the
    # drafter speculates on — every output must be byte-identical
    REQ0='{"token_ids": [1, 2, 3, 4, 5], "max_new_tokens": 12, "id": 0}'
    REQ1='{"token_ids": [3, 1, 4, 1, 5, 9], "max_new_tokens": 10, "temperature": 0.8, "top_k": 8, "seed": 123, "id": 1}'
    REQ2='{"token_ids": [1, 2, 3, 4, 5], "max_new_tokens": 12, "id": 2}'
    REQ3='{"token_ids": [7, 8, 9, 7, 8, 9, 7, 8, 9, 7, 8], "max_new_tokens": 10, "id": 3}'
    n=0
    for BODY in "$REQ0" "$REQ1" "$REQ2" "$REQ3"; do
        code=$(curl_s "$WORK/shard_$n" "$SHURL/v1/generate" "$BODY") \
            || fail "shard request $n curl"
        [ "$code" = 200 ] || fail "shard request $n -> $code"
        code=$(curl_s "$WORK/shctrl_$n" "$SHCTRL_URL/v1/generate" "$BODY") \
            || fail "shard control $n curl"
        [ "$code" = 200 ] || fail "shard control $n -> $code"
        $PY - "$WORK/shard_$n" "$WORK/shctrl_$n" <<'EOF' || fail "shard request $n: output differs from single-device control"
import json, sys
a = json.load(open(sys.argv[1]))
b = json.load(open(sys.argv[2]))
assert a["token_ids"] == b["token_ids"], (a["token_ids"], b["token_ids"])
EOF
        n=$((n + 1))
    done
    N_REQ=$n
    # one streamed request: the NDJSON deltas must reassemble to the
    # same token stream on both gateways
    STREAM_REQ='{"token_ids": [9, 8, 7, 6], "max_new_tokens": 8, "stream": true, "id": 9}'
    code=$(curl_s "$WORK/shard_stream" "$SHURL/v1/generate" "$STREAM_REQ") || fail "shard stream curl"
    [ "$code" = 200 ] || fail "shard stream -> $code"
    code=$(curl_s "$WORK/shctrl_stream" "$SHCTRL_URL/v1/generate" "$STREAM_REQ") || fail "shard control stream curl"
    [ "$code" = 200 ] || fail "shard control stream -> $code"
    $PY - "$WORK/shard_stream" "$WORK/shctrl_stream" <<'EOF' || fail "shard stream differs from single-device control"
import json, sys
def toks(path):
    out = []
    for ln in open(path):
        if ln.strip():
            out.extend(json.loads(ln).get("token_ids", []))
    return out
a, b = toks(sys.argv[1]), toks(sys.argv[2])
assert a and a == b, (a, b)
EOF

    code=$(curl_s "$WORK/shard_stats" "$SHURL/stats") || fail "shard stats curl"
    [ "$code" = 200 ] || fail "shard stats -> $code"
    $PY - "$WORK/shard_stats" "$N_REQ" <<'EOF' || fail "shard stats wrong: $(cat "$WORK/shard_stats")"
import json, sys
stats = json.load(open(sys.argv[1]))
assert stats["completed"] == int(sys.argv[2]) + 1, stats["completed"]
assert stats["shed"] == {}, stats["shed"]          # zero 5xx
mesh = stats["engine"]["mesh"]
assert mesh["enabled"], mesh
assert mesh["devices"] == 4, mesh
assert mesh["kv_shards"] == 4, mesh                # pools split 4-way
assert mesh["topology"] == {"tensor": 4}, mesh
assert mesh["param_bytes_per_chip"] > 0, mesh
row = stats["replicas"][0]
assert row["mesh"]["param_bytes_per_chip"] \
    < row["mesh"]["param_bytes_total"], row["mesh"]  # per-chip pricing
assert row["prefix_hits"] >= 1, row                # the repeat hit
EOF
    curl_s "$WORK/shard_metrics" "$SHURL/metrics" >/dev/null 2>&1
    grep -q 'tony_mesh_enabled 1' "$WORK/shard_metrics" || fail "no tony_mesh_enabled on /metrics"
    grep -q 'tony_mesh_devices 4' "$WORK/shard_metrics" || fail "no tony_mesh_devices on /metrics"
    grep -q 'tony_mesh_kv_shards 4' "$WORK/shard_metrics" || fail "no tony_mesh_kv_shards on /metrics"

    kill -TERM $SHGW_PID $SHCTRL_PID
    for P in $SHGW_PID $SHCTRL_PID; do
        i=0
        while kill -0 $P 2>/dev/null; do
            [ $i -ge $BOUND ] && fail "shard gateway did not drain within ${BOUND}s of SIGTERM"
            sleep 1; i=$((i + 1))
        done
    done
    wait $SHGW_PID; rc=$?
    [ $rc = 0 ] || fail "shard gateway exited $rc after SIGTERM"
    SHGW_PID=''
    SHCTRL_PID=''
    echo "serve-smoke: shard OK (mesh=4 replica byte-identical to single-device control, topology + per-chip pricing on /stats)"
}

# ---- storm round (also standalone: SERVE_SMOKE_ROUNDS=storm) ---------
# ISSUE-16: the event-driven edge under a connection storm. One
# gateway subprocess behind GatewayEdge; tools/storm.py first parks
# 500 idle keep-alive connections (per-connection memory cost), then
# fires 2000 concurrent NDJSON streams in bursts. Gates: every stream
# completes 200 (zero shed, zero unintentional 5xx), token-exact spot
# checks vs unary controls, the edge stats block on /stats and
# tony_edge_* series on /metrics, then a clean SIGTERM drain.
storm_round() {
    JAX_PLATFORMS=cpu $PY -m tony_tpu.cli.gateway --demo-model \
        --serve-batch 64 --chunk-steps 4 \
        --max-queue 4096 --max-pending 4096 \
        --port 0 --compile-cache '' \
        >"$WORK/storm_boot.log" 2>"$WORK/storm_stderr.log" &
    STGW_PID=$!
    STURL=''
    i=0
    while [ $i -lt $BOUND ]; do
        STURL=$(sed -n 's/.*gateway at \(http:[^ ]*\).*/\1/p' "$WORK/storm_boot.log")
        [ -n "$STURL" ] && break
        kill -0 $STGW_PID 2>/dev/null || fail "storm gateway died at boot: $(cat "$WORK/storm_stderr.log")"
        sleep 1; i=$((i + 1))
    done
    [ -n "$STURL" ] || fail "storm gateway did not print URL within ${BOUND}s"
    echo "serve-smoke: storm gateway at $STURL (event edge)"

    timeout -k 10 "$BOUND" $PY tools/storm.py --base "$STURL" \
        --idle 500 --streams 2000 --tokens 4 --bursts 10 \
        --burst-gap 0.1 --check 8 --server-pid $STGW_PID \
        --timeout "$BOUND" --json "$WORK/storm.json" \
        >"$WORK/storm_out.log" 2>&1 \
        || fail "storm.py failed: $(tail -5 "$WORK/storm_out.log")"
    $PY - "$WORK/storm.json" <<'EOF' || fail "storm gates: $(cat "$WORK/storm.json")"
import json, sys
doc = json.load(open(sys.argv[1]))
idle, st = doc["idle"], doc["storm"]
assert idle["opened"] == 500, idle
assert idle["connect_errors"] == 0, idle
assert st["launched"] == 2000, st
assert st["completed_200"] == 2000, st       # every stream finished
assert st["shed"] == 0, st                   # no 429/503 at this scale
assert st["errors"] == 0, st                 # zero unintentional 5xx
assert st["tokens_checked"] > 0, st
assert st["tokens_exact"] == st["tokens_checked"], st
edge = st["edge"]
assert edge["kind"] == "event", edge
assert edge["slow_client_aborts"] == 0, edge
assert edge["conn_limit_sheds"] == 0, edge
EOF

    code=$(curl_s "$WORK/storm_stats" "$STURL/stats") || fail "storm stats curl"
    [ "$code" = 200 ] || fail "storm stats -> $code"
    $PY - "$WORK/storm_stats" <<'EOF' || fail "no edge block on /stats: $(cat "$WORK/storm_stats")"
import json, sys
stats = json.load(open(sys.argv[1]))
edge = stats["edge"]
assert edge["kind"] == "event", edge
assert edge["requests"] >= 2000, edge
assert edge["accepts"] >= 2500, edge         # idle conns + streams
EOF
    curl_s "$WORK/storm_metrics" "$STURL/metrics" >/dev/null 2>&1
    grep -q 'tony_edge_threads ' "$WORK/storm_metrics" || fail "no tony_edge_threads on /metrics"
    grep -q 'tony_edge_accepts_total ' "$WORK/storm_metrics" || fail "no tony_edge_accepts_total on /metrics"
    grep -q 'tony_edge_slow_client_aborts_total 0' "$WORK/storm_metrics" || fail "no tony_edge_slow_client_aborts_total on /metrics"

    kill -TERM $STGW_PID
    i=0
    while kill -0 $STGW_PID 2>/dev/null; do
        [ $i -ge $BOUND ] && fail "storm gateway did not drain within ${BOUND}s of SIGTERM"
        sleep 1; i=$((i + 1))
    done
    wait $STGW_PID; rc=$?
    [ $rc = 0 ] || fail "storm gateway exited $rc after SIGTERM"
    grep -q 'drained clean' "$WORK/storm_stderr.log" || fail "storm gateway did not report a clean drain"
    STGW_PID=''

    # overload sub-phase (this PR): a deliberately TINY gateway (2
    # slots, queue 8) takes a burst it cannot absorb — capacity sheds
    # storm, and the shed_storm alert rule must actually page (before
    # this rule, a 429 storm moved /stats and the autoscaler but never
    # the alert bus) while the streams that DID land keep completing
    JAX_PLATFORMS=cpu $PY -m tony_tpu.cli.gateway --demo-model \
        --serve-batch 2 --chunk-steps 4 --max-queue 8 --max-pending 8 \
        --alert-shed-storm 20 --alert-shed-window 60 \
        --alert-interval 0.2 --no-alert-bundles \
        --port 0 --compile-cache '' \
        >"$WORK/olstorm_boot.log" 2>"$WORK/olstorm_stderr.log" &
    STGW_PID=$!
    OLURL=''
    i=0
    while [ $i -lt $BOUND ]; do
        OLURL=$(sed -n 's/.*gateway at \(http:[^ ]*\).*/\1/p' "$WORK/olstorm_boot.log")
        [ -n "$OLURL" ] && break
        kill -0 $STGW_PID 2>/dev/null || fail "overload gateway died at boot: $(cat "$WORK/olstorm_stderr.log")"
        sleep 1; i=$((i + 1))
    done
    [ -n "$OLURL" ] || fail "overload gateway did not print URL within ${BOUND}s"
    timeout -k 10 "$BOUND" $PY tools/storm.py --base "$OLURL" \
        --idle 0 --streams 120 --tokens 4 --bursts 2 \
        --burst-gap 0.05 --check 0 --server-pid $STGW_PID \
        --timeout "$BOUND" --json "$WORK/olstorm.json" \
        >"$WORK/olstorm_out.log" 2>&1 \
        || fail "overload storm.py failed: $(tail -5 "$WORK/olstorm_out.log")"
    code=$(curl_s "$WORK/olstorm_stats" "$OLURL/stats") || fail "overload stats curl"
    [ "$code" = 200 ] || fail "overload stats -> $code"
    $PY - "$WORK/olstorm.json" "$WORK/olstorm_stats" <<'EOF' || fail "shed_storm gates: $(cat "$WORK/olstorm.json")"
import json, sys
st = json.load(open(sys.argv[1]))["storm"]
assert st["completed_200"] > 0, st          # landed streams finished
assert st["shed"] >= 20, st                 # the storm really shed
assert st["errors"] == 0, st                # 429/503 only, no 5xx
stats = json.load(open(sys.argv[2]))
alerts = stats["alerts"]
assert alerts["fired"].get("shed_storm", 0) >= 1, alerts["fired"]
EOF
    kill -TERM $STGW_PID
    i=0
    while kill -0 $STGW_PID 2>/dev/null; do
        [ $i -ge $BOUND ] && fail "overload gateway did not drain within ${BOUND}s of SIGTERM"
        sleep 1; i=$((i + 1))
    done
    wait $STGW_PID || true
    STGW_PID=''
    echo "serve-smoke: storm OK (2000/2000 streams over the event edge, zero shed, token-exact spot checks, shed_storm alert fired under overload, clean drain)"
}

# ---- migrate round (also standalone: SERVE_SMOKE_ROUNDS=migrate) -----
# ISSUE-18: live session migration. Two replicas lease ONE shared
# PagePool; a throttled in-flight stream is frozen mid-decode by
# remove_replica and adopted by the survivor WITHOUT copying KV
# (owner swap). The pins: tokens byte-identical to a no-migration
# control, zero 5xx, /stats engine.migrations registers the handover
# (pages_moved stays 0, bytes_avoided grows), and the retiring drain
# returns in freeze-time — visibly faster than decoding the stream's
# remaining budget to completion would have been.
migrate_round() {
    timeout -k 10 "$BOUND" env JAX_PLATFORMS=cpu $PY - <<'EOF' || fail "migrate round"
import time

import jax, jax.numpy as jnp, numpy as np
from tony_tpu.gateway.core import Gateway, GenRequest
from tony_tpu.models import Transformer, TransformerConfig
from tony_tpu.serve import Request, Server
from tony_tpu.serve.faults import FaultPlan
from tony_tpu.serve.slots import PagePool

cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                        n_layers=2, d_ff=64, max_seq_len=64,
                        dtype=jnp.float32, attention_backend="reference")
model = Transformer(cfg)
params = model.init(jax.random.PRNGKey(0),
                    jnp.zeros((1, 8), jnp.int32))["params"]
prompt = np.random.default_rng(3).integers(1, 64, size=13).tolist()
BUDGET, WEDGE = 48, 0.03

def mk(**kw):
    return Server(model, params, batch_size=2, eos_id=-1, paged=True,
                  kv_page_size=8, prefix_cache_mb=0,
                  fault_plan=FaultPlan.wedge_at(1, WEDGE, times=-1),
                  **kw)

# no-migration control on a fresh engine
ctrl = Server(model, params, batch_size=2, eos_id=-1, paged=True,
              kv_page_size=8, prefix_cache_mb=0)
ctrl.submit(Request(list(prompt), BUDGET, id="c", temperature=0.8,
                    top_k=8, seed=7))
expect = list(list(ctrl.run())[0].tokens)

pool = PagePool(model, params, 128, 8, shared=True)
gw = Gateway([mk(page_pool=pool), mk(page_pool=pool)]).start()
try:
    t = gw.submit(GenRequest(list(prompt), max_new_tokens=BUDGET,
                             temperature=0.8, top_k=8, seed=7,
                             id="mig"))
    deadline = time.monotonic() + 60
    while t._n_emitted < 3:
        assert time.monotonic() < deadline, "stream never got going"
        time.sleep(0.02)
    left = BUDGET - t._n_emitted  # tokens a full decode still owes
    t0 = time.monotonic()
    assert gw.remove_replica(t.replica, timeout=60)
    rm_s = time.monotonic() - t0
    res = t.result(timeout=120)
    assert list(res.tokens) == expect, "migrated stream diverged"
    snap = gw.snapshot()
    assert snap["shed"] == {}, snap["shed"]  # zero 5xx
    mig = snap["engine"]["migrations"]
    assert mig["out"] >= 1 and mig["in"] >= 1, mig
    assert mig["pages_moved"] == 0 and mig["bytes_avoided"] > 0, mig
    # the drain point: freeze-time, not decode-to-completion time
    full = left * WEDGE
    assert rm_s < full / 2, (rm_s, full)
    print("serve-smoke: migrate drain %.3fs vs >=%.2fs decode-to-"
          "completion; %d KV bytes swapped in place" %
          (rm_s, full, mig["bytes_avoided"]))
finally:
    assert gw.drain(timeout=60)
assert pool.n_used == 0, pool.n_used  # every page accounted for
EOF
    echo "serve-smoke: migrate OK (mid-stream owner swap, token-exact, zero 5xx, fast drain)"
}

# ---- REBALANCE round (in-process) ------------------------------------
# The pressure loop end to end: pile every stream onto one replica of
# a two-engine shared-pool fleet, start the Rebalancer, and watch it
# notice the skew and migrate a live session to the idle replica —
# with a GatewayHistory attached so the decision lands in
# metrics/rebalance.jsonl exactly as an operator would replay it.
# The pins: >=1 autonomous move, every stream token-identical to its
# no-rebalance control, zero 5xx, and the decision log on disk.
rebalance_round() {
    timeout -k 10 "$BOUND" env JAX_PLATFORMS=cpu WORK="$WORK" $PY - <<'EOF' || fail "rebalance round"
import json, os, time

import jax, jax.numpy as jnp, numpy as np
from tony_tpu.gateway import Gateway, GatewayHistory, Rebalancer
from tony_tpu.gateway.core import GenRequest
from tony_tpu.models import Transformer, TransformerConfig
from tony_tpu.serve import Request, Server
from tony_tpu.serve.faults import FaultPlan
from tony_tpu.serve.slots import PagePool

cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                        n_layers=2, d_ff=64, max_seq_len=64,
                        dtype=jnp.float32, attention_backend="reference")
model = Transformer(cfg)
params = model.init(jax.random.PRNGKey(0),
                    jnp.zeros((1, 8), jnp.int32))["params"]
rng = np.random.default_rng(11)
prompts = [rng.integers(1, 64, size=9).tolist() for _ in range(3)]
BUDGET, WEDGE = 40, 0.03

def mk(**kw):
    return Server(model, params, batch_size=4, eos_id=-1, paged=True,
                  kv_page_size=8, prefix_cache_mb=0,
                  fault_plan=FaultPlan.wedge_at(1, WEDGE, times=-1),
                  **kw)

# no-rebalance controls, one fresh engine, one stream at a time
ctrl = Server(model, params, batch_size=1, eos_id=-1, paged=True,
              kv_page_size=8, prefix_cache_mb=0)
expect = {}
for i, p in enumerate(prompts):
    ctrl.submit(Request(list(p), BUDGET, id=f"c{i}", temperature=0.8,
                        top_k=8, seed=i))
    expect[i] = list(list(ctrl.run())[0].tokens)

pool = PagePool(model, params, 128, 8, shared=True)
hist = GatewayHistory(os.path.join(os.environ["WORK"], "rebhist"),
                      n_replicas=2)
gw = Gateway([mk(page_pool=pool), mk(page_pool=pool)],
             history=hist).start()
try:
    # pile all three streams onto replica 0
    gw.replicas[1].outstanding = 500
    tickets = [gw.submit(GenRequest(list(p), max_new_tokens=BUDGET,
                                    temperature=0.8, top_k=8, seed=i,
                                    id=f"s{i}"))
               for i, p in enumerate(prompts)]
    deadline = time.monotonic() + 60
    while any(t._n_emitted < 3 for t in tickets):
        assert time.monotonic() < deadline, "streams never got going"
        time.sleep(0.02)
    assert all(t.replica == 0 for t in tickets), \
        [t.replica for t in tickets]
    gw.replicas[1].outstanding = 0
    # 3/4 vs 0/4 occupancy: gap 0.75, 3 extra sessions — skewed
    reb = Rebalancer(gw, interval_s=0.05, skew_frac=0.4,
                     min_sessions=2, stable=2, cooldown_s=0.5).start()
    while gw.snapshot()["rebalance"]["moves"] < 1:
        assert time.monotonic() < deadline, "rebalancer never moved"
        time.sleep(0.02)
    for i, t in enumerate(tickets):
        res = t.result(timeout=120)
        assert list(res.tokens) == expect[i], f"stream s{i} diverged"
    snap = gw.snapshot()
    assert snap["shed"] == {}, snap["shed"]  # zero 5xx
    reb_stats = snap["rebalance"]
    assert reb_stats["enabled"] and reb_stats["moves"] >= 1, reb_stats
    moved = [t for t in tickets if t.replica == 1]
    assert moved, "move counted but no stream changed replica"
    path = os.path.join(hist.job_dir, "metrics", "rebalance.jsonl")
    rows = [json.loads(l) for l in open(path)]
    assert any(r["action"] == "move" for r in rows), rows
    print("serve-smoke: rebalancer made %d move(s) in %d tick(s); "
          "%d decision row(s) on disk" %
          (reb_stats["moves"], reb_stats["ticks"], len(rows)))
finally:
    assert gw.drain(timeout=60)
assert pool.n_used == 0, pool.n_used  # every page accounted for
EOF
    echo "serve-smoke: rebalance OK (autonomous move, token-exact, zero 5xx, decisions on disk)"
}

if [ "${SERVE_SMOKE_ROUNDS:-all}" = rebalance ]; then
    rebalance_round   # `make rebalance-smoke`: just the rebalancer round
    exit 0
fi
if [ "${SERVE_SMOKE_ROUNDS:-all}" = migrate ]; then
    migrate_round   # `make migrate-smoke`: just the live-migration round
    exit 0
fi
if [ "${SERVE_SMOKE_ROUNDS:-all}" = storm ]; then
    storm_round   # `make storm-smoke`: just the connection-storm round
    exit 0
fi
if [ "${SERVE_SMOKE_ROUNDS:-all}" = shard ]; then
    shard_round   # `make shard-smoke`: just the sharded-replica round
    exit 0
fi
if [ "${SERVE_SMOKE_ROUNDS:-all}" = autotune ]; then
    autotune_round   # `make autotune-smoke`: just the shape-controller round
    exit 0
fi
if [ "${SERVE_SMOKE_ROUNDS:-all}" = disagg ]; then
    disagg_round   # `make disagg-smoke`: just the disaggregation round
    exit 0
fi
if [ "${SERVE_SMOKE_ROUNDS:-all}" = goodput ]; then
    goodput_round   # `make goodput-smoke`: just the goodput/alerts round
    exit 0
fi
if [ "${SERVE_SMOKE_ROUNDS:-all}" = chaos ]; then
    chaos_round   # `make chaos-smoke`: just the fault-injection round
    exit 0
fi
if [ "${SERVE_SMOKE_ROUNDS:-all}" = remote ]; then
    remote_round   # `make remote-smoke`: just the remote-replica round
    exit 0
fi
if [ "${SERVE_SMOKE_ROUNDS:-all}" = recovery ]; then
    recovery_round   # `make recovery-smoke`: just the crash-recovery round
    exit 0
fi
if [ "${SERVE_SMOKE_ROUNDS:-all}" = bundle ]; then
    bundle_round   # `make bundle-smoke`: just the flight-recorder round
    exit 0
fi
if [ "${SERVE_SMOKE_ROUNDS:-all}" = autoscale ]; then
    autoscale_round   # `make autoscale-smoke`: just the elastic round
    exit 0
fi

# ---- boot the gateway on an ephemeral port ---------------------------
# TONY_PROFILE_DIR: the observability round's on-demand capture must
# land under $WORK, not ./profiles in the checkout
TONY_PROFILE_DIR="$WORK/profiles" \
JAX_PLATFORMS=cpu $PY -m tony_tpu.cli.gateway --demo-model \
    --replicas 2 --port 0 --compile-cache '' --speculate-k 4 \
    >"$WORK/boot.log" 2>"$WORK/stderr.log" &
GW_PID=$!

# the boot line prints the bound URL; wait for it (bounded)
URL=''
i=0
while [ $i -lt $BOUND ]; do
    URL=$(sed -n 's/.*gateway at \(http:[^ ]*\).*/\1/p' "$WORK/boot.log")
    [ -n "$URL" ] && break
    kill -0 $GW_PID 2>/dev/null || fail "gateway died at boot: $(cat "$WORK/stderr.log")"
    sleep 1; i=$((i + 1))
done
[ -n "$URL" ] || fail "gateway did not print its URL within ${BOUND}s"
echo "serve-smoke: gateway at $URL"

# ---- health ----------------------------------------------------------
code=$(curl_s "$WORK/healthz" "$URL/healthz") || fail "healthz curl"
[ "$code" = 200 ] || fail "healthz -> $code"
code=$(curl_s "$WORK/readyz" "$URL/readyz") || fail "readyz curl"
[ "$code" = 200 ] || fail "readyz -> $code"

# ---- concurrent generate: 4 unary + 2 streaming ----------------------
# PIDs collected explicitly: $(jobs -p) runs in a subshell under dash
# and comes back empty, turning `wait` into wait-for-the-gateway
CURL_PIDS=''
n=0
while [ $n -lt 4 ]; do
    curl_s "$WORK/unary_$n" "$URL/v1/generate" \
        "{\"token_ids\": [$((1 + n)), 2, 3], \"max_new_tokens\": 4, \"id\": $n}" \
        >"$WORK/unary_${n}.code" &
    CURL_PIDS="$CURL_PIDS $!"
    n=$((n + 1))
done
n=0
while [ $n -lt 2 ]; do
    curl_s "$WORK/stream_$n" "$URL/v1/generate" \
        "{\"token_ids\": [$((9 + n)), 8], \"max_new_tokens\": 5, \"stream\": true}" \
        >"$WORK/stream_${n}.code" &
    CURL_PIDS="$CURL_PIDS $!"
    n=$((n + 1))
done
wait $CURL_PIDS

n=0
while [ $n -lt 4 ]; do
    [ "$(cat "$WORK/unary_${n}.code")" = 200 ] || fail "unary $n -> $(cat "$WORK/unary_${n}.code")"
    grep -q '"finish_reason"' "$WORK/unary_$n" || fail "unary $n: no finish_reason"
    n=$((n + 1))
done
n=0
while [ $n -lt 2 ]; do
    [ "$(cat "$WORK/stream_${n}.code")" = 200 ] || fail "stream $n -> $(cat "$WORK/stream_${n}.code")"
    # well-formed stream: >= 2 NDJSON lines, each valid JSON, last has
    # finish_reason (the $PY check parses every line)
    $PY - "$WORK/stream_$n" <<'EOF' || fail "stream $n: malformed NDJSON"
import json, sys
lines = [ln for ln in open(sys.argv[1]) if ln.strip()]
assert len(lines) >= 2, f"only {len(lines)} lines"
docs = [json.loads(ln) for ln in lines]
assert docs[-1]["finish_reason"] in ("eos", "length"), docs[-1]
deltas = [t for d in docs[:-1] for t in d["token_ids"]]
assert docs[-1]["token_ids"][-len(deltas):] == deltas, "delta mismatch"
EOF
    n=$((n + 1))
done

# ---- shared-prefix round: the prefix KV cache must register hits -----
# same 12-token preamble, different tails, one exact repeat; sequential
# + session-pinned so all three land on ONE replica's store
PREFIX='1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12'
n=0
for TAIL in '21, 22' '23, 24' '21, 22'; do
    code=$(curl_s "$WORK/prefix_$n" "$URL/v1/generate" \
        "{\"token_ids\": [$PREFIX, $TAIL], \"max_new_tokens\": 3, \"session\": \"warm\"}") \
        || fail "prefix round $n curl"
    [ "$code" = 200 ] || fail "prefix round $n -> $code"
    n=$((n + 1))
done

# ---- speculation round: repetitive prompt, drafts must be accepted ---
# a cyclic prompt is the prompt-lookup sweet spot; same request against
# a --speculate-k 0 control gateway must produce IDENTICAL token_ids
SPEC_REQ='{"token_ids": [1, 2, 3, 1, 2, 3, 1, 2, 3, 1, 2, 3], "max_new_tokens": 10, "session": "spec"}'
code=$(curl_s "$WORK/spec_on" "$URL/v1/generate" "$SPEC_REQ") || fail "spec round curl"
[ "$code" = 200 ] || fail "spec round -> $code"

JAX_PLATFORMS=cpu $PY -m tony_tpu.cli.gateway --demo-model \
    --replicas 1 --port 0 --compile-cache '' --speculate-k 0 \
    >"$WORK/ctrl_boot.log" 2>"$WORK/ctrl_stderr.log" &
CTRL_PID=$!
CTRL_URL=''
i=0
while [ $i -lt $BOUND ]; do
    CTRL_URL=$(sed -n 's/.*gateway at \(http:[^ ]*\).*/\1/p' "$WORK/ctrl_boot.log")
    [ -n "$CTRL_URL" ] && break
    kill -0 $CTRL_PID 2>/dev/null || fail "control gateway died at boot: $(cat "$WORK/ctrl_stderr.log")"
    sleep 1; i=$((i + 1))
done
[ -n "$CTRL_URL" ] || fail "control gateway did not print its URL within ${BOUND}s"
code=$(curl_s "$WORK/spec_off" "$CTRL_URL/v1/generate" "$SPEC_REQ") || fail "spec control curl"
[ "$code" = 200 ] || fail "spec control -> $code"
$PY - "$WORK/spec_on" "$WORK/spec_off" <<'EOF' || fail "speculation changed greedy output"
import json, sys
on = json.load(open(sys.argv[1]))
off = json.load(open(sys.argv[2]))
assert on["token_ids"] == off["token_ids"], (on, off)
assert on["metrics"]["drafted"] > 0 and on["metrics"]["accepted"] > 0, on["metrics"]
EOF
kill -TERM $CTRL_PID
i=0
while kill -0 $CTRL_PID 2>/dev/null; do
    [ $i -ge $BOUND ] && fail "control gateway did not drain"
    sleep 1; i=$((i + 1))
done
CTRL_PID=''

# ---- paged-KV round: tiny page pool under shared-prefix traffic ------
# a deliberately small pool (10 pages x 8 tokens vs 4 slots x 64
# max_seq_len) forces admissions through the reservation gate while
# the prefix store aliases pages copy-on-write. Every request must
# answer 200 (backpressure queues, never 5xx), /stats engine.kv_pages
# must show live CoW sharing, and outputs must be byte-identical to a
# --no-paged-kv control gateway.
JAX_PLATFORMS=cpu $PY -m tony_tpu.cli.gateway --demo-model \
    --replicas 1 --port 0 --compile-cache '' \
    --kv-page-size 8 --kv-pages 10 \
    >"$WORK/paged_boot.log" 2>"$WORK/paged_stderr.log" &
PAGED_PID=$!
PAGED_URL=''
i=0
while [ $i -lt $BOUND ]; do
    PAGED_URL=$(sed -n 's/.*gateway at \(http:[^ ]*\).*/\1/p' "$WORK/paged_boot.log")
    [ -n "$PAGED_URL" ] && break
    kill -0 $PAGED_PID 2>/dev/null || fail "paged gateway died at boot: $(cat "$WORK/paged_stderr.log")"
    sleep 1; i=$((i + 1))
done
[ -n "$PAGED_URL" ] || fail "paged gateway did not print its URL within ${BOUND}s"

JAX_PLATFORMS=cpu $PY -m tony_tpu.cli.gateway --demo-model \
    --replicas 1 --port 0 --compile-cache '' --no-paged-kv \
    >"$WORK/pctrl_boot.log" 2>"$WORK/pctrl_stderr.log" &
CTRL_PID=$!
PCTRL_URL=''
i=0
while [ $i -lt $BOUND ]; do
    PCTRL_URL=$(sed -n 's/.*gateway at \(http:[^ ]*\).*/\1/p' "$WORK/pctrl_boot.log")
    [ -n "$PCTRL_URL" ] && break
    kill -0 $CTRL_PID 2>/dev/null || fail "unpaged control gateway died at boot: $(cat "$WORK/pctrl_stderr.log")"
    sleep 1; i=$((i + 1))
done
[ -n "$PCTRL_URL" ] || fail "unpaged control gateway did not print its URL within ${BOUND}s"

PAGED_PREAMBLE='5, 4, 3, 2, 1, 6, 7, 8, 9, 10, 11, 12, 13, 14'
n=0
for TAIL in '21' '22' '21' '23' '22' '24'; do
    REQ="{\"token_ids\": [$PAGED_PREAMBLE, $TAIL], \"max_new_tokens\": 4, \"id\": $n}"
    code=$(curl_s "$WORK/paged_$n" "$PAGED_URL/v1/generate" "$REQ") \
        || fail "paged round $n curl"
    [ "$code" = 200 ] || fail "paged round $n -> $code (pool pressure must queue, not 5xx)"
    code=$(curl_s "$WORK/pctrl_$n" "$PCTRL_URL/v1/generate" "$REQ") \
        || fail "paged control $n curl"
    [ "$code" = 200 ] || fail "paged control $n -> $code"
    $PY - "$WORK/paged_$n" "$WORK/pctrl_$n" <<'EOF' || fail "paged round $n: output differs from unpaged control"
import json, sys
paged = json.load(open(sys.argv[1]))
ctrl = json.load(open(sys.argv[2]))
assert paged["token_ids"] == ctrl["token_ids"], (paged, ctrl)
EOF
    n=$((n + 1))
done

code=$(curl_s "$WORK/paged_stats" "$PAGED_URL/stats") || fail "paged stats curl"
[ "$code" = 200 ] || fail "paged stats -> $code"
$PY - "$WORK/paged_stats" <<'EOF' || fail "paged stats: kv_pages block wrong"
import json, sys
stats = json.load(open(sys.argv[1]))
assert stats["completed"] == 6, stats["completed"]
assert stats["shed"] == {}, stats["shed"]  # zero 5xx under pool pressure
kv = stats["engine"]["kv_pages"]
assert kv["enabled"], kv
assert kv["total"] == 10 and kv["page_size"] == 8, kv
assert kv["cow_shared"] > 0, kv   # prompt + donation entries share pages
assert kv["used"] + kv["free"] == kv["total"], kv
prefix = stats["engine"]["prefix"]
assert prefix["hits"] > 0, prefix  # the exact repeats aliased, not copied
EOF

kill -TERM $PAGED_PID $CTRL_PID
for P in $PAGED_PID $CTRL_PID; do
    i=0
    while kill -0 $P 2>/dev/null; do
        [ $i -ge $BOUND ] && fail "paged-round gateway did not drain"
        sleep 1; i=$((i + 1))
    done
done
PAGED_PID=''
CTRL_PID=''
echo "serve-smoke: paged OK (small pool, CoW sharing, zero 5xx, outputs == unpaged control)"

# ---- stats + graceful drain -----------------------------------------
code=$(curl_s "$WORK/stats" "$URL/stats") || fail "stats curl"
[ "$code" = 200 ] || fail "stats -> $code"
grep -q '"completed": 10' "$WORK/stats" || fail "stats: expected 10 completed: $(cat "$WORK/stats")"
$PY - "$WORK/stats" <<'EOF' || fail "stats: no prefix-cache hits / no accepted drafts"
import json, sys
engine = json.load(open(sys.argv[1]))["engine"]
prefix = engine["prefix"]
assert prefix["enabled"], prefix
assert prefix["hits"] > 0 and prefix["hit_tokens"] > 0, prefix
assert 0 < prefix["hit_rate"] <= 1, prefix
spec = engine["spec"]
assert spec["enabled"], spec
assert spec["drafted"] > 0 and spec["accepted"] > 0, spec
assert 0 < spec["acceptance_rate"] <= 1, spec
kv = engine["kv_pages"]  # the default gateway serves paged
assert kv["enabled"] and kv["total"] > 0, kv
EOF

# ---- observability round: /metrics exposition + request traces ------
# a request with a client-supplied request_id, then: scrape /metrics
# and format-validate the exposition (HELP/TYPE headers, sample lines,
# cumulative-monotonic histogram buckets ending in +Inf, the latency
# histograms an autoscaler consumes), and fetch the request's trace as
# Chrome trace-event JSON and span-check it
code=$(curl_s "$WORK/obs_req" "$URL/v1/generate" \
    '{"token_ids": [31, 32, 33], "max_new_tokens": 4, "request_id": "obs-1"}') \
    || fail "obs request curl"
[ "$code" = 200 ] || fail "obs request -> $code"
grep -q '"request_id": "obs-1"' "$WORK/obs_req" || fail "request_id not echoed: $(cat "$WORK/obs_req")"

code=$(curl_s "$WORK/metrics" "$URL/metrics") || fail "metrics curl"
[ "$code" = 200 ] || fail "metrics -> $code"
$PY - "$WORK/metrics" <<'EOF' || fail "/metrics exposition invalid"
import re, sys
text = open(sys.argv[1]).read()
sample = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*")*\})?'
    r' (-?\d+(\.\d+)?([eE][+-]?\d+)?|\+Inf|-Inf|NaN)$')
types, buckets = {}, {}
for line in text.splitlines():
    if not line:
        continue
    if line.startswith("# HELP "):
        continue
    if line.startswith("# TYPE "):
        _, _, name, mtype = line.split(None, 3)
        assert mtype in ("counter", "gauge", "histogram"), line
        types[name] = mtype
        continue
    assert sample.match(line), f"malformed: {line!r}"
    name = re.split(r"[{ ]", line, 1)[0]
    base = re.sub(r"_(bucket|sum|count)$", "", name)
    if types.get(base) == "histogram" and name.endswith("_bucket"):
        series = re.sub(r',?le="[^"]+"', "", line.split(" ")[0])
        le = re.search(r'le="([^"]+)"', line).group(1)
        buckets.setdefault(series, []).append((le, float(line.rsplit(" ", 1)[1])))
for series, pts in buckets.items():
    vals = [v for _, v in pts]
    assert vals == sorted(vals), f"non-monotonic buckets: {series}"
    assert pts[-1][0] == "+Inf", f"missing +Inf: {series}"
# the families the acceptance names, consistent with a live gateway
assert types["tony_request_ttft_seconds"] == "histogram", types
assert types["tony_request_tpot_seconds"] == "histogram"
assert types["tony_request_queue_wait_seconds"] == "histogram"
assert types["tony_replica_failures_total"] == "counter"
assert types["tony_engine_prefix_hits_total"] == "counter"
assert types["tony_engine_spec_accepted_total"] == "counter"
assert re.search(r"^tony_requests_completed_total 11$", text, re.M), \
    "completed counter wrong"
EOF

code=$(curl_s "$WORK/trace" "$URL/debug/trace/obs-1") || fail "trace curl"
[ "$code" = 200 ] || fail "debug/trace -> $code"
$PY - "$WORK/trace" <<'EOF' || fail "trace is not valid span-checked Chrome JSON"
import json, sys
doc = json.load(open(sys.argv[1]))
assert doc["otherData"]["request_id"] == "obs-1", doc["otherData"]
events = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
names = [e["name"] for e in events]
assert names[0] == "request" and "attempt-1" in names, names
assert "queue_wait" in names and ("prefill" in names or "hit_admit" in names), names
root = events[0]
# 5 us tolerance: ts is epoch MICROseconds (~1.7e15), where float64
# granularity is ~0.25 us — exact comparisons are noise, not bugs
for e in events:
    assert e["dur"] >= 0 and e["ts"] >= root["ts"] - 5, e
    assert e["ts"] + e["dur"] <= root["ts"] + root["dur"] + 5, e
EOF
echo "serve-smoke: observability OK (/metrics format-valid, trace span-checked)"

# ---- on-demand profile round: arm, drive, capture lands --------------
# steps=1: the next working scheduler iteration is captured. The FIRST
# start_trace of a process can take >10 s (profiler plugin spin-up) —
# bounded below, and well inside the default 30 s stall horizon.
code=$(curl_s "$WORK/prof_arm" "$URL/debug/profile?steps=1&logdir=smoke" '{}') \
    || fail "profile arm curl"
[ "$code" = 200 ] || fail "profile arm -> $code: $(cat "$WORK/prof_arm")"
i=0
while [ $i -lt $BOUND ]; do
    curl_s "$WORK/prof_drive" "$URL/v1/generate" \
        '{"token_ids": [41, 42], "max_new_tokens": 3}' >/dev/null 2>&1
    curl_s "$WORK/prof_status" "$URL/debug/profile" >/dev/null 2>&1
    grep -q '"captures": [1-9]' "$WORK/prof_status" && break
    sleep 1; i=$((i + 1))
done
grep -q '"captures": [1-9]' "$WORK/prof_status" || fail "profile capture never finished: $(cat "$WORK/prof_status")"
echo "serve-smoke: profile OK (on-demand xplane capture landed)"

kill -TERM $GW_PID
i=0
while kill -0 $GW_PID 2>/dev/null; do
    [ $i -ge $BOUND ] && fail "gateway did not drain within ${BOUND}s of SIGTERM"
    sleep 1; i=$((i + 1))
done
wait $GW_PID
rc=$?
[ $rc = 0 ] || fail "gateway exited $rc after SIGTERM"
GW_PID=''
echo "serve-smoke: OK (10 requests, prefix hits, accepted drafts, clean drain)"

# ---- chaos round: kill a replica's work, keep serving ----------------
chaos_round

# ---- autoscale round: burst -> scale up -> drain to the floor --------
autoscale_round

# ---- goodput/alerts round: tiny pool -> alert fires -> resolves ------
goodput_round

# ---- disagg round: role split + chunked prefill + host page tier -----
disagg_round

# ---- autotune round: shape controller actuates, stays token-exact ----
autotune_round

# ---- shard round: mesh=4 replica byte-identical to single-device -----
shard_round

# ---- remote round: agents on "hosts", kill -9 one, keep serving ------
remote_round

# ---- bundle round: synthetic alert -> flight-recorder dump -----------
bundle_round

# ---- storm round: 2000 concurrent streams over the event edge --------
storm_round

# ---- migrate round: freeze a live stream, survivor adopts it ---------
migrate_round

# ---- rebalance round: skewed fleet -> autonomous session move --------
rebalance_round

# ---- recovery round: kill -9 the gateway, --recover replays the WAL --
recovery_round
echo "serve-smoke: ALL OK"
