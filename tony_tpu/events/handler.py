"""Event handler: queue + writer thread + inprogress->final rename.

Reference: events/EventHandler.java:22 — AM emits events into a
BlockingQueue drained by a writer thread into an in-progress history file
under intermediate/<app>/; on stop, drains the queue and renames the file to
the final name encoding completion time + status (:137-155).
"""

from __future__ import annotations

import getpass
import json
import logging
import os
import queue
import threading
import time

from tony_tpu import constants as C
from tony_tpu.events import history
from tony_tpu.events.event import Event, JobMetadata

log = logging.getLogger(__name__)


class EventHandler:
    def __init__(self, history_root: str, app_id: str, user: str | None = None):
        self.history_root = history_root
        self.app_id = app_id
        self.user = user or getpass.getuser()
        self.started_ms = int(time.time() * 1000)
        self.queue: "queue.Queue[Event | None]" = queue.Queue()
        self.job_dir = history.intermediate_dir(history_root, app_id)
        os.makedirs(self.job_dir, exist_ok=True)
        self.inprogress_path = os.path.join(
            self.job_dir, history.inprogress_name(app_id, self.started_ms)
        )
        self._thread: threading.Thread | None = None
        self._stopped = threading.Event()
        self._write_metadata("RUNNING", -1)

    # -- lifecycle (ref: setUpThread :43 / start) ---------------------------
    def start(self) -> "EventHandler":
        self._thread = threading.Thread(target=self._drain, name="event-writer",
                                        daemon=True)
        self._thread.start()
        return self

    def emit(self, event: Event) -> None:
        """Ref: emitEvent :88 — never blocks the coordinator."""
        if not self._stopped.is_set():
            self.queue.put(event)

    def _drain(self) -> None:
        with open(self.inprogress_path, "a", buffering=1) as f:
            while True:
                ev = self.queue.get()
                if ev is None:
                    return
                try:
                    f.write(json.dumps(ev.to_dict()) + "\n")
                except Exception:
                    log.exception("failed writing event %s", ev.type)

    def stop(self, final_status: str) -> str:
        """Drain, write final metadata, rename inprogress -> final
        (ref: stop + rename :137-155). Returns the final jhist path."""
        self._stopped.set()
        self.queue.put(None)
        if self._thread:
            self._thread.join(timeout=10)
        completed_ms = int(time.time() * 1000)
        final = os.path.join(
            self.job_dir,
            history.finished_name(self.app_id, self.started_ms, completed_ms,
                                  self.user, final_status),
        )
        try:
            os.rename(self.inprogress_path, final)
        except FileNotFoundError:
            open(final, "a").close()
        self._write_metadata(final_status, completed_ms)
        return final

    def _write_metadata(self, status: str, completed_ms: int) -> None:
        meta = JobMetadata(
            id=self.app_id,
            user=self.user,
            started=self.started_ms,
            completed=completed_ms,
            status=status,
            conf_path=os.path.join(self.job_dir, C.TONY_FINAL_CONF),
        )
        with open(os.path.join(self.job_dir, C.METADATA_FILE), "w") as f:
            json.dump(meta.to_dict(), f, indent=2)
