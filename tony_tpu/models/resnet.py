"""ResNet family in flax — the north-star benchmark model.

BASELINE.md: "ResNet-50 images/sec/chip via ClusterSubmitter-equivalent at
>= 90% of native JAX" (the reference's horovod-on-tony example trains
ResNet-50; TonY itself has no model code, so this is new, TPU-first code).

TPU notes: NHWC layout (XLA:TPU native), bfloat16 compute with float32
batch-norm statistics and params, 3x3 convs land on the MXU as implicit
GEMMs; no data-dependent control flow.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class ResNetBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckResNetBlock(nn.Module):
    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros_init())(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype,
                       param_dtype=jnp.float32)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=self.dtype,
                       param_dtype=jnp.float32, axis_name=None)
        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                 name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = self.act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_size in enumerate(self.stage_sizes):
            for j in range(block_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    self.num_filters * 2 ** i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    act=self.act,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     param_dtype=jnp.float32)(x)
        return x.astype(jnp.float32)


ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=ResNetBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=ResNetBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3],
                   block_cls=BottleneckResNetBlock)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3],
                    block_cls=BottleneckResNetBlock)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3],
                    block_cls=BottleneckResNetBlock)
