# Build/verify entry points (reference parity: the gradle build's
# check/test wiring, build.gradle:113-116 + .circleci/config.yml).
#
#   make lint   - static analysis: ruff when installed AND the in-tree
#                 AST checker (tools/lint.py) — ruff alone would let
#                 the in-tree rules drift on boxes that have it, and
#                 vice versa; tools/serve_smoke.sh runs the same gate
#                 at its top so smoke runs fail fast on lint drift
#   make smoke  - <60 s unit tier (no jax-heavy model/e2e suites):
#                 config, session, scheduler, rpc, events, utils,
#                 remotefs, runtimes, workflow, tpu_info, compilecache,
#                 proxy, profiler
#   make check  - lint + smoke (the pre-commit gate)
#   make test   - the full suite (~15-20 min on a 1-core box)
#   make bench  - the driver-contract benchmark (one JSON line)
#   make serve-smoke - boot a tiny-model gateway, concurrent curl
#                 clients (unary + streaming), a /metrics exposition +
#                 /debug/trace + on-demand profile observability round,
#                 SIGTERM drain; every phase `timeout`-bounded so a
#                 hang exits nonzero
#   make chaos-smoke - just the fault-injection round of serve-smoke:
#                 a 2-replica gateway with replica 0's dispatches
#                 killed via TONY_SERVE_FAULTS must keep serving
#                 (failover, zero 5xx) and rejoin the dead replica
#   make autoscale-smoke - just the elastic round of serve-smoke:
#                 burst load at a min=1/max=3 gateway must scale up
#                 (probe-admitted), serve with zero 5xx, and drain
#                 back to the floor once idle

PY ?= python

LINT_PATHS = tony_tpu tests examples tools bench.py __graft_entry__.py

SMOKE_TESTS = tests/test_config.py tests/test_session.py \
	tests/test_scheduler.py tests/test_rpc.py tests/test_events.py \
	tests/test_utils.py tests/test_remotefs.py tests/test_runtimes.py \
	tests/test_workflow.py tests/test_tpu_info.py \
	tests/test_compilecache.py tests/test_proxy.py tests/test_profiler.py

#   make goodput-smoke - just the goodput/alerts round of serve-smoke:
#                 a tiny KV page pool under load must fire a
#                 kv_pages_pressure alert (visible on /stats, in
#                 history alerts.jsonl, and on the portal), resolve
#                 once idle, and /debug/goodput must name the largest
#                 waste bucket
#   make remote-smoke - just the remote-replica round of serve-smoke:
#                 2 replica-agent subprocesses behind an --agents
#                 gateway; kill -9 one mid-run -> zero 5xx, outputs
#                 token-exact vs a local-replica control, the corpse
#                 quarantined, the survivor SIGTERM-drained clean;
#                 plus (ISSUE-15) the survivor's dispatch counts and a
#                 non-null merged goodput block on /stats,
#                 tony_goodput_fraction + tony_transport_clock_offset_ms
#                 on /metrics, and a /debug/profile fan-out capture on
#                 the survivor agent
#   make bundle-smoke - just the flight-recorder round of serve-smoke:
#                 a live subprocess gateway with --history and a
#                 synthetic queue_aging alert must dump one
#                 self-contained debug bundle (alerts, traces,
#                 per-replica dispatch/goodput blocks, signals) into
#                 <job dir>/bundles/, validated as JSON; GET
#                 /debug/bundle must serve the same document shape

#   make disagg-smoke - just the disaggregation round of serve-smoke:
#     a --roles prefill=1,decode=1 gateway with chunked prefill and a
#     host-RAM KV page tier under mixed long-prompt/short-chat traffic
#     -> zero 5xx, token-exact vs a single-pool control, host-tier
#     page-ins and multi-chunk prefills visible on /stats

#   make autotune-smoke - just the shape-controller round of
#     serve-smoke: an --autotune gateway booted at chunk-steps 1 under
#     mixed traffic must actuate (grow chunk depth off the goodput
#     ledger), stay token-exact vs a static control gateway with zero
#     5xx, converge once idle, and land the decision in /stats
#     engine.autotune + tony_autotune_* metrics + history
#     metrics/autotune.jsonl

#   make shard-smoke - just the sharded-replica round of serve-smoke:
#     a --mesh 4 gateway on 4 virtual CPU devices (params sharded on
#     output dims, KV page pools sharded 4-way on the kv-head axis)
#     under greedy/sampled/prefix/streaming traffic, byte-identical
#     outputs vs a single-device control gateway, mesh topology +
#     per-chip pricing on /stats engine.mesh + tony_mesh_* metrics
#   make storm-smoke - just the connection-storm round of serve-smoke:
#     tools/storm.py parks 500 idle keep-alive connections on an
#     event-edge gateway, then fires 2000 concurrent NDJSON streams
#     in bursts — zero shed / zero unintentional 5xx, token-exact
#     spot checks vs unary controls, edge block on /stats +
#     tony_edge_* on /metrics, clean SIGTERM drain
#   make migrate-smoke - just the live-migration round of serve-smoke:
#     two replicas leasing ONE shared PagePool, remove_replica freezes
#     a throttled in-flight stream mid-decode and the survivor adopts
#     it by owner swap — token-exact vs a no-migration control, zero
#     5xx, zero KV pages copied, retiring drain bounded by freeze
#     cost instead of the stream's remaining decode budget
#   make rebalance-smoke - just the rebalancer round of serve-smoke:
#     three live streams piled onto one replica of a two-engine
#     shared-pool fleet, the Rebalancer detects the occupancy skew
#     and autonomously migrates a session to the idle replica —
#     token-exact vs no-rebalance controls, zero 5xx, the decision
#     trail in the gateway history's metrics/rebalance.jsonl
#   make recovery-smoke - just the crash-recovery round of serve-smoke:
#     a --journal gateway over two agent subprocesses is kill -9'd
#     mid-stream, the agents park the orphans after --gateway-grace,
#     a --recover boot replays the WAL and adopts them token-exact
#     (zero re-prefill), every stream re-fetched byte-identical via
#     GET /v1/stream/<id>?offset=0 vs a never-crashed control — zero
#     5xx after restart, clean drain compacts the journal to empty

.PHONY: lint smoke check test bench serve-smoke chaos-smoke \
	autoscale-smoke goodput-smoke remote-smoke disagg-smoke \
	autotune-smoke shard-smoke bundle-smoke storm-smoke \
	migrate-smoke rebalance-smoke recovery-smoke

lint:
	@if command -v ruff >/dev/null 2>&1; then \
		echo "ruff check"; ruff check $(LINT_PATHS) || exit 1; \
	else \
		echo "(no ruff in image — in-tree checker only)"; \
	fi
	@echo "tools/lint.py"
	@$(PY) tools/lint.py $(LINT_PATHS)

smoke:
	$(PY) -m pytest $(SMOKE_TESTS) -q -p no:cacheprovider

check: lint smoke

test:
	$(PY) -m pytest tests/ -q

bench:
	$(PY) bench.py

serve-smoke:
	PY=$(PY) sh tools/serve_smoke.sh

chaos-smoke:
	PY=$(PY) SERVE_SMOKE_ROUNDS=chaos sh tools/serve_smoke.sh

autoscale-smoke:
	PY=$(PY) SERVE_SMOKE_ROUNDS=autoscale sh tools/serve_smoke.sh

goodput-smoke:
	PY=$(PY) SERVE_SMOKE_ROUNDS=goodput sh tools/serve_smoke.sh

remote-smoke:
	PY=$(PY) SERVE_SMOKE_ROUNDS=remote sh tools/serve_smoke.sh

disagg-smoke:
	PY=$(PY) SERVE_SMOKE_ROUNDS=disagg sh tools/serve_smoke.sh

autotune-smoke:
	PY=$(PY) SERVE_SMOKE_ROUNDS=autotune sh tools/serve_smoke.sh

shard-smoke:
	PY=$(PY) SERVE_SMOKE_ROUNDS=shard sh tools/serve_smoke.sh

bundle-smoke:
	PY=$(PY) SERVE_SMOKE_ROUNDS=bundle sh tools/serve_smoke.sh

storm-smoke:
	PY=$(PY) SERVE_SMOKE_ROUNDS=storm sh tools/serve_smoke.sh

migrate-smoke:
	PY=$(PY) SERVE_SMOKE_ROUNDS=migrate sh tools/serve_smoke.sh

rebalance-smoke:
	PY=$(PY) SERVE_SMOKE_ROUNDS=rebalance sh tools/serve_smoke.sh

recovery-smoke:
	PY=$(PY) SERVE_SMOKE_ROUNDS=recovery sh tools/serve_smoke.sh
