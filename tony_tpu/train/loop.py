"""High-level training loop: loader -> jitted step -> checkpoints/eval/logs.

No reference analog (TonY's "training loop" is the user script it execs,
SURVEY.md section 2.1 Utils.executeShell). tony-tpu ships the loop so a
job script reduces to model + loss + conf: ``fit`` wires the sharded
DataLoader, the pjit'd Trainer step, orbax checkpointing (with
coordinator-retry resume via TONY_CHECKPOINT_DIR), periodic eval, and
metric sinks into one call. Host work (logging, checkpoint scheduling)
stays off the device path: metrics are only fetched when a sink needs
them, so steps dispatch back-to-back and XLA pipelines them.
"""

from __future__ import annotations

import functools
import json
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable

import jax
import jax.numpy as jnp
import optax

from tony_tpu.train.checkpoint import CheckpointManager, job_checkpoint_dir
from tony_tpu.train.trainer import Trainer, TrainState

log = logging.getLogger(__name__)


@dataclass
class FitResult:
    state: TrainState
    steps_run: int
    resumed_from: int | None
    history: list[dict] = field(default_factory=list)
    # exponential moving average of params (None unless fit(ema_decay=...))
    ema_params: Any = None


class JsonlMetricsLogger:
    """Metric sink appending one JSON object per logged step — the same
    jsonl idiom as the event/history pipeline, so the portal can serve it."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)

    def __call__(self, step: int, metrics: dict) -> None:
        with open(self.path, "a") as f:
            f.write(json.dumps({"step": step, **metrics}) + "\n")


def fit(trainer: Trainer, params: Any, train_data: Iterable, *,
        num_steps: int | None = None,
        total_steps: int | None = None,
        checkpoint_dir: str | None = None,
        checkpoint_every: int = 0,
        max_checkpoints: int = 3,
        eval_data: Iterable | None = None,
        eval_fn: Callable[[Any, Any], Any] | None = None,
        eval_every: int = 0,
        log_every: int = 50,
        metric_sinks: list[Callable[[int, dict], None]] | None = None,
        ema_decay: float = 0.0,
        ) -> FitResult:
    """Train until ``train_data`` is exhausted or ``num_steps`` is reached.

    Args:
      trainer: a configured Trainer (mesh/apply_fn/optimizer/fsdp).
      params: initial params pytree (ignored when a checkpoint is restored).
      train_data: iterable of batches (e.g. tony_tpu.data.DataLoader with
        sharding= so batches arrive as global jax.Arrays).
      num_steps: cap on ADDITIONAL steps this call runs (counted from the
        restored step). For retry-resume jobs use total_steps instead.
      total_steps: absolute target step: a resumed attempt completes the
        original budget (trains total_steps - restored_step more) rather
        than a fresh num_steps. Both given -> the earlier bound wins.
      checkpoint_dir: where to save/restore; defaults to the
        coordinator-injected TONY_CHECKPOINT_DIR (tony.application.
        checkpoint-dir), making retry attempts resume automatically.
        None/absent env -> no checkpointing.
      checkpoint_every: save cadence in steps (0 = only the final save,
        which always happens when a checkpoint dir is configured).
      eval_data / eval_fn: eval_fn(params, batch) -> scalar-or-dict, run
        over all of eval_data every ``eval_every`` steps; means are logged
        under "eval/...".
      log_every: host-side logging cadence (each log forces a metrics
        fetch; between logs, steps dispatch without synchronizing).
      metric_sinks: callables (step, metrics-dict) — e.g.
        JsonlMetricsLogger — invoked at the log cadence and after eval.
      ema_decay: > 0 maintains a device-resident exponential moving
        average of params (ema = decay*ema + (1-decay)*params after every
        step; typical 0.999), returned as FitResult.ema_params — the
        standard eval/serving weights for vision and diffusion training.
        The EMA lives alongside params with the same shardings and one
        cheap fused elementwise update per step; it is NOT checkpointed —
        a retry-resumed attempt restarts the average from the restored
        params.

    Returns FitResult (final state, steps run, resume step, logged history).
    """
    resumed_from = None
    manager = None
    placed = None
    # abstract state: shapes/dtypes only, no device allocation — so a
    # resuming attempt never materializes the fresh state it would discard
    abstract = jax.eval_shape(trainer.init_state, params)
    shardings = trainer.state_shardings(abstract)
    ckpt_dir = checkpoint_dir or job_checkpoint_dir()
    if ckpt_dir:
        manager = CheckpointManager(ckpt_dir, max_to_keep=max_checkpoints)
        if manager.latest_step() is not None:
            template = jax.tree.map(
                lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                                  sharding=s),
                abstract, shardings)
            restored = manager.restore(template)
            if restored is not None:
                placed = restored
                resumed_from = int(placed.step)
                log.info("fit: resumed from checkpoint step %d", resumed_from)
    if placed is None:
        placed = jax.device_put(trainer.init_state(params), shardings)
        if trainer.donate:
            # device_put can alias buffers of the CALLER's params (no-op
            # placement, or zero-copy on host platforms), and the first
            # donated step would delete them out from under the caller.
            # That reaches opt_state too when an optimizer's init stores
            # params references (lookahead-style slow weights), so detect
            # aliasing by underlying buffer pointer and copy exactly the
            # aliased leaves — fresh zeros_like opt leaves are never
            # copied, keeping init peak memory flat in the near-HBM
            # regime donation targets.
            def ptrs(x):
                try:
                    return {s.data.unsafe_buffer_pointer()
                            for s in x.addressable_shards}
                except Exception:
                    return None

            caller_bufs: set = set()
            for x in jax.tree.leaves(params):
                p = ptrs(x)
                if p:
                    caller_bufs |= p

            def fresh(x):
                p = ptrs(x)
                # unknown pointers -> copy to be safe
                if p is None or p & caller_bufs:
                    return jnp.copy(x)
                return x

            placed = jax.tree.map(fresh, placed)
    step_fn = trainer.compile_step(shardings)

    # compile the eval step once: shapes are static (drop_remainder
    # contract), and an uncompiled per-batch apply would run eager
    eval_step = jax.jit(eval_fn) if eval_fn else None

    ema_params = None
    ema_step = None
    if ema_decay:
        # deep copy, NOT a reference: step_fn donates its input state
        # (Trainer.donate default), which would delete aliased buffers out
        # from under the first EMA update
        ema_params = jax.tree.map(jnp.copy, placed.params)
        ema_step = jax.jit(functools.partial(
            optax.incremental_update, step_size=1.0 - ema_decay))

    sinks = list(metric_sinks or [])
    history: list[dict] = []
    start_step = int(placed.step)
    target = None if num_steps is None else start_step + num_steps
    if total_steps is not None:
        target = total_steps if target is None else min(target, total_steps)
    steps_run = 0
    last_metrics = None
    t0 = time.monotonic()

    def emit(step: int, metrics: dict) -> None:
        history.append({"step": step, **metrics})
        for sink in sinks:
            sink(step, metrics)

    # Log-boundary metrics are fetched ASYNCHRONOUSLY: a synchronous
    # float() at the boundary parks the host on a device->host round trip
    # (milliseconds over a tunneled chip) while the dispatch queue drains —
    # the measured few-percent fit() overhead of r2 (VERDICT r2 #5). Instead
    # the boundary starts a device->host copy and the values are emitted at
    # the NEXT boundary (or at loop end), by which time the copy long
    # finished and float() costs nothing. Sinks therefore observe each
    # boundary one log period late, with identical (step, metrics) pairs.
    pending: tuple[int, Any, float] | None = None

    def flush_pending() -> None:
        nonlocal pending
        if pending is None:
            return
        p_step, p_metrics, p_rate = pending
        pending = None
        fetched = {k: float(v) for k, v in p_metrics.items()}
        log.info("step %d: %s (%.2f steps/s)", p_step,
                 {k: round(v, 4) for k, v in fetched.items()}, p_rate)
        emit(p_step, {**fetched, "steps_per_sec": p_rate})

    data_iter = None
    if target is None or start_step < target:  # budget not already met
        if resumed_from and hasattr(train_data, "from_step"):
            # resume the data order too: skip the batches already consumed
            data_iter = train_data.from_step(start_step)
        else:
            if resumed_from:
                log.warning(
                    "fit: resumed model state at step %d but train_data has "
                    "no from_step — the iterator restarts from its "
                    "beginning, replaying already-seen batches", resumed_from)
            data_iter = iter(train_data)

    try:
        while data_iter is not None and \
                (target is None or start_step + steps_run < target):
            try:
                batch = next(data_iter)
            except StopIteration:
                break
            placed, last_metrics = step_fn(placed, batch)
            if ema_step is not None:
                ema_params = ema_step(placed.params, ema_params)
            steps_run += 1
            step = start_step + steps_run
            if log_every and steps_run % log_every == 0:
                flush_pending()  # previous boundary's copy is done by now
                for v in last_metrics.values():
                    if hasattr(v, "copy_to_host_async"):
                        v.copy_to_host_async()
                pending = (step, last_metrics,
                           steps_run / (time.monotonic() - t0))
            if manager and checkpoint_every and \
                    steps_run % checkpoint_every == 0:
                manager.save(step, placed)
            if eval_step and eval_data is not None and eval_every and \
                    steps_run % eval_every == 0:
                flush_pending()  # keep history/sinks step-ordered
                ev = _run_eval(eval_step, placed.params, eval_data)
                if ev:
                    emit(step, ev)
    finally:
        # emit the deferred boundary even when the loop dies mid-window —
        # the last logged metrics are exactly what a crash post-mortem
        # needs. A flush failure must not mask the original exception.
        try:
            flush_pending()
        except Exception:
            log.exception("fit: failed to flush pending metrics")
        # release the loader's prefetch thread + staged device batches
        if data_iter is not None and hasattr(data_iter, "close"):
            data_iter.close()

    if manager:
        final = start_step + steps_run
        # the periodic save may already have written this exact step
        # (orbax raises StepAlreadyExists rather than overwriting)
        if manager.latest_step() != final:
            manager.save(final, placed, force=True)
        manager.wait()
        manager.close()
    return FitResult(state=placed, steps_run=steps_run,
                     resumed_from=resumed_from, history=history,
                     ema_params=ema_params)


def _run_eval(eval_fn, params, eval_data) -> dict:
    totals: dict[str, float] = {}
    n = 0
    for batch in eval_data:
        out = eval_fn(params, batch)
        if not isinstance(out, dict):
            out = {"loss": out}
        for k, v in out.items():
            totals[k] = totals.get(k, 0.0) + float(v)
        n += 1
    if n == 0:
        # a one-shot generator passed as eval_data is exhausted after the
        # first eval — surface it instead of silently logging nothing
        log.warning("fit: eval pass saw no batches (eval_data exhausted? "
                    "pass a re-iterable like a DataLoader or a list)")
        return {}
    return {f"eval/{k}": v / n for k, v in totals.items()}
