"""Goodput ledger + alert bus tests (ISSUE 10).

Pinned bottom-up:

- ``obs.goodput`` units: the cost model's scaling behavior (bytes grow
  with depth/view, verify reads parameters once where a chunk reads
  them per micro-step), the roofline-reference detection, and the
  ledger's structural sums-to-<=1 invariant on synthetic summaries;
- THE acceptance pin: on a live engine run the ledger's bucket
  fractions sum to <= 1.0 AND reconcile exactly with the timeline
  (per-kind useful+padding+overshoot+rejected == steady ms) and the
  engine counters (``sum(fed - tokens)`` over decode+verify ==
  ``wasted_steps``; landed tokens == tokens the requests kept);
- ``obs.alerts`` units: fire-once dedup, resolve debounce, the rule
  predicates (queue aging, KV pressure, TTFT burn over histogram
  deltas, breaker flap windows, goodput collapse vs baseline), and a
  raising rule never taking the bus down;
- gateway integration: a deliberately tiny KV page pool under live
  load fires ``kv_pages_pressure`` into /stats alerts + history
  ``metrics/alerts.jsonl`` and RESOLVES when load stops;
  ``GET /debug/goodput`` names a largest waste bucket and
  ``GET /debug/traces`` lists terminal tags over real HTTP.
"""

import json
import time
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from tony_tpu.gateway import Gateway, GatewayHistory, GatewayHTTP, GenRequest
from tony_tpu.models import Transformer, TransformerConfig
from tony_tpu.obs.alerts import (AlertBus, BreakerFlapRule,
                                 GoodputCollapseRule, KvPagesPressureRule,
                                 QueueAgingRule, Rule, TtftSloBurnRule)
from tony_tpu.obs.goodput import (WASTE_BUCKETS, CostModel,
                                  detect_hbm_gbps, ledger, merge_ledgers)
from tony_tpu.serve import Request, Server


@pytest.fixture(scope="module")
def tiny():
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_seq_len=32,
                            dtype=jnp.float32,
                            attention_backend="reference")
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


# ---------------------------------------------------- cost model units


def _cm(**kw):
    base = dict(param_bytes=10_000_000, param_count=5_000_000,
                kv_token_bytes=256.0, n_heads=8, head_dim=64,
                vocab_size=32_000)
    base.update(kw)
    return CostModel(**base)


def test_cost_model_scales_with_depth_and_view():
    cm = _cm()
    b1, f1 = cm.decode(1, 4, 128)
    b8, f8 = cm.decode(8, 4, 128)
    assert b8 == pytest.approx(8 * b1) and f8 == pytest.approx(8 * f1)
    bwide, _ = cm.decode(1, 4, 1024)
    assert bwide > b1  # a longer live view moves more cache bytes
    # a verify window reads the parameters ONCE; a chunk of the same
    # depth re-reads them per micro-step — the whole point of the
    # one-dispatch verify
    bv, _ = cm.verify(8, 4, 128)
    assert bv < b8
    # paged exact-hit admission moves ~a page; the unpaged hit copies
    # a whole row (the extras.paged 14.8x fewer-bytes claim, in model)
    bhit, _ = cm.hit_admit(row_bytes=1_000_000)
    bcow, _ = cm.cow_admit(fork_bytes=4_096)
    assert bcow < bhit


def test_cost_model_utilization_reference_gating():
    none_bw, none_mfu = _cm().utilization(1e9, 1e9, 10.0)
    assert none_bw is None and none_mfu is None  # no reference: null
    cm = _cm(hbm_gbps=1000.0, peak_flops=100e12)
    bw, mfu = cm.utilization(5e9, 100e12 * 0.01, 10.0)
    # 5 GB in 10 ms against 1000 GB/s = 50%; 1e12 FLOPs in 10 ms
    # against 100 TFLOP/s = 100%
    assert bw == pytest.approx(50.0, abs=0.1)
    assert mfu == pytest.approx(100.0, abs=0.1)


def test_detect_hbm_gbps_env_override(monkeypatch):
    monkeypatch.setenv("TONY_HBM_GBPS", "123.5")
    assert detect_hbm_gbps() == 123.5
    monkeypatch.setenv("TONY_HBM_GBPS", "not-a-number")
    assert detect_hbm_gbps() >= 0.0  # falls through to the chip table


def test_ledger_structural_invariant_synthetic():
    summary = {
        "decode": {"ms": 80.0, "compile_ms": 20.0, "useful_ms": 40.0,
                   "padding_ms": 10.0, "overshoot_ms": 8.0,
                   "rejected_ms": 2.0, "est_bytes": 1e9,
                   "est_flops": 1e12, "est_bytes_steady": 8e8,
                   "est_flops_steady": 8e11},
        "prefill": {"ms": 20.0, "compile_ms": 5.0, "useful_ms": 12.0,
                    "padding_ms": 3.0, "overshoot_ms": 0.0,
                    "rejected_ms": 0.0, "est_bytes": 1e8,
                    "est_flops": 1e11, "est_bytes_steady": 9e7,
                    "est_flops_steady": 9e10},
    }
    led = ledger(summary, wall_ms=200.0, hbm_gbps=819.0)
    total = sum(led["buckets"].values())
    assert total <= 1.0 + 1e-9
    assert led["buckets"]["idle"] == pytest.approx(0.5)
    assert led["largest_waste"] == "idle"
    assert led["utilization"]["decode"]["hbm_bw_pct"] is not None
    assert led["utilization"]["decode"]["mfu_pct"] is None  # no peak
    # wall SHORTER than dispatch time (clock jitter): still <= 1
    led2 = ledger(summary, wall_ms=50.0)
    assert sum(led2["buckets"].values()) <= 1.0 + 1e-9
    assert led2["buckets"]["idle"] == 0.0
    # fleet merge re-weights by wall
    merged = merge_ledgers([led, led])
    assert sum(merged["buckets"].values()) <= 1.0 + 1e-9
    assert merged["wall_ms"] == pytest.approx(400.0)
    assert merged["largest_waste"] == "idle"
    assert merge_ledgers([]) == {} and merge_ledgers([None]) == {}


# ----------------------------------------- THE live reconciliation pin


@pytest.mark.parametrize("paged", [True, False])
def test_ledger_reconciles_with_timeline_and_counters(tiny, paged):
    """The acceptance invariant: bucket fractions sum to <= 1.0 and
    reconcile with timeline ms/compile_ms/tokens and the engine's
    wasted_steps/spec counters on a LIVE run (speculation + prefix on,
    mixed budgets so chunk overshoot, draft rejection, and padding all
    actually occur)."""
    model, params = tiny
    server = Server(model, params, batch_size=3, eos_id=-1,
                    chunk_steps=4, speculate_k=3, prefix_cache_mb=1.0,
                    paged=paged)

    def reqs(base):
        return [Request([1, 2, 3, 1, 2, 3, 1, 2], 9, id=base),
                Request([5, 4, 3, 2], 3, id=base + 1),
                Request([1, 2, 3, 1, 2, 3, 1, 2], 11, id=base + 2),
                Request([9, 8], 5, id=base + 3)]

    # two passes through the SAME engine: the first pays every
    # (kind, shape) first-call — all compile-bucket — the second runs
    # the same programs steady, so overshoot/padding carry real time
    results = list(server.run(reqs(0))) + list(server.run(reqs(10)))
    assert len(results) == 8

    summ = server.timeline.summary()
    # per-kind exact split: useful+padding+overshoot+rejected == steady
    for kind, a in summ.items():
        split = (a["useful_ms"] + a["padding_ms"] + a["overshoot_ms"]
                 + a["rejected_ms"])
        assert split == pytest.approx(a["ms"] - a["compile_ms"],
                                      abs=0.05), kind
    # position accounting reproduces the engine's waste counter
    wasted = sum(summ[k]["fed"] - summ[k]["tokens"]
                 for k in ("decode", "verify") if k in summ)
    assert wasted == server.wasted_steps
    # landed tokens reconcile with what the requests kept
    landed = sum(a["tokens"] for a in summ.values())
    assert landed == sum(len(r.tokens) for r in results)
    # every record was priced
    assert all(a["est_bytes"] > 0 for a in summ.values())

    led = server.goodput()
    assert sum(led["buckets"].values()) <= 1.0 + 1e-6
    assert led["largest_waste"] in WASTE_BUCKETS
    assert led["useful_fraction"] > 0
    # fresh engine: the first calls flagged compile carry real time
    assert led["ms"]["compile"] > 0
    # batch 3 with stragglers pads (empty slots in the static shape);
    # chunk overshoot has its own deterministic pin below
    assert led["ms"]["padding"] > 0
    # CPU box: no roofline reference -> utilization is null, bytes
    # real (speculation can make every decode round a verify, so pick
    # whichever step kind this run produced)
    step_kind = "verify" if "verify" in led["utilization"] else "decode"
    if detect_hbm_gbps() == 0.0:
        assert led["hbm_gbps"] is None
        assert led["utilization"][step_kind]["hbm_bw_pct"] is None
    assert led["utilization"][step_kind]["est_bytes"] > 0


def test_overshoot_bucket_charges_trimmed_chunk_time(tiny):
    """A slot finishing mid-chunk decodes trimmed garbage to the chunk
    end — the `wasted_steps` counter as TIME, pinned on the
    in_dispatch_eos=False control: a steady k=4 chunk round with a
    budget-3 co-tenant must charge the overshoot bucket, and the
    position accounting must equal the counter exactly."""
    model, params = tiny
    server = Server(model, params, batch_size=2, eos_id=-1,
                    chunk_steps=4, in_dispatch_eos=False)

    def run_pair(base):
        list(server.run([Request([1, 2, 3], 3, id=base),
                         Request([4, 5, 6], 9, id=base + 1)]))

    run_pair(0)   # first pass pays the compiles
    run_pair(10)  # steady: the budget-3 slot overshoots the k=4 chunk
    assert server.wasted_steps > 0
    summ = server.timeline.summary()
    assert summ["decode"]["fed"] - summ["decode"]["tokens"] \
        == server.wasted_steps
    led = server.goodput()
    assert led["ms"]["overshoot"] > 0


def test_in_dispatch_eos_zeroes_the_overshoot_bucket(tiny):
    """ISSUE-13: the same mixed-budget workload under the default
    in-dispatch EOS freeze lands ZERO overshoot — fed == landed on
    every decode dispatch, the trailing positions are frozen re-emits
    charged to padding, and the reconciliation pins hold without
    loosening (wasted_steps stays exactly sum(fed - tokens) == 0)."""
    model, params = tiny
    server = Server(model, params, batch_size=2, eos_id=-1,
                    chunk_steps=4)

    def run_pair(base):
        list(server.run([Request([1, 2, 3], 3, id=base),
                         Request([4, 5, 6], 9, id=base + 1)]))

    run_pair(0)   # first pass pays the compiles
    run_pair(10)  # steady: the budget-3 slot FREEZES inside the chunk
    assert server.wasted_steps == 0
    assert server.frozen_steps > 0
    assert server.freeze_faults == 0
    summ = server.timeline.summary()
    assert summ["decode"]["fed"] == summ["decode"]["tokens"]
    led = server.goodput()
    assert led["ms"]["overshoot"] == 0.0
    assert led["ms"]["padding"] > 0  # the frozen tail lands here


def test_explicit_hbm_reference_prices_utilization(tiny):
    model, params = tiny
    server = Server(model, params, batch_size=2, eos_id=-1,
                    hbm_gbps=800.0)
    list(server.run([Request([1, 2, 3], 4, id=0)]))
    list(server.run([Request([1, 2, 4], 4, id=1)]))  # steady pass
    led = server.goodput()
    assert led["hbm_gbps"] == 800.0
    util = led["utilization"]["decode"]
    assert util["hbm_bw_pct"] is not None and util["hbm_bw_pct"] > 0
    # per-dispatch tags carry the same estimate
    recs = [r for r in server.timeline.recent() if r.kind == "decode"]
    assert recs and all("hbm_bw_pct" in r.tags for r in recs
                        if not r.compile)


def test_merged_local_plus_remote_ledger_pins(tiny):
    """ISSUE-15: the sums-<=1 and fed==landed reconciliation pins,
    extended to a MERGED local+remote fleet — a gateway over one
    in-process engine and one remote stub whose ledger/timeline
    arrive over the obs-pull channel. The merged engine.dispatch
    block must keep the position-accounting identities (in-dispatch
    EOS: fed == tokens on decode, fleet wasted_steps == 0) and the
    merged ledger must keep its structural invariant with the pulled
    remote ledger included."""
    import time as _time

    from tony_tpu.gateway.remote import RemoteServer
    from tony_tpu.serve.agent import AgentHTTP, ReplicaAgent

    model, params = tiny
    agent = AgentHTTP(ReplicaAgent(Server(
        model, params, batch_size=2, eos_id=-1))).start()
    stub = RemoteServer(agent.address, heartbeat_interval_s=0.1,
                        lease_misses=3, boot_timeout_s=20.0)
    local = Server(model, params, batch_size=2, eos_id=-1)
    gw = Gateway([local, stub], max_queue=32, max_attempts=3,
                 stall_timeout_s=10.0, breaker_base_s=0.05,
                 breaker_max_s=0.2).start()
    try:
        n, budget = 6, 8
        tickets = [gw.submit(GenRequest([1 + i, 2, 3],
                                        max_new_tokens=budget,
                                        id=i)) for i in range(n)]
        for t in tickets:
            t.result(timeout=120)
        # both replicas actually served (least-outstanding spread)
        hosts = {t.metrics["host"] for t in tickets}
        assert hosts == {"local", agent.address}, hosts
        remote_tokens = sum(budget for t in tickets
                            if t.metrics["host"] == agent.address)
        deadline = _time.monotonic() + 30
        while _time.monotonic() < deadline:
            summ = stub.timeline.summary()
            if summ and sum(a["tokens"] for a in summ.values()) \
                    >= remote_tokens:
                break
            _time.sleep(0.02)
        stub._obs_pull = False  # freeze the pulled state
        snap = gw.snapshot()
        disp = snap["engine"]["dispatch"]
        # landed tokens reconcile across BOTH replicas' timelines
        assert sum(a["tokens"] for a in disp.values()) == n * budget
        # in-dispatch EOS identity survives the merge: every decode
        # position fed landed a kept token, fleet-wide
        assert disp["decode"]["fed"] == disp["decode"]["tokens"]
        assert snap["engine"]["wasted_steps"] == 0
        # the merged ledger: local + pulled-remote, sums <= 1, and
        # both constituent ledgers were real
        rows = {r["replica"]: r for r in snap["replicas"]}
        assert rows[0]["goodput"] is not None  # local
        assert rows[1]["goodput"] is not None  # pulled remote
        for row in rows.values():
            assert sum(row["goodput"]["buckets"].values()) <= 1 + 1e-6
        fleet = snap["engine"]["goodput"]
        assert fleet and sum(fleet["buckets"].values()) <= 1 + 1e-6
        assert fleet["wall_ms"] > max(
            rows[0]["goodput"]["wall_ms"],
            rows[1]["goodput"]["wall_ms"])  # both walls summed
        assert fleet["useful_fraction"] > 0
        assert fleet["largest_waste"] in WASTE_BUCKETS
        # /debug/goodput's report shape holds over the mixed fleet
        report = gw.goodput_report()
        assert report["enabled"]
        assert {r["replica"] for r in report["replicas"]} == {0, 1}
    finally:
        gw.drain(timeout=60)
        agent.stop()


def test_goodput_none_with_timeline_off(tiny):
    model, params = tiny
    server = Server(model, params, batch_size=2, eos_id=-1,
                    timeline=False)
    list(server.run([Request([1, 2, 3], 3, id=0)]))
    assert server.goodput() is None


# ------------------------------------------------------ alert bus units


def test_alert_bus_fire_once_resolve_debounced():
    state = {"on": False}
    rule = Rule("toggling", check=lambda s: {"x": 1} if state["on"]
                else None, fire_after=1, resolve_after=2)
    bus = AlertBus([rule])
    assert bus.evaluate({}) == []
    state["on"] = True
    events = bus.evaluate({})
    assert [e.state for e in events] == ["firing"]
    # active: no re-fire while the condition holds
    assert bus.evaluate({}) == [] and len(bus.active()) == 1
    state["on"] = False
    assert bus.evaluate({}) == []  # first clear tick: debounced
    events = bus.evaluate({})      # second: resolves
    assert [e.state for e in events] == ["resolved"]
    assert bus.active() == []
    snap = bus.snapshot()
    assert snap["fired"]["toggling"] == 1
    assert snap["resolved"]["toggling"] == 1
    assert len(snap["recent"]) == 2
    # a blip shorter than fire_after never fires
    blip = Rule("blip", check=lambda s: s.get("d"), fire_after=2)
    bus2 = AlertBus([blip])
    bus2.evaluate({"d": {"x": 1}})
    assert bus2.evaluate({}) == [] and bus2.active() == []


def test_alert_bus_survives_raising_rule():
    def boom(signals):
        raise RuntimeError("broken rule")

    bus = AlertBus([Rule("boom", check=boom),
                    Rule("ok", check=lambda s: {"v": 1})])
    events = bus.evaluate({})
    assert [e.alert for e in events] == ["ok"]


def test_queue_and_kv_rules_predicates():
    q = QueueAgingRule(queue_wait_s=2.0)
    assert q.evaluate({"oldest_wait_s": 1.0}) is None
    assert q.evaluate({"oldest_wait_s": 3.0, "depth": 4})[
        "oldest_wait_s"] == 3.0
    kv = KvPagesPressureRule(kv_free_frac=0.15)
    assert kv.evaluate({"kv_pages_total": 0}) is None  # unpaged fleet
    busy = {"kv_pages_total": 10, "kv_pages_free": 10,
            "kv_pages_reserved": 10, "active_slots": 1, "depth": 0}
    assert kv.evaluate(busy)["free_after_reserve_frac"] == 0.0
    idle = dict(busy, active_slots=0)
    assert kv.evaluate(idle) is None  # residency without load != pressure
    roomy = dict(busy, kv_pages_reserved=2)
    assert kv.evaluate(roomy) is None


def test_ttft_burn_rule_histogram_delta():
    rule = TtftSloBurnRule(ttft_slo_s=0.25, burn_frac=0.10,
                           min_samples=5)

    def hist(count, over):
        return {"count": count,
                "buckets": {"0.25": count - over, "1": over,
                            "+Inf": 0}}

    assert rule.evaluate({"ttft_hist": hist(10, 0)}) is None  # baseline
    # 6 new completions, 0 over: no burn
    assert rule.evaluate({"ttft_hist": hist(16, 0)}) is None
    # 8 new, 4 over the SLO edge: 50% burn
    out = rule.evaluate({"ttft_hist": hist(24, 4)})
    assert out and out["burn_frac"] == pytest.approx(0.5)
    # tiny tick below min_samples never judges
    assert rule.evaluate({"ttft_hist": hist(26, 6)}) is None
    # slo 0 = rule off
    assert TtftSloBurnRule(ttft_slo_s=0.0).evaluate(
        {"ttft_hist": hist(100, 100)}) is None


def test_breaker_flap_and_goodput_collapse_rules():
    flap = BreakerFlapRule(flap_failures=2, flap_window_s=60.0)
    assert flap.evaluate({"now": 0.0, "replica_failures": 0,
                          "states": ["healthy"]}) is None
    assert flap.evaluate({"now": 1.0, "replica_failures": 1,
                          "states": ["healthy"]}) is None
    out = flap.evaluate({"now": 2.0, "replica_failures": 2,
                         "states": ["broken"]})
    assert out and out["failures_in_window"] == 2
    assert out["unhealthy_replicas"] == 1
    # breaker STATES alone never fire: a probing/broken replica is
    # also the routine autoscale probe-admission path — a critical
    # alert per healthy scale-up would bury the real signal
    assert BreakerFlapRule().evaluate(
        {"now": 0.0, "replica_failures": 0,
         "states": ["healthy", "broken", "probing"]}) is None

    from tony_tpu.obs.alerts import ShedStormRule

    storm = ShedStormRule(storm_count=10, storm_window_s=5.0)
    assert storm.evaluate({"now": 0.0,
                           "shed_capacity_total": 0}) is None
    # a slow trickle of sheds never accumulates past the window
    assert storm.evaluate({"now": 1.0,
                           "shed_capacity_total": 4}) is None
    out = storm.evaluate({"now": 2.0, "shed_capacity_total": 15})
    assert out and out["sheds_in_window"] == 15
    assert out["window_s"] == 5.0
    # the window prunes by TIME: the burst above ages out, so the
    # same cumulative level 10 s later is calm, not a storm
    assert storm.evaluate({"now": 12.0,
                           "shed_capacity_total": 16}) is None

    col = GoodputCollapseRule(collapse_frac=0.5, min_updates=3)
    state = {"toks": 0, "useful": 0.0, "disp": 0.0}

    def tick(rule, d_useful, d_disp, flowing=True):
        state["toks"] += 10 if flowing else 0
        state["useful"] += d_useful
        state["disp"] += d_disp
        return rule.evaluate({"goodput_useful_ms": state["useful"],
                              "goodput_dispatch_ms": state["disp"],
                              "tokens_out": state["toks"]})

    for _ in range(5):  # establish the baseline at ~0.8 per-tick
        assert tick(col, 80.0, 100.0) is None
    out = tick(col, 10.0, 100.0)  # this tick's useful collapsed
    assert out and out["baseline"] == pytest.approx(0.8, abs=0.01)
    assert out["useful_fraction"] == pytest.approx(0.1, abs=0.01)
    # idle lulls and trickle traffic must NOT fire: the denominator
    # is DISPATCH time, and tiny-dispatch ticks are not judged
    col2 = GoodputCollapseRule(collapse_frac=0.5, min_updates=3)
    state = {"toks": 0, "useful": 0.0, "disp": 0.0}
    for _ in range(5):
        tick(col2, 80.0, 100.0)
    # fully idle tick (no dispatch, no tokens): not judged
    assert tick(col2, 0.0, 0.0, flowing=False) is None
    # trickle tick: one short healthy request in a mostly-idle
    # second — per-dispatch fraction is still ~0.8, no false fire
    assert tick(col2, 24.0, 30.0) is None
    # sub-threshold dispatch activity: not judged at all
    assert tick(col2, 1.0, 10.0) is None


# ------------------------------------------------- gateway integration


def test_kv_pressure_alert_fires_and_resolves_live(tiny, tmp_path):
    """The serve-smoke acceptance, in-process: a tiny KV page pool
    under live load fires kv_pages_pressure into /stats alerts and
    history metrics/alerts.jsonl, then RESOLVES once load stops."""
    model, params = tiny
    # 6 pages x 4 tokens = 24-token pool; each request's worst case
    # (3 + 20 = 23 tokens -> 6 pages) reserves the WHOLE pool, so
    # pressure is sustained while anything runs and others queue
    hist = GatewayHistory(str(tmp_path))
    gw = Gateway([Server(model, params, batch_size=2, eos_id=-1,
                         kv_page_size=4, kv_pages=6)],
                 history=hist, alert_interval_s=0.02,
                 alert_thresholds={"kv_free_frac": 0.15}).start()
    try:
        tickets = [gw.submit(GenRequest([1 + i, 2, 3],
                                        max_new_tokens=20, id=i))
                   for i in range(6)]
        deadline = time.monotonic() + 60
        fired = False
        while time.monotonic() < deadline and not fired:
            snap = gw.alerts.snapshot()
            fired = any(a["alert"] == "kv_pages_pressure"
                        for a in snap["active"])
            time.sleep(0.005)
        assert fired, gw.alerts.snapshot()
        for t in tickets:
            t.result(timeout=120)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            snap = gw.alerts.snapshot()
            if not snap["active"] and \
                    snap["resolved"].get("kv_pages_pressure"):
                break
            time.sleep(0.02)
        snap = gw.snapshot()["alerts"]
        assert snap["enabled"] and not snap["active"], snap
        assert snap["fired"]["kv_pages_pressure"] >= 1
        assert snap["resolved"]["kv_pages_pressure"] >= 1
    finally:
        assert gw.drain(timeout=60)
    rows = [json.loads(ln) for ln in
            open(hist._alerts_path) if ln.strip()]
    states = {(r["alert"], r["state"]) for r in rows}
    assert ("kv_pages_pressure", "firing") in states, rows
    assert ("kv_pages_pressure", "resolved") in states, rows


def test_alerts_disabled_gateway(tiny):
    model, params = tiny
    gw = Gateway([Server(model, params, batch_size=2, eos_id=-1)],
                 alerts=False).start()
    try:
        gw.submit(GenRequest([1, 2, 3], max_new_tokens=3,
                             id="a")).result(timeout=60)
        assert gw.snapshot()["alerts"] == {"enabled": False}
    finally:
        assert gw.drain(timeout=60)


def _get_json(url):
    with urllib.request.urlopen(url, timeout=60) as r:
        return r.status, json.loads(r.read())


def test_http_debug_goodput_and_traces(tiny):
    """GET /debug/goodput names a largest waste bucket and
    /debug/traces lists buffered traces WITH terminal tags, over real
    HTTP."""
    model, params = tiny
    gw = Gateway([Server(model, params, batch_size=2, eos_id=-1)]).start()
    http = GatewayHTTP(gw, port=0).start()
    url = f"http://{http.host}:{http.port}"
    try:
        body = json.dumps({"token_ids": [1, 2, 3], "max_new_tokens": 4,
                           "request_id": "gp-1"}).encode()
        req = urllib.request.Request(url + "/v1/generate", data=body)
        urllib.request.urlopen(req, timeout=120).read()

        status, doc = _get_json(url + "/debug/goodput")
        assert status == 200 and doc["enabled"]
        assert doc["largest_waste"] in WASTE_BUCKETS
        assert sum(doc["fleet"]["buckets"].values()) <= 1.0 + 1e-6
        assert doc["replicas"][0]["replica"] == 0

        status, doc = _get_json(url + "/debug/traces")
        assert status == 200
        rows = {r["request_id"]: r for r in doc["traces"]}
        assert rows["gp-1"]["outcome"] == "done"
        assert rows["gp-1"]["tokens_out"] == 4
        assert rows["gp-1"]["placements"] == 1  # replica placements
        assert rows["gp-1"]["attempts"] == 0    # failed engine runs
    finally:
        http.stop()
        assert gw.drain(timeout=60)
