"""Device-mesh construction for DP/FSDP/TP/PP/SP/EP parallelism.

New territory relative to the reference (SURVEY.md section 2.4: TonY has no
tensor/pipeline/sequence parallelism — it only orchestrates processes).
Here parallelism is expressed the TPU way: a named ``jax.sharding.Mesh``
over the slice, PartitionSpec annotations, and XLA-inserted collectives
riding ICI (scaling-book recipe: pick a mesh, annotate, let XLA insert
collectives).

Canonical axis names used across the framework:

  data    - data parallelism (batch sharding; gradient psum)
  fsdp    - fully-sharded data parallelism (param/optimizer sharding)
  tensor  - tensor/model parallelism (head & mlp sharding)
  pipe    - pipeline stages
  seq     - sequence/context parallelism (ring attention)
  expert  - expert parallelism (MoE all-to-all)
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

import jax
import numpy as np
from jax.sharding import Mesh

DATA, FSDP, TENSOR, PIPE, SEQ, EXPERT = "data", "fsdp", "tensor", "pipe", "seq", "expert"
ALL_AXES = (DATA, FSDP, TENSOR, PIPE, SEQ, EXPERT)


@dataclass
class MeshSpec:
    """Sizes per logical axis; -1 on exactly one axis means "absorb the
    remaining devices" (like a reshape wildcard)."""

    data: int = -1
    fsdp: int = 1
    tensor: int = 1
    pipe: int = 1
    seq: int = 1
    expert: int = 1

    def sizes(self) -> dict[str, int]:
        return {
            DATA: self.data,
            FSDP: self.fsdp,
            TENSOR: self.tensor,
            PIPE: self.pipe,
            SEQ: self.seq,
            EXPERT: self.expert,
        }

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = self.sizes()
        wild = [a for a, s in sizes.items() if s == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one wildcard axis, got {wild}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}")
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh axes product {fixed} != device count {n_devices}")
        return sizes


def make_mesh(spec: MeshSpec | None = None, devices=None,
              drop_trivial: bool = False) -> Mesh:
    """Build the named mesh. Axis order is (data, fsdp, tensor, pipe, seq,
    expert) — outer axes map to DCN/slower links, inner axes to ICI, which
    is the layout that keeps tensor/seq collectives on the fastest rings.
    """
    devices = list(devices if devices is not None else jax.devices())
    spec = spec or MeshSpec()
    sizes = spec.resolve(len(devices))
    names = [a for a in ALL_AXES if not (drop_trivial and sizes[a] == 1)]
    shape = [sizes[a] for a in names]
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, tuple(names))


def data_parallel_mesh(devices=None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices), (DATA,))


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape.get(axis, 1)
