from tony_tpu.client.client import TaskUpdateListener, TonyClient

__all__ = ["TonyClient", "TaskUpdateListener"]
