from tony_tpu.workflow.job import FlowContext, WorkflowJob
from tony_tpu.workflow.airflow import TonyTpuOperator

__all__ = ["FlowContext", "WorkflowJob", "TonyTpuOperator"]
