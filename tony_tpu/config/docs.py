"""Config reference generator.

The reference ships ``tony-default.xml`` (417 lines, 60 keys) which doubles
as the user-facing documentation of every configuration key
(tony-core/src/main/resources/tony-default.xml); TonY's wiki renders it.
Here the typed schema in ``keys.py`` is the single source of truth, and this
module renders it to markdown. ``CONFIG.md`` at the repo root is the checked
-in rendering, drift-locked by ``tests/test_config.py`` the same way
``TestTonyConfigurationFields`` locks keys <-> XML in the reference
(SURVEY.md section 4.3).

Regenerate with::

    python -m tony_tpu.config.docs > CONFIG.md
"""

from __future__ import annotations

from tony_tpu.config import keys as K

_HEADER = """\
# tony-tpu configuration reference

<!-- GENERATED FILE — do not edit. Regenerate with:
     python -m tony_tpu.config.docs > CONFIG.md
     tests/test_config.py fails if this file drifts from the schema. -->

Every key, its default, type, and meaning. Layering precedence (low to
high): built-in defaults -> `--conf_file` (TOML/JSON/k=v) -> repeated
`--conf k=v` CLI overrides -> `$TONY_CONF_DIR/tony-site.*`. The merged
config is written to the job dir as `tony-final.json` and re-read by the
coordinator and every agent (reference: tony-default.xml + tony.xml +
`--conf` + tony-site.xml -> tony-final.xml).
"""

_ROLE_HEADER = """\
## Per-role keys: `tony.<role>.*`

Role names are free-form (reference: TonyConfigurationKeys.java:189-257 —
`tony.<role>.instances` etc. are regex-matched, so users can invent roles
like `head` for ray). Reserved namespace segments that are never parsed as
role names: {reserved}.
"""


def _fmt_default(v) -> str:
    if v == "":
        return "(empty)"
    if isinstance(v, bool):
        return "true" if v else "false"
    return f"`{v}`"


def _table(rows: list[tuple[str, K.Key]]) -> list[str]:
    out = ["| Key | Default | Type | Description |",
           "|---|---|---|---|"]
    for name, key in rows:
        doc = key.doc.replace("|", "\\|")  # literal pipes break md tables
        out.append(f"| `{name}` | {_fmt_default(key.default)} | "
                   f"{key.type.__name__} | {doc} |")
    return out


def render_config_reference() -> str:
    """Markdown reference for every global and per-role key."""
    from tony_tpu.config.config import _NON_ROLE_SEGMENTS

    groups: dict[str, list[tuple[str, K.Key]]] = {}
    for name, key in K.KEYS.items():
        prefix = ".".join(name.split(".")[:2])
        groups.setdefault(prefix, []).append((name, key))

    lines = [_HEADER]
    for prefix in sorted(groups):
        lines.append(f"## `{prefix}.*`\n")
        lines.extend(_table(sorted(groups[prefix])))
        lines.append("")
    reserved = ", ".join(f"`{s}`" for s in sorted(_NON_ROLE_SEGMENTS))
    lines.append(_ROLE_HEADER.format(reserved=reserved))
    lines.extend(_table(sorted(K.ROLE_SUFFIXES.items())))
    lines.append("")
    multi = ", ".join(f"`{k}`" for k in sorted(K.MULTI_VALUE_KEYS))
    lines.append("## Multi-value keys\n")
    lines.append(f"Repeated `--conf` occurrences append (not replace) for: "
                 f"{multi} (reference: TonyClient.java:672-684).")
    return "\n".join(lines) + "\n"


if __name__ == "__main__":
    print(render_config_reference(), end="")
