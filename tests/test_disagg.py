"""Disaggregated prefill/decode (ISSUE-12): chunked prefill, the
prefill/decode role split with page-list handoff (local AND over the
agent wire), and prefix-affinity routing.

The exactness discipline is the same as test_paged/test_prefix: every
new path is pinned TOKEN-IDENTICAL to the single-pool interleaved
control — chunked prefill against monolithic, role-split against a
generalist gateway (greedy and seeded sampling both), remote handoff
against local. The scheduling claims (a long prompt no longer starves
co-tenants; affinity beats least-outstanding to the warm replica) are
pinned on deterministic counters, not wall clocks. CPU-only.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.gateway import Gateway, GenRequest
from tony_tpu.models import Transformer, TransformerConfig
from tony_tpu.serve import Request, Server


@pytest.fixture(scope="module")
def tiny():
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_seq_len=64,
                            dtype=jnp.float32,
                            attention_backend="reference")
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _prompts(seed=0, sizes=(40, 6, 24, 12)):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, 64, size=n).tolist() for n in sizes]


def _collect(server, reqs):
    for r in reqs:
        server.submit(r)
    out = {}
    for res in server.run():
        out[res.id] = res
    return out


# ------------------------------------------------------ chunked prefill


@pytest.mark.parametrize("paged", [True, False])
def test_chunked_prefill_token_parity(tiny, paged):
    """Greedy outputs are byte-identical chunked vs monolithic, on
    both cache layouts, and the chunk accounting shows on the Result
    (a 40-token prompt at a 16-token budget = 3 dispatches)."""
    model, params = tiny
    prompts = _prompts()

    def run(chunk):
        srv = Server(model, params, batch_size=2, paged=paged,
                     kv_page_size=8, prefill_chunk_tokens=chunk)
        return _collect(srv, [Request(list(p), 6, id=i)
                              for i, p in enumerate(prompts)]), srv

    mono, _ = run(0)
    chunked, srv = run(16)
    assert {i: r.tokens for i, r in mono.items()} \
        == {i: r.tokens for i, r in chunked.items()}
    assert chunked[0].prefill_chunks == 3      # 16 + 16 + final 8
    assert chunked[1].prefill_chunks == 1      # short prompt: one shot
    assert srv.prefill_chunk_dispatches >= 3
    assert srv.prefill_chunked == 2            # the 40- and 24-token

    # sampled requests too: the first-token draw and rng chain must
    # survive the chunk boundary
    def run_sampled(chunk):
        srv = Server(model, params, batch_size=2, paged=paged,
                     kv_page_size=8, prefill_chunk_tokens=chunk)
        return _collect(srv, [
            Request(list(prompts[0]), 6, id=0, temperature=0.8,
                    top_k=5, seed=3)])

    assert run_sampled(0)[0].tokens == run_sampled(16)[0].tokens


def test_chunked_prefill_with_prefix_seed_parity(tiny):
    """Chunking composes with the prefix store: the second request's
    suffix prefills in chunks FROM the seeded offset, token-exact vs
    the store-on monolithic control."""
    model, params = tiny
    rng = np.random.default_rng(1)
    base = rng.integers(1, 64, size=32).tolist()
    prompts = [base + rng.integers(1, 64, size=8).tolist()
               for _ in range(2)]

    def run(chunk):
        srv = Server(model, params, batch_size=2, paged=True,
                     kv_page_size=8, prefix_cache_mb=2.0,
                     prefill_chunk_tokens=chunk)
        outs = {}
        for i, p in enumerate(prompts):
            srv.submit(Request(list(p), 5, id=i))
            outs.update({r.id: r.tokens for r in srv.run()})
        return outs, srv

    mono, _ = run(0)
    chunked, srv = run(16)
    assert mono == chunked
    assert srv.prefix_hits >= 1  # the seed actually engaged


def test_chunked_prefill_interleaves_decode_rounds(tiny):
    """The starvation cap itself: a short co-tenant FINISHES while the
    long prompt is still mid-chunked-prefill — under a monolithic
    admit the short request could not even decode before the long
    prefill completed its dispatch."""
    model, params = tiny
    rng = np.random.default_rng(2)
    long_p = rng.integers(1, 64, size=40).tolist()
    srv = Server(model, params, batch_size=2, paged=True,
                 kv_page_size=8, prefill_chunk_tokens=16)
    srv.submit(Request(list(long_p), 6, id="long"))
    srv.submit(Request([9, 9, 9], 2, id="short"))
    finished = srv.step()  # admits both; long takes chunk 1 only
    assert srv.n_prefilling == 1
    assert any(r.id == "short" for r in finished), \
        "short co-tenant should finish while the long prompt is " \
        "still prefilling"
    rest = list(srv.run())
    assert any(r.id == "long" and r.prefill_chunks == 3 for r in rest)


def test_chunked_prefill_reset_releases_pages(tiny):
    """A reset mid-chunked-prefill hands every page + reservation
    back (the failover recovery path must not leak the parked
    slot's pool state)."""
    model, params = tiny
    srv = Server(model, params, batch_size=2, paged=True,
                 kv_page_size=8, prefill_chunk_tokens=16)
    srv.submit(Request(list(range(1, 41)), 6, id=0))
    srv.step()
    assert srv.n_prefilling == 1
    srv.reset()
    pool = srv.slots.pool
    assert srv.n_prefilling == 0 and srv.done
    assert pool.n_used == 0 and pool.reserved == 0


# ------------------------------------------------------ role-split local


def _mk_server(tiny, **kw):
    model, params = tiny
    kw.setdefault("prefix_cache_mb", 2.0)
    return Server(model, params, batch_size=2, paged=True,
                  kv_page_size=8, **kw)


def _request_mix(prompts):
    """Greedy + seeded-sampled requests over the same prompts."""
    return [
        GenRequest(list(p), 8, id=f"r{i}", seed=i,
                   temperature=0.5 if i % 2 else 0.0,
                   top_k=4 if i % 2 else 0)
        for i, p in enumerate(prompts)
    ]


def test_role_split_token_parity_and_accounting(tiny):
    """The headline pin: a prefill=1,decode=1 fleet with chunked
    prefill produces byte-identical streams (greedy AND seeded
    sampling) to a generalist single-pool control; the decode pool ran
    ZERO prefill dispatches and every request crossed as a handoff."""
    prompts = _prompts(3)
    gw = Gateway([_mk_server(tiny, prefill_chunk_tokens=16),
                  _mk_server(tiny)],
                 roles=["prefill", "decode"]).start()
    ctrl = Gateway([_mk_server(tiny)]).start()
    try:
        tickets = [gw.submit(r) for r in _request_mix(prompts)]
        outs = {t.request.id[1:]: t.result(timeout=300).tokens
                for t in tickets}
        ctl = {r.id[1:]: ctrl.submit(r).result(timeout=300).tokens
               for r in _request_mix(prompts)}
        assert outs == ctl
        snap = gw.snapshot()
        assert snap["shed"] == {}, snap["shed"]
        assert snap["routing"]["handoffs"] == len(prompts)
        assert snap["engine"]["handoffs"]["out"] == len(prompts)
        assert snap["engine"]["handoffs"]["in"] == len(prompts)
        rows = {r["replica"]: r for r in snap["replicas"]}
        assert rows[0]["role"] == "prefill"
        assert rows[1]["role"] == "decode"
        assert rows[0]["prefills"] > 0
        assert rows[1]["prefills"] == 0  # decode pool never prefills
        assert rows[1]["handoffs_in"] == len(prompts)
        # the per-request record names both halves
        meta = tickets[0].metrics
        assert meta["prefill_replica"] == 0
        assert meta["replica"] == 1
        assert meta["prefill_chunks"] == 3  # 40 tokens at 16/chunk
    finally:
        gw.drain(timeout=60)
        ctrl.drain(timeout=60)


def test_role_split_hot_prompt_skips_prefill_entirely(tiny):
    """An exact-repeat prompt on the prefill pool hands off as a pure
    page gather (no prefill dispatch at all) — the fleet-wide
    hot-system-prompt story."""
    prompt = list(range(1, 25))
    gw = Gateway([_mk_server(tiny), _mk_server(tiny)],
                 roles=["prefill", "decode"]).start()
    try:
        a = gw.submit(GenRequest(list(prompt), 4, id="a"))
        ra = a.result(timeout=300)
        before = gw.replicas[0].server.prefills
        b = gw.submit(GenRequest(list(prompt), 4, id="b"))
        rb = b.result(timeout=300)
        assert gw.replicas[0].server.prefills == before
        assert b.metrics["prefix_hit_tokens"] == len(prompt)
        assert b.metrics["prefill_chunks"] == 0
        assert rb.tokens == ra.tokens  # greedy repeat: same stream
    finally:
        gw.drain(timeout=60)


def test_handoff_geometry_mismatch_refused_at_submit(tiny):
    """A cross-pool page-geometry mismatch (independently launched
    agents CAN disagree on --kv-page-size) must be one request's
    clean ValueError at submit — discovered inside step() it would
    fail the whole replica and cascade through failover."""
    model, params = tiny
    pre = Server(model, params, batch_size=2, paged=True,
                 kv_page_size=16)
    dec = Server(model, params, batch_size=2, paged=True,
                 kv_page_size=4)
    prompt = list(range(1, 23))
    pre.submit(Request(list(prompt), 4, id="x", prefill_only=True))
    (hand,) = pre.run()
    # 22 tokens: 2 pages of 16 from the prefill pool, but the decode
    # pool needs 6 pages of 4 — the payload cannot cover the prompt
    with pytest.raises(ValueError, match="page geometry"):
        dec.submit(Request(list(prompt), 4, id="x",
                           handoff=hand.handoff))
    assert dec.done  # nothing admitted, nothing leaked


def test_roles_validation(tiny):
    model, params = tiny
    paged = _mk_server(tiny)
    unpaged = Server(model, params, batch_size=2, paged=False)
    with pytest.raises(ValueError, match="at least one"):
        Gateway([paged, _mk_server(tiny)], roles=["prefill", "prefill"])
    with pytest.raises(ValueError, match="paged"):
        Gateway([paged, unpaged], roles=["prefill", "decode"])
    with pytest.raises(ValueError, match="names"):
        Gateway([paged], roles=["prefill", "decode"])
    with pytest.raises(ValueError, match="disaggregation"):
        unpaged.submit(Request([1, 2], 2, prefill_only=True))


def test_role_split_decode_failover_reruns_handoff(tiny):
    """A decode replica failing mid-stream re-runs the ticket — with
    its handoff payload — on another decode replica, token-exact
    (the payload is immutable; the retry scatters the same bytes)."""
    import os
    from unittest import mock

    fault = '{"op": "fail", "dispatch": 3, "replica": 1}'
    with mock.patch.dict(os.environ, {"TONY_SERVE_FAULTS": fault}):
        from tony_tpu.serve import FaultPlan

        servers = [_mk_server(tiny), _mk_server(tiny),
                   _mk_server(tiny)]
        servers[1].fault_plan = FaultPlan.from_env(replica=1)
    gw = Gateway(servers, roles=["prefill", "decode", "decode"],
                 stall_timeout_s=30.0, breaker_base_s=0.1).start()
    ctrl = Gateway([_mk_server(tiny)]).start()
    try:
        prompts = _prompts(5, sizes=(24, 18))
        outs = {}
        for i, p in enumerate(prompts):
            outs[i] = gw.submit(GenRequest(list(p), 8, id=f"r{i}")) \
                .result(timeout=300).tokens
        for i, p in enumerate(prompts):
            got = ctrl.submit(GenRequest(list(p), 8, id=f"c{i}")) \
                .result(timeout=300).tokens
            assert outs[i] == got, i
        snap = gw.snapshot()
        assert snap["shed"] == {}, snap["shed"]
        assert snap["supervision"]["replica_failures"] >= 1
    finally:
        gw.drain(timeout=60)
        ctrl.drain(timeout=60)


# ----------------------------------------------------- role-split remote


def test_role_split_remote_agents_token_parity(tiny):
    """The /v1/handoff wire op: both pools behind real agent HTTP
    shims — the payload crosses the wire base64-encoded in BOTH
    directions (prefill result -> gateway -> decode submit) and stays
    token-exact vs a local generalist control."""
    from tony_tpu.gateway import RemoteServer
    from tony_tpu.serve.agent import AgentHTTP, ReplicaAgent

    prompts = _prompts(4, sizes=(40, 6, 24))
    https = [AgentHTTP(ReplicaAgent(_mk_server(
        tiny, prefill_chunk_tokens=16))).start(),
        AgentHTTP(ReplicaAgent(_mk_server(tiny))).start()]
    stubs = [RemoteServer(h.address, heartbeat_interval_s=0.2)
             for h in https]
    gw = Gateway(stubs, roles=["prefill", "decode"]).start()
    ctrl = Gateway([_mk_server(tiny)]).start()
    try:
        outs = {r.id: gw.submit(r).result(timeout=300).tokens
                for r in _request_mix(prompts)}
        for r in _request_mix(prompts):
            got = ctrl.submit(
                GenRequest(list(r.prompt), 8, id=f"c{r.id}",
                           seed=r.seed, temperature=r.temperature,
                           top_k=r.top_k)).result(timeout=300).tokens
            assert outs[r.id] == got, r.id
        snap = gw.snapshot()
        assert snap["shed"] == {}, snap["shed"]
        assert snap["routing"]["handoffs"] == len(prompts)
    finally:
        gw.drain(timeout=60)
        ctrl.drain(timeout=60)
        for h in https:
            h.stop()


# -------------------------------------------------------- prefix affinity


def test_prefix_affinity_routes_to_warm_replica(tiny):
    """The router sends a shared-prefix request to the replica whose
    radix tree holds it, even when least-outstanding points the other
    way — and with affinity OFF (the A/B control) the same skew sends
    it to the cold replica."""
    base = list(range(1, 21))

    def run(affinity):
        gw = Gateway([_mk_server(tiny), _mk_server(tiny)],
                     prefix_affinity=affinity).start()
        try:
            gw.submit(GenRequest(list(base), 4,
                                 id="warm")).result(timeout=300)
            # skew load so least-outstanding prefers replica 1
            gw.replicas[0].outstanding = 500
            t = gw.submit(GenRequest(list(base) + [7, 8], 4,
                                     id="probe"))
            t.result(timeout=300)
            return t.metrics["replica"], gw.snapshot()["routing"]
        finally:
            gw.drain(timeout=60)

    replica, routing = run(True)
    assert replica == 0 and routing["prefix_routed"] >= 1, routing
    replica_off, routing_off = run(False)
    assert replica_off == 1 and routing_off["prefix_routed"] == 0


def test_prefix_affinity_ignores_trivial_matches(tiny):
    """A sub-threshold match (shorter than _AFFINITY_MIN_TOKENS and
    not the whole prompt) must NOT override load balance."""
    gw = Gateway([_mk_server(tiny), _mk_server(tiny)]).start()
    try:
        gw.submit(GenRequest([5, 6, 7], 3, id="a")).result(timeout=300)
        gw.replicas[0].outstanding = 500
        # shares only the 3-token prefix -> below the 8-token floor
        t = gw.submit(GenRequest([5, 6, 7] + list(range(30, 50)), 3,
                                 id="b"))
        t.result(timeout=300)
        assert t.metrics["replica"] == 1
    finally:
        gw.drain(timeout=60)


def test_prefix_store_radix_shape_stats(tiny):
    """Satellite: PrefixStore.stats() carries nodes and max_depth (in
    tokens), and they track inserts/splits/evictions."""
    from tony_tpu.serve import PrefixStore

    store = PrefixStore(1 << 20)
    empty = store.stats()
    assert empty["nodes"] == 1 and empty["max_depth"] == 0
    row = {"k": np.zeros((4,), np.float32)}
    store.insert(np.arange(10, dtype=np.int32), row)
    st = store.stats()
    assert st["nodes"] == 2 and st["max_depth"] == 10
    # shares 4 tokens: the edge splits -> mid node + two leaves
    seq = np.concatenate([np.arange(4), np.arange(50, 56)]) \
        .astype(np.int32)
    store.insert(seq, row)
    st = store.stats()
    assert st["nodes"] == 4 and st["max_depth"] == 10
    assert store.match_len(np.arange(10, dtype=np.int32)) == 10
    assert store.match_len(np.arange(4, dtype=np.int32)) == 4
    assert store.has(seq) and not store.has(np.arange(3))
