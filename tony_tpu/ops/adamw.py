"""Fused AdamW: one pallas pass over (grad, param, mu, nu) per step.

The optimizer bucket of the flagship step is pure HBM bandwidth. Measured
on-chip (v5e, 378M-param tree, device-busy trace): XLA already fuses the
optax `scale_by_adam -> add_decayed -> scale -> apply_updates` chain into
elementwise fusions running at ~670 GB/s — the materialized-updates tax
the r4 trace suggested does not exist at this scale, and a straight
pallas transcription only matches it (647 GB/s; with
``input_output_aliases`` it HALVES to ~350 GB/s on this backend, so the
kernel deliberately does not alias). The real win is TRAFFIC, which a
kernel makes natural:

- **grads read in compute dtype** (bf16 halves the g pass),
- **the next step's bf16 compute params are emitted by the same pass**
  (``compute_dtype=...``): the train step's separate master->bf16 cast
  pass disappears, and the backward writes bf16 grad leaves instead of
  fp32,
- **optional bf16 moments** (``moment_dtype``): halves the mu/nu passes
  — an accuracy trade the caller opts into.

Math matches ``optax.adamw`` in fp32 (same moment update, bias
correction by ``count+1``, decoupled weight decay, final ``-lr``
scaling); every input is upcast to fp32 in VMEM before the update.

Sharding: a pallas call is opaque to GSPMD (see ops/quant.py's tensor-
parallel note), so under a sharded param tree the update runs per-leaf
under ``shard_map`` with that leaf's PartitionSpec — elementwise math
needs no collectives; every device updates its local shard. Leaves too
small or oddly shaped for the kernel fall back to plain jnp (XLA fuses
those fine; the bandwidth lives in the big matmul kernels anyway).

Reference parity note: the reference framework has no optimizer at all
(training belongs to the user script, SURVEY.md §2.5) — this is part of
tony-tpu's in-tree compute stack built for the TPU roofline.
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from tony_tpu.utils.compat import shard_map
from jax.experimental import pallas as pl
from jax.sharding import Mesh, PartitionSpec as P

from tony_tpu.ops.platform import interpret_mode as _interp

_LANES = 1024  # flat leaves are viewed (rows, _LANES); fp32 tile-friendly
# 7-8 live tiles x 4 B x rows x lanes, double-buffered by Mosaic:
# 128 rows ~= 8 MB of the 16 MB VMEM budget (256 OOM'd on-chip at
# 17-18 MB); 0.5 MB DMA chunks already stream at the measured HBM rate
_BLOCK_ROWS = 128


def _min_kernel_elems() -> int:
    """Leaves with at least this many (local) elements take the pallas
    kernel; the rest take the jnp path. DEFAULT = never: measured on the
    tunneled v5e at flagship scale, the per-pallas-call fixed cost
    (~0.19 ms x 113 leaves) loses to XLA's own elementwise fusions,
    which already run the same 7-pass floor at ~670 GB/s — the fused
    WIN here is the compute-dtype carry + bf16 grads (jnp path), worth
    +1.1 MFU points on the flagship (218.6 vs 223.5 ms/step), while the
    all-pallas variant measured 235.2 ms. Env-tunable for
    experimentation and so dryruns/tests can force the kernel+shard_map
    composition on tiny leaves (interpret mode)."""
    import os

    return int(os.environ.get("TONY_FUSED_ADAMW_MIN_ELEMS",
                              str(1 << 62)))


def _adamw_kernel(hyp_ref, g_ref, p_ref, mu_ref, nu_ref, *out_refs,
                  b1, b2, eps, wd):
    p_out, mu_out, nu_out = out_refs[:3]
    lr = hyp_ref[0, 0]
    c1 = hyp_ref[0, 1]  # 1 / (1 - b1^t)
    c2 = hyp_ref[0, 2]  # 1 / (1 - b2^t)
    g = g_ref[:].astype(jnp.float32)
    mu = b1 * mu_ref[:].astype(jnp.float32) + (1.0 - b1) * g
    nu = b2 * nu_ref[:].astype(jnp.float32) + (1.0 - b2) * g * g
    p = p_ref[:].astype(jnp.float32)
    upd = (mu * c1) / (jnp.sqrt(nu * c2) + eps) + wd * p
    p_new = p - lr * upd
    p_out[:] = p_new.astype(p_out.dtype)
    mu_out[:] = mu.astype(mu_out.dtype)
    nu_out[:] = nu.astype(nu_out.dtype)
    if len(out_refs) == 4:  # fused master->compute cast (bf16 serving of
        out_refs[3][:] = p_new.astype(out_refs[3].dtype)  # the fwd pass)


def _leaf_update_jnp(g, p, mu, nu, lr, c1, c2, *, b1, b2, eps, wd,
                     compute_dtype=None):
    g = g.astype(jnp.float32)
    mu_n = b1 * mu.astype(jnp.float32) + (1.0 - b1) * g
    nu_n = b2 * nu.astype(jnp.float32) + (1.0 - b2) * g * g
    p32 = p.astype(jnp.float32)
    upd = (mu_n * c1) / (jnp.sqrt(nu_n * c2) + eps) + wd * p32
    p_new = p32 - lr * upd
    out = (p_new.astype(p.dtype), mu_n.astype(mu.dtype),
           nu_n.astype(nu.dtype))
    if compute_dtype is not None:
        out += (p_new.astype(compute_dtype),)
    return out


def _leaf_update_kernel(g, p, mu, nu, hyp, *, b1, b2, eps, wd,
                        compute_dtype=None):
    n = p.size
    rows = n // _LANES
    br = min(_BLOCK_ROWS, rows)
    while rows % br:
        br -= 1
    view = lambda a: a.reshape(rows, _LANES)  # noqa: E731
    kern = functools.partial(_adamw_kernel, b1=b1, b2=b2, eps=eps, wd=wd)
    tile = lambda i: (i, 0)  # noqa: E731
    out_shape = [jax.ShapeDtypeStruct((rows, _LANES), p.dtype),
                 jax.ShapeDtypeStruct((rows, _LANES), mu.dtype),
                 jax.ShapeDtypeStruct((rows, _LANES), nu.dtype)]
    if compute_dtype is not None:
        out_shape.append(jax.ShapeDtypeStruct((rows, _LANES),
                                              compute_dtype))
    outs = pl.pallas_call(
        kern,
        out_shape=tuple(out_shape),
        grid=(rows // br,),
        in_specs=[
            pl.BlockSpec((1, 128), lambda i: (0, 0)),
            pl.BlockSpec((br, _LANES), tile),
            pl.BlockSpec((br, _LANES), tile),
            pl.BlockSpec((br, _LANES), tile),
            pl.BlockSpec((br, _LANES), tile),
        ],
        out_specs=(pl.BlockSpec((br, _LANES), tile),) * len(out_shape),
        # NO input_output_aliases: measured on-chip (v5e) aliasing drops
        # the kernel from 647 to ~350 GB/s; buffer liveness is handled by
        # the jit-level donation of the train state instead
        interpret=_interp(),
    )(hyp, view(g), view(p), view(mu), view(nu))
    shape = p.shape
    return tuple(o.reshape(shape) for o in outs)


class FusedAdamWState(NamedTuple):
    count: jnp.ndarray  # int32 step counter (optax ScaleByAdamState twin)
    mu: Any
    nu: Any
    # bf16 (compute-dtype) copy of the params, emitted by the SAME fused
    # pass that writes the fp32 master — the train step forwards/backs
    # through this copy, so no separate cast pass ever runs and grads
    # arrive (and are read by the next update) in compute dtype.
    # None when the caller runs full-precision.
    compute_params: Any = None


class FusedAdamW(NamedTuple):
    """AdamW config consumed by ``fused_adamw_update`` and recognized by
    ``train.Trainer`` as the fused-optimizer flag (pass it where an optax
    transformation would go). Hyperparameters mirror ``optax.adamw``.

    ``moment_dtype`` (e.g. ``jnp.bfloat16``) stores mu/nu at reduced
    precision — halves the moment HBM passes at an accuracy cost the
    caller opts into; default fp32 matches optax bit-for-bit."""

    learning_rate: float = 1e-3
    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8
    weight_decay: float = 1e-4
    moment_dtype: Any = None

    def init(self, params, compute_dtype=None) -> FusedAdamWState:
        def zeros():
            return jax.tree.map(
                lambda p: jnp.zeros(p.shape, self.moment_dtype or p.dtype),
                params)

        compute = None
        if compute_dtype is not None:
            compute = jax.tree.map(
                lambda p: p.astype(compute_dtype)
                if jnp.issubdtype(p.dtype, jnp.floating) else p, params)
        return FusedAdamWState(count=jnp.zeros((), jnp.int32),
                               mu=zeros(), nu=zeros(),
                               compute_params=compute)


def fused_adamw_update(opt: FusedAdamW, grads, state: FusedAdamWState,
                       params, *, mesh: Mesh | None = None,
                       param_specs=None, compute_dtype=None):
    """One fused AdamW step: returns (new_params, new_state).

    ``param_specs`` (a pytree of PartitionSpec matching ``params``) plus
    ``mesh`` routes sharded leaves through shard_map so the kernel runs
    on local shards; replicated/absent specs run the kernel directly.
    ``compute_dtype`` emits ``state.compute_params`` from the same pass.
    """
    count = state.count + 1
    t = count.astype(jnp.float32)
    c1 = 1.0 / (1.0 - jnp.power(opt.b1, t))
    c2 = 1.0 / (1.0 - jnp.power(opt.b2, t))
    # optax-style schedules drop in: a callable learning_rate is
    # evaluated at the PRE-increment count, matching scale_by_schedule
    lr = opt.learning_rate(state.count) if callable(opt.learning_rate) \
        else opt.learning_rate
    lr = jnp.asarray(lr, jnp.float32)
    # scalars ride in one small VMEM operand: lr may be a traced schedule
    # value and t always is, so they cannot be closed over statically
    hyp = jnp.zeros((1, 128), jnp.float32)
    hyp = hyp.at[0, 0].set(lr).at[0, 1].set(c1).at[0, 2].set(c2)
    static = dict(b1=opt.b1, b2=opt.b2, eps=opt.eps, wd=opt.weight_decay)

    leaves_g, treedef = jax.tree_util.tree_flatten(grads)
    leaves_p = treedef.flatten_up_to(params)
    leaves_mu = treedef.flatten_up_to(state.mu)
    leaves_nu = treedef.flatten_up_to(state.nu)
    if param_specs is None:
        leaves_spec = [None] * len(leaves_g)
    else:
        leaves_spec = treedef.flatten_up_to(param_specs)

    out: list[list] = [[], [], [], []]
    for g, p, mu, nu, spec in zip(leaves_g, leaves_p, leaves_mu,
                                  leaves_nu, leaves_spec):
        cdt = compute_dtype if (
            compute_dtype is not None
            and jnp.issubdtype(p.dtype, jnp.floating)) else None
        sharded = (mesh is not None and spec is not None
                   and any(ax is not None for ax in spec))
        # local (per-shard) element count decides the kernel/jnp split.
        # A spec entry may be a TUPLE of axis names (P(('data','fsdp'))
        # — legal, and what batch_sharding emits on multi-axis meshes):
        # the dim splits over every named axis, so divide by each.
        n_local = p.size
        if sharded:
            for ax in spec:
                if ax is None:
                    continue
                for a in (ax if isinstance(ax, tuple) else (ax,)):
                    n_local //= mesh.shape[a]
        if n_local < _min_kernel_elems() or n_local % _LANES:
            new = _leaf_update_jnp(g, p, mu, nu, lr, c1, c2,
                                   compute_dtype=cdt, **static)
        elif sharded:
            fn = functools.partial(_leaf_update_kernel,
                                   compute_dtype=cdt, **static)
            n_out = 3 if cdt is None else 4
            new = shard_map(
                lambda g_, p_, mu_, nu_, h_: fn(g_, p_, mu_, nu_, h_),
                mesh=mesh,
                in_specs=(spec, spec, spec, spec, P(None, None)),
                out_specs=(spec,) * n_out,
                # pallas out_shapes carry no varying-mesh-axes info, so
                # the vma checker cannot type them (same as QuantDense)
                check_vma=False,
            )(g, p, mu, nu, hyp)
        else:
            new = _leaf_update_kernel(g, p, mu, nu, hyp,
                                      compute_dtype=cdt, **static)
        for i, leaf in enumerate(new):
            out[i].append(leaf)
        if cdt is None and compute_dtype is not None:
            # non-float leaf: carry the UPDATED value (new[0]), not the
            # stale input — params and compute_params must never diverge
            # (the train step differentiates through compute_params)
            out[3].append(new[0])

    unflatten = treedef.unflatten
    return unflatten(out[0]), FusedAdamWState(
        count=count, mu=unflatten(out[1]), nu=unflatten(out[2]),
        compute_params=unflatten(out[3]) if compute_dtype is not None
        else None)
