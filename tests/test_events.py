"""Event/history pipeline tests (ref: TestEventHandler, TestParserUtils,
TestHistoryFileUtils, portal mover/purger behavior)."""

import os
import time

from tony_tpu.events import (
    EventHandler,
    EventType,
    application_finished,
    application_inited,
    task_finished,
    task_started,
)
from tony_tpu.events import history
from tony_tpu.events.mover import move_finished_jobs, purge_old_history


def test_handler_writes_and_renames(tmp_path):
    root = str(tmp_path)
    h = EventHandler(root, "application_abc123", user="alice").start()
    h.emit(application_inited("application_abc123", 2, "host0"))
    h.emit(task_started("worker", 0, "host0"))
    h.emit(task_finished("worker", 0, "FINISHED", {"rss": 1.0}))
    h.emit(application_finished("application_abc123", "SUCCEEDED", 0))
    final = h.stop("SUCCEEDED")
    assert os.path.exists(final)
    assert "SUCCEEDED" in os.path.basename(final)
    events = history.parse_events(final)
    assert [e.type for e in events] == [
        EventType.APPLICATION_INITED,
        EventType.TASK_STARTED,
        EventType.TASK_FINISHED,
        EventType.APPLICATION_FINISHED,
    ]
    assert events[2].payload["metrics"] == {"rss": 1.0}
    meta = history.parse_metadata(os.path.dirname(final))
    assert meta.user == "alice"
    assert meta.status == "SUCCEEDED"
    assert meta.completed > 0


def test_emit_after_stop_is_noop(tmp_path):
    h = EventHandler(str(tmp_path), "application_x1").start()
    final = h.stop("FAILED")
    h.emit(task_started("w", 0, "h"))  # must not raise or write
    assert history.parse_events(final) == []


def test_history_name_codec():
    name = history.finished_name("application_1_2", 100, 200, "bob", "FAILED")
    parsed = history.parse_history_name(name)
    assert parsed == {
        "app_id": "application_1_2",
        "started": 100,
        "completed": 200,
        "user": "bob",
        "status": "FAILED",
        "inprogress": False,
    }
    ip = history.inprogress_name("application_9", 55)
    p2 = history.parse_history_name(ip)
    assert p2["inprogress"] and p2["started"] == 55
    assert history.parse_history_name("garbage.txt") is None
    assert history.is_valid_history_name(name)
    assert not history.is_valid_history_name("application_1-abc.jhist.jsonl")


def test_list_jobs_and_mover(tmp_path):
    root = str(tmp_path)
    # one finished job still in intermediate/, one running
    h1 = EventHandler(root, "application_done")
    h1.start()
    h1.emit(task_started("w", 0, "h"))
    h1.stop("SUCCEEDED")
    h2 = EventHandler(root, "application_running").start()
    h2.emit(task_started("w", 0, "h"))
    time.sleep(0.05)

    jobs = history.list_jobs(root)
    assert {j["app_id"] for j in jobs} == {"application_done", "application_running"}

    moved = move_finished_jobs(root, stale_after_s=3600)
    assert len(moved) == 1 and "finished" in moved[0]
    # running job untouched; finished job discoverable in finished tree
    jobs = history.list_jobs(root)
    byid = {j["app_id"]: j for j in jobs}
    assert byid["application_done"]["status"] == "SUCCEEDED"
    assert "finished" in byid["application_done"]["dir"]
    assert byid["application_running"]["inprogress"]
    h2.stop("FAILED")


def test_mover_finalizes_stale_inprogress(tmp_path):
    root = str(tmp_path)
    h = EventHandler(root, "application_dead").start()
    h.emit(task_started("w", 0, "h"))
    time.sleep(0.1)
    # simulate a killed coordinator: inprogress file goes stale
    moved = move_finished_jobs(root, stale_after_s=0.01)
    assert len(moved) == 1
    jobs = history.list_jobs(root)
    assert jobs[0]["status"] == "KILLED"


def test_purger(tmp_path):
    root = str(tmp_path)
    h = EventHandler(root, "application_old")
    h.start()
    h.stop("SUCCEEDED")
    move_finished_jobs(root, stale_after_s=3600)
    assert purge_old_history(root, retention_sec=10**9) == []
    purged = purge_old_history(root, retention_sec=-10)
    assert len(purged) == 1
    assert history.list_jobs(root) == []


def test_portal_pages_and_api(tmp_path):
    """Boot the portal on a seeded history dir and fetch every page + its
    JSON twin (ref: tony-portal Play functional tests over example data),
    including the beyond-reference training-metrics page."""
    import json as _json
    import os
    import urllib.error
    import urllib.request

    from tony_tpu.portal.app import Portal

    root = str(tmp_path)
    h = EventHandler(root, "application_p1")
    h.start()
    h.emit(task_started("worker", 0, "host1"))
    # seed a config + archived training metrics like the coordinator does
    with open(os.path.join(h.job_dir, "tony-final.json"), "w") as f:
        _json.dump({"tony.application.name": "ptest"}, f)
    os.makedirs(os.path.join(h.job_dir, "metrics"), exist_ok=True)
    with open(os.path.join(h.job_dir, "metrics", "train.jsonl"), "wb") as f:
        # includes untrusted content: non-dict JSON, NaN, and a bad byte —
        # the page must skip/null them, not 500
        f.write(b'{"step": 5, "loss": 1.5}\n42\n{"step": 7, "loss": NaN}\n'
                b'\xff garbage\n{"step": 10, "loss": 0.7}\n')
    h.stop("SUCCEEDED")

    portal = Portal(root, port=0).start()
    try:
        base = f"http://127.0.0.1:{portal.port}"

        def get(path):
            with urllib.request.urlopen(base + path, timeout=10) as r:
                return r.status, r.read().decode()

        status, body = get("/")
        assert status == 200 and "application_p1" in body
        assert "/job/application_p1/metrics" in body  # index links metrics
        status, body = get("/api/")
        assert _json.loads(body)[0]["app_id"] == "application_p1"
        status, body = get("/job/application_p1/config")
        assert status == 200 and "ptest" in body
        status, body = get("/api/job/application_p1/events")
        events = _json.loads(body)
        assert any(e["type"] == "TASK_STARTED" for e in events)
        status, body = get("/job/application_p1/logs")
        assert status == 200
        status, body = get("/job/application_p1/metrics")
        assert status == 200 and "loss" in body
        status, body = get("/api/job/application_p1/metrics")
        series = _json.loads(body)  # strict: would fail on a bare NaN token
        assert series["train"][-1] == {"step": 10, "loss": 0.7}
        assert series["train"][1] == {"step": 7, "loss": None}  # NaN nulled
        assert len(series["train"]) == 3  # non-dict + garbage lines dropped
        try:
            get("/job/nosuchjob/config")
            raise AssertionError("expected 404")
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        portal.stop()


def test_portal_renders_gateway_scaling_and_alerts(tmp_path):
    """ISSUE-10 satellite: the gateway history job's scaling.jsonl
    (written since PR 8) and the new alerts.jsonl render on the
    portal's metrics page — previously no test ever opened the page
    on either file. Rows are written through the REAL GatewayHistory
    record paths, then fetched over the portal's HTML page and its
    JSON twin."""
    import json as _json
    import urllib.request

    from tony_tpu.gateway import GatewayHistory
    from tony_tpu.portal.app import Portal

    root = str(tmp_path)
    hist = GatewayHistory(root, app_id="application_gateway_obs",
                          n_replicas=2)
    hist.record({"id": "r1", "replica": 0, "ttft_ms": 5.0,
                 "tokens_out": 4})
    hist.record_scaling({"t": 1.0, "action": "scale_up",
                         "reason": "queue_depth", "replicas_live": 2})
    hist.record_scaling({"t": 9.0, "action": "scale_down",
                         "reason": "idle", "replicas_live": 1})
    hist.record_alert({"t": 2.0, "alert": "kv_pages_pressure",
                       "severity": "warning", "state": "firing",
                       "message": "KV page pool under pressure"})
    hist.record_alert({"t": 6.0, "alert": "kv_pages_pressure",
                       "severity": "warning", "state": "resolved",
                       "message": "KV page pool under pressure"})
    hist.close("SUCCEEDED")

    portal = Portal(root, port=0).start()
    try:
        base = f"http://127.0.0.1:{portal.port}"

        def get(path):
            with urllib.request.urlopen(base + path, timeout=10) as r:
                return r.status, r.read().decode()

        status, body = get("/job/application_gateway_obs/metrics")
        assert status == 200
        # each jsonl file renders as its own section next to requests
        assert "<h3>requests</h3>" in body
        assert "<h3>scaling</h3>" in body
        assert "<h3>alerts</h3>" in body
        assert "scale_up" in body and "scale_down" in body
        assert "kv_pages_pressure" in body
        assert "firing" in body and "resolved" in body
        status, body = get("/api/job/application_gateway_obs/metrics")
        series = _json.loads(body)
        assert [r["action"] for r in series["scaling"]] == \
            ["scale_up", "scale_down"]
        assert [r["state"] for r in series["alerts"]] == \
            ["firing", "resolved"]
        assert series["alerts"][0]["alert"] == "kv_pages_pressure"
    finally:
        portal.stop()


def test_portal_token_auth_and_pagination(tmp_path):
    """Hardening: with a token set, unauthenticated requests get 401;
    bearer header and ?token= both pass. The index paginates and the
    cache caps the scan (ref slot: tony-portal kerberos+HTTPS,
    app/hadoop/Configuration.java)."""
    import json as _json
    import urllib.error
    import urllib.request

    from tony_tpu.portal.app import Portal

    root = str(tmp_path)
    for i in range(5):
        h = EventHandler(root, f"application_pg{i}")
        h.start()
        h.emit(task_started("worker", 0, "host1"))
        h.stop("SUCCEEDED")

    portal = Portal(root, port=0, token="s3cret", max_jobs=3).start()
    try:
        base = f"http://127.0.0.1:{portal.port}"

        def get(path, headers=None):
            req = urllib.request.Request(base + path, headers=headers or {})
            with urllib.request.urlopen(req, timeout=10) as r:
                return r.status, r.read().decode()

        try:
            get("/")
            raise AssertionError("expected 401")
        except urllib.error.HTTPError as e:
            assert e.code == 401
        status, _ = get("/", {"Authorization": "Bearer s3cret"})
        assert status == 200
        status, body = get("/api/?token=s3cret")
        jobs = _json.loads(body)
        assert len(jobs) == 3  # max_jobs caps the cached scan
        # pagination slices the capped list
        status, body = get("/api/?token=s3cret&per=2&page=2")
        assert len(_json.loads(body)) == 1
        status, body = get("/?token=s3cret&per=2&page=1")
        assert "older" in body  # nav link to the next page
        # every rendered link must carry the query token forward, or the
        # next click 401s
        assert "page=2&per=2&token=s3cret" in body
        assert "/config?token=s3cret" in body
    finally:
        portal.stop()


def test_portal_tls_with_pinned_fingerprint(tmp_path):
    """VERDICT r2 #9: the portal serves HTTPS with the per-job cert
    machinery from rpc/tls.py; a client pinning the SHA-256 fingerprint
    gets the jobs API, and a tampered pin is rejected (the HTTPS+keystore
    slot of tony-portal's app/hadoop config)."""
    import json
    import socket

    import pytest

    from tony_tpu.portal.app import Portal
    from tony_tpu.rpc.tls import cert_fingerprint, client_wrap, \
        mint_self_signed

    root = str(tmp_path)
    h = EventHandler(root, "application_tls1")
    h.start()
    h.emit(task_started("worker", 0, "host1"))
    h.stop("SUCCEEDED")

    cert, key = mint_self_signed(str(tmp_path / "tls"), "tony-portal-test")
    fp = cert_fingerprint(cert)
    portal = Portal(root, port=0, tls_cert=cert, tls_key=key).start()
    try:
        def https_get(path, pin):
            raw = socket.create_connection(("127.0.0.1", portal.port),
                                           timeout=10)
            try:
                tls_sock = client_wrap(raw, pin)
            except BaseException:
                raw.close()
                raise
            with tls_sock:
                tls_sock.sendall(f"GET {path} HTTP/1.1\r\n"
                                 f"Host: 127.0.0.1\r\n"
                                 f"Connection: close\r\n\r\n".encode())
                buf = b""
                while chunk := tls_sock.recv(65536):
                    buf += chunk
            head, _, body = buf.partition(b"\r\n\r\n")
            return int(head.split()[1]), body

        status, body = https_get("/api", fp)
        assert status == 200
        jobs = json.loads(body[body.index(b"["):].decode())
        assert jobs and jobs[0]["app_id"] == "application_tls1"

        with pytest.raises(ConnectionError, match="fingerprint mismatch"):
            https_get("/api", "0" * 64)
    finally:
        portal.stop()
