from tony_tpu.portal.app import main

raise SystemExit(main())
