"""Test env: force an 8-device virtual CPU mesh before jax initializes.

Multi-chip sharding tests run on xla_force_host_platform_device_count=8
per the build contract (real multi-chip hardware is unavailable; the driver
separately dry-runs __graft_entry__.dryrun_multichip).
"""

import os
import sys

# Hard-set, not setdefault: the session env carries JAX_PLATFORMS=axon (the
# TPU tunnel) and a sitecustomize hook that re-registers it via
# jax.config.update("jax_platforms", "axon,cpu") at interpreter startup —
# the env var alone cannot win. Tests must never dial the TPU relay:
# (1) fix the config in this process, (2) drop the sitecustomize trigger
# env so subprocesses (agents, payload scripts) skip registration entirely.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavyweight tests excluded from the tier-1 budget "
        "(ROADMAP.md runs -m 'not slow'); run explicitly with -m slow")
