from tony_tpu.ops.attention import flash_attention
from tony_tpu.ops.fused import add_rmsnorm, rmsnorm

__all__ = ["flash_attention", "rmsnorm", "add_rmsnorm"]
