"""KV-cache decode + generation.

Correctness anchor: incremental decode must produce the same logits as the
full (non-decode) forward pass over the same tokens.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models import Transformer, TransformerConfig, generate


@pytest.fixture(scope="module")
def tiny():
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=2,
                            d_ff=64, max_seq_len=32, dtype=jnp.float32,
                            attention_backend="reference")
    model = Transformer(cfg)
    tokens = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    return model, params


def test_decode_matches_full_forward(tiny):
    model, params = tiny
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 10), 0, 64)
    full = model.apply({"params": params}, tokens)

    from tony_tpu.models import init_cache

    cache = init_cache(model, params, 2)
    # feed one token at a time through the cache
    step_logits = []
    variables = {"params": params, "cache": cache}
    for i in range(tokens.shape[1]):
        logits, mut = model.apply(variables, tokens[:, i:i + 1], decode=True,
                                  mutable=["cache"])
        variables = {"params": params, "cache": mut["cache"]}
        step_logits.append(logits[:, 0])
    incremental = jnp.stack(step_logits, axis=1)
    np.testing.assert_allclose(np.asarray(full), np.asarray(incremental),
                               rtol=2e-4, atol=2e-4)


def test_prefill_matches_full_forward(tiny):
    model, params = tiny
    tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 10), 0, 64)
    full = model.apply({"params": params}, tokens)

    from tony_tpu.models import init_cache

    cache = init_cache(model, params, 2)
    prefill, _ = model.apply({"params": params, "cache": cache}, tokens,
                             decode=True, mutable=["cache"])
    np.testing.assert_allclose(np.asarray(full), np.asarray(prefill),
                               rtol=2e-4, atol=2e-4)


def test_generate_greedy_deterministic(tiny):
    model, params = tiny
    prompt = jnp.array([[1, 2, 3]], jnp.int32)
    out1 = generate(model, params, prompt, max_new_tokens=6)
    out2 = generate(model, params, prompt, max_new_tokens=6)
    assert out1.shape == (1, 6)
    assert out1.dtype == jnp.int32
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


def test_generate_greedy_matches_stepwise_argmax(tiny):
    """Greedy generate == repeatedly running the full forward + argmax."""
    model, params = tiny
    prompt = jnp.array([[5, 9]], jnp.int32)
    out = generate(model, params, prompt, max_new_tokens=4)
    tokens = prompt
    for i in range(4):
        logits = model.apply({"params": params}, tokens)
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        assert int(nxt[0]) == int(out[0, i])
        tokens = jnp.concatenate([tokens, nxt[:, None]], axis=1)


def test_generate_sampled_shapes(tiny):
    model, params = tiny
    prompt = jnp.array([[1], [2]], jnp.int32)
    out = generate(model, params, prompt, max_new_tokens=5, temperature=0.8,
                   top_k=10, rng=jax.random.PRNGKey(3))
    assert out.shape == (2, 5)
    assert bool(jnp.all((out >= 0) & (out < 64)))


def test_generate_rejects_cache_overflow(tiny):
    model, params = tiny  # max_seq_len=32
    prompt = jnp.zeros((1, 8), jnp.int32)
    with pytest.raises(ValueError, match="max_seq_len"):
        generate(model, params, prompt, max_new_tokens=30)


def test_generate_eos_freezes(tiny):
    model, params = tiny
    prompt = jnp.array([[1, 2]], jnp.int32)
    # discover what greedy emits first, then treat that as eos
    first = int(generate(model, params, prompt, max_new_tokens=1)[0, 0])
    out = generate(model, params, prompt, max_new_tokens=5, eos_id=first)
    assert np.asarray(out)[0].tolist() == [first] * 5


def test_generate_eos_list_stops_on_any(tiny):
    """HF-style list of eos ids (Llama-3 ships [128001, 128009]): decode
    must stop on ANY listed id, freezing to the first."""
    model, params = tiny
    prompt = jnp.array([[1, 2]], jnp.int32)
    first = int(generate(model, params, prompt, max_new_tokens=1)[0, 0])
    # the hit id listed second: rows must still freeze (to the first id)
    out = generate(model, params, prompt, max_new_tokens=5,
                   eos_id=(63, first))
    toks = np.asarray(out)[0].tolist()
    assert toks[0] == first and toks[1:] == [63] * 4
    # empty list = no stop token, same as -1
    out_none = generate(model, params, prompt, max_new_tokens=5, eos_id=())
    out_neg = generate(model, params, prompt, max_new_tokens=5, eos_id=-1)
    assert np.asarray(out_none).tolist() == np.asarray(out_neg).tolist()
    # negative ids are filtered, never used as freeze token (-1 first in
    # the list must NOT be emitted into the output)
    out_f = generate(model, params, prompt, max_new_tokens=5,
                     eos_id=[-1, first])
    out_s = generate(model, params, prompt, max_new_tokens=5, eos_id=first)
    assert np.asarray(out_f).tolist() == np.asarray(out_s).tolist()
    assert -1 not in np.asarray(out_f).tolist()[0]


def test_beam_search_eos_list(tiny):
    from tony_tpu.models import beam_search

    model, params = tiny
    prompt = jnp.array([[1, 2]], jnp.int32)
    first = int(beam_search(model, params, prompt, max_new_tokens=1,
                            num_beams=2)[0, 0])
    out = np.asarray(beam_search(model, params, prompt, max_new_tokens=5,
                                 num_beams=2, eos_id=(first, 63)))[0]
    eos_seen = False
    for t in out.tolist():
        if eos_seen:
            assert t == first  # frozen to the FIRST listed id
        if t in (first, 63):
            eos_seen = True
    # single-id tuple and a plain LIST (HF config shape; unhashable, so it
    # must be normalized before the static-arg jit boundary) both behave
    # exactly like the scalar form
    a = beam_search(model, params, prompt, max_new_tokens=5, num_beams=2,
                    eos_id=(first,))
    b = beam_search(model, params, prompt, max_new_tokens=5, num_beams=2,
                    eos_id=first)
    c = beam_search(model, params, prompt, max_new_tokens=5, num_beams=2,
                    eos_id=[first, -1])
    assert np.asarray(a).tolist() == np.asarray(b).tolist()
    assert np.asarray(c).tolist() == np.asarray(b).tolist()


def test_generate_top_p_shapes_and_validity(tiny):
    model, params = tiny
    prompt = jnp.array([[1, 2, 3]], jnp.int32)
    out = generate(model, params, prompt, max_new_tokens=5, temperature=0.8,
                   top_p=0.9, rng=jax.random.PRNGKey(3))
    assert out.shape == (1, 5)
    assert ((out >= 0) & (out < 64)).all()


def test_top_p_one_matches_plain_sampling():
    # top_p=1.0 must be a no-op: identical draws to raw categorical sampling
    from tony_tpu.models.generate import sample_logits

    logits = jax.random.normal(jax.random.PRNGKey(0), (4, 64))
    rng = jax.random.PRNGKey(1)
    a = sample_logits(logits, rng, 1.0, 0, 1.0)
    b = jax.random.categorical(rng, logits, axis=-1)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_top_p_zero_degrades_to_top1():
    # top_p<=0 must keep the argmax token, never sample uniform noise
    from tony_tpu.models.generate import sample_logits

    logits = jax.random.normal(jax.random.PRNGKey(2), (4, 64))
    for seed in range(4):
        tok = sample_logits(logits, jax.random.PRNGKey(seed), 1.0, 0, 0.0)
        np.testing.assert_array_equal(
            np.asarray(tok), np.asarray(jnp.argmax(logits, axis=-1)))


def test_top_p_restricts_to_nucleus():
    from tony_tpu.models.generate import sample_logits

    # one dominant token (p ~ 0.97): nucleus at p=0.5 is just that token
    logits = jnp.zeros((1, 16)).at[0, 7].set(5.0)
    for seed in range(8):
        tok = sample_logits(logits, jax.random.PRNGKey(seed), 1.0, 0, 0.5)
        assert int(tok[0]) == 7


def test_top_p_greedy_unaffected(tiny):
    model, params = tiny
    prompt = jnp.array([[1, 2, 3]], jnp.int32)
    a = generate(model, params, prompt, max_new_tokens=4, temperature=0.0,
                 top_p=0.3)
    b = generate(model, params, prompt, max_new_tokens=4, temperature=0.0)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_sliding_window_decode_full_cache():
    """Windowed decode's static slice must stay correct up to the last
    cache slot (the clip at max_len - span engages)."""
    from tony_tpu.models import Transformer, TransformerConfig

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_seq_len=16,
                            dtype=jnp.float32, attention_backend="reference",
                            sliding_window=4)
    model = Transformer(cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 16), 0, 64)
    params = model.init(jax.random.PRNGKey(1), tokens)["params"]
    full = np.asarray(model.apply({"params": params}, tokens))
    cache = model.init(jax.random.PRNGKey(0), tokens, decode=True)["cache"]
    steps = []
    variables = {"params": params, "cache": cache}
    for i in range(16):
        logits, mut = model.apply(variables, tokens[:, i:i + 1], decode=True,
                                  mutable=["cache"])
        variables = {"params": params, "cache": mut["cache"]}
        steps.append(np.asarray(logits[:, 0]))
    np.testing.assert_allclose(np.stack(steps, axis=1), full,
                               atol=1e-4, rtol=1e-4)


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_generate_cli_on_local_checkpoint(tmp_path):
    """tony-tpu generate: local HF dir -> framework decode loop, offline."""
    import subprocess
    import sys

    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    config = transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=48,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=32, tie_word_embeddings=True)
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(config).eval()
    mdir = tmp_path / "ckpt"
    hf.save_pretrained(str(mdir))
    import os
    proc = subprocess.run(
        [sys.executable, "-m", "tony_tpu.cli.generate", "--model", str(mdir),
         "--token-ids", "1,2,3", "--max-new-tokens", "4",
         "--eos-id", "63"],  # out-of-path id: no early stop either side
        capture_output=True, text=True, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": os.path.dirname(os.path.dirname(
                 os.path.abspath(__file__)))})
    assert proc.returncode == 0, proc.stderr[-2000:]
    ids = [int(x) for x in proc.stdout.strip().split(",")]
    assert ids[:3] == [1, 2, 3] and len(ids) == 7
    # greedy must match HF generate on the same checkpoint
    with torch.no_grad():
        ref = hf.generate(torch.tensor([[1, 2, 3]]), max_new_tokens=4,
                          do_sample=False, pad_token_id=0, eos_token_id=63)
    assert ids == ref[0].tolist()


def _np_beam_search(model, params, prompt, T, k):
    """Brute numpy beam reference: rescore via full forwards each step."""
    b = prompt.shape[0]
    beams = [[([], 0.0)] for _ in range(b)]  # per batch: [(toks, score)]
    for t in range(T):
        new_beams = []
        for bi in range(b):
            cands = []
            for toks, score in beams[bi]:
                seq = np.concatenate([np.asarray(prompt[bi]), toks]).astype(
                    np.int32)[None]
                logits = np.asarray(model.apply({"params": params},
                                                jnp.asarray(seq)))[0, -1]
                logp = np.asarray(
                    jax.nn.log_softmax(jnp.asarray(logits, jnp.float32)))
                for v in range(logits.shape[-1]):
                    cands.append((toks + [v], score + float(logp[v])))
            cands.sort(key=lambda c: -c[1])
            new_beams.append([(np.asarray(c[0], np.int64), c[1])
                              for c in cands[:k]])
        beams = [[(list(t_), s) for t_, s in nb] for nb in new_beams]
    return [max(bm, key=lambda c: c[1] / len(c[0]))[0] for bm in beams]


def test_beam_search_matches_numpy_reference(tiny):
    from tony_tpu.models import beam_search

    model, params = tiny
    prompt = jnp.array([[3, 9, 1], [7, 2, 5]], jnp.int32)
    got = np.asarray(beam_search(model, params, prompt, max_new_tokens=4,
                                 num_beams=3))
    ref = _np_beam_search(model, params, prompt, T=4, k=3)
    for bi in range(2):
        np.testing.assert_array_equal(got[bi], np.asarray(ref[bi]))


def test_beam_search_k1_equals_greedy(tiny):
    from tony_tpu.models import beam_search

    model, params = tiny
    prompt = jnp.array([[1, 2, 3]], jnp.int32)
    bs = beam_search(model, params, prompt, max_new_tokens=5, num_beams=1)
    gr = generate(model, params, prompt, max_new_tokens=5)
    np.testing.assert_array_equal(np.asarray(bs), np.asarray(gr))


def test_beam_search_beats_or_ties_greedy_score(tiny):
    """The winning beam's sequence log-prob must be >= greedy's."""
    from tony_tpu.models import beam_search

    model, params = tiny

    def seq_logprob(prompt, cont):
        seq = jnp.concatenate([prompt, cont], axis=1)
        logits = model.apply({"params": params}, seq)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        total = 0.0
        for i in range(cont.shape[1]):
            pos = prompt.shape[1] - 1 + i
            total += float(logp[0, pos, int(cont[0, i])])
        return total

    prompt = jnp.array([[5, 11, 2]], jnp.int32)
    bs = beam_search(model, params, prompt, max_new_tokens=5, num_beams=4)
    gr = generate(model, params, prompt, max_new_tokens=5)
    assert seq_logprob(prompt, bs) >= seq_logprob(prompt, gr) - 1e-4


def test_beam_search_eos_freezes(tiny):
    from tony_tpu.models import beam_search

    model, params = tiny
    prompt = jnp.array([[1, 2]], jnp.int32)
    first = int(beam_search(model, params, prompt, max_new_tokens=1,
                            num_beams=2)[0, 0])
    out = np.asarray(beam_search(model, params, prompt, max_new_tokens=5,
                                 num_beams=2, eos_id=first))[0]
    eos_seen = False
    for t in out.tolist():
        if eos_seen:
            assert t == first  # frozen after eos
        if t == first:
            eos_seen = True


def test_generate_under_tensor_parallel_sharding():
    """The docstring claim: under a Mesh, sharded params + jit give
    tensor-parallel decode with unchanged results."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tony_tpu.models.transformer import logical_axis_rules_tree
    from tony_tpu.parallel import MeshSpec, make_mesh
    from tony_tpu.parallel.sharding import tree_shardings

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_kv_heads=2, n_layers=2, d_ff=64,
                            max_seq_len=32, dtype=jnp.float32,
                            attention_backend="reference")
    model = Transformer(cfg)
    prompt = jnp.array([[3, 1, 4], [1, 5, 9]], jnp.int32)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    ref = np.asarray(generate(model, params, prompt, max_new_tokens=6))

    mesh = make_mesh(MeshSpec(data=2, fsdp=2, tensor=2))
    axes = logical_axis_rules_tree(params)
    sh = tree_shardings(mesh, axes, "tp")
    placed = jax.device_put(params, sh)
    prompt_sh = jax.device_put(prompt, NamedSharding(mesh, P("data")))
    out = np.asarray(generate(model, placed, prompt_sh, max_new_tokens=6))
    np.testing.assert_array_equal(out, ref)


def test_repetition_penalty_noop_at_one(tiny):
    model, params = tiny
    prompt = jnp.array([[1, 2, 3]], jnp.int32)
    a = generate(model, params, prompt, max_new_tokens=5)
    b = generate(model, params, prompt, max_new_tokens=5,
                 repetition_penalty=1.0)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_repetition_penalty_blocks_repeats(tiny):
    """An overwhelming penalty + greedy must emit all-distinct tokens (also
    distinct from the prompt)."""
    model, params = tiny
    prompt = jnp.array([[7, 7, 7]], jnp.int32)
    out = np.asarray(generate(model, params, prompt, max_new_tokens=10,
                              repetition_penalty=1e6))[0]
    toks = out.tolist()
    assert len(set(toks)) == len(toks)
    assert 7 not in toks


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_beam_search_scan_layers_model():
    """scan_layers caches carry a leading n_layers axis: the beam widen and
    parent-gather must hit the batch axis, not the layers axis."""
    from tony_tpu.models import beam_search

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2, n_layers=2,
                            d_ff=64, max_seq_len=32, dtype=jnp.float32,
                            attention_backend="reference", scan_layers=True)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    prompt = jnp.array([[3, 9, 1]], jnp.int32)
    bs = beam_search(model, params, prompt, max_new_tokens=4, num_beams=3)
    ref = _np_beam_search(model, params, prompt, T=4, k=3)
    np.testing.assert_array_equal(np.asarray(bs)[0], np.asarray(ref[0]))
    # and k=1 equals greedy on the same scanned model
    np.testing.assert_array_equal(
        np.asarray(beam_search(model, params, prompt, max_new_tokens=4,
                               num_beams=1)),
        np.asarray(generate(model, params, prompt, max_new_tokens=4)))


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_generate_cli_bf16_serving(tmp_path):
    """--dtype bf16 (the serving precision: half the decode parameter
    traffic) runs the same checkpoint end-to-end; token COUNT contract
    holds (bit-parity is an fp32 guarantee, not a bf16 one)."""
    import os
    import subprocess
    import sys

    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    config = transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=48,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=32, tie_word_embeddings=True)
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(config).eval()
    mdir = tmp_path / "ckpt"
    hf.save_pretrained(str(mdir))
    proc = subprocess.run(
        [sys.executable, "-m", "tony_tpu.cli.generate", "--model", str(mdir),
         "--token-ids", "1,2,3", "--max-new-tokens", "4",
         "--dtype", "bf16", "--eos-id", "63"],
        capture_output=True, text=True, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": os.path.dirname(os.path.dirname(
                 os.path.abspath(__file__)))})
    assert proc.returncode == 0, proc.stderr[-2000:]
    ids = [int(x) for x in proc.stdout.strip().split(",")]
    assert ids[:3] == [1, 2, 3] and len(ids) == 7
    assert all(0 <= i < 64 for i in ids)


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_score_cli_on_local_checkpoint(tmp_path):
    """tony-tpu score: perplexity must match a torch teacher-forced NLL."""
    import subprocess
    import sys

    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    config = transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=48,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=32, tie_word_embeddings=True)
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(config).eval()
    mdir = tmp_path / "ckpt"
    hf.save_pretrained(str(mdir))
    import os
    ids = [1, 2, 3, 4, 5, 6]
    proc = subprocess.run(
        [sys.executable, "-m", "tony_tpu.cli.score", "--model", str(mdir),
         "--token-ids", ",".join(map(str, ids))],
        capture_output=True, text=True, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": os.path.dirname(os.path.dirname(
                 os.path.abspath(__file__)))})
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = [l for l in proc.stdout.splitlines() if l.startswith("TOTAL")][0]
    got_nll = float(line.split("nll/token=")[1].split()[0])
    with torch.no_grad():
        out = hf(torch.tensor([ids]), labels=torch.tensor([ids]))
    np.testing.assert_allclose(got_nll, float(out.loss), rtol=1e-3)


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_score_buckets_one_compile_per_bucket(tiny):
    """VERDICT r2 #10: scoring varied lengths compiles O(#buckets)
    programs (jit's shape-keyed cache), and bucket padding never changes
    the score (padded targets are masked; causal attention isolates pads)."""
    from tony_tpu.cli.score import bucket_len, make_score_fn

    model, params = tiny
    score = make_score_fn(model, {"params": params})
    rng = np.random.default_rng(0)
    lengths = [3, 5, 7, 9, 12, 17, 20, 31]  # buckets: 32 only (max_seq 32)
    results = {}
    for n in lengths:
        ids = rng.integers(1, 64, size=n).tolist()
        results[n] = score(ids)
    buckets = {bucket_len(n, model.cfg.max_seq_len) for n in lengths}
    assert buckets == {32}
    assert score.jitted._cache_size() == len(buckets)  # ONE compile

    # exactness: padded-bucket score == unpadded dense forward
    rng = np.random.default_rng(0)  # regenerate the same ids stream
    for n in lengths:
        ids = rng.integers(1, 64, size=n).tolist()
        tokens = jnp.asarray([ids], jnp.int32)
        logits = model.apply({"params": params}, tokens)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        picked = jnp.take_along_axis(
            logp[:, :-1], tokens[:, 1:, None], axis=-1)[0, :, 0]
        want = float(-picked.sum())
        np.testing.assert_allclose(results[n][0], want, rtol=2e-5)
        assert results[n][1] == n - 1


def test_score_bucket_len():
    from tony_tpu.cli.score import bucket_len

    assert bucket_len(3, 2048) == 32
    assert bucket_len(33, 2048) == 64
    assert bucket_len(64, 2048) == 64
    assert bucket_len(1500, 2048) == 2048
    assert bucket_len(5000, 2048) == 2048  # capped (caller truncates ids)


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_generate_cli_batches_same_length_prompts(tmp_path):
    """Multiple --token-ids of equal length decode as ONE batch; outputs
    print in input order and match per-prompt greedy decodes exactly
    (no padding, so batching cannot change numerics)."""
    import os
    import subprocess
    import sys

    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    config = transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=48,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=32, tie_word_embeddings=True)
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(config).eval()
    mdir = tmp_path / "ckpt"
    hf.save_pretrained(str(mdir))
    prompts = ["1,2,3", "9,8,7", "5,6"]  # two same-length + one distinct
    proc = subprocess.run(
        [sys.executable, "-m", "tony_tpu.cli.generate", "--model", str(mdir),
         *sum((["--token-ids", p] for p in prompts), []),
         "--max-new-tokens", "4", "--eos-id", "63"],
        capture_output=True, text=True, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": os.path.dirname(os.path.dirname(
                 os.path.abspath(__file__)))})
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = proc.stdout.strip().splitlines()
    assert len(lines) == 3
    for line, p in zip(lines, prompts):
        got = [int(x) for x in line.split(",")]
        start = [int(x) for x in p.split(",")]
        assert got[:len(start)] == start
        with torch.no_grad():
            ref = hf.generate(torch.tensor([start]), max_new_tokens=4,
                              do_sample=False, pad_token_id=0,
                              eos_token_id=63)
        assert got == ref[0].tolist(), (line, p)


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_score_cli_int8_close_to_fp(tmp_path):
    """--int8 scoring runs the quantized serving config; its perplexity
    must sit within a few percent of full precision (the quality-cost
    measurement the flag exists for)."""
    import os
    import subprocess
    import sys

    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    config = transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=48,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=32, tie_word_embeddings=True)
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(config).eval()
    mdir = tmp_path / "ckpt"
    hf.save_pretrained(str(mdir))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": os.path.dirname(os.path.dirname(
               os.path.abspath(__file__)))}
    ids = "1,2,3,4,5,6"

    def run(*extra):
        proc = subprocess.run(
            [sys.executable, "-m", "tony_tpu.cli.score", "--model",
             str(mdir), "--token-ids", ids, *extra],
            capture_output=True, text=True, timeout=240, env=env)
        assert proc.returncode == 0, proc.stderr[-2000:]
        line = [l for l in proc.stdout.splitlines()
                if l.startswith("TOTAL")][0]
        return float(line.split("nll/token=")[1].split()[0])

    fp = run()
    q8 = run("--int8")
    assert abs(q8 - fp) / fp < 0.05, (fp, q8)


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_score_cli_kv_int8_close_to_fp(tmp_path):
    """--kv-int8 scores THROUGH the quantized KV cache (decode/prefill
    path): nll/token must sit within a few percent of full precision —
    the cache-quality measurement the flag exists for."""
    import os
    import subprocess
    import sys

    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    config = transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=48,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=32, tie_word_embeddings=True)
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(config).eval()
    mdir = tmp_path / "ckpt"
    hf.save_pretrained(str(mdir))
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": os.path.dirname(os.path.dirname(
               os.path.abspath(__file__)))}
    ids = "1,2,3,4,5,6"

    def run(*extra):
        proc = subprocess.run(
            [sys.executable, "-m", "tony_tpu.cli.score", "--model",
             str(mdir), "--token-ids", ids, *extra],
            capture_output=True, text=True, timeout=240, env=env)
        assert proc.returncode == 0, proc.stderr[-2000:]
        line = [l for l in proc.stdout.splitlines()
                if l.startswith("TOTAL")][0]
        return float(line.split("nll/token=")[1].split()[0])

    fp = run()
    kv8 = run("--kv-int8")
    assert abs(kv8 - fp) / fp < 0.05, (fp, kv8)
