from tony_tpu.models.resnet import (
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
)
from tony_tpu.models.generate import (beam_search, generate, init_cache,
                                      sample_logits, single_decode_step)
from tony_tpu.models.pipeline import pipelined_forward
from tony_tpu.models.quantize import (
    quantize_for_serving,
    shard_expert_qparams,
)
from tony_tpu.models.hf import (
    convert_gpt2_state_dict,
    convert_llama_state_dict,
    from_hf_gemma,
    from_hf_gpt2,
    from_hf_llama,
    from_hf_mixtral,
    from_hf_neox,
    from_hf_phi,
    gemma_config,
    gpt2_config,
    llama_config,
)
from tony_tpu.models.transformer import (
    MoEMLP,
    RopeScaling,
    Transformer,
    TransformerConfig,
    moe_aux_loss,
)

__all__ = [
    "MoEMLP",
    "convert_gpt2_state_dict",
    "convert_llama_state_dict",
    "from_hf_gemma",
    "from_hf_gpt2",
    "from_hf_llama",
    "from_hf_mixtral",
    "from_hf_neox",
    "from_hf_phi",
    "gemma_config",
    "gpt2_config",
    "llama_config",
    "moe_aux_loss",
    "beam_search",
    "generate",
    "pipelined_forward",
    "quantize_for_serving",
    "shard_expert_qparams",
    "init_cache",
    "sample_logits",
    "single_decode_step",
    "ResNet",
    "ResNet18",
    "ResNet34",
    "ResNet50",
    "ResNet101",
    "ResNet152",
    "RopeScaling",
    "Transformer",
    "TransformerConfig",
]
