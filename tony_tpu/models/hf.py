"""Hugging Face checkpoint import for the flagship transformer.

No reference analog (TonY has no models). GPT-2-family weights map onto
``TransformerConfig(norm="layer", positional="learned", use_bias=True,
activation="gelu_tanh")``; the converter is pure tensor reshuffling
(torch state_dict -> jax pytree), so it works on any GPT-2-sized
checkpoint already on disk — no network needed.

HF GPT-2 layout notes: ``Conv1D`` stores weights as [in, out] (already
the jax kernel orientation); ``c_attn`` packs Q,K,V as one [d, 3d]
matrix split here into per-head kernels; ``wte`` is tied to the LM head
(our model ties through the same ``embedding`` param).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from tony_tpu.models.transformer import (
    RopeScaling,
    Transformer,
    TransformerConfig,
)


_HF_ACTIVATIONS = {"gelu_new": "gelu_tanh", "gelu_pytorch_tanh": "gelu_tanh",
                   "gelu": "gelu", "silu": "silu", "swish": "silu"}


def gpt2_config(hf_config, **overrides) -> TransformerConfig:
    """TransformerConfig matching a transformers GPT2Config."""
    act = getattr(hf_config, "activation_function", "gelu_new")
    if act not in _HF_ACTIVATIONS:
        raise ValueError(f"unsupported GPT-2 activation_function {act!r}; "
                         f"supported: {sorted(_HF_ACTIVATIONS)}")
    n_inner = getattr(hf_config, "n_inner", None)
    kw = dict(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.n_embd,
        n_heads=hf_config.n_head,
        n_layers=hf_config.n_layer,
        d_ff=n_inner if n_inner else 4 * hf_config.n_embd,
        max_seq_len=hf_config.n_positions,
        dtype=jnp.float32,
        attention_backend="reference",
        norm="layer",
        positional="learned",
        use_bias=True,
        activation=_HF_ACTIVATIONS[act],
        norm_eps=hf_config.layer_norm_epsilon,
    )
    kw.update(overrides)
    return TransformerConfig(**kw)


def _np(t) -> np.ndarray:
    return t.detach().cpu().numpy()


def convert_gpt2_state_dict(state_dict: dict, cfg: TransformerConfig) -> Any:
    """torch GPT-2 state_dict -> tony-tpu Transformer params pytree."""
    d, h, dh = cfg.d_model, cfg.n_heads, cfg.head_dim
    sd = {k.removeprefix("transformer."): v for k, v in state_dict.items()}
    params: dict[str, Any] = {
        "embedding": _np(sd["wte.weight"]),
        "pos_embedding": _np(sd["wpe.weight"]),
        "ln_f": {"scale": _np(sd["ln_f.weight"]),
                 "bias": _np(sd["ln_f.bias"])},
    }
    for i in range(cfg.n_layers):
        pre = f"h.{i}."
        qkv_w = _np(sd[pre + "attn.c_attn.weight"])  # [d, 3d] (Conv1D)
        qkv_b = _np(sd[pre + "attn.c_attn.bias"])  # [3d]
        qw, kw, vw = np.split(qkv_w, 3, axis=1)
        qb, kb, vb = np.split(qkv_b, 3, axis=0)
        block = {
            "ln1": {"scale": _np(sd[pre + "ln_1.weight"]),
                    "bias": _np(sd[pre + "ln_1.bias"])},
            "ln2": {"scale": _np(sd[pre + "ln_2.weight"]),
                    "bias": _np(sd[pre + "ln_2.bias"])},
            "attn": {
                "q": {"kernel": qw.reshape(d, h, dh),
                      "bias": qb.reshape(h, dh)},
                "k": {"kernel": kw.reshape(d, h, dh),
                      "bias": kb.reshape(h, dh)},
                "v": {"kernel": vw.reshape(d, h, dh),
                      "bias": vb.reshape(h, dh)},
                "o": {"kernel": _np(
                          sd[pre + "attn.c_proj.weight"]).reshape(h, dh, d),
                      "bias": _np(sd[pre + "attn.c_proj.bias"])},
            },
            "mlp": {
                "wi": {"kernel": _np(sd[pre + "mlp.c_fc.weight"]),
                       "bias": _np(sd[pre + "mlp.c_fc.bias"])},
                "wo": {"kernel": _np(sd[pre + "mlp.c_proj.weight"]),
                       "bias": _np(sd[pre + "mlp.c_proj.bias"])},
            },
        }
        params[f"block_{i}"] = block
    return {"params": jax.tree.map(jnp.asarray, params)}


def from_hf_gpt2(model) -> tuple[Transformer, Any]:
    """(Transformer, params) from a transformers GPT2LMHeadModel (or its
    GPT2Model trunk) instance — local weights, no network."""
    cfg = gpt2_config(model.config)
    params = convert_gpt2_state_dict(model.state_dict(), cfg)
    return Transformer(cfg), params


def _effective_sliding_window(hf_config) -> int:
    """Sliding-window size actually in force for this checkpoint.

    Mistral: ``sliding_window`` (None = full attention). Qwen2 ships
    ``sliding_window`` set but gated behind ``use_sliding_window`` (False
    on the released checkpoints), so honor the gate when present.
    """
    win = getattr(hf_config, "sliding_window", None)
    if not win:
        return 0
    if not getattr(hf_config, "use_sliding_window", True):
        return 0
    # Qwen2-style layer gating: HF windows only layers with
    # layer_idx >= max_window_layers. A single global cfg.sliding_window
    # can represent "all layers" (gate at 0) or "no layers" (gate past the
    # stack); anything in between would silently diverge — reject.
    gate = getattr(hf_config, "max_window_layers", 0) or 0
    if gate >= hf_config.num_hidden_layers:
        return 0
    if gate > 0:
        raise ValueError(
            f"per-layer sliding-window gating (max_window_layers={gate} of "
            f"{hf_config.num_hidden_layers}) is not supported; only "
            "all-layers or no-layers windows import exactly")
    return int(win)


def _rope_scaling(hf_config) -> RopeScaling | None:
    """HF rope_scaling dict -> RopeScaling (llama3 / linear), None when
    absent or "default". Unknown kinds (yarn, dynamic, longrope) are
    rejected — importing them as plain RoPE would silently corrupt
    long-position attention."""
    rs = getattr(hf_config, "rope_scaling", None)
    if not rs:
        return None
    kind = rs.get("rope_type", rs.get("type", ""))
    if kind == "default":
        return None
    if kind == "linear":
        return RopeScaling(kind="linear", factor=float(rs["factor"]))
    if kind == "llama3":
        return RopeScaling(
            kind="llama3",
            factor=float(rs["factor"]),
            low_freq_factor=float(rs["low_freq_factor"]),
            high_freq_factor=float(rs["high_freq_factor"]),
            original_max_len=int(rs["original_max_position_embeddings"]))
    raise ValueError(f"unsupported rope_scaling type {kind!r} "
                     "(supported: default, linear, llama3)")


def llama_config(hf_config, **overrides) -> TransformerConfig:
    """TransformerConfig matching a transformers LlamaConfig or close kin:
    any RMSNorm + RoPE + GQA + SwiGLU architecture, including Mistral
    (sliding-window attention -> cfg.sliding_window), Qwen2 (q/k/v
    projection biases -> cfg.qkv_bias), and Llama-3 long-context
    checkpoints (rope_scaling llama3/linear -> cfg.rope_scaling).
    Variants with full attention_bias/mlp_bias or exotic rope scaling are
    rejected rather than silently mis-imported."""
    if getattr(hf_config, "attention_bias", False) or \
            getattr(hf_config, "mlp_bias", False):
        raise ValueError("attention_bias/mlp_bias Llama variants are not "
                         "supported (only Qwen2-style qkv biases are)")
    if "activation" in overrides:
        act_name = overrides["activation"]  # caller (gemma_config) already
        # resolved the family's activation-field semantics
    else:
        act = getattr(hf_config, "hidden_act", "silu")
        if act not in _HF_ACTIVATIONS:
            raise ValueError(f"unsupported hidden_act {act!r}")
        act_name = _HF_ACTIVATIONS[act]
    kw = dict(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=getattr(hf_config, "num_key_value_heads", None),
        n_layers=hf_config.num_hidden_layers,
        d_ff=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        dtype=jnp.float32,
        attention_backend="reference",
        norm="rms",
        positional="rope",
        use_bias=False,
        qkv_bias=getattr(hf_config, "model_type", "") == "qwen2",
        sliding_window=_effective_sliding_window(hf_config),
        activation=act_name,
        norm_eps=hf_config.rms_norm_eps,
        rope_theta=getattr(hf_config, "rope_theta", 10_000.0),
        rope_scaling=_rope_scaling(hf_config),
        gated_mlp=True,
        tied_embeddings=getattr(hf_config, "tie_word_embeddings", False),
    )
    kw.update(overrides)
    return TransformerConfig(**kw)


def _convert_rms_decoder(state_dict: dict, cfg: TransformerConfig, *,
                         family: str, ffn_consumed, ffn_build) -> Any:
    """Shared RMSNorm+RoPE+GQA decoder conversion (Llama-layout state
    dicts): embedding / final norm / lm_head, per-layer norms and
    q/k/v/o, with the strict leftover check. The FFN leaf — dense SwiGLU
    vs sparse MoE — comes from the caller: ``ffn_consumed(i)`` names its
    tensors, ``ffn_build(i, proj)`` returns ``(param_name, leaf_dict)``.

    torch ``nn.Linear`` stores [out, in]; jax kernels are [in, out], so
    every projection transposes. q/k/v rows are head-major, so the
    transposed [d, heads*dh] reshapes straight into [d, heads, dh];
    RoPE conventions already agree (half-split rotate, see
    ``rotary_embedding``).
    """
    d, h, dh, kvh = cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.kv_heads
    sd = {k.removeprefix("model."): v for k, v in state_dict.items()}
    # lm_head.weight is consumed untied and a duplicate view when tied
    consumed = {"embed_tokens.weight", "norm.weight", "lm_head.weight"}
    for i in range(cfg.n_layers):
        consumed |= {f"layers.{i}.{s}.weight" for s in (
            "input_layernorm", "post_attention_layernorm",
            "self_attn.q_proj", "self_attn.k_proj", "self_attn.v_proj",
            "self_attn.o_proj")}
        if cfg.qkv_bias:
            consumed |= {f"layers.{i}.self_attn.{p}_proj.bias"
                         for p in "qkv"}
        consumed |= ffn_consumed(i)
    # strictness: an unmapped tensor means this checkpoint is NOT the
    # architecture the config claimed (e.g. stray projection biases when
    # qkv_bias is off) and the import would be silently wrong. inv_freq
    # buffers (old transformers) carry no weights.
    leftover = {k for k in sd
                if k not in consumed and not k.endswith("inv_freq")}
    if leftover:
        raise ValueError(
            f"state_dict has tensors the {family} importer does not map "
            f"(not a plain-{family} architecture?): {sorted(leftover)[:8]}")
    params: dict[str, Any] = {
        "embedding": _np(sd["embed_tokens.weight"]),
        "ln_f": {"scale": _np(sd["norm.weight"])},
    }
    if not cfg.tied_embeddings:
        params["lm_head"] = _np(sd["lm_head.weight"])
    for i in range(cfg.n_layers):
        pre = f"layers.{i}."
        proj = lambda name: _np(sd[pre + name + ".weight"]).T  # noqa: E731

        def head_proj(name, heads):
            leaf = {"kernel": proj(name).reshape(d, heads, dh)}
            if cfg.qkv_bias:
                leaf["bias"] = _np(
                    sd[pre + name + ".bias"]).reshape(heads, dh)
            return leaf

        ffn_name, ffn_leaf = ffn_build(i, proj)
        params[f"block_{i}"] = {
            "ln1": {"scale": _np(sd[pre + "input_layernorm.weight"])},
            "ln2": {"scale": _np(
                sd[pre + "post_attention_layernorm.weight"])},
            "attn": {
                "q": head_proj("self_attn.q_proj", h),
                "k": head_proj("self_attn.k_proj", kvh),
                "v": head_proj("self_attn.v_proj", kvh),
                "o": {"kernel": proj("self_attn.o_proj").reshape(h, dh, d)},
            },
            ffn_name: ffn_leaf,
        }
    return {"params": jax.tree.map(jnp.asarray, params)}


def convert_llama_state_dict(state_dict: dict, cfg: TransformerConfig) -> Any:
    """torch Llama state_dict -> tony-tpu Transformer params pytree."""

    def ffn_consumed(i):
        return {f"layers.{i}.mlp.{p}.weight"
                for p in ("gate_proj", "up_proj", "down_proj")}

    def ffn_build(i, proj):
        return "mlp", {
            "wg": {"kernel": proj("mlp.gate_proj")},
            "wi": {"kernel": proj("mlp.up_proj")},
            "wo": {"kernel": proj("mlp.down_proj")},
        }

    return _convert_rms_decoder(state_dict, cfg, family="Llama",
                                ffn_consumed=ffn_consumed,
                                ffn_build=ffn_build)


def from_hf_llama(model) -> tuple[Transformer, Any]:
    """(Transformer, params) from a transformers LlamaForCausalLM (or
    Mistral/Qwen2-compatible) instance — local weights, no network."""
    cfg = llama_config(model.config)
    params = convert_llama_state_dict(model.state_dict(), cfg)
    return Transformer(cfg), params


def mixtral_config(hf_config, **overrides) -> TransformerConfig:
    """TransformerConfig matching a transformers MixtralConfig.

    Mixtral = Mistral attention (RMSNorm + RoPE + GQA + optional sliding
    window) with EVERY dense MLP replaced by a top-k sparse MoE of SwiGLU
    experts whose gate weights are softmax-then-renormalized over the
    selected k (transformers MixtralSparseMoeBlock). Import maps onto
    ``moe_every=1`` + the Mixtral knobs, with ``moe_dropless=True`` so
    evaluation is EXACT (no capacity dropping) — the capacity-routed
    training path stays available by flipping moe_dropless/capacity."""
    act = getattr(hf_config, "hidden_act", "silu")
    if act not in _HF_ACTIVATIONS:
        raise ValueError(f"unsupported Mixtral hidden_act {act!r}; "
                         f"supported: {sorted(_HF_ACTIVATIONS)}")
    kw = dict(
        gated_mlp=False,  # no dense MLP anywhere; moe_every=1 covers all
        moe_every=1,
        moe_num_experts=hf_config.num_local_experts,
        moe_top_k=hf_config.num_experts_per_tok,
        moe_gated=True,
        moe_renormalize=True,
        moe_dropless=True,
        moe_activation=_HF_ACTIVATIONS[act],
        moe_d_ff=hf_config.intermediate_size,
    )
    kw.update(overrides)
    return llama_config(hf_config, **kw)


def convert_mixtral_state_dict(state_dict: dict,
                               cfg: TransformerConfig) -> Any:
    """torch Mixtral state_dict -> tony-tpu params. The attention/norm
    layout is Llama's (shared converter); each block's MoE maps
    gate.weight [E, D] -> router [D, E] and experts.e.{w1,w3,w2} ->
    stacked wg/wi/wo with the expert-leading orientation of
    parallel/moe.py."""
    e = cfg.moe_num_experts

    def ffn_consumed(i):
        return {f"layers.{i}.block_sparse_moe.gate.weight"} | {
            f"layers.{i}.block_sparse_moe.experts.{x}.{w}.weight"
            for x in range(e) for w in ("w1", "w2", "w3")}

    def ffn_build(i, proj):
        return "moe", {
            "router": proj("block_sparse_moe.gate"),  # [D, E]
            "wg": np.stack([proj(f"block_sparse_moe.experts.{x}.w1")
                            for x in range(e)]),  # [E, D, FF]
            "wi": np.stack([proj(f"block_sparse_moe.experts.{x}.w3")
                            for x in range(e)]),  # [E, D, FF]
            "wo": np.stack([proj(f"block_sparse_moe.experts.{x}.w2")
                            for x in range(e)]),  # [E, FF, D]
        }

    return _convert_rms_decoder(state_dict, cfg, family="Mixtral",
                                ffn_consumed=ffn_consumed,
                                ffn_build=ffn_build)


def from_hf_mixtral(model) -> tuple[Transformer, Any]:
    """(Transformer, params) from a transformers MixtralForCausalLM —
    local weights, no network. Evaluation is exact (dropless dense MoE)."""
    if getattr(model.config, "model_type", "") != "mixtral":
        raise ValueError(
            f"from_hf_mixtral got model_type "
            f"{getattr(model.config, 'model_type', None)!r}")
    cfg = mixtral_config(model.config)
    params = convert_mixtral_state_dict(model.state_dict(), cfg)
    return Transformer(cfg), params


def neox_config(hf_config, **overrides) -> TransformerConfig:
    """TransformerConfig matching a transformers GPTNeoXConfig (Pythia /
    GPT-NeoX-20B family): LayerNorm (with bias) + PARTIAL rotary
    (rotary_pct of each head) + biased dense everywhere + classic
    2-matmul gelu MLP, and — on every released Pythia checkpoint —
    the parallel residual (x + attn(ln1 x) + mlp(ln2 x))."""
    act = getattr(hf_config, "hidden_act", "gelu")
    if act not in _HF_ACTIVATIONS:
        raise ValueError(f"unsupported GPT-NeoX hidden_act {act!r}; "
                         f"supported: {sorted(_HF_ACTIVATIONS)}")
    if not getattr(hf_config, "attention_bias", True):
        # bias-free NeoX variants lack tensors this importer maps; a
        # silent mis-model is worse than a refusal (strictness convention)
        raise ValueError("attention_bias=False GPT-NeoX variants are not "
                         "supported")
    head_dim = hf_config.hidden_size // hf_config.num_attention_heads
    rotary_dims = int(head_dim * getattr(hf_config, "rotary_pct", 1.0))
    if rotary_dims % 2:
        # the half-split rotation needs an even width (true of every
        # released NeoX/Pythia checkpoint; HF's rotate_half would produce
        # mismatched halves for an odd width too)
        raise ValueError(
            f"rotary_pct x head_dim = {rotary_dims} is odd; partial "
            "rotary needs an even rotary width")
    kw = dict(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        n_heads=hf_config.num_attention_heads,
        n_layers=hf_config.num_hidden_layers,
        d_ff=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        dtype=jnp.float32,
        attention_backend="reference",
        norm="layer",
        positional="rope",
        use_bias=True,
        activation=_HF_ACTIVATIONS[act],
        norm_eps=hf_config.layer_norm_eps,
        rope_theta=float(getattr(hf_config, "rotary_emb_base", 10_000.0)),
        rope_scaling=_rope_scaling(hf_config),  # map linear / reject exotic
        rotary_dims=0 if rotary_dims >= head_dim else rotary_dims,
        parallel_residual=getattr(hf_config, "use_parallel_residual", True),
        tied_embeddings=getattr(hf_config, "tie_word_embeddings", False),
    )
    kw.update(overrides)
    return TransformerConfig(**kw)


def convert_neox_state_dict(state_dict: dict, cfg: TransformerConfig) -> Any:
    """torch GPT-NeoX state_dict -> tony-tpu params. The fused
    query_key_value projection packs rows head-major as [q_h, k_h, v_h]
    per head: transposed [d, 3hd] reshapes to [d, h, 3, dh] and splits
    on the packed axis."""
    d, h, dh = cfg.d_model, cfg.n_heads, cfg.head_dim
    sd = {k.removeprefix("gpt_neox."): v for k, v in state_dict.items()}
    consumed = {"embed_in.weight", "final_layer_norm.weight",
                "final_layer_norm.bias", "embed_out.weight"}
    for i in range(cfg.n_layers):
        consumed |= {f"layers.{i}.{s}.{wb}" for wb in ("weight", "bias")
                     for s in ("input_layernorm", "post_attention_layernorm",
                               "attention.query_key_value",
                               "attention.dense", "mlp.dense_h_to_4h",
                               "mlp.dense_4h_to_h")}
    buffers = ("inv_freq", "attention.bias", "attention.masked_bias",
               "rotary_emb.inv_freq")
    leftover = {k for k in sd if k not in consumed
                and not k.endswith(buffers)}
    if leftover:
        raise ValueError(
            f"state_dict has tensors the GPT-NeoX importer does not map "
            f"(not a plain-NeoX architecture?): {sorted(leftover)[:8]}")
    params: dict[str, Any] = {
        "embedding": _np(sd["embed_in.weight"]),
        "ln_f": {"scale": _np(sd["final_layer_norm.weight"]),
                 "bias": _np(sd["final_layer_norm.bias"])},
    }
    if not cfg.tied_embeddings:
        params["lm_head"] = _np(sd["embed_out.weight"])
    for i in range(cfg.n_layers):
        pre = f"layers.{i}."
        qkv_w = _np(sd[pre + "attention.query_key_value.weight"]).T \
            .reshape(d, h, 3, dh)
        qkv_b = _np(sd[pre + "attention.query_key_value.bias"]) \
            .reshape(h, 3, dh)

        def lin(name):
            return {"kernel": _np(sd[pre + name + ".weight"]).T,
                    "bias": _np(sd[pre + name + ".bias"])}

        params[f"block_{i}"] = {
            "ln1": {"scale": _np(sd[pre + "input_layernorm.weight"]),
                    "bias": _np(sd[pre + "input_layernorm.bias"])},
            "ln2": {"scale": _np(
                        sd[pre + "post_attention_layernorm.weight"]),
                    "bias": _np(
                        sd[pre + "post_attention_layernorm.bias"])},
            "attn": {
                "q": {"kernel": qkv_w[:, :, 0], "bias": qkv_b[:, 0]},
                "k": {"kernel": qkv_w[:, :, 1], "bias": qkv_b[:, 1]},
                "v": {"kernel": qkv_w[:, :, 2], "bias": qkv_b[:, 2]},
                "o": {"kernel": _np(sd[pre + "attention.dense.weight"])
                      .T.reshape(h, dh, d),
                      "bias": _np(sd[pre + "attention.dense.bias"])},
            },
            "mlp": {
                "wi": lin("mlp.dense_h_to_4h"),
                "wo": lin("mlp.dense_4h_to_h"),
            },
        }
    return {"params": jax.tree.map(jnp.asarray, params)}


def from_hf_neox(model) -> tuple[Transformer, Any]:
    """(Transformer, params) from a transformers GPTNeoXForCausalLM
    (Pythia family) — local weights, no network."""
    if getattr(model.config, "model_type", "") != "gpt_neox":
        raise ValueError(
            f"from_hf_neox got model_type "
            f"{getattr(model.config, 'model_type', None)!r}")
    cfg = neox_config(model.config)
    params = convert_neox_state_dict(model.state_dict(), cfg)
    return Transformer(cfg), params


def phi_config(hf_config, **overrides) -> TransformerConfig:
    """TransformerConfig matching a transformers PhiConfig (Phi-1/1.5/2):
    LayerNorm + partial rotary (``partial_rotary_factor``) + biased dense
    everywhere + parallel residual where BOTH branches read the SAME
    input LayerNorm, + an untied lm_head WITH bias. The shared norm maps
    onto this model's two-norm parallel block by duplicating the weights
    into ln2 (identical input -> identical math)."""
    act = getattr(hf_config, "hidden_act", "gelu_new")
    if act not in _HF_ACTIVATIONS:
        raise ValueError(f"unsupported Phi hidden_act {act!r}; "
                         f"supported: {sorted(_HF_ACTIVATIONS)}")
    head_dim = hf_config.hidden_size // hf_config.num_attention_heads
    rotary_dims = int(head_dim * getattr(hf_config, "partial_rotary_factor",
                                         0.5))
    if rotary_dims % 2:
        raise ValueError(
            f"partial_rotary_factor x head_dim = {rotary_dims} is odd; "
            "partial rotary needs an even rotary width")
    # No released Phi ties embeddings; a tied variant would silently drop
    # the converted biased lm_head (tied logits read the embedding), so
    # refuse rather than mismodel — same convention as the NeoX
    # attention_bias=False refusal above.
    if getattr(hf_config, "tie_word_embeddings", False):
        raise ValueError("tie_word_embeddings=True Phi variants are not "
                         "supported (the importer emits an untied biased "
                         "lm_head; a tied model would silently ignore it)")
    kw = dict(
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=getattr(hf_config, "num_key_value_heads", None),
        n_layers=hf_config.num_hidden_layers,
        d_ff=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        dtype=jnp.float32,
        attention_backend="reference",
        norm="layer",
        positional="rope",
        use_bias=True,
        activation=_HF_ACTIVATIONS[act],
        norm_eps=hf_config.layer_norm_eps,
        rope_theta=float(getattr(hf_config, "rope_theta", 10_000.0)),
        rope_scaling=_rope_scaling(hf_config),
        rotary_dims=0 if rotary_dims >= head_dim else rotary_dims,
        parallel_residual=True,
        tied_embeddings=getattr(hf_config, "tie_word_embeddings", False),
        lm_head_bias=True,
    )
    kw.update(overrides)
    return TransformerConfig(**kw)


def convert_phi_state_dict(state_dict: dict, cfg: TransformerConfig) -> Any:
    """torch Phi state_dict -> tony-tpu params. Llama-style per-layer
    names but LayerNorm (weight+bias), biased q/k/v/dense/fc1/fc2, a
    single input_layernorm duplicated into ln1+ln2 (shared-norm parallel
    residual), and a biased untied lm_head."""
    d, h, dh, kvh = cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.kv_heads
    sd = {k.removeprefix("model."): v for k, v in state_dict.items()}
    consumed = {"embed_tokens.weight", "final_layernorm.weight",
                "final_layernorm.bias", "lm_head.weight", "lm_head.bias"}
    for i in range(cfg.n_layers):
        consumed |= {f"layers.{i}.{s}.{wb}" for wb in ("weight", "bias")
                     for s in ("input_layernorm", "self_attn.q_proj",
                               "self_attn.k_proj", "self_attn.v_proj",
                               "self_attn.dense", "mlp.fc1", "mlp.fc2")}
    leftover = {k for k in sd if k not in consumed
                and not k.endswith("inv_freq")}
    if leftover:
        raise ValueError(
            f"state_dict has tensors the Phi importer does not map "
            f"(not a plain-Phi architecture?): {sorted(leftover)[:8]}")
    params: dict[str, Any] = {
        "embedding": _np(sd["embed_tokens.weight"]),
        "ln_f": {"scale": _np(sd["final_layernorm.weight"]),
                 "bias": _np(sd["final_layernorm.bias"])},
        "lm_head": _np(sd["lm_head.weight"]),
        "lm_head_bias": _np(sd["lm_head.bias"]),
    }
    for i in range(cfg.n_layers):
        pre = f"layers.{i}."
        proj = lambda name: _np(sd[pre + name + ".weight"]).T  # noqa: E731
        bias = lambda name: _np(sd[pre + name + ".bias"])  # noqa: E731
        norm = {"scale": _np(sd[pre + "input_layernorm.weight"]),
                "bias": _np(sd[pre + "input_layernorm.bias"])}

        def head_proj(name, heads):
            return {"kernel": proj(name).reshape(d, heads, dh),
                    "bias": bias(name).reshape(heads, dh)}

        params[f"block_{i}"] = {
            "ln1": dict(norm),
            "ln2": dict(norm),  # shared input norm -> both branches
            "attn": {
                "q": head_proj("self_attn.q_proj", h),
                "k": head_proj("self_attn.k_proj", kvh),
                "v": head_proj("self_attn.v_proj", kvh),
                "o": {"kernel": proj("self_attn.dense").reshape(h, dh, d),
                      "bias": bias("self_attn.dense")},
            },
            "mlp": {
                "wi": {"kernel": proj("mlp.fc1"), "bias": bias("mlp.fc1")},
                "wo": {"kernel": proj("mlp.fc2"), "bias": bias("mlp.fc2")},
            },
        }
    return {"params": jax.tree.map(jnp.asarray, params)}


def from_hf_phi(model) -> tuple[Transformer, Any]:
    """(Transformer, params) from a transformers PhiForCausalLM — local
    weights, no network."""
    if getattr(model.config, "model_type", "") != "phi":
        raise ValueError(
            f"from_hf_phi got model_type "
            f"{getattr(model.config, 'model_type', None)!r}")
    cfg = phi_config(model.config)
    params = convert_phi_state_dict(model.state_dict(), cfg)
    return Transformer(cfg), params


def gemma_config(hf_config, **overrides) -> TransformerConfig:
    """TransformerConfig matching a transformers GemmaConfig (Gemma-1).

    Gemma's distinctives vs Llama: explicit per-head width (7B: 16 heads
    x 256 > hidden 3072), embeddings scaled by sqrt(hidden) in activation
    dtype, RMSNorm applied as (1 + weight) with zero-init weight, tied
    embeddings, and gelu-tanh gated MLP. Gemma-2 (attn/final logit
    softcapping, alternating local attention) is NOT this architecture
    and is rejected by the model_type check in from_hf_gemma."""
    # transformers' GemmaMLP runs ACT2FN[config.hidden_act] (verified on
    # 4.57) even though hub configs ALSO carry hidden_activation — parity
    # is against the installed torch reference, so mirror its resolution
    # exactly: hidden_act first, hidden_activation as the fallback
    act = getattr(hf_config, "hidden_act", None) or \
        getattr(hf_config, "hidden_activation", None) or "gelu_pytorch_tanh"
    if act not in _HF_ACTIVATIONS:
        raise ValueError(f"unsupported Gemma activation {act!r}")
    # the shared RMSNorm+RoPE+GQA+gated-MLP mapping (and its strictness:
    # attention/mlp-bias rejection, rope_scaling map-or-reject) lives in
    # llama_config; only Gemma's distinctives are overridden here
    kw = dict(
        activation=_HF_ACTIVATIONS[act],
        qkv_bias=False,
        tied_embeddings=getattr(hf_config, "tie_word_embeddings", True),
        explicit_head_dim=getattr(hf_config, "head_dim", 0) or 0,
        embed_scale=True,
        norm_unit_offset=True,
    )
    kw.update(overrides)
    return llama_config(hf_config, **kw)


def from_hf_gemma(model) -> tuple[Transformer, Any]:
    """(Transformer, params) from a transformers GemmaForCausalLM.
    The state-dict layout is Llama's (same projection/norm names), so the
    conversion is shared; only the config semantics differ."""
    if getattr(model.config, "model_type", "") != "gemma":
        raise ValueError(
            f"from_hf_gemma got model_type "
            f"{getattr(model.config, 'model_type', None)!r} (gemma2's "
            "softcapping/local-attention architecture is not this model)")
    cfg = gemma_config(model.config)
    params = convert_llama_state_dict(model.state_dict(), cfg)
    return Transformer(cfg), params
