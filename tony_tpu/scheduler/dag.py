"""Role-dependency gang scheduler.

Reference: TaskScheduler.java (179 LoC) — validates the role graph is a DAG,
schedules roles whose ``depends-on`` sets are satisfied, and releases
dependents as upstream roles' instances all complete. Also supports the
two-stage prepare/training split (ref: util/Utils.java:371-419
parseContainerRequests with tony.application.prepare-stage/training-stage).

This is pure logic over an abstract ``allocate`` callback; the coordinator
wires the callback to real agent placement.
"""

from __future__ import annotations

import logging
from typing import Callable

from tony_tpu.config import TonyConf
from tony_tpu.session import RoleRequest, Session

log = logging.getLogger(__name__)


class CycleError(ValueError):
    pass


class TaskScheduler:
    """Schedules role gangs respecting the dependency DAG."""

    def __init__(
        self,
        session: Session,
        allocate: Callable[[RoleRequest], None],
        conf: TonyConf | None = None,
    ):
        self.session = session
        self.allocate = allocate
        self.requests = dict(session.requests)
        self.deps = self._build_dependency_graph(conf)
        self.scheduled: set[str] = set()
        self.completed_roles: set[str] = set()

    # -- graph (ref: buildTaskDependencyGraph :75, isDAG :142) --------------
    def _build_dependency_graph(self, conf: TonyConf | None) -> dict[str, set[str]]:
        deps: dict[str, set[str]] = {
            role: set(req.depends_on) for role, req in self.requests.items()
        }
        # stage split: every training-stage role implicitly depends on every
        # *tracked* prepare-stage role — untracked roles (long-running ps/
        # sidecars) never "complete" and must not gate training (ref:
        # Utils.java:380 tasksToDependOn excludes untrackedJobTypes)
        if conf is not None:
            prepare_conf = conf.get_list("tony.application.prepare-stage")
            training_conf = conf.get_list("tony.application.training-stage")
            unknown = (set(prepare_conf) | set(training_conf)) - set(deps)
            if unknown:
                raise CycleError(
                    f"stage lists name unknown roles: {sorted(unknown)}")
            # one stage set, the other empty: auto-fill with the remaining
            # roles (ref: Utils.ensureStagedTasksIntegrity :431-449)
            if prepare_conf and not training_conf:
                training_conf = [r for r in deps if r not in prepare_conf]
            elif training_conf and not prepare_conf:
                prepare_conf = [r for r in deps if r not in training_conf]
            untracked = self.session.untracked | self.session.sidecars
            prepare = [r for r in prepare_conf if r not in untracked]
            for t in training_conf:
                deps[t].update(prepare)
        for role, ds in deps.items():
            unknown = ds - set(self.requests)
            if unknown:
                raise CycleError(f"role {role} depends on unknown roles: {sorted(unknown)}")
        if not self._is_dag(deps):
            raise CycleError(f"role dependency graph has a cycle: {deps}")
        return deps

    @staticmethod
    def _is_dag(deps: dict[str, set[str]]) -> bool:
        indeg = {r: len(ds) for r, ds in deps.items()}
        rdeps: dict[str, set[str]] = {r: set() for r in deps}
        for r, ds in deps.items():
            for d in ds:
                rdeps[d].add(r)
        queue = [r for r, n in indeg.items() if n == 0]
        seen = 0
        while queue:
            r = queue.pop()
            seen += 1
            for dep in rdeps[r]:
                indeg[dep] -= 1
                if indeg[dep] == 0:
                    queue.append(dep)
        return seen == len(deps)

    # -- scheduling (ref: scheduleTasks :55, scheduleJob :93) ---------------
    def schedule(self) -> list[str]:
        """Schedule every role whose dependencies are satisfied; returns the
        roles scheduled this call."""
        newly: list[str] = []
        for role, req in self.requests.items():
            if role in self.scheduled:
                continue
            if self.deps[role] <= self.completed_roles:
                log.info("scheduling role %s (%d instances)", role, req.instances)
                self.session.add_expected(req.instances)
                self.allocate(req)
                self.scheduled.add(role)
                newly.append(role)
        return newly

    # -- release (ref: registerDependencyCompleted :118) --------------------
    def on_role_instance_completed(self, role: str) -> list[str]:
        """Mark progress; if all instances of ``role`` completed, re-run
        scheduling and return any newly released roles."""
        slots = self.session.tasks.get(role)
        if slots is None:
            return []
        if all(t is not None and t.completed for t in slots):
            self.completed_roles.add(role)
            return self.schedule()
        return []

    def all_scheduled(self) -> bool:
        return self.scheduled == set(self.requests)

    def blocked_roles(self) -> set[str]:
        return set(self.requests) - self.scheduled
