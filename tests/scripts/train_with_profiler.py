"""Payload: a tiny training loop polling StepProfiler; exits 0 only if an
on-demand capture actually happened (driven by the coordinator's
request_profile command through the heartbeat channel)."""
import os
import sys
import time

sys.path.insert(0, os.environ["TONY_REPO_ROOT"])

import jax.numpy as jnp

from tony_tpu.profiler import StepProfiler


def main() -> int:
    prof = StepProfiler()
    deadline = time.time() + 30
    while time.time() < deadline:
        (jnp.ones((16, 16)) @ jnp.ones((16, 16))).block_until_ready()
        prof.poll()
        if prof.captures >= 1 and prof.active_steps_left == 0:
            print("capture complete")
            return 0
        time.sleep(0.05)
    print("no capture before deadline", file=sys.stderr)
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
