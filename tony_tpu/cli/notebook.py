"""``tony-tpu notebook`` — NotebookSubmitter equivalent.

Reference: tony-cli NotebookSubmitter.java:46-152: submits a single-task
app hosting e.g. Jupyter, watches task infos to discover the notebook's
host, and starts a local TCP proxy tunneling a gateway port to it; 24 h
default timeout.
"""

from __future__ import annotations

import argparse
import logging

from tony_tpu import constants as C
from tony_tpu.client import TonyClient
from tony_tpu.config import build_conf
from tony_tpu.proxy import ProxyServer

log = logging.getLogger(__name__)


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="tony-tpu notebook")
    parser.add_argument("--executes", required=True,
                        help="notebook command, e.g. 'jupyter lab --port $TB_PORT'")
    parser.add_argument("--conf", action="append", default=[])
    parser.add_argument("--conf_file")
    parser.add_argument("--port", type=int, default=0,
                        help="local gateway port (0 = ephemeral)")
    parser.add_argument("--timeout_hours", type=float, default=24.0)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    conf = build_conf(args.conf_file, args.conf)
    conf.set("tony.application.executes", args.executes)
    conf.set("tony.application.framework", "standalone")
    conf.set(f"tony.{C.NOTEBOOK_JOB_NAME}.instances", 1)
    conf.set("tony.application.untracked.jobtypes", "")
    conf.set("tony.application.timeout-ms", int(args.timeout_hours * 3600 * 1000))

    client = TonyClient(conf)
    proxy_holder: dict = {}

    def on_update(infos):
        """Discover the notebook host and start the proxy (ref:
        NotebookSubmitter proxy wiring :112-133)."""
        if proxy_holder:
            return
        for info in infos:
            if info.name == C.NOTEBOOK_JOB_NAME and info.status == "RUNNING" and info.host:
                proxy = ProxyServer(info.host, 8888, local_port=args.port).start()
                proxy_holder["proxy"] = proxy
                print(f"notebook tunnel ready: http://localhost:{proxy.local_port}")

    client.add_listener(on_update)
    ok = False
    try:
        ok = client.run()
    finally:
        if "proxy" in proxy_holder:
            proxy_holder["proxy"].stop()
    return C.EXIT_SUCCESS if ok else C.EXIT_FAIL


if __name__ == "__main__":
    raise SystemExit(main())
