"""Data sources for the input pipeline.

No reference analog: TonY leaves data loading entirely to the user script
(its examples read MNIST from local disk/HDFS themselves). A TPU framework
cannot — keeping the MXU fed is half the throughput battle — so tony-tpu
ships a small source/loader layer: a ``Source`` is random-access over
*examples* (host-side numpy), and the ``DataLoader`` (loader.py) turns it
into sharded, prefetched, device-resident global batches.

Sources are deliberately host-side and framework-free (pure numpy): the
device boundary is crossed exactly once, in the loader.
"""

from __future__ import annotations

import json
import os
from typing import Any, Mapping, Sequence

import numpy as np


class Source:
    """Random-access examples: len() + [i] -> dict of numpy arrays."""

    def __len__(self) -> int:  # pragma: no cover - interface
        raise NotImplementedError

    def __getitem__(self, idx: int) -> Mapping[str, np.ndarray]:
        raise NotImplementedError  # pragma: no cover - interface


class ArraySource(Source):
    """Wraps a dict of equal-leading-dim numpy arrays (in-memory dataset)."""

    def __init__(self, arrays: Mapping[str, np.ndarray]):
        if not arrays:
            raise ValueError("ArraySource needs at least one array")
        sizes = {k: len(v) for k, v in arrays.items()}
        if len(set(sizes.values())) != 1:
            raise ValueError(f"leading dims differ: {sizes}")
        self.arrays = {k: np.asarray(v) for k, v in arrays.items()}
        self._n = next(iter(sizes.values()))

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, idx: int) -> Mapping[str, np.ndarray]:
        return {k: v[idx] for k, v in self.arrays.items()}


class SyntheticTokenSource(Source):
    """Deterministic random token sequences (LM training/benchmarks).

    Example i is reproducible from (seed, i) alone, so every process
    materializes identical data without coordination — the multi-host-safe
    way to synthesize.
    """

    def __init__(self, num_examples: int, seq_len: int, vocab_size: int,
                 seed: int = 0):
        self.num_examples = num_examples
        self.seq_len = seq_len
        self.vocab_size = vocab_size
        self.seed = seed

    def __len__(self) -> int:
        return self.num_examples

    def __getitem__(self, idx: int) -> Mapping[str, np.ndarray]:
        rng = np.random.default_rng((self.seed, idx))
        return {"tokens": rng.integers(
            0, self.vocab_size, (self.seq_len,), dtype=np.int32)}


class SyntheticImageSource(Source):
    """Deterministic random image/label pairs (vision benchmarks)."""

    def __init__(self, num_examples: int, height: int, width: int,
                 channels: int = 3, num_classes: int = 1000, seed: int = 0):
        self.num_examples = num_examples
        self.shape = (height, width, channels)
        self.num_classes = num_classes
        self.seed = seed

    def __len__(self) -> int:
        return self.num_examples

    def __getitem__(self, idx: int) -> Mapping[str, np.ndarray]:
        rng = np.random.default_rng((self.seed, idx))
        return {
            "image": rng.standard_normal(self.shape, dtype=np.float32),
            "label": np.int32(rng.integers(0, self.num_classes)),
        }


class JsonlSource(Source):
    """Pre-tokenized examples from .jsonl file(s): one JSON object per line,
    values are lists/scalars converted to numpy. Line offsets are indexed
    once at open, so access is random without loading the file into memory.
    """

    def __init__(self, paths: str | Sequence[str],
                 dtypes: Mapping[str, Any] | None = None):
        if isinstance(paths, (str, os.PathLike)):
            paths = [paths]
        self.paths = [str(p) for p in paths]
        self.dtypes = dict(dtypes or {})
        self._index: list[tuple[int, int]] = []  # (file idx, byte offset)
        for fi, path in enumerate(self.paths):
            offset = 0
            with open(path, "rb") as f:
                for line in f:
                    if line.strip():
                        self._index.append((fi, offset))
                    offset += len(line)

    def __len__(self) -> int:
        return len(self._index)

    def __getitem__(self, idx: int) -> Mapping[str, np.ndarray]:
        fi, offset = self._index[idx]
        with open(self.paths[fi], "rb") as f:
            f.seek(offset)
            obj = json.loads(f.readline())
        out = {}
        for k, v in obj.items():
            dtype = self.dtypes.get(k)
            out[k] = np.asarray(v, dtype=dtype) if dtype else np.asarray(v)
        return out


class InstructionSource(Source):
    """Supervised fine-tuning examples: prompt/completion pairs ->
    fixed-length ``{"tokens", "loss_mask"}`` where the mask is 1 ONLY on
    completion (+eos) token positions — prompts and padding contribute
    nothing to the objective. The standard SFT recipe wired to the
    in-tree loss::

        loss = cross_entropy_loss(logits[:, :-1], batch["tokens"][:, 1:],
                                  mask=batch["loss_mask"][:, 1:])

    (``loss_mask[t]`` marks token t as a PREDICTION TARGET; shifting by
    one aligns it with the teacher-forced logits, exactly like the
    packed-corpus segment masking.)

    ``pairs`` is any Source/sequence of dicts carrying text under
    ``prompt_key``/``completion_key`` (e.g. a ``JsonlSource`` over an
    instruction dataset). ``tokenizer`` is any object with ``encode()``
    (the in-tree ``ByteTokenizer`` works fully offline). Tokenization is
    lazy per example — nothing is materialized up front. Examples whose
    prompt alone fills ``seq_len`` yield an all-zero mask (0 loss), not
    an error: bulk datasets carry a tail of overlong rows.
    """

    def __init__(self, pairs, tokenizer, seq_len: int, *,
                 prompt_key: str = "prompt",
                 completion_key: str = "completion",
                 eos_id: int | None = None, pad_id: int = 0):
        if seq_len < 2:
            raise ValueError("seq_len must be >= 2 (one target at least)")
        self.pairs = pairs
        self.tokenizer = tokenizer
        self.seq_len = seq_len
        self.prompt_key = prompt_key
        self.completion_key = completion_key
        self.eos_id = eos_id
        self.pad_id = pad_id

    def __len__(self) -> int:
        return len(self.pairs)

    def __getitem__(self, idx: int) -> Mapping[str, np.ndarray]:
        row = self.pairs[idx]
        prompt = self.tokenizer.encode(str(row[self.prompt_key]))
        completion = self.tokenizer.encode(str(row[self.completion_key]))
        if self.eos_id is not None:
            completion = completion + [self.eos_id]
        tokens = np.full((self.seq_len,), self.pad_id, np.int32)
        mask = np.zeros((self.seq_len,), np.float32)
        ids = (prompt + completion)[:self.seq_len]
        tokens[:len(ids)] = ids
        mask[len(prompt):len(ids)] = 1.0  # completion positions only
        return {"tokens": tokens, "loss_mask": mask}


class PackedTokenSource(Source):
    """Flat binary token stream (np.memmap) sliced into fixed-length
    windows — the standard packed-pretraining format (one giant .bin of
    uint16/uint32 token ids, documents separated by an EOS id upstream).

    Example i is tokens[i*stride : i*stride + seq_len + 1] split into
    ``tokens`` (inputs) and ``labels`` (inputs shifted by one), so the
    loader feeds next-token prediction directly. ``stride`` defaults to
    ``seq_len`` (disjoint windows); smaller strides overlap.

    memmap keeps the host working set at pages actually touched, so a
    multi-hundred-GB corpus serves random access from every host without
    loading; combined with the DataLoader's per-process strides each host
    only ever pages in its own shard of the permutation.
    """

    def __init__(self, path: str, seq_len: int, dtype=np.uint16,
                 stride: int | None = None,
                 segment_eos_id: int | None = None):
        self.path = str(path)
        self.seq_len = seq_len
        self.stride = seq_len if stride is None else stride
        # emit per-window "segments" (document index within the window,
        # split at this eos id) for segment-masked attention — packed
        # documents then never attend across their boundaries
        self.segment_eos_id = segment_eos_id
        if self.stride <= 0:
            raise ValueError(f"stride must be positive, got {self.stride}")
        self._tokens = np.memmap(self.path, dtype=dtype, mode="r")
        # +1: each window needs a trailing target for the shifted labels
        n = (len(self._tokens) - self.seq_len - 1) // self.stride + 1
        if len(self._tokens) < self.seq_len + 1:
            raise ValueError(
                f"{path}: {len(self._tokens)} tokens < seq_len+1 "
                f"({self.seq_len + 1})")
        self._n = n

    def __len__(self) -> int:
        return self._n

    def __getitem__(self, idx: int) -> Mapping[str, np.ndarray]:
        start = idx * self.stride
        window = np.asarray(self._tokens[start:start + self.seq_len + 1],
                            dtype=np.int32)
        out = {"tokens": window[:-1], "labels": window[1:]}
        if self.segment_eos_id is not None:
            toks = out["tokens"]
            is_eos = (toks == self.segment_eos_id).astype(np.int32)
            # segment of position i = number of eos strictly before i
            # (an eos token still belongs to the document it terminates)
            out["segments"] = np.cumsum(is_eos) - is_eos
        return out


class MixtureSource(Source):
    """Weighted mixture of sources — the standard pretraining-corpus blend
    (e.g. 70% web, 20% code, 10% books).

    Deterministic and multi-host safe: example i's component is drawn from
    (seed, i) alone and its index within the component advances as an
    independent deterministic stream, so every process materializes the
    identical mixture without coordination (same contract as
    SyntheticTokenSource). Components cycle independently: a small
    component repeats (standard epoch-mixing) rather than truncating the
    mixture. All components must share an example schema.

    ``num_examples`` bounds the virtual length (mixtures are usually
    sampled-with-replacement streams, so length is a budget, not a size).
    """

    def __init__(self, components: "Sequence[tuple[Source, float]]",
                 num_examples: int, seed: int = 0):
        if not components:
            raise ValueError("MixtureSource needs at least one component")
        self.sources = [s for s, _ in components]
        weights = np.asarray([w for _, w in components], np.float64)
        if (weights <= 0).any():
            raise ValueError(f"weights must be positive, got {weights}")
        self.probs = weights / weights.sum()
        self.num_examples = num_examples
        self.seed = seed
        # per-component pick counts are cumulative over the index stream;
        # computing them per __getitem__ would be O(i), so precompute the
        # component choice for every index once (num_examples ints)
        rng = np.random.default_rng((seed, 0xB1E2D))
        self._choice = rng.choice(len(self.sources), size=num_examples,
                                  p=self.probs).astype(np.int32)
        # within-component position: the k-th pick of component c maps to
        # its example (k mod len(c)); vectorized — a Python loop here
        # would cost minutes of per-host startup at stream-scale budgets
        self._pos = np.zeros(num_examples, np.int64)
        for c in range(len(self.sources)):
            mask = self._choice == c
            self._pos[mask] = np.arange(int(mask.sum()), dtype=np.int64)

    def __len__(self) -> int:
        return self.num_examples

    def __getitem__(self, idx: int) -> Mapping[str, np.ndarray]:
        c = int(self._choice[idx])
        src = self.sources[c]
        return src[int(self._pos[idx]) % len(src)]

    def component_counts(self) -> np.ndarray:
        """How many of the virtual examples come from each component."""
        return np.bincount(self._choice, minlength=len(self.sources))
