"""Per-job TLS for the control-plane RPC — the transport-security half of
the reference's ClientToAM story.

Reference: ApplicationMaster.java:484-504 builds a ClientToAMTokenSecret-
Manager and hands Hadoop RPC a SASL-wrapped transport;
security/TokenCache.java:22-78 distributes the credentials. The rebuild's
HMAC frames (wire.py) already carry the integrity half; this module adds
confidentiality: the CLIENT mints a self-signed per-job certificate into
the job dir at staging time (openssl subprocess — stdlib-only code), the
coordinator serves TLS with it, and every peer (client, agents) verifies
the certificate by SHA-256 fingerprint carried in the job's env — no CA,
no hostname checks, exactly one key pair per job, dead with the job dir.
"""

from __future__ import annotations

import base64
import hashlib
import logging
import os
import subprocess

log = logging.getLogger(__name__)

CERT_FILE = "tls-cert.pem"
KEY_FILE = "tls-key.pem"


class TlsError(RuntimeError):
    pass


def mint_self_signed(job_dir: str, cn: str) -> tuple[str, str]:
    """Write <job_dir>/tls-cert.pem + tls-key.pem (idempotent); returns
    their paths. RSA-2048, 7-day validity — a job outliving that has
    bigger problems."""
    cert = os.path.join(job_dir, CERT_FILE)
    key = os.path.join(job_dir, KEY_FILE)
    if os.path.exists(cert) and os.path.exists(key):
        return cert, key
    os.makedirs(job_dir, exist_ok=True)
    try:
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", key, "-out", cert, "-days", "7",
             "-subj", f"/CN={cn}"],
            check=True, capture_output=True, timeout=60)
    except (OSError, subprocess.SubprocessError) as e:
        detail = getattr(e, "stderr", b"") or b""
        raise TlsError(
            f"could not mint the per-job TLS cert (is openssl installed?): "
            f"{e} {detail.decode(errors='replace')[-200:]}") from e
    os.chmod(key, 0o600)
    return cert, key


def cert_fingerprint(cert_path: str) -> str:
    """SHA-256 over the DER certificate — what peers pin (env-carried)."""
    with open(cert_path, "rb") as f:
        pem = f.read()
    try:
        body = pem.split(b"-----BEGIN CERTIFICATE-----")[1] \
            .split(b"-----END CERTIFICATE-----")[0]
        der = base64.b64decode(b"".join(body.split()))
    except (IndexError, ValueError) as e:
        raise TlsError(f"unparseable certificate {cert_path}: {e}") from e
    return hashlib.sha256(der).hexdigest()


def server_context(cert_path: str, key_path: str):
    import ssl

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(cert_path, key_path)
    return ctx


def client_wrap(sock, fingerprint: str):
    """Wrap + pin: self-signed means no chain to verify — the pinned
    fingerprint IS the trust anchor, so CERT_NONE here is not 'insecure',
    it just moves verification to the explicit digest compare."""
    import ssl

    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_NONE
    import hmac

    wrapped = ctx.wrap_socket(sock)
    der = wrapped.getpeercert(binary_form=True)
    got = hashlib.sha256(der or b"").hexdigest()
    if not der or not hmac.compare_digest(got, fingerprint):
        wrapped.close()
        raise ConnectionError(
            f"TLS certificate fingerprint mismatch (got {got[:16]}..., "
            f"pinned {fingerprint[:16]}...) — wrong or impostor coordinator")
    return wrapped
