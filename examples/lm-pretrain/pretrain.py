"""Decoder-LM pretraining on the full tony-tpu stack: DataLoader ->
GQA/MoE Transformer -> chunked large-vocab CE -> fit() with checkpointing.

No reference analog (tony-examples are MNIST-era scripts that hand-roll
their input and loops) — this is the "what a modern job script looks like"
example: ~60 lines of configuration, everything else is framework.

Runs standalone (single process) or under a tony-tpu gang; with
tony.application.checkpoint-dir set, a coordinator retry resumes from the
latest checkpoint automatically (fit() reads TONY_CHECKPOINT_DIR).

    python -m tony_tpu.cli.local --conf_file examples/lm-pretrain/job.toml
"""

from __future__ import annotations

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))  # repo root, for standalone runs

import jax
import jax.numpy as jnp
import optax


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--global-batch", type=int, default=32)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--vocab", type=int, default=512)
    p.add_argument("--moe", action="store_true", help="MoE FFN every 2nd block")
    p.add_argument("--accum", type=int, default=1,
                   help="gradient-accumulation microbatches: activation "
                        "footprint of ONE micro, optimizer amortized over "
                        "the global batch (the r5 flagship recipe trains "
                        "at micro 4 x accum 16 = batch 64)")
    p.add_argument("--fused-adamw", action="store_true",
                   help="FusedAdamW + compute-dtype carry: the update "
                        "emits the next step's bf16 params (no separate "
                        "cast pass, bf16 grads) — the bench flagship "
                        "optimizer (docs/PERF.md r5)")
    p.add_argument("--text", nargs="+", default=None, metavar="FILE",
                   help="pretrain on these text files (byte-tokenized into "
                        "a packed .bin) instead of synthetic tokens")
    args = p.parse_args()

    from tony_tpu import distributed
    from tony_tpu.data import (ByteTokenizer, DataLoader, PackedTokenSource,
                               SyntheticTokenSource, encode_files_to_bin)
    from tony_tpu.models import Transformer, TransformerConfig, moe_aux_loss
    from tony_tpu.ops import chunked_cross_entropy
    from tony_tpu.parallel import data_parallel_mesh
    from tony_tpu.parallel.sharding import batch_sharding
    from tony_tpu.train import (
        FusedAdamW,
        JsonlMetricsLogger,
        Trainer,
        fit,
    )

    distributed.initialize()  # no-op outside a gang
    mesh = data_parallel_mesh()

    tok = None
    if args.text:
        # raw text -> packed corpus: byte tokenizer keeps this offline
        tok = ByteTokenizer()
        args.vocab = tok.vocab_size
        # job dir is per-job; standalone runs get a run-unique tempdir so
        # concurrent runs on one host never clobber a live memmap —
        # removed at exit so repeated runs don't fill /tmp
        work = os.environ.get("TONY_JOB_DIR")
        if not work:
            import atexit
            import shutil

            work = tempfile.mkdtemp(prefix="lm-pretrain-")
            atexit.register(shutil.rmtree, work, ignore_errors=True)
        corpus = os.path.join(work, f"corpus-{jax.process_index()}.bin")
        n_tok = encode_files_to_bin(args.text, corpus, tok.encode,
                                    eos_id=tok.eos_id)
        print(f"tokenized {len(args.text)} file(s) -> {n_tok} tokens")

    # --fused-adamw is the bf16 recipe end to end: the MODEL computes in
    # bf16 too (compute_dtype alone would be undone by fp32 layer dtypes)
    model_dtype = jnp.bfloat16 if args.fused_adamw else jnp.float32
    lr = 3e-3
    cfg = TransformerConfig(
        vocab_size=args.vocab, d_model=64, n_heads=4, n_kv_heads=2,
        n_layers=2, d_ff=128, max_seq_len=args.seq_len,
        dtype=model_dtype, attention_backend="blockwise",
        attention_block_size=64,
        moe_every=2 if args.moe else 0, moe_num_experts=4, moe_top_k=2)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, args.seq_len), jnp.int32))

    def apply_fn(p, batch):
        # segment ids (packed real text): documents in one window never
        # attend across their boundaries
        segs = batch.get("segments")
        # hidden + chunked CE: the [B, L, V] logits are never materialized
        if cfg.moe_every:
            hidden, mut = model.apply(p, batch["tokens"], return_hidden=True,
                                      segment_ids=segs, mutable=["losses"])
            aux = moe_aux_loss(mut["losses"])
        else:
            hidden = model.apply(p, batch["tokens"], return_hidden=True,
                                 segment_ids=segs)
            aux = 0.0
        # drop the cross-boundary target after each EOS: the next
        # document's first token is unpredictable noise
        loss_mask = None if segs is None else segs[:, :-1] == segs[:, 1:]
        ce = chunked_cross_entropy(hidden[:, :-1], p["params"]["embedding"],
                                   batch["tokens"][:, 1:], chunk_size=256,
                                   mask=loss_mask)
        return ce + aux

    if tok is not None:
        source = PackedTokenSource(corpus, seq_len=args.seq_len,
                                   segment_eos_id=tok.eos_id)
    else:
        source = SyntheticTokenSource(
            num_examples=args.global_batch * max(args.steps, 1),
            seq_len=args.seq_len, vocab_size=args.vocab, seed=0)
    loader = DataLoader(source, global_batch_size=args.global_batch,
                        num_epochs=None, sharding=batch_sharding(mesh))

    if args.fused_adamw:
        optimizer, compute_dtype = FusedAdamW(lr), jnp.bfloat16
    else:
        optimizer, compute_dtype = optax.adamw(lr), None
    trainer = Trainer(mesh=mesh, apply_fn=apply_fn,
                      optimizer=optimizer, donate=False,
                      compute_dtype=compute_dtype,
                      accum_steps=args.accum)
    sinks = []
    # one writer per job: the job dir is shared by the whole gang
    if os.environ.get("TONY_JOB_DIR") and jax.process_index() == 0:
        sinks.append(JsonlMetricsLogger(
            os.path.join(os.environ["TONY_JOB_DIR"], "metrics",
                         "train.jsonl")))
    # total_steps (not num_steps): a coordinator retry resumes and
    # completes the original budget instead of training a fresh one
    result = fit(trainer, params, loader, total_steps=args.steps,
                 checkpoint_every=max(args.steps // 2, 1), log_every=5,
                 metric_sinks=sinks)
    losses = [h["loss"] for h in result.history if "loss" in h]
    print(f"trained {result.steps_run} steps"
          + (f" (resumed from {result.resumed_from})"
             if result.resumed_from else "")
          + (f"; loss {losses[0]:.3f} -> {losses[-1]:.3f}" if losses else ""))
    if losses and not all(jnp.isfinite(jnp.asarray(losses))):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
