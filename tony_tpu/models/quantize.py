"""int8 weight-only serving conversion for the flagship transformer.

``quantize_for_serving(model, params)`` rewrites every dense kernel of a
trained/imported model into the ``{kernel_q8 int8, scale fp32}`` form
that ``TransformerConfig(quantized=True)``'s QuantDense consumes through
the pallas dequant-matmul (ops/quant.py) — HALF the weight bytes per
decode step (docs/PERF.md decode roofline). Embeddings, norms, biases,
and the LM head stay full precision: they are a small fraction of the
bytes and dominate quality.

Scope: the dense transformer family (everything models/hf.py imports —
GPT-2, Llama/Mistral/Qwen2, Gemma, GPT-NeoX, Phi) plus MoE expert
weights (Mixtral: per-expert, per-output-channel scales, served through
a vmapped pallas dequant matmul). scan-stacked layers are rejected
rather than half-converted.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

from tony_tpu.models.transformer import Transformer
from tony_tpu.ops.quant import quantize_q8

# parent module names whose "kernel" leaf becomes int8
_DENSE_NAMES = ("q", "k", "v", "o", "wi", "wg", "wo")


def _quantize_kernel(kernel, is_o: bool, on_device: bool = False):
    """kernel [in, *out] (q/k/v/wi/wg/wo) or [*in, out] (o) -> 2-D
    int8 + per-output-channel scale, matching QuantDense's flatten.

    ``on_device``: keep the leaf a jax array so multi-GB checkpoints
    already living in HBM never round-trip to host (the tunneled
    backend's transfer path would dominate the conversion)."""
    if on_device:
        import jax.numpy as xp
    else:
        xp = np
    arr = kernel if on_device else np.asarray(kernel)
    if is_o:  # o: [heads, dh, d] — leading axes are the INPUT
        in_flat = arr.shape[0] * arr.shape[1] if arr.ndim == 3 \
            else arr.shape[0]
        w2 = xp.reshape(arr, (in_flat, arr.shape[-1]))
    else:  # [in, *out]
        w2 = xp.reshape(arr, (arr.shape[0], -1))
    w_q, scale = quantize_q8(w2)
    return {"kernel_q8": w_q, "scale": scale}


def quantize_transformer_params(params: Any, on_device: bool = False) -> Any:
    """params pytree (as from model.init / hf import) -> quantized tree.
    Biases ride along unchanged; every other leaf passes through.
    ``on_device``: quantize with jnp, for params already in HBM."""

    xp = np
    if on_device:
        import jax.numpy as xp  # noqa: F811

    def quantize_expert(arr):
        # [E, in, out]: contraction over axis 1, so the per-output-channel
        # scale is per (expert, out) — the 3-D analog of quantize_q8
        a = xp.asarray(arr, xp.float32)
        absmax = xp.max(xp.abs(a), axis=1)
        scale = xp.maximum(absmax, 1e-8) / 127.0
        q = xp.clip(xp.round(a / scale[:, None, :]), -127, 127) \
            .astype(xp.int8)
        return q, scale.astype(xp.float32)

    def walk(node, name=""):
        if not isinstance(node, dict):
            return node
        if "kernel" in node and name in _DENSE_NAMES:
            out = _quantize_kernel(node["kernel"], is_o=(name == "o"),
                                   on_device=on_device)
            if "bias" in node:
                out["bias"] = node["bias"]
            extra = set(node) - {"kernel", "bias"}
            if extra:
                raise ValueError(f"unexpected leaves under {name}: {extra}")
            return out
        if "router" in node and "wi" in node:  # MoE expert block (Mixtral)
            out = {"router": node["router"]}
            for nm in ("wi", "wg", "wo"):
                if nm in node:
                    out[nm + "_q8"], out[nm + "_scale"] = \
                        quantize_expert(node[nm])
            extra = set(node) - {"router", "wi", "wg", "wo"}
            if extra:
                raise ValueError(f"unexpected MoE leaves: {extra}")
            return out
        return {k: walk(v, k) for k, v in node.items()}

    return walk(params)


def quantize_for_serving(model: Transformer, params: Any,
                         on_device: bool = False
                         ) -> tuple[Transformer, Any]:
    """(model, params) -> (quantized model, quantized params): the
    returned pair drops into generate()/score exactly like the original.
    ``on_device``: convert with jnp so a multi-GB tree already in HBM
    never round-trips through host memory.
    """
    cfg = model.cfg
    if cfg.scan_layers:
        raise ValueError("int8 serving conversion expects per-block "
                         "params (scan_layers stacks them)")
    qcfg = dataclasses.replace(cfg, quantized=True)
    return Transformer(qcfg), quantize_transformer_params(
        params, on_device=on_device)


def shard_expert_qparams(mesh, qparams: Any, axis: str = "expert") -> Any:
    """Place a quantized tree's MoE expert weights SHARDED on ``axis``
    (wi/wg/wo_q8 on dim 0, their scales likewise) and leave everything
    else where it is. This is the placement the shard_mapped q8 expert
    FFN consumes (parallel/moe.py): per-device HBM holds only E/ways
    experts — how a 47B-class Mixtral fits a slice. Pair with a
    TransformerConfig whose ``mesh`` carries the same axis."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    def place(node, name=""):
        if isinstance(node, dict):
            return {k: place(v, k) for k, v in node.items()}
        if name in ("wi_q8", "wg_q8", "wo_q8"):
            return jax.device_put(jnp.asarray(node),
                                  NamedSharding(mesh, P(axis, None, None)))
        if name in ("wi_scale", "wg_scale", "wo_scale"):
            return jax.device_put(jnp.asarray(node),
                                  NamedSharding(mesh, P(axis, None)))
        return node

    return place(qparams)


def quantize_cli(model, params):
    """CLI-facing wrapper: unsupported configs exit with a clean message
    instead of a traceback (shared by the generate and score CLIs)."""
    try:
        return quantize_for_serving(model, params)
    except ValueError as e:
        raise SystemExit(f"--int8: {e}")
