from tony_tpu.agent.executor import Heartbeater, TaskAgent

__all__ = ["Heartbeater", "TaskAgent"]
