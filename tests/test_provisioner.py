"""Provisioner tests — the RM capacity-acquisition analog, driven through
a fake gcloud binary (ref: TonyClient.submitApplication
TonyClient.java:314-349; per-role container requests
TaskScheduler.java:93-105, util/Utils.java:420-430)."""

import json
import os

import pytest

from tony_tpu.config import ConfError, TonyConf
from tony_tpu.coordinator.provisioner import (
    STATE_READY,
    GcloudRunner,
    ProvisioningError,
    StaticProvisioner,
    TpuVmProvisioner,
    chips_in_accelerator_type,
    preflight_chips,
    provisioner_from_conf,
    required_chips,
)
from tony_tpu.mini import MiniTonyCluster, script_conf

SCRIPTS = os.path.join(os.path.dirname(__file__), "scripts")
FAKE_GCLOUD = os.path.join(SCRIPTS, "fake_gcloud.py")
FAKE_SSH = os.path.join(SCRIPTS, "fake_ssh.sh")
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def gdir(tmp_path, monkeypatch):
    d = tmp_path / "gcloud-state"
    d.mkdir()
    monkeypatch.setenv("FAKE_GCLOUD_DIR", str(d))
    return str(d)


def make_prov(gdir, name="t1", **kw):
    kw.setdefault("timeout_s", 10)
    kw.setdefault("poll_interval_s", 0.01)
    runner = GcloudRunner(FAKE_GCLOUD, project="proj", zone="zone-a")
    return TpuVmProvisioner(name, "v5p-8", "tpu-ubuntu2204-base", runner,
                           **kw)


def node_state(gdir, name="t1"):
    with open(os.path.join(gdir, f"{name}.node.json")) as f:
        return json.load(f)


def calls(gdir):
    path = os.path.join(gdir, "calls.log")
    if not os.path.exists(path):
        return ""
    with open(path) as f:
        return f.read()


# -- sizing -------------------------------------------------------------


def test_required_chips_sums_roles():
    conf = TonyConf()
    conf.set("tony.worker.instances", 4)
    conf.set("tony.worker.chips", 4)
    conf.set("tony.ps.instances", 2)  # no chips -> excluded
    conf.set("tony.evaluator.instances", 1)
    conf.set("tony.evaluator.chips", 2)
    assert required_chips(conf) == 18


def test_chips_in_accelerator_type():
    # v2-v5p name TensorCores (2/chip); v5e/v6e name chips
    assert chips_in_accelerator_type("v5p-32") == 16
    assert chips_in_accelerator_type("v4-8") == 4
    assert chips_in_accelerator_type("v5litepod-16") == 16
    assert chips_in_accelerator_type("v6e-8") == 8
    assert chips_in_accelerator_type("") == 0
    assert chips_in_accelerator_type("weird-shape") == 0


# -- TpuVmProvisioner over fake gcloud ----------------------------------


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_provision_creates_awaits_ready_then_deletes(gdir):
    prov = make_prov(gdir)
    hosts = prov.provision()
    assert hosts == ["10.0.0.1", "10.0.0.2"]
    assert prov.state == STATE_READY
    log = calls(gdir)
    assert "tpu-vm create t1" in log and "--accelerator-type v5p-8" in log
    assert "--zone zone-a" in log and "--project proj" in log
    prov.deprovision()
    assert node_state(gdir)["deleted"] is True


def test_provision_adopts_existing_slice(gdir):
    with open(os.path.join(gdir, "t1.node.json"), "w") as f:
        json.dump({"name": "t1", "state": "READY", "describes": 99,
                   "deleted": False}, f)
    prov = make_prov(gdir)
    hosts = prov.provision()
    assert hosts == ["10.0.0.1", "10.0.0.2"]
    assert "create" not in calls(gdir)


def test_provision_rejects_existing_when_reuse_off(gdir):
    with open(os.path.join(gdir, "t1.node.json"), "w") as f:
        json.dump({"name": "t1", "state": "READY", "describes": 0,
                   "deleted": False}, f)
    with pytest.raises(ProvisioningError, match="already exists"):
        make_prov(gdir, reuse=False).provision()


def test_provision_times_out(gdir, monkeypatch):
    monkeypatch.setenv("FAKE_GCLOUD_READY_AFTER", "100000")
    with pytest.raises(ProvisioningError, match="not READY within"):
        make_prov(gdir, timeout_s=0.3).provision()


def test_provision_fails_on_doomed_node(gdir, monkeypatch):
    monkeypatch.setenv("FAKE_GCLOUD_DOOM", "1")
    with pytest.raises(ProvisioningError, match="PREEMPTED"):
        make_prov(gdir).provision()


def test_provision_create_denied(gdir, monkeypatch):
    monkeypatch.setenv("FAKE_GCLOUD_FAIL_CREATE", "1")
    with pytest.raises(ProvisioningError, match="quota"):
        make_prov(gdir).provision()


def test_keep_skips_teardown(gdir):
    prov = make_prov(gdir, keep=True)
    prov.provision()
    prov.deprovision()
    assert node_state(gdir)["deleted"] is False


def test_queued_mode(gdir):
    prov = make_prov(gdir, queued=True)
    hosts = prov.provision()
    assert hosts == ["10.0.0.1", "10.0.0.2"]
    log = calls(gdir)
    assert "queued-resources create t1 --node-id t1" in log
    assert "--runtime-version" in log and "--version " not in log
    prov.deprovision()
    assert "queued-resources delete t1" in calls(gdir)
    assert node_state(gdir)["deleted"] is True


# -- conf plumbing ------------------------------------------------------


def test_provisioner_from_conf_modes():
    conf = TonyConf()
    conf.set("tony.application.hosts", "h1,h2")
    prov = provisioner_from_conf(conf, "application_1")
    assert isinstance(prov, StaticProvisioner)
    assert prov.provision() == ["h1", "h2"]

    conf.set("tony.provisioner.mode", "tpu-vm")
    conf.set("tony.provisioner.accelerator-type", "v5p-8")
    prov2 = provisioner_from_conf(conf, "application_1")
    assert isinstance(prov2, TpuVmProvisioner)
    assert prov2.name == "tony-application-1"  # derived, app-id qualified

    conf.set("tony.provisioner.mode", "nope")
    with pytest.raises(ConfError, match="unknown tony.provisioner.mode"):
        provisioner_from_conf(conf, "application_1")


def test_provisioner_from_conf_rejects_undersized_slice():
    conf = TonyConf()
    conf.set("tony.worker.instances", 2)
    conf.set("tony.worker.chips", 4)  # 8 chips wanted
    conf.set("tony.provisioner.mode", "tpu-vm")
    conf.set("tony.provisioner.accelerator-type", "v4-8")  # 4 chips
    with pytest.raises(ConfError, match="4 chips but roles request 8"):
        provisioner_from_conf(conf, "app")


def test_provisioner_from_conf_requires_accel_type():
    conf = TonyConf()
    conf.set("tony.provisioner.mode", "tpu-vm")
    with pytest.raises(ConfError, match="accelerator-type"):
        provisioner_from_conf(conf, "app")


# -- autoscaler scale paths (ISSUE-9) -----------------------------------


def test_static_provisioner_scale_idempotence():
    """The autoscaler backend's contract on the no-op provisioner:
    provision()/deprovision() are idempotent (repeat calls return the
    same hosts / stay no-ops, state stays READY) — a scale-up/down
    cycle through a StaticProvisioner must never mutate capacity."""
    prov = StaticProvisioner(["h1", "h2"])
    assert prov.state == STATE_READY
    assert prov.provision() == ["h1", "h2"]
    assert prov.provision() == ["h1", "h2"]  # re-provision: same hosts
    prov.deprovision()
    prov.deprovision()  # double-release: no-op, no raise
    assert prov.state == STATE_READY
    assert prov.provision() == ["h1", "h2"]  # usable after release
    assert StaticProvisioner().provision() == []  # hostless default


def test_static_provisioner_drives_autoscaler_backend():
    """ProvisionerBackend over StaticProvisioners: each create()
    acquires through provision(), destroy() releases exactly the
    matching slice — the in-process analog of the TPU-VM scale path."""
    from tony_tpu.gateway import ProvisionerBackend

    provs = {}

    def factory(slot):
        provs[slot] = StaticProvisioner([f"host-{slot}"])
        return provs[slot]

    backend = ProvisionerBackend(factory, lambda hosts: list(hosts))
    s0, s1 = backend.create(), backend.create()
    assert (s0, s1) == (["host-0"], ["host-1"])
    backend.destroy(s0)
    backend.destroy(s0)  # unknown/already-destroyed: no-op
    assert provs[1].provision() == ["host-1"]  # s1's slice untouched


def test_provisioner_from_conf_bad_numeric_conf_is_typed():
    """Malformed numeric conf values fail TYPED (ConfError naming the
    key), not as a bare int() stack trace — both at set() time (typed
    keys) and at provisioner_from_conf() time (values that bypassed
    coercion, e.g. a hand-edited final conf)."""
    conf = TonyConf()
    with pytest.raises(ConfError, match="timeout-ms must be an integer"):
        conf.set("tony.provisioner.timeout-ms", "soon")
    conf2 = TonyConf()
    conf2.set("tony.provisioner.mode", "queued")
    conf2.set("tony.provisioner.accelerator-type", "v5p-8")
    # values can reach the reader uncoerced (hand-edited final conf);
    # the dispatch must still fail typed, naming the key
    conf2._values["tony.tpu.num-slices"] = "many"
    with pytest.raises(ConfError, match="num-slices must be an integer"):
        provisioner_from_conf(conf2, "app")
    conf3 = TonyConf()
    conf3.set("tony.provisioner.mode", "tpu-vm")
    conf3.set("tony.provisioner.accelerator-type", "v5p-8")
    conf3._values["tony.worker.instances"] = 2
    conf3._values["tony.worker.chips"] = "lots"
    with pytest.raises(ConfError, match="chips must be an integer"):
        provisioner_from_conf(conf3, "app")


def test_provisioner_from_conf_missing_conf_dispatch():
    """Dispatch with missing conf: mode none + no hosts is a working
    empty StaticProvisioner (local devices); slice modes without an
    accelerator type fail typed."""
    prov = provisioner_from_conf(TonyConf(), "app")
    assert isinstance(prov, StaticProvisioner)
    assert prov.provision() == []
    conf = TonyConf()
    conf.set("tony.provisioner.mode", "queued")
    with pytest.raises(ConfError, match="accelerator-type"):
        provisioner_from_conf(conf, "app")


# -- local preflight ----------------------------------------------------


def fake_tpu_info(tmp_path, n_chips: int) -> str:
    path = os.path.join(str(tmp_path), "tpu-info")
    chips = [{"device_id": i, "hbm_total_bytes": 1} for i in range(n_chips)]
    body = json.dumps({"accelerator_type": "test", "chips": chips})
    with open(path, "w") as f:
        f.write(f"#!/bin/sh\necho '{body}'\n")
    os.chmod(path, 0o755)
    return path


def test_preflight_chips(tmp_path):
    conf = TonyConf()
    conf.set("tony.worker.instances", 2)
    conf.set("tony.worker.chips", 2)  # 4 wanted
    conf.set("tony.tpu.info-exec-path", fake_tpu_info(tmp_path, 2))
    err = preflight_chips(conf)
    assert err and "request 4 chips" in err and "has 2" in err

    conf.set("tony.tpu.info-exec-path", fake_tpu_info(tmp_path, 4))
    assert preflight_chips(conf) is None

    conf2 = TonyConf()  # no chip demand -> never checked
    conf2.set("tony.worker.instances", 8)
    assert preflight_chips(conf2) is None


# -- e2e: submit -> provision -> train -> deprovision -------------------


def test_provision_e2e_submit_train_teardown(gdir, monkeypatch):
    """The full RM story on the mini cluster: the coordinator creates the
    slice through (fake) gcloud, launches the gang onto its hosts through
    (fake) ssh, trains, and tears the slice down at stop."""
    monkeypatch.setenv("FAKE_GCLOUD_HOSTS", "localhost")
    monkeypatch.setenv("FAKE_GCLOUD_READY_AFTER", "2")
    with MiniTonyCluster() as cluster:
        conf = script_conf(cluster, os.path.join(SCRIPTS, "exit_0.py"),
                           {"worker": 2})
        conf.set("tony.application.launch-mode", "ssh")
        conf.set("tony.application.ssh-bin", FAKE_SSH)
        conf.set("tony.application.remote-pythonpath", REPO_ROOT)
        conf.set("tony.provisioner.mode", "tpu-vm")
        conf.set("tony.provisioner.accelerator-type", "v5p-8")
        conf.set("tony.provisioner.gcloud-bin", FAKE_GCLOUD)
        conf.set("tony.provisioner.poll-interval-ms", 50)
        client = cluster.submit(conf)
        assert client.final_status["status"] == "SUCCEEDED", \
            client.final_status
        name = f"tony-{client.app_id.replace('_', '-')}"
        st = node_state(gdir, name)
        assert st["deleted"] is True  # torn down at job stop
        log = calls(gdir)
        assert f"tpu-vm create {name}" in log
        assert f"tpu-vm delete {name}" in log


def test_provision_failure_fails_job_fast(gdir, monkeypatch):
    monkeypatch.setenv("FAKE_GCLOUD_FAIL_CREATE", "1")
    with MiniTonyCluster() as cluster:
        conf = script_conf(cluster, os.path.join(SCRIPTS, "exit_0.py"),
                           {"worker": 1})
        conf.set("tony.application.launch-mode", "ssh")
        conf.set("tony.application.ssh-bin", FAKE_SSH)
        conf.set("tony.provisioner.mode", "tpu-vm")
        conf.set("tony.provisioner.accelerator-type", "v5p-8")
        conf.set("tony.provisioner.gcloud-bin", FAKE_GCLOUD)
        client = cluster.make_client(conf)
        ok = client.run()
        assert not ok
        assert "provisioning failed" in str(
            client.final_status.get("reason", ""))


# -- multislice (multi-node queued resources) ---------------------------


def test_queued_multi_node_multislice(gdir):
    """VERDICT r2 #4: tony.tpu.num-slices>1 provisions ONE queued resource
    with N nodes (--node-count/--node-prefix); hosts concatenate in node
    order so contiguous flat-index ranges map onto one slice."""
    prov = make_prov(gdir, queued=True, node_count=2)
    assert prov.node_names() == ["t1-0", "t1-1"]
    hosts = prov.provision()
    assert hosts == ["10.0.0.1", "10.0.0.2", "10.0.1.1", "10.0.1.2"]
    log = calls(gdir)
    assert "queued-resources create t1 --node-count 2 --node-prefix t1" \
        in log
    assert "--node-id" not in log
    prov.deprovision()
    assert node_state(gdir, "t1-0")["deleted"] is True
    assert node_state(gdir, "t1-1")["deleted"] is True


def test_multi_node_requires_queued_mode(gdir):
    with pytest.raises(ConfError, match="requires"):
        make_prov(gdir, node_count=2)


def test_provisioner_from_conf_multislice():
    conf = TonyConf()
    conf.set("tony.provisioner.mode", "queued")
    conf.set("tony.provisioner.accelerator-type", "v5p-8")
    conf.set("tony.tpu.num-slices", 3)
    prov = provisioner_from_conf(conf, "app_x")
    assert isinstance(prov, TpuVmProvisioner)
    assert prov.node_count == 3 and prov.queued
