#!/usr/bin/env python3
"""In-tree lint gate (reference parity: build.gradle:113-116 wires
Checkstyle + FindBugs into every build; this is the Python analog).

The TPU image bakes no linter and installs are forbidden, so the gate is
a fast AST/text checker covering the high-signal rules; `ruff.toml` at
the repo root configures the same rules for CI environments that do have
ruff (.github/workflows/ci.yml runs it when available and falls back to
this script otherwise).

Checks:
  - the file parses (syntax gate)
  - line length <= 99 (repo style is ~79 soft, 99 hard)
  - no trailing whitespace, no tab indentation
  - no bare `except:`
  - no mutable default arguments (list/dict/set displays)
  - unused module-level imports (skipped in __init__.py re-export files
    and for names listed in __all__ or marked `# noqa`)
  - imports positioned after code (E402-lite: only docstring, comments,
    `from __future__`, and simple assignments may precede imports;
    function-local imports are exempt — the repo uses them deliberately
    for lazy heavy deps)

Exit code 0 = clean; 1 = findings (printed one per line, file:line).
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

MAX_LEN = 99
SKIP_DIRS = {".git", "__pycache__", ".claude", "native"}


def iter_py(root: Path):
    for p in sorted(root.rglob("*.py")):
        if not any(part in SKIP_DIRS for part in p.parts):
            yield p


def check_text(path: Path, src: str, out: list[str]):
    for i, line in enumerate(src.splitlines(), 1):
        if len(line) > MAX_LEN:
            out.append(f"{path}:{i} line too long ({len(line)} > {MAX_LEN})")
        if line != line.rstrip() and line.strip():
            out.append(f"{path}:{i} trailing whitespace")
        stripped = line.lstrip(" ")
        if stripped.startswith("\t") or line.startswith("\t"):
            out.append(f"{path}:{i} tab indentation")


class _Visitor(ast.NodeVisitor):
    def __init__(self, path: Path, src_lines: list[str], out: list[str]):
        self.path, self.lines, self.out = path, src_lines, out

    def _noqa(self, node) -> bool:
        line = self.lines[node.lineno - 1] if node.lineno <= len(self.lines) \
            else ""
        return "# noqa" in line

    def visit_ExceptHandler(self, node):
        if node.type is None and not self._noqa(node):
            self.out.append(f"{self.path}:{node.lineno} bare except")
        self.generic_visit(node)

    def _check_defaults(self, node):
        for d in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None]:
            if isinstance(d, (ast.List, ast.Dict, ast.Set)) \
                    and not self._noqa(d):
                self.out.append(
                    f"{self.path}:{d.lineno} mutable default argument")

    def visit_FunctionDef(self, node):
        self._check_defaults(node)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node):
        self._check_defaults(node)
        self.generic_visit(node)


def _imported_names(node) -> list[tuple[str, int]]:
    if isinstance(node, ast.ImportFrom) and node.module == "__future__":
        return []  # compiler directive, not a binding anyone must use
    names = []
    for alias in node.names:
        name = alias.asname or alias.name.split(".")[0]
        if name != "*":
            names.append((name, node.lineno))
    return names


def check_unused_imports(path: Path, tree: ast.Module, src: str,
                         out: list[str]):
    if path.name == "__init__.py":  # re-export files
        return
    lines = src.splitlines()
    imported: dict[str, int] = {}
    for node in tree.body:  # module level only
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            for name, lineno in _imported_names(node):
                if "# noqa" not in (lines[lineno - 1]
                                    if lineno <= len(lines) else ""):
                    imported[name] = lineno
    if not imported:
        return
    used: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            pass  # the base Name node is visited separately
    # names in __all__ strings count as used (re-exports)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__" \
                        and isinstance(node.value, (ast.List, ast.Tuple)):
                    for elt in node.value.elts:
                        if isinstance(elt, ast.Constant):
                            used.add(str(elt.value))
    for name, lineno in imported.items():
        if name not in used:
            out.append(f"{path}:{lineno} unused import '{name}'")


def check_import_position(path: Path, tree: ast.Module, src: str,
                          out: list[str]):
    lines = src.splitlines()
    seen_code = False
    for node in tree.body:
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            if seen_code:
                line = lines[node.lineno - 1] \
                    if node.lineno <= len(lines) else ""
                if "# noqa" not in line:
                    out.append(f"{path}:{node.lineno} import after "
                               f"module-level code (E402)")
        elif isinstance(node, ast.Expr):
            # docstrings AND expression-statement calls: the canonical
            # jax pattern sets os.environ / jax.config BEFORE importing
            # the heavy modules — that must not force a noqa
            continue
        elif isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue  # simple module constants before lazy imports are ok
        elif isinstance(node, ast.If):
            continue  # TYPE_CHECKING / platform guards
        elif isinstance(node, ast.Try):
            continue  # optional-dependency guards
        else:
            seen_code = True


def main(argv=None) -> int:
    roots = [Path(a) for a in (argv or sys.argv[1:])] or [Path(".")]
    findings: list[str] = []
    n = 0
    for root in roots:
        files = [root] if root.is_file() else list(iter_py(root))
        for path in files:
            n += 1
            src = path.read_text(encoding="utf-8")
            try:
                tree = ast.parse(src, filename=str(path))
            except SyntaxError as e:
                findings.append(f"{path}:{e.lineno} syntax error: {e.msg}")
                continue
            check_text(path, src, findings)
            _Visitor(path, src.splitlines(), findings).visit(tree)
            check_unused_imports(path, tree, src, findings)
            check_import_position(path, tree, src, findings)
    for f in findings:
        print(f)
    print(f"lint: {n} files, {len(findings)} findings",
          file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
