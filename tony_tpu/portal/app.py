"""Job-history web portal.

Reference: tony-portal (Play Framework app, 1216 LoC Java + Scala templates):
jobs-metadata index, per-job config/events/logs pages, caches, and the
background history mover/purger. Rebuilt on the stdlib http.server (no Play
in the image) with the same four pages:

  /                     jobs index (ref: conf/routes:1 JobsMetadataPageController)
  /job/<id>/config      merged conf   (ref: JobConfigPageController)
  /job/<id>/events      event log     (ref: JobEventsPageController)
  /job/<id>/logs        task log list (ref: JobLogsPageController)

plus JSON twins under /api/... for tooling.

Entry: ``python -m tony_tpu.portal --history <dir> [--port N]``.
"""

from __future__ import annotations

import argparse
import html
import json
import logging
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from tony_tpu.events import history
from tony_tpu.events.mover import move_finished_jobs, purge_old_history

log = logging.getLogger(__name__)

_PAGE = """<!doctype html><html><head><title>tony-tpu history</title>
<style>
body {{ font-family: monospace; margin: 2em; }}
table {{ border-collapse: collapse; }}
td, th {{ border: 1px solid #999; padding: 4px 10px; text-align: left; }}
th {{ background: #eee; }}
.SUCCEEDED {{ color: green; }} .FAILED {{ color: red; }} .RUNNING {{ color: orange; }}
</style></head><body><h2>{title}</h2>{body}</body></html>"""


class PortalState:
    """Cached history scan (ref: cache/CacheWrapper.java Guava caches).

    ``max_jobs`` caps what one scan keeps in memory (newest first — the
    reference's Guava cache is bounded the same way); older jobs stay on
    disk and age out via the purger."""

    def __init__(self, history_root: str, ttl_s: float = 5.0,
                 max_jobs: int = 2000):
        self.history_root = history_root
        self.ttl_s = ttl_s
        self.max_jobs = max_jobs
        self._jobs: list[dict] = []
        self._scanned = 0.0
        self._lock = threading.Lock()

    def jobs(self) -> list[dict]:
        with self._lock:
            if time.monotonic() - self._scanned > self.ttl_s:
                self._jobs = history.list_jobs(
                    self.history_root)[:self.max_jobs]
                self._scanned = time.monotonic()
            return list(self._jobs)

    def find(self, app_id: str) -> dict | None:
        for j in self.jobs():
            if j["app_id"] == app_id:
                return j
        return None


class PortalHandler(BaseHTTPRequestHandler):
    state: PortalState  # set by serve()
    token: str = ""  # non-empty -> bearer/query-token auth required

    def log_message(self, fmt, *args):  # quiet
        log.debug(fmt, *args)

    def do_GET(self):
        try:
            self._route()
        except Exception as e:
            log.exception("portal error")
            self._send(500, f"internal error: {e}", "text/plain")

    _qtok = ""  # query-token of the current request, echoed into links

    def _href(self, path: str, *extra: str) -> str:
        qs = [e for e in extra if e]
        if self._qtok:
            from urllib.parse import quote

            qs.append("token=" + quote(self._qtok))
        return path + ("?" + "&".join(qs) if qs else "")

    def _authorized(self, params: dict) -> bool:
        """Optional shared-token gate (the kerberos+HTTPS slot of
        tony-portal, app/hadoop/Configuration.java, scaled to the
        stdlib server: header ``Authorization: Bearer <t>`` or ``?token=``
        for browser use)."""
        import hmac

        if not self.token:
            return True
        header = self.headers.get("Authorization", "")
        cand = header[7:] if header.startswith("Bearer ") else \
            (params.get("token") or [""])[0]
        return hmac.compare_digest(cand, self.token)

    def _route(self):
        from urllib.parse import parse_qs

        path, _, query = self.path.partition("?")
        params = parse_qs(query)
        if not self._authorized(params):
            return self._send(401, "unauthorized (token required)",
                              "text/plain")
        # browsers authenticate via ?token=; every rendered link must
        # carry it forward or the next click lands on a 401
        self._qtok = (params.get("token") or [""])[0] if self.token else ""
        parts = [p for p in path.split("/") if p]
        api = bool(parts) and parts[0] == "api"
        if api:
            parts = parts[1:]
        if not parts:
            return self._jobs_index(api, params)
        if parts[0] == "job" and len(parts) >= 3:
            app_id, page = parts[1], parts[2]
            job = self.state.find(app_id)
            if job is None:
                return self._send(404, "no such job", "text/plain")
            if page == "config":
                return self._job_config(job, api)
            if page == "events":
                return self._job_events(job, api)
            if page == "logs":
                return self._job_logs(job, api)
            if page == "metrics":
                return self._job_metrics(job, api)
        return self._send(404, "not found", "text/plain")

    # -- pages --------------------------------------------------------------
    def _jobs_index(self, api: bool, params: dict | None = None):
        """Paginated index: ?page=N (1-based) & per=N (default 200, max
        2000). The API keeps its bare-list shape, sliced the same way."""
        params = params or {}

        def _qint(key: str, default: int, lo: int, hi: int) -> int:
            try:
                return min(max(int((params.get(key) or [default])[0]), lo), hi)
            except ValueError:
                return default

        per = _qint("per", 200, 1, 2000)
        page = _qint("page", 1, 1, 10 ** 9)
        all_jobs = self.state.jobs()
        jobs = all_jobs[(page - 1) * per:page * per]
        if api:
            return self._send(200, json.dumps(jobs), "application/json")
        def _row(j):
            # aid hoisted out of the nested f-strings: quoting a dict key
            # inside a same-quoted inner f-string needs python >= 3.12
            aid = j["app_id"]
            return (
                f"<tr><td><a href='{self._href(f'/job/{aid}/config')}'>"
                f"{aid}</a></td>"
                f"<td class='{j['status']}'>{j['status']}</td>"
                f"<td>{j['user'] or '-'}</td>"
                f"<td>{_ts(j['started'])}</td><td>{_ts(j['completed'])}</td>"
                f"<td><a href='{self._href(f'/job/{aid}/events')}'>events</a> "
                f"<a href='{self._href(f'/job/{aid}/logs')}'>logs</a> "
                f"<a href='{self._href(f'/job/{aid}/metrics')}'>metrics</a>"
                f"</td></tr>")

        rows = "".join(_row(j) for j in jobs)
        nav = []
        if page > 1:
            nav.append(f"<a href='{self._href('/', f'page={page - 1}', f'per={per}')}'"
                       f">&larr; newer</a>")
        if page * per < len(all_jobs):
            nav.append(f"<a href='{self._href('/', f'page={page + 1}', f'per={per}')}'"
                       f">older &rarr;</a>")
        body = (f"<table><tr><th>application</th><th>status</th><th>user</th>"
                f"<th>started</th><th>completed</th><th>links</th></tr>{rows}</table>"
                f"<p>{len(all_jobs)} jobs cached &middot; page {page} "
                f"&middot; {' '.join(nav)}</p>")
        self._send(200, _PAGE.format(title="tony-tpu job history", body=body))

    def _job_config(self, job: dict, api: bool):
        conf = history.parse_config(job["dir"]) or {}
        if api:
            return self._send(200, json.dumps(conf), "application/json")
        rows = "".join(
            f"<tr><td>{html.escape(str(k))}</td><td>{html.escape(str(v))}</td></tr>"
            for k, v in sorted(conf.items()))
        body = (f"<p><a href='{self._href('/')}'>&larr; jobs</a></p>"
                f"<table>{rows}</table>")
        self._send(200, _PAGE.format(title=f"{job['app_id']} config", body=body))

    def _job_events(self, job: dict, api: bool):
        events = [e.to_dict() for e in history.parse_events(job["jhist"])]
        if api:
            return self._send(200, json.dumps(events), "application/json")
        rows = "".join(
            f"<tr><td>{_ts(e['timestamp'])}</td><td>{e['type']}</td>"
            f"<td>{html.escape(json.dumps(e['event']))}</td></tr>" for e in events)
        body = (f"<p><a href='{self._href('/')}'>&larr; jobs</a></p>"
                f"<table>{rows}</table>")
        self._send(200, _PAGE.format(title=f"{job['app_id']} events", body=body))

    def _job_logs(self, job: dict, api: bool):
        """Task log files staged alongside history (ref: JobLogPageController
        links out to YARN log URLs; here logs are local files)."""
        logs_dir = os.path.join(os.path.dirname(job["dir"]), "..", "..")
        found = []
        for j in (job["dir"], os.path.join(job["dir"], "logs")):
            if os.path.isdir(j):
                for f in sorted(os.listdir(j)):
                    if f.endswith(".log"):
                        found.append(os.path.join(j, f))
        if api:
            return self._send(200, json.dumps(found), "application/json")
        items = "".join(f"<li>{html.escape(p)}</li>" for p in found) or "<li>none</li>"
        body = (f"<p><a href='{self._href('/')}'>&larr; jobs</a></p>"
                f"<ul>{items}</ul>")
        self._send(200, _PAGE.format(title=f"{job['app_id']} logs", body=body))

    def _job_metrics(self, job: dict, api: bool):
        """Training metrics archived by the coordinator from train.fit's
        jsonl sinks (<history job dir>/metrics/*.jsonl). Beyond-reference:
        tony-portal serves only events/config/logs."""
        import collections

        mdir = os.path.join(job["dir"], "metrics")
        # stream with a bounded tail: metric files grow with run length and
        # are re-read per request (no reason to hold 10^5 rows for a page
        # that shows 200); non-dict JSON lines are skipped, any task can
        # write into metrics/ so the content is untrusted
        keep = 2000 if api else 200
        series: dict[str, list[dict]] = {}
        if os.path.isdir(mdir):
            for name in sorted(os.listdir(mdir)):
                if not name.endswith(".jsonl"):
                    continue
                rows: collections.deque = collections.deque(maxlen=keep)
                # errors="replace": one bad byte must not 500 the page
                # (the mangled line is then dropped by the JSON guard)
                with open(os.path.join(mdir, name), errors="replace") as f:
                    for line in f:
                        if line.strip():
                            try:
                                row = json.loads(line)
                            except json.JSONDecodeError:
                                continue
                            if isinstance(row, dict):
                                rows.append(_finite(row))
                series[name[:-len(".jsonl")]] = list(rows)
        if api:
            # NaN/Infinity already nulled by _finite: bare NaN tokens are
            # not JSON and break strict parsers (browsers, jq)
            return self._send(200, json.dumps(series), "application/json")
        sections = []
        for name, rows in series.items():
            cols = sorted({k for r in rows for k in r})
            head = "".join(f"<th>{html.escape(c)}</th>" for c in cols)
            body_rows = "".join(
                "<tr>" + "".join(
                    f"<td>{html.escape(str(r.get(c, '')))}</td>"
                    for c in cols) + "</tr>"
                for r in rows)
            sections.append(f"<h3>{html.escape(name)}</h3>"
                            f"<table><tr>{head}</tr>{body_rows}</table>")
        body = (f"<p><a href='{self._href('/')}'>&larr; jobs</a></p>"
                + ("".join(sections) or "<p>no metrics recorded</p>"))
        self._send(200, _PAGE.format(title=f"{job['app_id']} metrics",
                                     body=body))

    def _send(self, code: int, body: str, ctype: str = "text/html"):
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)


def _finite(row: dict) -> dict:
    """Replace non-finite floats (a diverged run logs NaN loss) with None —
    json.dumps would otherwise emit bare NaN, which is not valid JSON."""
    import math

    return {k: (None if isinstance(v, float) and not math.isfinite(v) else v)
            for k, v in row.items()}


def _ts(ms: int) -> str:
    if ms is None or ms < 0:
        return "-"
    return time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(ms / 1000))


class Portal:
    def __init__(self, history_root: str, port: int = 0, host: str = "127.0.0.1",
                 mover_interval_ms: int = 300_000, retention_sec: int = 2_592_000,
                 token: str = "", max_jobs: int = 2000,
                 tls_cert: str = "", tls_key: str = ""):
        self.state = PortalState(history_root, max_jobs=max_jobs)
        handler = type("BoundHandler", (PortalHandler,),
                       {"state": self.state, "token": token})
        self.server = ThreadingHTTPServer((host, port), handler)
        self.tls = bool(tls_cert and tls_key)
        if self.tls:
            # same transport story as the control plane (rpc/tls.py): a
            # self-signed per-deployment cert, clients pin its SHA-256
            # fingerprint (the HTTPS+keystore slot of tony-portal,
            # app/hadoop/Requirements.java / portal keystore conf)
            from tony_tpu.rpc.tls import server_context

            # handshake DEFERRED to the per-request thread: with the
            # default handshake-on-accept, one client that connects and
            # stalls (plain-http probe, TCP health check) would park the
            # single accept loop and freeze the whole portal
            self.server.socket = server_context(tls_cert, tls_key) \
                .wrap_socket(self.server.socket, server_side=True,
                             do_handshake_on_connect=False)
        self.host, self.port = self.server.server_address[:2]
        self.mover_interval_s = mover_interval_ms / 1000
        self.retention_sec = retention_sec
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    def start(self) -> "Portal":
        t = threading.Thread(target=self.server.serve_forever, name="portal",
                             daemon=True)
        t.start()
        m = threading.Thread(target=self._housekeeping, name="history-mover",
                             daemon=True)
        m.start()
        self._threads = [t, m]
        log.info("portal at %s://%s:%d",
                 "https" if self.tls else "http", self.host, self.port)
        return self

    def _housekeeping(self) -> None:
        """Ref: HistoryFileMover + HistoryFilePurger background loops."""
        while not self._stop.wait(self.mover_interval_s):
            try:
                move_finished_jobs(self.state.history_root)
                purge_old_history(self.state.history_root, self.retention_sec)
            except Exception:
                log.exception("history housekeeping failed")

    def stop(self) -> None:
        self._stop.set()
        self.server.shutdown()
        self.server.server_close()


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="tony-tpu portal")
    parser.add_argument("--history", required=True)
    parser.add_argument("--port", type=int, default=19885)
    # loopback by default: exposing the portal beyond the host is an
    # explicit opt-in (pair --host 0.0.0.0 with --token)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--token", default=os.environ.get(
        "TONY_PORTAL_TOKEN", ""),
        help="require Authorization: Bearer <token> (or ?token=) on every "
             "request; defaults to $TONY_PORTAL_TOKEN")
    parser.add_argument("--max-jobs", type=int, default=2000,
                        help="cap on history entries held in memory")
    parser.add_argument("--tls-cert", default="",
                        help="serve HTTPS with this certificate (pair with "
                             "--tls-key)")
    parser.add_argument("--tls-key", default="")
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    tls_cert, tls_key = args.tls_cert, args.tls_key
    if args.host not in ("127.0.0.1", "localhost", "::1") and not tls_cert:
        # non-loopback without a cert: mint one rather than serving the
        # history in cleartext off-host; clients pin the printed digest
        from tony_tpu.rpc.tls import cert_fingerprint, mint_self_signed

        tls_cert, tls_key = mint_self_signed(
            os.path.join(args.history, ".portal-tls"), "tony-portal")
        print(f"minted portal TLS cert; pin fingerprint "
              f"{cert_fingerprint(tls_cert)}")
    portal = Portal(args.history, port=args.port, host=args.host,
                    token=args.token, max_jobs=args.max_jobs,
                    tls_cert=tls_cert, tls_key=tls_key).start()
    scheme = "https" if portal.tls else "http"
    print(f"tony-tpu portal at {scheme}://{portal.host}:{portal.port}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        portal.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
