"""Serving front door over N ``serve.Server`` replicas.

``core`` is the admission/routing/drain machinery (pure Python, no
sockets — unit-testable); ``admission`` the weighted-fair-queuing
tiers + tenant quotas; ``autoscale`` the elastic control loop driving
``Gateway.add_replica``/``remove_replica``; ``remote`` the
remote-replica stub (serve ON provisioned hosts: a replica agent per
host, lease heartbeats, epoch fencing, resumable streams); ``http``
the stdlib thread-per-connection network face; ``edge`` the
event-driven selector front end (one loop thread + a small worker
pool holds tens of thousands of concurrent streams). The CLI entrypoint is ``python -m
tony_tpu.cli.gateway``; ``tony-tpu generate --serve`` drives the same
core over stdin/stdout JSONL; ``python -m tony_tpu.cli.replica`` is
the per-host agent.
"""

from tony_tpu.gateway.admission import (DEFAULT_TIER, DEFAULT_TIER_WEIGHTS,
                                        TenantQuotas, WFQueue,
                                        parse_tier_weights)
from tony_tpu.gateway.autoscale import (AutoScaler, ProvisionerBackend,
                                        ScaleError, ThreadBackend)
from tony_tpu.gateway.core import (BadRequest, DeadlineExceeded, Gateway,
                                   GatewayClosed, GatewayHistory,
                                   GatewayQueueFull, GenRequest,
                                   NoHealthyReplicas, QuotaExceeded,
                                   RetryBudgetExhausted, Shed, Ticket)
from tony_tpu.gateway.edge import GatewayEdge
from tony_tpu.gateway.http import GatewayHTTP
from tony_tpu.gateway.rebalance import Rebalancer
from tony_tpu.gateway.remote import (AgentHTTPError, AgentTransport,
                                     RemoteServer, launch_local_agent)

__all__ = [
    "AgentHTTPError",
    "AgentTransport",
    "AutoScaler",
    "BadRequest",
    "DEFAULT_TIER",
    "DEFAULT_TIER_WEIGHTS",
    "DeadlineExceeded",
    "Gateway",
    "GatewayClosed",
    "GatewayEdge",
    "GatewayHTTP",
    "GatewayHistory",
    "GatewayQueueFull",
    "GenRequest",
    "NoHealthyReplicas",
    "ProvisionerBackend",
    "QuotaExceeded",
    "Rebalancer",
    "RemoteServer",
    "RetryBudgetExhausted",
    "ScaleError",
    "Shed",
    "TenantQuotas",
    "ThreadBackend",
    "Ticket",
    "WFQueue",
    "launch_local_agent",
    "parse_tier_weights",
]
