"""RPC layer tests: framing, auth, dispatch, reconnect, concurrency."""

import threading

import pytest

from tony_tpu.rpc import RpcClient, RpcError, RpcServer
from tony_tpu.rpc import wire


class Handler:
    def __init__(self):
        self.lock = threading.Lock()
        self.counter = 0

    def echo(self, value):
        return value

    def add(self, a, b):
        return a + b

    def boom(self):
        raise ValueError("kaput")

    def bump(self):
        with self.lock:
            self.counter += 1
            return self.counter

    def _private(self):
        return "secret"


@pytest.fixture
def server():
    s = RpcServer(Handler(), secret="tok").start()
    yield s
    s.stop()


def test_basic_call(server):
    c = RpcClient(server.host, server.port, secret="tok")
    assert c.call("echo", value={"a": [1, 2]}) == {"a": [1, 2]}
    assert c.call("add", a=2, b=3) == 5
    c.close()


def test_handler_exception_returns_error(server):
    c = RpcClient(server.host, server.port, secret="tok")
    with pytest.raises(RpcError, match="kaput"):
        c.call("boom")
    # connection still usable afterwards
    assert c.call("add", a=1, b=1) == 2
    c.close()


def test_unknown_and_private_methods(server):
    c = RpcClient(server.host, server.port, secret="tok")
    with pytest.raises(RpcError, match="unknown method"):
        c.call("nope")
    with pytest.raises(RpcError, match="unknown method"):
        c.call("_private")
    c.close()


def test_bad_token_rejected(server):
    c = RpcClient(server.host, server.port, secret="WRONG")
    with pytest.raises(RpcError, match="authentication failed"):
        c.call("add", a=1, b=2)
    c.close()


def test_missing_token_rejected(server):
    c = RpcClient(server.host, server.port, secret=None)
    with pytest.raises(RpcError, match="authentication failed"):
        c.call("add", a=1, b=2)
    c.close()


def test_no_auth_server():
    s = RpcServer(Handler()).start()
    try:
        c = RpcClient(s.host, s.port)
        assert c.call("add", a=1, b=1) == 2
        c.close()
    finally:
        s.stop()


def test_concurrent_clients(server):
    results = []

    def work():
        c = RpcClient(server.host, server.port, secret="tok")
        for _ in range(10):
            results.append(c.call("bump"))
        c.close()

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sorted(results) == list(range(1, 41))


def test_reconnect_after_server_restart():
    handler = Handler()
    s = RpcServer(handler, secret="tok").start()
    c = RpcClient(s.host, s.port, secret="tok")
    assert c.call("add", a=1, b=1) == 2
    port = s.port
    s.stop()
    s2 = RpcServer(handler, port=port, secret="tok").start()
    try:
        assert c.call("add", a=2, b=2, retries=5) == 4
    finally:
        c.close()
        s2.stop()


def test_sign_verify_tamper():
    sig = wire.sign("sec", "m", {"a": 1})
    assert wire.verify("sec", "m", {"a": 1}, sig)
    assert not wire.verify("sec", "m", {"a": 2}, sig)  # tampered params
    assert not wire.verify("sec", "m2", {"a": 1}, sig)  # tampered method
    assert not wire.verify("other", "m", {"a": 1}, sig)


def test_poll_till_non_null():
    vals = iter([None, None, "ready"])
    c = RpcClient("localhost", 1)
    assert c.poll_till_non_null(lambda: next(vals), interval_s=0.01) == "ready"
    with pytest.raises(TimeoutError):
        c.poll_till_non_null(lambda: None, interval_s=0.01, timeout_s=0.05)


# -- TLS (the transport-security half of ClientToAM; rpc/tls.py) -------------


class _EchoTls:
    def echo(self, value):
        return value


def test_tls_mint_fingerprint_and_roundtrip(tmp_path):
    from tony_tpu.rpc import RpcServer
    from tony_tpu.rpc.tls import cert_fingerprint, mint_self_signed

    cert, key = mint_self_signed(str(tmp_path), "tony-test")
    # idempotent: second mint returns the same files
    assert mint_self_signed(str(tmp_path), "tony-test") == (cert, key)
    fp = cert_fingerprint(cert)
    assert len(fp) == 64

    server = RpcServer(_EchoTls(), secret="s3", tls=(cert, key)).start()
    try:
        c = RpcClient("127.0.0.1", server.port, secret="s3",
                      tls_fingerprint=fp)
        assert c.call("echo", value=41) == 41
        c.close()
        # wrong pin: refused before any frame flows
        bad = RpcClient("127.0.0.1", server.port, secret="s3",
                        tls_fingerprint="0" * 64, timeout=5)
        with pytest.raises(ConnectionError):
            bad.call("echo", retries=0, value=1)
        bad.close()
        # plaintext client against the TLS server: dropped at handshake
        plain = RpcClient("127.0.0.1", server.port, secret="s3", timeout=5)
        with pytest.raises(ConnectionError):
            plain.call("echo", retries=0, value=1)
        plain.close()
    finally:
        server.stop()


def test_tls_e2e_job(tmp_path):
    """Full gang under HMAC + TLS: client mints at staging, coordinator
    serves, agents pin from env."""
    import os

    from tony_tpu.mini import MiniTonyCluster, script_conf

    scripts = os.path.join(os.path.dirname(__file__), "scripts")
    with MiniTonyCluster() as cluster:
        conf = script_conf(cluster, os.path.join(scripts, "check_env.py"),
                           {"worker": 2})
        conf.set("tony.application.security.enabled", True)
        conf.set("tony.application.security.tls", True)
        client = cluster.submit(conf)
        assert client.final_status["status"] == "SUCCEEDED", \
            client.final_status
        assert os.path.exists(os.path.join(client.job_dir, "tls-cert.pem"))
