"""Serving front door over N ``serve.Server`` replicas.

``core`` is the admission/routing/drain machinery (pure Python, no
sockets — unit-testable); ``http`` is the stdlib network face. The CLI
entrypoint is ``python -m tony_tpu.cli.gateway``; ``tony-tpu generate
--serve`` drives the same core over stdin/stdout JSONL.
"""

from tony_tpu.gateway.core import (BadRequest, DeadlineExceeded, Gateway,
                                   GatewayClosed, GatewayHistory,
                                   GatewayQueueFull, GenRequest,
                                   NoHealthyReplicas, RetryBudgetExhausted,
                                   Shed, Ticket)
from tony_tpu.gateway.http import GatewayHTTP

__all__ = [
    "BadRequest",
    "DeadlineExceeded",
    "Gateway",
    "GatewayClosed",
    "GatewayHTTP",
    "GatewayHistory",
    "GatewayQueueFull",
    "GenRequest",
    "NoHealthyReplicas",
    "RetryBudgetExhausted",
    "Shed",
    "Ticket",
]
