"""Runtime registry — ServiceLoader equivalent.

Reference: FrameworkRuntimeProvider.java:29-61 resolves the configured
framework type to a runtime via Java ServiceLoader
(META-INF/services/...AbstractFrameworkRuntime). Here it's an explicit
registry plus ``register()`` for out-of-tree runtimes.
"""

from __future__ import annotations

from tony_tpu.runtime.base import AMAdapter, Runtime, TaskAdapter
from tony_tpu.runtime.horovod_runtime import HorovodRuntime
from tony_tpu.runtime.jax_runtime import JaxRuntime
from tony_tpu.runtime.mxnet_runtime import MXNetRuntime
from tony_tpu.runtime.pytorch_runtime import PyTorchRuntime
from tony_tpu.runtime.ray_runtime import RayRuntime
from tony_tpu.runtime.standalone import StandaloneRuntime
from tony_tpu.runtime.tf_runtime import TFRuntime

_REGISTRY: dict[str, type[Runtime]] = {}


def register(runtime_cls: type[Runtime]) -> type[Runtime]:
    _REGISTRY[runtime_cls.name] = runtime_cls
    return runtime_cls


for _rt in (JaxRuntime, TFRuntime, PyTorchRuntime, MXNetRuntime,
            HorovodRuntime, StandaloneRuntime, RayRuntime):
    register(_rt)


def get_runtime(framework: str) -> type[Runtime]:
    try:
        return _REGISTRY[framework.lower()]
    except KeyError:
        raise ValueError(
            f"unknown framework {framework!r}; known: {sorted(_REGISTRY)}"
        ) from None


def get_am_adapter(framework: str) -> AMAdapter:
    """Ref: FrameworkRuntimeProvider.getAMAdapter :53."""
    return get_runtime(framework).get_am_adapter()


def get_task_adapter(framework: str) -> TaskAdapter:
    """Ref: FrameworkRuntimeProvider.getTaskAdapter :61."""
    return get_runtime(framework).get_task_adapter()
