from tony_tpu.config.config import ConfError, TonyConf, build_conf, role_key
from tony_tpu.config import keys

__all__ = ["TonyConf", "ConfError", "build_conf", "role_key", "keys"]
