from tony_tpu.coordinator.coordinator import ClientRpcHandler, Coordinator
from tony_tpu.coordinator.launcher import (
    Launcher,
    LocalProcessLauncher,
    SshLauncher,
)
from tony_tpu.coordinator.liveness import LivenessMonitor

__all__ = [
    "ClientRpcHandler",
    "Coordinator",
    "Launcher",
    "LivenessMonitor",
    "LocalProcessLauncher",
    "SshLauncher",
]
