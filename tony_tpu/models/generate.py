"""Autoregressive generation: prefill + KV-cache decode under one jit.

No reference analog (TonY orchestrates training jobs; inference is out of
scope there) — this is framework surface the TPU rebuild adds so the
flagship transformer is usable end-to-end. TPU-first design:

- the KV cache is a static [b, max_seq_len, kv_heads, dh] buffer per layer
  (Attention._decode_attention; GQA caches only n_kv_heads), so prefill and
  every decode step compile once each — no dynamic shapes, no recompiles
- the decode loop is a single lax.scan over max_new_tokens: one XLA
  program, device-resident carry (cache + last token + rng), zero
  host<->device traffic until the final token block comes back
- sampling (greedy / temperature / top-k) is branchless inside the scan
- under a Mesh the cache shards like activations (batch on "data", heads
  on "tensor"), so tensor-parallel decode works unchanged via jit+sharding
- serve with ``scan_layers=False`` (the checkpoint-import default):
  scanned layers stack the caches [n_layers, ...] and every token then
  pays a full per-layer-cache dynamic-slice/update-slice round trip —
  measured 2.1x slower decode at d768x12L (docs/PERF.md). scan_layers
  is a TRAINING compile-time optimization, not a serving one.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp


def init_cache(model, params, batch_size: int, dtype=None) -> Any:
    """Allocate the per-layer KV cache sized by cfg.max_seq_len."""
    cfg = model.cfg
    tokens = jnp.zeros((batch_size, cfg.max_seq_len), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tokens, decode=True)
    return variables["cache"]


def sample_logits(logits, rng, temperature, top_k: int, top_p: float = 1.0):
    """Greedy when temperature==0, else softmax sampling with optional
    top-k and top-p (nucleus) cuts. ``temperature`` is a traced operand —
    changing it per call (a serving loop sweeping 0.7, 0.8, ...) never
    recompiles; the greedy case rides the same program via a where.
    ``top_k`` and ``top_p`` are static: they change the compiled program
    (top_k sets the sort slice; top_p=1.0 skips the nucleus sorts entirely
    so the default decode hot path pays zero extra work), recompiling once
    per distinct value."""
    scaled = logits / jnp.maximum(jnp.asarray(temperature, logits.dtype), 1e-6)
    if top_k > 0:
        kth = jnp.sort(scaled, axis=-1)[..., -top_k][..., None]
        scaled = jnp.where(scaled < kth, -1e30, scaled)
    if top_p < 1.0:
        # nucleus cut: drop tokens outside the smallest probability mass
        # >= p. One descending sort + cumsum; a token survives if the mass
        # strictly before it is < p; the top token always survives (so
        # top_p<=0 degrades to top-1 sampling, not uniform noise).
        order = jnp.argsort(-scaled, axis=-1)
        sorted_probs = jax.nn.softmax(
            jnp.take_along_axis(scaled, order, axis=-1).astype(jnp.float32),
            axis=-1)
        mass_before = jnp.cumsum(sorted_probs, axis=-1) - sorted_probs
        keep_sorted = (mass_before < top_p).at[..., 0].set(True)
        # scatter the mask back to vocab order via the inverse permutation
        keep = jnp.take_along_axis(
            keep_sorted, jnp.argsort(order, axis=-1), axis=-1)
        scaled = jnp.where(keep, scaled, -1e30)
    sampled = jax.random.categorical(rng, scaled, axis=-1)
    greedy = jnp.argmax(logits, axis=-1)
    return jnp.where(jnp.asarray(temperature) == 0.0, greedy, sampled)


def _penalize_repeats(logits, seen, penalty):
    """CTRL-style repetition penalty: a token already in the sequence has
    its logit divided by ``penalty`` when positive, multiplied when
    negative (both push probability down for penalty > 1). Traced operand:
    penalty=1.0 rides the same compiled program as a no-op."""
    penalty = jnp.asarray(penalty, logits.dtype)
    penalized = jnp.where(logits > 0, logits / penalty, logits * penalty)
    return jnp.where(seen, penalized, logits)


def normalize_eos_ids(eos_id) -> tuple:
    """Normalize eos_id to a tuple of valid ids: int (-1/None = none) or
    a tuple/list of ids (HF configs ship lists — Llama-3 instruct:
    [128001, 128009]); negatives are dropped. Runs OUTSIDE jit (the >= 0
    filter inspects values), in the public generate/beam_search wrappers —
    both decoders see identical semantics for every input shape."""
    if isinstance(eos_id, (list, tuple)):
        return tuple(int(e) for e in eos_id if int(e) >= 0)
    return (int(eos_id),) if eos_id is not None and int(eos_id) >= 0 else ()


def _is_eos(tok, eos_ids):
    """True where ``tok`` equals ANY of the eos ids (stop on any; a
    generation must not run past end-of-turn just because it isn't the
    first listed id)."""
    if not eos_ids:
        return jnp.zeros(tok.shape, bool)
    hit = tok == eos_ids[0]
    for e in eos_ids[1:]:
        hit = hit | (tok == e)
    return hit


def single_decode_step(model, params, cache, tok, positions=None,
                       page_table=None):
    """ONE token step through the KV cache: feed ``tok`` [b] at the
    current position(s), return ``(new_cache, last_logits [b, V])``.

    The shared decode body of ``_generate``'s scan and the serving
    loop's resident step (serve/engine.py): the scalar-index path
    (``positions=None``, all rows in lockstep) and the per-slot path
    (``positions`` [b], every row at its own cache position — negative
    marks an empty slot) run the same model.apply; only the position
    bookkeeping differs (Attention._decode_attention). ``page_table``
    [b, max_pages] switches the per-slot path to the paged cache
    layout (serve/slots.PagePool — ``cache`` holds page pools instead
    of per-slot rows; same attention reduction over the gathered
    view)."""
    kwargs = {} if positions is None else {"positions": positions}
    if page_table is not None:
        kwargs["page_table"] = page_table
    logits, vars_ = model.apply({"params": params, "cache": cache},
                                tok[:, None], decode=True,
                                mutable=["cache"], **kwargs)
    return vars_["cache"], logits[:, -1]


def multi_decode_step(model, params, cache, toks, positions,
                      page_table=None):
    """A ``k``-token per-slot window through the KV cache in ONE apply:
    feed ``toks`` [b, k] with every row at its own positions [b, k],
    return ``(new_cache, logits [b, k, V])`` — the logits AFTER each
    window token, i.e. logits[:, j] scores the token following
    ``toks[:, j]``.

    The speculative-decoding verify body (serve/engine._verify_chunk):
    ``single_decode_step`` scores one position per dispatch; this
    scores the whole draft window in one compute-dense batched pass —
    the Leviathan et al. trade of sequential memory-bound steps for one
    parallel verification. Row i's tokens write K/V at positions
    ``positions[i, :]`` and attend causally by position (intra-window
    included); entries with ``positions[i, j] < 0`` are padding whose
    cache writes are dropped and whose logits are garbage
    (Attention._decode_attention's [b, k] mode). ``page_table``
    [b, max_pages] switches to the paged cache layout (the paged
    serving engine's verify window AND its prefill: a prefill is just
    one big per-slot window writing straight into the slot's pages)."""
    kwargs = {} if page_table is None else {"page_table": page_table}
    logits, vars_ = model.apply({"params": params, "cache": cache},
                                toks, decode=True, mutable=["cache"],
                                positions=positions, **kwargs)
    return vars_["cache"], logits


def generate(model, params, prompt, *, max_new_tokens: int,
             temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
             rng: jax.Array | None = None, eos_id=-1,
             repetition_penalty: float = 1.0):
    """Generate max_new_tokens continuations of ``prompt`` [b, Lp].

    Returns [b, max_new_tokens] int32. ``eos_id`` is an int (-1 = no stop
    token) or a list/tuple of ids (stop on any; frozen rows re-emit the
    first) — normalized here, outside jit, so invalid ids never reach the
    compiled program. Tokens after an eos are frozen (computed but
    masked — fixed trip count keeps the scan static; early-exit would
    force a while_loop with dynamic shapes downstream).
    ``repetition_penalty`` > 1 discourages tokens already in the prompt or
    generated so far (CTRL-style; traced — sweeping values never
    recompiles).
    """
    return _generate(model, params, prompt, max_new_tokens=max_new_tokens,
                     temperature=temperature, top_k=top_k, top_p=top_p,
                     rng=rng, eos_ids=normalize_eos_ids(eos_id),
                     repetition_penalty=repetition_penalty)


@functools.partial(jax.jit, static_argnames=("model", "max_new_tokens",
                                             "top_k", "top_p", "eos_ids"))
def _generate(model, params, prompt, *, max_new_tokens: int,
              temperature: float, top_k: int, top_p: float,
              rng: jax.Array | None, eos_ids: tuple,
              repetition_penalty: float):
    freeze = eos_ids[0] if eos_ids else -1
    if rng is None:
        rng = jax.random.PRNGKey(0)
    b, prompt_len = prompt.shape
    if prompt_len + max_new_tokens > model.cfg.max_seq_len:
        raise ValueError(
            f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
            f"exceeds cfg.max_seq_len ({model.cfg.max_seq_len}): the KV "
            "cache would overflow")
    vocab = model.cfg.vocab_size
    cache = init_cache(model, params, b)
    seen = jnp.zeros((b, vocab), bool)
    seen = seen.at[jnp.arange(b)[:, None], prompt].set(True)

    # prefill: one pass over the whole prompt fills every layer's cache
    logits, vars_ = model.apply({"params": params, "cache": cache}, prompt,
                                decode=True, mutable=["cache"])
    rng, sub = jax.random.split(rng)
    last = _penalize_repeats(logits[:, -1], seen, repetition_penalty)
    next_tok = sample_logits(last, sub, temperature, top_k, top_p)
    seen = seen.at[jnp.arange(b), next_tok].set(True)
    done = _is_eos(next_tok, eos_ids)

    def step(carry, _):
        cache, tok, rng, done, seen = carry
        cache, logits_last = single_decode_step(model, params, cache, tok)
        rng, sub = jax.random.split(rng)
        last = _penalize_repeats(logits_last, seen, repetition_penalty)
        nxt = sample_logits(last, sub, temperature, top_k, top_p)
        nxt = jnp.where(done, freeze, nxt)
        seen = seen.at[jnp.arange(b), nxt].set(True)
        done = done | _is_eos(nxt, eos_ids)
        return (cache, nxt, rng, done, seen), nxt

    carry = (vars_["cache"], next_tok, rng, done, seen)
    if max_new_tokens > 1:
        _, rest = jax.lax.scan(step, carry, None, length=max_new_tokens - 1)
        rest = jnp.moveaxis(rest, 0, 1)  # [steps, b] -> [b, steps]
        return jnp.concatenate([next_tok[:, None], rest], axis=1)
    return next_tok[:, None]


def beam_search(model, params, prompt, *, max_new_tokens: int,
                num_beams: int = 4, eos_id=-1,
                length_penalty: float = 1.0):
    """Beam-search decode: returns the highest-scoring continuation
    [b, max_new_tokens] (ties to the KV cache exactly like generate()).

    One jitted program (static num_beams/max_new_tokens): beams live as a
    widened batch [b*k] so the per-layer cache shards/updates like any
    batch; each step does one fused top-k over [k*V] joint candidates and
    reorders the cache with a batch-dim gather. ``eos_id`` is an int
    (-1 = none) or a list/tuple of ids — normalized here, outside jit, so
    lists never hit the static-arg hasher; beams finishing on any listed
    id are frozen: they re-emit the first eos at zero added score. The
    winner per batch row maximizes score / (generated_len **
    length_penalty), HF-style length normalization.
    """
    return _beam_search(model, params, prompt,
                        max_new_tokens=max_new_tokens, num_beams=num_beams,
                        eos_ids=normalize_eos_ids(eos_id),
                        length_penalty=length_penalty)


@functools.partial(jax.jit, static_argnames=("model", "max_new_tokens",
                                             "num_beams", "eos_ids"))
def _beam_search(model, params, prompt, *, max_new_tokens: int,
                 num_beams: int, eos_ids: tuple, length_penalty: float):
    freeze = eos_ids[0] if eos_ids else 0
    b, prompt_len = prompt.shape
    k = num_beams
    if prompt_len + max_new_tokens > model.cfg.max_seq_len:
        raise ValueError(
            f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
            f"exceeds cfg.max_seq_len ({model.cfg.max_seq_len})")
    vocab = model.cfg.vocab_size
    neg = jnp.float32(-1e30)

    def _cache_batch_axis(path, leaf):
        """Batch axis of a cache leaf, or None for non-batched leaves.

        The KV buffers are [..., b, max_len, kvh, dh] — batch is always
        4th-from-last; scan_layers models prepend an n_layers axis, so
        keying on axis 0 (or on a dim happening to equal b) would widen or
        gather the LAYERS axis and silently corrupt the cache. Index
        counters (cache_index/pos_index) carry no batch dim."""
        name = str(path[-1].key if hasattr(path[-1], "key") else path[-1])
        if name in ("cached_key", "cached_value"):
            return leaf.ndim - 4
        return None

    def widen(path, c):
        ax = _cache_batch_axis(path, c)
        return c if ax is None else jnp.repeat(c, k, axis=ax)

    # prefill ONCE at batch b (all beams share the prompt), then widen the
    # cache rows to b*k — prefill dominates latency for long prompts and
    # repeating it per beam would compute k identical copies
    cache = init_cache(model, params, b)
    logits, vars_ = model.apply({"params": params, "cache": cache},
                                prompt, decode=True, mutable=["cache"])
    cache = jax.tree_util.tree_map_with_path(widen, vars_["cache"])
    logp0 = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32), axis=-1)
    scores, first_tok = jax.lax.top_k(logp0, k)  # [b, k]
    finished = _is_eos(first_tok, eos_ids)
    out = jnp.full((b, k, max_new_tokens), freeze, jnp.int32)
    out = out.at[:, :, 0].set(first_tok)
    lengths = jnp.ones((b, k), jnp.int32)

    def step(carry, t):
        cache, tok, scores, finished, out, lengths = carry
        logits, vars_ = model.apply(
            {"params": params, "cache": cache},
            tok.reshape(b * k)[:, None], decode=True, mutable=["cache"])
        cache = vars_["cache"]
        logp = jax.nn.log_softmax(
            logits[:, -1].astype(jnp.float32), axis=-1).reshape(b, k, vocab)
        if eos_ids:
            # frozen beams: only the freeze eos continues, at no added score
            eos_only = jnp.full((vocab,), neg).at[freeze].set(0.0)
            logp = jnp.where(finished[:, :, None], eos_only[None, None],
                             logp)
        cand = scores[:, :, None] + logp  # [b, k, V]
        new_scores, flat = jax.lax.top_k(cand.reshape(b, k * vocab), k)
        beam_idx = flat // vocab  # [b, k]
        new_tok = flat % vocab
        # reorder beam-major state by the winning parent beams
        rows = (jnp.arange(b)[:, None] * k + beam_idx).reshape(-1)  # [b*k]
        cache = jax.tree_util.tree_map_with_path(
            lambda p, c: c if _cache_batch_axis(p, c) is None
            else jnp.take(c, rows, axis=_cache_batch_axis(p, c)), cache)
        out = jnp.take_along_axis(out, beam_idx[:, :, None], axis=1)
        lengths = jnp.take_along_axis(lengths, beam_idx, axis=1)
        was_finished = jnp.take_along_axis(finished, beam_idx, axis=1)
        out = out.at[:, :, t].set(jnp.where(was_finished, freeze, new_tok))
        lengths = jnp.where(was_finished, lengths, lengths + 1)
        finished = was_finished | _is_eos(new_tok, eos_ids)
        return (cache, new_tok, new_scores, finished, out, lengths), None

    carry = (cache, first_tok, scores, finished, out, lengths)
    if max_new_tokens > 1:
        carry, _ = jax.lax.scan(step, carry, jnp.arange(1, max_new_tokens))
    _, _, scores, finished, out, lengths = carry
    norm = scores / (lengths.astype(jnp.float32) ** length_penalty)
    best = jnp.argmax(norm, axis=1)  # [b]
    return jnp.take_along_axis(out, best[:, None, None], axis=1)[:, 0]
