"""The coordinator process — ApplicationMaster equivalent.

Reference: ApplicationMaster.java (1347 LoC): registers control-plane RPC +
metrics RPC servers, builds the session, gang-schedules tasks through the
DAG scheduler, launches per-task agents, runs a heartbeat liveness monitor
and a monitor loop (timeout / registration-timeout / startup-failure /
training-finished / client stop), retries the whole session on failure
(session epoch++), emits history events, and supports a preprocess /
single-node mode where the coordinator itself hosts the user process
(doPreprocessingJob :780-832).

Process entry: ``python -m tony_tpu.coordinator --conf <tony-final.json>
--app-id <id> --job-dir <dir>``. The client discovers the RPC endpoint via
``coordinator.json`` written into the job dir (stands in for the YARN
application report's host:port).
"""

from __future__ import annotations

import argparse
import contextlib
import glob
import json
import logging
import os
import shutil
import shlex
import threading
import time

from tony_tpu import constants as C
from tony_tpu.config import ConfError, TonyConf
from tony_tpu.coordinator.chips import ChipAllocator
from tony_tpu.coordinator.launcher import Launcher, LocalProcessLauncher
from tony_tpu.coordinator.liveness import LivenessMonitor
from tony_tpu.coordinator.provisioner import (
    ProvisioningError,
    StaticProvisioner,
    preflight_chips,
    provisioner_from_conf,
)
from tony_tpu.events import (
    EventHandler,
    application_finished,
    application_inited,
    task_finished,
    task_started,
)
from tony_tpu.metrics import MetricsStore
from tony_tpu.rpc import RpcServer
from tony_tpu.runtime import get_am_adapter
from tony_tpu.scheduler import TaskScheduler
from tony_tpu.session import Session, SessionStatus
from tony_tpu.utils import execute_shell, local_host_name, python_interpreter

log = logging.getLogger(__name__)


class ClientRpcHandler:
    """The 8 control-plane verbs (ref: inner RpcForClient,
    ApplicationMaster.java:854-970; proto service
    tensorflow_cluster_service_protos.proto:11-20)."""

    def __init__(self, coord: "Coordinator"):
        self._coord = coord

    def get_task_infos(self):
        return [i.to_dict() for i in self._coord.session.task_infos()]

    def get_cluster_spec(self, task_id: str):
        return self._coord.cluster_spec_if_ready(task_id)

    def register_worker_spec(self, task_id: str, spec: str):
        """Ref: registerWorkerSpec :907-926 — returns the cluster spec only
        once the runtime's gate opens; agents poll until non-null."""
        return self._coord.register_worker_spec(task_id, spec)

    def register_tensorboard_url(self, url: str):
        self._coord.tensorboard_url = url
        log.info("TensorBoard registered at %s", url)
        return True

    def register_execution_result(self, task_id: str, exit_code: int,
                                  session_id: int = -1,
                                  preempted: bool = False):
        return self._coord.register_execution_result(
            task_id, int(exit_code), int(session_id), bool(preempted))

    def finish_application(self):
        self._coord.client_done.set()
        return self._coord.application_status()

    def task_executor_heartbeat(self, task_id: str):
        """Liveness ping; the response piggybacks queued coordinator->agent
        commands (profile requests etc.) — the rebuild's channel for
        on-demand actions the reference lacks."""
        self._coord.liveness.ping(task_id)
        return {"commands": self._coord.drain_commands(task_id)}

    def request_profile(self, task_id: str, num_steps: int = 5):
        """Queue an on-demand xplane trace of a task (greenfield vs the
        reference; SURVEY.md section 5.1)."""
        return self._coord.queue_command(
            task_id, {"type": "profile", "num_steps": int(num_steps)})

    def resize_role(self, role: str, instances: int):
        """Elastic resize: checkpoint-aware gang restart at the new size
        (real elasticity where the reference stubs it — see
        tony_tpu/elastic.py)."""
        return self._coord.request_resize(role, int(instances))

    def register_callback_info(self, task_id: str, info: str):
        self._coord.am_adapter.receive_task_callback_info(task_id, info)
        return True

    # rebuild extra: no RM exists to serve the application report, so status
    # is a first-class verb (ref: client polls YarnClient.getApplicationReport)
    def get_application_status(self):
        return self._coord.application_status()

    def force_kill(self):
        log.warning("client requested force kill")
        self._coord.killed.set()
        return True


class Coordinator:
    def __init__(self, conf: TonyConf, app_id: str, job_dir: str,
                 launcher: Launcher | None = None):
        self.conf = conf
        self.app_id = app_id
        self.job_dir = job_dir
        os.makedirs(job_dir, exist_ok=True)
        self.secret = os.environ.get(C.JOB_TOKEN) or None
        if not conf.get_bool("tony.application.security.enabled"):
            self.secret = None
        # preprocess-stage stdout params fed to training containers
        # (ref: containerEnv[TASK_PARAM_KEY], ApplicationMaster.java:826)
        self._model_params: str | None = None
        self.framework = str(conf.get("tony.application.framework"))
        self.mode = str(conf.get("tony.application.distributed-mode"))
        self.am_adapter = get_am_adapter(self.framework)
        self.am_adapter.validate_and_update_config(conf)
        self.session = Session(conf, session_id=0)
        self.scheduler: TaskScheduler | None = None
        self.provisioner = provisioner_from_conf(conf, app_id)
        # launcher construction is deferred until after provisioning: in
        # ssh mode the host list may only exist once the slice is READY —
        # but misconfig must still kill the process at startup (ref:
        # validateAndUpdateConfig fails the submission, not the session)
        self._launcher: Launcher | None = launcher
        if launcher is None:
            self._validate_launcher_conf()
        self._chips: ChipAllocator | None = None
        self.metrics = MetricsStore()
        self.liveness = LivenessMonitor(
            conf.get_int("tony.task.heartbeat-interval-ms", 1000),
            conf.get_int("tony.task.max-missed-heartbeats", 25),
            self._on_task_deemed_dead,
        )
        host = str(conf.get("tony.coordinator.host", "127.0.0.1"))
        self.tls: tuple[str, str] | None = None
        self._tls_fp = ""
        if conf.get_bool("tony.application.security.tls"):
            from tony_tpu.rpc.tls import cert_fingerprint, mint_self_signed

            # normally minted by the client at staging; mint here too so a
            # directly-constructed coordinator (tests, tony-mini) works
            self.tls = mint_self_signed(job_dir, f"tony-{app_id}")
            self._tls_fp = cert_fingerprint(self.tls[0])
        self.rpc = RpcServer(ClientRpcHandler(self), host=host,
                             secret=self.secret, tls=self.tls)
        self.metrics_rpc = RpcServer(self.metrics, host=host,
                                     secret=self.secret, tls=self.tls)
        history_root = str(conf.get("tony.history.location") or
                           os.path.join(job_dir, "history"))
        self.events = EventHandler(history_root, app_id)
        self.client_done = threading.Event()
        self.killed = threading.Event()
        self.tensorboard_url = ""
        self.attempt = 0
        self._launch_time: dict[str, float] = {}
        self._lock = threading.Lock()
        self._worker_termination_done = False
        self._pending_commands: dict[str, list[dict]] = {}
        self._pending_resize: dict[str, int] = {}
        self._resizing = False

    # -------------------------------------------------- agent command queue
    def queue_command(self, task_id: str, command: dict) -> bool:
        """Queue a command for delivery on the task's next heartbeat."""
        with self._lock:
            if not self.session.has_slot(task_id):
                return False
            self._pending_commands.setdefault(task_id, []).append(command)
        return True

    def drain_commands(self, task_id: str) -> list[dict]:
        with self._lock:
            return self._pending_commands.pop(task_id, [])

    # ------------------------------------------------------- elastic resize
    def request_resize(self, role: str, instances: int) -> bool:
        """Validate + queue an elastic resize; the monitor loop performs it
        (see tony_tpu/elastic.py for the protocol)."""
        if instances < 1:
            return False
        with self._lock:
            if role not in self.session.tasks:
                return False
            self._pending_resize[role] = instances
        return True

    def _take_pending_resize(self) -> dict[str, int]:
        with self._lock:
            resize, self._pending_resize = self._pending_resize, {}
            return resize

    def _perform_resize(self, resize: dict[str, int]) -> None:
        """Checkpoint-aware gang restart: notify tasks, grace, rebuild the
        session at the new sizes, relaunch."""
        from tony_tpu.events import session_resized

        self._resizing = True
        try:
            grace_s = self.conf.get_int("tony.elastic.grace-ms", 15_000) / 1000
            with self._lock:
                live = [t for t in self.session.all_tasks() if not t.completed]
                for task in live:
                    self._pending_commands.setdefault(task.id, []).append(
                        {"type": "save_and_exit"})
            log.info("elastic resize to %s: notified %d tasks, grace %.1fs",
                     resize, len(live), grace_s)
            deadline = time.monotonic() + grace_s
            while time.monotonic() < deadline:
                if all(t.completed for t in self.session.all_tasks()):
                    break
                time.sleep(0.1)
            for role, n in resize.items():
                self.conf.set(f"tony.{role}.instances", n)
            self._reset_session()
            # stale control files must not make the next epoch exit at step
            # 0 — cleaned after the old agents are dead so none can rewrite
            # one (agents also self-clean at startup, covering ssh hosts)
            from tony_tpu.elastic import CONTROL_FILENAME

            for path in glob.glob(os.path.join(
                    self.job_dir, CONTROL_FILENAME + "*")):
                with contextlib.suppress(OSError):
                    os.remove(path)
            self.events.emit(session_resized(
                self.app_id, self.session.session_id, resize))
            self._start_attempt()
        finally:
            self._resizing = False

    # ------------------------------------------------------------------ rpc
    def cluster_spec_if_ready(self, task_id: str) -> str | None:
        if self.am_adapter.can_start_task(self.mode, task_id):
            return self.am_adapter.construct_cluster_spec(task_id)
        return None

    def register_worker_spec(self, task_id: str, spec: str) -> str | None:
        task = self.session.register(task_id, spec)
        if task is None:
            log.warning("registration for unknown task %s", task_id)
            return None
        self.liveness.register(task_id)
        log.info("registered %s at %s (%d/%d)", task_id, spec,
                 self.session.num_registered, self.session.total_expected)
        return self.cluster_spec_if_ready(task_id)

    def register_execution_result(self, task_id: str, exit_code: int,
                                  session_id: int = -1,
                                  preempted: bool = False) -> bool:
        """A result from a previous session epoch (pre-resize/retry gang)
        must not complete the current epoch's task of the same id (ref:
        sessionId guard on TonySession results)."""
        if session_id >= 0 and session_id != self.session.session_id:
            log.info("ignoring stale result %s (epoch %d != %d)", task_id,
                     session_id, self.session.session_id)
            return False
        log.info("task %s registered exit code %d%s", task_id, exit_code,
                 " (preempted)" if preempted else "")
        self._complete_task(task_id, exit_code, preempted=preempted)
        return True

    # ---------------------------------------------------------- completions
    def _complete_task(self, task_id: str, exit_code: int,
                       preempted: bool = False) -> None:
        delay = os.environ.get(C.TEST_COMPLETION_DELAY)
        if delay:  # fault injection (ref: ApplicationMaster.java:1074-1083)
            time.sleep(int(delay) / 1000)
        if self._resizing:
            # the gang is being torn down for an elastic restart; exits in
            # this window (EXIT_RESIZE or kills) are not failures — record
            # completion so the grace loop can finish early, skip the
            # session's exit-status policy
            from tony_tpu.elastic import EXIT_RESIZE

            self.liveness.unregister(task_id)
            if self._chips is not None:
                self._chips.release(task_id)
            with self._lock:
                task = self.session.get_task_by_id(task_id)
                if task is not None:
                    # a cooperative EXIT_RESIZE is a clean exit, not a failure
                    task.set_exit_status(
                        0 if exit_code == EXIT_RESIZE else exit_code)
            return
        with self._lock:
            task = self.session.get_task_by_id(task_id)
            if task is None or task.completed:
                return
            # unregister first: a completed task must not expire later
            # (ref: 3-way race comment, ApplicationMaster.java:928-956)
            self.liveness.unregister(task_id)
            if self._chips is not None:
                self._chips.release(task_id)
            was_registered = task.registered
            self.session.on_task_completed(task.role, task.index, exit_code)
            if preempted and exit_code != 0 and \
                    self.session.status == SessionStatus.FAILED and \
                    self.session.failure_reason and \
                    f"task {task_id} failed" in self.session.failure_reason:
                # annotate so operators (and the history) see this was the
                # platform reclaiming capacity, not the training failing —
                # but only when THIS task's failure is the recorded reason
                # (a preempted worker arriving after a genuine chief crash
                # must not clobber the real first-failure reason)
                self.session.failure_reason += \
                    " [preempted: spot reclaim / maintenance]"
            self.events.emit(task_finished(
                task.role, task.index, task.status.name,
                self.metrics.get_metrics(task_id)))
            if not was_registered:
                # completed without ever registering -> startup failure
                # (ref: startupFailed :1271-1301)
                self.session.fail(
                    f"task {task_id} exited ({exit_code}) before registering")
        if self.scheduler is not None:
            self.scheduler.on_role_instance_completed(task.role)

    @property
    def launcher(self) -> Launcher:
        if self._launcher is None:
            self._launcher = self._launcher_from_conf()
        return self._launcher

    def _validate_launcher_conf(self) -> None:
        """The subset of _launcher_from_conf's checks that need no
        provisioned hosts, run eagerly at construction."""
        mode = str(self.conf.get("tony.application.launch-mode", "local"))
        docker_on = self.conf.get("tony.docker.enabled")
        if docker_on and mode not in ("local", "docker"):
            raise ValueError(
                f"tony.docker.enabled conflicts with launch-mode={mode}: "
                "docker launch runs containers on this host only")
        if (mode == "docker" or docker_on) and \
                not str(self.conf.get("tony.docker.image", "")):
            raise ValueError("docker launch requires tony.docker.image")
        if mode not in ("local", "docker", "ssh"):
            raise ValueError(f"unknown tony.application.launch-mode: {mode}")
        if mode == "ssh" and isinstance(self.provisioner, StaticProvisioner) \
                and not self.provisioner.hosts:
            raise ValueError(
                "launch-mode=ssh requires tony.application.hosts or a "
                "provisioner (tony.provisioner.mode)")

    def _provision(self) -> None:
        """Acquire capacity before the gang (the RM conversation — ref:
        TonyClient.submitApplication :314-349). Static mode only preflights
        local chip demand; tpu-vm/queued modes create/adopt the slice and
        feed its hosts to the ssh launcher."""
        mode = str(self.conf.get("tony.application.launch-mode", "local"))
        if isinstance(self.provisioner, StaticProvisioner):
            if mode in ("local", "docker"):
                # both modes share THIS host's chips (_task_env enforces
                # the same pair) — over-demand must die here, not mid-gang
                err = preflight_chips(self.conf)
                if err:
                    raise ProvisioningError(err)
            return
        hosts = self.provisioner.provision()
        if mode == "ssh" and hosts:
            # provisioned hosts replace any statically configured list —
            # the slice we just created IS the capacity for this job
            self.conf.set("tony.application.hosts", ",".join(hosts))

    def _launcher_from_conf(self) -> Launcher:
        """Pick agent placement from tony.application.launch-mode (local
        subprocesses, or ssh onto the slice's TPU-VM hosts)."""
        mode = str(self.conf.get("tony.application.launch-mode", "local"))
        if self.conf.get("tony.docker.enabled") and mode not in ("local", "docker"):
            raise ValueError(
                f"tony.docker.enabled conflicts with launch-mode={mode}: "
                "docker launch runs containers on this host only")
        if mode == "docker" or self.conf.get("tony.docker.enabled"):
            from tony_tpu.coordinator.launcher import DockerLauncher

            image = str(self.conf.get("tony.docker.image", ""))
            if not image:
                raise ValueError("docker launch requires tony.docker.image")
            mounts = [m.strip() for m in
                      str(self.conf.get("tony.docker.mounts", "")).split(",")
                      if m.strip()]
            extra = shlex.split(str(self.conf.get("tony.docker.run-args", "")))
            return DockerLauncher(
                image, self._on_task_process_exit, mounts=mounts,
                extra_args=extra,
                docker_bin=str(self.conf.get("tony.docker.bin", "docker")),
                workdir=self.job_dir)
        if mode == "ssh":
            from tony_tpu.coordinator.launcher import SshLauncher

            hosts = [h.strip() for h in
                     str(self.conf.get("tony.application.hosts", "")).split(",")
                     if h.strip()]
            if not hosts:
                raise ValueError(
                    "launch-mode=ssh requires tony.application.hosts")
            return SshLauncher(
                hosts, self._on_task_process_exit,
                remote_pythonpath=str(
                    self.conf.get("tony.application.remote-pythonpath", "")),
                ssh_bin=str(self.conf.get("tony.application.ssh-bin", "ssh")),
                app_id=self.app_id,
                chips_per_host=self.conf.get_int("tony.tpu.chips-per-host",
                                                 0),
                ship_job_dir=self.job_dir
                if self.conf.get_bool("tony.ssh.ship-job-dir") else "",
                remote_job_root=str(
                    self.conf.get("tony.ssh.remote-job-root", "")))
        if mode != "local":
            raise ValueError(f"unknown tony.application.launch-mode: {mode}")
        return LocalProcessLauncher(self._on_task_process_exit,
                                    workdir=self.job_dir)

    def _on_task_process_exit(self, task_id: str, exit_code: int) -> None:
        """Launcher backup path (ref: onContainersCompleted ->
        processFinishedContainer :1234-1268). Idempotent with the RPC result
        registration."""
        self._complete_task(task_id, exit_code)

    def _on_task_deemed_dead(self, task_id: str) -> None:
        """Ref: onTaskDeemedDead :1225-1232 — fail the application."""
        self.session.fail(f"task {task_id} missed heartbeats; deemed dead")
        self.launcher.kill_task(task_id)

    # ------------------------------------------------------------ lifecycle
    def prepare(self) -> None:
        """Ref: prepare :443-527."""
        self.rpc.start()
        self.metrics_rpc.start()
        self.liveness.start()
        self.events.start()
        self._write_endpoint_file()
        log.info("coordinator for %s listening on %s:%d (metrics %d)",
                 self.app_id, self.rpc.host, self.rpc.port, self.metrics_rpc.port)

    def _write_endpoint_file(self) -> None:
        info = {
            "app_id": self.app_id,
            "host": self.rpc.host,
            "port": self.rpc.port,
            "metrics_port": self.metrics_rpc.port,
            "pid": os.getpid(),
        }
        path = os.path.join(self.job_dir, "coordinator.json")
        with open(path + ".tmp", "w") as f:
            json.dump(info, f)
        os.replace(path + ".tmp", path)

    def _start_attempt(self) -> None:
        """Ref: start() :578-609 — build session, schedule the gang.
        With enable-preprocess AND training roles, the preprocess command
        runs first on the coordinator and its scraped stdout params feed
        the training containers (ref: run() :578-609 calls
        doPreprocessingJob then falls through to buildTonySession)."""
        if os.environ.get(C.TEST_COORD_THROW) and self.attempt == 0:
            raise RuntimeError("injected coordinator exception (TEST_COORD_THROW)")
        single_node = not self.session.requests
        if self.conf.get_bool("tony.application.enable-preprocess") or \
                single_node:
            ok = self._run_preprocess(single_node=single_node)
            if single_node or not ok:
                return  # terminal: status set by _run_preprocess
        self.am_adapter.set_session(self.session)
        self.scheduler = TaskScheduler(self.session, self._allocate_role, self.conf)
        self.events.emit(application_inited(
            self.app_id, self.session.total_expected, local_host_name()))
        self.scheduler.schedule()

    def _allocate_role(self, req) -> None:
        """Launch every instance of a role (ref: RMCallbackHandler +
        ContainerLauncher collapsed: no container negotiation on TPU)."""
        for i in range(req.instances):
            task = self.session.init_task(req.role, i)
            if task is None:
                continue
            env = self._task_env(req, task)
            log_path = os.path.join(self.job_dir, "logs",
                                    f"{task.role}-{task.index}{C.LOG_SUFFIX}")
            task.log_url = log_path
            self._launch_time[task.id] = time.monotonic()
            self.launcher.launch(task, env, log_path)
            self.events.emit(task_started(task.role, task.index, local_host_name()))

    @property
    def chips(self) -> ChipAllocator:
        """This host's chip pool for tasks sharing the coordinator host
        (local/docker launch modes). Sized from DISCOVERY only: when the
        host shows no chips, requests stay advisory (same stance as
        preflight_chips — a CPU CI host must run, not fail mid-launch;
        tony.tpu.chips-per-host is a slice-sizing hint, not a claim about
        this host)."""
        if self._chips is None:
            total = 0
            from tony_tpu.utils.tpu_info import TpuDiscoverer

            try:
                total = len(TpuDiscoverer(str(self.conf.get(
                    "tony.tpu.info-exec-path", "")))
                    .get_device_information().chips)
            except Exception:
                log.exception("chip discovery failed; chips advisory")
            self._chips = ChipAllocator(total)
        return self._chips

    def _task_env(self, req, task) -> dict[str, str]:
        """Agent env (ref: ContainerLauncher env :1168-1188)."""
        retries = self.conf.get_int("tony.coordinator.retry-count", 0)
        env = {
            C.JOB_NAME: task.role,
            C.TASK_INDEX: str(task.index),
            C.TASK_NUM: str(req.instances),
            C.IS_CHIEF: "true" if self.session.is_chief(task.role, task.index) else "false",
            C.JOB_ID: self.app_id,
            C.SESSION_ID: str(self.session.session_id),
            C.DISTRIBUTED_MODE: self.mode,
            C.ATTEMPT_NUMBER: str(self.attempt),
            C.NUM_AM_RETRIES: str(retries),
            C.COORDINATOR_HOST: self.rpc.host,
            C.COORDINATOR_PORT: str(self.rpc.port),
            C.METRICS_PORT: str(self.metrics_rpc.port),
            "TONY_CONF_PATH": os.path.join(self.job_dir, C.TONY_FINAL_CONF),
            C.JOB_DIR: self.job_dir,
            # every attempt of this job shares one compile cache, so a
            # retried/resumed task skips its XLA compiles (VERDICT r2 #2;
            # consumed by distributed.initialize via utils.compilecache)
            C.COMPILE_CACHE_DIR: os.path.join(self.job_dir, "compile-cache"),
            "TONY_TASK_COMMAND": self._task_command(req),
        }
        mode = str(self.conf.get("tony.application.launch-mode", "local"))
        if req.chips > 0 and mode in ("local", "docker") \
                and self.chips.total > 0:
            # shared host: disjoint device subsets per task (ref: YARN
            # hands each container its own GPU set, util/Utils.java:393-419)
            ids = self.chips.allocate(task.id, req.chips)
            env[C.TPU_VISIBLE_DEVICES] = ",".join(str(i) for i in ids)
        elif req.chips > 0 and mode == "ssh":
            # the ssh launcher owns placement, so it also owns the
            # per-host chip pools: ship the demand, it packs + assigns
            env[C.TASK_CHIPS] = str(req.chips)
        # memory/vcores reach the launcher ONLY when explicitly configured
        # for the role: the schema default (2g) must not impose an rlimit
        # on jax processes that map far more address space than they touch
        if f"tony.{req.role}.memory" in self.conf:
            env[C.TASK_MEMORY] = str(req.memory)
        if f"tony.{req.role}.vcores" in self.conf:
            env[C.TASK_VCORES] = str(req.vcores)
        if self.secret:
            env[C.JOB_TOKEN] = self.secret
        if self._tls_fp:
            env[C.TLS_FINGERPRINT] = self._tls_fp
        if self._model_params is not None:
            env[C.MODEL_PARAMS] = self._model_params
        ckpt = self._checkpoint_dir()
        if ckpt:
            # restart-with-resume (no ref analog — TonY's AM retry restarts
            # user scripts cold, SURVEY 5.4): every attempt gets the same
            # checkpoint root; on retry we also advertise the newest step
            # found so the task can log/assert what it resumes from
            env[C.CHECKPOINT_DIR] = ckpt
            from tony_tpu.train.checkpoint import scan_latest_step

            step = scan_latest_step(ckpt)
            if step is not None:
                env[C.RESUME_STEP] = str(step)
        return env

    def _checkpoint_dir(self) -> str | None:
        path = str(self.conf.get("tony.application.checkpoint-dir", ""))
        if not path:
            return None
        from tony_tpu.utils.remotefs import is_remote

        if is_remote(path):
            # gs:// checkpoint roots pass through untouched: orbax/
            # tensorstore write them natively; scan_latest_step simply
            # reports no local steps (resume still works via orbax)
            return path
        if not os.path.isabs(path):
            path = os.path.join(self.job_dir, path)
        os.makedirs(path, exist_ok=True)
        return path

    def _task_command(self, req) -> str:
        """Ref: TonyClient.buildTaskCommand :618-635 — role command override,
        else venv python + executes + task params."""
        if req.command:
            return req.command
        executes = str(self.conf.get("tony.application.executes", ""))
        if not executes:
            return ""
        params = str(self.conf.get("tony.application.task-params", ""))
        venv = str(self.conf.get("tony.application.python-command", "")) or \
            python_interpreter(os.path.join(self.job_dir, "venv"))
        if executes.endswith(".py"):
            return f"{venv} {executes} {params}".strip()
        return f"{executes} {params}".strip()

    def _run_preprocess(self, single_node: bool = True) -> bool:
        """Single-node / preprocess mode: the coordinator hosts the user
        process itself (ref: doPreprocessingJob :780-832). Returns True on
        success. In preprocess-then-train mode (``single_node=False``) a
        success is NOT terminal: the task's stdout is scraped for a
        ``Model parameters: <params>`` line and the remainder is exported
        to every training container as ``MODEL_PARAMS`` (ref:
        :819-832 scraping amstdout.log into Constants.TASK_PARAM_KEY)."""
        cmd = str(self.conf.get("tony.coordinator.command", "")) \
            if not single_node else ""
        cmd = cmd or self._task_command_single()
        log.info("running preprocess/single-node command: %s", cmd)
        task_log = os.path.join(self.job_dir, "logs", "coordinator-task.log")
        code = execute_shell(
            cmd,
            self.conf.get_int("tony.task.executor.execution-timeout-ms", 0),
            env={C.JOB_ID: self.app_id, C.JOB_NAME: "coordinator",
                 C.PREPROCESSING_JOB: "true"},
            log_path=task_log,
        )
        if code != 0:
            self.session.fail(f"preprocess/single-node task exited {code}")
            self._preprocess_ran = True
            return False
        if single_node:
            self.session.status = SessionStatus.SUCCEEDED
            self._preprocess_ran = True
            return True
        self._model_params = self._scrape_model_params(task_log)
        return True

    @staticmethod
    def _scrape_model_params(task_log: str) -> str | None:
        """First ``Model parameters: `` stdout line's remainder, or None
        (ref: ApplicationMaster.java:819-832)."""
        marker = "Model parameters: "
        try:
            with open(task_log, errors="replace") as f:
                for line in f:
                    if marker in line:
                        return line.split(marker, 1)[1].rstrip("\n")
        except OSError:
            log.warning("preprocess log %s unreadable; no MODEL_PARAMS",
                        task_log)
        return None

    def _task_command_single(self) -> str:
        executes = str(self.conf.get("tony.application.executes", ""))
        params = str(self.conf.get("tony.application.task-params", ""))
        if executes.endswith(".py"):
            return f"{python_interpreter(None)} {executes} {params}".strip()
        return f"{executes} {params}".strip()

    # --------------------------------------------------------------- monitor
    def _monitor(self) -> SessionStatus:
        """Ref: monitor() :634-715."""
        interval = self.conf.get_int("tony.coordinator.monitor-interval-ms", 1000) / 1000
        timeout_ms = self.conf.get_int("tony.application.timeout-ms", 0)
        reg_timeout_s = self.conf.get_int(
            "tony.coordinator.registration-timeout-ms", 900_000) / 1000
        start = time.monotonic()
        while True:
            if getattr(self, "_preprocess_ran", False):
                return self.session.status
            if self.killed.is_set():
                self.session.fail("killed by client")
                return self.session.status
            if timeout_ms and (time.monotonic() - start) * 1000 > timeout_ms:
                self.session.fail(f"application timed out after {timeout_ms} ms")
                return self.session.status
            if self.session.status != SessionStatus.RUNNING:
                return self.session.status
            resize = self._take_pending_resize()
            if resize:
                self._perform_resize(resize)
                continue
            if self.session.training_finished():
                return self.session.update_session_status()
            self._check_registration_timeouts(reg_timeout_s)
            self._maybe_kill_chief_for_test()
            time.sleep(interval)

    def _check_registration_timeouts(self, reg_timeout_s: float) -> None:
        """Ref: registrationTimeout :1309-1329."""
        now = time.monotonic()
        for task in self.session.all_tasks():
            if task.registered or task.completed:
                continue
            launched = self._launch_time.get(task.id)
            if launched is not None and now - launched > reg_timeout_s:
                self.session.fail(
                    f"task {task.id} failed to register within {reg_timeout_s:.0f}s")
                return

    def _maybe_kill_chief_for_test(self) -> None:
        """Fault injection (ref: killChiefWorkerIfTesting :1333-1344)."""
        if self._worker_termination_done or not os.environ.get(C.TEST_WORKER_TERMINATION):
            return
        if not self.session.all_registered():
            return
        for task in self.session.all_tasks():
            if self.session.is_chief(task.role, task.index):
                log.warning("TEST_WORKER_TERMINATION: killing chief %s", task.id)
                self.launcher.kill_task(task.id)
                self._worker_termination_done = True
                return

    # ------------------------------------------------------------------ run
    def run(self) -> bool:
        """Ref: run() :357-435 with the retry loop :382-422."""
        self.prepare()
        retries = self.conf.get_int("tony.coordinator.retry-count", 0)
        status = SessionStatus.FAILED
        try:
            try:
                self._provision()
            except (ProvisioningError, ConfError) as e:
                log.error("provisioning failed: %s", e)
                self.session.fail(f"provisioning failed: {e}")
                return self._stop(SessionStatus.FAILED)
            for self.attempt in range(retries + 1):
                try:
                    self._start_attempt()
                    if os.environ.get(C.TEST_COORD_CRASH) \
                            and self.attempt == 0 \
                            and os.environ.get(C.COORD_CLIENT_ATTEMPT,
                                               "0") == "0":
                        # crash exactly once: a client-respawned coordinator
                        # (attempt env > 0) proceeds, so respawn is testable
                        log.error("TEST_COORD_CRASH: hard-exiting coordinator")
                        os._exit(1)
                    status = self._monitor()
                except ConfError:
                    raise
                except Exception as e:
                    log.exception("coordinator attempt %d crashed", self.attempt)
                    self.session.fail(f"coordinator exception: {e}")
                    status = SessionStatus.FAILED
                if status == SessionStatus.SUCCEEDED or self.killed.is_set():
                    break
                if self.attempt < retries:
                    log.warning("attempt %d failed (%s); retrying",
                                self.attempt, self.session.failure_reason)
                    self._reset_session()
            return self._stop(status)
        finally:
            self.rpc.stop()
            self.metrics_rpc.stop()
            self.liveness.stop()

    def _reset_session(self) -> None:
        """Ref: reset() :612-628 — stop containers, rebuild session epoch."""
        self.launcher.stop_all()
        # a killed task from the old epoch never reports a result, so its
        # liveness entry would expire against the healthy new session
        self.liveness.clear()
        if self._chips is not None:
            self._chips.reset()
        old_id = self.session.session_id
        self.session = Session(self.conf, session_id=old_id + 1)
        self._launch_time.clear()
        self._worker_termination_done = False
        # a failed preprocess must not poison the retry: the flag would
        # make _monitor return before the fresh attempt's gang runs
        self._preprocess_ran = False
        self._model_params = None
        with self._lock:
            # undrained commands must not leak into the new epoch's tasks
            self._pending_commands.clear()
        self.am_adapter = get_am_adapter(self.framework)
        self.am_adapter.validate_and_update_config(self.conf)

    def _stop(self, status: SessionStatus) -> bool:
        """Ref: stop() :735-777 — stop containers, emit final event, wait
        briefly for the client's finish signal, finalize history."""
        if self._launcher is not None:  # never constructed if provisioning failed
            self._launcher.stop_all()
        self.provisioner.deprovision()
        final = "SUCCEEDED" if status == SessionStatus.SUCCEEDED else "FAILED"
        failed = sum(1 for t in self.session.all_tasks() if t.status.name == "FAILED")
        self.events.emit(application_finished(self.app_id, final, failed))
        self._archive_metrics()
        self._write_status_file(final)
        self.am_adapter.destroy()
        self.client_done.wait(timeout=30)
        self.events.stop(final)
        log.info("application %s finished: %s (%s)", self.app_id, final,
                 self.session.failure_reason or "ok")
        return status == SessionStatus.SUCCEEDED

    def _archive_metrics(self) -> None:
        """Copy training-metric jsonl files (written by train.fit sinks into
        <job_dir>/metrics/) into the history dir so the portal can serve
        them after the job dir is gone (no reference analog: TonY's history
        holds only events + config, SURVEY.md 5.5)."""
        src = os.path.join(self.job_dir, "metrics")
        if not os.path.isdir(src):
            return
        # wholly best-effort: a full/read-only history mount must not abort
        # _stop() (status file, adapter destroy, jhist finalize come after)
        try:
            dst = os.path.join(self.events.job_dir, "metrics")
            os.makedirs(dst, exist_ok=True)
            names = os.listdir(src)
        except OSError:
            log.exception("failed to create metrics archive dir")
            return
        for name in names:
            if name.endswith(".jsonl"):
                try:
                    shutil.copy2(os.path.join(src, name),
                                 os.path.join(dst, name))
                except OSError:
                    log.exception("failed to archive metrics file %s", name)

    def _write_status_file(self, final: str) -> None:
        path = os.path.join(self.job_dir, "status.json")
        with open(path + ".tmp", "w") as f:
            json.dump({
                "status": final,
                "reason": self.session.failure_reason,
                "tensorboard_url": self.tensorboard_url,
                "tasks": [i.to_dict() for i in self.session.task_infos()],
            }, f, indent=2)
        os.replace(path + ".tmp", path)

    def application_status(self) -> dict:
        status = self.session.status
        # Ref semantics: the client polls the *application* report, which
        # stays RUNNING across AM retries (YARN only finalizes at app end).
        # Without this, the client's poll can observe the transient FAILED
        # between a crashed attempt and _reset_session() and signal finish,
        # suppressing the retry (race window is up to one monitor interval).
        retries = self.conf.get_int("tony.coordinator.retry-count", 0)
        if status == SessionStatus.FAILED and self.attempt < retries \
                and not self.killed.is_set():
            return {
                "status": SessionStatus.RUNNING.value,
                "reason": f"attempt {self.attempt} failed "
                          f"({self.session.failure_reason}); retrying",
                "session_id": self.session.session_id,
                "attempt": self.attempt,
                "tensorboard_url": self.tensorboard_url,
                "phase": self.provisioner.state,
            }
        return {
            "status": status.value,
            "reason": self.session.failure_reason,
            "session_id": self.session.session_id,
            "attempt": self.attempt,
            "tensorboard_url": self.tensorboard_url,
            # provisioning state (CREATING/WAITING/READY/...) so the client
            # can show why no tasks exist yet during slice allocation
            "phase": self.provisioner.state,
        }


def main(argv: list[str] | None = None) -> int:
    """Ref: ApplicationMaster.main :332."""
    parser = argparse.ArgumentParser(prog="tony-tpu-coordinator")
    parser.add_argument("--conf", required=True, help="path to tony-final.json")
    parser.add_argument("--app-id", required=True)
    parser.add_argument("--job-dir", required=True)
    args = parser.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s")
    conf = TonyConf.from_final(args.conf)
    coord = Coordinator(conf, args.app_id, args.job_dir)
    ok = coord.run()
    return C.EXIT_SUCCESS if ok else C.EXIT_FAIL


if __name__ == "__main__":
    raise SystemExit(main())
