"""Headline benchmarks. Prints ONE JSON line:

  {"metric", "value", "unit", "vs_baseline", "extras": {...}}

Measurements (BASELINE.md rows 2-3 + VERDICT next-steps, r1-r3):

1. ResNet-50 images/sec/chip, tony-tpu Trainer vs the STRONGEST native
   JAX step (donated buffers, threaded state, matching bf16 compute,
   >=100 timed steps on TPU). vs_baseline = native_time / framework_time
   (>= 0.9 meets the north star).

2. Flagship transformer (386M decoder, seq 2048: pallas flash attention,
   scan_layers + remat, bf16 compute, chunked CE) tokens/sec/chip +
   PaLM-style model-FLOPs MFU through Trainer.build_step (docs/PERF.md
   roofline), and the same step through train.fit to show loop overhead
   ~= 0 (async metric sinks: no sync on the step path).

3. Kernel A/Bs (TPU-only): pallas flash vs XLA attention fwd+bwd with a
   measured block-size sweep; banded sliding-window vs full causal; int8
   weight-only dequant-matmul vs bf16 at decode shapes.

4. KV-cache decode throughput + HBM-bandwidth utilization (prefill
   subtracted) — the serving-path roofline. Plus the serving-layer
   data: continuous-vs-fixed batching (extras.serving), the gateway
   front door's concurrent-client throughput + p50/p99 TTFT at 1 vs 2
   replicas (extras.gateway), the prefix KV-cache store's prefill
   dispatches / TTFT on a shared-system-prompt workload, on vs off
   (extras.prefix), speculative decoding's decode-dispatch
   reduction + TPOT on an extractive/repetitive workload, on vs off
   (extras.spec), the paged KV cache's equal-batch overhead /
   equal-HBM batch-growth throughput / prefix-hit bytes-moved, paged
   vs fixed-shape rows (extras.paged), and the wall-clock cost of a
   mid-run replica death
   under the gateway's token-exact failover, faulted vs control
   (extras.faults), the observability layer's TPOT overhead
   (request tracing + dispatch timeline on vs off) with the new
   per-dispatch steady/compile cost split (extras.obs), and the
   goodput ledger datum — decode HBM-BW% from the product's analytic
   cost model + the wall-clock bucket decomposition at the
   serving-scale shape, with the overhead gate re-run goodput+alerts
   armed (extras.goodput), and the live-migration datum — drain-latency
   A/B of a planned replica exit with a stream in flight (freeze +
   owner swap vs decode-to-completion) plus the owner swap's
   bytes-not-moved against a timed gather_pages copy (extras.migrate).

5. Launch -> first-step latency through the REAL submit path
   (TonyClient -> coordinator -> agent -> payload jit step) on the mini
   cluster, cold AND warm (persistent compile cache) — reference cadence
   analogs: client poll 1 s TonyClient.java:1035, AM monitor 5 s
   ApplicationMaster.java:711.

Resilience: the platform probe retries with backoff; a CPU fallback
embeds the last-known-good on-chip artifact (BENCH_LKG_TPU.json) and
re-execs onto TPU if the tunnel recovers by the end of the run.

Off-TPU (CI boxes) every piece shrinks so the line still prints quickly.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import optax

# honor an env request for the CPU platform even under this image's TPU
# sitecustomize, which overrides jax_platforms at interpreter startup
_env_platforms = os.environ.get("JAX_PLATFORMS", "")
if _env_platforms and "axon" not in _env_platforms:
    jax.config.update("jax_platforms", _env_platforms)

REPO_DIR = os.path.dirname(os.path.abspath(__file__))
# last-known-good on-chip artifact: written after every TPU run, embedded
# into the line when a flaky tunnel forces a CPU fallback (VERDICT r2 #1a)
LKG_PATH = os.path.join(REPO_DIR, "BENCH_LKG_TPU.json")


def _probe_platform(timeout_s: float) -> str:
    """One subprocess platform probe; '' on timeout/failure."""
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s)
        return probe.stdout.strip().splitlines()[-1] \
            if probe.returncode == 0 and probe.stdout.strip() else ""
    except (subprocess.SubprocessError, OSError):
        return ""


def _platform() -> str:
    """Resolve the backend WITHOUT risking a hang: the tunneled TPU
    backend can block forever at init when the tunnel is down (observed
    >1 h), and jax.devices() in-process would take the backend lock with
    it. Probe in a SUBPROCESS with a deadline, RETRYING with backoff — a
    momentary tunnel blip must not demote a whole round's artifact to CPU
    (VERDICT r2 #1a). Only when every attempt fails is this process
    pinned to CPU (before any backend init) so the bench always prints
    its line. Must be called before any other jax backend use."""
    env_p = os.environ.get("JAX_PLATFORMS", "")
    if env_p and "axon" not in env_p:
        # an explicit non-TPU request needs no probe (and the probe child
        # would ignore it anyway: sitecustomize re-pins jax_platforms at
        # interpreter startup, dialing the tunnel regardless)
        return env_p.split(",")[0]
    # default worst case = 2 x 150s probes + 20s backoff ~= 320s, close
    # to the r2-proven single 240s probe: a down tunnel must not balloon
    # the driver's bench run past its patience (knobs raise it)
    tries = max(1, int(os.environ.get("TONY_BENCH_PROBE_RETRIES", "2")))
    timeout = float(os.environ.get("TONY_BENCH_PROBE_TIMEOUT", "150"))
    backoff = (20.0, 60.0)  # between attempts; the probe itself waits too
    for attempt in range(tries):
        if attempt:
            time.sleep(backoff[min(attempt - 1, len(backoff) - 1)])
        platform = _probe_platform(timeout)
        if platform:
            return platform
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
    return "cpu"


def _git_commit() -> str:
    try:
        out = subprocess.run(["git", "-C", REPO_DIR, "rev-parse", "HEAD"],
                             capture_output=True, text=True, timeout=10)
        return out.stdout.strip()[:12]
    except (subprocess.SubprocessError, OSError):
        return ""


def save_lkg(line: dict) -> None:
    """Persist an on-chip run (numbers + timestamp + commit) so later
    CPU-fallback runs still carry TPU evidence with provenance."""
    import datetime

    doc = {
        "timestamp": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
        "commit": _git_commit(),
        "source": "bench.py on-chip run",
        "line": line,
    }
    tmp = LKG_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
    os.replace(tmp, LKG_PATH)


def load_lkg() -> dict | None:
    try:
        with open(LKG_PATH) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


# peak bf16 FLOP/s and HBM bandwidth per chip: tables AND the name
# resolution SINGLE-SOURCED from the goodput cost model
# (obs/goodput.py) so the product sensor and the bench can never
# disagree about a chip's roofline
from tony_tpu.obs.goodput import HBM_BW_TABLE as _HBM_BW  # noqa: E402
from tony_tpu.obs.goodput import PEAK_BF16_TABLE as _PEAK_BF16  # noqa: E402
from tony_tpu.obs.goodput import chip_lookup as _chip_lookup  # noqa: E402


def peak_flops_per_chip() -> float:
    return _chip_lookup(_PEAK_BF16)


def hbm_bw_per_chip() -> float:
    return _chip_lookup(_HBM_BW)


def compiled_flops(jitted, *args) -> float:
    """Whole-step FLOPs from XLA's compiled cost analysis (0 if the
    backend doesn't report them)."""
    return compiled_analyses(jitted, *args)[0]


def compiled_analyses(jitted, *args) -> tuple[float, int]:
    """(flops, hbm_peak_bytes) from ONE lower+compile — re-tracing a
    flagship-sized step twice for two analyses costs minutes over the
    tunnel. Zeros where the backend reports nothing."""
    from tony_tpu.profiler.xplane import memory_bytes_of_compiled

    try:
        compiled = jitted.lower(*args).compile()
    except Exception:
        if os.environ.get("TONY_BENCH_DEBUG") == "1":
            import traceback

            traceback.print_exc()
        return 0.0, 0
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        flops = float(ca.get("flops", 0.0) or 0.0)
    except Exception:
        flops = 0.0
    return flops, memory_bytes_of_compiled(compiled)


def fresh(tree):
    """Deep-copy a pytree's arrays. Donated steps consume their input
    buffers, and jax.device_put aliases (does not copy) arrays already
    placed with the target sharding — each A/B side must own its
    buffers or one side's donation deletes the other's state."""
    return jax.tree.map(lambda a: jnp.array(a), tree)


def timed_round(step, carry, steps: int):
    """Time ``steps`` state-THREADED calls (carry consumed/donated and
    replaced each call — no reuse of stale buffers, no constant-folding
    of a repeated identical call). The closing barrier is a SCALAR HOST
    FETCH, not block_until_ready: on the tunneled axon platform
    block_until_ready can resolve before the queued work actually ran
    (measured: 20 8k matmuls "done" in 1 ms = 35 PFLOP/s on a 197-TFLOP
    chip), while a device->host value cannot be faked; one scalar fetch
    per round amortizes over the steps."""
    t0 = time.perf_counter()
    out = None
    for _ in range(steps):
        carry, out = step(carry)
    float(jnp.asarray(out).reshape(-1)[0])
    return time.perf_counter() - t0, carry


def ab_rounds(native_step, nat_carry, fw_step, fw_carry, steps: int,
              repeats: int):
    """Interleaved A/B: each round times native then framework
    back-to-back so device-speed drift slower than a round cancels in the
    per-round ratio; medians reported."""
    rounds = []
    for _ in range(repeats):
        t_nat, nat_carry = timed_round(native_step, nat_carry, steps)
        t_fw, fw_carry = timed_round(fw_step, fw_carry, steps)
        rounds.append((t_nat, t_fw))
    t_nat = sorted(t for t, _ in rounds)[len(rounds) // 2]
    t_fw = sorted(t for _, t in rounds)[len(rounds) // 2]
    ratios = sorted(tn / tf for tn, tf in rounds)
    return t_nat, t_fw, ratios[len(ratios) // 2]


# ---------------------------------------------------------------- resnet


def bench_resnet(on_tpu: bool) -> dict:
    import functools

    from tony_tpu.models import ResNet18, ResNet50
    from tony_tpu.parallel import data_parallel_mesh
    from tony_tpu.parallel.sharding import batch_sharding
    from tony_tpu.train import Trainer
    from jax.sharding import NamedSharding, PartitionSpec as P

    if on_tpu:
        # batch tunable for on-chip experiments; 128 is the known-good
        # v5e default (r2: 30.7% MFU) — a blind bump could OOM the
        # headline bench, so bigger batches are opt-in
        batch = int(os.environ.get("TONY_BENCH_RESNET_BATCH", "128"))
        model, size = ResNet50(num_classes=1000), 224
        steps, repeats = 100, 5
        compute = jnp.bfloat16
    else:
        model, batch, size = ResNet18(num_classes=100, num_filters=16), 16, 32
        steps, repeats = 8, 5  # the 1-core CI box jitters; median of 5
        # interleaved rounds keeps the proxy ratio within a few percent
        compute = None

    rng = jax.random.PRNGKey(0)
    images = jnp.ones((batch, size, size, 3), jnp.float32)
    labels = jnp.zeros((batch,), jnp.int32)
    variables = model.init(rng, images, train=False)
    params, batch_stats = variables["params"], variables.get("batch_stats", {})
    tx = optax.sgd(0.1, momentum=0.9)

    def cast(tree):
        if compute is None:
            return tree
        return jax.tree.map(
            lambda a: a.astype(compute)
            if jnp.issubdtype(a.dtype, jnp.floating) else a, tree)

    # ---- native step: the STRONGEST hand-rolled baseline — donated
    # buffers, bf16 compute mirroring Trainer.compute_dtype (fp32 master
    # params, cast inside the differentiated fn so grads come back fp32)
    def native_loss(p, bs, x, y):
        logits, new_state = model.apply(
            {"params": cast(p), "batch_stats": bs}, cast(x), train=True,
            mutable=["batch_stats"])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        onehot = jax.nn.one_hot(y, logp.shape[-1])
        return -jnp.mean(jnp.sum(onehot * logp, axis=-1)), \
            new_state["batch_stats"]

    @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
    def native_step(p, bs, o, x, y):
        (loss, new_bs), grads = jax.value_and_grad(
            native_loss, has_aux=True)(p, bs, x, y)
        updates, o = tx.update(grads, o, p)
        return optax.apply_updates(p, updates), new_bs, o, loss

    # whole-step FLOPs before any donation consumes the buffers
    flops_step = compiled_flops(native_step, params, batch_stats,
                                tx.init(params), images, labels)

    # ---- framework step: tony_tpu Trainer, same precision, donated ----
    mesh = data_parallel_mesh()

    def apply_fn(state_params, train_batch):
        logits, _ = model.apply(
            {"params": state_params, "batch_stats": train_batch["bs"]},
            train_batch["x"], train=True, mutable=["batch_stats"])
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        onehot = jax.nn.one_hot(train_batch["y"], logp.shape[-1])
        return -jnp.mean(jnp.sum(onehot * logp, axis=-1))

    trainer = Trainer(mesh=mesh, apply_fn=apply_fn, optimizer=tx,
                      donate=True, compute_dtype=compute)
    state = trainer.init_state(params)
    b_sh = batch_sharding(mesh)
    # bs rides in the batch tree, so it must carry the batch sharding the
    # step declares for every batch leaf (the global [C] view is the same;
    # on one chip the layouts coincide, on a virtual multi-device mesh a
    # replicated placement is a hard in_shardings mismatch)
    train_batch = {
        "x": jax.device_put(images, b_sh),
        "y": jax.device_put(labels, b_sh),
        "bs": jax.device_put(batch_stats, b_sh),
    }
    step_fn, placed = trainer.build_step(state)

    def fw_step(carry):
        new_state, metrics = step_fn(carry, train_batch)
        return new_state, metrics["loss"]

    def nat_step(carry):
        p, bs, o = carry
        p, bs, o, loss = native_step(p, bs, o, images, labels)
        return (p, bs, o), loss

    nat_carry = (fresh(params), fresh(batch_stats), tx.init(params))
    # warmup compiles both programs and primes the threading
    _, nat_carry = timed_round(nat_step, nat_carry, 1)
    _, placed = timed_round(fw_step, placed, 1)
    t_nat, t_fw, ratio = ab_rounds(nat_step, nat_carry, fw_step, placed,
                                   steps, repeats)

    n_chips = max(1, jax.device_count())
    fw_ips = batch * steps / t_fw
    peak = peak_flops_per_chip() if on_tpu else 0.0  # env names the chip
    # even when this process fell back to CPU; no peak -> no MFU claim
    mfu = (flops_step * steps / t_fw) / (peak * n_chips) if peak else 0.0
    return {
        "images_per_sec_per_chip": round(fw_ips / n_chips, 2),
        "vs_native": round(ratio, 4),
        "native_images_per_sec_per_chip": round(
            batch * steps / t_nat / n_chips, 2),
        "flops_per_step": flops_step,
        "mfu": round(mfu, 4),
        "timed_steps": steps,
    }


# ----------------------------------------------------------- transformer


def flagship_lm_setup(on_tpu: bool):
    """The flagship LM training setup — model, trainer, batch geometry —
    shared by bench_transformer and tools/trace_buckets.py so the env
    knobs (TONY_BENCH_LM_*) and config live in ONE place and the bucket
    tables always describe the benchmarked step.

    Returns (model, trainer, batch, accum, seq, steps)."""
    from tony_tpu.models import Transformer, TransformerConfig
    from tony_tpu.ops import chunked_cross_entropy
    from tony_tpu.parallel import data_parallel_mesh
    from tony_tpu.train import Trainer

    if on_tpu:
        # flagship: 386M-param decoder (28 x d1024/ff4096 + 33.6M tied
        # embedding), seq 2048, bf16, pallas flash attention, unrolled
        # layer stack + attn_saved remat
        # (VERDICT r2 #1b: >=350M params, seq >=2k, remat-tuned).
        # 8 heads x head_dim 128 (not 16 x 64): the flash kernels are
        # VPU-bound on the softmax passes, and halving the score-element
        # count at equal d_model halves attention kernel time (measured
        # 2.1x on v5e, round 4) at identical parameter count.
        # scan_layers=False: the scan machinery (residual stacking via
        # dynamic-update-slice, per-layer param slicing) measured ~45 ms
        # of a 257 ms device step; unrolled runs 235 ms vs 261 ms. The
        # one-time unrolled compile (~4 min over the tunnel) amortizes
        # through the persistent compile cache.
        cfg = TransformerConfig(
            vocab_size=32768, d_model=1024, n_layers=28, n_heads=8,
            d_ff=4096, max_seq_len=2048, attention_backend="pallas",
            attention_block_size=int(
                os.environ.get("TONY_BENCH_LM_BLOCK", "512")),
            attention_block_k=int(
                os.environ.get("TONY_BENCH_LM_BLOCK_K", "1024")),
            scan_layers=os.environ.get("TONY_BENCH_LM_SCAN", "0") == "1",
            remat=True,
            remat_policy=os.environ.get("TONY_BENCH_LM_REMAT",
                                        "attn_saved"))
        # microbatch 4: the remat policies that keep activations (dots /
        # attn_saved) fit v5e's 16 GB at batch 4; full remat fit batch 8
        # at 26% MFU — slower than batch 4 with saved activations.
        # accum scans microbatches of batch/accum inside the step:
        # activation footprint of ONE microbatch, optimizer + carry
        # amortized over the whole global batch — measured r5 ladder
        # 50.7% (accum 1) -> 51.7 (2) -> 53.2 (4) -> 54.0 (8) ->
        # 54.2 (16); global batch 64 x 2048 tokens is a standard LLM
        # training batch, recorded in the config string
        # TONY_BENCH_LM_BATCH is the GLOBAL batch; accum derives from it
        # and the microbatch size (TONY_BENCH_LM_MICRO, default 4) so
        # r4-era overrides like BATCH=4 still run (accum=1). An explicit
        # TONY_BENCH_LM_ACCUM wins when set.
        batch = int(os.environ.get("TONY_BENCH_LM_BATCH", "64"))
        micro = int(os.environ.get("TONY_BENCH_LM_MICRO", "4"))
        accum = int(os.environ.get("TONY_BENCH_LM_ACCUM",
                                   str(max(1, batch // micro))))
        seq = 2048
        # steps scale down with accum (stability comes from tokens
        # timed, not step count): accum 16 -> 6 steps x 3 rounds x
        # ~3.3 s/step of device time per round
        steps = max(6, 32 // max(accum, 1))
        compute = jnp.bfloat16  # MXU-native; fp32 master params in Trainer
    else:
        cfg = TransformerConfig(
            vocab_size=256, d_model=64, n_layers=2, n_heads=4, d_ff=128,
            max_seq_len=128, attention_backend="blockwise",
            attention_block_size=32)
        # batch must divide over however many (virtual) devices CI forces
        batch, seq, steps = max(2, jax.device_count()), 64, 10
        accum = 1
        compute = None

    model = Transformer(cfg)

    def apply_fn(p, train_batch):
        hidden = model.apply(p, train_batch["tokens"], return_hidden=True)
        # bf16 logit matmul (fp32 accumulation) on TPU: the fp32 head ran
        # several times below MXU rate and dominated the step (round 4)
        return chunked_cross_entropy(
            hidden[:, :-1], p["params"]["embedding"],
            train_batch["tokens"][:, 1:],
            chunk_size=int(os.environ.get("TONY_BENCH_LM_CE_CHUNK",
                                          "2048")),
            compute_dtype=compute)

    # fused pallas AdamW (r5): one read+write pass over g/p/mu/nu vs the
    # optax path's materialized updates tree — the optimizer bucket was
    # 21 ms of the 220 ms r4 step at 71% of the bandwidth roofline
    if os.environ.get("TONY_BENCH_LM_FUSED_ADAMW", "1") == "1":
        from tony_tpu.train import FusedAdamW

        optimizer = FusedAdamW(3e-4)
    else:
        optimizer = optax.adamw(3e-4)
    mesh = data_parallel_mesh()
    trainer = Trainer(mesh=mesh, apply_fn=apply_fn,
                      optimizer=optimizer, donate=True,
                      compute_dtype=compute, accum_steps=accum)
    return model, trainer, batch, accum, seq, steps


def bench_transformer(on_tpu: bool) -> dict:
    from tony_tpu.parallel.sharding import batch_sharding
    from tony_tpu.train import fit

    model, trainer, batch, accum, seq, steps = flagship_lm_setup(on_tpu)
    cfg = model.cfg
    optimizer = trainer.optimizer
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0,
                                cfg.vocab_size, jnp.int32)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, seq), jnp.int32))
    n_params = sum(x.size for x in jax.tree.leaves(params))
    # park the fp32 init params on HOST until the fit() phase: at
    # flagship scale they are ~1.5 GB of HBM the activation-saving remat
    # configs need (the optimizer keeps its own master copy)
    params = jax.device_get(params)
    # fresh copy: build_step's device_put aliases same-device arrays, and
    # the donating timed loop would otherwise consume `params` needed by
    # the fit() comparison below
    state = trainer.init_state(fresh(params))
    step_fn, placed = trainer.build_step(state)
    train_batch = {"tokens": jax.device_put(tokens,
                                            batch_sharding(trainer.mesh))}
    # XLA-executed FLOPs (includes remat recompute; 0 when the backend
    # reports no cost analysis — mfu_hw is then omitted rather than
    # faked) + compile-time HBM peak of the jitted step, from ONE
    # lower+compile (the tunneled backend reports no runtime
    # memory_stats — VERDICT r4 #5: the batch-4-vs-8 decision now
    # carries a measured number, not a hand estimate)
    flops_ca, hbm_est = compiled_analyses(step_fn, placed, train_batch)
    # XLA's cost analysis counts a while-loop body ONCE; the microbatch
    # scan executes it `accum` times per step — scale so mfu_hw stays a
    # comparable (if still pallas-blind) diagnostic across accum configs
    flops_ca *= max(accum, 1)

    # MODEL FLOPs (PaLM-style MFU accounting): 6·N per token fwd+bwd for
    # the dense stack + causal attention matmuls (fwd 4·b·s²·d, bwd 2x,
    # halved for causality -> 6·b·s²·d·L). The compiled cost analysis is
    # kept as a diagnostic, but with remat on it counts the RECOMPUTED
    # forward too and would overstate MFU.
    flops_model = 6.0 * n_params * batch * seq \
        + 6.0 * batch * seq * seq * cfg.d_model * cfg.n_layers

    def fw_step(carry):
        new_state, metrics = step_fn(carry, train_batch)
        return new_state, metrics["loss"]

    _, placed = timed_round(fw_step, placed, 2)  # compile + prime
    rounds = []
    for _ in range(3):  # median round: single-shot jitters on shared CPUs
        t_round, placed = timed_round(fw_step, placed, steps)
        rounds.append(t_round)
    t_step = sorted(rounds)[1]

    # the same step through train.fit: loop overhead must be ~0. fit()'s
    # metric fetches are async (emitted one boundary late), so with three
    # log windows the sinks fire at: boundary 2, boundary 3, and the
    # end-of-loop flush. stamps[1]-stamps[0] spans exactly the steady-
    # state window between boundaries 2 and 3 — fit's one-time recompile
    # lands in window 1, and no synchronous fetch sits inside the
    # measured window at all.
    window = max(steps // 2, 10)  # short windows on the CPU proxy
    # measure OS jitter, not loop overhead
    # five steady-state windows, scored by MINIMUM: box load (a shared
    # 1-core proxy, background pytest) only ever ADDS time to a window,
    # so the min is the load-robust overhead estimator — r2/r3 artifacts
    # swung 0.978 -> 1.045 on a single window (VERDICT r3 weak #2)
    n_windows = 5
    # sinks first fire at boundary 2, so K*window steps give K-2 interior
    # deltas: K = n_windows + 2 delivers the promised five
    fit_steps = (n_windows + 2) * window

    def batches():
        for _ in range(fit_steps):
            yield train_batch

    # release the timed-phase optimizer state BEFORE fit() builds its
    # own: at flagship scale two live TrainStates (master + both adam
    # moments each) are ~8.6 GB and push the dots remat config over HBM
    del placed, state
    stamps: list[float] = []
    fit(trainer, fresh(params), batches(), num_steps=fit_steps,
        log_every=window,
        metric_sinks=[lambda s, m: stamps.append(time.perf_counter())])
    # interior windows only: window 1 absorbs fit's one-time compile,
    # the final stamp is the end-of-loop flush (teardown rides on it)
    deltas = [b - a for a, b in zip(stamps[:-2], stamps[1:-1])]
    t_fit_step = min(deltas) / window if deltas else float("nan")

    try:
        hbm_peak = jax.local_devices()[0].memory_stats() \
            .get("peak_bytes_in_use", 0)
    except Exception:
        hbm_peak = 0
    hbm_peak = hbm_peak or hbm_est  # runtime stats when the backend has
    # them; the compile-time reservation otherwise (axon reports none)
    n_chips = max(1, jax.device_count())
    tok_s = batch * seq * steps / t_step
    peak = peak_flops_per_chip() if on_tpu else 0.0
    mfu = (flops_model * steps / t_step) / (peak * n_chips) if peak else 0.0
    # hardware utilization over EXECUTED flops (incl. remat recompute);
    # only meaningful when the backend actually reported them
    mfu_hw = (flops_ca * steps / t_step) / (peak * n_chips) \
        if peak and flops_ca > 0 else 0.0
    return {
        "tokens_per_sec_per_chip": round(tok_s / n_chips, 1),
        "mfu": round(mfu, 4),
        "mfu_hw_executed": round(mfu_hw, 4),
        "model_flops_per_step": flops_model,
        "n_params": n_params,
        "seq_len": seq,
        "config": f"d{cfg.d_model}xL{cfg.n_layers}h{cfg.n_heads}"
                  f"ff{cfg.d_ff} scan={cfg.scan_layers} "
                  f"remat={cfg.remat}/{cfg.remat_policy} "
                  f"attn={cfg.attention_backend}/{cfg.attention_block_size} "
                  f"opt={'fused_adamw' if not hasattr(optimizer, 'update') else 'optax_adamw'}"
                  + (f" accum={accum}" if accum > 1 else ""),
        "batch": batch,
        "hbm_peak_gb": round(hbm_peak / 2**30, 2),
        "flops_per_step": flops_ca,
        # ~1.0 = fit() adds nothing over the raw jitted step (metric
        # fetches are async; no sync sits on the step path). Min-vs-min:
        # both sides use their fastest window, so shared-box load cancels
        # instead of landing on whichever side ran during a spike. <1.0
        # is residual noise, not real speedup.
        "fit_overhead_ratio": round(t_fit_step / (min(rounds) / steps), 4),
        "raw_step_ms": round(t_step / steps * 1e3, 3),
        "fit_step_ms": round(t_fit_step * 1e3, 3),
        "timed_steps": steps,
    }


def bench_long_seq(on_tpu: bool) -> dict:
    """Long-context training on ONE chip: the 386M flagship at seq 8k
    AND 16k with a 1024-token sliding window through the banded flash
    kernel (O(L*window) compute and HBM traffic — full causal at 8k
    would cost 4x the attention FLOPs and not fit the remat budget).
    The banded claim predicts near-flat tokens/s as seq doubles at
    fixed window (VERDICT r4 stretch #9) — the 16k point measures it.
    Single-chip long-seq is the building block under ring/ulysses sp
    (multi-chip composition is covered by the driver's dryrun)."""
    if not on_tpu:
        return {"skipped": "long-seq training bench is TPU-only"}
    if os.environ.get("TONY_BENCH_LONG_SEQ") == "0":
        return {"skipped": "TONY_BENCH_LONG_SEQ=0"}
    from tony_tpu.models import Transformer, TransformerConfig
    from tony_tpu.ops import chunked_cross_entropy
    from tony_tpu.parallel import data_parallel_mesh
    from tony_tpu.parallel.sharding import batch_sharding
    from tony_tpu.train import Trainer

    def one_point(seq: int, window: int, batch: int, steps: int,
                  remat_policy: str = "attn_saved") -> dict:
        cfg = TransformerConfig(
            vocab_size=32768, d_model=1024, n_layers=28, n_heads=8,
            d_ff=4096, max_seq_len=seq, attention_backend="pallas",
            attention_block_size=512, attention_block_k=1024,
            sliding_window=window, scan_layers=False, remat=True,
            remat_policy=remat_policy)
        model = Transformer(cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq),
                                    0, cfg.vocab_size, jnp.int32)
        params = jax.device_get(model.init(jax.random.PRNGKey(0),
                                           jnp.zeros((1, seq), jnp.int32)))
        n_params = sum(x.size for x in jax.tree.leaves(params))

        def apply_fn(p, train_batch):
            hidden = model.apply(p, train_batch["tokens"],
                                 return_hidden=True)
            # chunk 1024 (not the flagship's 2048): the seq-8k point sat
            # at 15.96/15.75 GB HBM — halving the transient logit chunk
            # (~200 MB) is what keeps attn_saved remat on the chip
            return chunked_cross_entropy(
                hidden[:, :-1], p["params"]["embedding"],
                train_batch["tokens"][:, 1:], chunk_size=1024,
                compute_dtype=jnp.bfloat16)

        mesh = data_parallel_mesh()
        trainer = Trainer(mesh=mesh, apply_fn=apply_fn,
                          optimizer=optax.adamw(3e-4), donate=True,
                          compute_dtype=jnp.bfloat16)
        state = trainer.init_state(fresh(params))
        step_fn, placed = trainer.build_step(state)
        train_batch = {"tokens": jax.device_put(tokens,
                                                batch_sharding(mesh))}

        def fw_step(carry):
            new_state, metrics = step_fn(carry, train_batch)
            return new_state, metrics["loss"]

        _, placed = timed_round(fw_step, placed, 2)
        rounds = []
        for _ in range(3):
            t_round, placed = timed_round(fw_step, placed, steps)
            rounds.append(t_round)
        t_step = sorted(rounds)[1] / steps
        # windowed attention model FLOPs: 12*b*(key visits)*d_model*L
        # for the two score/value matmuls (the causal-halving convention
        # used for full attention does not apply — a banded window is
        # not halved). Key visits = sum_i min(i+1, window)
        # = s*window - window*(window-1)/2.
        key_visits = seq * window - window * (window - 1) / 2.0
        flops_model = 6.0 * n_params * batch * seq \
            + 12.0 * batch * key_visits * cfg.d_model * cfg.n_layers
        peak = peak_flops_per_chip()
        return {
            "tokens_per_sec_per_chip": round(batch * seq / t_step, 1),
            "seq_len": seq, "window": window, "batch": batch,
            "step_ms": round(t_step * 1e3, 1),
            "mfu": round(flops_model / t_step / peak, 4) if peak else 0.0,
            "remat_policy": remat_policy,
        }

    def point_with_fallback(seq, window, batch, steps):
        # attn_saved sat at 15.96/15.75 GB at seq 8k in r5 — compiler
        # layout drift tips a borderline fit either way between rounds,
        # so fall back to the heavier-remat dots policy (~1 MFU point
        # slower, fits comfortably) rather than lose the data point.
        # The retry runs OUTSIDE the handler: the caught exception's
        # traceback frames pin the failed attempt's device state (GBs)
        # until the except block exits.
        import gc

        try:
            return one_point(seq, window, batch, steps)
        except Exception:
            pass
        gc.collect()
        return one_point(seq, window, batch, steps, remat_policy="dots")

    out = point_with_fallback(8192, 1024, 1, 20)
    if os.environ.get("TONY_BENCH_LONG_SEQ_16K", "1") == "1":
        p16 = point_with_fallback(16384, 1024, 1, 10)
        out["seq16k"] = p16
        # O(L*window): tokens/s should hold ~flat as seq doubles at
        # fixed window (the dense-stack FLOPs/token are unchanged and
        # attention FLOPs/token are window-bound)
        out["tok_s_ratio_16k_vs_8k"] = round(
            p16["tokens_per_sec_per_chip"]
            / out["tokens_per_sec_per_chip"], 3)
    return out


# --------------------------------------------------------------- decode


def _bench_eos_refill(model, params, cfg, batch) -> dict:
    """The ISSUE-13 tentpole datum: in-dispatch EOS/refill lets
    chunk_steps grow without the overshoot bucket eating the win.
    Control = the pre-freeze engine at chunk 4 (the old sweet spot —
    deeper chunks lost their gain to trimmed overshoot); treatment =
    the frozen engine at chunk 16. Same mixed-budget greedy workload,
    outputs asserted identical; reports tok/s, decode dispatches per
    1k tokens, and the goodput-ledger decomposition
    (useful/padding/overshoot/spec_rejected fractions of steady
    decode+verify time) for BOTH arms, so every future BENCH_r
    artifact decomposes the roofline gap instead of only quoting a
    tok/s."""
    import numpy as np

    from tony_tpu.serve import Request, Server

    rng = np.random.default_rng(7)
    max_len = cfg.max_seq_len
    p_len = min(16, max_len // 4)
    head = max(4, min(64, max_len - p_len - 1))
    budgets = [max(3, int(b)) for b in
               rng.integers(head // 3, head, size=batch * 2)]
    prompts = [rng.integers(1, cfg.vocab_size - 1,
                            size=p_len).tolist()
               for _ in range(batch * 2)]

    def run(in_eos: bool, chunk: int):
        server = Server(model, params, batch_size=batch, eos_id=-1,
                        chunk_steps=chunk, in_dispatch_eos=in_eos)

        def reqs():
            return [Request(list(p), n, id=i) for i, (p, n)
                    in enumerate(zip(prompts, budgets))]

        list(server.run(reqs()))   # warm pass: pays every compile
        d0 = server.dispatches
        t0 = time.perf_counter()
        out = {r.id: r.tokens for r in server.run(reqs())}
        dt = time.perf_counter() - t0
        toks = sum(len(v) for v in out.values())
        summ = server.timeline.summary()
        steady = useful = padding = overshoot = rejected = 0.0
        for kind in ("decode", "verify"):
            a = summ.get(kind)
            if not a:
                continue
            steady += a["ms"] - a["compile_ms"]
            useful += a["useful_ms"]
            padding += a["padding_ms"]
            overshoot += a["overshoot_ms"]
            rejected += a["rejected_ms"]
        steady = max(steady, 1e-9)
        return out, {
            "chunk_steps": chunk,
            "tok_s": round(toks / dt, 1),
            "decode_dispatches": server.dispatches - d0,
            "dispatches_per_1k_tokens": round(
                1e3 * (server.dispatches - d0) / max(1, toks), 2),
            "wasted_steps": server.wasted_steps,
            "frozen_steps": server.frozen_steps,
            "ledger": {
                "useful": round(useful / steady, 4),
                "padding": round(padding / steady, 4),
                "overshoot": round(overshoot / steady, 4),
                "spec_rejected": round(rejected / steady, 4),
            },
        }

    out_c, control = run(False, 4)
    out_t, treat = run(True, 16)
    return {
        "control": control,
        "treatment": treat,
        "outputs_identical": out_c == out_t,
        "tok_s_ratio": round(treat["tok_s"]
                             / max(control["tok_s"], 1e-9), 3),
        "dispatch_ratio": round(
            control["dispatches_per_1k_tokens"]
            / max(treat["dispatches_per_1k_tokens"], 1e-9), 3),
    }


def _int8_kv_flash_bytes(cfg, params, batch, cache_tokens) -> dict:
    """The bytes side of the 0.54x ``int8_kv_flash_speedup``
    regression (ISSUE-13 satellite; open since BENCH_LKG): per decode
    step, the int8-KV flash arm re-reads every parameter byte plus the
    int8 cache + fp32 scales where the bf16-einsum base reads the
    full-precision cache — the analytic ratio says whether the
    measured slowdown CAN be a bytes problem at all. Measured at the
    bench shape the ratio is < 1 (int8 strictly shrinks the step's
    read set), so the regression is a dispatch/kernel-shape problem —
    docs/PERF.md carries the verdict and the next-attempt notes."""
    param_bytes = sum(x.size * x.dtype.itemsize
                      for x in jax.tree.leaves(params))
    kvh = cfg.kv_heads
    dh = cfg.head_dim
    item = jnp.dtype(cfg.dtype).itemsize
    base_kv = 2.0 * batch * cache_tokens * kvh * dh * item
    q8_kv = 2.0 * batch * cache_tokens * kvh * dh \
        + 2.0 * batch * cache_tokens * kvh * 4  # int8 + fp32 scales
    ratio = (param_bytes + q8_kv) / (param_bytes + base_kv)
    return {
        "int8_kv_flash_bytes_ratio": round(ratio, 4),
        "int8_kv_flash_verdict": "dispatch" if ratio <= 1.0
        else "bytes",
    }


def bench_decode(on_tpu: bool) -> dict:
    """KV-cache autoregressive decode throughput on the flagship decoder
    (the serving path: prefill + lax.scan decode under one jit).

    Runs un-gated (VERDICT r2 #2): the persistent compilation cache
    enabled in main() bounds the tunneled backend's >15-min decode
    compile to ONE cold run ever — every later process loads the
    serialized executable. TONY_BENCH_DECODE=0 skips explicitly when a
    cold cache + a dead-slow tunnel make even that one compile
    unaffordable."""
    from tony_tpu.models import Transformer, TransformerConfig, generate

    if on_tpu and os.environ.get("TONY_BENCH_DECODE") == "0":
        return {"skipped": "TONY_BENCH_DECODE=0"}
    if on_tpu:
        # UNROLLED layers (the serving default, and what checkpoint
        # imports produce): under scan_layers the stacked per-layer KV
        # cache shuttles ~6 MB of dynamic-slice/update-slice copies per
        # layer per token — measured 2.28 ms/token scanned vs 1.08
        # unrolled (2.1x) at this config. The decode program compiles
        # per-layer but is small, and the persistent cache bounds it to
        # one cold compile ever.
        cfg = TransformerConfig(
            vocab_size=32768, d_model=768, n_layers=12, n_heads=12,
            d_ff=3072, max_seq_len=512, scan_layers=False)
        batch, prompt_len, new = 8, 128, 256
    else:
        cfg = TransformerConfig(
            vocab_size=256, d_model=64, n_layers=2, n_heads=4, d_ff=128,
            max_seq_len=64)
        batch, prompt_len, new = 2, 16, 16
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, prompt_len), jnp.int32))["params"]
    if on_tpu:
        # bf16 param storage — the serving config (generate --dtype
        # bf16): decode re-reads every parameter byte per token, and
        # fp32 storage would double that traffic (r4: fp32 measured
        # 3.5k tok/s where bf16 reaches ~2x)
        params = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
    prompt = jax.random.randint(jax.random.PRNGKey(2), (batch, prompt_len),
                                0, cfg.vocab_size, jnp.int32)
    out = generate(model, params, prompt, max_new_tokens=new)  # compile
    float(jnp.asarray(out).reshape(-1)[0])
    t0 = time.perf_counter()
    out = generate(model, params, prompt, max_new_tokens=new)
    float(jnp.asarray(out).reshape(-1)[0])
    dt = time.perf_counter() - t0
    result = {
        "decode_tokens_per_sec": round(batch * new / dt, 1),
        "per_token_latency_ms": round(dt / new * 1e3, 3),
        "batch": batch, "new_tokens": new,
    }
    # ISSUE-13 satellites: (a) the serving-engine in-dispatch-EOS A/B
    # with the goodput-ledger decomposition every future BENCH_r
    # artifact carries, (b) the analytic bytes side of the 0.54x
    # int8_kv_flash regression (bytes-vs-dispatch verdict)
    try:
        result["eos_refill"] = _bench_eos_refill(model, params, cfg,
                                                 batch)
    except Exception as e:  # noqa: BLE001 — keep the core datum alive
        result["eos_refill"] = {"error": f"{type(e).__name__}: {e}"}
    result.update(_int8_kv_flash_bytes(cfg, params, batch,
                                       prompt_len + new // 2))
    bw = hbm_bw_per_chip() if on_tpu else 0.0
    if bw:
        # decode roofline: each step re-reads every parameter byte once
        # (amortized over the batch); utilization = achieved param
        # traffic / peak HBM bandwidth. The compute-MFU analog for the
        # serving path — near 1.0 means the decode loop is as fast as
        # the memory system allows at this batch size. The prefill pass
        # is EXCLUDED: a max_new_tokens=1 run (prefill + one step) is
        # subtracted so only true decode steps divide the wall time.
        one = generate(model, params, prompt, max_new_tokens=1)  # compile
        float(jnp.asarray(one).reshape(-1)[0])
        t1 = time.perf_counter()
        one = generate(model, params, prompt, max_new_tokens=1)
        float(jnp.asarray(one).reshape(-1)[0])
        dt_prefill = time.perf_counter() - t1
        decode_dt = max(dt - dt_prefill, 1e-9)
        param_bytes = sum(
            x.size * x.dtype.itemsize for x in jax.tree.leaves(params))
        result["params_bytes"] = param_bytes
        result["hbm_bw_utilization"] = round(
            ((new - 1) / decode_dt) * param_bytes / bw, 4)
    if on_tpu:
        # A/B the decode-path kernels (docs/PERF.md "next lever", landed
        # r4): pallas flash-decode, then flash + int8 KV cache. Compiled
        # kernels only make sense on the chip; CPU would time the pallas
        # interpreter (tests pin exactness there instead).
        import dataclasses

        def _timed_generate(m, p=None, nt=None):
            """(device_s, wall_s) of one full generate dispatch chain.
            Device-busy from an xplane trace is the primary (the ~4.5 ms
            tunnel launch overhead amortizes over a whole decode but
            still jittered wall ratios); wall is the cross-check."""
            from tony_tpu.profiler import trace_device_ms

            p = prompt if p is None else p
            nt = new if nt is None else nt
            out = generate(m, params, p, max_new_tokens=nt)  # compile
            float(jnp.asarray(out).reshape(-1)[0])
            t = time.perf_counter()
            out = generate(m, params, p, max_new_tokens=nt)
            float(jnp.asarray(out).reshape(-1)[0])
            wall = time.perf_counter() - t
            dev_ms = trace_device_ms(
                lambda: generate(m, params, p, max_new_tokens=nt),
                steps=1)
            return (dev_ms / 1e3 if dev_ms else wall), wall

        dev_base, _ = _timed_generate(model)
        # the RECOMMENDED int8-KV serving path (r5 finding): einsum
        # decode attention over the int8 cache — XLA fuses the dequant
        # into the attention einsum and runs at the HBM roofline
        # (measured standalone: 12.5 vs 19.2 us at cache 512, 1.5x),
        # which no pallas kernel can beat (both are bandwidth-bound)
        dev_e8, wall_e8 = _timed_generate(Transformer(dataclasses.replace(
            cfg, kv_cache_quant=True)))
        result["int8_kv_speedup"] = round(dev_base / dev_e8, 3)
        result["int8_kv_speedup_wall"] = round(dt / wall_e8, 3)
        # the pallas flash-decode variants, kept HONESTLY: on this
        # backend XLA's fused decode attention wins at every cache
        # length (see docs/PERF.md r5) — these exist for the regimes
        # XLA spills (scores past VMEM at very long cache) and as the
        # kernel-form reference
        dev_flash, wall_flash = _timed_generate(Transformer(
            dataclasses.replace(cfg, decode_attention="flash")))
        result["flash_decode_speedup"] = round(dev_base / dev_flash, 3)
        result["flash_decode_speedup_wall"] = round(dt / wall_flash, 3)
        dev_q8, wall_q8 = _timed_generate(Transformer(dataclasses.replace(
            cfg, decode_attention="flash", kv_cache_quant=True)))
        result["int8_kv_flash_speedup"] = round(dev_base / dev_q8, 3)
        result["int8_kv_flash_speedup_wall"] = round(dt / wall_q8, 3)
        # long-context regime (the one the kernels exist for: cache
        # bytes rival parameter bytes). Measured r4 at cache 3584+:
        # flash 1.02x einsum, flash+int8 KV 1.21x — versus 0.72x/0.81x
        # at cache 512, where XLA's fused small-score path wins.
        if os.environ.get("TONY_BENCH_DECODE_LONG", "1") == "1":
            cfg_l = dataclasses.replace(cfg, max_seq_len=4096)
            prompt_l = jax.random.randint(
                jax.random.PRNGKey(3), (4, 3584), 0, cfg.vocab_size,
                jnp.int32)
            new_l = 128

            dev_l, _ = _timed_generate(Transformer(cfg_l), prompt_l, new_l)
            dev_l_e8, _ = _timed_generate(Transformer(dataclasses.replace(
                cfg_l, kv_cache_quant=True)), prompt_l, new_l)
            dev_l_q8, _ = _timed_generate(Transformer(dataclasses.replace(
                cfg_l, decode_attention="flash", kv_cache_quant=True)),
                prompt_l, new_l)
            result["long_ctx_cache_len"] = 3584
            result["long_ctx_int8_kv_speedup"] = round(
                dev_l / dev_l_e8, 3)
            result["long_ctx_int8_kv_flash_speedup"] = round(
                dev_l / dev_l_q8, 3)
    return result


def bench_decode_1b(on_tpu: bool) -> dict:
    """The serving claims at the scale they are made for (VERDICT r4 #3):
    a ~1B-parameter decoder where PARAMETER BYTES dominate decode — the
    regime docs/PERF.md's rooflines assert (bf16 halves per-token latency
    vs fp32; weight-only int8 nearly halves it again; the loop runs at a
    meaningful fraction of HBM peak at batch 8). The 55M toy bench above
    is per-step-overhead-bound and cannot show any of this.

    Params are random-initialized ON DEVICE (no checkpoint transfer over
    the tunnel) and int8 conversion runs device-side too
    (quantize_for_serving(on_device=True)). TPU-only; skip with
    TONY_BENCH_DECODE_1B=0 when a cold compile cache makes the three
    decode programs (fp32/bf16/int8, ~20-layer unrolled) unaffordable."""
    if not on_tpu:
        return {"skipped": "1B decode bench is TPU-only"}
    if os.environ.get("TONY_BENCH_DECODE_1B", "1") == "0":
        return {"skipped": "TONY_BENCH_DECODE_1B=0"}
    import gc

    from tony_tpu.models import Transformer, TransformerConfig, generate
    from tony_tpu.models.quantize import quantize_for_serving

    # ~0.99B params: 67M tied embedding + 20 x 46M (d2048, GQA 16q/8kv
    # x128, ff8192). GQA is the serving standard and shrinks the cache.
    cfg = TransformerConfig(
        vocab_size=32768, d_model=2048, n_layers=20, n_heads=16,
        n_kv_heads=8, d_ff=8192, max_seq_len=512, scan_layers=False)
    batch = int(os.environ.get("TONY_BENCH_DECODE_1B_BATCH", "8"))
    prompt_len, new = 128, 128
    model = Transformer(cfg)
    params = jax.jit(
        lambda key: model.init(key, jnp.zeros((1, prompt_len), jnp.int32))
    )(jax.random.PRNGKey(0))["params"]
    n_params = sum(x.size for x in jax.tree.leaves(params))
    prompt = jax.random.randint(jax.random.PRNGKey(2), (batch, prompt_len),
                                0, cfg.vocab_size, jnp.int32)
    bw = hbm_bw_per_chip()

    def decode_ms_per_tok(m, p):
        """Prefill-subtracted per-token latency (see bench_decode)."""
        def run(nt):
            out = generate(m, p, prompt, max_new_tokens=nt)  # compile
            float(jnp.asarray(out).reshape(-1)[0])
            t0 = time.perf_counter()
            out = generate(m, p, prompt, max_new_tokens=nt)
            float(jnp.asarray(out).reshape(-1)[0])
            return time.perf_counter() - t0

        dt_full, dt_prefill = run(new), run(1)
        return max(dt_full - dt_prefill, 1e-9) / (new - 1) * 1e3

    out = {"n_params": n_params, "batch": batch,
           "config": f"d{cfg.d_model}xL{cfg.n_layers}"
                     f"h{cfg.n_heads}/kv{cfg.n_kv_heads}ff{cfg.d_ff}"}

    # fp32 storage (the naive import default); generate() takes the
    # BARE params tree (no {"params": ...} wrapper)
    ms_fp32 = decode_ms_per_tok(model, params)
    out["fp32_ms_per_tok"] = round(ms_fp32, 3)

    # bf16 storage: generate --dtype bf16 (cast once, on device)
    params_bf16 = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16)
        if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
    ms_bf16 = decode_ms_per_tok(model, params_bf16)
    out["bf16_ms_per_tok"] = round(ms_bf16, 3)
    out["bf16_vs_fp32"] = round(ms_fp32 / ms_bf16, 3)
    if bw:
        pbytes = sum(x.size * x.dtype.itemsize
                     for x in jax.tree.leaves(params_bf16))
        out["bf16_params_bytes"] = pbytes
        # decode roofline: every token re-reads all parameter bytes
        out["bf16_hbm_bw_utilization"] = round(
            pbytes / (ms_bf16 / 1e3) / bw, 4)

    # weight-only int8 (generate --int8), converted on device
    qmodel, qparams = quantize_for_serving(model, {"params": params},
                                           on_device=True)
    del params, params_bf16
    gc.collect()
    ms_int8 = decode_ms_per_tok(qmodel, qparams["params"])
    out["int8_ms_per_tok"] = round(ms_int8, 3)
    out["int8_vs_bf16_e2e"] = round(ms_bf16 / ms_int8, 3)
    out["int8_vs_fp32_e2e"] = round(ms_fp32 / ms_int8, 3)
    if bw:
        qbytes = sum(x.size * x.dtype.itemsize
                     for x in jax.tree.leaves(qparams))
        out["int8_params_bytes"] = qbytes
        out["int8_hbm_bw_utilization"] = round(
            qbytes / (ms_int8 / 1e3) / bw, 4)
    return out


def bench_serving(on_tpu: bool) -> dict:
    """Continuous batching vs fixed-batch generate() on a mixed-length
    workload (the ISSUE-1 acceptance datum): requests share a prompt
    length but draw exponential-ish OUTPUT budgets, the regime where
    request-level batching idles most slots behind the batch straggler.

    Fixed-batch baseline: requests grouped in arrival order into
    batches of ``batch``; each batch decodes max(budgets in batch)
    tokens through the one-dispatch generate() scan (its strongest
    form — no eos, so every step is useful for SOME row). Continuous:
    serve.Server retires each slot at exactly its budget and refills it
    the same iteration. Both sides run the identical jitted model;
    tok/s counts only REQUESTED tokens (the straggler padding fixed
    batching decodes past a row's budget is waste, not throughput).
    Programs are warmed (one untimed pass each) so the datum compares
    steady-state serving, not compile time. ``*_steps`` record the
    decode-step counts — the launch-overhead-free form of the same
    claim (the tunneled backend charges the host-driven continuous
    loop ~4.5 ms per step that the scan amortizes away, so wall ratios
    on the tunnel understate the algorithmic win the step counts pin)."""
    import numpy as np

    from tony_tpu.models import Transformer, TransformerConfig, generate
    from tony_tpu.serve import Request, Server

    if on_tpu:
        cfg = TransformerConfig(
            vocab_size=32768, d_model=768, n_layers=12, n_heads=12,
            d_ff=3072, max_seq_len=512, scan_layers=False)
        batch, n_req, prompt_len = 8, 32, 64
        lo, hi = 8, 192
    else:
        # big enough that a decode step's compute clears the per-dispatch
        # host floor (~1.5 ms on the CI box) — at smaller toy sizes the
        # datum measures dispatch overhead, not scheduling
        cfg = TransformerConfig(
            vocab_size=512, d_model=128, n_layers=3, n_heads=4, d_ff=256,
            max_seq_len=256)
        batch, n_req, prompt_len = 4, 16, 16
        lo, hi = 8, 224
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, prompt_len), jnp.int32))["params"]
    if on_tpu:
        params = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
    rng = np.random.default_rng(0)
    budgets = rng.exponential(scale=(hi - lo) / 3.0, size=n_req)
    budgets = (budgets.astype(int) + lo).clip(lo, hi)
    prompts = rng.integers(0, cfg.vocab_size, size=(n_req, prompt_len))

    def run_fixed() -> int:
        steps = out = 0
        for start in range(0, n_req, batch):
            grp = slice(start, start + batch)
            nt = int(budgets[grp].max())
            out = generate(model, params, jnp.asarray(prompts[grp],
                                                      jnp.int32),
                           max_new_tokens=nt)
            steps += nt
        float(jnp.asarray(out).reshape(-1)[0])
        return steps

    def run_continuous() -> Server:
        # chunk 16: throughput mode — amortizes the per-dispatch host
        # floor to ~0.1 ms/token (a streaming deployment would trade
        # some of this back for first-token latency)
        server = Server(model, params, batch_size=batch, eos_id=-1,
                        min_bucket=prompt_len, chunk_steps=16)
        n_done = sum(1 for _ in server.run(
            Request(prompts[i].tolist(), int(budgets[i]), id=i)
            for i in range(n_req)))
        assert n_done == n_req
        return server

    run_fixed()  # warm: compiles every (batch, nt) program
    run_continuous()  # warm: prefill bucket + resident step + admit
    t0 = time.perf_counter()
    fixed_steps = run_fixed()
    t_fixed = time.perf_counter() - t0
    t0 = time.perf_counter()
    server = run_continuous()
    t_cont = time.perf_counter() - t0
    useful = int(budgets.sum())
    return {
        "n_requests": n_req,
        "batch_slots": batch,
        "prompt_len": prompt_len,
        "output_budget_lo_hi": [int(lo), int(hi)],
        "useful_tokens": useful,
        "continuous_tok_s": round(useful / t_cont, 1),
        "fixed_batch_tok_s": round(useful / t_fixed, 1),
        "continuous_vs_fixed": round(t_fixed / t_cont, 3),
        "continuous_steps": server.steps,
        "fixed_steps": fixed_steps,
        "steps_saved_ratio": round(fixed_steps / max(server.steps, 1), 3),
    }


def bench_gateway(on_tpu: bool) -> dict:
    """The front-door datum (ISSUE-2 acceptance): concurrent clients
    through ``tony_tpu.gateway`` vs the same requests issued serially
    by one client. Serial leaves every slot but one idle; concurrent
    clients fill the continuous-batching slots, so concurrent tok/s
    must be >= the serial baseline (the asserted bound) and in practice
    well above it. Also records p50/p99 TTFT at 1 vs 2 replicas — the
    latency price of queueing under load that /stats exposes in
    production. Host-scheduling-bound by design, so the CPU-sized model
    is the right probe on either backend (the chip-side decode numbers
    live in extras.serving/decode)."""
    import threading

    import numpy as np

    from tony_tpu.gateway import Gateway, GenRequest
    from tony_tpu.models import Transformer, TransformerConfig
    from tony_tpu.serve import Server

    cfg = TransformerConfig(
        vocab_size=512, d_model=128, n_layers=3, n_heads=4, d_ff=256,
        max_seq_len=128)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 16), jnp.int32))["params"]
    rng = np.random.default_rng(0)
    n_req, prompt_len, batch = 16, 16, 4
    budgets = (rng.exponential(scale=12.0, size=n_req).astype(int)
               + 8).clip(8, 48)
    prompts = rng.integers(0, cfg.vocab_size, size=(n_req, prompt_len))
    useful = int(budgets.sum())

    def make_gateway(n_replicas):
        return Gateway(
            [Server(model, params, batch_size=batch, eos_id=-1,
                    min_bucket=prompt_len, chunk_steps=8)
             for _ in range(n_replicas)],
            max_queue=2 * n_req).start()

    def run_serial() -> float:
        gw = make_gateway(1)
        t0 = time.perf_counter()
        for i in range(n_req):
            gw.submit(GenRequest(prompts[i].tolist(), int(budgets[i]),
                                 id=i)).result(timeout=600)
        dt = time.perf_counter() - t0
        gw.drain(timeout=60)
        return dt

    def run_concurrent(n_replicas, n_clients=8):
        gw = make_gateway(n_replicas)
        errors = []

        def client(c):
            try:
                for i in range(c, n_req, n_clients):
                    gw.submit(GenRequest(prompts[i].tolist(),
                                         int(budgets[i]), id=i)) \
                        .result(timeout=600)
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(n_clients)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        snap = gw.snapshot()
        gw.drain(timeout=60)
        if errors:
            raise errors[0]
        return dt, snap

    run_concurrent(1)  # warm: compiles prefill bucket + chunk ladder
    t_serial = run_serial()
    t_c1, snap1 = run_concurrent(1)
    t_c2, snap2 = run_concurrent(2)
    serial_tok_s = useful / t_serial
    c1_tok_s = useful / t_c1
    c2_tok_s = useful / t_c2
    return {
        "n_requests": n_req,
        "useful_tokens": useful,
        "batch_slots": batch,
        "serial_tok_s": round(serial_tok_s, 1),
        "concurrent_tok_s_1r": round(c1_tok_s, 1),
        "concurrent_tok_s_2r": round(c2_tok_s, 1),
        # the acceptance bound: concurrent clients must not be SLOWER
        # than one serial client (continuous batching fills the slots)
        "concurrent_vs_serial": round(c1_tok_s / serial_tok_s, 3),
        "concurrent_beats_serial": bool(c1_tok_s >= serial_tok_s),
        "ttft_ms_1r": {"p50": snap1["ttft_ms"]["p50"],
                       "p99": snap1["ttft_ms"]["p99"]},
        "ttft_ms_2r": {"p50": snap2["ttft_ms"]["p50"],
                       "p99": snap2["ttft_ms"]["p99"]},
        "queue_wait_ms_1r_p99": snap1["queue_wait_ms"]["p99"],
        "queue_wait_ms_2r_p99": snap2["queue_wait_ms"]["p99"],
    }


def bench_prefix(on_tpu: bool) -> dict:
    """The prefix-store datum (ISSUE-3 acceptance): a shared-system-
    prompt workload — every request carries the same long preamble plus
    a short distinct tail, and half the prompts repeat exactly (the
    agents-hitting-one-endpoint traffic shape) — served with the radix
    PrefixStore on vs off. Off, every request prefills its full bucket;
    on, exact repeats skip prefill entirely (zero dispatches) and
    fresh tails prefill only their small suffix bucket at an offset.
    Requests are submitted serially through a 1-replica gateway so TTFT
    isolates prefill latency (no queueing). The deterministic form of
    the claim is the prefill dispatch/token counts; wall TTFT rides
    along (the tunneled backend's per-dispatch launch floor damps the
    CPU ratio). Greedy outputs are asserted identical on vs off —
    the exactness contract, re-checked at bench scale."""
    import numpy as np

    from tony_tpu.gateway import Gateway, GenRequest
    from tony_tpu.models import Transformer, TransformerConfig
    from tony_tpu.serve import Server, bucket_len

    cfg = TransformerConfig(
        vocab_size=512, d_model=128, n_layers=3, n_heads=4, d_ff=256,
        max_seq_len=256)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 16), jnp.int32))["params"]
    rng = np.random.default_rng(0)
    system_len, tail_len, n_distinct, budget = 96, 8, 6, 4
    system = rng.integers(0, cfg.vocab_size, size=system_len)
    prompts = [np.concatenate(
        [system, rng.integers(0, cfg.vocab_size, size=tail_len)]).tolist()
        for _ in range(n_distinct)]
    workload = prompts + prompts  # second half: exact repeats
    n_req = len(workload)

    def run(prefix_mb):
        server = Server(model, params, batch_size=4, min_bucket=16,
                        chunk_steps=4, prefix_cache_mb=prefix_mb)
        gw = Gateway([server], max_queue=2 * n_req).start()
        outs, t0 = [], time.perf_counter()
        for i, p in enumerate(workload):
            res = gw.submit(GenRequest(p, budget, id=i)) \
                .result(timeout=600)
            outs.append(res.tokens)
        dt = time.perf_counter() - t0
        snap = gw.snapshot()
        gw.drain(timeout=60)
        return outs, dt, snap, server

    run(0)   # warm: full-prefill bucket + chunk ladder
    run(64)  # warm: suffix bucket, hit-admit, donation read
    outs_off, t_off, snap_off, srv_off = run(0)
    outs_on, t_on, snap_on, srv_on = run(64)
    assert outs_on == outs_off, "prefix store changed greedy outputs"
    full_bucket = bucket_len(system_len + tail_len, cfg.max_seq_len, 16)
    return {
        "n_requests": n_req,
        "system_prompt_len": system_len,
        "full_prefill_bucket": full_bucket,
        "prefill_dispatches_off": srv_off.prefills,
        "prefill_dispatches_on": srv_on.prefills,
        "prefill_dispatch_ratio": round(
            srv_off.prefills / max(srv_on.prefills, 1), 3),
        "prefill_tokens_off": srv_off.prefills * full_bucket,
        "prefill_tokens_saved": srv_on.prefill_tokens_saved,
        "prefix_hit_rate": snap_on["engine"]["prefix"]["hit_rate"],
        "ttft_ms_off": {"p50": snap_off["ttft_ms"]["p50"],
                        "p99": snap_off["ttft_ms"]["p99"]},
        "ttft_ms_on": {"p50": snap_on["ttft_ms"]["p50"],
                       "p99": snap_on["ttft_ms"]["p99"]},
        "ttft_p50_speedup": round(
            snap_off["ttft_ms"]["p50"] /
            max(snap_on["ttft_ms"]["p50"], 1e-9), 3),
        "wall_speedup": round(t_off / t_on, 3),
    }


def bench_spec(on_tpu: bool) -> dict:
    """The speculative-decoding datum (ISSUE-4 acceptance): an
    extractive/repetitive workload — prompts built from a short
    repeated pattern, the traffic shape where prompt-lookup drafting
    shines (structured output, quote-the-context extraction, template
    filling) — served greedy with ``speculate_k`` on vs off at
    chunk_steps=1, the streaming default where every token otherwise
    costs one whole dispatch. Off, each generated token is one decode
    dispatch; on, one verify dispatch lands acceptance+1 tokens, so
    decode dispatches shrink by roughly the acceptance rate. The
    deterministic form of the claim is the dispatch/step counts
    (asserted >= 1x in tests/test_spec.py's slow datum test); wall
    TPOT rides along (the tunneled backend's per-dispatch launch floor
    makes it the LARGER win there — fewer dispatches is fewer host
    round trips). Outputs are asserted byte-identical on vs off — the
    greedy-parity contract, re-checked at bench scale. wasted_steps
    reports thrown-away PER-SLOT positions before/after (chunk
    overshoot off; rejected-draft + overshoot positions on) — compare
    against useful_tokens, not decode_steps (per-dispatch depth)."""
    import numpy as np

    from tony_tpu.models import Transformer, TransformerConfig
    from tony_tpu.serve import Request, Server

    if on_tpu:
        cfg = TransformerConfig(
            vocab_size=32768, d_model=768, n_layers=12, n_heads=12,
            d_ff=3072, max_seq_len=512, scan_layers=False)
        n_req, pat_len, prompt_len, budget, batch = 16, 5, 60, 96, 4
    else:
        cfg = TransformerConfig(
            vocab_size=512, d_model=128, n_layers=3, n_heads=4,
            d_ff=256, max_seq_len=256)
        n_req, pat_len, prompt_len, budget, batch = 8, 4, 24, 48, 4
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 16), jnp.int32))["params"]
    if on_tpu:
        params = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
    rng = np.random.default_rng(0)
    prompts = []
    for _ in range(n_req):
        pat = rng.integers(1, cfg.vocab_size, size=pat_len).tolist()
        prompts.append((pat * (prompt_len // pat_len + 1))[:prompt_len])

    def run(k: int):
        server = Server(model, params, batch_size=batch, eos_id=-1,
                        min_bucket=16, chunk_steps=1, speculate_k=k)
        t0 = time.perf_counter()
        outs = {r.id: r.tokens for r in server.run(
            Request(list(p), budget, id=i)
            for i, p in enumerate(prompts))}
        return outs, time.perf_counter() - t0, server

    run(0)  # warm: prefill bucket + single-step program
    run(8)  # warm: the verify window ladder
    outs_off, t_off, srv_off = run(0)
    outs_on, t_on, srv_on = run(8)
    identical = outs_on == outs_off
    assert identical, "speculation changed greedy outputs"
    useful = n_req * budget
    return {
        "n_requests": n_req,
        "speculate_k": 8,
        "useful_tokens": useful,
        "dispatches_off": srv_off.dispatches,
        "dispatches_on": srv_on.dispatches,
        "dispatch_ratio": round(
            srv_off.dispatches / max(srv_on.dispatches, 1), 3),
        "decode_steps_off": srv_off.steps,
        "decode_steps_on": srv_on.steps,
        "wasted_steps_off": srv_off.wasted_steps,
        "wasted_steps_on": srv_on.wasted_steps,
        "drafted": srv_on.spec_drafted,
        "accepted": srv_on.spec_accepted,
        "acceptance_rate": round(
            srv_on.spec_accepted / max(srv_on.spec_drafted, 1), 4),
        "tok_s_off": round(useful / t_off, 1),
        "tok_s_on": round(useful / t_on, 1),
        "tpot_ms_off": round(t_off / useful * 1e3, 3),
        "tpot_ms_on": round(t_on / useful * 1e3, 3),
        "tpot_speedup": round(t_off / t_on, 3),
        "outputs_identical": identical,
    }


def bench_paged(on_tpu: bool) -> dict:
    """The paged-KV datum (ISSUE-7 acceptance), three claims:

    (a) EQUAL BATCH the paged path must at least hold tok/s (the
    0.95x bound: the chunk-level page gather is bounded overhead). In
    practice it WINS on mixed-length traffic — the bucketed view
    makes every attention read O(live extent) where the fixed-shape
    path scans the whole [max_seq_len] buffer per micro-step
    (measured ~2x at 64-live-of-256 on the CI box; the ratio
    approaches the pure-overhead bound only when sequences actually
    fill max_seq_len).

    (b) EQUAL HBM paged serves a BIGGER batch: both sides get the same
    KV byte budget (``unpaged_batch x max_seq_len`` token-slots); the
    unpaged side must spend it on full-length rows, the paged side
    admits by actual worst-case pages, so short-request traffic runs
    at ~4x the concurrency and aggregate tok/s must clear 1.3x.

    (c) PREFIX HITS stop moving bytes: on an exact-repeat workload the
    unpaged store copies a full cache row per hit (``write_slot_row``
    inside ``_hit_admit``); the paged store aliases pages — the only
    bytes moved are the one copy-on-write boundary-page fork (when the
    prompt ends mid-page) and the stored [1, V] logits. Bytes are
    accounted analytically from the engines' own dispatch/fork
    counters and must differ by >= 10x; outputs are asserted identical
    across every arm (the exactness contract at bench scale)."""
    import numpy as np

    from tony_tpu.models import Transformer, TransformerConfig
    from tony_tpu.serve import Request, Server

    if on_tpu:
        cfg = TransformerConfig(
            vocab_size=32768, d_model=768, n_layers=12, n_heads=12,
            d_ff=3072, max_seq_len=512, scan_layers=False)
        batch, n_req, prompt_len = 8, 32, 64
        lo, hi, unpaged_batch, paged_batch = 8, 192, 4, 16
    else:
        cfg = TransformerConfig(
            vocab_size=512, d_model=128, n_layers=3, n_heads=4,
            d_ff=256, max_seq_len=256)
        batch, n_req, prompt_len = 4, 16, 16
        lo, hi, unpaged_batch, paged_batch = 8, 48, 2, 8
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 16), jnp.int32))["params"]
    if on_tpu:
        params = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
    rng = np.random.default_rng(0)
    budgets = (rng.exponential(scale=(hi - lo) / 3.0, size=n_req)
               .astype(int) + lo).clip(lo, hi)
    prompts = rng.integers(0, cfg.vocab_size, size=(n_req, prompt_len))

    def serve(paged: bool, bsz: int, kv_pages: int = 0):
        server = Server(model, params, batch_size=bsz, eos_id=-1,
                        min_bucket=prompt_len, chunk_steps=8,
                        paged=paged, kv_pages=kv_pages)
        t0 = time.perf_counter()
        outs = {r.id: r.tokens for r in server.run(
            Request(prompts[i].tolist(), int(budgets[i]), id=i)
            for i in range(n_req))}
        return outs, time.perf_counter() - t0, server

    # ---- (a) equal batch: gather overhead bound -----------------------
    serve(False, batch)  # warm the unpaged program ladder
    serve(True, batch)   # warm the paged ladder
    outs_u, t_u, _ = serve(False, batch)
    outs_p, t_p, srv_p = serve(True, batch)
    assert outs_p == outs_u, "paged cache changed greedy outputs"
    useful = int(budgets.sum())
    page_size = srv_p.slots.pool.page_size

    # ---- (b) equal HBM budget: batch grows into freed waste -----------
    # both sides own unpaged_batch * max_seq_len token-slots of KV; the
    # paged side spends them as pages across more slots
    eq_pages = unpaged_batch * (-(-cfg.max_seq_len // page_size))
    serve(False, unpaged_batch)
    serve(True, paged_batch, kv_pages=eq_pages)
    outs_u2, t_u2, _ = serve(False, unpaged_batch)
    outs_p2, t_p2, srv_p2 = serve(True, paged_batch, kv_pages=eq_pages)
    assert outs_p2 == outs_u2, "paged cache changed greedy outputs (b)"

    # ---- (c) prefix-hit admission bytes -------------------------------
    system = rng.integers(0, cfg.vocab_size, size=prompt_len * 3)
    shared = [np.concatenate(
        [system, rng.integers(0, cfg.vocab_size, size=4)]).tolist()
        for _ in range(4)]
    hit_load = shared + shared + shared  # 2/3 exact repeats

    def serve_prefix(paged: bool):
        # small pages for the bytes claim: the only per-hit copy left
        # is the boundary-page fork, and its cost is ONE page — the
        # smaller the page, the closer an exact hit gets to free
        server = Server(model, params, batch_size=4, eos_id=-1,
                        min_bucket=16, chunk_steps=4, paged=paged,
                        kv_page_size=16, prefix_cache_mb=64)
        outs = {r.id: r.tokens for r in server.run(
            Request(list(p), 4, id=i) for i, p in enumerate(hit_load))}
        return outs, server

    outs_hu, srv_hu = serve_prefix(False)
    outs_hp, srv_hp = serve_prefix(True)
    assert outs_hp == outs_hu, "paged prefix changed greedy outputs"
    hits_u, hits_p = srv_hu.prefix_hits, srv_hp.prefix_hits
    assert hits_u == hits_p and hits_p >= len(shared), (hits_u, hits_p)
    kinds_u = srv_hu.timeline.summary()
    kinds_p = srv_hp.timeline.summary()
    # unpaged exact hit moves one whole cache row; paged moves only the
    # forked boundary page (at most one) plus the stored logits it
    # sampled from
    logits_b = 4 * cfg.vocab_size
    bytes_u = kinds_u.get("hit_admit", {}).get("count", 0) \
        * (srv_hu._row_nbytes + logits_b)
    pool = srv_hp.slots.pool
    bytes_p = kinds_p.get("cow_admit", {}).get("count", 0) * logits_b \
        + pool.forks * pool.page_nbytes

    return {
        "n_requests": n_req,
        "page_size": page_size,
        "useful_tokens": useful,
        # (a) equal batch
        "equal_batch_slots": batch,
        "tok_s_unpaged": round(useful / t_u, 1),
        "tok_s_paged": round(useful / t_p, 1),
        "equal_batch_ratio": round(t_u / t_p, 3),
        "decode_dispatches": srv_p.dispatches,
        # (b) equal HBM
        "hbm_budget_token_slots": unpaged_batch * cfg.max_seq_len,
        "hbm_budget_pages": eq_pages,
        "unpaged_batch": unpaged_batch,
        "paged_batch": paged_batch,
        "tok_s_unpaged_eq_hbm": round(useful / t_u2, 1),
        "tok_s_paged_eq_hbm": round(useful / t_p2, 1),
        "equal_hbm_speedup": round(t_u2 / t_p2, 3),
        "paged_peak_pages_used": srv_p2.slots.pool.peak_used,
        # (c) prefix-hit bytes
        "prefix_hits": hits_p,
        "hit_admit_dispatches_unpaged": kinds_u.get(
            "hit_admit", {}).get("count", 0),
        "cow_admit_dispatches_paged": kinds_p.get(
            "cow_admit", {}).get("count", 0),
        "cow_forks": pool.forks,
        "hit_bytes_moved_unpaged": bytes_u,
        "hit_bytes_moved_paged": bytes_p,
        "hit_bytes_ratio": round(bytes_u / max(bytes_p, 1), 1),
        "outputs_identical": True,
    }


def bench_disagg(on_tpu: bool) -> dict:
    """The disaggregation datum (ISSUE-12 acceptance), two claims:

    (a) MIXED-TRAFFIC TTFT: short-chat requests sharing a gateway with
    long-prompt traffic. Control = two generalist replicas, monolithic
    prefill (a short request admitted behind a long prompt waits out
    its whole prefill dispatch); disagg = the same two engines as a
    prefill=1/decode=1 role split with chunked prefill (the long
    prompt prefills in bounded chunks, shorts slip between them and
    decode on the other pool). Outputs are asserted token-identical
    and zero requests shed — the latency win must not cost exactness
    or capacity.

    (b) FLEET PREFILL DISPATCHES under a shared system prompt with
    prefix-affinity routing on vs off: affinity concentrates the
    shared prefix on the replica that already holds it (one full
    prefill for the fleet), least-outstanding spreads it (one per
    replica). Deterministic counter, no clocks."""
    import numpy as np

    from tony_tpu.gateway import Gateway, GenRequest
    from tony_tpu.models import Transformer, TransformerConfig
    from tony_tpu.serve import Server

    cfg = TransformerConfig(
        vocab_size=256, d_model=128, n_layers=4, n_heads=4, d_ff=256,
        max_seq_len=512)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 16), jnp.int32))["params"]
    rng = np.random.default_rng(0)
    longs = [rng.integers(0, cfg.vocab_size, size=440).tolist()
             for _ in range(2)]
    shorts = [rng.integers(0, cfg.vocab_size, size=6).tolist()
              for _ in range(8)]

    def mk(**kw):
        return Server(model, params, batch_size=4, min_bucket=16,
                      chunk_steps=2, prefix_cache_mb=64.0, **kw)

    def run_mixed(roles, chunk):
        servers = [mk(prefill_chunk_tokens=chunk), mk()]
        gw = Gateway(servers, max_queue=64, roles=roles).start()
        # longs first, then the shorts they would otherwise starve
        lt = [gw.submit(GenRequest(list(p), 8, id=f"long{i}"))
              for i, p in enumerate(longs)]
        st = [gw.submit(GenRequest(list(p), 8, id=f"short{i}"))
              for i, p in enumerate(shorts)]
        outs = {t.request.id: t.result(timeout=600).tokens
                for t in lt + st}
        ttfts = sorted(t.metrics["ttft_ms"] for t in st)
        snap = gw.snapshot()
        gw.drain(timeout=60)
        assert snap["shed"] == {}, snap["shed"]
        return outs, ttfts, snap

    run_mixed(None, 0)  # warm every program off the measured path
    run_mixed(["prefill", "decode"], 64)
    ctrl_outs, ctrl_ttft, _ = run_mixed(None, 0)
    dis_outs, dis_ttft, dis_snap = run_mixed(["prefill", "decode"], 64)
    assert dis_outs == ctrl_outs, "role split changed outputs"

    def pct(vals, q):
        return vals[min(len(vals) - 1, int(q * (len(vals) - 1) + 0.5))]

    # (b) fleet prefill dispatches, affinity on vs off: warm ONE
    # replica with the system prompt, then fire the rest concurrently
    # (half exact repeats). Affinity concentrates them on the warm
    # store — exact repeats skip their prefill dispatch entirely;
    # least-outstanding spreads them onto the cold replica, which must
    # prefill. The counter is deterministic; no clocks.
    system = rng.integers(0, cfg.vocab_size, size=96).tolist()
    distinct = [system + rng.integers(0, cfg.vocab_size,
                                      size=8).tolist()
                for _ in range(4)]
    fleet = distinct[1:] + distinct  # 3 fresh tails + 4 exact repeats

    def run_fleet(affinity):
        servers = [mk(), mk()]
        gw = Gateway(servers, max_queue=64,
                     prefix_affinity=affinity).start()
        outs = [gw.submit(GenRequest(list(distinct[0]), 4, id="warm"))
                .result(timeout=600).tokens]
        tickets = [gw.submit(GenRequest(list(p), 4, id=i))
                   for i, p in enumerate(fleet)]
        outs.extend(t.result(timeout=600).tokens for t in tickets)
        prefills = sum(s.prefills for s in servers)
        snap = gw.snapshot()
        gw.drain(timeout=60)
        return outs, prefills, snap

    outs_off, prefills_off, _ = run_fleet(False)
    outs_on, prefills_on, snap_on = run_fleet(True)
    assert outs_on == outs_off, "affinity routing changed outputs"

    return {
        "n_long": len(longs), "n_short": len(shorts),
        "long_prompt_len": 440, "prefill_chunk_tokens": 64,
        "short_ttft_ms_control": {"p50": round(pct(ctrl_ttft, 0.5), 3),
                                  "p99": round(pct(ctrl_ttft, 0.99), 3)},
        "short_ttft_ms_disagg": {"p50": round(pct(dis_ttft, 0.5), 3),
                                 "p99": round(pct(dis_ttft, 0.99), 3)},
        "short_ttft_p50_improvement": round(
            pct(ctrl_ttft, 0.5) / max(pct(dis_ttft, 0.5), 1e-9), 3),
        "short_ttft_p99_improvement": round(
            pct(ctrl_ttft, 0.99) / max(pct(dis_ttft, 0.99), 1e-9), 3),
        "handoffs": dis_snap["routing"]["handoffs"],
        "chunk_dispatches":
            dis_snap["engine"]["prefill_chunks"]["dispatches"],
        "fleet_prefills_affinity_off": prefills_off,
        "fleet_prefills_affinity_on": prefills_on,
        "affinity_prefill_ratio": round(
            prefills_off / max(prefills_on, 1), 3),
        "prefix_routed": snap_on["routing"]["prefix_routed"],
        "outputs_identical": True,
    }


def bench_faults(on_tpu: bool) -> dict:
    """The fault-tolerance datum (ISSUE-5 acceptance): the same
    concurrent workload through a 2-replica gateway twice — fault-free
    control, then with replica 0 armed (``serve/faults.py``) to die
    mid-run — and the wall-clock price of a replica failure measured
    against it. The contract numbers ride along as booleans/counters:
    zero shed (a retriable failure is failover, never a 5xx), every
    output token-identical to the control (deterministic greedy re-run
    + resume-past-emitted), and the dead replica back in the rotation
    by the end (breaker probe). Host-scheduling-bound like the gateway
    datum, so the CPU-sized model is the right probe on either
    backend."""
    import threading

    import numpy as np

    from tony_tpu.gateway import Gateway, GenRequest
    from tony_tpu.models import Transformer, TransformerConfig
    from tony_tpu.serve import FaultPlan, Server

    cfg = TransformerConfig(
        vocab_size=512, d_model=128, n_layers=3, n_heads=4, d_ff=256,
        max_seq_len=128)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 16), jnp.int32))["params"]
    rng = np.random.default_rng(0)
    n_req, prompt_len, budget, batch = 12, 16, 24, 2
    prompts = rng.integers(0, cfg.vocab_size, size=(n_req, prompt_len))
    useful = n_req * budget

    def run(inject: bool):
        gw = Gateway(
            [Server(model, params, batch_size=batch, eos_id=-1,
                    min_bucket=prompt_len, chunk_steps=1,
                    fault_plan=(FaultPlan.fail_at(6) if inject and i == 0
                                else None))
             for i in range(2)],
            max_queue=2 * n_req, breaker_base_s=0.05, breaker_max_s=0.2)
        gw.start()
        outs, errors = {}, []

        def client(c, n_clients=6):
            try:
                for i in range(c, n_req, n_clients):
                    outs[i] = gw.submit(
                        GenRequest(prompts[i].tolist(), budget, id=i)) \
                        .result(timeout=600).tokens
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(6)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        if errors:
            gw.drain(timeout=60)
            raise errors[0]
        # the breaker probe is the recovery half of the story: wait
        # (bounded) for the dead replica to re-earn admission
        rejoined = True
        if inject:
            deadline = time.monotonic() + 60
            while gw.n_healthy < 2 and time.monotonic() < deadline:
                time.sleep(0.02)
            rejoined = gw.n_healthy == 2
        snap = gw.snapshot()
        gw.drain(timeout=60)
        return outs, dt, snap, rejoined

    run(False)  # warm: prefill bucket + decode program
    outs_ctrl, t_ctrl, snap_ctrl, _ = run(False)
    outs_chaos, t_chaos, snap_chaos, rejoined = run(True)
    identical = outs_chaos == outs_ctrl
    assert identical, "failover changed greedy outputs"
    sup = snap_chaos["supervision"]
    return {
        "n_requests": n_req,
        "useful_tokens": useful,
        "completed_control": snap_ctrl["completed"],
        "completed_faulted": snap_chaos["completed"],
        "shed_faulted": snap_chaos["shed"],  # the zero-5xx contract
        "replica_failures": sup["replica_failures"],
        "failovers": sup["failovers"],
        "retries": sup["retries"],
        "failed_replica_rejoined": rejoined,
        "outputs_identical": identical,
        "tok_s_control": round(useful / t_ctrl, 1),
        "tok_s_faulted": round(useful / t_chaos, 1),
        # the headline: what one mid-run replica death costs the
        # workload end-to-end (re-run prompts + degraded capacity
        # until the breaker rejoins the replica)
        "failover_cost": round(t_chaos / t_ctrl, 3),
    }


def bench_obs(on_tpu: bool) -> dict:
    """The observability-overhead datum (ISSUE-6 acceptance): the
    identical serving workload through a gateway with request tracing +
    dispatch timeline ENABLED vs fully DISABLED, TPOT compared. The
    obs layer is host-side appends under small locks, so the CPU-sized
    model is the right probe on either backend (the gateway/faults
    argument); chunk_steps=1 maximizes dispatches per token — the
    WORST case for a per-dispatch recording layer.

    The gate statistic is the MIN over per-pair ratios: rounds run in
    temporally-adjacent (on, off) pairs with alternating arm order,
    each pair yields on_median/off_median, and the reported ratio is
    the smallest. Boxes this runs on have measured 1.7x wall-clock
    swings between identical runs (±40% per-round medians), so any
    single round — or even each arm's best-of-N — flakes; but the
    noise is ONE-SIDED (a busy box only ever adds time), so if the obs
    layer truly cost X%, every pair measured in a calm window would
    still show >= X, and the min over pairs is a consistent
    upper-bound estimate of the true overhead. Order alternation stops
    a monotonic box-speed drift from systematically charging whichever
    arm runs second. The
    enabled arm also reports the
    new dispatch-timeline block itself (steady-state decode cost with
    the first-call compile split out — the ROADMAP-4 sensor)."""
    import numpy as np

    from tony_tpu.gateway import Gateway, GenRequest
    from tony_tpu.models import Transformer, TransformerConfig
    from tony_tpu.serve import Server

    cfg = TransformerConfig(
        vocab_size=512, d_model=128, n_layers=3, n_heads=4, d_ff=256,
        max_seq_len=128)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 16), jnp.int32))["params"]
    rng = np.random.default_rng(0)
    n_req, prompt_len, budget, batch = 12, 16, 48, 4
    prompts = rng.integers(0, cfg.vocab_size, size=(n_req, prompt_len))

    def run(obs_on: bool):
        gw = Gateway([Server(model, params, batch_size=batch, eos_id=-1,
                             min_bucket=prompt_len, chunk_steps=1,
                             timeline=obs_on)],
                     max_queue=2 * n_req, tracing=obs_on)
        tickets = [gw.submit(GenRequest(prompts[i].tolist(), budget,
                                        id=i)) for i in range(n_req)]
        gw.start()
        for t in tickets:
            t.result(timeout=600)
        tpots = sorted(t.metrics["tpot_ms"] for t in tickets)
        snap = gw.snapshot()
        snap["_traces"] = len(gw.traces) if gw.traces is not None else 0
        gw.drain(timeout=60)
        return tpots[len(tpots) // 2], snap

    def run_remote(obs_on: bool):
        """The ISSUE-15 arm: the same workload through a gateway over
        ONE in-process replica agent (real HTTP over loopback), with
        the fleet observability channel ARMED (obs-puller + stream
        span fragments + alerts + the bundle recorder pointed at a
        history dir) vs fully OFF. The agent's OWN engine records its
        timeline in both arms — the A/B isolates the gateway-side
        channel: pulls riding the heartbeat, record conversion, span
        grafting, ledger merging, and alert evaluation over the
        pulled state."""
        import shutil
        import tempfile

        from tony_tpu.gateway import GatewayHistory
        from tony_tpu.gateway.remote import RemoteServer
        from tony_tpu.serve.agent import AgentHTTP, ReplicaAgent

        agent = AgentHTTP(ReplicaAgent(Server(
            model, params, batch_size=batch, eos_id=-1,
            min_bucket=prompt_len, chunk_steps=1))).start()
        hist_dir = tempfile.mkdtemp(prefix="tony-bench-obs-")
        try:
            stub = RemoteServer(agent.address,
                                heartbeat_interval_s=0.2,
                                boot_timeout_s=120.0, obs_pull=obs_on)
            gw = Gateway([stub], max_queue=2 * n_req,
                         tracing=obs_on, alerts=obs_on,
                         alert_interval_s=0.2,
                         history=GatewayHistory(hist_dir)
                         if obs_on else None)
            tickets = [gw.submit(GenRequest(prompts[i].tolist(),
                                            budget, id=i))
                       for i in range(n_req)]
            gw.start()
            for t in tickets:
                t.result(timeout=600)
            tpots = sorted(t.metrics["tpot_ms"] for t in tickets)
            gw.drain(timeout=60)
        finally:
            agent.stop()
            shutil.rmtree(hist_dir, ignore_errors=True)
        return tpots[len(tpots) // 2]

    run(True)  # warm: prefill bucket + decode program
    run(False)
    pair_ratios, offs, ons = [], [], []
    snap_on = None
    for first in (False, True, False, True):  # pair order alternates
        pair = {}
        for obs_on in (first, not first):
            med, snap = run(obs_on)
            pair[obs_on] = med
            if obs_on:
                ons.append(med)
                snap_on = snap
            else:
                offs.append(med)
        pair_ratios.append(pair[True] / pair[False])
    # the remote arm: fewer pairs (each run pays a full agent boot) —
    # the min-over-pairs statistic carries the same one-sided-noise
    # argument as the local gate
    r_pairs, r_offs, r_ons = [], [], []
    for first in (False, True):
        pair = {}
        for obs_on in (first, not first):
            med = run_remote(obs_on)
            pair[obs_on] = med
            (r_ons if obs_on else r_offs).append(med)
        r_pairs.append(pair[True] / pair[False])
    disp = snap_on["engine"]["dispatch"]
    return {
        "n_requests": n_req,
        "tokens_per_request": budget,
        "tpot_ms_obs_off": round(min(offs), 3),
        "tpot_ms_obs_on": round(min(ons), 3),
        "pair_ratios": [round(r, 3) for r in pair_ratios],
        # the always-on-cheap contract; the slow gate asserts <= 1.1
        "tpot_ratio_on_off": round(min(pair_ratios), 3),
        # ISSUE-15: the fleet channel's cost against a remote replica,
        # measured not assumed (obs-puller + span fragments + alerts +
        # bundle recorder armed vs the whole channel off); the slow
        # gate asserts <= 1.1 here too
        "remote_tpot_ms_obs_off": round(min(r_offs), 3),
        "remote_tpot_ms_obs_on": round(min(r_ons), 3),
        "remote_pair_ratios": [round(r, 3) for r in r_pairs],
        "remote_tpot_ratio_obs_on_off": round(min(r_pairs), 3),
        "decode_dispatches": disp["decode"]["count"],
        "decode_steady_mean_ms": disp["decode"]["steady_mean_ms"],
        "decode_compile_ms": disp["decode"]["compile_ms"],
        "prefill_steady_mean_ms": disp["prefill"]["steady_mean_ms"],
        "traced_requests": snap_on["_traces"],
    }


def bench_goodput(on_tpu: bool) -> dict:
    """The goodput-attribution datum (ISSUE-10): (a) the ROADMAP-4
    decode-roofline number reproduced by the PRODUCT sensor instead of
    offline math — the serving-scale decode shape (386M-class, batch
    8 on TPU; a CPU proxy otherwise) driven through ``serve.Server``
    with the cost model on, reporting the decode dispatches' analytic
    HBM-BW% next to the ledger's bucket decomposition and the single
    largest waste bucket (CPU reports bytes with utilization null —
    no roofline reference, no made-up percentage); (b) the overhead
    gate RE-RUN with goodput+alerts armed: the identical workload
    through a gateway with timeline+tracing+alerts fully ON vs fully
    OFF, min-over-adjacent-pairs TPOT ratio (the extras.obs statistic
    and noise argument; the slow gate asserts <= 1.1x)."""
    import numpy as np

    from tony_tpu.gateway import Gateway, GenRequest
    from tony_tpu.models import Transformer, TransformerConfig
    from tony_tpu.serve import Request, Server

    if on_tpu:
        # the BENCH_LKG serving-scale shape: 386M-class decoder,
        # batch 8 — the 33%-of-HBM datum the ledger now attributes
        cfg = TransformerConfig(
            vocab_size=32768, d_model=768, n_layers=12, n_heads=12,
            d_ff=3072, max_seq_len=512, scan_layers=False)
        batch, n_req, prompt_len, budget = 8, 16, 64, 128
    else:
        cfg = TransformerConfig(
            vocab_size=512, d_model=128, n_layers=3, n_heads=4,
            d_ff=256, max_seq_len=128)
        batch, n_req, prompt_len, budget = 4, 8, 16, 32
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, prompt_len), jnp.int32))["params"]
    if on_tpu:
        params = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
    rng = np.random.default_rng(0)
    prompts = rng.integers(0, cfg.vocab_size, size=(n_req, prompt_len))

    def serve_once() -> Server:
        server = Server(model, params, batch_size=batch, eos_id=-1,
                        min_bucket=prompt_len, chunk_steps=16)
        if not on_tpu:
            # the CPU proxy must read the same on EVERY host: a TPU VM
            # can still detect its chip under JAX_PLATFORMS=cpu, which
            # would price the tiny proxy model against a real roofline
            # — pin the reference OFF so utilization is null by
            # contract (the slow gate asserts it)
            server.hbm_gbps = server.cost.hbm_gbps = 0.0
            server.peak_flops = server.cost.peak_flops = 0.0
        for _ in server.run(Request(prompts[i].tolist(), budget, id=i)
                            for i in range(n_req)):
            pass
        return server

    serve_once()  # warm: the steady-state ledger, not compile time
    server = serve_once()
    ledger = server.goodput()
    decode = server.timeline.summary().get("decode", {})
    util = ledger["utilization"].get("decode", {})
    out = {
        "n_requests": n_req,
        "batch_slots": batch,
        "tokens_per_request": budget,
        # the product sensor's roofline read: analytic bytes over
        # steady decode wall vs the chip's peak (null off-TPU)
        "decode_hbm_bw_pct": util.get("hbm_bw_pct"),
        "decode_mfu_pct": util.get("mfu_pct"),
        "decode_est_bytes": decode.get("est_bytes", 0),
        "hbm_gbps_reference": ledger["hbm_gbps"],
        "ledger_buckets": ledger["buckets"],
        "ledger_sum": round(sum(ledger["buckets"].values()), 6),
        "largest_waste": ledger["largest_waste"],
        "useful_fraction": ledger["useful_fraction"],
    }

    # (b) the overhead gate, goodput+alerts armed — extras.obs's
    # min-over-adjacent-pairs statistic (one-sided box noise argument
    # documented there); chunk_steps=1 is the per-dispatch worst case
    g_cfg = TransformerConfig(
        vocab_size=512, d_model=128, n_layers=3, n_heads=4, d_ff=256,
        max_seq_len=128)
    g_model = Transformer(g_cfg)
    g_params = g_model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 16), jnp.int32))["params"]
    g_n, g_prompt, g_budget, g_batch = 12, 16, 48, 4
    g_prompts = rng.integers(0, g_cfg.vocab_size, size=(g_n, g_prompt))

    def run(armed: bool):
        gw = Gateway([Server(g_model, g_params, batch_size=g_batch,
                             eos_id=-1, min_bucket=g_prompt,
                             chunk_steps=1, timeline=armed)],
                     max_queue=2 * g_n, tracing=armed, alerts=armed,
                     alert_interval_s=0.25)
        tickets = [gw.submit(GenRequest(g_prompts[i].tolist(), g_budget,
                                        id=i)) for i in range(g_n)]
        gw.start()
        for t in tickets:
            t.result(timeout=600)
        tpots = sorted(t.metrics["tpot_ms"] for t in tickets)
        gw.drain(timeout=60)
        return tpots[len(tpots) // 2]

    run(True)  # warm both arms' programs
    run(False)
    pair_ratios, offs, ons = [], [], []
    for first in (False, True, False, True):
        pair = {}
        for armed in (first, not first):
            pair[armed] = run(armed)
            (ons if armed else offs).append(pair[armed])
        pair_ratios.append(pair[True] / pair[False])
    out.update({
        "tpot_ms_armed": round(min(ons), 3),
        "tpot_ms_off": round(min(offs), 3),
        "pair_ratios": [round(r, 3) for r in pair_ratios],
        # the always-on-cheap contract with goodput+alerts included;
        # the slow gate asserts <= 1.1 (tests/test_bench.py)
        "tpot_ratio_armed_off": round(min(pair_ratios), 3),
    })
    return out


# ------------------------------------------------------ attention kernels


def timed_kernel(fn, args, steps: int = 20) -> float:
    """Kernel A/B harness shared by the attention and quant benches:
    compile + prime, then time `steps` dispatches closed by a scalar
    host fetch (the un-fakeable barrier, see timed_round)."""
    out = fn(*args)  # compile
    float(jnp.asarray(out).reshape(-1)[0].astype(jnp.float32))
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    float(jnp.asarray(out).reshape(-1)[0].astype(jnp.float32))
    return (time.perf_counter() - t0) / steps


def timed_kernel_device(fn, args, steps: int = 20) -> tuple[float, float]:
    """(device_s, wall_s) per dispatch. Device-busy time comes from an
    xplane trace of the timed loop (profiler.trace_device_ms): the
    tunneled backend adds ~4.5 ms of launch overhead per dispatch, which
    swamped small kernels and swung wall-clock A/B ratios 40% between
    identical runs (VERDICT r4 #3) — device time has no launch overhead
    in it, so trace-derived ratios are the artifact numbers and wall
    stays as a cross-check. Falls back to wall when the trace has no
    device plane (CPU) or proto stubs are missing."""
    from tony_tpu.profiler import trace_device_ms

    wall = timed_kernel(fn, args, steps)  # also compiles + primes
    dev_ms = trace_device_ms(fn, args, steps=steps)
    dev = dev_ms / 1e3 if dev_ms else wall
    return dev, wall


def bench_attention(on_tpu: bool) -> dict:
    """Pallas flash vs XLA reference attention, fwd+bwd — the checked-in
    artifact behind PARITY.md's kernel claims. TPU-only: the pallas
    interpreter on CPU measures the interpreter, not the kernel."""
    if not on_tpu:
        return {"skipped": "kernel A/B is only meaningful on TPU"}
    from tony_tpu.ops import flash_attention
    from tony_tpu.parallel import reference_attention

    def qkv(b, l, h, d, key=0):
        ks = jax.random.split(jax.random.PRNGKey(key), 3)
        return tuple(jax.random.normal(k, (b, l, h, d), jnp.bfloat16)
                     for k in ks)

    def fwd_bwd(attn):
        def loss(q, k, v):
            return attn(q, k, v).astype(jnp.float32).sum()

        g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
        return lambda q, k, v: g(q, k, v)[0]

    out = {}
    # claim 1: flash vs XLA reference at seq 2k (fwd+bwd), block size
    # MEASURED per chip generation rather than assumed (the sweep is 3
    # small kernel compiles, amortized by the persistent cache).
    # All ratios here are DEVICE-BUSY (trace-derived); _wall keys are the
    # launch-overhead-laden cross-check (VERDICT r4 #3).
    args = qkv(4, 2048, 12, 64)
    sweep, sweep_wall = {}, {}  # raw seconds; rounded at output boundary
    for blk in (256, 512, 1024):
        sweep[str(blk)], sweep_wall[str(blk)] = timed_kernel_device(
            fwd_bwd(lambda q, k, v, b=blk: flash_attention(
                q, k, v, True, b, b)), args)
    best_blk = int(min(sweep, key=lambda k: sweep[k]))
    t_flash = sweep[str(best_blk)]
    t_ref, t_ref_wall = timed_kernel_device(
        fwd_bwd(lambda q, k, v: reference_attention(
            q, k, v, causal=True)), args)
    out["flash_vs_xla_seq2k"] = round(t_ref / t_flash, 3)
    out["flash_vs_xla_seq2k_wall"] = round(
        t_ref_wall / sweep_wall[str(best_blk)], 3)
    out["flash_seq2k_ms"] = round(t_flash * 1e3, 3)
    out["block_sweep_seq2k_ms"] = {k: round(v * 1e3, 3)
                                   for k, v in sweep.items()}
    out["best_block"] = best_blk
    # claim 2: banded sliding window vs full causal at seq 8k, window 1k
    args8 = qkv(1, 8192, 12, 64, key=1)
    t_full, _ = timed_kernel_device(
        fwd_bwd(lambda q, k, v: flash_attention(
            q, k, v, True, 512, 512)), args8)
    t_win, _ = timed_kernel_device(
        fwd_bwd(lambda q, k, v: flash_attention(
            q, k, v, True, 512, 512, window=1024)), args8)
    out["windowed_vs_full_seq8k_w1k"] = round(t_full / t_win, 3)
    return out


def bench_quant(on_tpu: bool) -> dict:
    """int8 weight-only matmul vs bf16 at decode shapes (ops/quant.py).
    Decode is HBM-bound, so the int8 kernel's ceiling is ~2x; the
    measured ratio is the realized fraction of that. TPU-only: the
    pallas interpreter would measure itself.

    The matmul is looped INSIDE one jit (k == n, so the activation
    threads through itself), and the per-iteration time is the SLOPE
    between a short and a long loop: the tunneled backend's per-launch
    overhead (tens of ms — it swamped a ~40 us bandwidth-bound kernel
    and measured launch cost at ratio ~1 in the r4.0 artifact) cancels
    exactly in the difference. Trace-verified against device-busy time:
    q8 23.5 us/iter = 87 percent of HBM peak, 1.95x over bf16."""
    if not on_tpu:
        return {"skipped": "kernel A/B is only meaningful on TPU"}
    from jax import lax

    from tony_tpu.ops import q8_matmul, quantize_q8

    m, k, n = 8, 4096, 4096  # decode-step projection shape
    # the length SPREAD must put the device-time delta well above the
    # tunnel's per-launch overhead variance (tens of ms): 10k iterations
    # x ~45 us/iter bf16 = ~450 ms of signal
    short, long = 1000, 11000
    x = jax.random.normal(jax.random.PRNGKey(0), (m, k), jnp.bfloat16)
    w = jax.random.normal(jax.random.PRNGKey(1), (k, n), jnp.bfloat16)
    w_q, scale = quantize_q8(w)

    def looped(body, iters):
        def f(c):
            out, _ = lax.scan(lambda c, _: (body(c), None), c, None,
                              length=iters)
            return out
        return jax.jit(f)

    def slope(body):
        # per-iteration time = slope between the short and long loop on
        # DEVICE-BUSY times (trace-derived; r5): launch overhead never
        # enters, and any per-dispatch device-side constant (initial
        # transfers, scan setup) cancels in the difference. The wall
        # slope rides along as the cross-check it used to be the
        # primary of (median of 3 per length — a 2-point wall slope
        # amplified endpoint noise 1.9x -> 1.2x between runs).
        ts_dev, ts_wall = {}, {}
        for i in (short, long):
            fn = looped(body, i)
            reps = [timed_kernel_device(fn, (x,), steps=1)
                    for _ in range(3)]
            # median PER AXIS: a lexicographic tuple sort would pick the
            # wall value that happens to ride with the median device
            # time — possibly a launch-overhead outlier
            ts_dev[i] = sorted(d for d, _ in reps)[1]
            ts_wall[i] = sorted(w for _, w in reps)[1]
        return ((ts_dev[long] - ts_dev[short]) / (long - short),
                (ts_wall[long] - ts_wall[short]) / (long - short))

    t_bf16, t_bf16_wall = slope(lambda c: (c @ w).astype(jnp.bfloat16))
    t_q8, t_q8_wall = slope(lambda c: q8_matmul(c, w_q, scale,
                                                out_dtype=jnp.bfloat16))
    out = {
        "int8_vs_bf16_decode_shape": round(t_bf16 / t_q8, 3),
        "int8_vs_bf16_decode_shape_wall": round(t_bf16_wall / t_q8_wall, 3),
        "bf16_us": round(t_bf16 * 1e6, 1),
        "int8_us": round(t_q8 * 1e6, 1),
        # achieved weight-byte bandwidth of the int8 kernel (table-free)
        "int8_achieved_gbps": round(k * n / t_q8 / 1e9, 1),
    }
    bw = hbm_bw_per_chip()
    if bw:
        out["int8_bw_utilization"] = round(k * n / t_q8 / bw, 4)
    return out


# -------------------------------------------------------- launch latency


def bench_launch() -> dict:
    """Launch -> first-step latency through the REAL submit path:
    TonyClient (staging, conf finalize, coordinator spawn, 1 s poll) ->
    coordinator (gang schedule, agent launch) -> agent (register, exec) ->
    payload (jit + one step). The payload pins JAX to CPU: the parent
    bench owns the TPU chip, and this metric is orchestration latency,
    not accelerator speed.

    Submitted TWICE against one shared compile-cache dir (shell-env
    overrides the per-job default): the second job's payload loads its
    jitted step from the persistent cache, so the cold-vs-warm delta IS
    the launch-latency win of VERDICT r2 #2 carried through the real
    submit path."""
    import tempfile

    from tony_tpu.mini import MiniTonyCluster, script_conf

    workdir = tempfile.mkdtemp(prefix="tony_bench_")
    payload = os.path.join(workdir, "first_step.py")
    shared_cache = os.path.join(workdir, "compile-cache")
    with open(payload, "w") as f:
        f.write(
            "import json, os, time\n"
            "t = {'payload_start': time.time()}\n"
            "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            "from tony_tpu.utils import compilecache\n"
            "t['compile_cache'] = compilecache.enable()\n"
            "import jax, jax.numpy as jnp\n"
            "out = jax.jit(lambda x: (x @ x).sum())(jnp.ones((256, 256)))\n"
            "out.block_until_ready()\n"
            "t['first_step_done'] = time.time()\n"
            "with open(os.path.join(os.environ['TONY_JOB_DIR'],\n"
            "          'launch_times.json'), 'w') as fh:\n"
            "    json.dump(t, fh)\n")

    def one_job(cluster) -> dict | None:
        conf = script_conf(cluster, payload, {"worker": 1})
        conf.set("tony.application.shell-env",
                 f"TONY_COMPILE_CACHE_DIR={shared_cache}")
        client = cluster.make_client(conf)
        t_submit = time.time()
        ok = client.run()
        t_done = time.time()
        times = {}
        path = os.path.join(client.job_dir, "launch_times.json")
        if os.path.exists(path):
            with open(path) as f:
                times = json.load(f)
        coord_up = None
        cj = os.path.join(client.job_dir, "coordinator.json")
        if os.path.exists(cj):
            coord_up = os.path.getmtime(cj) - t_submit
        if not ok or "first_step_done" not in times:
            return None
        return {
            "submit_to_first_step_s": round(
                times["first_step_done"] - t_submit, 3),
            "submit_to_coordinator_up_s":
                round(coord_up, 3) if coord_up else None,
            "submit_to_task_start_s": round(
                times["payload_start"] - t_submit, 3),
            "submit_to_job_complete_s": round(t_done - t_submit, 3),
        }

    with MiniTonyCluster() as cluster:
        cold = one_job(cluster)
        warm = one_job(cluster)
    if cold is None:
        return {"error": "launch bench job failed"}
    out = dict(cold)
    if warm is not None:
        out["warm_submit_to_first_step_s"] = warm["submit_to_first_step_s"]
        out["warm_start_delta_s"] = round(
            cold["submit_to_first_step_s"] - warm["submit_to_first_step_s"],
            3)
    return out


def _storm_run(edge: str, idle: int, streams: int,
               timeout_s: float) -> dict:
    """Boot one demo-model gateway subprocess behind the given edge,
    drive it with tools/storm.py, SIGTERM-drain it, and return the
    flattened report. The subprocess pins JAX to CPU (this bench is
    host-scheduling-bound; the parent owns any chip)."""
    import signal as _signal

    root = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    gw = subprocess.Popen(
        [sys.executable, "-m", "tony_tpu.cli.gateway", "--demo-model",
         "--edge", edge, "--serve-batch", "64", "--chunk-steps", "4",
         "--max-queue", str(2 * streams + 64),
         "--max-pending", str(2 * streams + 64),
         "--port", "0", "--compile-cache", ""],
        cwd=root, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True)
    try:
        base = None
        deadline = time.time() + 120
        while time.time() < deadline:
            ln = gw.stdout.readline()
            if not ln:
                break
            if "gateway at http://" in ln:
                base = ln.split("gateway at ")[1].split()[0]
                break
        if base is None:
            return {"error": f"{edge} gateway never printed its boot line"}
        storm = subprocess.run(
            [sys.executable, os.path.join(root, "tools", "storm.py"),
             "--base", base, "--idle", str(idle),
             "--streams", str(streams),
             "--tokens", "8", "--bursts", "10", "--burst-gap", "0.2",
             "--check", "16", "--server-pid", str(gw.pid),
             "--timeout", str(timeout_s)],
            cwd=root, capture_output=True, text=True,
            timeout=timeout_s + 120)
        if storm.returncode != 0:
            tail = (storm.stderr or storm.stdout).strip()[-300:]
            return {"error": f"storm.py rc={storm.returncode}: {tail}"}
        doc = json.loads(storm.stdout)
        gw.send_signal(_signal.SIGTERM)
        try:
            drained = gw.wait(timeout=120) == 0
        except subprocess.TimeoutExpired:
            drained = False
        idle_r, st = doc.get("idle", {}), doc.get("storm", {})
        return {
            "edge": edge,
            "idle_connections": idle_r.get("opened"),
            "rss_kb_per_idle_conn": idle_r.get("rss_kb_per_idle_conn"),
            "streams": st.get("launched"),
            "completed_200": st.get("completed_200"),
            "shed": st.get("shed"),
            "shed_rate": st.get("shed_rate"),
            "errors": st.get("errors"),
            "peak_server_threads": st.get("peak_server_threads"),
            "edge_threads": (st.get("edge") or {}).get("threads"),
            "ttft_p50_ms": st.get("ttft_p50_ms"),
            "ttft_p99_ms": st.get("ttft_p99_ms"),
            "tokens_checked": st.get("tokens_checked"),
            "tokens_exact": st.get("tokens_exact"),
            "sigterm_drained_clean": drained,
        }
    finally:
        if gw.poll() is None:
            gw.kill()
            gw.wait(timeout=10)


def bench_storm(on_tpu: bool) -> dict:
    """Connection-storm datum for the event-driven edge (ISSUE-16).
    Slow lane, two measured runs on the demo model:

    1. the event edge under the full storm — 10k parked idle
       keep-alive connections (per-connection RSS cost), then 10k
       concurrent NDJSON streams in bursts (shed rate, TTFT tails,
       token-exact spot checks, peak thread count: the edge's thread
       count must NOT scale with connections);
    2. the thread-per-connection control (``--edge threaded``) at a
       fifth of that load — expected to shed/fail (its collapse IS
       the datum).

    The gate: the event edge completes >= 5x the streams the control
    sustains. ``TONY_BENCH_STORM_STREAMS`` scales both runs down for
    quick passes."""
    streams = int(os.environ.get("TONY_BENCH_STORM_STREAMS", "10000"))
    event = _storm_run("event", idle=streams, streams=streams,
                       timeout_s=600.0)
    if "error" in event:
        return event
    ctrl_streams = max(1, streams // 5)
    control = _storm_run("threaded", idle=0, streams=ctrl_streams,
                         timeout_s=420.0)
    out = {"event": event, "threaded_control": control}
    sustained = control.get("completed_200") or 0
    if control.get("errors") or control.get("shed"):
        # the control could not sustain even its 1/5 load: its max
        # sustainable concurrency is below ctrl_streams
        out["control_max_sustained_streams"] = sustained
    else:
        out["control_max_sustained_streams"] = ctrl_streams
    done = event.get("completed_200") or 0
    out["event_vs_control_ratio"] = round(
        done / max(1, out["control_max_sustained_streams"]), 2)
    out["fivefold_vs_threaded"] = (
        done == event.get("streams")
        and done >= 5 * out["control_max_sustained_streams"])
    return out


def bench_migrate(on_tpu: bool) -> dict:
    """Live-session-migration datum (ISSUE-18 acceptance). One seeded
    stream on a 2-replica gateway whose engines share ONE PagePool and
    are wedge-throttled 30 ms/dispatch (so a mid-stream freeze window
    exists on a CPU-sized model — the costs measured here are host-side
    scheduling + page bookkeeping, the right probe on either backend):

    1. drain-latency A/B: ``remove_replica`` with the stream live,
       migration armed (freeze + owner swap, the survivor resumes) vs
       disabled on the same config (``extract_session`` nulled on the
       victim -> the old decode-to-completion drain). Both arms must
       stay token-identical to a no-migration control and shed nothing;
       the headline is the drain-time ratio.
    2. the bytes ledger: the owner swap moved ZERO pages where a
       cross-host migration would have gathered+copied the session's
       whole KV — the counterfactual ``gather_pages`` copy is run and
       timed so bytes-not-moved has a measured price next to it."""
    import numpy as np

    from tony_tpu.gateway.core import Gateway, GenRequest
    from tony_tpu.models import Transformer, TransformerConfig
    from tony_tpu.serve import FaultPlan, Request, Server
    from tony_tpu.serve.slots import PagePool, gather_pages

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_seq_len=64,
                            dtype=jnp.float32,
                            attention_backend="reference")
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    prompt = np.random.default_rng(3).integers(1, 64, size=13).tolist()
    budget, wedge, page = 48, 0.03, 8

    ctrl = Server(model, params, batch_size=2, eos_id=-1, paged=True,
                  kv_page_size=page, prefix_cache_mb=0)
    ctrl.submit(Request(list(prompt), budget, id="c", temperature=0.8,
                        top_k=8, seed=7))
    expect = list(list(ctrl.run())[0].tokens)

    def run(migrate: bool):
        pool = PagePool(model, params, 128, page, shared=True)
        plan = lambda: FaultPlan.wedge_at(1, wedge, times=-1)  # noqa: E731
        gw = Gateway([Server(model, params, batch_size=2, eos_id=-1,
                             paged=True, kv_page_size=page,
                             prefix_cache_mb=0, page_pool=pool,
                             fault_plan=plan())
                      for _ in range(2)]).start()
        try:
            t = gw.submit(GenRequest(list(prompt), max_new_tokens=budget,
                                     temperature=0.8, top_k=8, seed=7,
                                     id="mig"))
            deadline = time.monotonic() + 60
            while t._n_emitted < 3 and time.monotonic() < deadline:
                time.sleep(0.02)
            victim = gw.replicas[t.replica]
            if not migrate:
                # null the hook -> remove_replica falls back to the
                # pre-ISSUE-18 decode-to-completion drain, same config
                victim.server.extract_session = None
            left = budget - t._n_emitted
            t0 = time.perf_counter()
            assert gw.remove_replica(t.replica, timeout=120)
            drain_s = time.perf_counter() - t0
            tokens = list(t.result(timeout=120).tokens)
            snap = gw.snapshot()
        finally:
            gw.drain(timeout=60)
        assert pool.n_used == 0, "page leak after drain"
        return tokens, drain_s, left, snap, pool

    run(True)  # warm: prefill bucket + decode + adopt programs
    toks_mig, s_mig, left_mig, snap_mig, pool = run(True)
    toks_off, s_off, left_off, snap_off, _ = run(False)
    identical = toks_mig == expect and toks_off == expect
    assert identical, "migration or drain changed seeded outputs"
    mig = snap_mig["engine"]["migrations"]

    # the counterfactual: gathering the frozen session's pages (what a
    # cross-host migration copies) — timed on the same pool geometry
    n_pages = -(-(len(prompt) + budget) // page)
    idx = jnp.arange(n_pages, dtype=jnp.int32)
    gather_ms = []
    for _ in range(5):
        t0 = time.perf_counter()
        jax.block_until_ready(gather_pages(pool.cache, idx))
        gather_ms.append((time.perf_counter() - t0) * 1e3)

    # 3. prefix-delta wire arm (ISSUE-19): freeze a live session to
    #    wire form, trim it against a WARM target's radix summary, and
    #    weigh the two payloads — then actually adopt the delta and
    #    pin the resumed stream to the control (the byte win is only
    #    worth reporting on a token-exact path)
    from tony_tpu.serve.migrate import delta_trim_doc, snapshot_to_doc
    from tony_tpu.serve.tier import payload_nbytes

    src = Server(model, params, batch_size=2, eos_id=-1, paged=True,
                 kv_page_size=page, prefix_cache_mb=0)
    src.submit(Request(list(prompt), budget, id="w", temperature=0.8,
                       top_k=8, seed=7))
    for _ in range(600):
        src.step()
        lv = next((l for l in src._live
                   if l is not None and l.request.id == "w"), None)
        if lv is not None and len(lv.generated) >= budget - 8:
            break
    snap = src.extract_session("w", wire=True)
    assert snap is not None, "wire freeze missed the live window"
    doc = snapshot_to_doc(snap)
    ctx = [int(t) for t in snap.prompt] \
        + [int(t) for t in snap.generated][:-1]
    tgt = Server(model, params, batch_size=2, eos_id=-1, paged=True,
                 kv_page_size=page, prefix_cache_mb=2.0)
    tgt.submit(Request(list(ctx), 1, id="warm"))
    list(tgt.run())
    trimmed = delta_trim_doc(doc, tgt.prefix_summary())
    assert trimmed is not None, "warm-target trim declined"
    full_b, delta_b = payload_nbytes(doc["pages"]), \
        payload_nbytes(trimmed["pages"])
    tgt.submit(Request(list(prompt), budget, id="w", migrate=trimmed))
    toks_delta = {r.id: list(r.tokens) for r in tgt.run()}["w"]
    assert toks_delta == expect, "delta adoption changed seeded outputs"

    # 4. page-granular shared-pool dispatch (ISSUE-19): two co-located
    #    engines on ONE pool, each driven by its own thread — the
    #    two-lock pool lets their dispatch windows overlap vs the
    #    ``serialize_dispatch=True`` single-writer control. Dispatches
    #    are wedge-throttled (10 ms, the drain A/B's trick) so each
    #    window has device-sized latency on a CPU-sized model: the A/B
    #    then measures exactly the lock structure — do co-located
    #    windows overlap or not. Same requests both arms, exactness
    #    asserted.
    import threading

    rng = np.random.default_rng(5)
    prompts = [rng.integers(1, 64, size=9).tolist() for _ in range(4)]
    cbudget = 48

    def pool_arm(serialize: bool):
        pool2 = PagePool(model, params, 128, page, shared=True)
        engines = [Server(model, params, batch_size=2, eos_id=-1,
                          paged=True, kv_page_size=page,
                          prefix_cache_mb=0, page_pool=pool2,
                          serialize_dispatch=serialize,
                          fault_plan=FaultPlan.wedge_at(1, 0.01,
                                                        times=-1))
                   for _ in range(2)]
        outs: list = [None, None]

        def drive(i: int):
            reqs = [Request(list(p), cbudget, id=f"{i}-{j}")
                    for j, p in enumerate(prompts[2 * i:2 * i + 2])]
            outs[i] = {r.id: list(r.tokens)
                       for r in engines[i].run(reqs)}

        threads = [threading.Thread(target=drive, args=(i,))
                   for i in range(2)]
        t0 = time.perf_counter()
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        wall = time.perf_counter() - t0
        assert pool2.n_used == 0, "page leak after concurrent run"
        toks = {**outs[0], **outs[1]}
        return 4 * cbudget / wall, toks

    pool_arm(False)  # warm: compile the d256 decode programs once
    tps_conc, toks_conc = pool_arm(False)
    tps_serial, toks_serial = pool_arm(True)
    assert toks_conc == toks_serial, \
        "shared-pool concurrency changed outputs"

    return {
        "outputs_identical": identical,
        "shed_migrate": snap_mig["shed"],       # the zero-5xx contract
        "shed_decode": snap_off["shed"],
        "tokens_left_at_freeze": left_mig,
        "tokens_left_at_drain_off": left_off,
        "drain_s_migrate": round(s_mig, 4),
        "drain_s_decode_to_completion": round(s_off, 4),
        # the headline: a planned exit costs freeze time, not the
        # stream's remaining decode budget
        "drain_speedup": round(s_off / max(s_mig, 1e-9), 1),
        "migrations_out": mig["out"],
        "migrations_in": mig["in"],
        "owner_swap_pages_moved": mig["pages_moved"],   # stays 0
        "owner_swap_bytes_avoided": mig["bytes_avoided"],
        "freeze_resume_ms": mig["freeze_resume_ms"],
        "gather_copy_pages": n_pages,
        "gather_copy_ms": round(float(np.median(gather_ms)), 3),
        # prefix-delta wire arm (ISSUE-19)
        "delta_outputs_identical": toks_delta == expect,
        "wire_bytes_full": full_b,
        "wire_bytes_delta": delta_b,
        "wire_bytes_ratio": round(full_b / max(delta_b, 1), 1),
        "delta_prefix_tokens": trimmed["delta"]["prefix_tokens"],
        "delta_in": tgt.migrate_delta_in,
        # shared-pool concurrent dispatch arm (ISSUE-19)
        "concurrent_outputs_identical": toks_conc == toks_serial,
        "pool_tok_s_concurrent": round(tps_conc, 1),
        "pool_tok_s_serialized": round(tps_serial, 1),
        "pool_concurrency_speedup": round(
            tps_conc / max(tps_serial, 1e-9), 2),
    }


def bench_recovery(on_tpu: bool) -> dict:
    """Crash-recovery datum (ISSUE-20 acceptance). Two arms:

    1. the crash: a journaling gateway over two HTTP replica agents
       (wedge-throttled 30 ms/dispatch so mid-stream windows exist on
       a CPU-sized model) is ``kill()``-ed mid-stream with 4 live
       requests, then a second gateway replays the WAL and recovers.
       Reported: replay + recovery wall time, adopted vs re-run vs
       finished counts, tokens salvaged without re-decode (the parked
       offsets), attempts charged, and the house rule — every
       recovered stream byte-identical to a never-crashed control,
       zero shed.
    2. the tax: end-to-end tok/s through the same local-replica
       gateway with and without the WAL (default "batch" fsync) —
       what durability costs when nothing crashes."""
    import tempfile

    import numpy as np

    from tony_tpu.gateway import journal as jr
    from tony_tpu.gateway.core import Gateway, GenRequest
    from tony_tpu.gateway.remote import RemoteServer
    from tony_tpu.models import Transformer, TransformerConfig
    from tony_tpu.serve import FaultPlan, Request, Server
    from tony_tpu.serve.agent import AgentHTTP, ReplicaAgent

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_seq_len=64,
                            dtype=jnp.float32,
                            attention_backend="reference")
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    rng = np.random.default_rng(11)
    prompts = [rng.integers(1, 64, size=11).tolist() for _ in range(4)]
    budget, wedge = 40, 0.03

    def mk(**kw):
        kw.setdefault("batch_size", 2)
        kw.setdefault("chunk_steps", 1)
        return Server(model, params, eos_id=-1, paged=True,
                      kv_page_size=8, prefix_cache_mb=0, **kw)

    ctrl = mk(batch_size=4)
    for i, p in enumerate(prompts):
        ctrl.submit(Request(list(p), budget, id=f"r{i}"))
    expect = {r.id: list(r.tokens) for r in ctrl.run()}

    tmp = tempfile.mkdtemp(prefix="bench-recovery-")

    def wait(cond, timeout=60.0):
        deadline = time.monotonic() + timeout
        while not cond() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert cond(), "bench_recovery wait timed out"

    # ---- arm 1: crash + WAL replay + adopt over two HTTP agents
    def slow():
        return FaultPlan.wedge_at(1, wedge, times=-1)

    agents = [AgentHTTP(ReplicaAgent(mk(fault_plan=slow()),
                                     gateway_grace_s=0.3,
                                     park_ttl_s=60), port=0).start()
              for _ in range(2)]

    def stub(a):
        return RemoteServer(a.address, heartbeat_interval_s=0.1,
                            lease_misses=3, read_timeout_s=2.0,
                            boot_timeout_s=20.0)

    j1 = jr.TicketJournal(os.path.join(tmp, "j1.ndjson"))
    gw1 = Gateway([stub(a) for a in agents], journal=j1,
                  park_ttl_s=60).start()
    tickets = [gw1.submit(GenRequest(list(p), max_new_tokens=budget,
                                     id=f"r{i}"))
               for i, p in enumerate(prompts)]
    wait(lambda: all(t._n_emitted >= 3 for t in tickets))
    gw1.kill()  # SIGKILL-shaped: no drain, no compaction
    journal_bytes = os.path.getsize(j1.path)
    t0 = time.perf_counter()
    entries = jr.replay(j1.path)
    replay_ms = (time.perf_counter() - t0) * 1e3
    salvage = sum(e.offset for e in entries.values() if e.live)
    j2 = jr.TicketJournal(os.path.join(tmp, "j2.ndjson"))
    gw2 = Gateway([stub(a) for a in agents], journal=j2,
                  park_ttl_s=60).start()
    try:
        report = gw2.recover_from_journal(entries)
        attempts = 0
        identical = report["shed"] == 0
        for i in range(len(prompts)):
            t = gw2.resume_ticket(f"r{i}")
            res = t.result(timeout=120)
            identical = identical and list(res.tokens) == expect[f"r{i}"]
            attempts += t.metrics["attempts"]
        snap = gw2.snapshot()
        identical = identical and snap["shed"] == {}
    finally:
        gw2.drain(timeout=60)
        for a in agents:
            a.stop()
    compacted = jr.replay(j2.path) == {}

    # ---- arm 2: the WAL's no-crash tax (local replica, no wedge)
    def serve_arm(journal):
        gw = Gateway([mk(batch_size=4)], journal=journal).start()
        try:
            t0 = time.perf_counter()
            ts = [gw.submit(GenRequest(list(p), max_new_tokens=budget,
                                       id=f"t{i}"))
                  for i, p in enumerate(prompts)]
            n = sum(len(t.result(timeout=120).tokens) for t in ts)
            wall = time.perf_counter() - t0
        finally:
            gw.drain(timeout=60)
        return n / wall

    serve_arm(None)  # warm: compile the decode programs once
    tps_plain = serve_arm(None)
    tps_journal = serve_arm(
        jr.TicketJournal(os.path.join(tmp, "jtax.ndjson")))

    return {
        "outputs_identical": identical,     # the house rule
        "streams": len(prompts),
        "adopted": report["adopted"],
        "rerun": report["rerun"],
        "finished": report["finished"],
        "shed": report["shed"],             # stays 0
        "attempts_charged": attempts,       # re-runs only
        "tokens_salvaged": salvage,         # journaled offsets: decode
                                            # work a re-prefill-free
                                            # adopt does NOT repeat
        "journal_bytes_at_crash": journal_bytes,
        "journal_replay_ms": round(replay_ms, 3),
        "recovery_wall_ms": report["wall_ms"],
        "clean_drain_compacts": compacted,
        "tok_s_no_journal": round(tps_plain, 1),
        "tok_s_journal_batch": round(tps_journal, 1),
        "journal_tax": round(
            1.0 - tps_journal / max(tps_plain, 1e-9), 4),
    }


def _maybe_reexec_on_tpu(line: dict) -> dict:
    """End-of-run second chance: the CPU benches took minutes — if the
    tunnel recovered meanwhile, re-run the WHOLE bench pinned to TPU in a
    fresh process (this one is irrevocably pinned to CPU) and ship its
    line instead. Guarded against recursion; the CPU line survives any
    child failure."""
    if os.environ.get("TONY_BENCH_NO_REEXEC") == "1":
        return line
    if _env_platforms and "axon" not in _env_platforms:
        return line  # an explicit CPU request is not a fallback
    if _probe_platform(
            float(os.environ.get("TONY_BENCH_PROBE_TIMEOUT", "150"))) \
            not in ("tpu", "axon"):
        return line  # still down (a 'cpu' probe is not a recovery)
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    env["TONY_BENCH_NO_REEXEC"] = "1"  # child gets ONE shot, no retries
    env["TONY_BENCH_PROBE_RETRIES"] = "1"
    try:
        child = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            capture_output=True, text=True, env=env,
            timeout=float(os.environ.get("TONY_BENCH_REEXEC_TIMEOUT",
                                         "2700")))
        for ln in reversed(child.stdout.strip().splitlines()):
            try:
                parsed = json.loads(ln)
            except ValueError:
                continue
            if isinstance(parsed, dict) and "metric" in parsed:
                child_platform = parsed.get("extras", {}).get("platform")
                if child_platform not in ("tpu", "axon"):
                    return line  # tunnel dropped again mid-child; keep
                    # the cpu line rather than shipping a second one
                    # with false TPU provenance
                parsed.setdefault("extras", {})["reexec"] = \
                    "tpu tunnel recovered after cpu fallback; re-ran on tpu"
                return parsed
    except (subprocess.SubprocessError, OSError):
        pass
    return line


class _StdoutToStderr:
    """FD-level stdout->stderr redirect around the bench body: every
    incidental print — sub-benches, jax/absl noise, the mini cluster's
    children (they inherit fd 1) — lands on stderr, so the artifact JSON
    printed AFTER restore is guaranteed to be the final (and only)
    stdout line and the round driver's ``parsed`` field is non-null
    (VERDICT item 7)."""

    def __enter__(self):
        sys.stdout.flush()
        self._saved = os.dup(1)
        os.dup2(2, 1)
        return self

    def __exit__(self, *exc):
        sys.stdout.flush()
        os.dup2(self._saved, 1)
        os.close(self._saved)
        return False


def main() -> None:
    with _StdoutToStderr():
        line = _collect_line()
    print(json.dumps(line))


def _collect_line() -> dict:
    from tony_tpu.utils import compilecache

    # persistent XLA compile cache, repo-scoped: bench reruns (and the
    # driver's end-of-round run) load yesterday's executables instead of
    # recompiling — this is what un-gates the decode bench on the tunnel
    cache_dir = compilecache.enable(
        os.environ.get("TONY_COMPILE_CACHE_DIR")
        or os.path.join(REPO_DIR, ".jax_compile_cache"))

    import gc

    platform = _platform()  # ONCE: a re-probe after the parent holds the
    # TPU would fail in the child and falsely demote the run to cpu
    on_tpu = platform in ("tpu", "axon")
    resnet = bench_resnet(on_tpu)
    extras = {"resnet": resnet, "platform": platform,
              "peak_flops_per_chip":
                  peak_flops_per_chip() if on_tpu else 0.0}
    try:
        extras["transformer"] = bench_transformer(on_tpu)
    except Exception as e:  # the headline line must survive a sub-bench
        extras["transformer"] = {"error": f"{type(e).__name__}: {e}"}
    gc.collect()  # TrainState/etc cycles pin GBs of HBM until swept
    try:
        extras["attention"] = bench_attention(on_tpu)
    except Exception as e:
        extras["attention"] = {"error": f"{type(e).__name__}: {e}"}
    gc.collect()  # TrainState/etc cycles pin GBs of HBM until swept
    try:
        extras["long_seq"] = bench_long_seq(on_tpu)
    except Exception as e:
        extras["long_seq"] = {"error": f"{type(e).__name__}: {e}"}
    gc.collect()  # TrainState/etc cycles pin GBs of HBM until swept
    try:
        extras["decode"] = bench_decode(on_tpu)
    except Exception as e:
        extras["decode"] = {"error": f"{type(e).__name__}: {e}"}
    gc.collect()  # TrainState/etc cycles pin GBs of HBM until swept
    try:
        extras["decode_1b"] = bench_decode_1b(on_tpu)
    except Exception as e:
        extras["decode_1b"] = {"error": f"{type(e).__name__}: {e}"}
    gc.collect()  # TrainState/etc cycles pin GBs of HBM until swept
    try:
        extras["serving"] = bench_serving(on_tpu)
    except Exception as e:
        extras["serving"] = {"error": f"{type(e).__name__}: {e}"}
    gc.collect()  # TrainState/etc cycles pin GBs of HBM until swept
    try:
        extras["gateway"] = bench_gateway(on_tpu)
    except Exception as e:
        extras["gateway"] = {"error": f"{type(e).__name__}: {e}"}
    gc.collect()  # TrainState/etc cycles pin GBs of HBM until swept
    try:
        extras["prefix"] = bench_prefix(on_tpu)
    except Exception as e:
        extras["prefix"] = {"error": f"{type(e).__name__}: {e}"}
    gc.collect()  # TrainState/etc cycles pin GBs of HBM until swept
    try:
        extras["spec"] = bench_spec(on_tpu)
    except Exception as e:
        extras["spec"] = {"error": f"{type(e).__name__}: {e}"}
    gc.collect()  # TrainState/etc cycles pin GBs of HBM until swept
    try:
        extras["paged"] = bench_paged(on_tpu)
    except Exception as e:
        extras["paged"] = {"error": f"{type(e).__name__}: {e}"}
    gc.collect()  # TrainState/etc cycles pin GBs of HBM until swept
    try:
        extras["disagg"] = bench_disagg(on_tpu)
    except Exception as e:
        extras["disagg"] = {"error": f"{type(e).__name__}: {e}"}
    gc.collect()  # TrainState/etc cycles pin GBs of HBM until swept
    try:
        extras["faults"] = bench_faults(on_tpu)
    except Exception as e:
        extras["faults"] = {"error": f"{type(e).__name__}: {e}"}
    gc.collect()  # TrainState/etc cycles pin GBs of HBM until swept
    try:
        extras["obs"] = bench_obs(on_tpu)
    except Exception as e:
        extras["obs"] = {"error": f"{type(e).__name__}: {e}"}
    gc.collect()  # TrainState/etc cycles pin GBs of HBM until swept
    try:
        extras["goodput"] = bench_goodput(on_tpu)
    except Exception as e:
        extras["goodput"] = {"error": f"{type(e).__name__}: {e}"}
    gc.collect()  # TrainState/etc cycles pin GBs of HBM until swept
    try:
        extras["quant"] = bench_quant(on_tpu)
    except Exception as e:
        extras["quant"] = {"error": f"{type(e).__name__}: {e}"}
    gc.collect()  # TrainState/etc cycles pin GBs of HBM until swept
    try:
        extras["storm"] = bench_storm(on_tpu)
    except Exception as e:
        extras["storm"] = {"error": f"{type(e).__name__}: {e}"}
    gc.collect()  # TrainState/etc cycles pin GBs of HBM until swept
    try:
        extras["migrate"] = bench_migrate(on_tpu)
    except Exception as e:
        extras["migrate"] = {"error": f"{type(e).__name__}: {e}"}
    gc.collect()  # TrainState/etc cycles pin GBs of HBM until swept
    try:
        extras["recovery"] = bench_recovery(on_tpu)
    except Exception as e:
        extras["recovery"] = {"error": f"{type(e).__name__}: {e}"}
    try:
        extras["launch"] = bench_launch()
    except Exception as e:
        extras["launch"] = {"error": f"{type(e).__name__}: {e}"}
    if cache_dir:
        extras["compile_cache"] = {
            "dir": cache_dir, "entries": len(compilecache.entries(cache_dir))}

    line = {
        "metric": "resnet_images_per_sec_per_chip"
                  + ("" if on_tpu else "_cpu_proxy"),
        "value": resnet["images_per_sec_per_chip"],
        "unit": "images/sec/chip",
        "vs_baseline": resnet["vs_native"],
        "extras": extras,
    }
    if on_tpu:
        save_lkg(line)
    else:
        lkg = load_lkg()
        if lkg:
            extras["last_known_good_tpu"] = lkg
        line = _maybe_reexec_on_tpu(line)
    return line


if __name__ == "__main__":
    main()
