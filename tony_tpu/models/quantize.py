"""int8 weight-only serving conversion for the flagship transformer.

``quantize_for_serving(model, params)`` rewrites every dense kernel of a
trained/imported model into the ``{kernel_q8 int8, scale fp32}`` form
that ``TransformerConfig(quantized=True)``'s QuantDense consumes through
the pallas dequant-matmul (ops/quant.py) — HALF the weight bytes per
decode step (docs/PERF.md decode roofline). Embeddings, norms, biases,
and the LM head stay full precision: they are a small fraction of the
bytes and dominate quality.

Scope: the dense transformer family (everything models/hf.py imports —
GPT-2, Llama/Mistral/Qwen2, Gemma, GPT-NeoX, Phi) plus MoE expert
weights (Mixtral: per-expert, per-output-channel scales, served through
a vmapped pallas dequant matmul). scan-stacked layers are rejected
rather than half-converted.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

from tony_tpu.models.transformer import Transformer
from tony_tpu.ops.quant import quantize_q8

# parent module names whose "kernel" leaf becomes int8
_DENSE_NAMES = ("q", "k", "v", "o", "wi", "wg", "wo")


def _quantize_kernel(kernel, is_o: bool):
    """kernel [in, *out] (q/k/v/wi/wg/wo) or [*in, out] (o) -> 2-D
    int8 + per-output-channel scale, matching QuantDense's flatten."""
    arr = np.asarray(kernel)
    if is_o:  # o: [heads, dh, d] — leading axes are the INPUT
        in_flat = arr.shape[0] * arr.shape[1] if arr.ndim == 3 \
            else arr.shape[0]
        w2 = arr.reshape(in_flat, arr.shape[-1])
    else:  # [in, *out]
        w2 = arr.reshape(arr.shape[0], -1)
    w_q, scale = quantize_q8(w2)
    return {"kernel_q8": w_q, "scale": scale}


def quantize_transformer_params(params: Any) -> Any:
    """params pytree (as from model.init / hf import) -> quantized tree.
    Biases ride along unchanged; every other leaf passes through."""

    def quantize_expert(arr) -> tuple[np.ndarray, np.ndarray]:
        # [E, in, out]: contraction over axis 1, so the per-output-channel
        # scale is per (expert, out) — the 3-D analog of quantize_q8
        a = np.asarray(arr, np.float32)
        absmax = np.max(np.abs(a), axis=1)
        scale = np.maximum(absmax, 1e-8) / 127.0
        q = np.clip(np.round(a / scale[:, None, :]), -127, 127) \
            .astype(np.int8)
        return q, scale.astype(np.float32)

    def walk(node, name=""):
        if not isinstance(node, dict):
            return node
        if "kernel" in node and name in _DENSE_NAMES:
            out = _quantize_kernel(node["kernel"], is_o=(name == "o"))
            if "bias" in node:
                out["bias"] = node["bias"]
            extra = set(node) - {"kernel", "bias"}
            if extra:
                raise ValueError(f"unexpected leaves under {name}: {extra}")
            return out
        if "router" in node and "wi" in node:  # MoE expert block (Mixtral)
            out = {"router": node["router"]}
            for nm in ("wi", "wg", "wo"):
                if nm in node:
                    out[nm + "_q8"], out[nm + "_scale"] = \
                        quantize_expert(node[nm])
            extra = set(node) - {"router", "wi", "wg", "wo"}
            if extra:
                raise ValueError(f"unexpected MoE leaves: {extra}")
            return out
        return {k: walk(v, k) for k, v in node.items()}

    return walk(params)


def quantize_for_serving(model: Transformer, params: Any
                         ) -> tuple[Transformer, Any]:
    """(model, params) -> (quantized model, quantized params): the
    returned pair drops into generate()/score exactly like the original.
    """
    cfg = model.cfg
    if cfg.scan_layers:
        raise ValueError("int8 serving conversion expects per-block "
                         "params (scan_layers stacks them)")
    qcfg = dataclasses.replace(cfg, quantized=True)
    return Transformer(qcfg), quantize_transformer_params(params)


def quantize_cli(model, params):
    """CLI-facing wrapper: unsupported configs exit with a clean message
    instead of a traceback (shared by the generate and score CLIs)."""
    try:
        return quantize_for_serving(model, params)
    except ValueError as e:
        raise SystemExit(f"--int8: {e}")
