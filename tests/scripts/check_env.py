"""Exit non-zero unless the common injected env contract holds
(ref: exit_0_check_env.py — the job's final status IS the assertion)."""
import json
import os
import sys

required = ["TONY_JOB_NAME", "TONY_TASK_INDEX", "TONY_TASK_NUM", "TONY_IS_CHIEF",
            "CLUSTER_SPEC", "TONY_JOB_ID", "TONY_SESSION_ID",
            "TONY_JOB_DIR", "TONY_COMPILE_CACHE_DIR"]
missing = [k for k in required if k not in os.environ]
if missing:
    print("missing env:", missing)
    sys.exit(1)

spec = json.loads(os.environ["CLUSTER_SPEC"])
role = os.environ["TONY_JOB_NAME"]
idx = int(os.environ["TONY_TASK_INDEX"])
if role not in spec or idx >= len(spec[role]):
    print("bad spec", spec, role, idx)
    sys.exit(2)
if not spec[role][idx]:
    print("own entry empty in spec", spec)
    sys.exit(3)
sys.exit(0)
