from tony_tpu.train.checkpoint import (
    CheckpointManager,
    auto_resume,
    job_checkpoint_dir,
    restore_or_init,
    scan_latest_step,
)
from tony_tpu.train.loop import FitResult, JsonlMetricsLogger, fit
from tony_tpu.train.lora import (
    lora_init,
    lora_param_count,
    materialize_lora,
    merge_lora,
    wrap_apply_fn,
)
from tony_tpu.ops.adamw import FusedAdamW, FusedAdamWState
from tony_tpu.train.trainer import (
    Trainer,
    TrainState,
    build_train_step,
    cross_entropy_loss,
)

__all__ = [
    "FusedAdamW",
    "FusedAdamWState",
    "lora_init",
    "lora_param_count",
    "materialize_lora",
    "merge_lora",
    "wrap_apply_fn",
    "CheckpointManager",
    "auto_resume",
    "fit",
    "FitResult",
    "JsonlMetricsLogger",
    "job_checkpoint_dir",
    "scan_latest_step",
    "Trainer",
    "TrainState",
    "build_train_step",
    "cross_entropy_loss",
    "restore_or_init",
]
