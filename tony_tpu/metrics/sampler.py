"""Per-task resource metrics sampler.

Reference: TaskMonitor.java:25 — a scheduled thread sampling RSS (via
ResourceCalculatorProcessTree) and GPU util/memory (via nvidia-smi XML,
util/gpu/GpuDiscoverer.java), keeping max + running-average aggregates,
pushed to the coordinator's metrics RPC. The TPU rebuild samples the user
process tree's RSS from /proc and TPU device metrics from the runtime when
available (``tpu-info``/libtpu metrics are not present off-pod; the hook
degrades to absent metrics, mirroring GpuDiscoverer's error cap).
"""

from __future__ import annotations

import logging
import os
import threading

log = logging.getLogger(__name__)

# Metric names (ref: TaskMonitor.METRICS_TO_COLLECT :34-37)
MAX_MEMORY_RSS = "MAX_MEMORY_RSS"
AVG_MEMORY_RSS = "AVG_MEMORY_RSS"
MAX_TPU_UTIL = "MAX_TPU_UTIL"
AVG_TPU_UTIL = "AVG_TPU_UTIL"
MAX_TPU_HBM = "MAX_TPU_HBM"
AVG_TPU_HBM = "AVG_TPU_HBM"


def process_tree_rss_bytes(pid: int) -> int:
    """Sum VmRSS over ``pid`` and its descendants (ResourceCalculator
    equivalent). Returns 0 when the tree is gone."""
    total = 0
    for p in _descendants(pid) | {pid}:
        try:
            with open(f"/proc/{p}/status") as f:
                for line in f:
                    if line.startswith("VmRSS:"):
                        total += int(line.split()[1]) * 1024
                        break
        except (FileNotFoundError, ProcessLookupError, PermissionError):
            continue
    return total


def _descendants(pid: int) -> set[int]:
    children: dict[int, list[int]] = {}
    try:
        for entry in os.listdir("/proc"):
            if not entry.isdigit():
                continue
            try:
                with open(f"/proc/{entry}/stat") as f:
                    parts = f.read().split()
                ppid = int(parts[3])
                children.setdefault(ppid, []).append(int(entry))
            except (OSError, IndexError, ValueError):
                continue
    except OSError:
        return set()
    out: set[int] = set()
    stack = [pid]
    while stack:
        p = stack.pop()
        for c in children.get(p, []):
            if c not in out:
                out.add(c)
                stack.append(c)
    return out


class TaskMetricsMonitor:
    """Sampler thread with max/avg aggregation (ref: setAvgMetrics/
    setMaxMetrics TaskMonitor.java:172-186)."""

    def __init__(self, pid_fn, push_fn, interval_ms: int = 5000,
                 tpu_info_exec_path: str = ""):
        from tony_tpu.utils.tpu_info import TpuDiscoverer

        self.pid_fn = pid_fn  # () -> pid | None of the user process
        self.push_fn = push_fn  # (metrics: dict) -> None
        self.interval_s = max(interval_ms, 100) / 1000
        self.discoverer = TpuDiscoverer(info_exec_path=tpu_info_exec_path)
        self._samples = 0
        self.metrics: dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def sample_once(self) -> dict[str, float]:
        pid = self.pid_fn()
        if pid is None:
            return self.metrics
        rss = float(process_tree_rss_bytes(pid))
        self._samples += 1
        self._fold(MAX_MEMORY_RSS, AVG_MEMORY_RSS, rss)
        tpu = self.discoverer.device_metrics()
        if "util" in tpu:
            self._fold(MAX_TPU_UTIL, AVG_TPU_UTIL, tpu["util"])
        if "hbm" in tpu:
            self._fold(MAX_TPU_HBM, AVG_TPU_HBM, tpu["hbm"])
        return self.metrics

    def _fold(self, max_key: str, avg_key: str, value: float) -> None:
        self.metrics[max_key] = max(self.metrics.get(max_key, 0.0), value)
        prev = self.metrics.get(avg_key, 0.0)
        self.metrics[avg_key] = prev + (value - prev) / self._samples

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.push_fn(self.sample_once())
            except Exception:
                log.exception("metrics push failed")

    def start(self) -> "TaskMetricsMonitor":
        self._thread = threading.Thread(target=self._loop, name="task-metrics",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)


class MetricsStore:
    """Coordinator-side metrics sink (ref: rpc/impl/MetricsRpcServer.java)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._by_task: dict[str, dict[str, float]] = {}

    def update_metrics(self, task_id: str, metrics: dict) -> bool:
        with self._lock:
            self._by_task[task_id] = {k: float(v) for k, v in metrics.items()}
        return True

    def get_metrics(self, task_id: str) -> dict[str, float]:
        with self._lock:
            return dict(self._by_task.get(task_id, {}))
