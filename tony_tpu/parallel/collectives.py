"""Thin named-axis collective helpers for shard_map code.

The data plane of the rebuild: where the reference delegated gradient
exchange to NCCL/Gloo/ps-lite (SURVEY.md section 2.5), here everything is
an XLA collective over ICI/DCN. These wrappers exist for readability and
for the cross-slice (DCN) helpers.
"""

from __future__ import annotations

import jax
from jax import lax


def all_reduce_mean(x, axis_name: str):
    """Gradient averaging for data parallelism (the Horovod-ring analog)."""
    return lax.pmean(x, axis_name)


def all_reduce_sum(x, axis_name: str):
    return lax.psum(x, axis_name)


def all_gather(x, axis_name: str, axis: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str, axis: int = 0):
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def ring_shift(x, axis_name: str, shift: int = 1):
    """Rotate shards around the axis ring (building block of ring attention
    and pipeline flow)."""
    n = lax.psum(1, axis_name)
    perm = [(j, (j + shift) % n) for j in range(n)]
    return lax.ppermute(x, axis_name, perm)


def grad_sync_tree(grads, axis_name: str):
    """pmean every leaf of a gradient pytree."""
    return jax.tree.map(lambda g: lax.pmean(g, axis_name), grads)
