from tony_tpu.session.session import RoleRequest, Session, SessionStatus
from tony_tpu.session.task import Task, TaskInfo, TaskStatus

__all__ = ["Session", "SessionStatus", "RoleRequest", "Task", "TaskInfo", "TaskStatus"]
