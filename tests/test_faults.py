"""Serving fault tolerance (ISSUE 5), pinned by deterministic injection.

The TonY robustness story ported to serving: replica threads heartbeat,
a watchdog declares stalled replicas failed, failed replicas' requests
FAIL OVER token-exactly to healthy replicas (the task-retry analog),
and the failed replica re-earns admission through a circuit breaker.
None of it is testable against real hardware misbehavior — so
``serve/faults.py`` injects failures deterministically, and this file
pins every path:

- ``FaultPlan`` semantics (env parsing, dispatch/request triggers,
  times, wedge) — pure python, no model;
- engine-level injection (the hooks actually fire inside ``step()``);
- the chaos anchor: 2-replica gateway, mid-stream replica kill ->
  zero 5xx, every output token-identical to a fault-free control,
  prefix store + speculation still live on the survivor, and the
  failed replica REJOINS after its breaker probe;
- the ISSUE-5 bugfix: a replica failure never 500s — queued tickets
  survive untouched, and anything genuinely shed (no healthy replica
  left, retry budget gone) sheds 503, retriable;
- the watchdog route: a WEDGED (not raising) dispatch is declared a
  stall and failed over;
- quarantine: a permanently broken replica leaves the rotation;
  all-replicas-down -> clean 503s + health "down".
"""

import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.gateway import (Gateway, GatewayClosed, GenRequest,
                              NoHealthyReplicas, RetryBudgetExhausted, Shed)
from tony_tpu.models import Transformer, TransformerConfig, generate
from tony_tpu.serve import Fault, FaultPlan, InjectedFault, Request, Server


@pytest.fixture(scope="module")
def tiny():
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_seq_len=32,
                            dtype=jnp.float32,
                            attention_backend="reference")
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _solo(tiny, prompt, n):
    model, params = tiny
    out = generate(model, params, jnp.asarray([prompt], jnp.int32),
                   max_new_tokens=n)
    return np.asarray(out)[0].tolist()


def _fast_supervision(**over):
    """Gateway supervision knobs scaled for a CPU tiny-model test:
    sub-second breaker laps, generous-but-bounded stall horizon."""
    kw = dict(max_attempts=3, stall_timeout_s=10.0, breaker_base_s=0.05,
              breaker_max_s=0.2, quarantine_after=5)
    kw.update(over)
    return kw


def _wait_state(replica, state, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if replica.state == state:
            return True
        time.sleep(0.02)
    return replica.state == state


# ------------------------------------------------------ FaultPlan unit


def test_fault_spec_validation():
    with pytest.raises(ValueError, match="trigger"):
        Fault("fail")
    with pytest.raises(ValueError, match="fault op must be one of"):
        Fault("explode", dispatch=1)
    with pytest.raises(ValueError, match="seconds"):
        Fault("wedge", dispatch=1)


def test_fault_plan_dispatch_trigger_fires_once_then_spends():
    plan = FaultPlan.fail_at(2)
    plan.on_dispatch()  # dispatch 1: below the trigger
    with pytest.raises(InjectedFault, match="dispatch 2"):
        plan.on_dispatch()
    plan.on_dispatch()  # spent: dispatch 3 sails through
    assert plan.fired == 1 and plan.n_dispatches == 3


def test_fault_plan_times_minus_one_is_permanent():
    plan = FaultPlan.fail_at(1, times=-1)
    for _ in range(3):
        with pytest.raises(InjectedFault):
            plan.on_dispatch()
    assert plan.fired == 3


def test_fault_plan_request_trigger():
    plan = FaultPlan.fail_request("victim")
    plan.on_admit("bystander")
    with pytest.raises(InjectedFault, match="victim"):
        plan.on_admit("victim")
    plan.on_admit("victim")  # spent


def test_fault_plan_wedge_sleeps():
    plan = FaultPlan.wedge_at(1, seconds=0.05)
    t0 = time.monotonic()
    plan.on_dispatch()  # wedges, does not raise
    assert time.monotonic() - t0 >= 0.05
    assert plan.fired == 1


def test_fault_plan_from_env_parsing_and_replica_filter():
    assert FaultPlan.from_env(env={}) is None
    assert FaultPlan.from_env(env={"TONY_SERVE_FAULTS": "  "}) is None
    env = {"TONY_SERVE_FAULTS": json.dumps(
        [{"op": "fail", "dispatch": 3, "replica": 0},
         {"op": "wedge", "dispatch": 1, "seconds": 0.5}])}
    p0 = FaultPlan.from_env(replica=0, env=env)
    assert len(p0.faults) == 2  # its own + the broadcast fault
    p1 = FaultPlan.from_env(replica=1, env=env)
    assert len(p1.faults) == 1 and p1.faults[0].op == "wedge"
    # a single JSON object works too
    solo = FaultPlan.from_env(env={"TONY_SERVE_FAULTS":
                                   '{"op": "fail", "dispatch": 1}'})
    assert len(solo.faults) == 1
    # typos raise loudly: a silently ignored fault would turn a chaos
    # run into a fault-free control asserting the wrong thing
    with pytest.raises(ValueError, match="not valid JSON"):
        FaultPlan.from_env(env={"TONY_SERVE_FAULTS": "{nope"})
    with pytest.raises(ValueError, match="objects"):
        FaultPlan.from_env(env={"TONY_SERVE_FAULTS": "[1]"})


# -------------------------------------------------- engine-level hooks


def test_engine_dispatch_fault_takes_real_failure_path(tiny):
    """An injected fault surfaces out of step() as a plain RuntimeError
    — the exact shape a real dead dispatch has."""
    model, params = tiny
    server = Server(model, params, batch_size=2, min_bucket=8,
                    fault_plan=FaultPlan.fail_at(2))
    server.submit(Request([1, 2, 3], max_new_tokens=6, id="r"))
    server.step()  # dispatch 1 fine
    with pytest.raises(RuntimeError):
        server.step()
    server.reset()  # the supervisor's recovery: engine serves again
    server.submit(Request([1, 2, 3], max_new_tokens=6, id="r2"))
    res = {r.id: r for r in server.run()}
    assert res["r2"].tokens == _solo(tiny, [1, 2, 3], 6)


def test_engine_request_fault_fires_at_admission(tiny):
    model, params = tiny
    server = Server(model, params, batch_size=2, min_bucket=8,
                    fault_plan=FaultPlan.fail_request("victim"))
    server.submit(Request([1, 2], max_new_tokens=2, id="ok"))
    server.submit(Request([3, 4], max_new_tokens=2, id="victim"))
    with pytest.raises(InjectedFault):  # admission happens inside step
        server.step()


# --------------------------------------------------------- chaos anchor


def test_midstream_replica_kill_is_token_exact_and_rejoins(tiny):
    """THE acceptance test: 2 replicas under load, replica 0 dies
    mid-stream. Every request — in-flight on the dead replica, queued
    behind it, running on the survivor — completes with tokens
    identical to a fault-free run; the client streams carry no
    duplicated or missing tokens across the failover; nothing sheds
    (zero 5xx); prefix store + speculation stay live on the survivor;
    and replica 0 rejoins after its breaker probe."""
    model, params = tiny
    servers = [Server(model, params, batch_size=2, min_bucket=8,
                      chunk_steps=1, prefix_cache_mb=1.0, speculate_k=2,
                      fault_plan=(FaultPlan.fail_at(4) if i == 0
                                  else None))
               for i in range(2)]
    gw = Gateway(servers, max_queue=64, **_fast_supervision())
    # shared prefix across some prompts: the survivor's radix store
    # sees real reuse while absorbing the failover load
    prompts = [[1 + i, 2, 3] for i in range(4)] + \
        [[9, 8, 7, 1 + i] for i in range(4)]
    n_new = 8  # >> 3 successful replica-0 steps: the kill is mid-stream
    streamed: dict[int, list] = {i: [] for i in range(len(prompts))}

    def on_event(ticket, event):
        if event[0] == "tokens":
            streamed[ticket.request.id].extend(event[1])

    # pre-start submits: equal costs alternate 0,1,0,1... so replica 0
    # deterministically holds admitted AND queued tickets when it dies
    tickets = [gw.submit(GenRequest(p, max_new_tokens=n_new, id=i),
                         on_event=on_event)
               for i, p in enumerate(prompts)]
    gw.start()
    for i, t in enumerate(tickets):
        res = t.result(timeout=120)  # a Shed here = the old 500 path
        want = _solo(tiny, prompts[i], n_new)
        assert res.tokens == want, i
        # the client-visible stream reassembles exactly across the kill
        assert streamed[i] == want, i

    snap = gw.snapshot()
    assert snap["shed"] == {}  # zero 5xx (or any shed) for a
    #                            retriable mid-stream failure
    assert snap["completed"] == len(prompts)
    sup = snap["supervision"]
    assert sup["replica_failures"] >= 1
    assert sup["failovers"] >= 1  # tickets moved, not shed
    assert sup["retries"] >= 1    # admitted tickets charged an attempt
    # queued-vs-admitted accounting: only tickets that touched the dead
    # engine are charged; at most one failure each
    attempts = [t.metrics["attempts"] for t in tickets]
    assert max(attempts) == 1 and min(attempts) == 0

    # survivor kept its accelerations through the failover
    assert servers[1].prefix is not None and servers[1].speculate_k == 2
    assert snap["engine"]["prefix"]["enabled"]
    assert snap["engine"]["spec"]["enabled"]

    # the failed replica re-earns admission via its breaker probe
    assert _wait_state(gw.replicas[0], "healthy"), gw.replicas[0].state
    assert gw.replicas[0].rejoins >= 1
    assert gw.replicas[0].probes >= 1
    health = gw.health()
    assert health["status"] == "ok" and health["healthy"] == 2

    # and serves real traffic again
    after = [gw.submit(GenRequest([5, 5 + i], max_new_tokens=4,
                                  id=100 + i)) for i in range(4)]
    for i, t in enumerate(after):
        assert t.result(timeout=120).tokens == _solo(
            tiny, [5, 5 + i], 4)
    assert {t.replica for t in after} == {0, 1}  # both in rotation
    assert gw.drain(timeout=60)


def test_wedged_dispatch_is_declared_stalled_and_failed_over(tiny):
    """The watchdog route: a dispatch that WEDGES (sleeps, never
    raises) stops the replica's heartbeats; the LivenessMonitor
    declares it failed, its tickets re-run token-exactly on the
    survivor, and the stale step's output is fenced off by the epoch
    when the wedge finally returns."""
    model, params = tiny
    servers = [Server(model, params, batch_size=2, min_bucket=8,
                      chunk_steps=1,
                      fault_plan=(FaultPlan.wedge_at(2, seconds=2.0)
                                  if i == 0 else None))
               for i in range(2)]
    gw = Gateway(servers, max_queue=32,
                 **_fast_supervision(stall_timeout_s=0.4))
    prompts = [[1 + i, 2, 3] for i in range(4)]
    tickets = [gw.submit(GenRequest(p, max_new_tokens=6, id=i))
               for i, p in enumerate(prompts)]
    gw.start()
    for i, t in enumerate(tickets):
        assert t.result(timeout=120).tokens == _solo(
            tiny, prompts[i], 6), i
    snap = gw.snapshot()
    assert snap["shed"] == {}
    assert snap["supervision"]["replica_failures"] >= 1
    # the wedge returns into a bumped epoch, recovery probes, rejoins
    assert _wait_state(gw.replicas[0], "healthy"), gw.replicas[0].state
    assert gw.drain(timeout=60)


# -------------------------------------- shed semantics (the 500 bugfix)


def test_single_replica_failure_sheds_503_never_500(tiny):
    """ISSUE-5 satellite bugfix pin: with no healthy replica to fail
    over to, tickets shed 503 (retriable service-unavailable) — the old
    _abort path's 500s, which told clients their REQUESTS were broken,
    are gone. Queued tickets included: they never touched the engine
    but have nowhere to go."""
    model, params = tiny
    servers = [Server(model, params, batch_size=2, min_bucket=8,
                      fault_plan=FaultPlan.fail_at(1, times=-1))]
    gw = Gateway(servers, max_queue=32,
                 **_fast_supervision(quarantine_after=2))
    tickets = [gw.submit(GenRequest([1 + i, 2], max_new_tokens=4, id=i))
               for i in range(3)]  # 2 will be admitted, 1 queued
    gw.start()
    for t in tickets:
        with pytest.raises(Shed) as e:
            t.result(timeout=120)
        assert e.value.http_status == 503, t.request.id
        # and the RIGHT 503: fleet trouble, not "gateway is draining"
        assert isinstance(e.value, NoHealthyReplicas), e.value
    snap = gw.snapshot()
    assert snap["shed"] == {503: 3}  # and NOTHING under 500
    # per-replica shed accounting reconciles with shed_by_status even
    # for gateway-side (post-steal) sheds
    assert sum(r["shed"] for r in snap["replicas"]) == 3
    # times=-1 keeps the probe failing too: quarantined for good
    assert _wait_state(gw.replicas[0], "quarantined")
    assert snap["supervision"]["replica_failures"] >= 1
    health = gw.health()
    assert health["status"] == "down" and health["healthy"] == 0
    # all-replicas-down: the front door sheds clean 503s at submit
    with pytest.raises(NoHealthyReplicas) as e:
        gw.submit(GenRequest([1, 2], max_new_tokens=2))
    assert e.value.http_status == 503
    final = gw.snapshot()
    assert final["supervision"]["quarantines"] == 1
    assert gw.drain(timeout=60)


def test_retry_budget_exhaustion_sheds_503(tiny):
    """Both replicas permanently broken: tickets bounce until their
    attempt budget or the healthy set runs out — shed 503 either way,
    and the retries counter shows the burned attempts."""
    model, params = tiny
    servers = [Server(model, params, batch_size=2, min_bucket=8,
                      fault_plan=FaultPlan.fail_at(1, times=-1))
               for _ in range(2)]
    gw = Gateway(servers, max_queue=32,
                 **_fast_supervision(max_attempts=2, quarantine_after=1))
    tickets = [gw.submit(GenRequest([1 + i, 2], max_new_tokens=4, id=i))
               for i in range(4)]
    gw.start()
    for t in tickets:
        with pytest.raises(Shed) as e:
            t.result(timeout=120)
        assert e.value.http_status == 503
        # budget exhaustion / fleet-down are retriable-503 classes,
        # never GatewayClosed's "shutting down" signal
        assert isinstance(e.value,
                          (RetryBudgetExhausted, NoHealthyReplicas))
        assert not isinstance(e.value, GatewayClosed)
    snap = gw.snapshot()
    assert list(snap["shed"]) == [503]
    assert snap["shed"][503] == 4
    assert snap["supervision"]["retries"] >= 1
    assert gw.drain(timeout=60)


def test_queued_tickets_survive_failure_untouched(tiny):
    """The other half of the bugfix: queued tickets (never admitted to
    the failed engine) move to the survivor with NO attempt charged and
    complete exactly — a replica failure must not cost bystanders their
    retry budget."""
    model, params = tiny
    servers = [Server(model, params, batch_size=1, min_bucket=8,
                      chunk_steps=1,
                      fault_plan=(FaultPlan.fail_at(3) if i == 0
                                  else None))
               for i in range(2)]
    gw = Gateway(servers, max_queue=32,
                 **_fast_supervision(max_attempts=1))
    # max_attempts=1: ANY charged attempt sheds — so the queued
    # tickets completing at all proves they were not charged
    prompts = [[1 + i, 2, 3] for i in range(6)]
    tickets = [gw.submit(GenRequest(p, max_new_tokens=6, id=i))
               for i, p in enumerate(prompts)]
    gw.start()
    done, shed = 0, 0
    for i, t in enumerate(tickets):
        try:
            res = t.result(timeout=120)
            assert res.tokens == _solo(tiny, prompts[i], 6), i
            done += 1
        except Shed as e:
            assert e.http_status == 503  # the one admitted victim,
            shed += 1                    # out of budget at 1 attempt
    # batch_size=1: exactly one ticket was in replica 0's engine when
    # it died; every queued bystander survived and ran exactly
    assert shed <= 1 and done == len(tickets) - shed
    snap = gw.snapshot()
    assert set(snap["shed"]) <= {503}
    assert gw.drain(timeout=60)


def test_wedge_during_drain_still_fails_over(tiny):
    """drain() keeps the watchdog alive until the join completes: a
    dispatch that wedges WHILE its replica drains is still declared
    stalled, its tickets fail over to the other (still-draining)
    replica, and every client gets a terminal event with exact tokens
    — the zero-loss drain promise holds through shutdown."""
    model, params = tiny
    servers = [Server(model, params, batch_size=2, min_bucket=8,
                      chunk_steps=1)
               for i in range(2)]
    # warm each engine's jits BEFORE arming: with a stall horizon this
    # tight (the point of the test), a first-step compile would read
    # as a stall — exactly the --stall-timeout footgun the docs call
    # out. Warming first keeps the fault the ONLY slow dispatch. The
    # warm generation runs LONG enough to cross every paged
    # view-bucket boundary the real run will reach (the paged engine
    # compiles one decode program per power-of-two live-extent bucket,
    # so a short warm would leave a mid-drain compile that reads as a
    # survivor stall).
    for s in servers:
        list(s.run([Request([1, 2], max_new_tokens=28, id="warm")]))
        s.reset()
    servers[0].fault_plan = FaultPlan.wedge_at(2, seconds=2.0)
    # throttle the survivor (30 ms/dispatch, forever): its drain must
    # still be running when the watchdog declares replica 0 stalled
    # (~0.4 s in), or failover correctly finds every other thread
    # already exited and sheds 503 — the OTHER documented drain
    # outcome, not the one this test pins. Per-iteration heartbeats
    # keep the throttled replica far inside the stall horizon.
    servers[1].fault_plan = FaultPlan(
        [Fault("wedge", dispatch=1, seconds=0.03, times=-1)])
    gw = Gateway(servers, max_queue=32,
                 **_fast_supervision(stall_timeout_s=0.4))
    prompts = [[1 + i, 2, 3] for i in range(4)]
    tickets = [gw.submit(GenRequest(p, max_new_tokens=24, id=i))
               for i, p in enumerate(prompts)]
    gw.start()
    assert gw.drain(timeout=120)
    for i, t in enumerate(tickets):
        res = t.result(timeout=10)  # terminal already: drain returned
        assert res.tokens == _solo(tiny, prompts[i], 24), i
    snap = gw.snapshot()
    assert snap["shed"] == {}
    assert snap["completed"] == len(prompts)
    assert snap["supervision"]["replica_failures"] >= 1


def test_delivery_side_accounting_failure_never_strands_a_client(
        tiny, tmp_path):
    """The delivery half runs under the same failure handling as the
    dispatch — and accounting sinks are hardened besides: a history
    row that cannot serialize (object() request id) is dropped with a
    logged exception, the client still gets its done event, and the
    replica stays healthy (no failover burned on bookkeeping)."""
    from tony_tpu.gateway import GatewayHistory
    model, params = tiny
    gw = Gateway([Server(model, params, batch_size=2, min_bucket=8)],
                 max_queue=8,
                 history=GatewayHistory(str(tmp_path), n_replicas=1),
                 **_fast_supervision())
    gw.start()
    res = gw.submit(GenRequest([1, 2, 3], max_new_tokens=4,
                               id=object())).result(timeout=120)
    assert res.tokens == _solo(tiny, [1, 2, 3], 4)
    snap = gw.snapshot()
    assert snap["completed"] == 1
    assert snap["supervision"]["replica_failures"] == 0
    assert gw.replicas[0].state == "healthy"
    assert gw.drain(timeout=60)


# -------------------------------------------------------- e2e (slow)


@pytest.mark.slow  # subprocess boot; tier-1 runs -m 'not slow'
def test_gateway_cli_chaos_env_hook(tmp_path):
    """The make chaos-smoke shape in-test: a real subprocess gateway
    armed through TONY_SERVE_FAULTS kills replica 0 mid-run; every
    HTTP request still answers 200 and /stats shows the failover."""
    import os
    import signal
    import subprocess
    import sys
    import threading
    import urllib.request

    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "PYTHONPATH": os.path.dirname(os.path.dirname(
               os.path.abspath(__file__))),
           "TONY_SERVE_FAULTS": json.dumps(
               {"op": "fail", "dispatch": 4, "replica": 0})}
    proc = subprocess.Popen(
        [sys.executable, "-m", "tony_tpu.cli.gateway", "--demo-model",
         "--replicas", "2", "--port", "0", "--compile-cache", "",
         "--breaker-base", "0.1", "--breaker-max", "1"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        env=env)
    try:
        url = proc.stdout.readline().strip().split()[3]
        codes, errors = [], []

        def client(i):
            try:
                req = urllib.request.Request(
                    url + "/v1/generate",
                    data=json.dumps({"token_ids": [1 + i, 2, 3],
                                     "max_new_tokens": 8,
                                     "id": i}).encode(),
                    headers={"Content-Type": "application/json"})
                codes.append(urllib.request.urlopen(
                    req, timeout=240).status)
            except Exception as e:  # noqa: BLE001 — collected, asserted
                errors.append((i, e))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=240)
        assert not errors, errors
        assert codes == [200] * 8
        stats = json.loads(urllib.request.urlopen(
            url + "/stats", timeout=30).read())
        assert stats["completed"] == 8
        assert stats["supervision"]["replica_failures"] >= 1
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=120) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
