from tony_tpu.agent.executor import main

raise SystemExit(main())
