"""Continuous-batching serving loop (tony_tpu.serve).

The exactness anchor: a request served through the slot scheduler —
including a slot evicted on EOS and re-admitted with a new prompt —
must produce token-for-token the same output as a solo ``generate()``
of that prompt. Scheduler invariants (admit/evict bookkeeping, chunk
overshoot trim, per-request rng isolation) ride along. CPU-only; the
per-slot decode path runs the same einsum attention as the scalar
path, so parity is exact, not approximate.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tony_tpu.models import Transformer, TransformerConfig, generate
from tony_tpu.serve import Request, Server, SlotCache, bucket_len


@pytest.fixture(scope="module")
def tiny():
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_seq_len=32,
                            dtype=jnp.float32,
                            attention_backend="reference")
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _solo(model, params, prompt, n, eos_id=-1):
    out = generate(model, params, jnp.asarray([prompt], jnp.int32),
                   max_new_tokens=n, eos_id=eos_id)
    return np.asarray(out)[0].tolist()


def _solo_trimmed(model, params, prompt, n, eos_ids):
    """Solo generate, cut at the first eos INCLUSIVE (serve reports up
    to and including the stop token; generate freezes past it)."""
    toks = _solo(model, params, prompt, n,
                 eos_id=list(eos_ids) if eos_ids else -1)
    for i, t in enumerate(toks):
        if t in eos_ids:
            return toks[:i + 1]
    return toks


def test_mixed_length_batch_matches_solo(tiny):
    """Mixed-length prompts through 2 slots == per-prompt solo decodes,
    token for token (the continuous-batching correctness anchor)."""
    model, params = tiny
    # three DISTINCT lengths: each solo generate compiles its own
    # prefill, so more lengths buy little extra coverage per second
    prompts = [[1, 2, 3], [5, 9], [17, 46, 10, 20, 62, 26]]
    server = Server(model, params, batch_size=2, eos_id=-1, min_bucket=8)
    results = {r.id: r for r in server.run(
        Request(p, max_new_tokens=6) for p in prompts)}
    assert len(results) == len(prompts)
    for i, p in enumerate(prompts):
        assert results[i].tokens == _solo(model, params, p, 6), p
        assert results[i].finish_reason == "length"
        assert results[i].prompt == p


def test_slot_reuse_after_eos_exact(tiny):
    """A slot evicted on EOS and re-admitted with a new prompt produces
    token-for-token the same output as a solo generate() of that
    prompt — stale cache content must never leak into the new tenant."""
    model, params = tiny
    probe = [17, 46, 10, 20, 62, 26]
    solo = _solo(model, params, probe, 8)
    # an id first emitted mid-sequence: EOS strikes after real decoding
    eos, idx = next((t, i) for i, t in enumerate(solo)
                    if i > 0 and t not in solo[:i])
    follower = [7, 2, 5, 11, 4]
    server = Server(model, params, batch_size=1, eos_id=eos, min_bucket=8)
    res = {r.id: r for r in server.run([
        Request(probe, max_new_tokens=8, id="first"),
        Request(follower, max_new_tokens=6, id="reused"),
    ])}
    assert res["first"].tokens == solo[:idx + 1]
    assert res["first"].finish_reason == "eos"
    # batch_size=1: "reused" decodes in the SAME slot "first" vacated
    assert res["reused"].tokens == _solo_trimmed(model, params, follower,
                                                 6, (eos,))


def test_chunk_size_does_not_change_results(tiny):
    """chunk_steps only trades dispatches for latency: results are
    identical at 1 (token-at-a-time) and 8 (overshoot + trim)."""
    model, params = tiny
    probe = [17, 46, 10, 20, 62, 26]
    solo = _solo(model, params, probe, 8)
    eos = next(t for i, t in enumerate(solo) if i > 0 and t not in solo[:i])
    reqs = [Request(probe, max_new_tokens=8, id="a"),
            Request([5, 9], max_new_tokens=7, id="b"),
            Request([3, 3, 3, 3], max_new_tokens=5, id="c")]
    import copy

    out = {}
    for chunk in (1, 8):
        server = Server(model, params, batch_size=2, eos_id=eos,
                        min_bucket=8, chunk_steps=chunk)
        out[chunk] = {r.id: (r.tokens, r.finish_reason)
                      for r in server.run(copy.deepcopy(reqs))}
    assert out[1] == out[8]


@pytest.mark.parametrize(
    "paged",
    # the unpaged cell rides the slow lane: unpaged frozen behavior is
    # already pinned tier-1 by the mid-chunk-EOS/refill and
    # overshoot-zero tests, and the paged cell compiles a superset of
    # the machinery (paged_view/write_back under freeze)
    [pytest.param(False, marks=pytest.mark.slow), True])
def test_frozen_chunk_invariance_1_vs_16(tiny, paged):
    """The ISSUE-13 chunk-invariance pin, extended to the frozen-slot
    variant: with in-dispatch EOS a chunk_steps=16 engine — deeper
    than every request's budget, so EVERY finishing slot freezes
    mid-chunk — is token-exact vs chunk_steps=1, across mixed EOS and
    budget finishes, paged and unpaged, with zero overshoot and the
    trim walk clean (freeze_faults == 0). Sampled co-tenants pin that
    frozen rows stop advancing rng without moving live draw chains."""
    model, params = tiny
    probe = [17, 46, 10, 20, 62, 26]
    solo = _solo(model, params, probe, 8)
    eos = next(t for i, t in enumerate(solo)
               if i > 0 and t not in solo[:i])
    reqs = [Request(probe, max_new_tokens=8, id="a"),
            Request([5, 9], max_new_tokens=13, id="b"),
            Request([3, 3, 3, 3], max_new_tokens=5, id="c"),
            Request([9, 9, 2], max_new_tokens=7, temperature=0.9,
                    top_k=8, seed=5, id="s")]
    import copy

    out, servers = {}, {}
    for chunk in (1, 16):
        server = Server(model, params, batch_size=2, eos_id=eos,
                        min_bucket=8, chunk_steps=chunk, paged=paged)
        out[chunk] = {r.id: (r.tokens, r.finish_reason)
                      for r in server.run(copy.deepcopy(reqs))}
        servers[chunk] = server
    assert out[1] == out[16]
    deep = servers[16]
    assert deep.wasted_steps == 0
    assert deep.frozen_steps > 0  # budget-5 slot froze inside k=16...
    assert deep.freeze_faults == 0  # ...and re-emitted only its final


@pytest.mark.slow  # two scan_layers+int8 engine compiles; slow lane
def test_frozen_decode_scan_layers_int8(tiny):
    """The remaining cells of the ISSUE-13 overshoot-zero matrix:
    in-dispatch EOS over a scan_layers + int8-KV engine (stacked
    [n_layers] cache counters broadcast the frozen sentinel writes,
    scale leaves drop them too) with speculation riding along —
    token-exact vs the legacy engine, zero wasted steps, trim walk
    clean."""
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_seq_len=64,
                            dtype=jnp.float32,
                            attention_backend="reference",
                            scan_layers=True, kv_cache_quant=True)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    reqs = [Request([1, 2, 3, 4] * 3, max_new_tokens=11, id="rep"),
            Request([7, 9, 11], max_new_tokens=4, id="short")]
    import copy

    out = {}
    for freeze in (False, True):
        # paged auto-downgrades nothing here (no sliding window):
        # exercise the paged default
        server = Server(model, params, batch_size=2, eos_id=-1,
                        min_bucket=8, chunk_steps=8, speculate_k=3,
                        in_dispatch_eos=freeze)
        out[freeze] = {r.id: (r.tokens, r.finish_reason)
                      for r in server.run(copy.deepcopy(reqs))}
        if freeze:
            assert server.wasted_steps == server.spec_drafted \
                - server.spec_accepted  # only rejected drafts remain
            assert server.freeze_faults == 0
    assert out[True] == out[False]


def test_mid_chunk_eos_refill_parity(tiny):
    """A slot that samples EOS mid-chunk freezes in-dispatch, is
    evicted by the trim walk, and its slot refills from the queue the
    same scheduler round — the waiting request's output must be
    token-exact vs a solo generate() (stale frozen re-emits must never
    leak into the next tenant), with zero wasted steps end to end."""
    model, params = tiny
    probe = [17, 46, 10, 20, 62, 26]
    solo = _solo(model, params, probe, 8)
    eos, idx = next((t, i) for i, t in enumerate(solo)
                    if i > 0 and t not in solo[:i])
    followers = [[7, 2, 5, 11, 4], [1, 6, 3], [44, 2, 9, 13]]
    server = Server(model, params, batch_size=2, eos_id=eos,
                    min_bucket=8, chunk_steps=8)
    reqs = [Request(probe, max_new_tokens=8, id="eos-mid")] + [
        Request(f, max_new_tokens=6, id=f"f{i}")
        for i, f in enumerate(followers)]
    res = {r.id: r for r in server.run(reqs)}
    assert res["eos-mid"].tokens == solo[:idx + 1]
    assert res["eos-mid"].finish_reason == "eos"
    for i, f in enumerate(followers):
        assert res[f"f{i}"].tokens == _solo_trimmed(
            model, params, f, 6, (eos,)), f
    assert server.wasted_steps == 0
    assert server.freeze_faults == 0


def test_admit_evict_scheduler_invariants(tiny):
    """More requests than slots: occupancy never exceeds batch_size, a
    slot never hosts two live requests, every request finishes exactly
    once, and the server drains clean."""
    model, params = tiny
    server = Server(model, params, batch_size=2, eos_id=-1, min_bucket=8)
    n = 7
    for i in range(n):
        server.submit(Request([1 + i, 2, 3], max_new_tokens=3 + (i % 4),
                              id=i))
    seen = []
    while not server.done:
        assert server.n_active <= 2
        live = [x for x in server._live if x is not None]
        assert len({id(x.request) for x in live}) == len(live)
        assert server.n_active == len(live)
        for r in server.step():
            seen.append(r.id)
    assert sorted(seen) == list(range(n))
    assert server.n_active == 0 and server.n_pending == 0
    assert server.slots.free_slots() == [0, 1]
    assert server.steps > 0 and server.prefills == n
    # every slot's host state was cleared on evict
    assert not server.slots.active.any()
    assert (server.slots.lengths == 0).all()


def test_greedy_row_isolated_from_sampled_neighbors(tiny):
    """A greedy request's output must not depend on what it is
    co-scheduled with (per-slot rng + row-independent attention)."""
    model, params = tiny
    greedy = Request([1, 2, 3], max_new_tokens=6, id="g")
    alone = {r.id: r.tokens for r in Server(
        model, params, batch_size=2, min_bucket=8).run([greedy])}
    import copy

    mixed = {r.id: r.tokens for r in Server(
        model, params, batch_size=2, min_bucket=8).run([
            copy.deepcopy(greedy),
            Request([9, 9], max_new_tokens=6, temperature=0.9, top_k=8,
                    seed=5, id="s"),
        ])}
    assert mixed["g"] == alone["g"] == _solo(model, params, [1, 2, 3], 6)


def test_sampled_requests_reproducible_by_seed(tiny):
    model, params = tiny

    def reqs():
        return [Request([1, 2, 3], 5, temperature=0.9, top_k=8, seed=7,
                        id=0),
                Request([4, 5], 5, temperature=0.7, seed=3, id=1)]

    runs = []
    for _ in range(2):
        server = Server(model, params, batch_size=2, min_bucket=8)
        runs.append({r.id: r.tokens for r in server.run(reqs())})
    assert runs[0] == runs[1]
    # a different seed moves the draws (overwhelmingly likely)
    server = Server(model, params, batch_size=2, min_bucket=8)
    other = {r.id: r.tokens for r in server.run(
        [Request([1, 2, 3], 5, temperature=0.9, top_k=8, seed=8, id=0),
         Request([4, 5], 5, temperature=0.7, seed=3, id=1)])}
    assert other[1] == runs[0][1]  # untouched request unchanged
    assert all(0 <= t < 64 for t in other[0])


def test_submit_validation_and_budget_clamp(tiny):
    model, params = tiny  # max_seq_len = 32
    server = Server(model, params, batch_size=1, min_bucket=8)
    with pytest.raises(ValueError, match="empty"):
        server.submit(Request([], max_new_tokens=4))
    with pytest.raises(ValueError, match="no room"):
        server.submit(Request(list(range(32)), max_new_tokens=4))
    with pytest.raises(ValueError, match="max_new_tokens"):
        server.submit(Request([1, 2], max_new_tokens=0))
    # a 30-token prompt leaves room for 2: budget of 10 clamps to 2
    server.submit(Request(list(range(1, 31)), max_new_tokens=10, id="c"))
    res = {r.id: r for r in server.run()}
    assert len(res["c"].tokens) == 2
    assert res["c"].finish_reason == "length"


def test_serve_per_slot_matches_solo_with_kv_int8(tiny):
    """Per-slot decode writes quant scales by scatter (the scalar path
    uses dynamic_update_slice): same values, same outputs — greedy
    through the int8 KV cache must equal the solo int8-KV decode."""
    import dataclasses

    model, params = tiny
    qmodel = Transformer(dataclasses.replace(model.cfg,
                                             kv_cache_quant=True))
    prompts = [[1, 2, 3], [5, 9, 11, 8]]
    server = Server(qmodel, params, batch_size=2, min_bucket=8)
    res = {r.id: r for r in server.run(
        Request(p, max_new_tokens=5) for p in prompts)}
    for i, p in enumerate(prompts):
        assert res[i].tokens == _solo(qmodel, params, p, 5), p


def test_serve_flash_decode_backend(tiny):
    """The serving step through the pallas flash-decode kernel
    (interpreted on CPU): per-slot lengths feed the kernel's [B] length
    vector; outputs match the einsum serve path."""
    import dataclasses

    model, params = tiny
    fmodel = Transformer(dataclasses.replace(model.cfg,
                                             decode_attention="flash"))
    prompts = [[1, 2, 3], [5, 9]]
    ref = {r.id: r.tokens for r in Server(
        model, params, batch_size=2, min_bucket=8).run(
        Request(p, max_new_tokens=4) for p in prompts)}
    got = {r.id: r.tokens for r in Server(
        fmodel, params, batch_size=2, min_bucket=8).run(
        Request(p, max_new_tokens=4) for p in prompts)}
    assert got == ref


def test_continuous_beats_fixed_on_decode_steps(tiny):
    """The scheduling claim in its launch-overhead-free form: on a
    mixed-budget workload the continuous scheduler executes strictly
    fewer batched decode steps than fixed batching's
    sum-of-batch-maxima (wall-clock tok/s is bench.py's datum; step
    counts are deterministic and CI-noise-proof)."""
    model, params = tiny
    budgets = [3, 14, 5, 9, 4, 12, 6, 15]
    batch = 4
    fixed_steps = sum(max(budgets[i:i + batch])
                      for i in range(0, len(budgets), batch))
    server = Server(model, params, batch_size=batch, eos_id=-1,
                    min_bucket=8, chunk_steps=4)
    n_done = sum(1 for _ in server.run(
        Request([1 + i, 2, 3], max_new_tokens=b, id=i)
        for i, b in enumerate(budgets)))
    assert n_done == len(budgets)
    assert server.steps < fixed_steps, (server.steps, fixed_steps)


def test_slotcache_admit_evict_reset(tiny):
    model, params = tiny
    slots = SlotCache(model, params, 3)
    assert slots.free_slots() == [0, 1, 2]
    assert list(slots.positions()) == [-1, -1, -1]
    slots.admit(1, length=4, last_token=7, temperature=0.5, top_k=3,
                rng_key=jax.random.PRNGKey(1))
    assert slots.free_slots() == [0, 2]
    assert slots.n_active == 1
    assert list(slots.positions()) == [-1, 4, -1]
    with pytest.raises(ValueError, match="occupied"):
        slots.admit(1, length=2, last_token=0, temperature=0.0, top_k=0,
                    rng_key=jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="length"):
        slots.admit(0, length=0, last_token=0, temperature=0.0, top_k=0,
                    rng_key=jax.random.PRNGKey(0))
    slots.evict(1)
    assert slots.free_slots() == [0, 1, 2]
    slots.admit(0, length=2, last_token=1, temperature=0.0, top_k=0,
                rng_key=jax.random.PRNGKey(0))
    slots.reset()
    assert slots.n_active == 0 and not slots.active.any()


def test_slotcache_row_copy_isolated(tiny):
    """admit(row_cache=...) writes exactly one slot's row: other slots'
    cache content is untouched (the standalone copy path the engine
    fuses into its prefill dispatch)."""
    from tony_tpu.models import init_cache

    model, params = tiny
    slots = SlotCache(model, params, 2)
    row = init_cache(model, params, 1)
    row = jax.tree_util.tree_map(
        lambda x: jnp.full_like(x, 3) if x.ndim >= 3 else x, row)
    before = jax.tree_util.tree_leaves(slots.cache)
    slots.admit(1, length=1, last_token=0, temperature=0.0, top_k=0,
                rng_key=jax.random.PRNGKey(0), row_cache=row)
    for old, new in zip(before, jax.tree_util.tree_leaves(slots.cache)):
        if new.ndim >= 4:  # KV buffers [b, S, kvh, dh]
            np.testing.assert_array_equal(np.asarray(new[0]),
                                          np.asarray(old[0]))
            assert (np.asarray(new[1]) == 3).all()


@pytest.mark.parametrize("scan_layers,kv_int8", [
    # the satellite case: stacked [n_layers, ...] leaves AND int8
    # scale leaves together; the plain layout rides the slow tier
    # (every serve test exercises it implicitly through admit/evict)
    (True, True),
    pytest.param(False, False, marks=pytest.mark.slow)])
def test_slot_row_write_read_roundtrip(scan_layers, kv_int8):
    """read_slot_row is the EXACT inverse of write_slot_row for every
    batched leaf — including scan_layers' stacked [n_layers, ...] KV
    buffers (batch is 4th-from-last, NOT axis 0) and int8-KV scale
    leaves (batch 3rd-from-last). The prefix store's donation path
    (engine._donate -> read_slot_row -> later write via
    _prefill_admit/_hit_admit) depends on this bit-for-bit."""
    import dataclasses

    from tony_tpu.models import init_cache
    from tony_tpu.serve import cache_batch_axis, read_slot_row, \
        write_slot_row

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=2,
                            n_layers=2, d_ff=64, max_seq_len=16,
                            dtype=jnp.float32,
                            attention_backend="reference")
    cfg = dataclasses.replace(cfg, scan_layers=scan_layers,
                              kv_cache_quant=kv_int8)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 4), jnp.int32))["params"]
    cache = init_cache(model, params, 3)
    # fill every leaf with distinct values so a wrong-axis slice would
    # come back provably different
    rng = np.random.default_rng(0)

    def randomize(leaf):
        vals = rng.integers(-100, 100, size=leaf.shape)
        return jnp.asarray(vals, leaf.dtype)

    cache = jax.tree_util.tree_map(randomize, cache)
    row = jax.tree_util.tree_map(
        lambda leaf: randomize(leaf),
        init_cache(model, params, 1))
    slot = 1
    written = write_slot_row(cache, row, slot)
    back = read_slot_row(written, slot)
    leaves_c = jax.tree_util.tree_flatten_with_path(cache)[0]
    leaves_r = jax.tree_util.tree_leaves(row)
    leaves_w = jax.tree_util.tree_leaves(written)
    leaves_b = jax.tree_util.tree_leaves(back)
    saw_scale = saw_stacked = False
    for (path, old), r, w, b in zip(leaves_c, leaves_r, leaves_w,
                                    leaves_b):
        ax = cache_batch_axis(path, old)
        name = str(path[-1].key if hasattr(path[-1], "key")
                   else path[-1])
        if ax is None:
            # shared counters pass through unchanged in both directions
            np.testing.assert_array_equal(np.asarray(w), np.asarray(old))
            np.testing.assert_array_equal(np.asarray(b), np.asarray(old))
            continue
        saw_scale |= name.endswith("_scale")
        saw_stacked |= scan_layers and old.ndim >= 5
        # write-then-read round-trips the row exactly...
        np.testing.assert_array_equal(np.asarray(b), np.asarray(r))
        # ...and the OTHER slots' content is untouched
        others = [i for i in range(3) if i != slot]
        np.testing.assert_array_equal(
            np.asarray(jnp.take(w, np.asarray(others), axis=ax)),
            np.asarray(jnp.take(old, np.asarray(others), axis=ax)))
    assert saw_scale == kv_int8
    if scan_layers:
        assert saw_stacked


def test_bucket_len():
    assert bucket_len(3, 2048) == 16
    assert bucket_len(16, 2048) == 16
    assert bucket_len(17, 2048) == 32
    assert bucket_len(1500, 2048) == 2048
    assert bucket_len(5, 8, minimum=4) == 8


def test_results_stream_in_finish_order(tiny):
    """Short requests surface before long ones submitted earlier — the
    point of iteration-level scheduling."""
    model, params = tiny
    server = Server(model, params, batch_size=2, eos_id=-1, min_bucket=8,
                    chunk_steps=1)
    order = [r.id for r in server.run([
        Request([1, 2, 3], max_new_tokens=12, id="long"),
        Request([5, 9], max_new_tokens=2, id="short"),
    ])]
    assert order == ["short", "long"]


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_serve_cli_jsonl(tiny, tmp_path):
    """generate --serve end-to-end over a local HF checkpoint: JSONL
    in -> JSONL out, greedy parity with HF generate per request."""
    import json
    import os
    import subprocess
    import sys

    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    config = transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=48,
        num_hidden_layers=1, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=32, tie_word_embeddings=True)
    torch.manual_seed(0)
    hf = transformers.LlamaForCausalLM(config).eval()
    mdir = tmp_path / "ckpt"
    hf.save_pretrained(str(mdir))
    reqs = [("a", [1, 2, 3], 4), ("b", [9, 8], 6), ("c", [5, 6, 7, 8], 3)]
    stdin = "\n".join(json.dumps({"id": rid, "token_ids": ids,
                                  "max_new_tokens": n})
                      for rid, ids, n in reqs)
    proc = subprocess.run(
        [sys.executable, "-m", "tony_tpu.cli.generate", "--model",
         str(mdir), "--serve", "--serve-batch", "2", "--eos-id", "63"],
        input=stdin, capture_output=True, text=True, timeout=240,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": os.path.dirname(os.path.dirname(
                 os.path.abspath(__file__)))})
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [json.loads(ln) for ln in proc.stdout.strip().splitlines()]
    got = {ln["id"]: ln for ln in lines}
    assert set(got) == {"a", "b", "c"}
    for rid, ids, n in reqs:
        with torch.no_grad():
            ref = hf.generate(torch.tensor([ids]), max_new_tokens=n,
                              do_sample=False, pad_token_id=0,
                              eos_token_id=63)
        assert got[rid]["token_ids"] == ref[0].tolist(), rid
        assert got[rid]["finish_reason"] in ("eos", "length")
