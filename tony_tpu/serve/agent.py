"""The replica agent: one ``serve.Server`` behind a thin HTTP shim.

The remote half of the TonY container story, serving flavor: the
gateway (the ApplicationMaster analog) acquires hosts through
``coordinator/provisioner.py`` and the work runs THERE — this module
is the TaskExecutor it launches on each host (``python -m
tony_tpu.cli.replica``). It deliberately knows nothing about routing,
admission tiers, failover or supervision; all of that stays in the
gateway, which drives the agent through four endpoints (the wire
behind ``gateway/remote.RemoteServer``):

  POST /v1/submit     one engine request: ``{"id", "prompt": [ids],
                      "max_new_tokens", "temperature", "top_k",
                      "seed", "epoch"}``. Engine refusals keep their
                      types over the wire (``kind`` = "QueueFull" /
                      "PoolExhausted" / "ValueError") so the stub can
                      re-raise them and the gateway's admission paths
                      behave identically local or remote.
  GET  /v1/stream/<id>?offset=N&epoch=E
                      resumable NDJSON: ``{"offset", "token_ids",
                      "epoch"}`` lines at ABSOLUTE token offsets, a
                      ``{"keepalive": true}`` line at least every
                      ``keepalive_s`` while idle (so a healthy-but-
                      quiet stream never trips the client's read
                      timeout), and a final ``{"done": true,
                      "result": {...}}`` line. A dropped connection
                      costs nothing: reconnect with ``offset`` =
                      tokens already received and the stream resumes
                      exactly there — reconnect, not failover. The
                      terminal line additionally carries ``obs``: the
                      dispatch-timeline record fragments THIS request
                      rode (admits by request_id, decode/verify by the
                      ``requests`` tag) — so the gateway can graft the
                      request's complete span set into its trace
                      BEFORE delivering, instead of losing the tail
                      of a short request to the next obs-pull's lag.
                      (The puller dedups against these by agent seq.)
  POST /v1/migrate_in live migration, adopt half (ISSUE-18): the
                      /v1/submit contract plus ``migrate``, a frozen
                      session's wire snapshot (serve/migrate.py) —
                      pages ride the same base64 leaf codec as
                      /v1/handoff; the engine resumes decode at the
                      exact position with no prefill.
  POST /v1/migrate_out
                      live migration, freeze half: ``{"id", "epoch"}``
                      -> ``{"found", "snapshot"}``; the agent freezes
                      the live slot at a dispatch boundary, drops its
                      ticket (the stream continues from the adopting
                      replica), and the session's pages/sampler state
                      leave in wire form.
  GET  /v1/parked     orphaned-session parking (ISSUE-20): every
                      session a (re)connecting gateway can adopt —
                      in-flight slots frozen by the gateway-liveness
                      watchdog (the gateway's heartbeat went silent
                      past ``gateway_grace_s``) plus finished-but-
                      undelivered results, each held ``park_ttl_s``.
  POST /v1/adopt      ``{"id": rid, "epoch"}`` -> the parked session's
                      wire snapshot (or its finished result) — the
                      restart-recovery hand-off. The epoch fence is
                      the double-adopt guard: a second gateway on a
                      stale epoch gets 409, never a second copy; an
                      unknown/reaped rid gets 404 and the caller
                      re-runs from the prompt.
  POST /v1/reset      ``{"epoch"}``: adopt the (newer) epoch, hard-
                      reset the engine, drop every ticket — the
                      gateway's breaker recovery calls this before a
                      probe, so a wedged-then-revived agent sheds its
                      ghost requests instead of decoding for tickets
                      that re-ran elsewhere long ago.
  POST /v1/drain      stop admitting (submit -> 503), finish every
                      in-flight and pending request, reply
                      ``{"drained": true}``. SIGTERM in the CLI takes
                      this path too — the agent deregisters by
                      draining, never by vanishing.
  GET  /healthz       the heartbeat target: engine counters, epoch,
                      slots, ``ok``/``failed``/``draining``, and
                      ``t_mono`` (this process's monotonic clock — the
                      gateway's RTT-midpoint clock-offset estimate
                      reads it) — one cheap GET the gateway's lease
                      rides on.
  GET  /v1/obs?cursor=N
                      the fleet observability channel (ISSUE-15): the
                      engine's dispatch-timeline records with
                      ``seq > cursor`` still in the ring (wire form of
                      ``obs.timeline.DispatchRecord``, timestamps in
                      THIS process's monotonic clock), the lifetime
                      per-kind timeline summary, and the goodput
                      ledger — everything the gateway's obs-puller
                      needs to make this host as observable as an
                      in-process replica. Pull-based and cursor-
                      incremental so a slow gateway costs the agent
                      nothing but the GET; records evicted before
                      being pulled are simply gone (bounded memory
                      beats completeness for a debug channel). No
                      epoch fence: reading records cannot corrupt
                      state, and a fence would only blind the gateway
                      during the exact recoveries it most wants to see.
  POST /v1/profile    ``{"steps": N}``: arm a jax.profiler capture of
                      THIS agent's next N working stepper iterations
                      (the remote half of the gateway's
                      ``POST /debug/profile`` fan-out); the xplane
                      files land on THIS host under the agent's
                      profile dir. GET /v1/profile reports status.

EPOCH FENCE, agent side (the PR-5 fencing token carried over the
wire): every call carries the gateway's epoch for this replica and
every response echoes the epoch the agent is on. The agent adopts any
NEWER epoch it sees and answers 409 to any OLDER one — so once the
gateway has failed this replica over (bumping the epoch), a revived
agent's stale submissions are refused and its stale stream lines are
discarded client-side by the echo check. Neither side ever acts on
the other's past.

Engine faults (``TONY_SERVE_FAULTS``, serve/faults.py) arm the
agent's OWN engine via its environment — a ``step()`` that raises
marks the agent ``failed`` (healthz ok=false, streams end with an
error line, submits 503) until a reset revives it, which is exactly
the wedged-replica shape the gateway's breaker knows how to probe.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qsl, unquote

from tony_tpu.serve.engine import Request, Result, Server

log = logging.getLogger(__name__)

# how long a finished ticket's tokens+result stay fetchable, so a
# client that lost its connection right before the done line can
# reconnect and still collect the result (resume-by-offset covers the
# tokens; this covers the terminal line). ISSUE-20 generalizes this
# into the agent's PARK TTL: orphaned in-flight sessions (gateway
# lease gone silent) freeze into wire snapshots and stay adoptable
# for the same window.
FINISHED_KEEP_S = 60.0


class _StaleEpoch(Exception):
    """A call carried an epoch older than the one this agent adopted."""


class _Ticket:
    """One live-or-recently-finished request's agent-side record.
    ``seq0`` is the engine timeline's sequence number at submit time:
    every dispatch record this request rode has ``seq > seq0``, so the
    terminal-line fragment gather scans only the request's own tail of
    the ring, never the whole ring."""

    __slots__ = ("id", "tokens", "result", "t_done", "seq0", "rid",
                 "epoch")

    def __init__(self, request_id, seq0: int = 0, rid=None,
                 epoch: int = 0):
        self.id = request_id
        self.tokens: list[int] = []
        self.result: dict | None = None
        self.t_done: float | None = None
        self.seq0 = seq0
        # the GATEWAY's request id (ISSUE-20), when the submit carried
        # one — the agent keys tickets by the gateway's per-replica
        # engine id, but parking must be addressable by the id a
        # RESTARTED gateway still knows: the one in its journal
        self.rid = rid
        # the epoch the submit arrived under: the idempotence guard is
        # scoped to it, because a RESTARTED gateway's engine-id counter
        # starts over — its id 1 colliding with the dead incarnation's
        # finished-but-retained id 1 is a fresh request, not a retry
        self.epoch = epoch


def result_doc(res: Result) -> dict:
    """A ``serve.Result`` as its wire form (and back via
    ``result_from_doc``) — the exact fields the gateway's ``_deliver``
    reads. A prefill-pool HANDOFF result additionally carries the page
    payload + last-position logits, base64-encoded leaf-by-leaf
    (serve/tier.py codec, bitwise)."""
    out = {
        "id": res.id,
        "prompt": list(res.prompt),
        "tokens": list(res.tokens),
        "finish_reason": res.finish_reason,
        "prefix_hit_tokens": res.prefix_hit_tokens,
        "prefill_tokens_saved": res.prefill_tokens_saved,
        "drafted": res.drafted,
        "accepted": res.accepted,
        "prefill_chunks": res.prefill_chunks,
    }
    if res.handoff is not None:
        from tony_tpu.serve.tier import encode_array, encode_payload

        out["handoff"] = {
            "n_tokens": int(res.handoff["n_tokens"]),
            "pages": encode_payload(res.handoff["pages"]),
            "logits": encode_array(res.handoff["logits"]),
        }
    return out


def result_from_doc(doc: dict) -> Result:
    res = Result(
        id=doc["id"], prompt=list(doc["prompt"]),
        tokens=list(doc["tokens"]), finish_reason=doc["finish_reason"],
        prefix_hit_tokens=int(doc.get("prefix_hit_tokens", 0)),
        prefill_tokens_saved=int(doc.get("prefill_tokens_saved", 0)),
        drafted=int(doc.get("drafted", 0)),
        accepted=int(doc.get("accepted", 0)),
        prefill_chunks=int(doc.get("prefill_chunks", 0)))
    # the payload stays in WIRE form: a pure-router gateway relays it
    # to the decode replica verbatim, and the receiving ENGINE decodes
    # against its own cache treedef (local engines take it directly;
    # remote stubs pass it through /v1/handoff untouched)
    res.handoff = doc.get("handoff")
    return res


class ReplicaAgent:
    """Owns the engine and the ONE thread allowed to ``step()`` it.

    HTTP handler threads only ever call the engine's thread-safe
    ``submit()``; everything else (step, reset, drain) runs on the
    stepper thread, fed through a small command list — the same
    single-owner step contract the in-process ``_Replica`` keeps."""

    def __init__(self, server: Server, *, agent_id: str | None = None,
                 keepalive_s: float = 0.5,
                 profile_dir: str | None = None,
                 park_ttl_s: float | None = None,
                 gateway_grace_s: float = 0.0):
        from tony_tpu.profiler import ServeProfiler

        self.server = server
        self.agent_id = agent_id or f"agent-{uuid.uuid4().hex[:8]}"
        self.keepalive_s = max(0.05, keepalive_s)
        # orphaned-session parking (ISSUE-20): how long a parked
        # snapshot or finished-but-undelivered result stays adoptable
        # (generalizes FINISHED_KEEP_S), and how long the gateway may
        # go silent before in-flight slots freeze into parked
        # snapshots instead of decoding into the void (0 = watchdog
        # off: slots run to completion and park as finished results)
        self.park_ttl_s = FINISHED_KEEP_S if park_ttl_s is None \
            else max(1.0, float(park_ttl_s))
        self.gateway_grace_s = max(0.0, float(gateway_grace_s))
        self._last_contact = time.monotonic()
        self._parked: dict = {}  # rid -> {snapshot, epoch, offset, t_park}
        # on-demand xplane captures (POST /v1/profile — the remote half
        # of the gateway's /debug/profile fan-out): polled once per
        # WORKING stepper iteration; an un-armed poll is one attribute
        # read
        self.profiler = ServeProfiler(profile_dir)
        self.epoch = 0
        self.failed: str | None = None
        self.draining = False
        self.drained = threading.Event()  # the CLI's exit signal
        self._tickets: dict = {}
        self._cmds: list = []  # (kind, done_event) for the stepper
        # stepper heartbeat: refreshed once per loop iteration (idle
        # waits included). A dispatch that WEDGES inside step() stops
        # it — /healthz exposes the age so the gateway's lease can
        # treat a wedged-but-network-healthy agent as dead for serving
        self.last_step_beat = time.monotonic()
        self._cond = threading.Condition()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop,
                                        name="replica-agent-step",
                                        daemon=True)

    # ------------------------------------------------------- lifecycle

    def start(self) -> "ReplicaAgent":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        self._thread.join(timeout=5)
        # finalize a capture left mid-flight (operator armed it, the
        # agent drained) so its xplane files land
        self.profiler.close()

    # ------------------------------------------------------- the wire

    def check_epoch(self, epoch: int) -> None:
        """Adopt a newer epoch, refuse an older one (409 upstream).
        Under the condition lock so adopt-vs-adopt can't interleave."""
        # every epoch-carrying call is gateway contact: the parking
        # watchdog's liveness signal (ISSUE-20). A STALE call counts
        # too — a gateway on an old epoch is alive, just fenced.
        self._last_contact = time.monotonic()
        with self._cond:
            if epoch < self.epoch:
                raise _StaleEpoch(
                    f"stale epoch {epoch} (agent is on {self.epoch})")
            if epoch > self.epoch:
                log.info("agent %s adopting epoch %d (was %d)",
                         self.agent_id, epoch, self.epoch)
                self.epoch = epoch

    def submit(self, doc: dict) -> dict:
        """POST /v1/submit body -> response doc. Raises the engine's
        own refusal types (handler maps them to status + ``kind``)."""
        self.check_epoch(int(doc.get("epoch", 0)))
        if self.draining:
            raise RuntimeError("agent is draining")
        if self.failed is not None:
            raise RuntimeError(f"agent failed: {self.failed}")
        req = Request(
            prompt=[int(t) for t in doc["prompt"]],
            max_new_tokens=int(doc.get("max_new_tokens", 64)),
            temperature=float(doc.get("temperature", 0.0)),
            top_k=int(doc.get("top_k", 0)),
            seed=int(doc.get("seed", 0)),
            id=doc.get("id"),
            # disaggregation over the wire: prefill_only rides
            # /v1/submit; a handoff payload arrives via /v1/handoff
            # (same body + the encoded pages) — the engine decodes it
            prefill_only=bool(doc.get("prefill_only", False)),
            handoff=doc.get("handoff"),
            # live migration (ISSUE-18): a frozen session's wire doc
            # arrives via /v1/migrate_in — the engine adopts it with no
            # prefill and resumes decode at the exact position
            migrate=doc.get("migrate"))
        with self._cond:
            # IDEMPOTENT on the request id WITHIN the epoch: the stub
            # retries connect errors, and a reset that lands after the
            # agent processed the submit but before the stub read the
            # 200 would otherwise enqueue the same request twice
            # (double slot + page consumption under one id). A
            # colliding id under an OLDER epoch is a different gateway
            # incarnation (ISSUE-20: a restarted gateway's engine-id
            # counter starts over, and finished tickets of the dead
            # one linger for the reconnect grace) — evict the stale
            # record and admit fresh, or the recovered dispatch would
            # stream a dead gateway's result
            held = self._tickets.get(req.id)
            if held is not None and held.epoch >= self.epoch:
                return {"ok": True, "id": req.id, "epoch": self.epoch,
                        "duplicate": True}
            if held is not None:
                del self._tickets[req.id]
            # ticket registered UNDER the lock before the engine sees
            # the request: a stream connecting right after the 200 must
            # find it. seq0 read BEFORE the engine submit: any record
            # this request rides has a later sequence number.
            tl = self.server.timeline
            seq0 = tl.seq if tl is not None else 0
            self.server.submit(req)  # engine submit() is thread-safe;
            # inside our lock only to pair with the ticket insert
            self._tickets[req.id] = _Ticket(req.id, seq0,
                                            rid=doc.get("rid"),
                                            epoch=self.epoch)
            self._cond.notify_all()
        return {"ok": True, "id": req.id, "epoch": self.epoch}

    def reset(self, epoch: int) -> dict:
        """POST /v1/reset: adopt the epoch, hard-reset the engine on
        the stepper thread, drop every ticket."""
        self.check_epoch(int(epoch))
        done = threading.Event()
        with self._cond:
            self._cmds.append(("reset", done))
            self._cond.notify_all()
        if not done.wait(timeout=10):
            raise RuntimeError("reset did not complete in 10s")
        return {"ok": True, "epoch": self.epoch}

    def drain(self, timeout_s: float = 120.0) -> dict:
        """POST /v1/drain: stop admitting, finish everything."""
        self.draining = True
        with self._cond:
            self._cond.notify_all()
        deadline = time.monotonic() + max(0.0, timeout_s)
        with self._cond:
            while not self.server.done and self.failed is None \
                    and time.monotonic() < deadline:
                self._cond.wait(timeout=0.1)
            ok = self.server.done
        self.drained.set()
        return {"drained": bool(ok), "epoch": self.epoch}

    def healthz(self) -> dict:
        # the heartbeat IS the gateway's liveness signal to us: the
        # inverse of the PR-11 lease (they watch our stepper_age_s,
        # we watch their heartbeat cadence)
        self._last_contact = time.monotonic()
        server = self.server
        return {
            "ok": self.failed is None,
            "failed": self.failed,
            "draining": self.draining,
            "agent_id": self.agent_id,
            "pid": os.getpid(),
            "epoch": self.epoch,
            "batch_size": server.slots.batch_size,
            "max_seq_len": server.model.cfg.max_seq_len,
            "n_active": server.n_active,
            "n_pending": server.n_pending,
            "stepper_age_s": round(
                time.monotonic() - self.last_step_beat, 3),
            "paged": bool(server.paged),
            "speculate_k": server.speculate_k,
            "prefix": server.prefix is not None,
            # bounded radix summary (ISSUE-18): [[n_tokens, crc32],
            # ...] of cached prefixes, so the gateway's prefix-affinity
            # probe can score THIS remote replica instead of assuming 0
            "prefix_summary": server.prefix_summary(),
            "n_parked": len(self._parked),
            "park_ttl_s": self.park_ttl_s,
            "counters": server.counters(),
            # this process's monotonic clock, read in-handler: the
            # gateway brackets the call and estimates the clock offset
            # as t_mono - RTT midpoint (uncertainty = RTT/2)
            "t_mono": time.monotonic(),
        }

    def migrate_out(self, doc: dict) -> dict:
        """POST /v1/migrate_out: freeze one live session into its wire
        snapshot and drop its ticket — the source half of a remote
        migration (ISSUE-18). The engine's dispatch lock lands the
        freeze at a dispatch boundary, so the snapshot is token-exact
        no matter where the stepper was. ``found: false`` when the
        request is not in a live decode slot (still pending or
        mid-prefill — nothing worth moving; the caller re-runs it as
        an ordinary request)."""
        from tony_tpu.serve.migrate import snapshot_to_doc

        self.check_epoch(int(doc.get("epoch", 0)))
        if self.failed is not None:
            raise RuntimeError(f"agent failed: {self.failed}")
        rid = doc.get("id")
        snap = self.server.extract_session(rid, wire=True)
        if snap is None:
            return {"found": False, "epoch": self.epoch}
        with self._cond:
            # the ticket moves with the session: its stream continues
            # from the ADOPTING replica, and leaving it here would
            # park a never-finishing entry on the mux channel
            self._tickets.pop(rid, None)
            self._cond.notify_all()
        return {"found": True, "snapshot": snapshot_to_doc(snap),
                "epoch": self.epoch}

    # ------------------------------------- orphan parking (ISSUE-20)

    def parked(self) -> dict:
        """GET /v1/parked: every session a (re)connecting gateway can
        adopt — frozen in-flight snapshots AND finished-but-undelivered
        results (both held through the park TTL). No epoch fence:
        listing is read-only, and a recovering gateway needs it BEFORE
        it knows what epoch to adopt with."""
        now = time.monotonic()
        with self._cond:
            rows = [{"rid": rid, "epoch": p["epoch"],
                     "offset": p["offset"], "finished": False,
                     "age_s": round(now - p["t_park"], 3)}
                    for rid, p in self._parked.items()]
            rows += [{"rid": t.rid if t.rid is not None else t.id,
                      "epoch": self.epoch, "offset": len(t.tokens),
                      "finished": True,
                      "age_s": round(now - t.t_done, 3)}
                     for t in self._tickets.values()
                     if t.result is not None]
        return {"parked": rows, "epoch": self.epoch,
                "park_ttl_s": self.park_ttl_s}

    def adopt(self, doc: dict) -> dict:
        """POST /v1/adopt ``{"id": rid, "epoch"}``: hand one parked
        session to the calling gateway. The epoch fence IS the
        double-adopt guard: the first adopter arrives with a bumped
        epoch the agent adopts; a second gateway still on the old one
        gets 409, never a second copy. Resolution order — a parked
        snapshot, then a still-live slot (frozen on the spot, so a
        recovering gateway never waits out the watchdog grace), then a
        finished-but-undelivered result; ``found: false`` (404
        upstream) when the rid is unknown or the TTL already reaped
        it, and the caller re-runs from the prompt."""
        from tony_tpu.serve.migrate import snapshot_to_doc

        self.check_epoch(int(doc.get("epoch", 0)))
        rid = doc.get("id")
        with self._cond:
            p = self._parked.pop(rid, None)
        if p is not None:
            return {"found": True, "snapshot": p["snapshot"],
                    "offset": p["offset"], "epoch": self.epoch}
        engine_id = finished = None
        with self._cond:
            for t in self._tickets.values():
                if t.rid == rid or t.id == rid:
                    if t.result is not None:
                        finished = t
                    else:
                        engine_id = t.id
                    break
        if finished is not None:
            with self._cond:
                self._tickets.pop(finished.id, None)
                self._cond.notify_all()
            return {"found": True, "finished": True,
                    "result": finished.result, "epoch": self.epoch}
        if engine_id is not None:
            snap = self.server.extract_session(engine_id, wire=True)
            if snap is not None:
                with self._cond:
                    self._tickets.pop(engine_id, None)
                    self._cond.notify_all()
                return {"found": True,
                        "snapshot": snapshot_to_doc(snap),
                        "offset": len(snap.generated),
                        "epoch": self.epoch}
        return {"found": False, "epoch": self.epoch}

    def _watchdog_tick(self) -> None:
        """One stepper-loop beat of the parking machinery: reap parked
        entries past the TTL (the pages they held were gathered to
        host memory at freeze time — reaping is a dict delete), then
        freeze orphans once the gateway has been silent past the
        grace."""
        now = time.monotonic()
        with self._cond:
            dead = [rid for rid, p in self._parked.items()
                    if now - p["t_park"] > self.park_ttl_s]
            for rid in dead:
                del self._parked[rid]
        if dead:
            log.info("agent %s reaped %d parked session(s) past the "
                     "%.0fs park TTL", self.agent_id, len(dead),
                     self.park_ttl_s)
        if self.gateway_grace_s <= 0 or self.draining \
                or self.failed is not None:
            return
        if now - self._last_contact <= self.gateway_grace_s:
            return
        self._park_orphans()

    def _park_orphans(self) -> None:
        """Freeze every live decode slot into a parked wire snapshot —
        the gateway lease went silent, so instead of decoding into the
        void (and then aborting), the sessions park token-exact and
        wait for a recovering gateway's /v1/adopt. Runs on the stepper
        thread; ``extract_session`` lands each freeze at a dispatch
        boundary. Requests still pending (no slot yet) keep running
        and park later — as live slots on a future tick, or as
        finished-but-undelivered results."""
        from tony_tpu.serve.migrate import snapshot_to_doc

        with self._cond:
            live = [(t.id, t.rid) for t in self._tickets.values()
                    if t.result is None]
        n = 0
        for engine_id, rid in live:
            try:
                snap = self.server.extract_session(engine_id, wire=True)
            except Exception:
                log.exception("freeze-for-parking failed (%r)",
                              engine_id)
                continue
            if snap is None:
                continue  # pending / mid-prefill: nothing frozen yet
            key = rid if rid is not None else engine_id
            with self._cond:
                self._parked[key] = {
                    "snapshot": snapshot_to_doc(snap),
                    "epoch": self.epoch,
                    "offset": len(snap.generated),
                    "t_park": time.monotonic(),
                }
                self._tickets.pop(engine_id, None)
                self._cond.notify_all()
            n += 1
        if n:
            log.warning(
                "agent %s: gateway silent %.1fs — parked %d in-flight "
                "session(s) (TTL %.0fs)", self.agent_id,
                time.monotonic() - self._last_contact, n,
                self.park_ttl_s)

    def obs(self, cursor: int) -> dict:
        """GET /v1/obs payload: incremental timeline records past
        ``cursor``, the lifetime summary, and the goodput ledger.
        Degrades to an empty channel with the timeline off — an agent
        booted ``timeline=False`` is unobservable, not broken."""
        from tony_tpu.obs.timeline import record_doc

        tl = self.server.timeline
        if tl is None:
            return {"cursor": 0, "records": [], "summary": {},
                    "goodput": None, "epoch": self.epoch,
                    "t_mono": time.monotonic()}
        new, new_cursor = tl.take_new(max(0, int(cursor)))
        return {
            "cursor": new_cursor,
            "records": [record_doc(r) for r in new],
            "summary": tl.summary(),
            "goodput": self.server.goodput(),
            "epoch": self.epoch,
            "t_mono": time.monotonic(),
        }

    def request_obs(self, request_id) -> list:
        """The dispatch-record fragments one request rode (wire form),
        scanned from the timeline ring at stream end: admit records by
        ``request_id``, decode/verify records by the ``requests`` tag.
        Rides the stream's terminal line so the gateway grafts a
        finished request's COMPLETE span set before delivery — the
        cursor pull alone would lose the tail of any request shorter
        than one heartbeat. The scan anchors at the ticket's
        submit-time seq (``since(seq0)``) — the request's own slice of
        the ring, not the whole ring, so the gather cannot contend
        O(ring) work per finished request against the engine's hot
        ``record()`` lock. Ring-bounded like everything else here:
        records already evicted are gone, which only happens to
        requests that outlived the whole ring."""
        tl = self.server.timeline
        if tl is None:
            return []
        from tony_tpu.obs.timeline import record_doc

        with self._cond:
            ticket = self._tickets.get(request_id)
            seq0 = ticket.seq0 if ticket is not None else 0
        out = []
        for rec in tl.since(seq0):
            if rec.request_id == request_id or request_id in (
                    rec.tags.get("requests") or ()):
                out.append(record_doc(rec))
        return out

    # -------------------------------------------------------- stepper

    def _loop(self) -> None:
        while not self._stop.is_set():
            self.last_step_beat = time.monotonic()
            # BEFORE the idle short-circuit: a fully-parked agent is
            # idle (its slots were extracted), but the TTL reap and
            # the gateway-liveness watchdog must still run
            self._watchdog_tick()
            with self._cond:
                cmds, self._cmds = self._cmds, []
                busy = bool(self.server.n_active or self.server.n_pending)
                if not cmds and (not busy or self.failed is not None):
                    self._cond.wait(timeout=0.05)
                    continue
            for kind, done in cmds:
                if kind == "reset":
                    try:
                        self.server.reset()
                    except Exception:
                        log.exception("agent engine reset failed")
                    with self._cond:
                        self._tickets.clear()
                        self.failed = None
                        self._cond.notify_all()
                    done.set()
            if self.failed is not None:
                continue
            if not (self.server.n_active or self.server.n_pending):
                continue
            try:
                finished = self.server.step()
                # one WORKING iteration: the on-demand profile capture
                # counts it (near-free attribute read while un-armed) —
                # the agent-side twin of the gateway replica loop's poll
                self.profiler.poll()
                with self._cond:  # snapshot: submits mutate the dict
                    seen = {t.id: len(t.tokens)
                            for t in self._tickets.values()
                            if t.result is None}
                progress = self.server.live_progress(seen)
            except Exception as e:  # noqa: BLE001 — an engine failure
                # (injected or real) must not kill the agent process:
                # mark failed, end the streams, let the GATEWAY's
                # supervision decide (its heartbeat sees ok=false, its
                # breaker revives us through /v1/reset + probe)
                log.exception("agent engine step failed")
                try:
                    self.server.reset()
                except Exception:
                    log.exception("agent engine reset after failure")
                with self._cond:
                    self.failed = f"{type(e).__name__}: {e}"
                    self._tickets.clear()
                    self._cond.notify_all()
                continue
            now = time.monotonic()
            with self._cond:
                for rid, new in progress.items():
                    t = self._tickets.get(rid)
                    # ``new`` is the TAIL past what we already hold
                    # (live_progress(since=held)): append it — only
                    # this thread mutates tokens, so held counts taken
                    # above are still exact here
                    if t is not None and t.result is None and new:
                        t.tokens.extend(new)
                for res in finished:
                    t = self._tickets.get(res.id)
                    if t is None:  # e.g. the breaker probe driven by
                        continue   # run()? every submit makes a ticket
                    t.tokens = list(res.tokens)
                    t.result = result_doc(res)
                    t.t_done = now
                # prune finished tickets past the reconnect grace
                # (the park TTL, ISSUE-20 — FINISHED_KEEP_S default)
                for rid in [rid for rid, t in self._tickets.items()
                            if t.t_done is not None
                            and now - t.t_done > self.park_ttl_s]:
                    del self._tickets[rid]
                self._cond.notify_all()

    # --------------------------------------------------------- streams

    def stream_events(self, request_id, offset: int, epoch: int):
        """Generator of NDJSON docs for GET /v1/stream/<id>: token
        windows at absolute offsets from ``offset`` on, keepalives
        while idle, one terminal doc (done / error), then ends. Runs
        on the HTTP handler's own thread; only reads agent state under
        the condition."""
        self.check_epoch(epoch)
        offset = max(0, int(offset))
        last_emit = time.monotonic()
        while True:
            # each lap follows a frame the caller consumed (or is the
            # first): a gateway actively reading this stream is NOT
            # silent — refresh the parking watchdog's liveness signal
            self._last_contact = time.monotonic()
            with self._cond:
                t = self._tickets.get(request_id)
                if t is None:
                    yield {"error": f"unknown ticket {request_id!r}",
                           "gone": True, "epoch": self.epoch}
                    return
                if self.epoch != epoch:
                    # the gateway moved on mid-stream (reset/adopt):
                    # this stream is a previous epoch's — end it
                    yield {"error": "epoch superseded", "stale": True,
                           "epoch": self.epoch}
                    return
                if self.failed is not None:
                    yield {"error": self.failed, "failed": True,
                           "epoch": self.epoch}
                    return
                if t.epoch != epoch:
                    # a DEAD incarnation's leftover still holds this
                    # engine id (restarted gateways restart their id
                    # counters; finished tickets are retained a park
                    # TTL for reconnects): serving ITS tokens would
                    # hand the caller another request's output. The
                    # fresh submit that evicts it is in flight — wait.
                    self._cond.wait(timeout=self.keepalive_s)
                    tokens, result = [], None
                else:
                    tokens = t.tokens[offset:]
                    result = t.result
                    if not tokens and result is None:
                        self._cond.wait(timeout=self.keepalive_s)
                        tokens = t.tokens[offset:]
                        result = t.result
            if tokens:
                yield {"offset": offset, "token_ids": tokens,
                       "epoch": self.epoch}
                offset += len(tokens)
                last_emit = time.monotonic()
            if result is not None:
                yield {"done": True, "result": result,
                       "obs": self.request_obs(request_id),
                       "epoch": self.epoch}
                return
            if time.monotonic() - last_emit >= self.keepalive_s:
                yield {"keepalive": True, "epoch": self.epoch}
                last_emit = time.monotonic()

    def channel_events(self, resume: dict, epoch: int,
                       obs_cursor: int | None = None):
        """Generator of tagged NDJSON frames for POST /v1/channel — the
        MULTIPLEXED form of ``stream_events`` (ISSUE-16): ONE long-lived
        connection carries every ticket's stream, each frame tagged with
        its request id:

          {"channel": true, "resumed": N, "epoch"}     the accept frame
          {"rid", "off", "token_ids", "epoch"}         token window at
                                                       absolute offset
          {"rid", "done": true, "result", "obs", "epoch"}  terminal
          {"rid", "gone": true, "epoch"}               unknown ticket
                                                       (agent restart)
          {"keepalive": true, "epoch"}                 idle heartbeat
          {"obs": <v1/obs doc>, "epoch"}               incremental obs
                                                       batch (when the
                                                       caller sent
                                                       obs_cursor)
          {"stale": true, ...} / {"failed": true, ...} channel over

        ``resume`` maps request id -> tokens the caller already holds;
        a reconnect re-establishes EVERY in-flight stream at its
        absolute offset in this one round trip. Tickets the agent
        finished that the caller did NOT name in ``resume`` were fully
        delivered on a previous channel incarnation — they are skipped,
        never double-delivered. Tickets submitted while the channel is
        live join it automatically from offset 0.

        With ``obs_cursor`` the PR-15 observability pull rides the same
        wire: whenever the timeline holds records past the cursor, a
        full /v1/obs document goes out as an ``obs`` frame (the stub
        ingests it exactly like a pull response; its seq-dedup makes
        the occasional overlap with a GET pull harmless)."""
        self.check_epoch(epoch)
        offsets = {rid: max(0, int(off)) for rid, off in resume.items()}
        with self._cond:
            # finished tickets the caller did not ask to resume were
            # delivered before this channel opened — never re-stream.
            # Epoch-scoped: a DEAD incarnation's finished ticket under
            # a colliding id must not block the fresh ticket that will
            # evict it from ever joining this channel.
            done_sent = {rid for rid, t in self._tickets.items()
                         if t.result is not None and rid not in offsets
                         and t.epoch == epoch}
        yield {"channel": True, "resumed": len(offsets),
               "epoch": self.epoch}
        last_emit = time.monotonic()
        while True:
            # each lap follows frames the gateway's demux consumed: an
            # actively-read channel IS gateway contact — refresh the
            # parking watchdog so slow control calls (a wedged adopt
            # monopolizing the control connection) can't orphan
            # sessions the gateway is demonstrably streaming
            self._last_contact = time.monotonic()
            token_frames: list = []
            done_rids: list = []
            terminal: dict | None = None
            with self._cond:
                if self.epoch != epoch:
                    terminal = {"error": "epoch superseded",
                                "stale": True, "epoch": self.epoch}
                elif self.failed is not None:
                    terminal = {"error": self.failed, "failed": True,
                                "epoch": self.epoch}
                else:
                    # new submits join the channel from offset 0 —
                    # only THIS epoch's; a dead incarnation's retained
                    # tickets are adopt/reconnect state, not streams
                    for rid, t in self._tickets.items():
                        if rid not in offsets and rid not in done_sent \
                                and t.epoch == epoch:
                            offsets[rid] = 0
                    for rid in list(offsets):
                        t = self._tickets.get(rid)
                        if t is None:
                            # resume named a ticket the agent no longer
                            # holds (restart / pruned): the stub's
                            # restart-detection case, per stream
                            token_frames.append(
                                {"rid": rid, "gone": True,
                                 "epoch": self.epoch})
                            del offsets[rid]
                            continue
                        if t.epoch != epoch:
                            # a stale-incarnation leftover under an id
                            # the stub's resume named: the in-flight
                            # submit that evicts it hasn't landed yet.
                            # Serving its tokens/result would deliver
                            # ANOTHER request's output onto this one —
                            # skip the lap; the fresh ticket replaces
                            # it at this same id momentarily.
                            continue
                        off = offsets[rid]
                        tokens = t.tokens[off:]
                        if tokens:
                            token_frames.append(
                                {"rid": rid, "off": off,
                                 "token_ids": tokens,
                                 "epoch": self.epoch})
                            offsets[rid] = off + len(tokens)
                        if t.result is not None:
                            done_rids.append((rid, t.result))
                            del offsets[rid]
                            done_sent.add(rid)
                    if not token_frames and not done_rids:
                        self._cond.wait(timeout=self.keepalive_s)
            if terminal is not None:
                yield terminal
                return
            for frame in token_frames:
                yield frame
                last_emit = time.monotonic()
            for rid, result in done_rids:
                # request_obs takes the condition lock itself — the
                # gather runs OUTSIDE the lock held above
                yield {"rid": rid, "done": True, "result": result,
                       "obs": self.request_obs(rid),
                       "epoch": self.epoch}
                last_emit = time.monotonic()
            if obs_cursor is not None:
                tl = self.server.timeline
                if tl is not None and tl.seq > obs_cursor:
                    doc = self.obs(obs_cursor)
                    obs_cursor = doc["cursor"]
                    yield {"obs": doc, "epoch": self.epoch}
                    last_emit = time.monotonic()
            if time.monotonic() - last_emit >= self.keepalive_s:
                yield {"keepalive": True, "epoch": self.epoch}
                last_emit = time.monotonic()


class AgentHandler(BaseHTTPRequestHandler):
    agent: ReplicaAgent
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        log.debug(fmt, *args)

    # chaos hook (AgentHTTP.kill): when set, every handler aborts at
    # its next loop point and the socket dies without an HTTP goodbye —
    # the network face of SIGKILL, for in-process chaos tests
    killed = False

    def _check_killed(self) -> None:
        if type(self).killed:
            raise ConnectionAbortedError("agent killed")

    def do_GET(self):
        self._check_killed()
        path, _, query = self.path.partition("?")
        if path == "/healthz":
            return self._send(200, self.agent.healthz())
        if path == "/v1/obs":
            try:
                cursor = int(dict(parse_qsl(query)).get("cursor", 0))
            except ValueError as e:
                return self._send(400, {"error": str(e)})
            return self._send(200, self.agent.obs(cursor))
        if path == "/v1/profile":
            return self._send(200, self.agent.profiler.status())
        if path == "/v1/parked":
            return self._send(200, self.agent.parked())
        if path.startswith("/v1/stream/"):
            return self._stream(unquote(path[len("/v1/stream/"):]),
                                dict(parse_qsl(query)))
        return self._send(404, {"error": "not found"})

    def do_POST(self):
        self._check_killed()
        path = self.path.partition("?")[0]
        try:
            length = int(self.headers.get("Content-Length") or 0)
            body = json.loads(self.rfile.read(length)) if length else {}
            if not isinstance(body, dict):
                raise ValueError("request must be a JSON object")
        except (ValueError, TypeError) as e:
            return self._send(400, {"error": str(e)})
        if path == "/v1/channel":
            return self._channel(body)
        if path == "/v1/submit":
            return self._submit(body)
        if path == "/v1/handoff":
            # the decode pool's intake: same contract as /v1/submit
            # but the body carries a prefill pool's page payload —
            # separated so an operator's access log tells admission
            # traffic from page migration, and so the (much larger)
            # handoff bodies can grow their own limits later
            if "handoff" not in body:
                return self._send(400, {"error": "handoff body needs "
                                        "a 'handoff' payload"})
            return self._submit(body)
        if path == "/v1/migrate_in":
            # the adopt half of live migration (ISSUE-18): /v1/submit's
            # contract, body carries a frozen session's wire snapshot —
            # the engine resumes it with no prefill, no first-token draw
            if "migrate" not in body:
                return self._send(400, {"error": "migrate_in body "
                                        "needs a 'migrate' snapshot"})
            return self._submit(body)
        if path == "/v1/adopt":
            # restart recovery's hand-off (ISSUE-20): a parked (or
            # still-live, or finished-undelivered) session leaves for
            # the calling gateway. 404 = unknown/reaped (caller
            # re-runs from the prompt); 409 = the epoch fence caught
            # a second adopter on a stale epoch
            try:
                out = self.agent.adopt(body)
            except _StaleEpoch as e:
                return self._send(409, {"error": str(e),
                                        "epoch": self.agent.epoch})
            except (ValueError, TypeError, KeyError) as e:
                return self._send(400, {"error": str(e),
                                        "kind": "ValueError"})
            except RuntimeError as e:
                return self._send(503, {"error": str(e),
                                        "kind": "Unavailable"})
            return self._send(200 if out.get("found") else 404, out)
        if path == "/v1/migrate_out":
            try:
                return self._send(200, self.agent.migrate_out(body))
            except _StaleEpoch as e:
                return self._send(409, {"error": str(e),
                                        "epoch": self.agent.epoch})
            except (ValueError, TypeError, KeyError) as e:
                return self._send(400, {"error": str(e),
                                        "kind": "ValueError"})
            except RuntimeError as e:
                return self._send(503, {"error": str(e),
                                        "kind": "Unavailable"})
        if path == "/v1/reset":
            try:
                return self._send(200,
                                  self.agent.reset(body.get("epoch", 0)))
            except _StaleEpoch as e:
                return self._send(409, {"error": str(e),
                                        "epoch": self.agent.epoch})
            except (RuntimeError, TypeError, ValueError) as e:
                return self._send(500, {"error": str(e)})
        if path == "/v1/drain":
            timeout = float(body.get("timeout_s", 120.0))
            return self._send(200, self.agent.drain(timeout))
        if path == "/v1/profile":
            # the remote half of the gateway's /debug/profile fan-out:
            # arm a capture of this agent's next N working iterations.
            # Same status mapping as the gateway's own endpoint — 409
            # while one is pending/active (jax has ONE global session)
            try:
                steps = int(body.get("steps", 10))
                logdir = self.agent.profiler.request(steps)
            except ValueError as e:
                return self._send(400, {"error": str(e)})
            except RuntimeError as e:
                return self._send(409, {"error": str(e)})
            return self._send(200, {"armed": True, "steps": steps,
                                    "logdir": logdir})
        return self._send(404, {"error": "not found"})

    def _submit(self, body: dict) -> None:
        from tony_tpu.serve.engine import PoolExhausted, QueueFull
        from tony_tpu.serve.migrate import StaleDelta

        try:
            return self._send(200, self.agent.submit(body))
        except _StaleEpoch as e:
            return self._send(409, {"error": str(e),
                                    "epoch": self.agent.epoch})
        except QueueFull as e:
            return self._send(429, {"error": str(e), "kind": "QueueFull"})
        except PoolExhausted as e:
            return self._send(503, {"error": str(e),
                                    "kind": "PoolExhausted"})
        except StaleDelta as e:
            # must precede ValueError (StaleDelta subclasses it): the
            # sender retries ONCE with the full snapshot on this kind
            return self._send(400, {"error": str(e), "kind": "StaleDelta"})
        except (ValueError, TypeError, KeyError) as e:
            return self._send(400, {"error": str(e),
                                    "kind": "ValueError"})
        except RuntimeError as e:  # draining / failed
            return self._send(503, {"error": str(e), "kind": "Unavailable"})

    def _channel(self, body: dict) -> None:
        """POST /v1/channel: the multiplexed stream carrier. Body
        ``{"epoch": E, "streams": [[rid, off], ...], "obs_cursor": N}``
        (streams as PAIRS, not an object — JSON object keys are always
        strings and rids can be ints). Responds with an endless chunked
        NDJSON of tagged frames (see channel_events)."""
        try:
            epoch = int(body.get("epoch", 0))
            resume = {rid: int(off)
                      for rid, off in body.get("streams") or []}
            cursor = body.get("obs_cursor")
            cursor = int(cursor) if cursor is not None else None
        except (TypeError, ValueError) as e:
            return self._send(400, {"error": str(e)})
        try:
            events = self.agent.channel_events(resume, epoch, cursor)
            first = next(events)
        except _StaleEpoch as e:
            return self._send(409, {"error": str(e),
                                    "epoch": self.agent.epoch})
        except StopIteration:
            return self._send(500, {"error": "empty channel"})
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header("Cache-Control", "no-store")
        self.end_headers()
        self._chunk(first)
        for doc in events:
            self._check_killed()
            self._chunk(doc)
        self.wfile.write(b"0\r\n\r\n")

    def _stream(self, rid: str, params: dict) -> None:
        request_id: object = int(rid) if rid.lstrip("-").isdigit() else rid
        try:
            offset = int(params.get("offset", 0))
            epoch = int(params.get("epoch", 0))
        except ValueError as e:
            return self._send(400, {"error": str(e)})
        try:
            events = self.agent.stream_events(request_id, offset, epoch)
            first = next(events)
        except _StaleEpoch as e:
            return self._send(409, {"error": str(e),
                                    "epoch": self.agent.epoch})
        except StopIteration:  # generator contract: never empty
            return self._send(500, {"error": "empty stream"})
        # a missing ticket is a clean 404 BEFORE the stream commits:
        # the stub treats it as "the agent lost my request" (restart)
        if first.get("gone"):
            return self._send(404, first)
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header("Cache-Control", "no-store")
        self.end_headers()
        self._chunk(first)
        for doc in events:
            self._check_killed()
            self._chunk(doc)
        self.wfile.write(b"0\r\n\r\n")

    def _chunk(self, doc: dict) -> None:
        data = (json.dumps(doc) + "\n").encode()
        self.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")
        self.wfile.flush()

    def _send(self, code: int, doc: dict) -> None:
        data = json.dumps(doc).encode()
        if code >= 400:
            self.close_connection = True
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        if code >= 400:
            self.send_header("Connection", "close")
        self.end_headers()
        self.wfile.write(data)


class _AgentHTTPServer(ThreadingHTTPServer):
    def handle_error(self, request, client_address):
        # disconnects (client gone mid-stream) and the kill() chaos
        # abort are expected request endings, not tracebacks on stderr
        import sys

        exc = sys.exc_info()[1]
        if isinstance(exc, (ConnectionError, TimeoutError)):
            log.debug("agent connection ended: %r", exc)
            return
        super().handle_error(request, client_address)


class AgentHTTP:
    """Binds a ReplicaAgent to a ThreadingHTTPServer (start/stop),
    plus the ``kill()`` chaos helper: from the network's point of view
    the agent is SIGKILLed — open streams die mid-line, new
    connections are refused — while the test process lives on."""

    def __init__(self, agent: ReplicaAgent, host: str = "127.0.0.1",
                 port: int = 0):
        handler = type("BoundAgentHandler", (AgentHandler,),
                       {"agent": agent})
        self._handler = handler
        self.server = _AgentHTTPServer((host, port), handler)
        self.server.daemon_threads = True
        self.host, self.port = self.server.server_address[:2]
        self.address = f"{self.host}:{self.port}"
        self._thread: threading.Thread | None = None

    def start(self) -> "AgentHTTP":
        self.agent = self._handler.agent.start()
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        name="replica-agent-http",
                                        daemon=True)
        self._thread.start()
        log.info("replica agent %s at http://%s", self.agent.agent_id,
                 self.address)
        return self

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()
        self._handler.agent.stop()
        # a stopped server must also stop ANSWERING: daemon handler
        # threads still hold accepted keep-alive sockets (incl. the
        # mux channel), and a persistent client connection would keep
        # landing requests on the corpse — in-process restarts on the
        # same port would then feed a stub's control connection from
        # the DEAD agent while the live one never sees the request
        # (a real process exit RSTs these sockets; emulate that)
        self._handler.killed = True
        with self._handler.agent._cond:
            self._handler.agent._cond.notify_all()

    def kill(self) -> None:
        """Chaos: drop off the network like a SIGKILLed process."""
        self._handler.killed = True
        # wake stream handlers parked on the agent condition so they
        # hit the killed check now, not a keepalive later
        with self._handler.agent._cond:
            self._handler.agent._cond.notify_all()
        self.server.shutdown()
        self.server.server_close()
