"""Pipeline-parallel forward for the flagship transformer.

Connects the model's ``scan_layers`` stacked-block parameters (leading
"layers" dim, one slice per block — transformer.py:_scan_blocks) to the
``parallel.pipeline`` schedules: shard that dim over the ``pipe`` mesh
axis and each pipe device runs its blocks, with activations flowing
device-to-device per microbatch. GPipe or interleaved/circular
(``circular_repeats``) — see parallel/pipeline.py for the schedules.

Embedding + final norm + head stay outside the pipeline (they are the
first/last stages' work in practice; here they run replicated, which is
exact and keeps this helper schedule-agnostic).
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.sharding import Mesh

from tony_tpu.models.transformer import (
    Block,
    Transformer,
    make_norm,
)
from tony_tpu.parallel.mesh import PIPE
from tony_tpu.parallel.pipeline import pipeline_apply


def pipelined_forward(model: Transformer, params, tokens, *, mesh: Mesh,
                      n_microbatches: int, axis_name: str = PIPE,
                      circular_repeats: int = 1, interleaved: bool = False,
                      remat: bool = False, return_hidden: bool = False):
    """Forward pass with the block stack pipelined over ``axis_name``.

    model.cfg must have ``scan_layers=True`` (stacked block params) and
    ``n_layers == mesh.shape[axis_name] * circular_repeats`` (one virtual
    stage per block). ``params`` is the model's variables dict or its
    "params" subtree. Matches ``model.apply`` exactly (same params, same
    math; the pipeline only reorders WHERE each block runs).
    """
    cfg = model.cfg
    if not cfg.scan_layers:
        raise ValueError("pipelined_forward needs cfg.scan_layers=True "
                         "(stacked per-layer params)")
    p = params.get("params", params)
    n_stages = mesh.shape[axis_name]
    if cfg.n_layers != n_stages * circular_repeats:
        raise ValueError(
            f"n_layers={cfg.n_layers} must equal pipe axis {n_stages} x "
            f"circular_repeats {circular_repeats}")

    embed = p["embedding"]
    x = jnp.asarray(embed)[tokens].astype(cfg.dtype)
    if cfg.positional == "learned":
        x = x + jnp.asarray(p["pos_embedding"])[
            jnp.arange(tokens.shape[1])][None].astype(cfg.dtype)

    block = Block(cfg)

    def stage_fn(block_params, h):
        return block.apply({"params": block_params}, h)

    x = pipeline_apply(stage_fn, p["layers"]["block"], x, mesh=mesh,
                       n_microbatches=n_microbatches, axis_name=axis_name,
                       remat=remat, circular_repeats=circular_repeats,
                       interleaved=interleaved)

    x = make_norm(cfg, "ln_f").apply({"params": p["ln_f"]}, x)
    if return_hidden:
        return x.astype(jnp.float32)
    head = embed if cfg.tied_embeddings else p["lm_head"]
    logits = jnp.einsum("bld,vd->blv", x.astype(jnp.float32),
                        jnp.asarray(head))
    if cfg.lm_head_bias:
        logits = logits + jnp.asarray(p["lm_head_bias"])
    return logits
