#!/bin/sh
# serve-smoke: boot a tiny-model gateway, fire concurrent curl clients
# (unary + streaming), assert 200s and a well-formed NDJSON stream, run
# a shared-prefix round (same preamble, different tails) and assert the
# prefix KV cache registered hits on /stats, then exercise the SIGTERM
# graceful drain. Every phase is bounded by `timeout`, so a hang exits
# nonzero instead of wedging CI.
#
# Usage: tools/serve_smoke.sh  (from the repo root; `make serve-smoke`)
set -u

PY=${PY:-python}
BOUND=${SERVE_SMOKE_TIMEOUT:-300}   # whole-run ceiling, seconds
WORK=$(mktemp -d /tmp/serve_smoke.XXXXXX)
trap 'kill $GW_PID 2>/dev/null; rm -rf "$WORK"' EXIT INT TERM

fail() { echo "serve-smoke: FAIL: $1" >&2; exit 1; }

# ---- boot the gateway on an ephemeral port ---------------------------
JAX_PLATFORMS=cpu $PY -m tony_tpu.cli.gateway --demo-model \
    --replicas 2 --port 0 --compile-cache '' \
    >"$WORK/boot.log" 2>"$WORK/stderr.log" &
GW_PID=$!

# the boot line prints the bound URL; wait for it (bounded)
URL=''
i=0
while [ $i -lt $BOUND ]; do
    URL=$(sed -n 's/.*gateway at \(http:[^ ]*\).*/\1/p' "$WORK/boot.log")
    [ -n "$URL" ] && break
    kill -0 $GW_PID 2>/dev/null || fail "gateway died at boot: $(cat "$WORK/stderr.log")"
    sleep 1; i=$((i + 1))
done
[ -n "$URL" ] || fail "gateway did not print its URL within ${BOUND}s"
echo "serve-smoke: gateway at $URL"

curl_s() { timeout -k 5 "$BOUND" curl -sS -o "$1" -w '%{http_code}' "$2" ${3:+-d "$3"}; }

# ---- health ----------------------------------------------------------
code=$(curl_s "$WORK/healthz" "$URL/healthz") || fail "healthz curl"
[ "$code" = 200 ] || fail "healthz -> $code"
code=$(curl_s "$WORK/readyz" "$URL/readyz") || fail "readyz curl"
[ "$code" = 200 ] || fail "readyz -> $code"

# ---- concurrent generate: 4 unary + 2 streaming ----------------------
# PIDs collected explicitly: $(jobs -p) runs in a subshell under dash
# and comes back empty, turning `wait` into wait-for-the-gateway
CURL_PIDS=''
n=0
while [ $n -lt 4 ]; do
    curl_s "$WORK/unary_$n" "$URL/v1/generate" \
        "{\"token_ids\": [$((1 + n)), 2, 3], \"max_new_tokens\": 4, \"id\": $n}" \
        >"$WORK/unary_${n}.code" &
    CURL_PIDS="$CURL_PIDS $!"
    n=$((n + 1))
done
n=0
while [ $n -lt 2 ]; do
    curl_s "$WORK/stream_$n" "$URL/v1/generate" \
        "{\"token_ids\": [$((9 + n)), 8], \"max_new_tokens\": 5, \"stream\": true}" \
        >"$WORK/stream_${n}.code" &
    CURL_PIDS="$CURL_PIDS $!"
    n=$((n + 1))
done
wait $CURL_PIDS

n=0
while [ $n -lt 4 ]; do
    [ "$(cat "$WORK/unary_${n}.code")" = 200 ] || fail "unary $n -> $(cat "$WORK/unary_${n}.code")"
    grep -q '"finish_reason"' "$WORK/unary_$n" || fail "unary $n: no finish_reason"
    n=$((n + 1))
done
n=0
while [ $n -lt 2 ]; do
    [ "$(cat "$WORK/stream_${n}.code")" = 200 ] || fail "stream $n -> $(cat "$WORK/stream_${n}.code")"
    # well-formed stream: >= 2 NDJSON lines, each valid JSON, last has
    # finish_reason (the $PY check parses every line)
    $PY - "$WORK/stream_$n" <<'EOF' || fail "stream $n: malformed NDJSON"
import json, sys
lines = [ln for ln in open(sys.argv[1]) if ln.strip()]
assert len(lines) >= 2, f"only {len(lines)} lines"
docs = [json.loads(ln) for ln in lines]
assert docs[-1]["finish_reason"] in ("eos", "length"), docs[-1]
deltas = [t for d in docs[:-1] for t in d["token_ids"]]
assert docs[-1]["token_ids"][-len(deltas):] == deltas, "delta mismatch"
EOF
    n=$((n + 1))
done

# ---- shared-prefix round: the prefix KV cache must register hits -----
# same 12-token preamble, different tails, one exact repeat; sequential
# + session-pinned so all three land on ONE replica's store
PREFIX='1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12'
n=0
for TAIL in '21, 22' '23, 24' '21, 22'; do
    code=$(curl_s "$WORK/prefix_$n" "$URL/v1/generate" \
        "{\"token_ids\": [$PREFIX, $TAIL], \"max_new_tokens\": 3, \"session\": \"warm\"}") \
        || fail "prefix round $n curl"
    [ "$code" = 200 ] || fail "prefix round $n -> $code"
    n=$((n + 1))
done

# ---- stats + graceful drain -----------------------------------------
code=$(curl_s "$WORK/stats" "$URL/stats") || fail "stats curl"
[ "$code" = 200 ] || fail "stats -> $code"
grep -q '"completed": 9' "$WORK/stats" || fail "stats: expected 9 completed: $(cat "$WORK/stats")"
$PY - "$WORK/stats" <<'EOF' || fail "stats: no prefix-cache hits"
import json, sys
prefix = json.load(open(sys.argv[1]))["engine"]["prefix"]
assert prefix["enabled"], prefix
assert prefix["hits"] > 0 and prefix["hit_tokens"] > 0, prefix
assert 0 < prefix["hit_rate"] <= 1, prefix
EOF

kill -TERM $GW_PID
i=0
while kill -0 $GW_PID 2>/dev/null; do
    [ $i -ge $BOUND ] && fail "gateway did not drain within ${BOUND}s of SIGTERM"
    sleep 1; i=$((i + 1))
done
wait $GW_PID
rc=$?
[ $rc = 0 ] || fail "gateway exited $rc after SIGTERM"
echo "serve-smoke: OK (9 requests, prefix hits, clean drain)"
