"""Port reservation.

Reference: ServerPort/EphemeralPort/ReusablePort (+ reserve_reusable_port.py)
— TaskExecutor reserves rendezvous ports before registering, releases them
just before exec'ing the user process so the framework server can rebind
(TaskExecutor.java:89-101, 202-234). SO_REUSEPORT mode holds the port across
exec so there is no race window (rationale: ReusablePort.java:123-153).
On TPU the jax.distributed coordinator owns its own port, so the dance only
matters for chief rendezvous + TensorBoard ports; both modes are kept.
"""

from __future__ import annotations

import socket


class ServerPort:
    """A reserved TCP port; ``release()`` before handing it to the user
    process (unless SO_REUSEPORT keeps it held)."""

    def __init__(self, sock: socket.socket, reuse: bool):
        self._sock: socket.socket | None = sock
        self.reuse = reuse
        self.port: int = sock.getsockname()[1]

    def release(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    def __enter__(self) -> "ServerPort":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def reserve_port(reuse: bool = False, host: str = "") -> ServerPort:
    """EphemeralPort.create / ReusablePort.create equivalent."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    if reuse:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
    sock.bind((host, 0))
    sock.listen(1)
    return ServerPort(sock, reuse)


def local_host_name() -> str:
    return socket.gethostname()
