"""tony-tpu: a TPU-native cluster-orchestration framework for distributed ML.

Rebuilds the capabilities of LinkedIn's TonY (reference: /root/reference,
~17.6k LoC Java over YARN) as a TPU-first system:

- Control plane: a coordinator process (ApplicationMaster equivalent,
  ``tony_tpu.coordinator``) gang-schedules role tasks onto per-host agents
  (``tony_tpu.agent``), rendezvouses them via injected ``jax.distributed``
  env, monitors heartbeats/liveness, applies chief/untracked/sidecar
  exit-status policy, and persists a browsable job history.
- Data plane: *not* delegated to NCCL/Gloo/MPI like the reference — emitted
  as XLA collectives over ICI/DCN by jax/pjit (``tony_tpu.parallel``),
  with pallas kernels for hot ops (``tony_tpu.ops``) and flagship models
  (``tony_tpu.models``).

Reference layer map: SURVEY.md section 1; component parity: SURVEY.md section 2.
"""

from tony_tpu.version import __version__

__all__ = ["__version__"]
