"""Persistent XLA compilation-cache wiring (utils/compilecache.py).

VERDICT r2 #2: a retried/resumed attempt (or any second cold process)
must reuse compiled executables instead of recompiling. The e2e here is
the contract itself: process 1 compiles cold and populates the dir;
process 2 — a genuinely separate interpreter — compiles the same
program and takes cache HITS (observed via jax's own monitoring
counter) while writing nothing new.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

from tony_tpu import constants as C
from tony_tpu.utils import compilecache

# Child body: enable the cache from env, count persistent-cache hits via
# jax's monitoring events (introspection only — the production path never
# touches jax internals), run one jitted program, report.
_CHILD = """
import json, sys
from tony_tpu.utils import compilecache
enabled = compilecache.enable()
hits = [0]
from jax._src import monitoring  # test-only hit counter
monitoring.register_event_listener(
    lambda name, **kw: hits.__setitem__(0, hits[0] + 1)
    if name == "/jax/compilation_cache/cache_hits" else None)
import jax, jax.numpy as jnp
out = jax.jit(lambda x: (x @ x + 1.0).sum())(jnp.ones((64, 64)))
out.block_until_ready()
print(json.dumps({"enabled": enabled, "hits": hits[0]}))
"""


def _run_child(extra_env: dict) -> dict:
    env = {**os.environ, **extra_env}
    out = subprocess.run([sys.executable, "-c", _CHILD],
                         capture_output=True, text=True, env=env,
                         timeout=120)
    assert out.returncode == 0, out.stderr
    return json.loads(out.stdout.strip().splitlines()[-1])


def _reset(monkeypatch):
    monkeypatch.setattr(compilecache, "_enabled", None)


def test_enable_disabled_outside_job(monkeypatch):
    _reset(monkeypatch)
    monkeypatch.delenv(C.COMPILE_CACHE_DIR, raising=False)
    monkeypatch.delenv(C.JOB_DIR, raising=False)
    assert compilecache.enable() is None


def test_enable_resolution_order(tmp_path, monkeypatch):
    """Explicit arg beats env beats job-dir derivation; dir is created."""
    import jax

    calls = []
    monkeypatch.setattr(jax.config, "update",
                        lambda k, v: calls.append((k, v)))
    monkeypatch.setenv(C.COMPILE_CACHE_DIR, str(tmp_path / "from_env"))
    monkeypatch.setenv(C.JOB_DIR, str(tmp_path / "job"))

    _reset(monkeypatch)
    got = compilecache.enable(str(tmp_path / "explicit"))
    assert got == str(tmp_path / "explicit") and os.path.isdir(got)

    _reset(monkeypatch)
    assert compilecache.enable() == str(tmp_path / "from_env")

    _reset(monkeypatch)
    monkeypatch.delenv(C.COMPILE_CACHE_DIR)
    assert compilecache.enable() == str(tmp_path / "job" / "compile-cache")

    assert ("jax_compilation_cache_dir", str(tmp_path / "explicit")) in calls
    assert ("jax_persistent_cache_min_compile_time_secs", 0.0) in calls


def test_enable_is_sticky(tmp_path, monkeypatch):
    """Second enable() with a different dir keeps the first (one cache per
    process; flipping dirs mid-run would split it)."""
    import jax

    monkeypatch.setattr(jax.config, "update", lambda k, v: None)
    _reset(monkeypatch)
    first = compilecache.enable(str(tmp_path / "a"))
    assert compilecache.enable(str(tmp_path / "b")) == first


def test_second_cold_process_reuses_cache(tmp_path):
    """The headline contract: a brand-new interpreter compiling the same
    program takes persistent-cache hits and adds no new entries."""
    cache = tmp_path / "cc"
    env = {C.COMPILE_CACHE_DIR: str(cache)}

    first = _run_child(env)
    assert first["enabled"] == str(cache)
    assert first["hits"] == 0  # cold: nothing to hit
    populated = compilecache.entries(str(cache))
    assert populated  # cold run wrote executables

    second = _run_child(env)
    assert second["enabled"] == str(cache)
    assert second["hits"] > 0  # warm: reused at least the jitted program
    assert compilecache.entries(str(cache)) == populated  # nothing new
