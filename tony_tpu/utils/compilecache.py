"""Persistent XLA compilation-cache wiring.

No reference analog (TonY is JVM-side; the user script owns the ML
stack) — this is TPU-native launch-latency plumbing: XLA serializes
compiled executables to a cache dir, so a retried/resumed attempt (or
any later process compiling the same program: bench reruns, generate
CLI warm starts) skips its multi-second-to-minute compiles entirely.
Over the tunneled single-chip backend a decode program's compile was
measured at >15 min; a warm cache turns that into a file read.

The cache key covers the serialized computation, jaxlib/backend
versions, XLA flags, and compile options — a stale dir is never wrong,
only useless, so sharing one dir across attempts/processes is safe.

Scoping: the coordinator injects ``TONY_COMPILE_CACHE_DIR`` pointing
inside the job dir, which every retry attempt of a job shares (see
``Coordinator._task_env``), so attempt N+1 reuses attempt N's compiles.
"""

from __future__ import annotations

import logging
import os

from tony_tpu import constants as C

log = logging.getLogger(__name__)

_enabled: str | None = None


def enable(cache_dir: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at a directory.

    Resolution order: explicit ``cache_dir`` arg, then
    ``$TONY_COMPILE_CACHE_DIR`` (coordinator-injected, job-dir scoped),
    then ``$TONY_JOB_DIR/compile-cache``, else disabled (returns None).

    Thresholds are set to cache *everything* (min compile time 0, no
    min entry size): retry/resume latency is dominated by many small
    compiles, not one big one. Safe to call repeatedly — the first
    resolved dir wins for the life of the process (flipping dirs
    mid-process would split the cache for no benefit).
    """
    global _enabled
    if _enabled is not None:
        return _enabled
    cache_dir = (cache_dir or os.environ.get(C.COMPILE_CACHE_DIR) or "").strip()
    if not cache_dir:
        job_dir = os.environ.get(C.JOB_DIR, "").strip()
        if job_dir:
            cache_dir = os.path.join(job_dir, "compile-cache")
    if not cache_dir:
        return None
    import jax

    try:
        os.makedirs(cache_dir, exist_ok=True)
        # thresholds first, dir LAST: the dir is what arms the cache, so
        # a partial failure (e.g. an older jax missing a threshold knob)
        # leaves it fully off, never half-configured
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except Exception:
        # never let cache plumbing take down a training process: a
        # read-only FS or an older jax without a knob just runs cold
        log.exception("compile cache at %s unavailable; running cold",
                      cache_dir)
        return None
    _enabled = cache_dir
    log.info("persistent compilation cache: %s", cache_dir)
    return cache_dir


def entries(cache_dir: str) -> list[str]:
    """Names of cached executables (``*-cache`` files) under a cache dir.
    Diagnostic/test helper; empty for a missing dir."""
    try:
        return sorted(n for n in os.listdir(cache_dir) if n.endswith("-cache"))
    except OSError:
        return []
