"""Rendezvous bootstrap for the horovod-compat runtime.

Reference: ``src/main/resources/horovod_driver.py`` (189 LoC) — TonY forks
this script on the hidden ``driver`` task; it starts horovod's gloo
``RendezvousServer``, computes the host/slot assignment plan, and announces
the server port by *writing a file* named
``{port}____HOROVOD_RENDEZVOUS_SERVER____`` whose body is the slot-plan
JSON (``create_port_file`` :130-136, ``static_driver_fn`` :32-42).

The rebuild has no horovod dependency: the slot math
(rank/local_rank/cross_rank, horovod's ``get_host_assignments`` semantics)
is implemented in-tree, and the rendezvous server is a minimal HTTP KV
store speaking the gloo rendezvous GET/PUT contract. On TPU none of this
is needed for the flagship path — jax.distributed replaces it wholesale
(see runtime/jax_runtime.py) — this exists for capability parity with
gloo/horovod-style user payloads.

Test modes mirror the reference (`_build_fake_host_plan` :44-66, fast-fail
exit :164-167): ``--fake`` writes a fake plan on a fake port with no
server; ``--fail`` exits 1 immediately.

Elastic mode (``--elastic --discover CMD``): the reference stubs its
elastic driver entirely (``elastic_driver_fn`` at reference
horovod_driver.py:28-29 is ``pass``, with the horovod.runner.elastic
imports at :19-21 unused); here it is real. ``CMD`` is horovod's elastic
host-discovery contract — a command printing one ``host:slots`` line per
live host. The driver polls it, and on membership change rebuilds the
slot plan under a bumped ``generation``, republishes the port file, and
updates the KV store at ``/rendezvous/plan`` so running workers (and the
coordinator, via the re-announced file) observe the new world size. This
composes with the framework's own resize path (tony_tpu.elastic): point
the discovery command at ``cli.resize``'s host list.

Usage: ``python -m tony_tpu.runtime.horovod_driver -w host1:2,host2:1``
"""

from __future__ import annotations

import argparse
import http.server
import json
import os
import shlex
import subprocess
import sys
import threading
import time

PORT_FILE_SUFFIX = "____HOROVOD_RENDEZVOUS_SERVER____"
FAKE_SERVER_PORT = 9999


# ---------------------------------------------------------------------------
# Slot plan (horovod get_host_assignments semantics)
# ---------------------------------------------------------------------------

def parse_worker_list(worker_list: str) -> list[tuple[str, int]]:
    """``"h1:2,h2:1"`` -> ``[("h1", 2), ("h2", 1)]`` (ref: parse_hosts)."""
    hosts: list[tuple[str, int]] = []
    for part in worker_list.split(","):
        part = part.strip()
        if not part:
            continue
        host, _, n = part.rpartition(":")
        if not host:
            raise ValueError(f"bad worker entry {part!r} (want host:nproc)")
        hosts.append((host, int(n)))
    if not hosts:
        raise ValueError("empty worker list")
    return hosts


def build_slot_plan(hosts: list[tuple[str, int]]) -> list[dict]:
    """Host-major rank assignment with horovod's slot-info fields:

    - ``rank``: global, host-major then slot order
    - ``local_rank`` / ``local_size``: position / count on the host
    - ``cross_rank``: index of the host among hosts that have a slot at
      this local_rank; ``cross_size``: count of such hosts
    (ref: horovod get_host_assignments, consumed at
    runtime/HorovodRuntime.java:312-350).
    """
    plan: list[dict] = []
    size = sum(n for _, n in hosts)
    rank = 0
    for host, nproc in hosts:
        for local_rank in range(nproc):
            cross_hosts = [h for h, n in hosts if n > local_rank]
            plan.append({
                "hostname": host,
                "rank": rank,
                "size": size,
                "local_rank": local_rank,
                "local_size": nproc,
                "cross_rank": cross_hosts.index(host),
                "cross_size": len(cross_hosts),
            })
            rank += 1
    return plan


def build_fake_slot_plan() -> list[dict]:
    """Ref: _build_fake_host_plan :44-66 — a 2-slot localhost plan used by
    the conf-gated test mode so CI needs no real rendezvous."""
    return build_slot_plan([("localhost", 2)])


# ---------------------------------------------------------------------------
# Minimal gloo-style rendezvous KV server
# ---------------------------------------------------------------------------

class _KVHandler(http.server.BaseHTTPRequestHandler):
    """PUT stores the body under the path, GET returns it (404 until set),
    DELETE removes it — the gloo rendezvous contract shape."""

    store: dict[str, bytes] = {}
    lock = threading.Lock()

    def do_PUT(self) -> None:  # noqa: N802
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        with self.lock:
            self.store[self.path] = body
        self.send_response(200)
        self.end_headers()

    def do_GET(self) -> None:  # noqa: N802
        with self.lock:
            body = self.store.get(self.path)
        if body is None:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_DELETE(self) -> None:  # noqa: N802
        with self.lock:
            self.store.pop(self.path, None)
        self.send_response(200)
        self.end_headers()

    def log_message(self, fmt: str, *args) -> None:  # quiet
        pass


def start_rendezvous_server() -> tuple[http.server.ThreadingHTTPServer, int]:
    server = http.server.ThreadingHTTPServer(("0.0.0.0", 0), _KVHandler)
    thread = threading.Thread(target=server.serve_forever,
                              name="rendezvous-http", daemon=True)
    thread.start()
    return server, server.server_address[1]


# ---------------------------------------------------------------------------
# Port-file announcement (the TonY driver contract)
# ---------------------------------------------------------------------------

def create_port_file(directory: str, port: int, plan: list[dict],
                     generation: int | None = None) -> str:
    """Atomically write ``{port}____HOROVOD_RENDEZVOUS_SERVER____`` holding
    the slot-plan JSON (ref: create_port_file :130-136). Elastic mode adds
    a ``generation`` counter so consumers can detect replanning."""
    os.makedirs(directory, exist_ok=True)
    final = os.path.join(directory, f"{port}{PORT_FILE_SUFFIX}")
    tmp = final + ".tmp"
    body = {"port": port, "slots": plan}
    if generation is not None:
        body["generation"] = generation
    with open(tmp, "w") as f:
        json.dump(body, f)
    os.replace(tmp, final)
    return final


# ---------------------------------------------------------------------------
# Elastic host discovery (the horovod discovery-script contract)
# ---------------------------------------------------------------------------

def run_discovery(cmd: str) -> list[tuple[str, int]] | None:
    """Run the discovery command; parse one ``host[:slots]`` line per live
    host (slots default 1 — horovod's contract). Returns None on failure
    or empty output so the caller keeps the previous membership (a flaky
    discovery probe must not dissolve the gang)."""
    try:
        proc = subprocess.run(shlex.split(cmd), capture_output=True,
                              text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired, ValueError):
        return None
    if proc.returncode != 0:
        return None
    hosts: list[tuple[str, int]] = []
    for line in proc.stdout.splitlines():
        line = line.strip()
        if not line:
            continue
        host, sep, n = line.partition(":")
        try:
            hosts.append((host, int(n) if sep else 1))
        except ValueError:
            return None
    return hosts or None


def publish_plan(port: int, hosts: list[tuple[str, int]], directory: str,
                 generation: int) -> list[dict]:
    """Rebuild + re-announce the slot plan: the port file (coordinator
    contract) and the in-process KV store at ``/rendezvous/plan`` (running
    workers poll it to observe resizes without re-reading files)."""
    plan = build_slot_plan(hosts)
    body = json.dumps({"port": port, "slots": plan,
                       "generation": generation}).encode()
    with _KVHandler.lock:
        _KVHandler.store["/rendezvous/plan"] = body
    create_port_file(directory, port, plan, generation=generation)
    return plan


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("-w", "--worker-list", required=True,
                    help="comma list of host:nproc")
    ap.add_argument("-d", "--dir", default=".",
                    help="directory for the port file (default cwd)")
    ap.add_argument("--fake", action="store_true",
                    help="test mode: fake plan + fake port, no server")
    ap.add_argument("--fail", action="store_true",
                    help="test mode: exit 1 immediately (fast-fail)")
    ap.add_argument("--elastic", action="store_true",
                    help="poll --discover for membership changes and "
                         "republish the slot plan under a new generation")
    ap.add_argument("--discover", default="",
                    help="host-discovery command printing host[:slots] "
                         "lines (horovod's elastic contract)")
    ap.add_argument("--discover-interval", type=float, default=5.0,
                    help="seconds between discovery polls")
    args = ap.parse_args(argv)

    if args.fail:
        print("driver fast-fail test mode", file=sys.stderr)
        return 1

    if args.fake:
        plan = build_fake_slot_plan()
        create_port_file(args.dir, FAKE_SERVER_PORT, plan)
        while True:  # stay alive like a real rendezvous server; AM kills us
            time.sleep(3600)

    hosts = parse_worker_list(args.worker_list)
    server, port = start_rendezvous_server()
    if args.elastic:
        if not args.discover:
            print("--elastic needs --discover", file=sys.stderr)
            return 2
        generation = 0
        publish_plan(port, hosts, args.dir, generation)
        try:
            while True:
                time.sleep(args.discover_interval)
                new_hosts = run_discovery(args.discover)
                # order-insensitive: discovery enumerating the same hosts
                # in a different order must not reshuffle ranks
                if new_hosts is not None and \
                        sorted(new_hosts) != sorted(hosts):
                    hosts = new_hosts
                    generation += 1
                    publish_plan(port, hosts, args.dir, generation)
                    print(f"elastic replan: generation {generation}, "
                          f"{sum(n for _, n in hosts)} slots", flush=True)
        except KeyboardInterrupt:
            server.shutdown()
        return 0
    plan = build_slot_plan(hosts)
    create_port_file(args.dir, port, plan)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
