"""Payload: an elastic training loop. Counts steps into a per-role-index
progress file (the 'checkpoint'); on save_and_exit it exits EXIT_RESIZE;
on relaunch it resumes from the file and finishes at TARGET total steps.
Also records the TASK_NUM it saw, so the test can assert the gang grew."""
import os
import sys
import time

sys.path.insert(0, os.environ["TONY_REPO_ROOT"])

from tony_tpu import elastic

TARGET = 30


def main() -> int:
    role = os.environ["TONY_JOB_NAME"]
    index = os.environ["TONY_TASK_INDEX"]
    task_num = os.environ["TONY_TASK_NUM"]
    epoch = elastic.session_epoch()
    ckpt = os.path.join(os.getcwd(), f"progress-{role}-{index}.txt")
    sizes = os.path.join(os.getcwd(), f"sizes-{role}-{index}.txt")
    with open(sizes, "a") as f:
        f.write(f"{epoch}:{task_num}\n")

    step = 0
    if os.path.exists(ckpt):
        with open(ckpt) as f:
            step = int(f.read().strip() or 0)
        print(f"resumed at step {step} (epoch {epoch})")

    while step < TARGET:
        step += 1
        with open(ckpt, "w") as f:
            f.write(str(step))
        if elastic.save_and_exit_requested():
            print(f"save_and_exit at step {step}")
            return elastic.EXIT_RESIZE
        time.sleep(0.1)
    print(f"done at step {step} (epoch {epoch}, task_num {task_num})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
