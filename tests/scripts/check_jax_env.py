"""Assert the jax runtime rendezvous env (TPU-native TF_CONFIG analog)."""
import json
import os
import sys

addr = os.environ.get("TONY_JAX_COORDINATOR")
pid = os.environ.get("TONY_PROCESS_ID")
num = os.environ.get("TONY_NUM_PROCESSES")
if not addr or pid is None or num is None:
    print("missing jax env")
    sys.exit(1)
spec = json.loads(os.environ["CLUSTER_SPEC"])
total = sum(len(v) for v in spec.values())
if int(num) != total:
    print("bad num_processes", num, total)
    sys.exit(2)
if not (0 <= int(pid) < total):
    print("bad process_id", pid)
    sys.exit(3)
host, _, port = addr.rpartition(":")
if not host or not port.isdigit():
    print("bad coordinator addr", addr)
    sys.exit(4)
sys.exit(0)
