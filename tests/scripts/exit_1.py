"""Trivial failure payload (ref: exit_1.py)."""
import sys

sys.exit(1)
