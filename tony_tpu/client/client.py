"""Job-submission client — TonyClient equivalent.

Reference: TonyClient.java (1417 LoC): merges config layers, stages the
user's src dir / venv / resources into the job dir, writes tony-final.json,
launches the coordinator (YARN AM submission becomes a subprocess or remote
exec), polls application status + task infos on a 1 s cadence, streams
status tables to the console and listeners, and signals the coordinator to
finish (ref: monitorApplication :1031-1099, signalAMToFinish :1101-1111).
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import secrets as pysecrets
import shutil
import signal
import subprocess
import sys
import time
from typing import Callable

from tony_tpu import constants as C
from tony_tpu.config import TonyConf
from tony_tpu.rpc import RpcClient
from tony_tpu.runtime import get_am_adapter
from tony_tpu.session import TaskInfo
from tony_tpu.utils import (
    app_staging_dir,
    new_app_id,
    parse_resources,
    staging_root,
    unzip,
    zip_dir,
)

log = logging.getLogger(__name__)

TaskUpdateListener = Callable[[list[TaskInfo]], None]
"""Ref: client/TaskUpdateListener.java:11."""


class TonyClient:
    def __init__(self, conf: TonyConf):
        self.conf = conf
        self.app_id: str = ""
        self.job_dir: str = ""
        self.secret: str | None = None
        self.listeners: list[TaskUpdateListener] = []
        self.coordinator_proc: subprocess.Popen | None = None
        self.rpc: RpcClient | None = None
        self.final_status: dict | None = None
        self.tensorboard_url = ""
        self.tls_fingerprint: str | None = None

    def add_listener(self, listener: TaskUpdateListener) -> None:
        self.listeners.append(listener)

    # ------------------------------------------------------------ submission
    def init(self) -> None:
        """Validate conf + runtime preflight (ref: TonyClient.init :442 /
        validateTonyConf :788)."""
        self.conf.validate()
        self._validate_sidecar_tb()
        framework = str(self.conf.get("tony.application.framework"))
        get_am_adapter(framework).validate_and_update_config(self.conf)

    def _sidecar_tb_mode(self) -> str:
        """How a configured ``tensorboard`` role gets its command:
        ``user`` (explicit tony.tensorboard.command), ``builtin`` (log dir
        set -> ship the built-in launcher; ref: the reference gates sidecar
        TB on its log-dir flag, TonyClient.java:560-600), ``fallback``
        (tony.application.executes serves the role, the pre-existing
        entrypoint-switches-on-JOB_NAME pattern), or ``none``."""
        role = C.TENSORBOARD_JOB_NAME
        if role not in self.conf.roles():
            return "none"
        if str(self.conf.role_get(role, "command")):
            return "user"
        if str(self.conf.get("tony.application.tensorboard-log-dir", "")):
            return "builtin"
        if str(self.conf.get("tony.application.executes", "")):
            return "fallback"
        return "error"

    def _validate_sidecar_tb(self) -> None:
        """A ``tensorboard`` role with nothing to run fails at submit time,
        not as a silently tolerated sidecar crash."""
        if self._sidecar_tb_mode() == "error":
            from tony_tpu.config import ConfError
            raise ConfError(
                "tony.tensorboard.instances is set with no "
                "tony.tensorboard.command; the built-in sidecar launcher "
                "needs tony.application.tensorboard-log-dir")

    def _set_sidecar_tb_command(self) -> None:
        """Ship the built-in sidecar launcher into the job dir and point the
        command-less ``tensorboard`` role at it (ref: setSidecarTBResources
        TonyClient.java:571-600 localizing resources/sidecar_tensorboard.py).
        The script is stdlib-only, so it runs under the shipped venv's
        python when present, else the task host's python3 — never the
        client's interpreter, which may not exist under ssh/docker launch
        modes."""
        if self._sidecar_tb_mode() != "builtin":
            return
        from tony_tpu.runtime import sidecar_tensorboard
        script = os.path.join(self.job_dir, "sidecar_tensorboard.py")
        shutil.copyfile(sidecar_tensorboard.__file__, script)
        venv_python = os.path.join(self.job_dir, "venv", "bin", "python")
        interp = venv_python if os.path.exists(venv_python) else "python3"
        self.conf.set(f"tony.{C.TENSORBOARD_JOB_NAME}.command",
                      f"{interp} {script}")

    def stage(self) -> str:
        """Create the job dir and localize src/venv/resources into it
        (ref: processFinalTonyConf :229-310 + processTonyConfResources
        :701-780 — HDFS upload becomes shared-filesystem copy)."""
        self.app_id = new_app_id()
        root = staging_root(str(self.conf.get("tony.staging-dir", "")))
        self.job_dir = app_staging_dir(root, self.app_id)
        from tony_tpu.utils import remotefs

        src_dir = str(self.conf.get("tony.application.src-dir", ""))
        if src_dir and remotefs.is_remote(src_dir):
            # gs:// src tree lands directly in the job dir (the local-path
            # zip/unzip below exists only to filter + flatten a local dir)
            remotefs.fetch(src_dir.rstrip("/") + "/*", self.job_dir,
                           recursive=True)
        elif src_dir:
            z = zip_dir(src_dir, os.path.join(self.job_dir, C.TONY_SRC_ZIP))
            unzip(z, self.job_dir)  # agents exec with cwd=job_dir
        else:
            # no staging AT ALL (neither src-dir nor role resources): a
            # relative `executes` that resolves from the SUBMITTER's cwd
            # (the `--conf_file examples/x/job.toml` shape) would
            # otherwise be re-resolved against the task's cwd (the job
            # dir) and break; pin it to the client-side file. When
            # anything IS staged, a relative executes names the staged
            # copy inside the job dir — it must stay relative so the
            # ssh launcher's shipped/rewritten job dir resolves it.
            any_resources = any(
                str(self.conf.role_get(role, "resources"))
                for role in self.conf.roles())
            executes = str(self.conf.get("tony.application.executes", ""))
            if executes and not any_resources and \
                    not os.path.isabs(executes) and os.path.exists(executes):
                self.conf.set("tony.application.executes",
                              os.path.abspath(executes))
        venv = str(self.conf.get("tony.application.python-venv", ""))
        if venv and remotefs.is_remote(venv):
            if venv.endswith(".zip"):
                fetched = remotefs.fetch(
                    venv, os.path.join(self.job_dir, C.TONY_VENV_ZIP))
                unzip(fetched, os.path.join(self.job_dir, "venv"))
            else:  # a directory prefix, like the local copytree branch
                dest = os.path.join(self.job_dir, "venv")
                os.makedirs(dest, exist_ok=True)
                remotefs.fetch(venv.rstrip("/") + "/*", dest,
                               recursive=True)
        elif venv:
            if venv.endswith(".zip"):
                unzip(venv, os.path.join(self.job_dir, "venv"))
            else:
                shutil.copytree(venv, os.path.join(self.job_dir, "venv"),
                                dirs_exist_ok=True)
        for role in self.conf.roles():
            spec = str(self.conf.role_get(role, "resources"))
            for res in parse_resources(spec):
                res.localize(self.job_dir)
        self._set_sidecar_tb_command()
        if self.conf.get_bool("tony.application.security.enabled"):
            self.secret = pysecrets.token_hex(32)
        if self.conf.get_bool("tony.application.security.tls"):
            # per-job self-signed cert minted at staging (the TokenCache
            # analog); the coordinator serves it, all peers pin it
            from tony_tpu.rpc.tls import cert_fingerprint, mint_self_signed

            cert, _key = mint_self_signed(self.job_dir,
                                          f"tony-{self.app_id}")
            self.tls_fingerprint = cert_fingerprint(cert)
        self.conf.write_final(os.path.join(self.job_dir, C.TONY_FINAL_CONF))
        return self.job_dir

    def start_coordinator(self, attempt: int = 0) -> None:
        """Launch the coordinator process (ref: submitApplication :314-349 +
        buildCommand :900-919 — the AM container spec becomes a subprocess).
        ``attempt`` is the client-side respawn index (YARN AM-attempt
        analog), exported so fault injections can target one attempt."""
        # a respawn must not connect to the dead generation's endpoint
        for stale in ("coordinator.json", "status.json"):
            with contextlib.suppress(OSError):
                os.remove(os.path.join(self.job_dir, stale))
        env = dict(os.environ)
        env[C.COORD_CLIENT_ATTEMPT] = str(attempt)
        if self.secret:
            env[C.JOB_TOKEN] = self.secret
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        log_path = os.path.join(self.job_dir, "logs", "coordinator.log")
        os.makedirs(os.path.dirname(log_path), exist_ok=True)
        out = open(log_path, "ab", buffering=0)
        try:
            self.coordinator_proc = subprocess.Popen(
                [sys.executable, "-m", "tony_tpu.coordinator",
                 "--conf", os.path.join(self.job_dir, C.TONY_FINAL_CONF),
                 "--app-id", self.app_id,
                 "--job-dir", self.job_dir],
                env=env,
                stdout=out,
                stderr=subprocess.STDOUT,
                start_new_session=True,
            )
        finally:
            out.close()
        log.info("coordinator launched for %s (pid %d)", self.app_id,
                 self.coordinator_proc.pid)

    # ------------------------------------------------------------ monitoring
    def _connect_rpc(self, timeout_s: float = 60) -> RpcClient:
        """Poll for coordinator.json then connect (ref: initRpcClientAndLog-
        AMUrl :1208-1229 — RPC port appears in the application report)."""
        path = os.path.join(self.job_dir, "coordinator.json")
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if os.path.exists(path):
                with open(path) as f:
                    info = json.load(f)
                return RpcClient(info["host"], info["port"],
                                 secret=self.secret,
                                 tls_fingerprint=self.tls_fingerprint)
            if self.coordinator_proc and self.coordinator_proc.poll() is not None:
                raise RuntimeError(
                    f"coordinator exited ({self.coordinator_proc.returncode}) "
                    f"before serving RPC; see {self.job_dir}/logs/coordinator.log")
            time.sleep(0.2)
        raise TimeoutError("coordinator endpoint never appeared")

    def monitor(self) -> bool:
        """Poll status until terminal (ref: monitorApplication :1031-1099).
        Returns True on SUCCEEDED.

        A coordinator process that dies WITHOUT a terminal status is
        respawned up to tony.client.coordinator-max-attempts times (the
        YARN AM-restart analog, ref: tony.am.retry handled by RM attempts)
        — checkpoint-dir jobs then resume from the last checkpoint."""
        self.rpc = self._connect_rpc()
        interval = self.conf.get_int("tony.client.poll-interval-ms", 1000) / 1000
        max_attempts = max(
            self.conf.get_int("tony.client.coordinator-max-attempts", 1), 1)
        attempt = 0
        last_rendered = ""
        status: dict = {"status": "RUNNING"}
        while True:
            try:
                status = self.rpc.call("get_application_status")
                infos = [TaskInfo.from_dict(d) for d in self.rpc.call("get_task_infos")]
            except (ConnectionError, TimeoutError):
                if self.coordinator_proc and self.coordinator_proc.poll() is not None:
                    terminal = self._status_from_file()
                    if terminal is None and attempt + 1 < max_attempts:
                        attempt += 1
                        fence_s = self._respawn_fence_s()
                        log.warning(
                            "coordinator died (exit %s) with no terminal "
                            "status; fencing %.0fs then respawning "
                            "(attempt %d/%d)",
                            self.coordinator_proc.returncode, fence_s,
                            attempt + 1, max_attempts)
                        time.sleep(fence_s)
                        self.start_coordinator(attempt=attempt)
                        try:
                            self.rpc = self._connect_rpc()
                        except (RuntimeError, TimeoutError, ConnectionError):
                            # either it died again (the death branch above
                            # consumes the next attempt) or it is alive but
                            # slow to serve — keep re-trying the connect
                            # while the process lives so a late endpoint
                            # is still picked up
                            log.warning("respawned coordinator not "
                                        "reachable yet; will keep trying")
                            while self.coordinator_proc.poll() is None:
                                try:
                                    self.rpc = self._connect_rpc(
                                        timeout_s=10)
                                    break
                                except (RuntimeError, TimeoutError,
                                        ConnectionError):
                                    continue
                        continue
                    status = terminal or {
                        "status": "FAILED",
                        "reason": "coordinator process died",
                    }
                    break
                time.sleep(interval)
                continue
            rendered = self._render_tasks(infos)
            if not infos and status.get("phase") not in (None, "", "READY"):
                # slice allocation in flight: show WHY there are no tasks
                rendered = f"Provisioning TPU capacity: {status['phase']}"
            if rendered != last_rendered:
                print(rendered)
                last_rendered = rendered
            for listener in self.listeners:
                try:
                    listener(infos)
                except Exception:
                    log.exception("task update listener failed")
            if status.get("tensorboard_url"):
                self.tensorboard_url = status["tensorboard_url"]
            if status["status"] != "RUNNING":
                break
            time.sleep(interval)
        self.final_status = status
        self._signal_finish()
        ok = status["status"] == "SUCCEEDED"
        log.info("application %s: %s (%s)", self.app_id, status["status"],
                 status.get("reason") or "ok")
        return ok

    def _respawn_fence_s(self) -> float:
        """How long to wait before respawning a dead coordinator so the old
        gang is certainly off the chips. Worst-case agent exit after the
        coordinator dies: the outage clock starts only after the FIRST
        failed ping returns (one interval wait + one RPC timeout,
        uncounted), the horizon check fires at the completion of a later
        ping (one more interval + timeout of granularity), then the
        checkpoint grace and the agent's +2 s SIGKILL-backstop sleep run.
        Budget all of it, plus margin."""
        from tony_tpu.coordinator.liveness import (
            heartbeat_rpc_timeout_s,
            liveness_expiry_s,
        )

        hb_s = self.conf.get_int("tony.task.heartbeat-interval-ms",
                                 1000) / 1000
        grace_s = self.conf.get_int("tony.task.preemption-grace-ms",
                                    15_000) / 1000
        lag = 2 * (hb_s + heartbeat_rpc_timeout_s(self.conf))
        return liveness_expiry_s(self.conf) + lag + grace_s + 2 + 3

    def _status_from_file(self) -> dict | None:
        path = os.path.join(self.job_dir, "status.json")
        if os.path.exists(path):
            with open(path) as f:
                return json.load(f)
        return None

    def _signal_finish(self) -> None:
        """Ref: signalAMToFinish :1101-1111."""
        if self.rpc is None:
            return
        try:
            self.rpc.call("finish_application", retries=0)
        except (ConnectionError, TimeoutError, Exception):
            pass
        if self.coordinator_proc:
            try:
                self.coordinator_proc.wait(timeout=60)
            except subprocess.TimeoutExpired:
                log.warning("coordinator slow to exit; killing")
                self.force_kill()
        self.rpc.close()

    @staticmethod
    def _render_tasks(infos: list[TaskInfo]) -> str:
        """Ref: client status tables TonyClient.java:1123-1183."""
        if not infos:
            return "(no tasks scheduled yet)"
        width = max(len(f"{i.name}:{i.index}") for i in infos)
        lines = [f"  {f'{i.name}:{i.index}'.ljust(width)}  {i.status:<9} {i.url}"
                 for i in infos]
        return "\n".join(["Task status:"] + lines)

    # ---------------------------------------------------------------- control
    def force_kill(self) -> None:
        """Ref: forceKillApplication :1268."""
        if self.rpc is not None:
            try:
                self.rpc.call("force_kill", retries=0)
            except Exception:
                pass
        if self.coordinator_proc and self.coordinator_proc.poll() is None:
            try:
                os.killpg(self.coordinator_proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                self.coordinator_proc.kill()

    def run(self) -> bool:
        """Full submission flow (ref: TonyClient.run :195 / start :1290)."""
        self.init()
        self.stage()
        self.start_coordinator()
        try:
            return self.monitor()
        except BaseException:
            self.force_kill()
            raise
