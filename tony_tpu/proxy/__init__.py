from tony_tpu.proxy.proxy import ProxyServer

__all__ = ["ProxyServer"]
