"""The gateway's network face: a stdlib-only concurrent HTTP server.

One handler thread per connection (``ThreadingHTTPServer``, the same
transport the portal uses) — a slow reader stalls only its own thread,
never the decode loops, which live on the replica threads behind the
admission queue. Endpoints:

  POST /v1/generate   submit one request; JSON body (see _parse_body)
                      {"stream": true} -> chunked NDJSON: one
                      {"id", "token_ids": [delta...]} line per step,
                      then a final line with finish_reason/metrics.
                      Otherwise one JSON object when done.
  GET  /healthz       liveness: 200 while the process serves at all;
                      body = per-replica breaker state + heartbeat age
                      ("ok" / "degraded" / "down" — the early-warning
                      signal before /readyz flips)
  GET  /readyz        admission: 200 accepting / 503 draining OR zero
                      healthy replicas (the load-balancer signal
                      during graceful shutdown and total outage)
  GET  /stats         the Gateway.snapshot() JSON (counters, queue
                      depths, p50/p95/p99 queue-wait/TTFT/TPOT, and
                      the engine rollup — prefills/decode steps/
                      occupancy/wasted_steps plus the engine.spec
                      speculative-decoding acceptance block and the
                      engine.prefix hit-rate block)

Shed mapping (core.Shed.http_status): 400 bad request, 429 admission
queue full, 503 draining, 504 deadline exceeded. In streaming mode the
status line is only committed at the FIRST event, so a request shed
while queued still gets its real status code, not a 200 with an error
trailer.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable

from tony_tpu.gateway.core import Gateway, GenRequest, Shed

log = logging.getLogger(__name__)


class GatewayHandler(BaseHTTPRequestHandler):
    # bound by GatewayHTTP: the shared Gateway plus optional tokenizer
    # hooks (encode: str -> [ids]; decode: [ids] -> str)
    gateway: Gateway
    encode: Callable | None = None
    decode: Callable | None = None
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet: requests are metrics,
        log.debug(fmt, *args)  # not stderr noise

    # ------------------------------------------------------------- GET

    def do_GET(self):
        path = self.path.partition("?")[0]
        if path == "/healthz":
            # 200 while the PROCESS serves at all — but the body now
            # carries per-replica breaker state + heartbeat age, so a
            # balancer sees "degraded" before anything 503s
            return self._send(200, self.gateway.health())
        if path == "/readyz":
            if self.gateway.ready and self.gateway.n_healthy > 0:
                return self._send(200, {"status": "ready"})
            if self.gateway.ready:  # started, zero healthy replicas:
                # every breaker is open — shed clean 503s until a
                # probe rejoins one
                return self._send(503, {"status": "no healthy replicas"})
            return self._send(503, {"status": "draining"
                                    if self.gateway.draining
                                    else "starting"})
        if path == "/stats":
            return self._send(200, self.gateway.snapshot())
        return self._send(404, {"error": "not found"})

    # ------------------------------------------------------------ POST

    def do_POST(self):
        if self.path.partition("?")[0] != "/v1/generate":
            return self._send(404, {"error": "not found"})
        try:
            body = self._read_body()
            req, stream = self._parse_body(body)
        except (TypeError, ValueError) as e:
            # TypeError too: int()/float()/iteration over wrong-typed
            # JSON values ({"token_ids": 123}, {"temperature": null})
            # must be a 400, not a handler-thread crash + reset socket
            return self._send(400, {"error": str(e)})
        try:
            ticket = self.gateway.submit(req)
        except Shed as e:
            return self._send(e.http_status, {"error": e.reason})
        try:
            if stream:
                self._respond_stream(ticket)
            else:
                self._respond_unary(ticket)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away; the request finishes server-side
            # and its deadline/shed path handles abandoned successors

    def _read_body(self) -> dict:
        length = int(self.headers.get("Content-Length") or 0)
        if length <= 0:
            raise ValueError("missing request body")
        if length > 8 << 20:
            raise ValueError("request body too large")
        raw = self.rfile.read(length)
        try:
            body = json.loads(raw)
        except json.JSONDecodeError as e:
            raise ValueError(f"invalid JSON: {e}") from None
        if not isinstance(body, dict):
            raise ValueError("request must be a JSON object")
        return body

    def _parse_body(self, d: dict) -> tuple[GenRequest, bool]:
        if "token_ids" in d:
            ids = [int(x) for x in d["token_ids"]]
        elif "prompt" in d:
            if self.encode is None:
                raise ValueError(
                    "text prompt needs a tokenizer in the model dir; "
                    "send token_ids instead")
            ids = self.encode(str(d["prompt"]))
        else:
            raise ValueError("request needs token_ids or prompt")
        ttl = d.get("ttl_s", d.get("timeout_s"))
        return GenRequest(
            ids,
            max_new_tokens=int(d.get("max_new_tokens", 64)),
            temperature=float(d.get("temperature", 0.0)),
            top_k=int(d.get("top_k", 0)),
            seed=int(d.get("seed", 0)),
            id=d.get("id"),
            ttl_s=float(ttl) if ttl is not None else None,
            session=d.get("session"),
        ), bool(d.get("stream", False))

    # -------------------------------------------------------- responses

    def _finish_doc(self, res, metrics: dict) -> dict:
        out = {"id": res.id, "token_ids": list(res.prompt) + list(res.tokens),
               "finish_reason": res.finish_reason, "metrics": metrics}
        if self.decode is not None:
            out["text"] = self.decode(out["token_ids"])
        return out

    def _respond_unary(self, ticket) -> None:
        try:
            res = ticket.result()
        except Shed as e:
            return self._send(e.http_status, {"error": e.reason})
        # ticket.metrics is the replica's canonical per-request record
        # (same dict the stream's final line and /stats window carry)
        self._send(200, self._finish_doc(res, ticket.metrics or {}))

    def _respond_stream(self, ticket) -> None:
        """Chunked NDJSON. Headers are sent lazily at the first event
        so sheds keep their real status code."""
        headers_sent = False
        while True:
            kind, *rest = ticket.events.get()
            if kind == "tokens":
                if not headers_sent:
                    self._start_stream()
                    headers_sent = True
                self._chunk({"id": ticket.request.id, "token_ids": rest[0]})
            elif kind == "done":
                res, metrics = rest
                if not headers_sent:
                    self._start_stream()
                    headers_sent = True
                self._chunk(self._finish_doc(res, metrics))
                self.wfile.write(b"0\r\n\r\n")
                return
            elif kind == "shed":
                status, reason = rest
                if headers_sent:  # mid-stream shed: error line + close
                    self._chunk({"id": ticket.request.id, "error": reason,
                                 "status": status})
                    self.wfile.write(b"0\r\n\r\n")
                else:
                    self._send(status, {"error": reason})
                return

    def _start_stream(self) -> None:
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header("Cache-Control", "no-store")
        self.end_headers()

    def _chunk(self, doc: dict) -> None:
        data = (json.dumps(doc) + "\n").encode()
        self.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")
        self.wfile.flush()

    def _send(self, code: int, doc: dict) -> None:
        data = json.dumps(doc).encode()
        if code >= 400:
            # error replies may leave a POST body unread; under
            # HTTP/1.1 keep-alive those bytes would be parsed as the
            # NEXT request line — close instead of desyncing
            self.close_connection = True
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        if code >= 400:
            self.send_header("Connection", "close")
        self.end_headers()
        self.wfile.write(data)


class GatewayHTTP:
    """Binds a Gateway to a ThreadingHTTPServer; start()/stop()."""

    def __init__(self, gateway: Gateway, host: str = "127.0.0.1",
                 port: int = 0, encode: Callable | None = None,
                 decode: Callable | None = None):
        handler = type("BoundGatewayHandler", (GatewayHandler,),
                       {"gateway": gateway, "encode": staticmethod(encode)
                        if encode else None,
                        "decode": staticmethod(decode) if decode else None})
        self.server = ThreadingHTTPServer((host, port), handler)
        self.server.daemon_threads = True
        self.host, self.port = self.server.server_address[:2]
        self._thread: threading.Thread | None = None

    def start(self) -> "GatewayHTTP":
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        name="gateway-http", daemon=True)
        self._thread.start()
        log.info("gateway http at http://%s:%d", self.host, self.port)
        return self

    def stop(self) -> None:
        self.server.shutdown()
        self.server.server_close()
