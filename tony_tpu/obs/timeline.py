"""Per-dispatch engine timeline: what every device program launch cost.

The serving engine's counters (prefills / decode_steps / dispatches)
say HOW MUCH device work ran; this module records WHEN and HOW LONG —
one ``DispatchRecord`` per engine dispatch (prefill, hit-admit, decode
chunk, spec-verify), with the live-slot occupancy, the program's shape
knob (prefill bucket / chunk depth / verify window), the tokens the
dispatch actually landed, and a first-call flag separating compile
(or compile-cache-load) time from steady state. This is the direct
sensor for ROADMAP item 4's dispatch-overhead attack: the roofline gap
shows up here as host-side milliseconds per dispatch that the per-op
xplane view cannot see.

Durations are HOST WALL time from just before the dispatch call to
just after the engine's host sync of its outputs — on an async backend
that includes device execution plus transfer, which is exactly the
latency a request experiences. The ``compile`` flag marks the first
record of each (kind, shape) pair on this engine; with a warm
in-process jit cache or a persistent compile cache the flagged call
may be cheap — the flag means "first call", the duration says whether
it compiled.

A bounded ring keeps recent records for trace attachment and debug;
cumulative per-kind aggregates survive eviction, so ``summary()`` (the
``/stats`` ``dispatches`` block) is lifetime-accurate. Appending is a
lock plus a dataclass — cheap enough to leave on in production, which
the obs overhead gate (bench ``extras.obs``) pins.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field
from typing import Any


@dataclass
class DispatchRecord:
    """One engine dispatch. ``kind`` is "prefill" | "hit_admit" |
    "cow_admit" | "decode" | "verify" — cow_admit is the PAGED
    exact-prefix-hit admission (pages aliased host-side, one sampling
    dispatch): its own kind so per-kind ``tokens_per_dispatch`` never
    counts an aliasing admit as prefill work. ``bucket`` is the
    program's static shape knob (prefill bucket length, chunk depth,
    verify window — 0 for hit_admit/cow_admit); ``tokens`` counts
    tokens the dispatch landed for requests (trimmed overshoot
    excluded); ``request_id`` is set on admit dispatches (the engine
    id of the admitted request)."""

    kind: str
    t0: float          # time.monotonic() at dispatch start
    dur_ms: float      # host wall: dispatch + output sync
    occupancy: int     # live slots at dispatch time
    bucket: int
    tokens: int
    compile: bool      # first (kind, bucket) call on this engine
    request_id: Any = None
    tags: dict = field(default_factory=dict)
    seq: int = 0       # assigned by the timeline, monotonically
    # goodput-attribution fields (obs/goodput.py): ``work`` is the
    # program's static position capacity (chunk depth x batch, verify
    # window x batch, prefill bucket, 1 for admits), ``fed`` the
    # positions actually given real inputs (depth x occupancy,
    # last-token + drafts, suffix length), ``rejected`` the
    # speculative-draft positions the verify pass refused. The
    # duration split the ledger uses is exact by construction:
    # useful + padding + overshoot + rejected positions == work.
    # In-dispatch-EOS engines (ISSUE-13) count a finished slot's
    # FROZEN positions (re-emits, no KV writes) as not-fed, so
    # fed == tokens on every decode record and the overshoot bucket
    # is structurally 0 — the frozen tail lands in padding next to
    # the empty-slot positions it behaves like (the record's
    # ``frozen`` tag carries the count).
    # ``est_bytes``/``est_flops`` are the CostModel's analytic program
    # cost (0 when no cost model is attached).
    work: int = 0
    fed: int = 0
    rejected: int = 0
    est_bytes: float = 0.0
    est_flops: float = 0.0


# wire codec (the agent's ``GET /v1/obs`` channel): every field a
# DispatchRecord carries, JSON-shaped. ``t0`` stays in the RECORDING
# process's monotonic clock — the puller owns the clock-offset
# correction (gateway/remote.py), because only it can estimate the
# offset (RTT-midpoint over its own heartbeats).
def record_doc(rec: DispatchRecord) -> dict:
    return {
        "seq": rec.seq, "kind": rec.kind, "t0": rec.t0,
        "dur_ms": rec.dur_ms, "occupancy": rec.occupancy,
        "bucket": rec.bucket, "tokens": rec.tokens,
        "compile": rec.compile, "request_id": rec.request_id,
        "tags": dict(rec.tags), "work": rec.work, "fed": rec.fed,
        "rejected": rec.rejected, "est_bytes": rec.est_bytes,
        "est_flops": rec.est_flops,
    }


def record_from_doc(doc: dict) -> DispatchRecord:
    rec = DispatchRecord(
        kind=str(doc.get("kind", "?")), t0=float(doc.get("t0", 0.0)),
        dur_ms=float(doc.get("dur_ms", 0.0)),
        occupancy=int(doc.get("occupancy", 0)),
        bucket=int(doc.get("bucket", 0)),
        tokens=int(doc.get("tokens", 0)),
        compile=bool(doc.get("compile", False)),
        request_id=doc.get("request_id"),
        tags=dict(doc.get("tags") or {}),
        work=int(doc.get("work", 0)), fed=int(doc.get("fed", 0)),
        rejected=int(doc.get("rejected", 0)),
        est_bytes=float(doc.get("est_bytes", 0.0)),
        est_flops=float(doc.get("est_flops", 0.0)))
    rec.seq = int(doc.get("seq", 0))
    return rec


class DispatchTimeline:
    """Ring of recent ``DispatchRecord``s + lifetime per-kind
    aggregates. Thread-safe; the engine records from its owner thread,
    readers (``/stats``, the trace attacher) snapshot from others."""

    # per-kind lifetime aggregate template; the *_ms split keys are the
    # goodput ledger's input (obs/goodput.py)
    _AGG_KEYS = ("count", "ms", "max_ms", "compiles", "compile_ms",
                 "tokens", "work", "fed", "est_bytes", "est_flops",
                 "est_bytes_steady", "est_flops_steady",
                 "useful_ms", "padding_ms", "overshoot_ms",
                 "rejected_ms")

    def __init__(self, capacity: int = 1024):
        self._lock = threading.Lock()
        self._ring: deque[DispatchRecord] = deque(maxlen=max(1, capacity))
        self._seq = 0
        self._agg: dict[str, dict[str, float]] = {}

    def record(self, rec: DispatchRecord) -> None:
        with self._lock:
            self._seq += 1
            rec.seq = self._seq
            self._ring.append(rec)
            agg = self._agg.setdefault(
                rec.kind, {k: 0.0 for k in self._AGG_KEYS})
            agg["count"] += 1
            agg["ms"] += rec.dur_ms
            agg["max_ms"] = max(agg["max_ms"], rec.dur_ms)
            agg["tokens"] += rec.tokens
            agg["work"] += rec.work
            agg["fed"] += rec.fed
            agg["est_bytes"] += rec.est_bytes
            agg["est_flops"] += rec.est_flops
            if rec.compile:
                # a first-call dispatch is all compile bucket: its
                # duration is dominated by program build / cache load,
                # and splitting it by positions would charge compile
                # time to "useful"
                agg["compiles"] += 1
                agg["compile_ms"] += rec.dur_ms
                return
            # steady-only cost sums: the utilization estimate divides
            # by steady milliseconds, so its numerator must exclude
            # compile-marked records too — lifetime est_bytes above
            # keeps pricing every dispatch for the /metrics counters
            agg["est_bytes_steady"] += rec.est_bytes
            agg["est_flops_steady"] += rec.est_flops
            work = max(1, rec.work)
            # useful positions: tokens LANDED for decode/verify; for a
            # prefill the landed-token count is 1 (the sampled first
            # token) but the useful work is the fed suffix window; the
            # single-position admits are all useful
            if rec.kind in ("prefill", "prefill_chunk"):
                # prefill-shaped dispatches: the landed-token count is
                # 1 (or 0 for an intermediate chunk) but the useful
                # work is the fed prompt window
                useful = min(rec.fed, work)
            elif rec.work <= 1:
                useful = work
            else:
                useful = min(rec.tokens, rec.fed)
            rejected = min(max(0, rec.rejected), max(0, rec.fed - useful))
            padding = max(0, work - max(rec.fed, useful))
            overshoot = max(0, work - useful - rejected - padding)
            agg["useful_ms"] += rec.dur_ms * useful / work
            agg["rejected_ms"] += rec.dur_ms * rejected / work
            agg["padding_ms"] += rec.dur_ms * padding / work
            agg["overshoot_ms"] += rec.dur_ms * overshoot / work

    def take_new(self, cursor: int) -> tuple[list[DispatchRecord], int]:
        """Records with ``seq > cursor`` still in the ring, plus the new
        cursor — the trace attacher's incremental read. Records evicted
        before being read are simply gone (bounded memory beats
        completeness for a debug surface). O(new), not O(ring): this
        runs on the replica scheduler loop every iteration under the
        same lock ``record()`` needs, so a full-ring scan per step
        would be pure hot-loop waste."""
        with self._lock:
            if self._seq == cursor:
                return [], cursor
            new = []
            for rec in reversed(self._ring):  # deque ends are O(1)
                if rec.seq <= cursor:
                    break
                new.append(rec)
            new.reverse()
            return new, self._seq

    @property
    def seq(self) -> int:
        """The last assigned sequence number (a ``since()``/cursor
        anchor for callers that will later want 'records after now')."""
        with self._lock:
            return self._seq

    def since(self, seq: int) -> list[DispatchRecord]:
        """Records with ``seq > seq`` still in the ring — the
        NON-destructive cousin of ``take_new`` (no cursor owned): the
        agent's per-request fragment gather anchors at the request's
        submit-time seq, so a finished request scans only its own
        lifetime's tail instead of the whole ring. O(new), same
        reverse-iterate-and-break as take_new."""
        with self._lock:
            if self._seq <= seq:
                return []
            out = []
            for rec in reversed(self._ring):
                if rec.seq <= seq:
                    break
                out.append(rec)
            out.reverse()
            return out

    def recent(self, n: int = 64) -> list[DispatchRecord]:
        with self._lock:
            return list(self._ring)[-n:]

    def summary(self) -> dict:
        """The ``/stats`` ``dispatches`` block: lifetime per-kind
        aggregates with compile time split out, so steady-state
        mean_ms answers "what does one dispatch cost" without the
        first-call spike polluting it. The goodput extension rides
        along: position accounting (``work``/``fed``), the analytic
        ``est_bytes``/``est_flops`` totals, and the per-kind duration
        split (``useful_ms``/``padding_ms``/``overshoot_ms``/
        ``rejected_ms``) the ledger folds with the wall clock."""
        out: dict = {}
        with self._lock:
            items = {k: dict(v) for k, v in self._agg.items()}
        for kind, a in sorted(items.items()):
            steady_n = a["count"] - a["compiles"]
            steady_ms = a["ms"] - a["compile_ms"]
            out[kind] = {
                "count": int(a["count"]),
                "ms": round(a["ms"], 3),
                "max_ms": round(a["max_ms"], 3),
                "compiles": int(a["compiles"]),
                "compile_ms": round(a["compile_ms"], 3),
                "steady_mean_ms": round(steady_ms / steady_n, 3)
                if steady_n else 0.0,
                "tokens": int(a["tokens"]),
                "tokens_per_dispatch": round(a["tokens"] / a["count"], 3)
                if a["count"] else 0.0,
                "work": int(a["work"]),
                "fed": int(a["fed"]),
                "est_bytes": round(a["est_bytes"], 1),
                "est_flops": round(a["est_flops"], 1),
                "est_bytes_steady": round(a["est_bytes_steady"], 1),
                "est_flops_steady": round(a["est_flops_steady"], 1),
                "useful_ms": round(a["useful_ms"], 3),
                "padding_ms": round(a["padding_ms"], 3),
                "overshoot_ms": round(a["overshoot_ms"], 3),
                "rejected_ms": round(a["rejected_ms"], 3),
            }
        return out

    # summed across replicas in merge(); max_ms maxes, means recompute
    _SUM_KEYS = ("count", "ms", "compiles", "compile_ms", "tokens",
                 "work", "fed", "est_bytes", "est_flops",
                 "est_bytes_steady", "est_flops_steady", "useful_ms",
                 "padding_ms", "overshoot_ms", "rejected_ms")

    @classmethod
    def merge(cls, summaries: list[dict]) -> dict:
        """Sum per-kind summaries across replicas (the fleet view the
        gateway's ``/stats`` carries): counts/ms/tokens/bytes/flops and
        the ledger splits add, max_ms maxes, means are recomputed from
        the merged totals."""
        merged: dict = {}
        for s in summaries:
            for kind, v in s.items():
                m = merged.setdefault(kind, dict.fromkeys(
                    cls._SUM_KEYS, 0.0))
                m["max_ms"] = max(m.get("max_ms", 0.0), v["max_ms"])
                for key in cls._SUM_KEYS:
                    m[key] += v.get(key, 0)
        for kind, m in merged.items():
            steady_n = m["count"] - m["compiles"]
            steady_ms = m["ms"] - m["compile_ms"]
            for key in ("count", "compiles", "tokens", "work", "fed"):
                m[key] = int(m[key])
            for key in ("ms", "compile_ms", "useful_ms", "padding_ms",
                        "overshoot_ms", "rejected_ms"):
                m[key] = round(m[key], 3)
            m["est_bytes"] = round(m["est_bytes"], 1)
            m["est_flops"] = round(m["est_flops"], 1)
            m["est_bytes_steady"] = round(m["est_bytes_steady"], 1)
            m["est_flops_steady"] = round(m["est_flops_steady"], 1)
            m["steady_mean_ms"] = round(steady_ms / steady_n, 3) \
                if steady_n else 0.0
            m["tokens_per_dispatch"] = round(m["tokens"] / m["count"], 3) \
                if m["count"] else 0.0
        return merged
