"""LoRA fine-tuning (train/lora.py): adapter init/merge math, Trainer
integration with frozen base params, and serving after materialization."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tony_tpu.models import Transformer, TransformerConfig, generate
from tony_tpu.parallel import data_parallel_mesh
from tony_tpu.parallel.sharding import batch_sharding
from tony_tpu.train import (
    Trainer,
    cross_entropy_loss,
    lora_init,
    lora_param_count,
    materialize_lora,
    merge_lora,
    wrap_apply_fn,
)


@pytest.fixture(scope="module")
def base():
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4,
                            n_kv_heads=2, n_layers=2, d_ff=64,
                            max_seq_len=16, dtype=jnp.float32,
                            attention_backend="reference")
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))
    return model, params


def test_zero_init_is_exact_base(base):
    """B starts at zero, so step-0 LoRA output == base model output
    bit-for-bit — the property that makes LoRA a safe warm start."""
    model, params = base
    lora = lora_init(jax.random.PRNGKey(1), params, rank=4)
    merged = merge_lora(params, lora)
    tokens = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(model.apply(params, tokens)),
        np.asarray(model.apply(merged, tokens)))


def test_targets_and_shapes(base):
    """Default targets adapt q/v kernels only (incl. the multi-dim
    DenseGeneral output [d, heads, dh] / GQA kv shape), nothing else."""
    _, params = base
    lora = lora_init(jax.random.PRNGKey(1), params, rank=4)
    flat = {tuple(p.key for p in path): leaf for path, leaf in
            jax.tree_util.tree_flatten_with_path(lora)[0]}
    kinds = {path[-3] for path in flat}  # .../attn/<q|v>/<a|b>... parent
    assert kinds == {"q", "v"}, kinds
    blk = lora["params"]["block_0"]["attn"]
    assert blk["q"]["kernel"]["a"].shape == (32, 4)
    assert blk["q"]["kernel"]["b"].shape == (4, 4, 8)   # [r, heads, dh]
    assert blk["v"]["kernel"]["b"].shape == (4, 2, 8)   # GQA kv heads
    # adapters are tiny next to the model
    n_model = sum(x.size for x in jax.tree.leaves(params))
    assert lora_param_count(lora) < 0.1 * n_model


def test_merge_math_matches_manual(base):
    _, params = base
    lora = lora_init(jax.random.PRNGKey(2), params, rank=3)
    blk = lora["params"]["block_1"]["attn"]["q"]["kernel"]
    # make B nonzero so the delta is visible
    blk["b"] = jnp.ones_like(blk["b"]) * 0.01
    merged = merge_lora(params, lora, alpha=6.0)
    w = params["params"]["block_1"]["attn"]["q"]["kernel"]
    want = w + (6.0 / 3) * jnp.tensordot(blk["a"], blk["b"],
                                         axes=([1], [0]))
    np.testing.assert_allclose(
        np.asarray(merged["params"]["block_1"]["attn"]["q"]["kernel"]),
        np.asarray(want), rtol=1e-6)
    # untouched kernels are identical objects' values
    np.testing.assert_array_equal(
        np.asarray(merged["params"]["block_1"]["attn"]["k"]["kernel"]),
        np.asarray(params["params"]["block_1"]["attn"]["k"]["kernel"]))


def test_lora_rejects_no_match(base):
    _, params = base
    with pytest.raises(ValueError, match="no kernels matched"):
        lora_init(jax.random.PRNGKey(0), params, targets=("nope",))


def test_lora_training_and_serving(base):
    """End-to-end: Trainer optimizes ONLY the adapters (optimizer state is
    LoRA-sized), loss falls, and the materialized params serve through
    generate() while the base stays frozen."""
    model, params = base
    mesh = data_parallel_mesh()
    n_dev = max(1, jax.device_count())
    tokens = jax.random.randint(jax.random.PRNGKey(3), (n_dev, 8), 0, 64)
    batch = {"tokens": jax.device_put(tokens, batch_sharding(mesh))}

    def base_apply(p, b):
        logits = model.apply(p, b["tokens"])
        return cross_entropy_loss(logits[:, :-1], b["tokens"][:, 1:])

    lora = lora_init(jax.random.PRNGKey(4), params, rank=4)
    trainer = Trainer(mesh=mesh,
                      apply_fn=wrap_apply_fn(base_apply, params, alpha=8.0),
                      optimizer=optax.adam(3e-2), donate=False)
    state = trainer.init_state(lora)
    opt_leaves = sum(x.size for x in jax.tree.leaves(state.opt_state)
                     if hasattr(x, "size"))
    assert opt_leaves <= 3 * lora_param_count(lora)  # adam moments, LoRA-sized

    step_fn, placed = trainer.build_step(state)
    losses = []
    for _ in range(60):
        placed, metrics = step_fn(placed, batch)
        losses.append(float(metrics["loss"]))
    # q/v-only rank-4 adapters over a random base have modest capacity;
    # a clear monotone-ish drop is the mechanism assertion
    assert losses[-1] < losses[0] - 0.3, (losses[0], losses[-1])

    served = materialize_lora(params, placed.params, alpha=8.0)
    out_base = np.asarray(generate(model, params["params"],
                                   tokens[:1, :4], max_new_tokens=3))
    out_tuned = np.asarray(generate(model, served["params"],
                                    tokens[:1, :4], max_new_tokens=3))
    assert out_tuned.shape == out_base.shape  # serves fine; training moved
    # the merged weights (logits differ even if argmax happens to agree)
    lb = model.apply(params, tokens[:1])
    lt = model.apply(served, tokens[:1])
    assert not np.allclose(np.asarray(lb), np.asarray(lt))


def test_wrap_apply_fn_compute_dtype_casts_base(base):
    """Mixed precision flows through the wrapper: with
    compute_dtype=bf16 the merged weights reaching the model are bf16
    (an fp32 base would silently promote the whole forward)."""
    model, params = base
    lora = lora_init(jax.random.PRNGKey(5), params, rank=2)
    seen = {}

    def base_apply(p, batch):
        seen["dtype"] = p["params"]["block_0"]["attn"]["q"]["kernel"].dtype
        return jnp.float32(0.0)

    wrapped = wrap_apply_fn(base_apply, params,
                            compute_dtype=jnp.bfloat16)
    wrapped(lora, {})
    assert seen["dtype"] == jnp.bfloat16
    # and without the knob the base dtype passes through untouched
    wrap_apply_fn(base_apply, params)(lora, {})
    assert seen["dtype"] == jnp.float32
