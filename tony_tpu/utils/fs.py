"""Staging filesystem helpers: zip/unzip, job dirs, localizable resources.

Reference: util/Utils.java zipFolder/unzipArchive (:165-178),
extractResources (:750), uploadFileAndSetConfResources (:684);
LocalizableResource.java (path[::localName][#archive] parsing). HDFS is
replaced by a shared filesystem path (NFS/GCS-fuse on TPU-VMs); staging
layout mirrors ~/.tony/<app_id>/.
"""

from __future__ import annotations

import os
import shutil
import uuid
import zipfile
from dataclasses import dataclass

from tony_tpu import constants as C


def zip_dir(src_dir: str, dest_zip: str) -> str:
    os.makedirs(os.path.dirname(dest_zip) or ".", exist_ok=True)
    with zipfile.ZipFile(dest_zip, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, _, files in os.walk(src_dir):
            for name in files:
                full = os.path.join(root, name)
                zf.write(full, os.path.relpath(full, src_dir))
    return dest_zip


def unzip(archive: str, dest_dir: str) -> str:
    os.makedirs(dest_dir, exist_ok=True)
    with zipfile.ZipFile(archive) as zf:
        zf.extractall(dest_dir)
    return dest_dir


def staging_root(conf_value: str = "") -> str:
    return conf_value or os.path.join(os.path.expanduser("~"), C.TONY_STAGING_PREFIX)


def new_app_id() -> str:
    """application_<uuid> (ref: YARN appId; uuid keeps it collision-free
    without a central RM)."""
    return f"application_{uuid.uuid4().hex[:12]}"


def app_staging_dir(root: str, app_id: str) -> str:
    d = os.path.join(root, app_id)
    os.makedirs(d, exist_ok=True)
    return d


@dataclass
class LocalizableResource:
    """One ``path[::localName][#archive]`` resource spec
    (ref: LocalizableResource.java:30-114)."""

    source: str
    local_name: str
    is_archive: bool

    @classmethod
    def parse(cls, spec: str) -> "LocalizableResource":
        spec = spec.strip()
        is_archive = spec.endswith("#archive")
        if is_archive:
            spec = spec[: -len("#archive")]
        if "::" in spec:
            source, local_name = spec.split("::", 1)
        else:
            source, local_name = spec, os.path.basename(spec.rstrip("/"))
        return cls(source=source, local_name=local_name, is_archive=is_archive)

    def localize(self, dest_dir: str) -> str:
        """Materialize into ``dest_dir`` (dirs are zipped by the client;
        archives are extracted, ref: Utils.extractResources). ``gs://``
        sources are fetched first (ref: LocalizableResource.java:30-114
        remote branch — HDFS download becomes a GCS copy)."""
        from tony_tpu.utils import remotefs

        os.makedirs(dest_dir, exist_ok=True)
        dest = os.path.join(dest_dir, self.local_name)
        if remotefs.is_remote(self.source):
            if self.is_archive:
                fetched = remotefs.fetch(self.source, dest + ".fetch.zip")
                try:
                    return unzip(fetched, dest)
                finally:
                    os.remove(fetched)
            # Directory-prefix resources (the remote analog of the local
            # isdir/copytree branch below; ref HDFS dir localization):
            # a trailing slash is an explicit dir, otherwise fall back to
            # a recursive fetch ONLY when the flat copy reports a
            # miss/dir-shaped error — auth or network failures must
            # surface as-is, not be masked by a doomed -r retry.
            if self.source.endswith("/"):
                return remotefs.fetch(self.source.rstrip("/"), dest,
                                      recursive=True)
            try:
                return remotefs.fetch(self.source, dest)
            except RuntimeError as e:
                msg = str(e).lower()
                dir_shaped = any(s in msg for s in (
                    "no such", "not found", "matched no objects",
                    "no urls matched", "omitting directory",
                    "is a directory"))
                if not dir_shaped:
                    raise
                return remotefs.fetch(self.source, dest, recursive=True)
        if self.is_archive:
            return unzip(self.source, dest)
        if os.path.isdir(self.source):
            if os.path.abspath(self.source) != os.path.abspath(dest):
                shutil.copytree(self.source, dest, dirs_exist_ok=True)
            return dest
        shutil.copy2(self.source, dest)
        return dest


def parse_resources(spec: str) -> list[LocalizableResource]:
    return [LocalizableResource.parse(s) for s in spec.split(",") if s.strip()]
