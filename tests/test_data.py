"""Input-pipeline tests: sources, per-process sharding, prefetch, global
batch assembly on the 8-device CPU mesh."""

import json

import jax
import numpy as np
import pytest

from tony_tpu.data import (
    ArraySource,
    DataLoader,
    JsonlSource,
    SyntheticImageSource,
    SyntheticTokenSource,
    device_prefetch,
)
from tony_tpu.parallel import MeshSpec, make_mesh
from tony_tpu.parallel.sharding import batch_sharding


def test_array_source_and_loader_basic():
    src = ArraySource({"x": np.arange(10, dtype=np.float32),
                       "y": np.arange(10, dtype=np.int32) * 2})
    dl = DataLoader(src, global_batch_size=4, shuffle=False, num_epochs=1,
                    process_index=0, process_count=1, prefetch=0)
    batches = list(dl)
    assert len(batches) == 2  # drop_remainder: 10 -> 2 full batches of 4
    np.testing.assert_array_equal(batches[0]["x"], [0, 1, 2, 3])
    np.testing.assert_array_equal(batches[1]["y"], [8, 10, 12, 14])
    assert dl.steps_per_epoch() == 2


def test_array_source_validates_dims():
    with pytest.raises(ValueError):
        ArraySource({"a": np.zeros(3), "b": np.zeros(4)})


def test_loader_rejects_dataset_smaller_than_batch():
    """steps_per_epoch == 0 must raise, not hang the gang's collective."""
    src = ArraySource({"x": np.arange(3, dtype=np.float32)})
    with pytest.raises(ValueError, match="dataset too small"):
        DataLoader(src, global_batch_size=8, process_index=0, process_count=2)


def test_per_process_sharding_disjoint_and_complete():
    """Across processes: same permutation, disjoint strides, full coverage."""
    src = ArraySource({"x": np.arange(16, dtype=np.int64)})
    seen = []
    for pi in range(4):
        dl = DataLoader(src, global_batch_size=8, shuffle=True, seed=7,
                        num_epochs=1, process_index=pi, process_count=4,
                        prefetch=0)
        assert dl.local_batch_size == 2
        for batch in dl:
            seen.extend(batch["x"].tolist())
    assert sorted(seen) == list(range(16))  # exactly once each


def test_uneven_dataset_same_batch_count_every_process():
    """15 examples over 4 processes: every process must yield the SAME
    number of batches (a straggler ending early would hang the cross-host
    collective), capped by the minimum per-process share."""
    src = ArraySource({"x": np.arange(15, dtype=np.int64)})
    counts = []
    for pi in range(4):
        dl = DataLoader(src, global_batch_size=8, shuffle=True, seed=1,
                        num_epochs=1, process_index=pi, process_count=4,
                        prefetch=0)
        counts.append(sum(1 for _ in dl))
        assert dl.steps_per_epoch() == counts[-1]
    assert len(set(counts)) == 1, counts
    assert counts[0] == 1  # floor(15/4)=3 -> 3//2=1 full local batch


def test_shuffle_differs_by_epoch_and_is_seeded():
    src = ArraySource({"x": np.arange(8, dtype=np.int64)})

    def epoch_order(seed, epochs):
        dl = DataLoader(src, global_batch_size=8, seed=seed,
                        num_epochs=epochs, process_index=0, process_count=1,
                        prefetch=0)
        return [b["x"].tolist() for b in dl]

    two = epoch_order(3, 2)
    assert two[0] != two[1]  # reshuffled per epoch
    assert epoch_order(3, 2) == two  # deterministic in seed


def test_synthetic_sources_deterministic():
    tok = SyntheticTokenSource(4, seq_len=8, vocab_size=100, seed=1)
    np.testing.assert_array_equal(tok[2]["tokens"], tok[2]["tokens"])
    assert tok[0]["tokens"].shape == (8,)
    assert (tok[0]["tokens"] != tok[1]["tokens"]).any()
    img = SyntheticImageSource(3, 8, 8, num_classes=10, seed=2)
    ex = img[1]
    assert ex["image"].shape == (8, 8, 3)
    assert 0 <= int(ex["label"]) < 10


def test_jsonl_source(tmp_path):
    p = tmp_path / "data.jsonl"
    rows = [{"tokens": [1, 2, 3], "label": 0}, {"tokens": [4, 5, 6], "label": 1}]
    p.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    src = JsonlSource(p, dtypes={"tokens": np.int32})
    assert len(src) == 2
    np.testing.assert_array_equal(src[1]["tokens"], [4, 5, 6])
    assert src[1]["tokens"].dtype == np.int32
    dl = DataLoader(src, global_batch_size=2, shuffle=False, num_epochs=1,
                    process_index=0, process_count=1, prefetch=0)
    (batch,) = list(dl)
    assert batch["tokens"].shape == (2, 3)


def test_global_array_assembly_on_mesh():
    """sharding= yields global jax.Arrays laid out over the 8-device mesh."""
    mesh = make_mesh(MeshSpec(data=-1))
    sh = batch_sharding(mesh)
    src = SyntheticTokenSource(32, seq_len=4, vocab_size=50, seed=0)
    dl = DataLoader(src, global_batch_size=16, num_epochs=1, sharding=sh,
                    process_index=0, process_count=1)
    batches = list(dl)
    assert len(batches) == 2
    arr = batches[0]["tokens"]
    assert isinstance(arr, jax.Array)
    assert arr.shape == (16, 4)
    assert arr.sharding.is_equivalent_to(sh, arr.ndim)


def test_prefetch_yields_same_as_sync():
    src = ArraySource({"x": np.arange(12, dtype=np.float32)})
    mk = lambda pf: DataLoader(  # noqa: E731
        src, global_batch_size=3, shuffle=True, seed=5, num_epochs=2,
        process_index=0, process_count=1, prefetch=pf)
    sync = [b["x"].tolist() for b in mk(0)]
    pre = [b["x"].tolist() for b in mk(3)]
    assert sync == pre and len(sync) == 8


def test_prefetch_propagates_errors():
    class Bad(ArraySource):
        def __getitem__(self, idx):
            if idx >= 2:
                raise RuntimeError("boom")
            return super().__getitem__(idx)

    src = Bad({"x": np.arange(4, dtype=np.float32)})
    dl = DataLoader(src, global_batch_size=2, shuffle=False, num_epochs=1,
                    process_index=0, process_count=1, prefetch=2)
    with pytest.raises(RuntimeError, match="boom"):
        list(dl)


def test_device_prefetch_wrapper():
    mesh = make_mesh(MeshSpec(data=-1))
    sh = batch_sharding(mesh)
    host = [{"x": np.full((8, 2), i, np.float32)} for i in range(3)]
    out = list(device_prefetch(iter(host), sh, size=2))
    assert len(out) == 3
    assert isinstance(out[1]["x"], jax.Array)
    np.testing.assert_array_equal(np.asarray(out[2]["x"]),
                                  np.full((8, 2), 2, np.float32))


@pytest.mark.slow  # heavyweight; tier-1 runs -m 'not slow'
def test_loader_trains_resnet_batch():
    """End-to-end: loader feeds the Trainer for 2 steps."""
    import jax.numpy as jnp
    import optax

    from tony_tpu.models import ResNet18
    from tony_tpu.parallel import data_parallel_mesh
    from tony_tpu.train import Trainer

    mesh = data_parallel_mesh()
    sh = batch_sharding(mesh)
    src = SyntheticImageSource(16, 8, 8, num_classes=4, seed=0)
    dl = DataLoader(src, global_batch_size=8, num_epochs=1, sharding=sh,
                    process_index=0, process_count=1)
    model = ResNet18(num_classes=4, num_filters=8, dtype=jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), jnp.ones((1, 8, 8, 3)),
                           train=False)

    def apply_fn(p, batch):
        logits = model.apply({"params": p,
                              "batch_stats": variables["batch_stats"]},
                             batch["image"], train=False)
        onehot = jax.nn.one_hot(batch["label"], 4)
        return -jnp.mean(jnp.sum(
            onehot * jax.nn.log_softmax(logits), axis=-1))

    trainer = Trainer(mesh=mesh, apply_fn=apply_fn,
                      optimizer=optax.sgd(0.1), donate=False)
    state = trainer.init_state(variables["params"])
    step_fn, placed = trainer.build_step(state)
    n = 0
    for batch in dl:
        placed, metrics = step_fn(placed, batch)
        assert jnp.isfinite(metrics["loss"])
        n += 1
    assert n == 2


def test_packed_token_source(tmp_path):
    """memmap windows with shifted labels; stride controls overlap."""
    import numpy as np
    from tony_tpu.data import PackedTokenSource

    tokens = np.arange(100, dtype=np.uint16)
    path = tmp_path / "corpus.bin"
    tokens.tofile(path)

    src = PackedTokenSource(str(path), seq_len=16)
    # disjoint windows: (100 - 17) // 16 + 1 = 6
    assert len(src) == 6
    ex = src[0]
    assert ex["tokens"].dtype == np.int32
    np.testing.assert_array_equal(ex["tokens"], np.arange(16))
    np.testing.assert_array_equal(ex["labels"], np.arange(1, 17))
    ex = src[2]
    np.testing.assert_array_equal(ex["tokens"], np.arange(32, 48))

    overlapping = PackedTokenSource(str(path), seq_len=16, stride=8)
    assert len(overlapping) == (100 - 17) // 8 + 1
    np.testing.assert_array_equal(overlapping[1]["tokens"],
                                  np.arange(8, 24))

    with pytest.raises(ValueError, match="tokens < seq_len"):
        PackedTokenSource(str(path), seq_len=200)


def test_packed_token_source_through_loader(tmp_path):
    """PackedTokenSource drives the sharded DataLoader end-to-end."""
    import numpy as np
    from tony_tpu.data import DataLoader, PackedTokenSource

    np.arange(1000, dtype=np.uint32).tofile(tmp_path / "c.bin")
    src = PackedTokenSource(str(tmp_path / "c.bin"), seq_len=32,
                            dtype=np.uint32)
    loader = DataLoader(src, global_batch_size=4, seed=0)
    batch = next(iter(loader))
    assert batch["tokens"].shape == (4, 32)
    assert batch["labels"].shape == (4, 32)
    np.testing.assert_array_equal(np.asarray(batch["labels"])[:, :-1],
                                  np.asarray(batch["tokens"])[:, 1:])


def test_packed_token_source_rejects_zero_stride(tmp_path):
    import numpy as np
    from tony_tpu.data import PackedTokenSource

    np.arange(100, dtype=np.uint16).tofile(tmp_path / "c.bin")
    with pytest.raises(ValueError, match="stride must be positive"):
        PackedTokenSource(str(tmp_path / "c.bin"), seq_len=16, stride=0)


def test_byte_tokenizer_roundtrip():
    from tony_tpu.data import ByteTokenizer

    tok = ByteTokenizer()
    s = "hello, TPU — héllo\n"
    ids = tok.encode(s)
    assert all(0 <= i < 256 for i in ids)
    assert tok.decode(ids) == s
    assert tok.decode(ids + [tok.eos_id]) == s  # eos stripped


def test_encode_corpus_to_bin_feeds_packed_source(tmp_path):
    from tony_tpu.data import (ByteTokenizer, PackedTokenSource,
                               encode_corpus_to_bin)

    tok = ByteTokenizer()
    docs = ["first document", "second, longer document body",
            "third " * 20]
    out = str(tmp_path / "corpus.bin")
    total = encode_corpus_to_bin(docs, out, tok.encode, eos_id=tok.eos_id)
    expected = sum(len(tok.encode(d)) + 1 for d in docs)
    assert total == expected
    src = PackedTokenSource(out, seq_len=16)
    ex = src[0]
    assert ex["tokens"].shape == (16,) and ex["labels"].shape == (16,)
    # windows are the shifted stream: labels[i] == tokens[i+1] within window
    np.testing.assert_array_equal(ex["tokens"][1:], ex["labels"][:-1])
    # eos separators present in the stream
    flat = np.memmap(out, dtype=np.uint16, mode="r")
    assert (np.asarray(flat) == tok.eos_id).sum() == len(docs)


def test_encode_corpus_rejects_overflowing_dtype(tmp_path):
    from tony_tpu.data import encode_corpus_to_bin

    with pytest.raises(ValueError, match="out of range"):
        encode_corpus_to_bin(["x"], str(tmp_path / "o.bin"),
                             lambda s: [70_000], dtype=np.uint16)


def test_encode_files_to_bin(tmp_path):
    from tony_tpu.data import ByteTokenizer, encode_files_to_bin

    tok = ByteTokenizer()
    p1, p2 = tmp_path / "a.txt", tmp_path / "b.txt"
    p1.write_text("aaa")
    p2.write_text("bbbb")
    out = str(tmp_path / "c.bin")
    total = encode_files_to_bin([str(p1), str(p2)], out, tok.encode,
                                eos_id=tok.eos_id)
    assert total == 3 + 1 + 4 + 1
    flat = np.fromfile(out, dtype=np.uint16)
    assert flat.tolist() == tok.encode("aaa") + [256] + tok.encode("bbbb") + [256]


def test_encode_files_streams_in_blocks(tmp_path):
    """Block splitting at line boundaries must not change the token stream."""
    from tony_tpu.data import ByteTokenizer, encode_files_to_bin

    tok = ByteTokenizer()
    text = "".join(f"line number {i}\n" for i in range(200))
    p = tmp_path / "t.txt"
    p.write_text(text)
    out1, out2 = str(tmp_path / "big.bin"), str(tmp_path / "small.bin")
    encode_files_to_bin([str(p)], out1, tok.encode, eos_id=tok.eos_id)
    encode_files_to_bin([str(p)], out2, tok.encode, eos_id=tok.eos_id,
                        block_bytes=64)  # forces many blocks
    np.testing.assert_array_equal(np.fromfile(out1, np.uint16),
                                  np.fromfile(out2, np.uint16))


def test_mixture_source_ratios_and_determinism():
    from tony_tpu.data import ArraySource, MixtureSource

    a = ArraySource({"x": np.zeros((10, 2), np.float32)})
    b = ArraySource({"x": np.ones((3, 2), np.float32)})
    mix = MixtureSource([(a, 0.75), (b, 0.25)], num_examples=4000, seed=7)
    counts = mix.component_counts()
    assert abs(counts[0] / 4000 - 0.75) < 0.03
    # deterministic across constructions (multi-host contract)
    mix2 = MixtureSource([(a, 0.75), (b, 0.25)], num_examples=4000, seed=7)
    for i in (0, 17, 3999):
        np.testing.assert_array_equal(mix[i]["x"], mix2[i]["x"])
    # small component cycles rather than truncating
    ones = sum(int(mix[i]["x"][0]) for i in range(200))
    assert ones > 3  # component b (len 3) sampled far more than its size


def test_mixture_source_cycles_small_component_through_all_examples():
    from tony_tpu.data import ArraySource, MixtureSource

    vals = np.arange(3, dtype=np.float32).reshape(3, 1)
    b = ArraySource({"x": vals})
    mix = MixtureSource([(b, 1.0)], num_examples=9, seed=0)
    got = [float(mix[i]["x"][0]) for i in range(9)]
    assert got == [0, 1, 2, 0, 1, 2, 0, 1, 2]


def test_mixture_source_validates():
    from tony_tpu.data import ArraySource, MixtureSource

    a = ArraySource({"x": np.zeros((2, 1), np.float32)})
    with pytest.raises(ValueError, match="positive"):
        MixtureSource([(a, 0.0)], num_examples=10)
    with pytest.raises(ValueError, match="at least one"):
        MixtureSource([], num_examples=10)


def test_mixture_source_through_loader():
    from tony_tpu.data import ArraySource, DataLoader, MixtureSource

    a = ArraySource({"x": np.zeros((8, 2), np.float32)})
    b = ArraySource({"x": np.ones((8, 2), np.float32)})
    mix = MixtureSource([(a, 0.5), (b, 0.5)], num_examples=64, seed=1)
    loader = DataLoader(mix, global_batch_size=16, seed=2, num_epochs=1,
                        process_index=0, process_count=1)
    batches = list(loader)
    assert len(batches) == 4
    vals = np.concatenate([np.asarray(bt["x"])[:, 0] for bt in batches])
    assert 10 < vals.sum() < 54  # both components present


def test_packed_source_emits_segments(tmp_path):
    from tony_tpu.data import (ByteTokenizer, PackedTokenSource,
                               encode_corpus_to_bin)

    tok = ByteTokenizer()
    docs = ["ab", "cde", "f"]
    out = str(tmp_path / "c.bin")
    encode_corpus_to_bin(docs, out, tok.encode, eos_id=tok.eos_id)
    # stream: a b EOS c d e EOS f EOS  (9 tokens)
    src = PackedTokenSource(out, seq_len=8, segment_eos_id=tok.eos_id)
    ex = src[0]
    assert ex["segments"].tolist() == [0, 0, 0, 1, 1, 1, 1, 2]
    # without the flag no segments key appears
    src2 = PackedTokenSource(out, seq_len=8)
    assert "segments" not in src2[0]


def test_instruction_source_masks_prompt_and_padding(tmp_path):
    """SFT source: loss mask covers ONLY completion (+eos) positions; the
    wiring into cross_entropy_loss trains toward completions alone."""
    import json

    from tony_tpu.data import InstructionSource, JsonlSource
    from tony_tpu.data.tokenize import ByteTokenizer

    path = tmp_path / "sft.jsonl"
    rows = [{"prompt": "ab", "completion": "cd"},
            {"prompt": "xyz", "completion": "q"}]
    path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    tok = ByteTokenizer()
    src = InstructionSource(JsonlSource(str(path)), tok, seq_len=8,
                            eos_id=0, pad_id=0)
    assert len(src) == 2

    ex = src[0]
    assert ex["tokens"].shape == (8,) and ex["loss_mask"].shape == (8,)
    p, c = tok.encode("ab"), tok.encode("cd")
    assert ex["tokens"][:2].tolist() == p
    assert ex["tokens"][2:5].tolist() == c + [0]  # completion + eos
    # mask: prompt 0, completion+eos 1, padding 0
    assert ex["loss_mask"].tolist() == [0, 0, 1, 1, 1, 0, 0, 0]

    # shifted-mask loss contract: only completion targets contribute
    import jax
    import jax.numpy as jnp

    from tony_tpu.train import cross_entropy_loss

    logits = jnp.asarray(
        np.random.default_rng(0).standard_normal((1, 7, 260)), jnp.float32)
    tokens = jnp.asarray(ex["tokens"][None])
    mask = jnp.asarray(ex["loss_mask"][None])
    got = float(cross_entropy_loss(logits, tokens[:, 1:], mask[:, 1:]))
    logp = jax.nn.log_softmax(logits, axis=-1)
    picked = np.take_along_axis(np.asarray(logp),
                                np.asarray(tokens[:, 1:, None]), 2)[0, :, 0]
    m = np.asarray(mask[0, 1:])
    np.testing.assert_allclose(got, -(picked * m).sum() / m.sum(), rtol=1e-5)


def test_instruction_source_overlong_prompt_zero_mask():
    from tony_tpu.data import InstructionSource
    from tony_tpu.data.tokenize import ByteTokenizer

    pairs = [{"prompt": "abcdefghij", "completion": "z"}]
    src = InstructionSource(pairs, ByteTokenizer(), seq_len=6)
    ex = src[0]
    assert ex["loss_mask"].sum() == 0  # nothing to train on, no crash
    assert ex["tokens"].shape == (6,)
