"""Ray head/worker example.

Reference analog: tony-examples/ray-on-tony — ray runs as plain roles with
custom commands, and `discovery.py` digs the head address out of the
CLUSTER_SPEC env. tony-tpu's ray runtime promotes discovery to first-class
env: every task gets RAY_HEAD_ADDRESS / RAY_HEAD_IP / RAY_HEAD_PORT.

With ray installed the head role runs `ray start --head` and workers run
`ray start --address=$RAY_HEAD_ADDRESS`; this script validates the
discovery contract (and submits a trivial task when ray is importable).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))  # repo root, for standalone runs

import tony_tpu.distributed as dist


def main() -> int:
    role, index = dist.task_identity()
    if not role:
        print("standalone run (not launched by tony-tpu); nothing to discover")
        return 0
    head_addr = os.environ.get("RAY_HEAD_ADDRESS", "")
    head_ip = os.environ.get("RAY_HEAD_IP", "")
    head_port = os.environ.get("RAY_HEAD_PORT", "")
    if not head_addr or not head_ip or not head_port.isdigit():
        print(f"{role}:{index} missing ray discovery env", file=sys.stderr)
        return 1
    print(f"{role}:{index} discovered head at {head_addr}")

    try:
        import ray
    except ImportError:
        return 0  # env contract validated; no ray in this image

    if role == "head":
        ray.init()
        print(ray.cluster_resources())
    else:
        ray.init(address=head_addr)
    ray.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
