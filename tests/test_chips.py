"""Per-task chip assignment + resource enforcement on shared hosts
(ref: tony.<role>.gpus as an enforced container resource,
HadoopCompatibleAdapter.java:71, util/Utils.java:393-419)."""

import json
import os

import pytest

from tony_tpu import constants as C
from tony_tpu.config import TonyConf
from tony_tpu.coordinator.chips import ChipAllocator
from tony_tpu.coordinator.launcher import parse_memory_bytes


def test_chip_allocator_disjoint_sets():
    alloc = ChipAllocator(4)
    a = alloc.allocate("worker:0", 2)
    b = alloc.allocate("worker:1", 2)
    assert a == [0, 1] and b == [2, 3]
    with pytest.raises(RuntimeError, match="only 0 of 4 are free"):
        alloc.allocate("worker:2", 1)
    alloc.release("worker:0")
    assert alloc.allocate("worker:2", 2) == [0, 1]
    # same-task re-allocation returns the existing hold (idempotent)
    assert alloc.allocate("worker:2", 2) == [0, 1]
    alloc.reset()
    assert alloc.allocate("x", 4) == [0, 1, 2, 3]


def test_parse_memory_bytes():
    assert parse_memory_bytes("2g") == 2 * 1024 ** 3
    assert parse_memory_bytes("512m") == 512 * 1024 ** 2
    assert parse_memory_bytes("1.5g") == int(1.5 * 1024 ** 3)
    assert parse_memory_bytes("1024") == 1024
    assert parse_memory_bytes("") == 0
    assert parse_memory_bytes("weird") == 0


def _fake_tpu_info(tmp, n: int) -> str:
    path = os.path.join(tmp, "tpu-info")
    chips = [{"device_id": i} for i in range(n)]
    with open(path, "w") as f:
        f.write("#!/bin/sh\necho '%s'\n" % json.dumps(
            {"accelerator_type": "test", "chips": chips}))
    os.chmod(path, 0o755)
    return path


def make_coord(tmp, conf):
    from tony_tpu.coordinator.coordinator import Coordinator

    conf.set("tony.staging-dir", tmp)
    conf.set("tony.history.location", os.path.join(tmp, "hist"))
    return Coordinator(conf, "application_chips", os.path.join(tmp, "job"))


def test_task_env_assigns_disjoint_chip_subsets(tmp_path):
    """Two 2-chip tasks on one (fake) 4-chip host must see different
    device pairs; completion releases the hold."""
    from tony_tpu.session import RoleRequest, Task

    tmp = str(tmp_path)
    conf = TonyConf()
    conf.set("tony.worker.instances", 2)
    conf.set("tony.worker.chips", 2)
    conf.set("tony.tpu.info-exec-path", _fake_tpu_info(tmp, 4))
    coord = make_coord(tmp, conf)
    try:
        req = RoleRequest.from_conf(conf, "worker")
        t0 = Task(role="worker", index=0)
        t1 = Task(role="worker", index=1)
        env0 = coord._task_env(req, t0)
        env1 = coord._task_env(req, t1)
        assert env0[C.TPU_VISIBLE_DEVICES] == "0,1"
        assert env1[C.TPU_VISIBLE_DEVICES] == "2,3"
        coord.chips.release(t0.id)
        t2 = Task(role="worker", index=2)
        assert coord._task_env(req, t2)[C.TPU_VISIBLE_DEVICES] == "0,1"
    finally:
        coord.rpc.stop()
        coord.metrics_rpc.stop()


def test_task_env_chips_advisory_without_discovery(tmp_path):
    """No discovered chips + no explicit chips-per-host: chip requests
    stay advisory (same stance as preflight_chips) — no
    TPU_VISIBLE_DEVICES, no mid-launch RuntimeError."""
    from tony_tpu.session import RoleRequest, Task

    tmp = str(tmp_path)
    conf = TonyConf()
    conf.set("tony.worker.instances", 2)
    conf.set("tony.worker.chips", 8)
    # discovery sees nothing: point the info exec at a chipless fake
    conf.set("tony.tpu.info-exec-path", _fake_tpu_info(tmp, 0))
    coord = make_coord(tmp, conf)
    try:
        env = coord._task_env(RoleRequest.from_conf(conf, "worker"),
                              Task(role="worker", index=0))
        assert C.TPU_VISIBLE_DEVICES not in env
    finally:
        coord.rpc.stop()
        coord.metrics_rpc.stop()


def test_task_env_memory_only_when_explicit(tmp_path):
    """The schema default (2g) must NOT become an rlimit; an explicit
    tony.<role>.memory must."""
    from tony_tpu.session import RoleRequest, Task

    tmp = str(tmp_path)
    conf = TonyConf()
    conf.set("tony.worker.instances", 1)
    conf.set("tony.ps.instances", 1)
    conf.set("tony.ps.memory", "512m")
    conf.set("tony.ps.vcores", 2)
    coord = make_coord(tmp, conf)
    try:
        wenv = coord._task_env(RoleRequest.from_conf(conf, "worker"),
                               Task(role="worker", index=0))
        assert C.TASK_MEMORY not in wenv and C.TASK_VCORES not in wenv
        penv = coord._task_env(RoleRequest.from_conf(conf, "ps"),
                               Task(role="ps", index=0))
        assert penv[C.TASK_MEMORY] == "512m"
        assert penv[C.TASK_VCORES] == "2"
    finally:
        coord.rpc.stop()
        coord.metrics_rpc.stop()


def test_local_launcher_applies_rlimit(tmp_path, monkeypatch):
    """The agent process runs under RLIMIT_AS == the exported memory."""
    import time

    from tony_tpu.coordinator import launcher as L
    from tony_tpu.session import Task

    probe = os.path.join(str(tmp_path), "probe.py")
    out_file = os.path.join(str(tmp_path), "rlimit.txt")
    with open(probe, "w") as f:
        f.write("import resource, os\n"
                f"open({out_file!r}, 'w').write("
                "str(resource.getrlimit(resource.RLIMIT_AS)[0]))\n")
    import sys

    monkeypatch.setattr(L, "AGENT_ARGV", [sys.executable, probe])
    exits = []
    lch = L.LocalProcessLauncher(on_exit=lambda t, c: exits.append((t, c)))
    task = Task(role="worker", index=0)
    lch.launch(task, {C.TASK_MEMORY: "256m"},
               os.path.join(str(tmp_path), "w.log"))
    deadline = time.monotonic() + 15
    while not os.path.exists(out_file) and time.monotonic() < deadline:
        time.sleep(0.05)
    assert os.path.exists(out_file)
    time.sleep(0.1)
    assert int(open(out_file).read()) == 256 * 1024 ** 2


def test_docker_command_carries_memory_and_cpus():
    from tony_tpu.coordinator.launcher import build_docker_command
    from tony_tpu.session import Task

    argv = build_docker_command(
        Task(role="worker", index=0),
        {C.TASK_MEMORY: "4g", C.TASK_VCORES: "8"}, image="img")
    assert argv[argv.index("--memory") + 1] == "4g"
    assert argv[argv.index("--cpus") + 1] == "8"
