"""``tony-tpu score`` — perplexity/log-likelihood of a local HF checkpoint.

The eval face of the serving stack (sibling of ``tony-tpu generate``):
import a GPT-2/Llama/Mistral/Qwen2 directory, run the full forward, and
report per-token negative log-likelihood + perplexity over the given
text or token ids. Offline.

Inputs are PADDED TO BUCKETS (powers of two, capped at the model's max
length): the jitted scorer is shape-keyed, so a file of varied lengths
compiles O(#buckets) programs instead of one per distinct length —
padded positions are masked out of the sum, so scores are exact
(causal attention: a pad token can only influence its own masked-out
positions). VERDICT r2 #10.

    python -m tony_tpu.cli.score --model ./my-llama --text-file eval.txt
    python -m tony_tpu.cli.score --model ./ckpt --token-ids 1,2,3,4
"""

from __future__ import annotations

import argparse
import math
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tony-tpu score",
        description="Perplexity of a local HF checkpoint over given text",
    )
    p.add_argument("--model", required=True,
                   help="local checkpoint directory (HF format)")
    p.add_argument("--text", action="append", default=[],
                   help="text to score (repeatable; needs a tokenizer in "
                        "the model dir)")
    p.add_argument("--text-file", action="append", default=[],
                   help="file whose contents to score (repeatable)")
    p.add_argument("--token-ids", action="append", default=[],
                   help="raw ids, comma-separated (repeatable)")
    p.add_argument("--max-len", type=int, default=0,
                   help="truncate inputs to this many tokens "
                        "(default: the model's max_seq_len)")
    p.add_argument("--kv-int8", action="store_true",
                   help="score THROUGH an int8 KV cache (decode/prefill "
                        "path): measures the cache quantization's exact "
                        "nll/token cost for serving")
    p.add_argument("--int8", action="store_true",
                   help="score with int8 weight-only quantization (the "
                        "serving config; measures the quality cost of "
                        "--int8 generation)")
    p.add_argument("--dtype", choices=("fp32", "bf16"), default="fp32",
                   help="parameter storage dtype: score with bf16 to "
                        "measure the quality cost of bf16 serving "
                        "(generate --dtype bf16); default fp32")
    return p


_MIN_BUCKET = 32


def bucket_len(n: int, limit: int) -> int:
    """Smallest power-of-two >= n (floor 32), capped at ``limit``.
    One shared bucketing algorithm (serve's prefill uses the same
    helper with a smaller floor)."""
    from tony_tpu.serve import bucket_len as _bucket

    return _bucket(n, limit, minimum=_MIN_BUCKET)


def make_score_fn(model, params, through_cache: bool = False):
    """One jitted scorer reused for every input; jit's shape-keyed cache
    means exactly one compile per bucket length. Returns
    ``fn(ids) -> (total nll, token count)`` with the padding masked out.

    ``through_cache`` scores via the decode/prefill path (the cache is
    written, then logits read back through it) — with
    ``cfg.kv_cache_quant`` this measures the int8 KV cache's exact
    nll/token cost, the serving-quality analog of ``--int8``'s weight
    cost."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    @jax.jit
    def nll(tokens, tgt_mask):
        if through_cache:
            cache = model.init(jax.random.PRNGKey(0), tokens,
                               decode=True)["cache"]
            logits, _ = model.apply(
                {"params": params["params"] if "params" in params
                 else params, "cache": cache},
                tokens, decode=True, mutable=["cache"])
        else:
            logits = model.apply(params, tokens)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        picked = jnp.take_along_axis(
            logp[:, :-1], tokens[:, 1:, None], axis=-1)[0, :, 0]
        return -(picked * tgt_mask).sum()

    limit = model.cfg.max_seq_len

    def score(ids) -> tuple[float, int]:
        ids = ids[:limit]  # a caller --max-len above the model cap must
        # not overflow the capped bucket
        n = len(ids)
        padded_len = bucket_len(n, limit)
        tokens = np.zeros((1, padded_len), np.int32)
        tokens[0, :n] = ids
        # target j (predicted from position j) is real iff j+1 < n
        tgt_mask = (np.arange(1, padded_len) < n).astype(np.float32)
        return float(nll(tokens, tgt_mask)), n - 1

    score.jitted = nll  # tests count compiles via _cache_size()
    return score


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from tony_tpu.cli.generate import load_model

    inputs: list[list[int]] = []
    texts = list(args.text)
    for path in args.text_file:
        with open(path, encoding="utf-8") as f:
            texts.append(f.read())
    model, params, config = load_model(args.model)
    if args.dtype == "bf16" and args.int8:
        print("note: --int8 supplies its own storage format; "
              "--dtype bf16 is ignored", file=sys.stderr)
    if args.dtype == "bf16" and not args.int8:
        import jax
        import jax.numpy as jnp

        params = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(x.dtype, jnp.floating) else x,
            params)
    if args.int8:
        from tony_tpu.models.quantize import quantize_cli

        model, params = quantize_cli(model, params)
    if args.kv_int8:
        import dataclasses

        from tony_tpu.models import Transformer

        model = Transformer(dataclasses.replace(model.cfg,
                                                kv_cache_quant=True))
    if texts:
        import transformers

        tokenizer = transformers.AutoTokenizer.from_pretrained(args.model)
        inputs += [tokenizer.encode(t) for t in texts]
    inputs += [[int(i) for i in ids.split(",")] for ids in args.token_ids]
    if not inputs:
        print("need --text, --text-file, or --token-ids", file=sys.stderr)
        return 2

    limit = min(args.max_len or model.cfg.max_seq_len,
                model.cfg.max_seq_len)
    score = make_score_fn(model, params, through_cache=args.kv_int8)
    total_nll = 0.0
    total_tokens = 0
    for ids in inputs:
        ids = ids[:limit]
        if len(ids) < 2:
            print("skipping input with < 2 tokens", file=sys.stderr)
            continue
        nll, n = score(ids)
        total_nll += nll
        total_tokens += n
        print(f"tokens={n} nll/token={nll / n:.4f} "
              f"ppl={math.exp(nll / n):.2f}")
    if total_tokens:
        avg = total_nll / total_tokens
        print(f"TOTAL tokens={total_tokens} nll/token={avg:.4f} "
              f"ppl={math.exp(avg):.2f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
