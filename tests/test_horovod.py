"""Horovod-compat runtime tests.

Reference analogs: runtime/TestHorovodRuntime.java (worker list, cluster
spec), horovod/TestHorovodDriver.java (driver wrapper in fake mode — no
horovod installed), and the TestTonyE2E horovod cases (:531-567: driver
crash, pass, debug mode).
"""

import json
import os
import sys

import pytest

from tony_tpu import constants as C
from tony_tpu.config import ConfError, TonyConf
from tony_tpu.runtime.base import TaskContext
from tony_tpu.runtime.horovod_driver import (
    FAKE_SERVER_PORT,
    build_fake_slot_plan,
    build_slot_plan,
    parse_worker_list,
)
from tony_tpu.runtime.horovod_runtime import (
    HorovodAMAdapter,
    HorovodDriver,
    HorovodTaskAdapter,
    build_worker_list,
)
from tony_tpu.session import Session

SCRIPTS = os.path.join(os.path.dirname(__file__), "scripts")


# -- slot plan math ----------------------------------------------------------


def test_parse_worker_list():
    assert parse_worker_list("h1:2, h2:1") == [("h1", 2), ("h2", 1)]
    with pytest.raises(ValueError):
        parse_worker_list("")


def test_slot_plan_ranks_and_sizes():
    plan = build_slot_plan([("h1", 2), ("h2", 1)])
    assert [s["rank"] for s in plan] == [0, 1, 2]
    assert all(s["size"] == 3 for s in plan)
    # h1 slots: local 0,1; h2: local 0
    assert [s["local_rank"] for s in plan] == [0, 1, 0]
    assert plan[0]["local_size"] == 2 and plan[2]["local_size"] == 1
    # cross rank/size: local_rank 0 exists on both hosts, local_rank 1 only h1
    assert plan[0]["cross_rank"] == 0 and plan[0]["cross_size"] == 2
    assert plan[2]["cross_rank"] == 1 and plan[2]["cross_size"] == 2
    assert plan[1]["cross_rank"] == 0 and plan[1]["cross_size"] == 1


def test_fake_plan_is_two_local_slots():
    plan = build_fake_slot_plan()
    assert len(plan) == 2
    assert all(s["hostname"] == "localhost" for s in plan)


def test_build_worker_list_groups_hosts():
    spec = {"worker": ["h1:100", "h1:101", "h2:102"]}
    assert build_worker_list(spec) == "h1:2,h2:1"
    with pytest.raises(ValueError):
        build_worker_list({"worker": []})


# -- driver wrapper (fake + fail modes; ref: TestHorovodDriver) --------------


def test_driver_fake_mode(tmp_path):
    driver = HorovodDriver.create("localhost:2", str(tmp_path), fake=True)
    try:
        assert driver.port == FAKE_SERVER_PORT
        assert len(driver.slots) == 2
        info = json.loads(driver.callback_info("myhost"))
        assert info["host"] == "myhost"
        assert info["port"] == FAKE_SERVER_PORT
    finally:
        driver.kill()


def test_driver_fast_fail(tmp_path):
    with pytest.raises(RuntimeError):
        HorovodDriver.create("localhost:2", str(tmp_path), fail=True)


def test_driver_real_server(tmp_path):
    """Real mode starts an HTTP KV rendezvous server on a live port."""
    import urllib.request

    driver = HorovodDriver.create("localhost:2", str(tmp_path))
    try:
        assert driver.port > 0
        url = f"http://127.0.0.1:{driver.port}/rdzv/k1"
        req = urllib.request.Request(url, data=b"v1", method="PUT")
        assert urllib.request.urlopen(req).status == 200
        assert urllib.request.urlopen(url).read() == b"v1"
    finally:
        driver.kill()


# -- AM adapter --------------------------------------------------------------


def _gang_conf(workers: int = 2) -> TonyConf:
    conf = TonyConf()
    conf.set("tony.application.framework", "horovod")
    conf.set("tony.worker.instances", workers)
    conf.set("tony.worker.command", "true")
    return conf


def test_am_injects_untracked_driver_role():
    conf = _gang_conf()
    am = HorovodAMAdapter()
    am.validate_and_update_config(conf)
    assert C.DRIVER_JOB_NAME in conf.roles()
    assert conf.role_get(C.DRIVER_JOB_NAME, "instances") == 1
    assert C.DRIVER_JOB_NAME in conf.get_list(
        "tony.application.untracked.jobtypes")


def test_am_rejects_user_driver_role():
    conf = _gang_conf()
    conf.set("tony.driver.instances", 1)
    with pytest.raises(ConfError):
        HorovodAMAdapter().validate_and_update_config(conf)


def test_am_gating_driver_then_workers():
    conf = _gang_conf(workers=2)
    am = HorovodAMAdapter()
    am.validate_and_update_config(conf)
    session = Session(conf)
    for role in session.requests:
        for i in range(session.requests[role].instances):
            session.init_task(role, i)
    session.add_expected(3)
    am.set_session(session)

    # nothing registered: neither driver nor workers may start
    assert not am.can_start_task(C.GANG, "driver:0")
    assert not am.can_start_task(C.GANG, "worker:0")
    session.register("worker:0", "h1:100")
    session.register("worker:1", "h1:101")
    # all non-driver registered -> driver may start; workers still gated
    assert am.can_start_task(C.GANG, "driver:0")
    assert not am.can_start_task(C.GANG, "worker:0")
    session.register("driver:0", "h1:99")
    assert not am.can_start_task(C.GANG, "worker:0")  # await callback
    am.receive_task_callback_info("driver:0", json.dumps(
        {"host": "h1", "port": 4242, "slots": build_slot_plan([("h1", 2)])}))
    assert am.can_start_task(C.GANG, "worker:0")
    spec = json.loads(am.construct_cluster_spec("worker:0"))
    assert spec["__aux__"]["rendezvous_port"] == 4242
    assert len(spec["__aux__"]["slots"]) == 2
    # the driver's own spec carries no aux payload
    assert "__aux__" not in json.loads(am.construct_cluster_spec("driver:0"))


# -- worker env --------------------------------------------------------------


def _worker_ctx(index: int, aux: dict) -> TaskContext:
    return TaskContext(
        conf=TonyConf(),
        role="worker",
        index=index,
        task_num=2,
        is_chief=index == 0,
        cluster_spec={"worker": ["h1:100", "h1:101"], "driver": ["h1:99"]},
        command="true",
        aux=aux,
    )


def test_worker_env_slot_assignment():
    aux = {"rendezvous_host": "h1", "rendezvous_port": 4242,
           "slots": build_slot_plan([("h1", 2)])}
    adapter = HorovodTaskAdapter()
    env0 = adapter.build_task_env(_worker_ctx(0, aux))
    env1 = adapter.build_task_env(_worker_ctx(1, aux))
    assert env0[C.HOROVOD_CONTROLLER] == "gloo"
    assert env0[C.HOROVOD_GLOO_RENDEZVOUS_ADDR] == "h1"
    assert env0[C.HOROVOD_GLOO_RENDEZVOUS_PORT] == "4242"
    assert env0[C.HOROVOD_RANK] == "0" and env1[C.HOROVOD_RANK] == "1"
    assert env0[C.HOROVOD_LOCAL_RANK] == "0" and env1[C.HOROVOD_LOCAL_RANK] == "1"
    assert env0[C.HOROVOD_SIZE] == "2"


def test_driver_role_env_has_no_horovod_vars():
    adapter = HorovodTaskAdapter()
    ctx = TaskContext(
        conf=TonyConf(), role="driver", index=0, task_num=1, is_chief=False,
        cluster_spec={"worker": ["h1:100"], "driver": ["h1:99"]},
        command=":")
    env = adapter.build_task_env(ctx)
    assert C.HOROVOD_RANK not in env


# -- e2e over the mini cluster (ref: TestTonyE2E :531-567) -------------------


from tony_tpu.mini import MiniTonyCluster, script_conf  # noqa: E402


@pytest.fixture
def cluster():
    with MiniTonyCluster() as c:
        yield c


def _horovod_conf(cluster, script_name: str, **extra) -> TonyConf:
    conf = script_conf(
        cluster, os.path.join(SCRIPTS, script_name), {"worker": 2},
        framework="horovod")
    conf.set("tony.horovod.test-mode", True)
    for k, v in extra.items():
        conf.set(k, v)
    return conf


def test_horovod_e2e_pass(cluster):
    """Ref: testHorovodTrainingShouldPass — fake rendezvous, env checked by
    the payload."""
    conf = _horovod_conf(cluster, "check_horovod_env.py")
    client = cluster.submit(conf)
    assert client.final_status["status"] == "SUCCEEDED", client.final_status


def test_horovod_driver_crash_fails_job(cluster):
    """Ref: testHorovodModeShouldFailOnDriverFailure — fast-fail driver."""
    conf = _horovod_conf(cluster, "exit_0.py")
    conf.set("tony.horovod.test-fast-fail", True)
    client = cluster.submit(conf)
    assert client.final_status["status"] == "FAILED"


def test_horovod_debug_driver(cluster):
    """Ref: testHorovodDebugModeShouldPass — user-supplied driver command."""
    conf = _horovod_conf(cluster, "check_horovod_env.py")
    conf.set("tony.horovod.test-mode", False)
    conf.set("tony.horovod.driver.debug-command",
             f"{sys.executable} {os.path.join(SCRIPTS, 'horovod_debug_driver.py')}")
    client = cluster.submit(conf)
    assert client.final_status["status"] == "SUCCEEDED", client.final_status


def test_rendezvous_server_not_orphaned_after_job(cluster):
    """The rendezvous bootstrap must die with the job: as a session leader
    it used to survive the launcher's group SIGKILL of the driver agent,
    leaking one server per completed horovod job (observed: 39 orphans on
    one CI host)."""
    import subprocess

    client = cluster.submit(_horovod_conf(cluster, "exit_0.py"))
    assert client.final_status["status"] == "SUCCEEDED", client.final_status
    # no process may still reference this job's staging dir (the driver's
    # -d argument); pgrep exits non-zero when nothing matches
    res = subprocess.run(["pgrep", "-f", client.job_dir],
                         capture_output=True, text=True)
    assert res.returncode != 0, \
        f"orphaned processes for {client.job_dir}: {res.stdout}"


def test_elastic_driver_replans_on_discovery_change(tmp_path):
    """--elastic: membership change via the discovery command republishes
    the slot plan under a bumped generation (the reference's
    elastic_driver_fn is a stub — reference horovod_driver.py:28-29)."""
    import glob
    import subprocess
    import sys
    import time

    # discovery flips from 2 hosts to 3 after the flag file appears
    flag = tmp_path / "grow"
    disc = tmp_path / "discover.py"
    disc.write_text(
        "import os, sys\n"
        "print('h1:2')\nprint('h2:2')\n"
        f"if os.path.exists({str(flag)!r}):\n"
        "    print('h3:2')\n")
    workdir = tmp_path / "wd"
    proc = subprocess.Popen(
        [sys.executable, "-m", "tony_tpu.runtime.horovod_driver",
         "-w", "h1:2,h2:2", "-d", str(workdir), "--elastic",
         "--discover", f"{sys.executable} {disc}",
         "--discover-interval", "0.2"])
    try:
        def read_port_file(deadline=20.0):
            end = time.time() + deadline
            while time.time() < end:
                files = glob.glob(str(workdir / "*HOROVOD_RENDEZVOUS*"))
                if files:
                    try:
                        with open(files[0]) as f:
                            return json.load(f)
                    except (ValueError, OSError):
                        pass
                time.sleep(0.1)
            raise AssertionError("port file never appeared")

        body = read_port_file()
        assert body["generation"] == 0
        assert len(body["slots"]) == 4
        flag.write_text("x")
        end = time.time() + 20
        while time.time() < end:
            body = read_port_file()
            if body.get("generation", 0) >= 1:
                break
            time.sleep(0.2)
        assert body["generation"] >= 1
        assert len(body["slots"]) == 6  # h3:2 joined
        ranks = sorted(s["rank"] for s in body["slots"])
        assert ranks == list(range(6))
    finally:
        proc.kill()
        proc.wait()


def test_elastic_discovery_failure_keeps_membership(tmp_path):
    """A failing/garbled discovery probe must NOT dissolve the gang."""
    from tony_tpu.runtime.horovod_driver import run_discovery

    assert run_discovery("false") is None
    assert run_discovery("echo not_a_number:xx") is None
    assert run_discovery("echo ''") is None
    assert run_discovery("echo h1:2") == [("h1", 2)]
    assert run_discovery("echo h1") == [("h1", 1)]
