"""Dependency-free Prometheus text exposition (format 0.0.4).

The gateway already collects everything an autoscaler or scrape agent
needs — counters, gauges, latency distributions — but spoke only JSON
(``/stats``). This module renders the standard text format without any
client library: ``MetricFamily`` (one ``# HELP``/``# TYPE`` header +
samples), ``Histogram`` (fixed-bucket cumulative with ``_bucket``/
``_sum``/``_count`` rendering), and the label-escaping rules from the
exposition spec (backslash, double-quote, newline escaped in label
values; metric/label names restricted to ``[a-zA-Z_][a-zA-Z0-9_]*``).

``Histogram`` is also the gateway's internal latency accumulator: the
rolling ``/stats`` window keeps exact recent percentiles, the
histogram keeps LIFETIME distributions in fixed buckets — the form a
scraper can rate() and aggregate across replicas, which a windowed
percentile cannot.
"""

from __future__ import annotations

import math
import threading

# latency buckets in SECONDS (the prometheus base-unit convention),
# log-spaced from 1 ms to 60 s: wide enough for queue waits under
# load shedding, fine enough to resolve a 10 ms TPOT regression
DEFAULT_TIME_BUCKETS_S = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def escape_label_value(value) -> str:
    """Exposition-spec label escaping: backslash first, then quote and
    newline."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt(value) -> str:
    """Sample value formatting: integers render bare (no trailing .0),
    floats via repr-ish shortest form, specials per the spec."""
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, int):
        return str(value)
    v = float(value)
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)


def _labels(labels: dict | None) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{escape_label_value(v)}"'
                     for k, v in labels.items())
    return "{" + inner + "}"


class MetricFamily:
    """One metric name: HELP/TYPE header + samples. ``mtype`` is
    "counter" | "gauge" | "histogram" (untyped renders as gauge)."""

    def __init__(self, name: str, mtype: str, help_text: str):
        self.name = name
        self.mtype = mtype
        self.help = help_text
        self.samples: list[tuple[str, dict | None, float]] = []

    def add(self, value, labels: dict | None = None,
            suffix: str = "") -> "MetricFamily":
        self.samples.append((self.name + suffix, labels, value))
        return self

    def render(self) -> str:
        lines = [f"# HELP {self.name} {self.help}",
                 f"# TYPE {self.name} {self.mtype}"]
        for name, labels, value in self.samples:
            lines.append(f"{name}{_labels(labels)} {_fmt(value)}")
        return "\n".join(lines)


class Histogram:
    """Thread-safe fixed-bucket histogram. ``observe()`` is two adds
    under a lock — cheap enough for the request-done path. Buckets are
    stored non-cumulative and rendered cumulative (the exposition
    format), always ending in ``+Inf``."""

    def __init__(self, buckets: tuple = DEFAULT_TIME_BUCKETS_S):
        self.buckets = tuple(sorted(buckets))
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.buckets) + 1)  # +1: the +Inf tail
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        i = 0
        for i, b in enumerate(self.buckets):  # noqa: B007 — linear scan:
            # len(buckets) ~ 15, a bisect would not pay for itself
            if value <= b:
                break
        else:
            i = len(self.buckets)
        with self._lock:
            self._counts[i] += 1
            self.sum += value
            self.count += 1

    def family(self, name: str, help_text: str,
               labels: dict | None = None) -> MetricFamily:
        fam = MetricFamily(name, "histogram", help_text)
        with self._lock:
            counts = list(self._counts)
            total, s = self.count, self.sum
        cum = 0
        for b, c in zip(self.buckets, counts):
            cum += c
            fam.add(cum, {**(labels or {}), "le": _fmt(b)},
                    suffix="_bucket")
        fam.add(total, {**(labels or {}), "le": "+Inf"}, suffix="_bucket")
        fam.add(s, labels, suffix="_sum")
        fam.add(total, labels, suffix="_count")
        return fam

    def snapshot(self) -> dict:
        """JSON-friendly view for /stats debugging."""
        with self._lock:
            return {"count": self.count, "sum": round(self.sum, 6),
                    "buckets": dict(zip([_fmt(b) for b in self.buckets]
                                        + ["+Inf"], self._counts))}


def hist_over_edge(hist_snapshot: dict, threshold: float) -> tuple:
    """``(samples over the threshold, total samples)`` from a
    ``Histogram.snapshot()`` dict. The threshold rounds UP to the next
    bucket edge: the straddling bucket (values <= that edge, possibly
    all meeting the threshold) counts as WITHIN — a threshold between
    edges must not report the whole fleet as over. ONE implementation
    shared by the autoscaler's TTFT-SLO-burn signal and the alert
    bus's ``ttft_slo_burn`` rule, so a scale decision and an alert can
    never disagree about the same histogram."""
    total = hist_snapshot.get("count", 0)
    buckets = [(float("inf") if le == "+Inf" else float(le), n)
               for le, n in hist_snapshot.get("buckets", {}).items()]
    eff = min((e for e, _ in buckets if e >= threshold),
              default=float("inf"))
    over = sum(n for e, n in buckets if e > eff)
    return over, total


def render(families: list[MetricFamily]) -> str:
    """The whole exposition document (trailing newline included, as
    the spec requires)."""
    return "\n".join(f.render() for f in families if f.samples) + "\n"
